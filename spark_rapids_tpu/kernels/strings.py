"""String kernels over Arrow offsets+bytes device layout.

Reference analogue: cuDF string kernels used by stringFunctions.scala.
TPU-first: strings have no native XLA type, so every op here is integer
arithmetic over the offsets/bytes buffers — gathers, searchsorted-style
binary searches, and byte-table lookups — all static-shape.
"""
from __future__ import annotations

import functools
from typing import List, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..columnar.column import StringColumn, bucket_capacity


def string_lengths(offsets) -> jnp.ndarray:
    return (offsets[1:] - offsets[:-1]).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_words",))
def _pack_words(offsets, data, num_words: int):
    """[cap, num_words] big-endian uint64 words of each string, zero-padded."""
    cap = offsets.shape[0] - 1
    starts = offsets[:-1]
    lens = offsets[1:] - starts
    # byte index matrix [cap, num_words*8]
    k = jnp.arange(num_words * 8, dtype=jnp.int32)
    idx = starts[:, None] + k[None, :]
    inb = k[None, :] < lens[:, None]
    byts = jnp.where(inb, jnp.take(data, jnp.clip(idx, 0, data.shape[0] - 1)),
                     jnp.uint8(0)).astype(jnp.uint64)
    w = byts.reshape(cap, num_words, 8)
    shifts = jnp.uint64(8) * (jnp.uint64(7) - jnp.arange(8, dtype=jnp.uint64))
    words = jnp.sum(w << shifts[None, None, :], axis=-1, dtype=jnp.uint64)
    return words


def needed_key_words(col: StringColumn, num_rows: int) -> int:
    """Bucketed uint64 word count needed to encode this column's strings.

    Uses the column's host-known ``max_bytes`` bound when present; a
    column derived purely on device pays ONE offsets sync and caches
    the bound on the instance (each uncached call would otherwise
    serialize behind all pending device work)."""
    from ..columnar.column import GatheredStringColumn
    if type(col) is GatheredStringColumn and col._mat is None:
        # lazy gather view: bound from the SOURCE without materializing
        # (view rows are a subset of source rows).  Prefer the source's
        # cached live bound over full capacity — stale offsets past a
        # shrunk source's live rows must not inflate the bucket here
        # any more than they may in the non-view path below.
        src = col.src
        if src.max_bytes is None:
            cached = getattr(src, "_live_max_bytes", None)
            if cached is not None:
                return needed_key_words(src, cached[0])
        return needed_key_words(src, src.capacity)
    max_len = col.max_bytes
    if max_len is None:
        if not isinstance(num_rows, (int, np.integer)):
            # a device/lazy row count (batch.rows_dev): the live-bound
            # scan below needs the concrete value — one declared pull
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="strings_prep"):
                num_rows = int(num_rows)
        cached = getattr(col, "_live_max_bytes", None)
        if cached is not None and cached[0] >= num_rows:
            max_len = cached[1]
        else:
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="strings_prep"):
                lens = np.asarray(col.offsets[1:]) - np.asarray(
                    col.offsets[:-1])
            # restrict to live rows: stale offsets beyond num_rows (a
            # shrunk batch) must not inflate the bucket
            max_len = int(lens[:num_rows].max()) if num_rows else 0
            col._live_max_bytes = (num_rows, max_len)
    num_words = max(1, -(-max_len // 8))
    return 1 << (num_words - 1).bit_length()


def string_key_words(col: StringColumn, num_rows: int,
                     num_words: int = None) -> List[jnp.ndarray]:
    """uint64 key words for sort/group/join: byte words + length tiebreak.

    ``num_words`` must be agreed across batches that will be compared
    against each other (joins unify via needed_key_words over both sides).
    """
    if num_words is None:
        # max length is host-known from offsets (one small sync per batch;
        # the reference similarly reads cuDF column metadata host-side).
        num_words = needed_key_words(col, num_rows)
    words = _pack_words(col.offsets, col.data, num_words)
    out = [words[:, i] for i in range(num_words)]
    out.append(string_lengths(col.offsets).astype(jnp.uint64))
    return out


@jax.jit
def _gather_offsets(offsets, validity, indices, live=None):
    starts = offsets[:-1]
    lens = offsets[1:] - starts
    ncap = indices.shape[0]
    src = jnp.clip(indices, 0, starts.shape[0] - 1)
    glens = jnp.take(lens, src)
    gvalid = jnp.take(validity, src)
    if live is not None:
        # dead output lanes (gather pads them with a repeated index)
        # must contribute zero bytes, or the no-sync unique-gather byte
        # bound below does not hold
        gvalid = gvalid & live
    glens = jnp.where(gvalid, glens, 0)
    new_offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(glens).astype(jnp.int32)])
    total = new_offsets[-1]
    return new_offsets, gvalid, jnp.take(starts, src), total


@functools.partial(jax.jit, static_argnames=("out_bytes",))
def _materialize_bytes(data, new_offsets, src_starts, out_bytes: int):
    j = jnp.arange(out_bytes, dtype=jnp.int32)
    row = jnp.searchsorted(new_offsets[1:], j, side="right").astype(jnp.int32)
    row = jnp.clip(row, 0, new_offsets.shape[0] - 2)
    within = j - new_offsets[row]
    src_idx = jnp.take(src_starts, row) + within
    live = j < new_offsets[-1]
    return jnp.where(live,
                     jnp.take(data, jnp.clip(src_idx, 0, data.shape[0] - 1)),
                     jnp.uint8(0))


def gather_strings(offsets, data, validity, indices, live=None,
                   unique=False, max_bytes=None):
    """Row gather for string columns.

    Sizing the output byte buffer needs a host-known bound.  The
    default is the exact total — one device sync per gather (a full
    dispatch-queue round trip on remote backends).  Two SYNC-FREE
    static bounds are used when available:

    - ``unique=True``: every live output lane reads a distinct source
      row, so output bytes <= the source buffer — sort permutations,
      filter compactions and aggregate representative gathers (callers
      must pass ``live`` when their index vector pads dead lanes with
      a repeated index).
    - ``max_bytes``: rows * max-single-string-length, used when that
      bound is not much larger than the source buffer.
    """
    new_offsets, gvalid, src_starts, total = _gather_offsets(
        offsets, validity, indices, live)
    # _materialize_bytes does O(out_bytes) device work, so a static
    # bound only beats the ~0.1-0.2s sync when it is SMALL; large
    # source buffers keep the exact-size sync
    _NOSYNC_MAX = 1 << 22
    src_bytes = max(int(data.shape[0]), 1)
    mb_bound = indices.shape[0] * max_bytes if max_bytes else None
    if unique and src_bytes <= _NOSYNC_MAX:
        out_bytes = src_bytes
        if mb_bound is not None:
            out_bytes = min(out_bytes, bucket_capacity(max(1, mb_bound)))
    elif mb_bound is not None and mb_bound <= _NOSYNC_MAX:
        out_bytes = bucket_capacity(max(1, mb_bound))
    else:
        from ..analysis import residency  # lazy: avoids import cycle
        with residency.declared_transfer(site="size_probe"):
            out_bytes = bucket_capacity(max(1, int(total)))
    buf = _materialize_bytes(data, new_offsets, src_starts, out_bytes)
    return new_offsets, buf, gvalid


# ---------------------------------------------------------------------------
# value kernels
# ---------------------------------------------------------------------------

_UPPER_TBL = np.arange(256, dtype=np.uint8)
_UPPER_TBL[ord("a"): ord("z") + 1] -= 32
_LOWER_TBL = np.arange(256, dtype=np.uint8)
_LOWER_TBL[ord("A"): ord("Z") + 1] += 32


@jax.jit
def upper_bytes(data):
    return jnp.take(jnp.asarray(_UPPER_TBL), data.astype(jnp.int32))


@jax.jit
def lower_bytes(data):
    return jnp.take(jnp.asarray(_LOWER_TBL), data.astype(jnp.int32))


def upper(col: StringColumn) -> StringColumn:
    return StringColumn(col.offsets, upper_bytes(col.data), col.validity,
                        max_bytes=col.max_bytes)


def lower(col: StringColumn) -> StringColumn:
    return StringColumn(col.offsets, lower_bytes(col.data), col.validity,
                        max_bytes=col.max_bytes)


@jax.jit
def _substring_offsets(offsets, start, length):
    """Spark substring semantics: 1-based start, negative counts from end."""
    starts = offsets[:-1]
    lens = offsets[1:] - starts
    s = jnp.where(start > 0, start - 1,
                  jnp.where(start < 0, jnp.maximum(lens + start, 0), 0))
    s = jnp.minimum(s, lens)
    l = jnp.clip(length, 0, lens - s)
    return (starts + s).astype(jnp.int32), l.astype(jnp.int32)


def substring(col: StringColumn, start: int, length: int) -> StringColumn:
    cap = col.capacity
    start_a = jnp.full((cap,), start, jnp.int32)
    len_a = jnp.full((cap,), length if length is not None else 2**31 - 1,
                     jnp.int32)
    src_starts, new_lens = _substring_offsets(col.offsets, start_a, len_a)
    new_lens = jnp.where(col.validity, new_lens, 0)
    new_offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(new_lens).astype(jnp.int32)])
    from ..analysis import residency  # lazy: avoids import cycle
    with residency.declared_transfer(site="size_probe"):
        total = int(new_offsets[-1])
    out_bytes = bucket_capacity(max(1, total))
    buf = _materialize_bytes(col.data, new_offsets, src_starts, out_bytes)
    mb = col.max_bytes
    if mb is not None and length is not None:
        mb = min(mb, max(length, 0))
    return StringColumn(new_offsets, buf, col.validity, max_bytes=mb)


def char_length(col: StringColumn) -> jnp.ndarray:
    """UTF-8 code point count (Spark length()): count non-continuation bytes."""
    is_cont = (col.data & jnp.uint8(0xC0)) == jnp.uint8(0x80)
    cont_cum = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(is_cont.astype(jnp.int32))])
    ends = jnp.clip(col.offsets[1:], 0, cont_cum.shape[0] - 1)
    begs = jnp.clip(col.offsets[:-1], 0, cont_cum.shape[0] - 1)
    byte_len = col.offsets[1:] - col.offsets[:-1]
    cont = jnp.take(cont_cum, ends) - jnp.take(cont_cum, begs)
    return (byte_len - cont).astype(jnp.int32)


def byte_length(col: StringColumn) -> jnp.ndarray:
    return (col.offsets[1:] - col.offsets[:-1]).astype(jnp.int32)


def starts_with(col: StringColumn, prefix: bytes) -> jnp.ndarray:
    pat = np.frombuffer(prefix, np.uint8)
    cap = col.capacity
    if pat.size == 0:
        return jnp.ones(cap, bool)
    starts = col.offsets[:-1]
    lens = col.offsets[1:] - starts
    k = jnp.arange(pat.size, dtype=jnp.int32)
    idx = jnp.clip(starts[:, None] + k[None, :], 0, col.data.shape[0] - 1)
    byts = jnp.take(col.data, idx)
    eq = jnp.all(byts == jnp.asarray(pat)[None, :], axis=1)
    return eq & (lens >= pat.size)


def ends_with(col: StringColumn, suffix: bytes) -> jnp.ndarray:
    pat = np.frombuffer(suffix, np.uint8)
    cap = col.capacity
    if pat.size == 0:
        return jnp.ones(cap, bool)
    lens = col.offsets[1:] - col.offsets[:-1]
    starts = col.offsets[1:] - pat.size
    k = jnp.arange(pat.size, dtype=jnp.int32)
    idx = jnp.clip(starts[:, None] + k[None, :], 0, col.data.shape[0] - 1)
    byts = jnp.take(col.data, idx)
    eq = jnp.all(byts == jnp.asarray(pat)[None, :], axis=1)
    return eq & (lens >= pat.size)


def contains(col: StringColumn, needle: bytes) -> jnp.ndarray:
    """Substring containment via sliding window compare on the byte buffer."""
    pat = np.frombuffer(needle, np.uint8)
    if pat.size == 0:
        return jnp.ones(col.capacity, bool)
    data = col.data
    B = data.shape[0]
    k = jnp.arange(pat.size, dtype=jnp.int32)
    idx = jnp.clip(jnp.arange(B, dtype=jnp.int32)[:, None] + k[None, :], 0,
                   B - 1)
    win_eq = jnp.all(jnp.take(data, idx) == jnp.asarray(pat)[None, :], axis=1)
    # match position p counts for row i if starts[i] <= p <= ends[i]-len(pat)
    hit_cum = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(win_eq.astype(jnp.int32))])
    starts = col.offsets[:-1]
    ends = jnp.maximum(col.offsets[1:] - pat.size + 1, starts)
    a = jnp.take(hit_cum, jnp.clip(starts, 0, B))
    b = jnp.take(hit_cum, jnp.clip(ends, 0, B))
    return (b - a) > 0


def find_in_row(col: StringColumn, needle: bytes,
                from_rel) -> jnp.ndarray:
    """Per row: smallest byte offset >= ``from_rel[row]`` where
    ``needle`` occurs, else -1.  Powers the device multi-%%-segment
    LIKE path (GpuOverrides treats 'regexp like a regular string' the
    same way) — ordered segment search without the host regex engine."""
    import jax
    pat = np.frombuffer(needle, np.uint8)
    cap = col.capacity
    if pat.size == 0:
        return jnp.maximum(from_rel, 0).astype(jnp.int32)
    data = col.data
    B = data.shape[0]
    k = jnp.arange(pat.size, dtype=jnp.int32)
    idx = jnp.clip(jnp.arange(B, dtype=jnp.int32)[:, None] + k[None, :],
                   0, B - 1)
    win_eq = jnp.all(jnp.take(data, idx) == jnp.asarray(pat)[None, :],
                     axis=1)
    g = jnp.arange(B, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(col.offsets[1:], g, side="right"),
                   0, cap - 1).astype(jnp.int32)
    starts = jnp.take(col.offsets[:-1], row)
    ends = jnp.take(col.offsets[1:], row)
    rel = g - starts
    ok = win_eq & (g + pat.size <= ends) & \
        (rel >= jnp.take(from_rel.astype(jnp.int32), row))
    inf = jnp.int32(2 ** 31 - 1)
    cand = jnp.where(ok, rel, inf)
    best = jax.ops.segment_min(cand, row, num_segments=cap)
    return jnp.where(best == inf, jnp.int32(-1), best.astype(jnp.int32))
