"""Basic device kernels: selection compaction, gather plans, hashing.

Reference analogues: cuDF apply_boolean_mask/gather (used by GpuFilterExec,
basicPhysicalOperators.scala:230) and spark murmur3 hashing
(HashFunctions.scala, GpuHashPartitioning.scala).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..obs.trace import traced


@jax.jit
def compact_indices(keep_mask, num_rows):
    """Turn a boolean keep-mask into a stable gather plan.

    Returns (indices[cap], new_count).  Rows where keep is True are moved to
    the front preserving order; the tail is filled with clipped indices whose
    validity the caller masks off.
    """
    cap = keep_mask.shape[0]
    in_range = jnp.arange(cap) < num_rows
    keep = keep_mask & in_range
    # stable: argsort of (not keep) keeps relative order of kept rows
    order = jnp.argsort(jnp.where(keep, 0, 1), stable=True)
    new_count = jnp.sum(keep)
    return order, new_count


@jax.jit
def prefix_positions(keep_mask):
    """positions[i] = output slot of row i if kept (cumsum-1)."""
    return jnp.cumsum(keep_mask.astype(jnp.int32)) - 1


# ---------------------------------------------------------------------------
# Murmur3-style 64-bit mixing for partitioning / hash expressions.
# Self-consistent across the framework (our oracle is our CPU path, not
# JVM Spark), matching the role of Spark's Murmur3_x86_32(seed=42).
# ---------------------------------------------------------------------------

M1 = 0xff51afd7ed558ccd
M2 = 0xc4ceb9fe1a85ec53


@jax.jit
def mix64(x):
    x = x.astype(jnp.uint64)
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(M1)
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(M2)
    x = x ^ (x >> jnp.uint64(33))
    return x


@traced("hash_words")
def hash_words(word_lists, seed: int = 42):
    """Combine lists of uint64 word arrays into one 64-bit hash per row."""
    h = jnp.full(word_lists[0].shape, jnp.uint64(seed))
    for w in word_lists:
        h = mix64(h ^ w)
    return h


@functools.partial(jax.jit, static_argnames=("num_parts",))
def hash_to_partition(hashes, num_parts: int):
    return (hashes % jnp.uint64(num_parts)).astype(jnp.int32)
