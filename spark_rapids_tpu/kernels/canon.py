"""Canonical sortable key-word encoding.

Every orderable SQL value is mapped to one or more **uint64 words** whose
unsigned lexicographic order equals the SQL ordering of the values.  Sorts,
group-bys and joins all operate on these words, so there is exactly one
comparison code path on the device and it is pure integer VPU work — the
shape XLA tiles best (SURVEY.md §7 "hard parts": sort-based designs map
better to XLA than open-addressing hash tables).

Encodings:
- signed ints  -> x XOR 0x8000...  (order-preserving bias to unsigned)
- floats       -> IEEE-754 trick: if sign bit set, flip all bits, else set
                  sign bit.  NaNs are canonicalized first (Spark treats all
                  NaNs equal and greater than any other value; -0.0 == 0.0 —
                  reference: NormalizeFloatingNumbers.scala).
- bool/date/timestamp/decimal -> via their integer representation
- strings      -> big-endian uint64 words of the UTF-8 bytes, zero padded,
                  plus a final length word as tie-break (exact byte-wise
                  order == code-point order for UTF-8)
- null handling: a leading null-rank word per key (0/1/2) encodes
  nulls-first/last and pushes rows past num_rows to the very end.
- descending   -> bitwise NOT of every word (reverses unsigned order).
"""
from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..columnar import dtypes as T
from ..columnar.column import Column, StringColumn

# python int (not a jnp scalar): creating device values at import
# time would initialize the backend before sessions configure it
SIGN64 = 0x8000000000000000


def _ints_to_words(data, nbits: int):
    x = data.astype(jnp.int64)
    return (x.view(jnp.uint64) if nbits == 64
            else x.astype(jnp.uint64)) ^ jnp.uint64(SIGN64)


def _f32_order_word(x) -> jnp.ndarray:
    """f32 array -> one u64 word whose unsigned order == numeric order
    (NaNs canonicalized greatest, -0.0 == 0.0)."""
    x = jnp.where(jnp.isnan(x), jnp.float32(jnp.nan), x)
    x = jnp.where(x == 0.0, jnp.float32(0.0), x)
    bits = x.view(jnp.uint32)
    sign = (bits & jnp.uint32(0x80000000)) != 0
    w = jnp.where(sign, ~bits, bits | jnp.uint32(0x80000000))
    return w.astype(jnp.uint64)


def _f64_bitcast_supported() -> bool:
    """Real TPUs have no f64 ALU: XLA emulates f64 as an f32 pair and
    cannot lower a 64-bit float bitcast.  CPU (tests, virtual meshes)
    can, and there the single-word encoding is exact for full binary64."""
    import jax
    return jax.default_backend() == "cpu"


def _float_to_words(data) -> List[jnp.ndarray]:
    if data.dtype == jnp.dtype(jnp.float32):
        return [_f32_order_word(data)]
    f64 = data.astype(jnp.float64)
    # canonicalize: all NaNs -> +NaN quiet; -0.0 -> 0.0
    f64 = jnp.where(jnp.isnan(f64), jnp.float64(jnp.nan), f64)
    f64 = jnp.where(f64 == 0.0, jnp.float64(0.0), f64)
    if _f64_bitcast_supported():
        bits = f64.view(jnp.uint64)
        sign = (bits & jnp.uint64(SIGN64)) != 0
        flipped = jnp.where(sign, ~bits, bits | jnp.uint64(SIGN64))
        # +NaN lands above +inf (flip keeps NaN mantissa bits set)
        return [flipped]
    # On chip: the emulated f64 is a double-double (hi, lo) f32 pair, so
    # the exact order of representable values is the lexicographic order
    # of the order-words of (hi, lo, residual) — three u32 bitcasts, each
    # of which the chip CAN do.  The residual word covers the few bits a
    # second rounding can still hold.
    hi64 = f64.astype(jnp.float32).astype(jnp.float64)
    ok = jnp.isfinite(f64) & jnp.isfinite(hi64)
    rem1 = jnp.where(ok, f64 - hi64, 0.0)
    lo64 = rem1.astype(jnp.float32).astype(jnp.float64)
    rem2 = jnp.where(ok, rem1 - lo64, 0.0)
    return [_f32_order_word(f64.astype(jnp.float32)),
            _f32_order_word(rem1.astype(jnp.float32)),
            _f32_order_word(rem2.astype(jnp.float32))]


def column_key_words(col: Column, num_rows: int, *, descending: bool = False,
                     nulls_last: bool = False,
                     str_words: int = None) -> List[jnp.ndarray]:
    """Return the list of uint64 word arrays encoding this column as a key.

    The first word is the null/range rank; the rest are value words.
    """
    cap = col.capacity
    in_range = jnp.arange(cap) < num_rows
    valid = col.validity & in_range
    if nulls_last:
        null_rank = jnp.where(valid, jnp.uint64(0), jnp.uint64(1))
    else:
        null_rank = jnp.where(valid, jnp.uint64(1), jnp.uint64(0))
    # rows past num_rows always sort to the absolute end
    null_rank = jnp.where(in_range, null_rank, jnp.uint64(2))

    words = value_words(col, num_rows, str_words=str_words)
    if descending:
        words = [~w for w in words]
        # null rank is NOT inverted: padding must stay at the end and spark's
        # desc default is nulls_last which the caller passes explicitly.
    # zero out words of invalid rows for determinism
    words = [jnp.where(valid, w, jnp.uint64(0)) for w in words]
    return [null_rank] + words


def value_words(col: Column, num_rows: int,
                str_words: int = None) -> List[jnp.ndarray]:
    """uint64 word list for the column values (no null rank)."""
    dt = col.dtype
    from ..columnar.column import GatheredStringColumn
    if type(col) is GatheredStringColumn and col._mat is None:
        # lazy gather view: gather the SOURCE column's words by index —
        # pure integer device work, no byte materialization and no
        # sizing sync.  num_words from the source's full capacity so
        # every view over one source agrees on word count.
        from . import strings as skern
        src = col.src
        if str_words is None:
            str_words = skern.needed_key_words(src, src.capacity)
        src_words = skern.string_key_words(src, src.capacity,
                                           num_words=str_words)
        return [jnp.take(w, col.idx, axis=0, mode="clip")
                for w in src_words]
    if isinstance(col, StringColumn):
        from . import strings as skern
        return skern.string_key_words(col, num_rows, num_words=str_words)
    from ..columnar.binary64 import Binary64Column
    if isinstance(col, Binary64Column):
        # exact total-order word straight from the bit pattern (the
        # order_word flip is exact integer work; Spark order: NaN
        # greatest, -0.0 == 0.0)
        from . import binary64 as b64
        return [b64.order_word(col.data).astype(jnp.uint64)]
    if dt == T.BOOL:
        return [col.data.astype(jnp.uint64)]
    if dt.is_integral or isinstance(dt, T.DecimalType) or dt in (T.DATE,
                                                                 T.TIMESTAMP):
        return [_ints_to_words(col.data, 64)]
    if dt.is_fractional:
        return _float_to_words(col.data)
    if dt == T.NULL:
        return [jnp.zeros(col.capacity, jnp.uint64)]
    raise NotImplementedError(f"key encoding for {dt}")


def batch_key_words(cols: List[Column], num_rows: int,
                    descending: List[bool] = None,
                    nulls_last: List[bool] = None,
                    str_words: List[int] = None) -> List[jnp.ndarray]:
    descending = descending or [False] * len(cols)
    nulls_last = nulls_last or [False] * len(cols)
    str_words = str_words or [None] * len(cols)
    out: List[jnp.ndarray] = []
    for c, d, nl, sw in zip(cols, descending, nulls_last, str_words):
        out.extend(column_key_words(c, num_rows, descending=d, nulls_last=nl,
                                    str_words=sw))
    if not out:
        # zero keys: single constant word (everything equal)
        cap = cols[0].capacity if cols else 16
        out = [jnp.zeros(cap, jnp.uint64)]
    return out


def words_equal_adjacent(words: List[jnp.ndarray]) -> jnp.ndarray:
    """For sorted word arrays: mask[i] = row i differs from row i-1 (i>0)."""
    diff = jnp.zeros(words[0].shape[0], dtype=bool)
    for w in words:
        prev = jnp.concatenate([w[:1], w[:-1]])
        diff = diff | (w != prev)
    return diff.at[0].set(True)


def words_less(words_a: List[jnp.ndarray], idx_a, words_b: List[jnp.ndarray],
               idx_b) -> jnp.ndarray:
    """Vectorized lexicographic a[idx_a] < b[idx_b] over word lists."""
    lt = jnp.zeros(jnp.broadcast_shapes(jnp.shape(idx_a), jnp.shape(idx_b)),
                   dtype=bool)
    eq = jnp.ones_like(lt)
    for wa, wb in zip(words_a, words_b):
        a = wa[idx_a]
        b = wb[idx_b]
        lt = lt | (eq & (a < b))
        eq = eq & (a == b)
    return lt
