"""Group-by aggregation kernels — the device core of GpuHashAggregateExec

(reference: aggregate.scala:240).

TPU-first: instead of cuDF's open-addressing hash groupby, we sort by
canonical key words and run segmented reductions (``jax.ops.segment_*``) —
sort + segment-scan lowers to XLA's native sort and scatter-add, which tile
onto the VPU far better than data-dependent hash probing (SURVEY.md §7
"hard parts").  One compiled kernel per (schema, capacity) bucket.
"""
from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp

from . import canon
from .sort import sorted_words
from .basic import compact_indices


@dataclasses.dataclass
class GroupPlan:
    perm: jnp.ndarray          # sort permutation over the input rows
    seg_id: jnp.ndarray        # segment id per sorted row (live rows: 0..G-1)
    live_sorted: jnp.ndarray   # sorted-row liveness mask (in-range rows)
    rep_indices: jnp.ndarray   # original row index of each group representative
    num_groups: jnp.ndarray    # scalar int


def groupby_plan(words: List[jnp.ndarray]) -> GroupPlan:
    """Build the sort+segment plan for a set of canonical key words.

    ``words`` must come from canon.batch_key_words (first word of each key is
    the null/range rank; rank 2 == past-num_rows padding).
    """
    sorted_ws, perm = sorted_words(words)
    live = sorted_ws[0] != jnp.uint64(2)
    boundary = canon.words_equal_adjacent(sorted_ws) & live
    seg_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_id = jnp.maximum(seg_id, 0)
    num_groups = jnp.sum(boundary)
    rep_order, _ = compact_indices(boundary, boundary.shape[0])
    rep_indices = jnp.take(perm, rep_order)
    return GroupPlan(perm, seg_id, live, rep_indices, num_groups)


def _sorted_vals(plan: GroupPlan, values, validity):
    v = jnp.take(values, plan.perm)
    ok = jnp.take(validity, plan.perm) & plan.live_sorted
    return v, ok


def seg_sum(plan: GroupPlan, values, validity, out_dtype=None):
    cap = values.shape[0]
    v, ok = _sorted_vals(plan, values, validity)
    acc = v.astype(out_dtype or v.dtype)
    contrib = jnp.where(ok, acc, jnp.zeros_like(acc))
    return jax.ops.segment_sum(contrib, plan.seg_id, num_segments=cap)


def seg_count(plan: GroupPlan, validity):
    cap = validity.shape[0]
    _, ok = _sorted_vals(plan, validity, validity)
    return jax.ops.segment_sum(ok.astype(jnp.int64), plan.seg_id,
                               num_segments=cap)


def seg_count_all(plan: GroupPlan):
    cap = plan.seg_id.shape[0]
    return jax.ops.segment_sum(plan.live_sorted.astype(jnp.int64), plan.seg_id,
                               num_segments=cap)


def _type_extreme(dtype, want_max: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if not want_max else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if not want_max else info.min, dtype)


def seg_min(plan: GroupPlan, values, validity):
    cap = values.shape[0]
    v, ok = _sorted_vals(plan, values, validity)
    if jnp.issubdtype(v.dtype, jnp.floating):
        # Spark total order: NaN greatest, -0.0 == 0.0.  No bit encoding
        # (the chip cannot bitcast f64): min over non-NaN values, falling
        # back to NaN only when a group has nothing else.
        v = jnp.where(v == 0.0, jnp.array(0.0, v.dtype), v)
        nan = jnp.isnan(v)
        contrib = jnp.where(ok & ~nan, v, jnp.array(jnp.inf, v.dtype))
        m = jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)
        has_num = jax.ops.segment_max((ok & ~nan).astype(jnp.int32),
                                      plan.seg_id, num_segments=cap) > 0
        return jnp.where(has_num, m, jnp.array(jnp.nan, v.dtype))
    ident = _type_extreme(v.dtype, want_max=False)
    contrib = jnp.where(ok, v, ident)
    return jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)


def seg_max(plan: GroupPlan, values, validity):
    cap = values.shape[0]
    v, ok = _sorted_vals(plan, values, validity)
    if jnp.issubdtype(v.dtype, jnp.floating):
        # NaN is the greatest value: any NaN in the group wins
        v = jnp.where(v == 0.0, jnp.array(0.0, v.dtype), v)
        nan = jnp.isnan(v)
        contrib = jnp.where(ok & ~nan, v, jnp.array(-jnp.inf, v.dtype))
        m = jax.ops.segment_max(contrib, plan.seg_id, num_segments=cap)
        has_nan = jax.ops.segment_max((ok & nan).astype(jnp.int32),
                                      plan.seg_id, num_segments=cap) > 0
        return jnp.where(has_nan, jnp.array(jnp.nan, v.dtype), m)
    ident = _type_extreme(v.dtype, want_max=True)
    contrib = jnp.where(ok, v, ident)
    return jax.ops.segment_max(contrib, plan.seg_id, num_segments=cap)


def seg_first_index(plan: GroupPlan, validity, ignore_nulls: bool = True):
    """Original-row index of the first (valid) row per group."""
    cap = validity.shape[0]
    ok = jnp.take(validity, plan.perm) & plan.live_sorted if ignore_nulls \
        else plan.live_sorted
    pos = jnp.arange(cap, dtype=jnp.int64)
    contrib = jnp.where(ok, pos, jnp.int64(cap))
    first_pos = jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)
    safe = jnp.clip(first_pos, 0, cap - 1).astype(jnp.int32)
    return jnp.take(plan.perm, safe), first_pos < cap


def seg_first_index_by_order(plan: GroupPlan, col, want_min: bool = True,
                             num_rows: int = None):
    """Index of the lexicographically min/max value per group (strings etc.).

    Works on canonical value words: iteratively narrow candidates word by
    word with segment_min, then take the first surviving index.
    """
    from . import canon
    cap = col.capacity
    if num_rows is None:
        num_rows = cap
    words = canon.value_words(col, num_rows)
    if not want_min:
        words = [~w for w in words]
    ok = jnp.take(col.validity, plan.perm) & plan.live_sorted
    cand = ok
    for w in words:
        ws = jnp.take(w, plan.perm).astype(jnp.uint64)
        big = jnp.uint64(0xFFFFFFFFFFFFFFFF)
        contrib = jnp.where(cand, ws, big)
        m = jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)
        cand = cand & (ws == jnp.take(m, plan.seg_id))
    pos = jnp.arange(cap, dtype=jnp.int64)
    contrib = jnp.where(cand, pos, jnp.int64(cap))
    first_pos = jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)
    has = first_pos < cap
    safe = jnp.clip(first_pos, 0, cap - 1).astype(jnp.int32)
    return jnp.take(plan.perm, safe), has


def seg_last_index(plan: GroupPlan, validity, ignore_nulls: bool = True):
    cap = validity.shape[0]
    ok = jnp.take(validity, plan.perm) & plan.live_sorted if ignore_nulls \
        else plan.live_sorted
    pos = jnp.arange(cap, dtype=jnp.int64)
    contrib = jnp.where(ok, pos, jnp.int64(-1))
    last_pos = jax.ops.segment_max(contrib, plan.seg_id, num_segments=cap)
    safe = jnp.clip(last_pos, 0, cap - 1).astype(jnp.int32)
    return jnp.take(plan.perm, safe), last_pos >= 0
