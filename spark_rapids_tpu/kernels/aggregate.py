"""Group-by aggregation kernels — the device core of GpuHashAggregateExec

(reference: aggregate.scala:240).

TPU-first: instead of cuDF's open-addressing hash groupby, we sort by
canonical key words and run segmented reductions (``jax.ops.segment_*``) —
sort + segment-scan lowers to XLA's native sort and scatter-add, which tile
onto the VPU far better than data-dependent hash probing (SURVEY.md §7
"hard parts").  One compiled kernel per (schema, capacity) bucket.
"""
from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp

from . import canon
from .sort import sorted_words
from .basic import compact_indices


@dataclasses.dataclass
class GroupPlan:
    perm: jnp.ndarray          # sort permutation over the input rows
    seg_id: jnp.ndarray        # segment id per sorted row (live rows: 0..G-1)
    live_sorted: jnp.ndarray   # sorted-row liveness mask (in-range rows)
    rep_indices: jnp.ndarray   # original row index of each group representative
    num_groups: jnp.ndarray    # scalar int


def groupby_plan(words: List[jnp.ndarray]) -> GroupPlan:
    """Build the sort+segment plan for a set of canonical key words.

    ``words`` must come from canon.batch_key_words (first word of each key is
    the null/range rank; rank 2 == past-num_rows padding).
    """
    sorted_ws, perm = sorted_words(words)
    live = sorted_ws[0] != jnp.uint64(2)
    boundary = canon.words_equal_adjacent(sorted_ws) & live
    seg_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_id = jnp.maximum(seg_id, 0)
    num_groups = jnp.sum(boundary)
    rep_order, _ = compact_indices(boundary, boundary.shape[0])
    rep_indices = jnp.take(perm, rep_order)
    return GroupPlan(perm, seg_id, live, rep_indices, num_groups)


def _sorted_vals(plan: GroupPlan, values, validity):
    v = jnp.take(values, plan.perm)
    ok = jnp.take(validity, plan.perm) & plan.live_sorted
    return v, ok


def seg_sum(plan: GroupPlan, values, validity, out_dtype=None):
    cap = values.shape[0]
    v, ok = _sorted_vals(plan, values, validity)
    acc = v.astype(out_dtype or v.dtype)
    contrib = jnp.where(ok, acc, jnp.zeros_like(acc))
    return jax.ops.segment_sum(contrib, plan.seg_id, num_segments=cap)


def seg_count(plan: GroupPlan, validity):
    cap = validity.shape[0]
    _, ok = _sorted_vals(plan, validity, validity)
    return jax.ops.segment_sum(ok.astype(jnp.int64), plan.seg_id,
                               num_segments=cap)


def seg_count_all(plan: GroupPlan):
    cap = plan.seg_id.shape[0]
    return jax.ops.segment_sum(plan.live_sorted.astype(jnp.int64), plan.seg_id,
                               num_segments=cap)


def _type_extreme(dtype, want_max: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if not want_max else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if not want_max else info.min, dtype)


def seg_min(plan: GroupPlan, values, validity):
    cap = values.shape[0]
    v, ok = _sorted_vals(plan, values, validity)
    if jnp.issubdtype(v.dtype, jnp.floating):
        # Spark total order: NaN greatest, -0.0 == 0.0.  No bit encoding
        # (the chip cannot bitcast f64): min over non-NaN values, falling
        # back to NaN only when a group has nothing else.
        v = jnp.where(v == 0.0, jnp.array(0.0, v.dtype), v)
        nan = jnp.isnan(v)
        contrib = jnp.where(ok & ~nan, v, jnp.array(jnp.inf, v.dtype))
        m = jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)
        has_num = jax.ops.segment_max((ok & ~nan).astype(jnp.int32),
                                      plan.seg_id, num_segments=cap) > 0
        return jnp.where(has_num, m, jnp.array(jnp.nan, v.dtype))
    ident = _type_extreme(v.dtype, want_max=False)
    contrib = jnp.where(ok, v, ident)
    return jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)


def seg_max(plan: GroupPlan, values, validity):
    cap = values.shape[0]
    v, ok = _sorted_vals(plan, values, validity)
    if jnp.issubdtype(v.dtype, jnp.floating):
        # NaN is the greatest value: any NaN in the group wins
        v = jnp.where(v == 0.0, jnp.array(0.0, v.dtype), v)
        nan = jnp.isnan(v)
        contrib = jnp.where(ok & ~nan, v, jnp.array(-jnp.inf, v.dtype))
        m = jax.ops.segment_max(contrib, plan.seg_id, num_segments=cap)
        has_nan = jax.ops.segment_max((ok & nan).astype(jnp.int32),
                                      plan.seg_id, num_segments=cap) > 0
        return jnp.where(has_nan, jnp.array(jnp.nan, v.dtype), m)
    ident = _type_extreme(v.dtype, want_max=True)
    contrib = jnp.where(ok, v, ident)
    return jax.ops.segment_max(contrib, plan.seg_id, num_segments=cap)


def seg_first_index(plan: GroupPlan, validity, ignore_nulls: bool = True):
    """Original-row index of the first (valid) row per group."""
    cap = validity.shape[0]
    ok = jnp.take(validity, plan.perm) & plan.live_sorted if ignore_nulls \
        else plan.live_sorted
    pos = jnp.arange(cap, dtype=jnp.int64)
    contrib = jnp.where(ok, pos, jnp.int64(cap))
    first_pos = jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)
    safe = jnp.clip(first_pos, 0, cap - 1).astype(jnp.int32)
    return jnp.take(plan.perm, safe), first_pos < cap


def seg_first_index_by_order(plan: GroupPlan, col, want_min: bool = True,
                             num_rows: int = None):
    """Index of the lexicographically min/max value per group (strings etc.).

    Works on canonical value words: iteratively narrow candidates word by
    word with segment_min, then take the first surviving index.
    """
    from . import canon
    cap = col.capacity
    if num_rows is None:
        num_rows = cap
    words = canon.value_words(col, num_rows)
    if not want_min:
        words = [~w for w in words]
    ok = jnp.take(col.validity, plan.perm) & plan.live_sorted
    cand = ok
    for w in words:
        ws = jnp.take(w, plan.perm).astype(jnp.uint64)
        big = jnp.uint64(0xFFFFFFFFFFFFFFFF)
        contrib = jnp.where(cand, ws, big)
        m = jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)
        cand = cand & (ws == jnp.take(m, plan.seg_id))
    pos = jnp.arange(cap, dtype=jnp.int64)
    contrib = jnp.where(cand, pos, jnp.int64(cap))
    first_pos = jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)
    has = first_pos < cap
    safe = jnp.clip(first_pos, 0, cap - 1).astype(jnp.int32)
    return jnp.take(plan.perm, safe), has


def seg_last_index(plan: GroupPlan, validity, ignore_nulls: bool = True):
    cap = validity.shape[0]
    ok = jnp.take(validity, plan.perm) & plan.live_sorted if ignore_nulls \
        else plan.live_sorted
    pos = jnp.arange(cap, dtype=jnp.int64)
    contrib = jnp.where(ok, pos, jnp.int64(-1))
    last_pos = jax.ops.segment_max(contrib, plan.seg_id, num_segments=cap)
    safe = jnp.clip(last_pos, 0, cap - 1).astype(jnp.int32)
    return jnp.take(plan.perm, safe), last_pos >= 0


# ---------------------------------------------------------------------------
# Sort-free bucket-table group-by (the TPU-native fast path).
#
# Reference context: cuDF's hash group-by (aggregate.scala:240 lowers to
# open-addressing hash tables on GPU).  Hash probing is hostile to XLA,
# but most BI group-bys have small combined key cardinality RANGE —
# so instead of hashing, each key word is rebased by its device-computed
# minimum and the keys mixed-radix-packed into a bucket id < table_size.
# Aggregation is then direct per-bucket reduction: sums/counts ride
# one-hot matmuls on the MXU; min/max ride small-output scatters.
# No sort, no gathers, no 64-bit scatters (which cost ~20x f32 on TPU).
#
# A device-side `fit` flag records whether the batch really fit the
# table (key range, u32 value range for int min/max, f32 finiteness for
# float sums); callers dispatch speculatively and re-run the rare
# non-fitting batch on the general sort path (exec/tpu_aggregate.py).
# ---------------------------------------------------------------------------


def table_bucket(key_words, key_valids, live, table: int):
    """Mixed-radix bucket assignment over single-word keys.

    key_words: one uint64 word per key (canon.value_words[0]);
    key_valids: per-key validity; live: row mask (in-range AND past any
    folded-in filters).  Each key contributes digit 0 for null and
    1 + (word - min) otherwise; digits pack most-significant-first, so
    bucket ascending == (nulls-first key tuple) ascending — matching the
    sort path's group order.  Dead rows get bucket == table.
    Returns (bucket i32[cap], fit bool, mins, cards).
    """
    cap = key_words[0].shape[0]
    bucket = jnp.zeros(cap, jnp.int32)
    total = jnp.uint64(1)
    fit = jnp.bool_(True)
    mins, cards = [], []
    for w, valid in zip(key_words, key_valids):
        lv = live & valid
        any_v = jnp.any(lv)
        wmin = jnp.where(any_v,
                         jnp.min(jnp.where(lv, w, jnp.uint64(2**64 - 1))),
                         jnp.uint64(0))
        wmax = jnp.where(any_v,
                         jnp.max(jnp.where(lv, w, jnp.uint64(0))),
                         jnp.uint64(0))
        rng = wmax - wmin
        # card clamped so products can't wrap; fit goes False anyway
        card = jnp.minimum(rng, jnp.uint64(table)).astype(jnp.int32) + 2
        total = jnp.minimum(total * card.astype(jnp.uint64),
                            jnp.uint64(1) << jnp.uint64(32))
        digit = jnp.where(
            valid,
            jnp.minimum(w - wmin, jnp.uint64(table)).astype(jnp.int32) + 1,
            0)
        bucket = jnp.minimum(bucket * card + digit, table)
        mins.append(wmin)
        cards.append(card)
    fit = total <= jnp.uint64(table)
    bucket = jnp.where(live, bucket, table).astype(jnp.int32)
    return bucket, fit, mins, cards


def table_compact(counts, table: int):
    """Group directory from per-bucket live counts: (present, order,
    num_groups) where order[g] = bucket of group g, ascending."""
    present = counts > 0
    num_groups = jnp.sum(present).astype(jnp.int32)
    order = jnp.argsort(jnp.where(present, 0, 1), stable=True) \
        .astype(jnp.int32)
    return present, order, num_groups
