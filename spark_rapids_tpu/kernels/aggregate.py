"""Group-by aggregation kernels — the device core of GpuHashAggregateExec

(reference: aggregate.scala:240).

TPU-first: instead of cuDF's open-addressing hash groupby, we sort by
canonical key words and run segmented reductions (``jax.ops.segment_*``) —
sort + segment-scan lowers to XLA's native sort and scatter-add, which tile
onto the VPU far better than data-dependent hash probing (SURVEY.md §7
"hard parts").  One compiled kernel per (schema, capacity) bucket.
"""
from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp

from . import canon
from .sort import sorted_words
from .basic import compact_indices
from ..obs.trace import traced


@dataclasses.dataclass
class GroupPlan:
    perm: jnp.ndarray          # sort permutation over the input rows
    seg_id: jnp.ndarray        # segment id per sorted row (live rows: 0..G-1)
    live_sorted: jnp.ndarray   # sorted-row liveness mask (in-range rows)
    rep_indices: jnp.ndarray   # original row index of each group representative
    num_groups: jnp.ndarray    # scalar int
    head_pos: jnp.ndarray      # sorted position of each group's FIRST row
    last_pos: jnp.ndarray      # sorted position of each group's LAST row


@traced("groupby_plan")
def groupby_plan(words: List[jnp.ndarray]) -> GroupPlan:
    """Build the sort+segment plan for a set of canonical key words.

    ``words`` must come from canon.batch_key_words (first word of each key is
    the null/range rank; rank 2 == past-num_rows padding).

    Besides the segment ids, the plan carries each group's first/last
    SORTED position (``head_pos``/``last_pos``): groups are contiguous
    runs after the sort, so per-group reductions of sums/counts become
    prefix-scan + two boundary gathers — a cumsum is near-free on the
    VPU while a 64-bit scatter-add costs ~5x an f32 one (measured; XLA
    emulates i64 as 32-bit pairs and scatters serialize badly).
    """
    sorted_ws, perm = sorted_words(words)
    live = sorted_ws[0] != jnp.uint64(2)
    boundary = canon.words_equal_adjacent(sorted_ws) & live
    seg_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_id = jnp.maximum(seg_id, 0)
    num_groups = jnp.sum(boundary)
    rep_order, _ = compact_indices(boundary, boundary.shape[0])
    rep_indices = jnp.take(perm, rep_order)
    # group g spans sorted rows [head_pos[g], last_pos[g]]; dead rows sort
    # after all live rows, so the last live group ends at live_count-1
    n = boundary.shape[0]
    head_pos = rep_order.astype(jnp.int32)
    live_count = jnp.sum(live.astype(jnp.int32))
    gi = jnp.arange(n, dtype=jnp.int32)
    nxt = jnp.concatenate([head_pos[1:], jnp.zeros(1, jnp.int32)])
    last_pos = jnp.where(gi + 1 < num_groups, nxt - 1, live_count - 1)
    return GroupPlan(perm, seg_id, live, rep_indices, num_groups,
                     head_pos, last_pos)


def _sorted_vals(plan: GroupPlan, values, validity):
    v = jnp.take(values, plan.perm)
    ok = jnp.take(validity, plan.perm) & plan.live_sorted
    return v, ok


def seg_prefix_sum(plan: GroupPlan, contrib):
    """Per-group sum of an already-masked per-SORTED-row integer array via
    cumsum + boundary gathers (no scatter).  Exact for any integer dtype:
    the whole-batch running sum may wrap, but wraparound cancels in the
    boundary subtraction (two's complement), so each group's total is
    exact whenever it fits the dtype — the same contract as a direct
    per-group sum."""
    cap = contrib.shape[0]
    cum = jnp.cumsum(contrib)
    ex = cum - contrib                       # exclusive prefix per row
    hp = jnp.clip(plan.head_pos, 0, cap - 1)
    lp = jnp.clip(plan.last_pos, 0, cap - 1)
    total = jnp.take(cum, lp) - jnp.take(ex, hp)
    gi = jnp.arange(cap, dtype=jnp.int32)
    return jnp.where(gi < plan.num_groups, total,
                     jnp.zeros_like(total))


def seg_sum(plan: GroupPlan, values, validity, out_dtype=None):
    cap = values.shape[0]
    v, ok = _sorted_vals(plan, values, validity)
    acc = v.astype(out_dtype or v.dtype)
    contrib = jnp.where(ok, acc, jnp.zeros_like(acc))
    if jnp.issubdtype(contrib.dtype, jnp.integer) or \
            contrib.dtype == jnp.bool_:
        return seg_prefix_sum(plan, contrib)
    if contrib.dtype == jnp.float64 and jax.default_backend() != "cpu" \
            and _pair_sum_enabled():
        # Opt-in accuracy mode: on chip f64 IS an (hi, lo) f32 pair;
        # accumulate with the integer superaccumulator over the two
        # components — deterministic, order-independent, and faithful
        # to everything the device representation holds.  Costs ~4x the
        # scatter (the chip's emulated 64-bit integer ALU is slow), so
        # the default is the f64-emulated scatter (error ~(n/G)*2^-48,
        # far inside the engines' 1e-9 comparison tolerance).
        return _seg_sum_f64_pair(plan, acc, ok)
    return jax.ops.segment_sum(contrib, plan.seg_id, num_segments=cap)


def _pair_sum_enabled() -> bool:
    from ..config import get_active, AGG_PAIR_SUM
    try:
        return bool(get_active().get(AGG_PAIR_SUM))
    except Exception:  # noqa: BLE001 - before config init
        return False


# -- f32-pair superaccumulator for FLOAT64 sums ------------------------------
# The chip has no f64 ALU: XLA emulates f64 as an (hi, lo) f32 pair, so a
# FLOAT64 column's device value IS hi+lo with 24-bit-exact components.
# Summing with emulated adds costs a long pair-arithmetic chain per element
# AND loses precision with batch size.  Instead: split each value into its
# two f32 components (exact), decompose each component into <=2 signed
# 32-bit limb contributions on a 160-bit integer window anchored at the
# batch max exponent, reduce per limb with integer prefix sums over the
# sorted segment order (seg_prefix_sum: cumsum + boundary gathers), and
# reconstruct one f32-pair result per GROUP.  Deterministic,
# order-independent, error <= 2^-47 relative to the window (terms >W0
# bits below the batch max fold into sticky; W0 ~ 111 bits).

_PAIR_NL = 5                 # 160-bit window


def _pair_w0(n: int) -> int:
    # 2n terms (hi+lo per row); keep c1 within limb NL-1: j = W0>>5 <= 3
    return min(127, _PAIR_NL * 32 - 24 - (2 * max(n, 2)).bit_length() - 2)


def _f32_parts(sig, e, fin_ok, emax, W0):
    """One f32 component -> (limb index j, c0, c1, lost) contributions.

    value = sig * 2^(e-150); window bit 0 weighs 2^(emax-150-W0)."""
    d = emax - e
    p = jnp.int32(W0) - d
    keep = fin_ok & (p > jnp.int32(-24)) & (sig != jnp.uint64(0))
    rs = jnp.clip(-p, 0, 31).astype(jnp.uint64)
    sig2 = sig >> rs
    lost = fin_ok & ((sig2 << rs) != sig)
    lost = lost | (fin_ok & (p <= jnp.int32(-24)) & (sig != jnp.uint64(0)))
    pc = jnp.clip(p, 0, W0)
    j = pc >> jnp.int32(5)
    r = (pc & jnp.int32(31)).astype(jnp.uint64)
    l64 = sig2 << r                                  # <= 55 bits
    c0 = (l64 & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64)
    c1 = (l64 >> jnp.uint64(32)).astype(jnp.int64)
    return j, c0, c1, keep, lost


def _unpack_f32(f):
    u = jax.lax.bitcast_convert_type(f, jnp.uint32)
    neg = (u >> jnp.uint32(31)) != jnp.uint32(0)
    e = ((u >> jnp.uint32(23)) & jnp.uint32(0xFF)).astype(jnp.int32)
    m = (u & jnp.uint32(0x7FFFFF)).astype(jnp.uint64)
    sig = jnp.where(e > 0, m | jnp.uint64(1 << 23), m)
    ee = jnp.maximum(e, 1)
    return neg, ee, sig, e


def _pack_f32(sig24, e_biased):
    """(up-to-24-bit significand, biased f32 exponent for bit 23)
    -> f32, with left-normalization of leading zeros, subnormal squeeze
    and overflow->inf.  No rounding: the caller passes truncated bits
    (we keep 48 = 2x24 bits total, well past the pair's precision)."""
    # normalize: shift the MSB up to bit 23 (the residual component can
    # carry leading zeros when the sum's bits 39..16 start low)
    lz = jnp.zeros(sig24.shape, jnp.int32)
    x = sig24
    for shift in (16, 8, 4, 2, 1):
        m = x < (jnp.uint64(1) << jnp.uint64(24 - shift))
        lz = jnp.where(m, lz + shift, lz)
        x = jnp.where(m, x << jnp.uint64(shift), x)
    lz = jnp.minimum(lz, jnp.int32(24))
    sig24 = jnp.where(sig24 == jnp.uint64(0), sig24,
                      sig24 << jnp.clip(lz, 0, 24).astype(jnp.uint64))
    e_biased = e_biased - lz
    squeeze = jnp.clip(jnp.int32(1) - e_biased, 0, 31).astype(jnp.uint64)
    sig = sig24 >> squeeze
    e = jnp.where(squeeze > 0, jnp.int32(1), e_biased)
    subn = sig < jnp.uint64(1 << 23)
    exp_field = jnp.where(subn | (sig == jnp.uint64(0)), jnp.int32(0), e)
    u = ((exp_field.astype(jnp.uint32) & jnp.uint32(0xFF))
         << jnp.uint32(23)) | \
        (sig.astype(jnp.uint32) & jnp.uint32(0x7FFFFF))
    u = jnp.where(e_biased > 254, jnp.uint32(0x7F800000), u)
    return jax.lax.bitcast_convert_type(u, jnp.float32)


def _seg_sum_f64_pair(plan: GroupPlan, v, ok):
    n = v.shape[0]
    W0 = _pair_w0(n)
    fin = jnp.isfinite(v)
    fin_ok = ok & fin
    nan_f = ok & jnp.isnan(v)
    pinf_f = ok & jnp.isposinf(v)
    ninf_f = ok & jnp.isneginf(v)
    vq = jnp.where(fin_ok, v, 0.0)
    hi = vq.astype(jnp.float32)
    lo = (vq - hi.astype(jnp.float64)).astype(jnp.float32)
    hneg, he, hsig, _ = _unpack_f32(hi)
    lneg, le, lsig, _ = _unpack_f32(lo)
    # per-GROUP anchor: one large-magnitude group must not push other
    # groups' rows below the window (i32 scatter-max is native)
    emax_g = jax.ops.segment_max(jnp.where(fin_ok, he, jnp.int32(0)),
                                 plan.seg_id, num_segments=n)
    emax = jnp.take(emax_g, plan.seg_id)
    hj, hc0, hc1, hkeep, hlost = _f32_parts(hsig, he, fin_ok, emax, W0)
    lj, lc0, lc1, lkeep, llost = _f32_parts(lsig, le, fin_ok, emax, W0)
    z = jnp.int64(0)
    hs = jnp.where(hneg, jnp.int64(-1), jnp.int64(1))
    ls = jnp.where(lneg, jnp.int64(-1), jnp.int64(1))
    hc0 = jnp.where(hkeep, hc0 * hs, z)
    hc1 = jnp.where(hkeep, hc1 * hs, z)
    lc0 = jnp.where(lkeep, lc0 * ls, z)
    lc1 = jnp.where(lkeep, lc1 * ls, z)
    limbs = []
    for L in range(_PAIR_NL):
        lc = jnp.where(hj == L, hc0, z) + jnp.where(lj == L, lc0, z)
        if L >= 1:
            lc = lc + jnp.where(hj == L - 1, hc1, z) + \
                jnp.where(lj == L - 1, lc1, z)
        limbs.append(seg_prefix_sum(plan, lc))
    nan_cnt = seg_prefix_sum(plan, nan_f.astype(jnp.int32))
    pinf_cnt = seg_prefix_sum(plan, pinf_f.astype(jnp.int32))
    ninf_cnt = seg_prefix_sum(plan, ninf_f.astype(jnp.int32))

    # ---- per-group finalize ----
    m32 = jnp.int64(0xFFFFFFFF)
    carry = jnp.int64(0)
    lo32s = []
    for L in range(_PAIR_NL):
        s = limbs[L] + carry
        l32 = s & m32
        carry = (s - l32) >> jnp.int64(32)
        lo32s.append(l32)
    total_neg = carry < 0
    mags = []
    c = jnp.where(total_neg, jnp.int64(1), jnp.int64(0))
    for L in range(_PAIR_NL):
        t = jnp.where(total_neg, (~lo32s[L]) & m32, lo32s[L]) + c
        mags.append((t & m32).astype(jnp.uint64))
        c = jnp.where(total_neg, t >> jnp.int64(32), jnp.int64(0))
    words = [(mags[1] << jnp.uint64(32)) | mags[0],
             (mags[3] << jnp.uint64(32)) | mags[2],
             mags[4]]
    nzs = [w != jnp.uint64(0) for w in words]
    top = jnp.zeros(n, jnp.int32)
    any_nz = jnp.zeros(n, bool)
    for i in range(3):
        top = jnp.where(nzs[i], jnp.int32(i), top)
        any_nz = any_nz | nzs[i]

    def pick(idx):
        out = jnp.zeros(n, jnp.uint64)
        for i in range(3):
            out = jnp.where(idx == i, words[i], out)
        return out
    hiw = pick(top)
    loww = pick(top - 1)
    from .binary64 import _clz64
    lz = _clz64(hiw)
    lzu = jnp.clip(lz, 0, 63).astype(jnp.uint64)
    combined = (hiw << lzu) | ((loww >> (jnp.uint64(63) - lzu))
                               >> jnp.uint64(1))
    b_msb = jnp.int64(64) * top.astype(jnp.int64) + 63 - lz
    # f32-biased exponent of the MSB: 2^(b_msb + emax-150-W0) = 2^(e-127)
    e1 = (b_msb + emax_g.astype(jnp.int64) -
          jnp.int64(W0 + 23)).astype(jnp.int32)
    f1 = _pack_f32(combined >> jnp.uint64(40), e1)
    # second component: next 24 bits, 24 binades down
    sig2 = (combined >> jnp.uint64(16)) & jnp.uint64(0xFFFFFF)
    f2 = _pack_f32(sig2, e1 - 24)
    mag_val = f1.astype(jnp.float64) + f2.astype(jnp.float64)
    out = jnp.where(total_neg, -mag_val, mag_val)
    out = jnp.where(any_nz, out, 0.0)
    out = jnp.where(pinf_cnt > 0, jnp.float64(jnp.inf), out)
    out = jnp.where(ninf_cnt > 0, jnp.float64(-jnp.inf), out)
    out = jnp.where((nan_cnt > 0) | ((pinf_cnt > 0) & (ninf_cnt > 0)),
                    jnp.float64(jnp.nan), out)
    gi = jnp.arange(n, dtype=jnp.int32)
    return jnp.where(gi < plan.num_groups, out, 0.0)


def seg_count(plan: GroupPlan, validity):
    _, ok = _sorted_vals(plan, validity, validity)
    return seg_prefix_sum(plan, ok.astype(jnp.int32)).astype(jnp.int64)


def seg_count_all(plan: GroupPlan):
    return seg_prefix_sum(
        plan, plan.live_sorted.astype(jnp.int32)).astype(jnp.int64)


def _type_extreme(dtype, want_max: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if not want_max else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if not want_max else info.min, dtype)


def seg_minmax_u64(plan: GroupPlan, words, ok, want_max: bool):
    """Per-group min/max of uint64 order-words WITHOUT a 64-bit scatter:
    two u32 scatter passes (hi word, then lo word among hi-winners).
    64-bit scatters are ~5x slower than 32-bit ones on the chip (XLA
    lowers i64 as 32-bit pairs); this keeps the reduction native."""
    cap = words.shape[0]
    w = words.astype(jnp.uint64)
    if not want_max:
        w = ~w                               # min == max of complement
    hi = (w >> jnp.uint64(32)).astype(jnp.uint32)
    lo = (w & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    z = jnp.uint32(0)
    mhi = jax.ops.segment_max(jnp.where(ok, hi, z), plan.seg_id,
                              num_segments=cap)
    on_hi = ok & (hi == jnp.take(mhi, plan.seg_id))
    mlo = jax.ops.segment_max(jnp.where(on_hi, lo, z), plan.seg_id,
                              num_segments=cap)
    out = (mhi.astype(jnp.uint64) << jnp.uint64(32)) | \
        mlo.astype(jnp.uint64)
    if not want_max:
        out = ~out
    return out


def _seg_minmax_i64(plan, v, ok, want_max: bool):
    # order-preserving int64 -> uint64 (flip sign bit), two-stage u32
    w = v.astype(jnp.uint64) ^ jnp.uint64(1 << 63)
    # masked-off rows contribute the identity via ok in seg_minmax_u64
    m = seg_minmax_u64(plan, w, ok, want_max)
    out = (m ^ jnp.uint64(1 << 63)).astype(jnp.int64)
    # groups with no contributing rows keep the type identity (the
    # caller masks validity by count anyway)
    has = seg_prefix_sum(plan, ok.astype(jnp.int32)) > 0
    ident = _type_extreme(jnp.int64, want_max)
    return jnp.where(has, out, ident)


def seg_min(plan: GroupPlan, values, validity):
    cap = values.shape[0]
    v, ok = _sorted_vals(plan, values, validity)
    if jnp.issubdtype(v.dtype, jnp.floating):
        # Spark total order: NaN greatest, -0.0 == 0.0.  No bit encoding
        # (the chip cannot bitcast f64): min over non-NaN values, falling
        # back to NaN only when a group has nothing else.
        v = jnp.where(v == 0.0, jnp.array(0.0, v.dtype), v)
        nan = jnp.isnan(v)
        contrib = jnp.where(ok & ~nan, v, jnp.array(jnp.inf, v.dtype))
        m = jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)
        has_num = seg_prefix_sum(plan, (ok & ~nan).astype(jnp.int32)) > 0
        return jnp.where(has_num, m, jnp.array(jnp.nan, v.dtype))
    if v.dtype in (jnp.int64, jnp.uint64):
        if v.dtype == jnp.uint64:
            return seg_minmax_u64(plan, v, ok, want_max=False)
        return _seg_minmax_i64(plan, v, ok, want_max=False)
    ident = _type_extreme(v.dtype, want_max=False)
    contrib = jnp.where(ok, v, ident)
    return jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)


def seg_max(plan: GroupPlan, values, validity):
    cap = values.shape[0]
    v, ok = _sorted_vals(plan, values, validity)
    if jnp.issubdtype(v.dtype, jnp.floating):
        # NaN is the greatest value: any NaN in the group wins
        v = jnp.where(v == 0.0, jnp.array(0.0, v.dtype), v)
        nan = jnp.isnan(v)
        contrib = jnp.where(ok & ~nan, v, jnp.array(-jnp.inf, v.dtype))
        m = jax.ops.segment_max(contrib, plan.seg_id, num_segments=cap)
        has_nan = seg_prefix_sum(plan, (ok & nan).astype(jnp.int32)) > 0
        return jnp.where(has_nan, jnp.array(jnp.nan, v.dtype), m)
    if v.dtype in (jnp.int64, jnp.uint64):
        if v.dtype == jnp.uint64:
            return seg_minmax_u64(plan, v, ok, want_max=True)
        return _seg_minmax_i64(plan, v, ok, want_max=True)
    ident = _type_extreme(v.dtype, want_max=True)
    contrib = jnp.where(ok, v, ident)
    return jax.ops.segment_max(contrib, plan.seg_id, num_segments=cap)


def seg_first_index(plan: GroupPlan, validity, ignore_nulls: bool = True):
    """Original-row index of the first (valid) row per group."""
    cap = validity.shape[0]
    ok = jnp.take(validity, plan.perm) & plan.live_sorted if ignore_nulls \
        else plan.live_sorted
    pos = jnp.arange(cap, dtype=jnp.int32)
    contrib = jnp.where(ok, pos, jnp.int32(cap))
    first_pos = jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)
    safe = jnp.clip(first_pos, 0, cap - 1).astype(jnp.int32)
    return jnp.take(plan.perm, safe), first_pos < cap


def seg_first_index_by_order(plan: GroupPlan, col, want_min: bool = True,
                             num_rows: int = None):
    """Index of the lexicographically min/max value per group (strings etc.).

    Works on canonical value words: iteratively narrow candidates word by
    word with segment_min, then take the first surviving index.
    """
    from . import canon
    cap = col.capacity
    if num_rows is None:
        num_rows = cap
    words = canon.value_words(col, num_rows)
    if not want_min:
        words = [~w for w in words]
    ok = jnp.take(col.validity, plan.perm) & plan.live_sorted
    cand = ok
    for w in words:
        ws = jnp.take(w, plan.perm).astype(jnp.uint64)
        m = seg_minmax_u64(plan, ws, cand, want_max=False)
        cand = cand & (ws == jnp.take(m, plan.seg_id))
    pos = jnp.arange(cap, dtype=jnp.int32)
    contrib = jnp.where(cand, pos, jnp.int32(cap))
    first_pos = jax.ops.segment_min(contrib, plan.seg_id, num_segments=cap)
    has = first_pos < cap
    safe = jnp.clip(first_pos, 0, cap - 1).astype(jnp.int32)
    return jnp.take(plan.perm, safe), has


def seg_last_index(plan: GroupPlan, validity, ignore_nulls: bool = True):
    cap = validity.shape[0]
    ok = jnp.take(validity, plan.perm) & plan.live_sorted if ignore_nulls \
        else plan.live_sorted
    pos = jnp.arange(cap, dtype=jnp.int32)
    contrib = jnp.where(ok, pos, jnp.int32(-1))
    last_pos = jax.ops.segment_max(contrib, plan.seg_id, num_segments=cap)
    safe = jnp.clip(last_pos, 0, cap - 1).astype(jnp.int32)
    return jnp.take(plan.perm, safe), last_pos >= 0


# ---------------------------------------------------------------------------
# Sort-free bucket-table group-by (the TPU-native fast path).
#
# Reference context: cuDF's hash group-by (aggregate.scala:240 lowers to
# open-addressing hash tables on GPU).  Hash probing is hostile to XLA,
# but most BI group-bys have small combined key cardinality RANGE —
# so instead of hashing, each key word is rebased by its device-computed
# minimum and the keys mixed-radix-packed into a bucket id < table_size.
# Aggregation is then direct per-bucket reduction: sums/counts ride
# one-hot matmuls on the MXU; min/max ride small-output scatters.
# No sort, no gathers, no 64-bit scatters (which cost ~20x f32 on TPU).
#
# A device-side `fit` flag records whether the batch really fit the
# table (key range, u32 value range for int min/max, f32 finiteness for
# float sums); callers dispatch speculatively and re-run the rare
# non-fitting batch on the general sort path (exec/tpu_aggregate.py).
# ---------------------------------------------------------------------------


def table_bucket(key_words, key_valids, live, table: int):
    """Mixed-radix bucket assignment over single-word keys.

    key_words: one uint64 word per key (canon.value_words[0]);
    key_valids: per-key validity; live: row mask (in-range AND past any
    folded-in filters).  Each key contributes digit 0 for null and
    1 + (word - min) otherwise; digits pack most-significant-first, so
    bucket ascending == (nulls-first key tuple) ascending — matching the
    sort path's group order.  Dead rows get bucket == table.
    Returns (bucket i32[cap], fit bool, mins, cards).
    """
    cap = key_words[0].shape[0]
    bucket = jnp.zeros(cap, jnp.int32)
    total = jnp.uint64(1)
    fit = jnp.bool_(True)
    mins, cards = [], []
    for w, valid in zip(key_words, key_valids):
        lv = live & valid
        any_v = jnp.any(lv)
        wmin = jnp.where(any_v,
                         jnp.min(jnp.where(lv, w, jnp.uint64(2**64 - 1))),
                         jnp.uint64(0))
        wmax = jnp.where(any_v,
                         jnp.max(jnp.where(lv, w, jnp.uint64(0))),
                         jnp.uint64(0))
        rng = wmax - wmin
        # card clamped so products can't wrap; fit goes False anyway
        card = jnp.minimum(rng, jnp.uint64(table)).astype(jnp.int32) + 2
        total = jnp.minimum(total * card.astype(jnp.uint64),
                            jnp.uint64(1) << jnp.uint64(32))
        digit = jnp.where(
            valid,
            jnp.minimum(w - wmin, jnp.uint64(table)).astype(jnp.int32) + 1,
            0)
        bucket = jnp.minimum(bucket * card + digit, table)
        mins.append(wmin)
        cards.append(card)
    fit = total <= jnp.uint64(table)
    bucket = jnp.where(live, bucket, table).astype(jnp.int32)
    return bucket, fit, mins, cards


def table_compact(counts, table: int):
    """Group directory from per-bucket live counts: (present, order,
    num_groups) where order[g] = bucket of group g, ascending."""
    present = counts > 0
    num_groups = jnp.sum(present).astype(jnp.int32)
    order = jnp.argsort(jnp.where(present, 0, 1), stable=True) \
        .astype(jnp.int32)
    return present, order, num_groups
