"""Exact IEEE-754 binary64 arithmetic as integer kernels ("softfloat").

Why this exists: real TPUs have no float64 ALU.  XLA emulates ``f64`` with
a pair of ``f32``s, which means ~48-bit precision, an f32 exponent range
(doubles beyond ~1e38 become inf/NaN, below ~1e-38 flush to zero) and
non-IEEE rounding — a 1e300 SQL DOUBLE literally cannot round-trip device
memory.  SQL DOUBLE semantics (Spark/cuDF, reference: GpuCast.scala,
arithmetic.scala) require the full binary64 domain.

The TPU-native answer: a DOUBLE column's device buffer holds the IEEE-754
**bit pattern in int64**, and arithmetic is exact integer IEEE-754
implemented here.  64-bit *integer* ops ARE exact on TPU (XLA lowers them
to 32-bit pair arithmetic losslessly — verified by probe), so every kernel
below is bit-exact with the host's float64, including subnormals,
signed zeros, infinities and round-to-nearest-even.

This is also a win for the rest of the engine: ordering, grouping, joins
and hash partitioning already operate on integer key words
(kernels/canon.py), so doubles-as-bits removes the only non-integer data
path from the device entirely.

Every public function takes/returns **int64 arrays of bit patterns**
(referred to as "bits").  Scalars enter via :func:`bits_of`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# -- constants (python ints; jnp scalars are created lazily inside kernels) --
SIGN = 0x8000000000000000
EXP_MASK = 0x7FF0000000000000
MANT_MASK = 0x000FFFFFFFFFFFFF
MAG_MASK = 0x7FFFFFFFFFFFFFFF
IMPLICIT = 1 << 52
QNAN = 0x7FF8000000000000
INF = 0x7FF0000000000000
ONE = 0x3FF0000000000000
MAX_FINITE = 0x7FEFFFFFFFFFFFFF


def bits_of(value: float) -> int:
    """Host-side: python float -> bit-pattern int (for literals/fills)."""
    return int(np.float64(value).view(np.int64))


def float_of(bits: int) -> float:
    """Host-side: bit-pattern int -> python float."""
    return float(np.int64(bits).view(np.float64))


def _u(x):
    return x.astype(jnp.uint64) if x.dtype != jnp.uint64 else x


def _i(x):
    return x.astype(jnp.int64) if x.dtype != jnp.int64 else x


def _c(v):
    return jnp.uint64(v)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def is_nan(bits) -> jnp.ndarray:
    u = _u(bits)
    return (u & _c(MAG_MASK)) > _c(INF)


def is_inf(bits) -> jnp.ndarray:
    u = _u(bits)
    return (u & _c(MAG_MASK)) == _c(INF)


def is_zero(bits) -> jnp.ndarray:
    u = _u(bits)
    return (u & _c(MAG_MASK)) == _c(0)


def is_finite(bits) -> jnp.ndarray:
    u = _u(bits)
    return (u & _c(EXP_MASK)) != _c(EXP_MASK)


def is_negative(bits) -> jnp.ndarray:
    """Sign bit set (true for -0.0; NaN sign is ignored by callers)."""
    return (_u(bits) & _c(SIGN)) != _c(0)


def sign_column(bits) -> jnp.ndarray:
    """Spark Signum: -1.0 / 0.0 / 1.0 (NaN -> NaN), as bits."""
    neg = bits_const(-1.0)
    pos = bits_const(1.0)
    zero = jnp.int64(0)
    out = jnp.where(is_zero(bits), zero,
                    jnp.where(is_negative(bits), neg, pos))
    return jnp.where(is_nan(bits), jnp.int64(QNAN), out)


def bits_const(value: float):
    return jnp.int64(bits_of(value))


# ---------------------------------------------------------------------------
# ordering (Spark total order: -NaN conflated, NaN greatest, -0.0 == 0.0)
# ---------------------------------------------------------------------------

def order_word(bits) -> jnp.ndarray:
    """uint64 whose unsigned order equals Spark's total order on doubles.

    All NaNs are canonicalized to +QNaN, and -0.0 to +0.0, *before* the
    IEEE flip trick (reference: NormalizeFloatingNumbers.scala), so
    NaN == NaN and -0.0 == 0.0 hold under plain integer equality.
    """
    u = _u(bits)
    u = jnp.where(is_nan(u), _c(QNAN), u)
    u = jnp.where((u & _c(MAG_MASK)) == _c(0), _c(0), u)
    neg = (u & _c(SIGN)) != _c(0)
    return jnp.where(neg, ~u, u | _c(SIGN))


def word_to_bits(word) -> jnp.ndarray:
    """Inverse of order_word (canonicalized values only)."""
    w = _u(word)
    neg = (w & _c(SIGN)) == _c(0)
    return _i(jnp.where(neg, ~w, w & _c(MAG_MASK)))


def lt(a_bits, b_bits):
    return order_word(a_bits) < order_word(b_bits)


def le(a_bits, b_bits):
    return order_word(a_bits) <= order_word(b_bits)


def eq(a_bits, b_bits):
    return order_word(a_bits) == order_word(b_bits)


def min2(a_bits, b_bits):
    return jnp.where(lt(b_bits, a_bits), b_bits, a_bits)


def max2(a_bits, b_bits):
    return jnp.where(lt(a_bits, b_bits), b_bits, a_bits)


# ---------------------------------------------------------------------------
# bit utilities
# ---------------------------------------------------------------------------

def _clz64(x):
    """Count leading zeros of uint64 (64 for zero) via binary reduction."""
    x = _u(x)
    n = jnp.zeros(x.shape, jnp.int64)
    for shift in (32, 16, 8, 4, 2, 1):
        mask = x < (_c(1) << _c(64 - shift))
        n = jnp.where(mask, n + shift, n)
        x = jnp.where(mask, x << _c(shift), x)
    return n


def _unpack(bits):
    """-> (neg bool, exp int64 raw 0..2047, mant uint64 52-bit)."""
    u = _u(bits)
    neg = (u & _c(SIGN)) != _c(0)
    exp = ((u & _c(EXP_MASK)) >> _c(52)).astype(jnp.int64)
    mant = u & _c(MANT_MASK)
    return neg, exp, mant


def _significand(exp, mant):
    """Effective (significand, exponent) treating subnormals as exp=1."""
    sig = jnp.where(exp > 0, mant | _c(IMPLICIT), mant)
    e = jnp.where(exp > 0, exp, jnp.int64(1))
    return sig, e


def _pack(neg, exp, mant):
    """exp: biased int64 (1..2046 normal); mant 52-bit; no rounding."""
    u = (_u(exp) << _c(52)) | (_u(mant) & _c(MANT_MASK))
    return _i(jnp.where(neg, u | _c(SIGN), u))


def _shift_right_sticky(sig, n):
    """sig >> n with sticky-OR of shifted-out bits; n >= 0 (clamped 63)."""
    n = jnp.minimum(n.astype(jnp.int64), jnp.int64(63))
    nn = _u(n)
    dropped = sig & ((_c(1) << nn) - _c(1))
    return (sig >> nn) | jnp.where(dropped != _c(0), _c(1), _c(0))


def _round_pack(neg, e, sig57):
    """Round-to-nearest-even a 57-bit significand (54 value bits + guard,
    round, sticky in the low 3 bits is NOT the layout here).

    Layout contract: ``sig57`` holds the significand aligned so the
    implicit-1 position is bit 55 (i.e. value bits 55..3) with bits 2..0 =
    guard/round/sticky.  ``e`` is the biased exponent for bit 55 == 2^52.
    Handles subnormal squeeze (e <= 0), overflow to inf, exact-zero.
    """
    # subnormal squeeze: shift right so e becomes 1
    squeeze = jnp.maximum(jnp.int64(1) - e, jnp.int64(0))
    sig57 = jnp.where(squeeze > 0, _shift_right_sticky(sig57, squeeze), sig57)
    e = jnp.where(squeeze > 0, jnp.int64(1), e)

    lsb = (sig57 >> _c(3)) & _c(1)
    guard = (sig57 >> _c(2)) & _c(1)
    rest = sig57 & _c(3)
    round_up = (guard == _c(1)) & ((rest != _c(0)) | (lsb == _c(1)))
    sig = (sig57 >> _c(3)) + jnp.where(round_up, _c(1), _c(0))

    # carry out of rounding: significand reached 2^53 -> renormalize
    carried = sig >= _c(1 << 53)
    sig = jnp.where(carried, sig >> _c(1), sig)
    e = jnp.where(carried, e + 1, e)

    # result subnormal if significand lost its implicit bit
    subn = sig < _c(IMPLICIT)
    exp_field = jnp.where(subn, jnp.int64(0), e)
    exp_field = jnp.where(sig == _c(0), jnp.int64(0), exp_field)

    overflow = e > 2046
    out = _pack(neg, exp_field, sig)
    out = jnp.where(overflow, _pack(neg, jnp.int64(2047), _c(0)), out)
    return out


# ---------------------------------------------------------------------------
# add / sub
# ---------------------------------------------------------------------------

def add(a_bits, b_bits):
    """IEEE-754 binary64 addition, round-to-nearest-even."""
    an, ae, am = _unpack(a_bits)
    bn, be, bm = _unpack(b_bits)
    asig, aexp = _significand(ae, am)
    bsig, bexp = _significand(be, bm)

    # order by magnitude (exp, mant): big, small
    a_mag = _u(a_bits) & _c(MAG_MASK)
    b_mag = _u(b_bits) & _c(MAG_MASK)
    swap = b_mag > a_mag
    big_sig = jnp.where(swap, bsig, asig)
    big_e = jnp.where(swap, bexp, aexp)
    big_n = jnp.where(swap, bn, an)
    sml_sig = jnp.where(swap, asig, bsig)
    sml_e = jnp.where(swap, aexp, bexp)
    sml_n = jnp.where(swap, an, bn)

    # align with 3 extra bits (guard/round/sticky); implicit at bit 55
    big55 = big_sig << _c(3)
    sml55 = _shift_right_sticky(sml_sig << _c(3), big_e - sml_e)

    same_sign = big_n == sml_n
    ssum = big55 + sml55                       # <= 2^57
    sdiff = big55 - sml55                      # >= 0 by magnitude order
    sig = jnp.where(same_sign, ssum, sdiff)

    # normalize: same-sign may carry to bit 56; diff may cancel low
    carry = sig >= _c(1 << 56)
    sig = jnp.where(carry, _shift_right_sticky(sig, jnp.int64(1)), sig)
    e = jnp.where(carry, big_e + 1, big_e)
    # left-normalize after cancellation (keep exponent >= 1 for subnormals)
    lz = _clz64(sig) - 8                       # bits above position 55
    shift_l = jnp.clip(lz, 0, jnp.maximum(e - 1, 0))
    sig = sig << _u(shift_l)
    e = e - shift_l

    out = _round_pack(big_n, e, sig)
    # exact cancellation -> +0.0 (RNE rule)
    out = jnp.where(sig == _c(0), jnp.int64(0), out)

    # specials
    a_nan, b_nan = is_nan(a_bits), is_nan(b_bits)
    a_inf, b_inf = is_inf(a_bits), is_inf(b_bits)
    an_s = is_negative(a_bits)
    bn_s = is_negative(b_bits)
    out = jnp.where(a_inf & b_inf & (an_s != bn_s), jnp.int64(QNAN),
                    jnp.where(a_inf, _i(_u(a_bits)),
                              jnp.where(b_inf, _i(_u(b_bits)), out)))
    # x + (-x) handled above; zero operands: 0 + y = y exactly, but
    # -0 + -0 = -0
    both_zero = is_zero(a_bits) & is_zero(b_bits)
    neg_zero = both_zero & an_s & bn_s
    neg_zero_bits = jnp.int64(SIGN - 2 ** 64)          # -0.0 as signed i64
    out = jnp.where(both_zero, jnp.where(neg_zero, neg_zero_bits,
                                         jnp.int64(0)), out)
    only_a = is_zero(b_bits) & ~is_zero(a_bits)
    only_b = is_zero(a_bits) & ~is_zero(b_bits)
    out = jnp.where(only_a, _i(_u(a_bits)), out)
    out = jnp.where(only_b, _i(_u(b_bits)), out)
    out = jnp.where(a_nan | b_nan, jnp.int64(QNAN), out)
    return out


def neg(bits):
    return _i(_u(bits) ^ _c(SIGN))


def sub(a_bits, b_bits):
    return add(a_bits, neg(b_bits))


def abs_(bits):
    return _i(_u(bits) & _c(MAG_MASK))


# ---------------------------------------------------------------------------
# mul
# ---------------------------------------------------------------------------

def _mul_64x64(a, b):
    """Full 128-bit product of two uint64 -> (hi, lo) uint64."""
    mask32 = _c(0xFFFFFFFF)
    a0 = a & mask32
    a1 = a >> _c(32)
    b0 = b & mask32
    b1 = b >> _c(32)
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> _c(32)) + (p01 & mask32) + (p10 & mask32)
    lo = (p00 & mask32) | (mid << _c(32))
    hi = p11 + (p01 >> _c(32)) + (p10 >> _c(32)) + (mid >> _c(32))
    return hi, lo


def _normalize_sig(sig, e):
    """Shift significand up so the implicit bit is at position 52
    (subnormal inputs), adjusting the exponent."""
    lz = _clz64(sig) - 11           # leading zeros above bit 52
    lz = jnp.maximum(lz, jnp.int64(0))
    return sig << _u(lz), e - lz


def mul(a_bits, b_bits):
    """IEEE-754 binary64 multiplication, round-to-nearest-even."""
    an, ae, am = _unpack(a_bits)
    bn, be, bm = _unpack(b_bits)
    rn = an != bn
    asig, aexp = _significand(ae, am)
    bsig, bexp = _significand(be, bm)
    asig, aexp = _normalize_sig(asig, aexp)
    bsig, bexp = _normalize_sig(bsig, bexp)

    hi, lo = _mul_64x64(asig, bsig)           # product in [2^104, 2^106)
    # significand target: implicit at bit 55 (56-bit value + grs in round)
    # product bit 105 set => top = bit 105; else bit 104.
    top105 = (hi & _c(1 << 41)) != _c(0)
    # take bits [105..50] or [104..49] into a 56-bit sig with sticky
    shift = jnp.where(top105, jnp.int64(50), jnp.int64(49))
    # sig = (hi:lo) >> shift, sticky from dropped lo bits
    sh = _u(shift)
    sig = (hi << (_c(64) - sh)) | (lo >> sh)
    dropped = lo & ((_c(1) << sh) - _c(1))
    sig = sig | jnp.where(dropped != _c(0), _c(1), _c(0))
    e = aexp + bexp - 1023 + jnp.where(top105, jnp.int64(1), jnp.int64(0))

    out = _round_pack(rn, e, sig)

    # specials
    a_nan, b_nan = is_nan(a_bits), is_nan(b_bits)
    a_inf, b_inf = is_inf(a_bits), is_inf(b_bits)
    a_zero, b_zero = is_zero(a_bits), is_zero(b_bits)
    inf_times_zero = (a_inf & b_zero) | (b_inf & a_zero)
    signed_zero = _i(jnp.where(rn, _c(SIGN), _c(0)))
    signed_inf = _i(jnp.where(rn, _c(SIGN | INF), _c(INF)))
    out = jnp.where(a_zero | b_zero, signed_zero, out)
    out = jnp.where(a_inf | b_inf, signed_inf, out)
    out = jnp.where(inf_times_zero | a_nan | b_nan, jnp.int64(QNAN), out)
    return out


# ---------------------------------------------------------------------------
# div
# ---------------------------------------------------------------------------

def div(a_bits, b_bits):
    """IEEE-754 binary64 division, round-to-nearest-even.

    Mantissa quotient by vectorized shift-subtract long division (55 bits +
    sticky) under ``lax.fori_loop`` — pure u64 compare/sub/shift per step,
    which XLA maps well onto the VPU's integer lanes.
    """
    an, ae, am = _unpack(a_bits)
    bn, be, bm = _unpack(b_bits)
    rn = an != bn
    asig, aexp = _significand(ae, am)
    bsig, bexp = _significand(be, bm)
    asig, aexp = _normalize_sig(asig, aexp)
    bsig, bexp = _normalize_sig(bsig, bexp)

    def step(_, state):
        rem, q = state
        ge = rem >= bsig
        rem = jnp.where(ge, rem - bsig, rem)
        q = (q << _c(1)) | jnp.where(ge, _c(1), _c(0))
        rem = rem << _c(1)
        return rem, q

    rem0 = asig
    q0 = jnp.zeros_like(asig)
    rem, q = jax.lax.fori_loop(0, 57, step, (rem0, q0))
    # q = floor(asig * 2^56 / bsig) in [2^55, 2^57); invariant rem < 2*bsig
    sticky = jnp.where(rem != _c(0), _c(1), _c(0))
    top57 = (q & _c(1 << 56)) != _c(0)
    # align implicit to bit 55: if quotient >= 2^56 shift down one
    sig = jnp.where(top57, _shift_right_sticky(q, jnp.int64(1)), q) | sticky
    e = aexp - bexp + 1023 + jnp.where(top57, jnp.int64(0), jnp.int64(-1))

    out = _round_pack(rn, e, sig)

    # specials
    a_nan, b_nan = is_nan(a_bits), is_nan(b_bits)
    a_inf, b_inf = is_inf(a_bits), is_inf(b_bits)
    a_zero, b_zero = is_zero(a_bits), is_zero(b_bits)
    signed_zero = _i(jnp.where(rn, _c(SIGN), _c(0)))
    signed_inf = _i(jnp.where(rn, _c(SIGN | INF), _c(INF)))
    out = jnp.where(b_inf, signed_zero, out)
    out = jnp.where(b_zero, signed_inf, out)
    out = jnp.where(a_zero, signed_zero, out)
    out = jnp.where(a_inf, signed_inf, out)
    nan_out = (a_nan | b_nan | (a_zero & b_zero) | (a_inf & b_inf))
    out = jnp.where(nan_out, jnp.int64(QNAN), out)
    return out


# ---------------------------------------------------------------------------
# sqrt
# ---------------------------------------------------------------------------

def sqrt(a_bits):
    """IEEE-754 binary64 square root (restoring digit recurrence, RNE)."""
    an, ae, am = _unpack(a_bits)
    sig, e = _significand(ae, am)
    sig, e = _normalize_sig(sig, e)
    # make unbiased exponent even: value = sig * 2^(e-1075+52)... work with
    # m in [2^52, 2^54): if exponent odd, shift sig left 1
    eu = e - 1023                      # unbiased
    odd = (eu & 1) != 0
    m = jnp.where(odd, sig << _c(1), sig)
    half_e = jnp.where(odd, (eu - 1) // 2, eu // 2)

    # digit recurrence on radicand R = m << 54 (108 bits): root of 54 bits
    # (53 value bits + 1 guard).  rem stays < 4*root + 4 => fits u64.
    def step(i, state):
        rem, root = state
        # bring down bit pair i of R (m occupies bits 107..54 of R)
        shift = jnp.maximum(jnp.int64(52) - 2 * i, jnp.int64(0))
        bits2 = jnp.where(jnp.int64(52) - 2 * i >= 0,
                          (m >> _u(shift)) & _c(3), _c(0))
        rem = (rem << _c(2)) | bits2
        trial = (root << _c(2)) | _c(1)
        ge = rem >= trial
        rem = jnp.where(ge, rem - trial, rem)
        root = (root << _c(1)) | jnp.where(ge, _c(1), _c(0))
        return rem, root

    rem0 = jnp.zeros_like(m)
    root0 = jnp.zeros_like(m)
    rem, root = jax.lax.fori_loop(0, 54, step, (rem0, root0))
    # root = floor(sqrt(m << 54)) in [2^53, 2^54): 53 value bits + guard.
    # sqrt never lands exactly between representables unless exact, so
    # guard + (rem != 0) sticky suffices for RNE.
    sticky = jnp.where(rem != _c(0), _c(1), _c(0))
    guard = root & _c(1)
    val53 = root >> _c(1)
    sig = (val53 << _c(3)) | (guard << _c(2)) | sticky
    out = _round_pack(jnp.zeros_like(an), half_e + 1023, sig)

    out = jnp.where(is_zero(a_bits), _i(_u(a_bits)), out)     # sqrt(±0)=±0
    neg_in = is_negative(a_bits) & ~is_zero(a_bits)
    out = jnp.where(is_inf(a_bits) & ~neg_in, jnp.int64(INF), out)
    out = jnp.where(neg_in | is_nan(a_bits), jnp.int64(QNAN), out)
    return out


# ---------------------------------------------------------------------------
# conversions
# ---------------------------------------------------------------------------

def from_i64(x):
    """int64 -> binary64 bits (RNE for |x| > 2^53)."""
    x = _i(x)
    neg_in = x < 0
    # |int64 min| overflows; handle via uint64 magnitude
    mag = jnp.where(neg_in, (~_u(x)) + _c(1), _u(x))
    lz = _clz64(mag)
    # place MSB at bit 55 (implicit position for _round_pack), grs below
    shift_l = lz - 8
    sig = jnp.where(shift_l >= 0, mag << _u(jnp.maximum(shift_l, 0)),
                    _shift_right_sticky(mag, -shift_l))
    e = jnp.int64(1086) - lz
    out = _round_pack(neg_in, e, sig)
    return jnp.where(mag == _c(0), jnp.int64(0), out)


def from_i32(x):
    return from_i64(x.astype(jnp.int64))


def to_i64(bits):
    """Truncate toward zero with Java/Spark long-cast semantics:
    NaN -> 0, saturate at Long.MIN/MAX."""
    n, exp, mant = _unpack(bits)
    sig, e = _significand(exp, mant)
    # value = sig * 2^(e - 1075); sig < 2^53
    right = jnp.clip(jnp.int64(1075) - e, 0, 63)
    left = jnp.clip(e - jnp.int64(1075), 0, 63)
    mag = jnp.where(e <= 1075, sig >> _u(right), sig << _u(left))
    out = jnp.where(n, -_i(mag), _i(mag))
    imax = jnp.int64(2 ** 63 - 1)
    imin = jnp.int64(-(2 ** 63))
    # e - 1075 >= 11 => |value| >= 2^63: saturate (covers exact -2^63 too)
    too_big = (e - jnp.int64(1075)) >= jnp.int64(11)
    out = jnp.where(too_big | is_inf(bits), jnp.where(n, imin, imax), out)
    out = jnp.where(is_nan(bits), jnp.int64(0), out)
    return out


def to_int(bits, np_dtype):
    """double -> integral cast with Spark non-ANSI semantics: NaN -> 0,
    saturate to the target bounds, truncate toward zero."""
    long = to_i64(bits)
    info = np.iinfo(np_dtype)
    clamped = jnp.clip(long, int(info.min), int(info.max))
    return clamped.astype(np_dtype)


def from_f32(f):
    """float32 array -> binary64 bits (exact widening; native u32 bitcast
    is supported on TPU)."""
    u32 = jax.lax.bitcast_convert_type(f, jnp.uint32).astype(jnp.uint64)
    sign = (u32 >> _c(31)) & _c(1)
    exp = ((u32 >> _c(23)) & _c(0xFF)).astype(jnp.int64)
    mant = u32 & _c(0x7FFFFF)
    # normal: rebias 127 -> 1023, mant << 29
    nexp = exp + (1023 - 127)
    out = _pack(sign != _c(0), nexp, mant << _c(29))
    # subnormal f32: value = mant * 2^-149 — normalize into f64 normal
    lz = _clz64(mant) - 41            # leading zeros above bit 22
    sub_mant = (mant << _u(lz + 1)) & _c(0x7FFFFF)     # drop implicit
    sub_exp = (1023 - 126) - (lz + 1)
    sub = _pack(sign != _c(0), sub_exp, sub_mant << _c(29))
    out = jnp.where(exp == 0, sub, out)
    out = jnp.where((exp == 0) & (mant == _c(0)),
                    _i((_u(sign) << _c(63))), out)
    inf_bits = _i((_u(sign) << _c(63)) | _c(INF))
    out = jnp.where(exp == 255,
                    jnp.where(mant == _c(0), inf_bits, jnp.int64(QNAN)), out)
    return out


def to_f32(bits):
    """binary64 bits -> float32 array (RNE narrowing)."""
    n, exp, mant = _unpack(bits)
    sig, e = _significand(exp, mant)
    sig, e = _normalize_sig(sig, e)
    # f32: 24-bit significand; rebias: e32 = e - 1023 + 127
    e32 = e - (1023 - 127)
    # shift 53-bit sig down to 24-bit value + grs: implicit from 52 to 26
    sig27 = _shift_right_sticky(sig, jnp.int64(52 - 26))
    # subnormal squeeze for f32
    squeeze = jnp.maximum(jnp.int64(1) - e32, jnp.int64(0))
    sig27 = jnp.where(squeeze > 0, _shift_right_sticky(sig27, squeeze),
                      sig27)
    e32 = jnp.where(squeeze > 0, jnp.int64(1), e32)
    lsb = (sig27 >> _c(3)) & _c(1)
    guard = (sig27 >> _c(2)) & _c(1)
    rest = sig27 & _c(3)
    round_up = (guard == _c(1)) & ((rest != _c(0)) | (lsb == _c(1)))
    sig24 = (sig27 >> _c(3)) + jnp.where(round_up, _c(1), _c(0))
    carried = sig24 >= _c(1 << 24)
    sig24 = jnp.where(carried, sig24 >> _c(1), sig24)
    e32 = jnp.where(carried, e32 + 1, e32)
    subn = sig24 < _c(1 << 23)
    exp_field = jnp.where(subn | (sig24 == _c(0)), jnp.int64(0), e32)
    overflow = e32 > 254
    u32 = ((_u(exp_field) & _c(0xFF)) << _c(23)) | (sig24 & _c(0x7FFFFF))
    u32 = jnp.where(overflow, _c(0x7F800000), u32)
    u32 = jnp.where(is_zero(bits), _c(0), u32)
    u32 = jnp.where(is_inf(bits), _c(0x7F800000), u32)
    u32 = jnp.where(is_nan(bits), _c(0x7FC00000), u32)
    u32 = u32 | jnp.where(n & ~is_nan(bits), _c(0x80000000), _c(0))
    return jax.lax.bitcast_convert_type(u32.astype(jnp.uint32), jnp.float32)


# ---------------------------------------------------------------------------
# integer-valued rounding
# ---------------------------------------------------------------------------

def trunc(bits):
    """Round toward zero to an integer-valued double."""
    n, exp, mant = _unpack(bits)
    e = exp - 1023                      # unbiased
    frac_bits = jnp.clip(jnp.int64(52) - e, 0, 63)
    mask = (_c(1) << _u(frac_bits)) - _c(1)
    new_mant = mant & ~mask
    out = _pack(n, exp, new_mant)
    out = jnp.where(e < 0, _i(jnp.where(n, _c(SIGN), _c(0))), out)
    out = jnp.where(e >= 52, _i(_u(bits)), out)
    out = jnp.where(~is_finite(bits), _i(_u(bits)), out)
    return out


def floor(bits):
    t = trunc(bits)
    went_up = is_negative(bits) & (order_word(t) != order_word(bits)) \
        & is_finite(bits)
    return jnp.where(went_up, sub(t, bits_const(1.0)), t)


def ceil(bits):
    t = trunc(bits)
    went_down = ~is_negative(bits) & (order_word(t) != order_word(bits)) \
        & is_finite(bits)
    return jnp.where(went_down, add(t, bits_const(1.0)), t)


def rint(bits):
    """Round half to even to an integer-valued double (Java Math.rint).

    Symmetric: computed on |x|, sign re-applied (preserves -0.0 results).
    """
    n, exp, mant = _unpack(bits)
    e = exp - 1023
    m = abs_(bits)
    down = trunc(m)                       # == floor for non-negative
    up = add(down, bits_const(1.0))
    # fractional part comparison against one half, in integer form
    sig, _ = _significand(exp, mant)
    frac_bits = jnp.clip(jnp.int64(52) - e, 0, 63)
    mask = (_c(1) << _u(frac_bits)) - _c(1)
    frac = sig & mask
    half = _c(1) << _u(jnp.maximum(frac_bits - 1, jnp.int64(0)))
    below = frac < half
    above = frac > half
    down_even = (to_i64(down) & jnp.int64(1)) == 0
    pick_down = below | (~above & down_even)
    out = jnp.where(pick_down, down, up)
    # e in [0, 52): general path above. e >= 52: already integer.
    out = jnp.where(e >= 52, m, out)
    # e == -1: |x| in [0.5, 1): tie at exactly 0.5 -> 0, else 1
    out = jnp.where(e == -1,
                    jnp.where(mant != _c(0), bits_const(1.0), jnp.int64(0)),
                    out)
    out = jnp.where(e < -1, jnp.int64(0), out)          # |x| < 0.5 -> 0
    out = jnp.where(is_zero(bits) | ~is_finite(bits), m, out)
    signed = jnp.where(n, neg(out), out)
    return jnp.where(is_nan(bits), jnp.int64(QNAN), signed)


# ---------------------------------------------------------------------------
# host-callback escape hatch for the transcendental tail
# ---------------------------------------------------------------------------

def host_unary(np_fn, bits):
    """Evaluate a numpy double fn exactly on the host (eager transfer).

    Used for the transcendental tail (exp/log/sin/...): numpy's libm IS the
    CPU oracle's implementation, so results are bit-identical to the CPU
    engine while the hot arithmetic path stays on-device.  The reference
    similarly gates incompatible float ops (docs/compatibility.md).
    Expression evaluation in this engine is eager (only kernels are jitted),
    and the axon PJRT backend has no host-callback support, so this is a
    plain device->host->device round-trip.
    """
    from ..analysis import residency  # lazy: avoids import cycle
    with residency.declared_transfer(site="binary64_host_libm"):
        arr = np.asarray(_i(bits)).view(np.float64)
    with np.errstate(all="ignore"):
        out = np.asarray(np_fn(arr), dtype=np.float64)
    return jnp.asarray(out.view(np.int64))


def host_binary(np_fn, a_bits, b_bits):
    from ..analysis import residency  # lazy: avoids import cycle
    with residency.declared_transfer(site="binary64_host_libm"):
        a = np.asarray(_i(a_bits)).view(np.float64)
        b = np.asarray(_i(b_bits)).view(np.float64)
    with np.errstate(all="ignore"):
        out = np.asarray(np_fn(a, b), dtype=np.float64)
    return jnp.asarray(out.view(np.int64))


# ---------------------------------------------------------------------------
# segmented / scan reductions
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# segmented sum: windowed integer superaccumulator
# ---------------------------------------------------------------------------
# Summing doubles exactly does NOT need a per-element softfloat adder: a
# double is sig * 2^(e-1075) with a 53-bit integer sig, so a segment's sum
# is an INTEGER sum in fixed point.  Each segment anchors a 256-bit window
# at its max exponent; every element decomposes into <=3 signed 32-bit limb
# contributions (pure shifts/masks), limbs accumulate with per-limb integer
# prefix sums over the sorted segment order (cumsum is native on the VPU;
# no 64-bit scatters, no associative_scan with a custom combiner — both
# are catastrophically slow/slow-to-compile on this backend), and ONE
# softfloat round-to-nearest-even runs per GROUP at the end.
#
# Accuracy: terms more than W0 bits below the segment max exponent fold
# into the sticky bit.  With NL=8 limbs W0 >= 256-53-log2(n)-2 (capped
# 191), so the result is the correctly-rounded exact sum unless the
# segment both spans >W0 bits of exponent range AND cancels its top ~100
# bits — far beyond f64 summation error in any order, which is the
# reference's own contract (integration tests compare with ulp tolerance).

_SUM_NL = 8          # 256-bit window


def _sum_w0(n: int) -> int:
    # max left-shift position: leave headroom for log2(n) carries above
    # the top term bit and keep limb index j = W0>>5 <= 5 (c2 lands at 7)
    return min(191, _SUM_NL * 32 - 53 - max(n, 2).bit_length() - 2)


def _derive_bounds(seg_id, contrib_mask):
    """Group boundary positions from sorted segment ids (fallback when no
    GroupPlan is available: tests / standalone use)."""
    n = seg_id.shape[0]
    if n > 1:
        head = jnp.concatenate([jnp.ones(1, bool),
                                seg_id[1:] != seg_id[:-1]])
    else:
        head = jnp.ones(1, bool)
    from .basic import compact_indices
    head_pos, num_groups = compact_indices(head, n)
    gi = jnp.arange(n, dtype=jnp.int32)
    nxt = jnp.concatenate([head_pos[1:].astype(jnp.int32),
                           jnp.zeros(1, jnp.int32)])
    last_pos = jnp.where(gi + 1 < num_groups, nxt - 1, jnp.int32(n - 1))
    return head_pos.astype(jnp.int32), last_pos, num_groups


def segmented_sum(sorted_bits, contrib_mask, seg_id, num_segments: int,
                  head_pos=None, last_pos=None, num_groups=None):
    """Exact binary64 sum per segment over sorted segment ids.

    ``head_pos``/``last_pos``/``num_groups`` are the GroupPlan boundary
    arrays (kernels/aggregate.groupby_plan); when omitted they are
    derived from ``seg_id`` (one extra argsort).
    """
    n = sorted_bits.shape[0]
    if head_pos is None:
        head_pos, last_pos, num_groups = _derive_bounds(seg_id,
                                                        contrib_mask)
    W0 = _sum_w0(n)
    u = _u(sorted_bits)
    exp_raw = ((u >> _c(52)) & _c(0x7FF)).astype(jnp.int32)
    mant = u & _c(MANT_MASK)
    sig = jnp.where(exp_raw > 0, mant | _c(IMPLICIT), mant)
    e = jnp.maximum(exp_raw, 1)
    negs = (u & _c(SIGN)) != _c(0)
    mag = u & _c(MAG_MASK)
    ok = contrib_mask
    nan_f = ok & (mag > _c(INF))
    pinf_f = ok & (u == _c(INF))
    ninf_f = ok & (u == _c(SIGN | INF))
    fin_ok = ok & (exp_raw != jnp.int32(2047))

    hp = jnp.clip(head_pos, 0, n - 1)
    lp = jnp.clip(last_pos, 0, n - 1)
    gi = jnp.arange(n, dtype=jnp.int32)
    glive = gi < num_groups

    def group_total(contrib):
        cum = jnp.cumsum(contrib)
        ex = cum - contrib
        total = jnp.take(cum, lp) - jnp.take(ex, hp)
        return jnp.where(glive, total, jnp.zeros_like(total))

    # group max exponent (i32 scatter-max: 32-bit scatters are native)
    emax_g = jax.ops.segment_max(jnp.where(fin_ok, e, jnp.int32(0)),
                                 seg_id, num_segments=n)
    d = jnp.take(emax_g, seg_id) - e
    p = jnp.int32(W0) - d
    # contributions entirely below the window fold into sticky
    keep = fin_ok & (p > jnp.int32(-53))
    rs = jnp.clip(-p, 0, 63).astype(jnp.uint64)
    sig2 = sig >> rs
    lost_low = fin_ok & ((sig2 << rs) != sig)
    dropped = fin_ok & (p <= jnp.int32(-53)) & (sig != _c(0))
    pc = jnp.clip(p, 0, W0)
    r = (pc & jnp.int32(31)).astype(jnp.uint64)
    j = pc >> jnp.int32(5)
    lo = sig2 << r
    hi = (sig2 >> (_c(63) - r)) >> _c(1)
    sgn = jnp.where(negs, jnp.int64(-1), jnp.int64(1))
    zero64 = jnp.int64(0)
    c0 = jnp.where(keep, (lo & _c(0xFFFFFFFF)).astype(jnp.int64) * sgn,
                   zero64)
    c1 = jnp.where(keep, (lo >> _c(32)).astype(jnp.int64) * sgn, zero64)
    c2 = jnp.where(keep, hi.astype(jnp.int64) * sgn, zero64)

    # per-limb group totals (each limb sum |.| <= n * 2^32 < 2^62: exact)
    limbs = []
    for L in range(_SUM_NL):
        lc = jnp.where(j == L, c0, zero64)
        if L >= 1:
            lc = lc + jnp.where(j == L - 1, c1, zero64)
        if L >= 2:
            lc = lc + jnp.where(j == L - 2, c2, zero64)
        limbs.append(group_total(lc))
    sticky_grp = group_total((lost_low | dropped).astype(jnp.int32)) > 0
    nan_cnt = group_total(nan_f.astype(jnp.int32))
    pinf_cnt = group_total(pinf_f.astype(jnp.int32))
    ninf_cnt = group_total(ninf_f.astype(jnp.int32))

    # ---- per-group finalize (all arrays are group-indexed, length n) ----
    m32 = jnp.int64(0xFFFFFFFF)
    carry = jnp.int64(0)
    lo32s = []
    for L in range(_SUM_NL):
        s = limbs[L] + carry
        lo32 = s & m32
        carry = (s - lo32) >> jnp.int64(32)
        lo32s.append(lo32)
    total_neg = carry < 0
    # magnitude limbs: conditional two's complement
    mags = []
    c = jnp.where(total_neg, jnp.int64(1), jnp.int64(0))
    for L in range(_SUM_NL):
        t = jnp.where(total_neg, (~lo32s[L]) & m32, lo32s[L]) + c
        mags.append((t & m32).astype(jnp.uint64))
        c = jnp.where(total_neg, t >> jnp.int64(32), jnp.int64(0))
    # combine to 4 u64 words, find top nonzero word
    words = [(mags[2 * i + 1] << _c(32)) | mags[2 * i] for i in range(4)]
    nzs = [w != _c(0) for w in words]
    top = jnp.zeros(n, jnp.int32)
    any_nz = jnp.zeros(n, bool)
    for i in range(4):
        top = jnp.where(nzs[i], jnp.int32(i), top)
        any_nz = any_nz | nzs[i]

    def pick(idx):
        out = jnp.zeros(n, jnp.uint64)
        for i in range(4):
            out = jnp.where(idx == i, words[i], out)
        return out
    hiw = pick(top)
    loww = pick(top - 1)                      # top == 0 -> stays zero
    lz = _clz64(hiw)                          # 0..63 when any_nz
    lzu = _u(jnp.clip(lz, 0, 63))
    combined = (hiw << lzu) | ((loww >> (_c(63) - lzu)) >> _c(1))
    dropped_low = (loww << lzu) != _c(0)
    lower_nz = jnp.zeros(n, bool)
    for i in range(4):
        lower_nz = lower_nz | (nzs[i] & (jnp.int32(i) < top - 1))
    sticky = dropped_low | lower_nz | sticky_grp | \
        ((combined & _c(0xFF)) != _c(0))
    sig57 = (combined >> _c(8)) | jnp.where(sticky, _c(1), _c(0))
    b_msb = jnp.int64(64) * top.astype(jnp.int64) + 63 - lz
    e_out = b_msb + emax_g.astype(jnp.int64) - jnp.int64(W0 + 52)
    out = _round_pack(total_neg, e_out, sig57)
    out = jnp.where(any_nz, out, jnp.int64(0))
    # specials: any NaN, or +inf and -inf together -> NaN; else inf wins
    out = jnp.where(pinf_cnt > 0, jnp.int64(INF), out)
    out = jnp.where(ninf_cnt > 0, jnp.int64((SIGN | INF) - 2 ** 64), out)
    out = jnp.where(
        (nan_cnt > 0) | ((pinf_cnt > 0) & (ninf_cnt > 0)),
        jnp.int64(QNAN), out)
    out = jnp.where(glive, out, jnp.int64(0))
    if n >= num_segments:
        return out[:num_segments]
    return jnp.pad(out, (0, num_segments - n))


def running_sum(bits, contrib_mask, seg_head):
    """Inclusive segmented running sum (window frames): bits per row."""
    zero = jnp.zeros_like(bits)
    vals = jnp.where(contrib_mask, bits, zero)

    def combine(left, right):
        lv, lf = left
        rv, rf = right
        v = jnp.where(rf, rv, add(lv, rv))
        return v, lf | rf

    scanned, _ = jax.lax.associative_scan(combine, (vals, seg_head))
    return scanned
