"""Sort kernels — the device core of GpuSortExec (reference:

GpuSortExec.scala:56, SortUtils.scala).

TPU-first: a single multi-operand ``lax.sort`` over canonical uint64 key
words (kernels/canon.py) + a trailing iota operand that yields the
permutation.  One code path for every dtype, stable, fully on-device.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
from jax import lax


def sort_permutation(words: List[jnp.ndarray]) -> jnp.ndarray:
    """Stable ascending sort over word tuples; returns permutation indices."""
    cap = words[0].shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    *_, perm = lax.sort(tuple(words) + (iota,), num_keys=len(words),
                        is_stable=True)
    return perm


def sorted_words(words: List[jnp.ndarray]):
    """Sort and also return the sorted word arrays (for boundary detection)."""
    cap = words[0].shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    out = lax.sort(tuple(words) + (iota,), num_keys=len(words), is_stable=True)
    return list(out[:-1]), out[-1]
