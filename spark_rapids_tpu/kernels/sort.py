"""Sort kernels — the device core of GpuSortExec (reference:

GpuSortExec.scala:56, SortUtils.scala).

TPU-first: multi-key sorts run as **LSD chained single-key passes** —
for each canonical uint64 key word (kernels/canon.py), least-significant
first, a stable (key, perm) ``lax.sort`` re-orders the permutation.
Rationale: a variadic ``lax.sort`` compiles a distinct XLA comparator
per (capacity, operand-count) pair, and on real TPU hardware each such
compile costs tens of seconds through the compile tunnel (measured:
~90s for a 6-key sort at 32k rows vs ~20s for the single-key kernel).
Chaining means ONE compiled pair-sort per capacity bucket serves every
sort/group-by/join/window in the engine, at the cost of K executions of
that one cached kernel — the right trade on an architecture where
compiles are expensive and reused kernels are nearly free.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from ..obs.trace import traced


@jax.jit
def _stable_pair_sort(key, perm):
    """The one compiled sort primitive: stable ascending by ``key``,
    carrying ``perm`` — shape-cached per (capacity bucket, key dtype).

    64-bit keys cost ~6x a u32 sort on real TPU (u64 ops lower to u32
    pairs), so callers with provably-narrow keys (partition ids, table
    buckets, range-rebased words) pass u32 keys directly."""
    _, out = lax.sort((key, perm), num_keys=1, is_stable=True)
    return out


@traced("sort_permutation")
def sort_permutation(words: List[jnp.ndarray]) -> jnp.ndarray:
    """Stable ascending sort over word tuples; returns permutation indices."""
    cap = words[0].shape[0]
    perm = jnp.arange(cap, dtype=jnp.int32)
    if len(words) == 1:
        w = words[0]
        if w.dtype != jnp.dtype(jnp.uint32):
            w = w.astype(jnp.uint64)
        return _stable_pair_sort(w, perm)
    # LSD: least-significant word first; stability makes later (more
    # significant) passes dominate
    for w in reversed(words):
        k = jnp.take(w.astype(jnp.uint64), perm)
        perm = _stable_pair_sort(k, perm)
    return perm


@traced("sorted_words")
def sorted_words(words: List[jnp.ndarray]):
    """Sort and also return the sorted word arrays (for boundary detection)."""
    perm = sort_permutation(words)
    return [jnp.take(w, perm) for w in words], perm
