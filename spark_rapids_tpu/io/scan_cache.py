"""Device-resident scan cache.

Reference analogue: ParquetCachedBatchSerializer (the reference caches
columnar batches so repeat reads skip decode) — applied here at the
scan, and kept ON DEVICE: on a remote-dispatch backend the
host->device transfer is the scarcest resource, so re-uploading the
same immutable file data every query dominates short queries.  Batches
are immutable (functional JAX arrays), so sharing them across queries
is safe.

Eviction: LRU past ``spark.rapids.tpu.io.deviceScanCache.bytes``; the
whole cache is dropped when the real device allocator reports OOM
(memory/pressure.py) — cached scans are always recomputable.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional, Tuple


class DeviceScanCache:
    _instance: Optional["DeviceScanCache"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._store: "OrderedDict[tuple, Tuple[list, int]]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    @classmethod
    def get(cls) -> "DeviceScanCache":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DeviceScanCache()
            return cls._instance

    def lookup(self, key: tuple) -> Optional[List[list]]:
        with self._lock:
            hit = self._store.get(key)
            if hit is None:
                self.misses += 1
                return None
            self._store.move_to_end(key)
            self.hits += 1
            return hit[0]

    def insert(self, key: tuple, parts: List[list], cap_bytes: int):
        nbytes = sum(b.nbytes() for part in parts for b in part)
        if nbytes > cap_bytes:
            return
        with self._lock:
            old = self._store.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._store[key] = (parts, nbytes)
            self._bytes += nbytes
            while self._bytes > cap_bytes and len(self._store) > 1:
                _, (_, nb) = self._store.popitem(last=False)
                self._bytes -= nb

    def clear(self):
        with self._lock:
            self._store.clear()
            self._bytes = 0

    @property
    def nbytes(self) -> int:
        return self._bytes


def clear_on_pressure():
    """Drop every cached scan (device-OOM hook; all entries are
    recomputable from their files)."""
    if DeviceScanCache._instance is not None:
        DeviceScanCache._instance.clear()
