"""Scan/write physical operators for both engines.

Reference: GpuFileSourceScanExec / GpuParquetFileFormat (write) and their
CPU counterparts; the planner (plan/overrides.py) picks TPU vs CPU per
tagging.
"""
from __future__ import annotations

import os
from typing import List, Optional

import pyarrow as pa
import pyarrow.parquet as papq
import pyarrow.csv as pacsv

from ..columnar.arrow import from_arrow, to_arrow, schema_to_arrow
from ..columnar.schema import Schema
from ..config import (TpuConf, PARQUET_READER_TYPE, MULTITHREAD_READ_THREADS,
                      SHUFFLE_PARTITIONS, MAX_READER_BATCH_ROWS)
from ..exec.base import PhysicalPlan, NUM_OUTPUT_ROWS
from ..exec.cpu import CpuExec
from ..exec.tpu_basic import TpuExec
from ..plan import logical as L
from .readers import (FilePartitionReader,
                      expand_paths_with_partitions,
                      split_files_into_partitions)


def _strategy(fmt: str, conf: TpuConf) -> str:
    if fmt != "parquet":
        return "PERFILE"
    s = conf.get(PARQUET_READER_TYPE).upper()
    if s == "AUTO":
        return "MULTITHREADED"
    return s


class TpuFileScan(TpuExec):
    """Reference: GpuFileSourceScanExec + reader strategies (§2.6)."""

    def __init__(self, logical: L.Scan, conf: TpuConf):
        super().__init__()
        self.logical = logical
        self.conf = conf
        self.files = expand_paths_with_partitions(logical.paths,
                                               conf)
        self.strategy = _strategy(logical.fmt, conf)
        self._partitions = split_files_into_partitions(
            self.files, conf.get(SHUFFLE_PARTITIONS))
        self.pushed_filters = None
        self._part_dtypes = {f.name: f.dtype
                             for f in logical.schema.fields}

    def set_pushed_filters(self, filters):
        """Planner-pushed predicate (GpuParquetScan pushdown role)."""
        self.pushed_filters = filters

    @property
    def output_schema(self):
        return self.logical.schema

    def num_partitions_hint(self):
        return len(self._partitions)

    def _node_string(self):
        pf = f", pushed={self.pushed_filters}" if self.pushed_filters else ""
        return (f"TpuFileScan[{self.logical.fmt}, {self.strategy}, "
                f"{len(self.files)} files{pf}]")

    def _reader(self, files):
        return FilePartitionReader(
            self.logical.fmt, files,
            columns=[f.name for f in self.logical.schema.fields],
            strategy=self.strategy,
            num_threads=self.conf.get(MULTITHREAD_READ_THREADS),
            options=self.logical.options,
            pushed_filters=self.pushed_filters,
            partition_dtypes=self._part_dtypes)

    def _chunks(self, table, max_rows):
        pos = 0
        n = table.num_rows
        while pos < n or (n == 0 and pos == 0):
            k = min(max_rows, n - pos)
            yield table.slice(pos, k)
            pos += max(k, 1)
            if n == 0:
                break

    def _cache_key(self, max_rows):
        """Identity of this scan's device batches: files+mtimes+sizes,
        column set/order, pushdown, batching geometry, and every session
        conf that changes the cached batch REPRESENTATION (exactDouble
        decides Binary64Column-vs-f64 at from_arrow time; the cache is
        process-global, so two sessions with different settings must not
        share batches)."""
        files = []
        for part in self._partitions:
            for f in part:
                path = f[0] if isinstance(f, tuple) else f
                pv = tuple(sorted(f[1].items())) if isinstance(f, tuple) \
                    else ()
                try:
                    st = os.stat(path)
                    files.append((path, st.st_mtime_ns, st.st_size, pv))
                except OSError:
                    return None
            files.append(("|",))        # partition boundary
        def freeze(x):
            if isinstance(x, dict):
                return tuple(sorted((k, freeze(v)) for k, v in x.items()))
            if isinstance(x, (set, frozenset)):
                return tuple(sorted(map(repr, x)))
            if isinstance(x, (list, tuple)):
                return tuple(freeze(v) for v in x)
            return x
        from ..columnar.binary64 import exact_double_enabled
        try:
            pushed = freeze(self.pushed_filters) \
                if self.pushed_filters else None
            key = (self.logical.fmt, tuple(files),
                   tuple((f.name, f.dtype.name)
                         for f in self.logical.schema.fields),
                   freeze(self.logical.options or {}),
                   pushed, max_rows, self.strategy,
                   exact_double_enabled())
            hash(key)                 # reject exotic unhashable leaves
        except Exception:
            return None               # unhashable option: never cache
        return key

    def execute(self):
        from ..config import SCAN_PREFETCH, SCAN_CACHE
        from .scan_cache import DeviceScanCache
        max_rows = self.conf.get(MAX_READER_BATCH_ROWS)
        key = self._cache_key(max_rows) if self.conf.get(SCAN_CACHE) \
            else None
        if key is not None:
            cached = DeviceScanCache.get().lookup(key)
            if cached is not None:
                def replay(batches):
                    for b in batches:
                        self.metrics[NUM_OUTPUT_ROWS] += b.num_rows
                        yield b
                return self._stats_wrap([replay(part) for part in cached])
        if not self.conf.get(SCAN_PREFETCH) or \
                sum(len(f) for f in self._partitions) <= 1:
            def run(files):
                for table in self._reader(files):
                    for chunk in self._chunks(table, max_rows):
                        self.metrics[NUM_OUTPUT_ROWS] += chunk.num_rows
                        yield from_arrow(chunk)
            parts = [run(files) for files in self._partitions]
        else:
            parts = self._execute_prefetch(max_rows)
        if key is None:
            return self._stats_wrap(parts)
        return self._stats_wrap(self._caching_iters(key, parts))

    def _stats_wrap(self, parts):
        """Per-partition output-row stats for the stats plane; the
        counting wrapper sits OUTSIDE the caching layer so the device
        cache stores unwrapped batches."""
        from ..obs import stats as obs_stats
        if not obs_stats.enabled(self.conf):
            return parts
        return obs_stats.count_scan_partitions(self, parts)

    def _caching_iters(self, key, parts):
        """Collect each partition's batches as they stream; install the
        scan into the device cache only when EVERY partition was fully
        consumed (a LIMIT short-circuit must not cache a prefix).
        Collection must never pin more than the cache budget: past it
        the scan cannot be cached anyway, so collection is abandoned
        and batches stream through unpinned (out-of-HBM scans keep
        their streaming memory profile)."""
        import threading
        from ..config import SCAN_CACHE_BYTES
        from .scan_cache import DeviceScanCache
        cap = int(self.conf.get(SCAN_CACHE_BYTES))
        # partition iterators may be consumed from concurrent tasks:
        # byte accounting / completion state shares one lock so the
        # budget cannot be overrun and insert happens exactly once
        lock = threading.Lock()
        state = {"bytes": 0, "abandoned": False, "inserted": False}
        collected = [[] for _ in parts]
        done = [False] * len(parts)

        def wrap(i, it):
            for b in it:
                with lock:
                    if not state["abandoned"]:
                        state["bytes"] += b.nbytes()
                        if state["bytes"] > cap:
                            state["abandoned"] = True
                            for part in collected:
                                part.clear()
                        else:
                            collected[i].append(b)
                yield b
            with lock:
                done[i] = True
                do_insert = (all(done) and not state["abandoned"]
                             and not state["inserted"])
                if do_insert:
                    state["inserted"] = True
            if do_insert:
                DeviceScanCache.get().insert(key, collected, cap)
        return [wrap(i, it) for i, it in enumerate(parts)]

    def _execute_prefetch(self, max_rows):
        """Producer threads decode host arrow tables AHEAD of
        consumption (bounded queue per partition), so scan I/O for
        partition N+1 overlaps device compute for partition N; the
        host->device upload of each chunk runs under the
        DeviceSemaphore (the GpuSemaphore.scala:27,101 admission gate —
        at most concurrentTpuTasks partitions touch the device at
        once)."""
        import queue as _q
        import threading
        from ..memory.arena import DeviceManager

        sem = DeviceManager.get().semaphore
        sentinels = {"end": object(), "err": object()}

        def start_producer(files):
            qd: "_q.Queue" = _q.Queue(maxsize=2)
            cancel = threading.Event()

            def put_or_cancel(item) -> bool:
                while not cancel.is_set():
                    try:
                        qd.put(item, timeout=0.5)
                        return True
                    except _q.Full:
                        continue
                return False

            def produce():
                try:
                    for table in self._reader(files):
                        if not put_or_cancel(table):
                            return
                    put_or_cancel(sentinels["end"])
                    # linger until the consumer drains the queue (or
                    # abandons the partition): a producer mid-decode
                    # already pins its thread on the bounded put, so a
                    # finished one holding its decoded tables until
                    # they're taken keeps the lifetime discipline
                    # uniform regardless of table count
                    while not cancel.is_set() and not qd.empty():
                        cancel.wait(0.05)
                except Exception as e:  # noqa: BLE001 - re-raised below
                    put_or_cancel((sentinels["err"], e))
            t = threading.Thread(target=produce, daemon=True,
                                 name="tpu-scan-prefetch")
            t.start()
            return qd, cancel

        pairs = [start_producer(files) for files in self._partitions]

        def run(qd, cancel):
            try:
                while True:
                    item = qd.get()
                    if item is sentinels["end"]:
                        return
                    if isinstance(item, tuple) and item and \
                            item[0] is sentinels["err"]:
                        raise item[1]
                    for chunk in self._chunks(item, max_rows):
                        self.metrics[NUM_OUTPUT_ROWS] += chunk.num_rows
                        sem.acquire_if_necessary()
                        try:
                            batch = from_arrow(chunk)
                        finally:
                            sem.release()
                        yield batch
            finally:
                # abandonment (LIMIT short-circuit, error, GC of the
                # generator) must release the producer: without this
                # the thread blocks forever on the bounded queue,
                # pinning decoded tables for the process lifetime
                cancel.set()
        return [run(qd, cancel) for qd, cancel in pairs]


class CpuFileScan(CpuExec):
    def __init__(self, logical: L.Scan, conf: TpuConf):
        super().__init__()
        self.logical = logical
        self.conf = conf
        self.files = expand_paths_with_partitions(logical.paths,
                                               conf)
        self._partitions = split_files_into_partitions(
            self.files, conf.get(SHUFFLE_PARTITIONS))
        self._part_dtypes = {f.name: f.dtype
                             for f in logical.schema.fields}

    @property
    def output_schema(self):
        return self.logical.schema

    def num_partitions_hint(self):
        return len(self._partitions)

    def execute(self):
        def run(files):
            reader = FilePartitionReader(
                self.logical.fmt, files,
                columns=[f.name for f in self.logical.schema.fields],
                options=self.logical.options,
                partition_dtypes=self._part_dtypes)
            for t in reader:
                yield t
        return [run(files) for files in self._partitions]


def tpu_scan_exec(logical: L.Scan, conf: TpuConf) -> PhysicalPlan:
    return TpuFileScan(logical, conf)


def cpu_scan_exec(logical: L.Scan, conf: TpuConf) -> PhysicalPlan:
    return CpuFileScan(logical, conf)


# ---------------------------------------------------------------------------
# writers (reference: GpuParquetFileFormat.scala:348, GpuFileFormatWriter)
# ---------------------------------------------------------------------------

class TpuFileWrite(TpuExec):
    """Write device batches to part files (one per partition)."""

    def __init__(self, logical: L.WriteFile, child: PhysicalPlan,
                 conf: TpuConf):
        super().__init__(child)
        self.logical = logical
        self.conf = conf

    @property
    def output_schema(self):
        return Schema([])

    def execute(self):
        return _run_committed_write(
            self.logical, self.children[0],
            lambda part: [to_arrow(b) for b in part if b.num_rows > 0],
            self.metrics)


class CpuFileWrite(CpuExec):
    def __init__(self, logical: L.WriteFile, child: PhysicalPlan,
                 conf: TpuConf):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return Schema([])

    def execute(self):
        return _run_committed_write(self.logical, self.children[0],
                                    list, self.metrics)


class WriteCommitProtocol:
    """Temp-dir + atomic-rename task commit for file writes.

    Reference: GpuFileFormatWriter.scala + the Hadoop commit protocol,
    with write statistics per BasicColumnarWriteStatsTracker.scala:1.
    Tasks write under ``<path>/_temporary-<job>/task-<i>/`` (partition
    subdirs included); a successful task promotes its files into the
    final directory with atomic ``os.replace``; a failed task aborts by
    deleting its attempt dir, leaving the output untouched.  Job commit
    drops the temp tree and writes the ``_SUCCESS`` marker."""

    def __init__(self, path: str, overwrite: bool = False):
        import uuid
        self.path = path
        self.overwrite = overwrite
        self.tmp = os.path.join(path, f"_temporary-{uuid.uuid4().hex[:8]}")
        #: job-level stats (BasicColumnarWriteJobStatsTracker metric
        #: names: numFiles / numOutputBytes / numOutputRows / numParts)
        self.stats = {"numFiles": 0, "numOutputBytes": 0,
                      "numOutputRows": 0, "numParts": 0}
        self._part_dirs = set()   # distinct partition paths, job-wide

    def setup_job(self):
        os.makedirs(self.tmp, exist_ok=True)

    def task_dir(self, task_id: int) -> str:
        d = os.path.join(self.tmp, f"task-{task_id:05d}")
        os.makedirs(d, exist_ok=True)
        return d

    def commit_task(self, task_id: int, num_rows: int):
        """Stage the task's files into the job-commit area (v1
        protocol: nothing reaches the final directory until JOB commit,
        so any failure leaves the target untouched); accumulate
        stats."""
        d = os.path.join(self.tmp, f"task-{task_id:05d}")
        staged = os.path.join(self.tmp, "__committed__")
        for root, _dirs, files in os.walk(d):
            rel = os.path.relpath(root, d)
            dest_dir = staged if rel == "." else \
                os.path.join(staged, rel)
            os.makedirs(dest_dir, exist_ok=True)
            if rel != "." and files:
                # DISTINCT partition paths job-wide, leaf dirs only
                # (BasicColumnarWriteJobStatsTracker semantics)
                self._part_dirs.add(rel)
            for f in files:
                fsrc = os.path.join(root, f)
                self.stats["numFiles"] += 1
                self.stats["numOutputBytes"] += os.path.getsize(fsrc)
                os.replace(fsrc, os.path.join(dest_dir, f))
        self.stats["numParts"] = len(self._part_dirs)
        self.stats["numOutputRows"] += int(num_rows)
        import shutil
        shutil.rmtree(d, ignore_errors=True)

    def abort_task(self, task_id: int):
        import shutil
        shutil.rmtree(os.path.join(self.tmp, f"task-{task_id:05d}"),
                      ignore_errors=True)

    def commit_job(self):
        """Promote every committed task's staged files atomically
        (per-file os.replace) into the final directory, then drop the
        temp tree and write the _SUCCESS marker.  Overwrite mode
        deletes the PREVIOUS dataset here — after every task has
        committed — so a failed overwrite leaves the old data intact.
        """
        import shutil
        if self.overwrite:
            for f in os.listdir(self.path):
                full = os.path.join(self.path, f)
                if f.startswith("part-") or f == "_SUCCESS":
                    os.unlink(full)
                elif "=" in f and os.path.isdir(full):
                    shutil.rmtree(full)
        staged = os.path.join(self.tmp, "__committed__")
        if os.path.isdir(staged):
            for root, _dirs, files in os.walk(staged):
                rel = os.path.relpath(root, staged)
                dest_dir = self.path if rel == "." else \
                    os.path.join(self.path, rel)
                os.makedirs(dest_dir, exist_ok=True)
                for f in files:
                    os.replace(os.path.join(root, f),
                               os.path.join(dest_dir, f))
        shutil.rmtree(self.tmp, ignore_errors=True)
        with open(os.path.join(self.path, "_SUCCESS"), "w"):
            pass

    def abort_job(self):
        import shutil
        shutil.rmtree(self.tmp, ignore_errors=True)


def _write_partitioned(fmt: str, table: pa.Table, root: str,
                       part_cols, task_id: int):
    """Hive-layout dynamic partitioned write: one file per key combo."""
    import pyarrow.compute as pc
    data_cols = [c for c in table.column_names if c not in part_cols]
    keys = table.select(part_cols)
    combos = keys.group_by(part_cols).aggregate([])
    for row in range(combos.num_rows):
        mask = None
        comps = []
        for c in part_cols:
            v = combos.column(c)[row]
            eq = pc.is_null(table.column(c)) if not v.is_valid else \
                pc.equal(table.column(c), v)
            eq = pc.fill_null(eq, False)
            mask = eq if mask is None else pc.and_(mask, eq)
            if not v.is_valid:
                sval = "__HIVE_DEFAULT_PARTITION__"
            else:
                from urllib.parse import quote
                # escape path separators/metacharacters (Spark's
                # escapePathName role)
                sval = quote(str(v.as_py()), safe="")
            comps.append(f"{c}={sval}")
        sub = table.filter(mask).select(data_cols)
        d = os.path.join(root, *comps)
        os.makedirs(d, exist_ok=True)
        _write_table(fmt, sub, os.path.join(d, f"part-{task_id:05d}"))


def _write_table(fmt: str, table: pa.Table, base: str):
    if fmt == "parquet":
        papq.write_table(table, base + ".parquet")
    elif fmt == "csv":
        pacsv.write_csv(table, base + ".csv")
    elif fmt == "orc":
        from pyarrow import orc as paorc
        paorc.write_table(table, base + ".orc")
    else:
        raise ValueError(f"unknown write format {fmt}")


def _run_committed_write(lg, child, tables_of, metrics):
    """Shared commit-protocol write driver for both engines:
    ``tables_of(part)`` yields the partition's arrow tables."""
    os.makedirs(lg.path, exist_ok=True)
    if lg.partition_by and any(c.startswith(("_", "."))
                               for c in lg.partition_by):
        # readers treat _/. prefixed directories as hidden (commit
        # temp dirs live there); such partition columns would write
        # data that every scan silently skips
        raise ValueError(
            "partition column names must not start with '_' or '.'")
    import shutil
    for f in os.listdir(lg.path):
        full = os.path.join(lg.path, f)
        if f.startswith("_temporary") and os.path.isdir(full):
            # leftover attempt dirs from a crashed writer
            shutil.rmtree(full)
    parts = child.execute()
    arrow_schema = schema_to_arrow(child.output_schema)
    # overwrite deletes the previous dataset at JOB COMMIT, not here:
    # a failed overwrite must leave the old data intact
    proto = WriteCommitProtocol(lg.path, overwrite=lg.mode == "overwrite")
    proto.setup_job()

    def run(i, part):
        tdir = proto.task_dir(i)
        try:
            tables = tables_of(part)
            table = pa.concat_tables(tables) if tables else \
                arrow_schema.empty_table()
            if lg.partition_by:
                _write_partitioned(lg.fmt, table, tdir,
                                   lg.partition_by, i)
            else:
                _write_table(lg.fmt, table,
                             os.path.join(tdir, f"part-{i:05d}"))
        except BaseException:
            proto.abort_task(i)
            proto.abort_job()
            raise
        proto.commit_task(i, table.num_rows)
        return iter(())
    try:
        out = [run(i, p) for i, p in enumerate(parts)]
    except BaseException:
        proto.abort_job()
        raise
    proto.commit_job()
    for k, v in proto.stats.items():
        metrics[k] += v
    return out


def tpu_write_exec(logical, child, conf):
    return TpuFileWrite(logical, child, conf)


def cpu_write_exec(logical, child, conf):
    return CpuFileWrite(logical, child, conf)
