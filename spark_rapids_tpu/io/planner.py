"""Scan/write physical operators for both engines.

Reference: GpuFileSourceScanExec / GpuParquetFileFormat (write) and their
CPU counterparts; the planner (plan/overrides.py) picks TPU vs CPU per
tagging.
"""
from __future__ import annotations

import os
from typing import List, Optional

import pyarrow as pa
import pyarrow.parquet as papq
import pyarrow.csv as pacsv

from ..columnar.arrow import from_arrow, to_arrow, schema_to_arrow
from ..columnar.schema import Schema
from ..config import (TpuConf, PARQUET_READER_TYPE, MULTITHREAD_READ_THREADS,
                      SHUFFLE_PARTITIONS, MAX_READER_BATCH_ROWS)
from ..exec.base import PhysicalPlan, NUM_OUTPUT_ROWS
from ..exec.cpu import CpuExec
from ..exec.tpu_basic import TpuExec
from ..plan import logical as L
from .readers import (FilePartitionReader, expand_paths,
                      split_files_into_partitions)


def _strategy(fmt: str, conf: TpuConf) -> str:
    if fmt != "parquet":
        return "PERFILE"
    s = conf.get(PARQUET_READER_TYPE).upper()
    if s == "AUTO":
        return "MULTITHREADED"
    return s


class TpuFileScan(TpuExec):
    """Reference: GpuFileSourceScanExec + reader strategies (§2.6)."""

    def __init__(self, logical: L.Scan, conf: TpuConf):
        super().__init__()
        self.logical = logical
        self.conf = conf
        self.files = expand_paths(logical.paths)
        self.strategy = _strategy(logical.fmt, conf)
        self._partitions = split_files_into_partitions(
            self.files, conf.get(SHUFFLE_PARTITIONS))
        self.pushed_filters = None

    def set_pushed_filters(self, filters):
        """Planner-pushed predicate (GpuParquetScan pushdown role)."""
        self.pushed_filters = filters

    @property
    def output_schema(self):
        return self.logical.schema

    def num_partitions_hint(self):
        return len(self._partitions)

    def _node_string(self):
        pf = f", pushed={self.pushed_filters}" if self.pushed_filters else ""
        return (f"TpuFileScan[{self.logical.fmt}, {self.strategy}, "
                f"{len(self.files)} files{pf}]")

    def execute(self):
        max_rows = self.conf.get(MAX_READER_BATCH_ROWS)

        def run(files):
            reader = FilePartitionReader(
                self.logical.fmt, files,
                strategy=self.strategy,
                num_threads=self.conf.get(MULTITHREAD_READ_THREADS),
                options=self.logical.options,
                pushed_filters=self.pushed_filters)
            for table in reader:
                pos = 0
                n = table.num_rows
                while pos < n or (n == 0 and pos == 0):
                    k = min(max_rows, n - pos)
                    chunk = table.slice(pos, k)
                    self.metrics[NUM_OUTPUT_ROWS] += chunk.num_rows
                    yield from_arrow(chunk)
                    pos += max(k, 1)
                    if n == 0:
                        break
        return [run(files) for files in self._partitions]


class CpuFileScan(CpuExec):
    def __init__(self, logical: L.Scan, conf: TpuConf):
        super().__init__()
        self.logical = logical
        self.conf = conf
        self.files = expand_paths(logical.paths)
        self._partitions = split_files_into_partitions(
            self.files, conf.get(SHUFFLE_PARTITIONS))

    @property
    def output_schema(self):
        return self.logical.schema

    def num_partitions_hint(self):
        return len(self._partitions)

    def execute(self):
        def run(files):
            reader = FilePartitionReader(self.logical.fmt, files,
                                         options=self.logical.options)
            for t in reader:
                yield t
        return [run(files) for files in self._partitions]


def tpu_scan_exec(logical: L.Scan, conf: TpuConf) -> PhysicalPlan:
    return TpuFileScan(logical, conf)


def cpu_scan_exec(logical: L.Scan, conf: TpuConf) -> PhysicalPlan:
    return CpuFileScan(logical, conf)


# ---------------------------------------------------------------------------
# writers (reference: GpuParquetFileFormat.scala:348, GpuFileFormatWriter)
# ---------------------------------------------------------------------------

class TpuFileWrite(TpuExec):
    """Write device batches to part files (one per partition)."""

    def __init__(self, logical: L.WriteFile, child: PhysicalPlan,
                 conf: TpuConf):
        super().__init__(child)
        self.logical = logical
        self.conf = conf

    @property
    def output_schema(self):
        return Schema([])

    def execute(self):
        lg = self.logical
        os.makedirs(lg.path, exist_ok=True)
        if lg.mode == "overwrite":
            for f in os.listdir(lg.path):
                if f.startswith("part-"):
                    os.unlink(os.path.join(lg.path, f))
        parts = self.children[0].execute()
        arrow_schema = schema_to_arrow(self.children[0].output_schema)

        def run(i, part):
            tables = [to_arrow(b) for b in part if b.num_rows > 0]
            table = pa.concat_tables(tables) if tables else \
                arrow_schema.empty_table()
            _write_table(lg.fmt, table,
                         os.path.join(lg.path, f"part-{i:05d}"))
            self.metrics[NUM_OUTPUT_ROWS] += table.num_rows
            return iter(())
        return [run(i, p) for i, p in enumerate(parts)]


class CpuFileWrite(CpuExec):
    def __init__(self, logical: L.WriteFile, child: PhysicalPlan,
                 conf: TpuConf):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return Schema([])

    def execute(self):
        lg = self.logical
        os.makedirs(lg.path, exist_ok=True)
        if lg.mode == "overwrite":
            for f in os.listdir(lg.path):
                if f.startswith("part-"):
                    os.unlink(os.path.join(lg.path, f))
        parts = self.children[0].execute()
        arrow_schema = schema_to_arrow(self.children[0].output_schema)

        def run(i, part):
            tables = list(part)
            table = pa.concat_tables(tables) if tables else \
                arrow_schema.empty_table()
            _write_table(lg.fmt, table,
                         os.path.join(lg.path, f"part-{i:05d}"))
            return iter(())
        return [run(i, p) for i, p in enumerate(parts)]


def _write_table(fmt: str, table: pa.Table, base: str):
    if fmt == "parquet":
        papq.write_table(table, base + ".parquet")
    elif fmt == "csv":
        pacsv.write_csv(table, base + ".csv")
    elif fmt == "orc":
        from pyarrow import orc as paorc
        paorc.write_table(table, base + ".orc")
    else:
        raise ValueError(f"unknown write format {fmt}")


def tpu_write_exec(logical, child, conf):
    return TpuFileWrite(logical, child, conf)


def cpu_write_exec(logical, child, conf):
    return CpuFileWrite(logical, child, conf)
