"""Scan predicate pushdown: Expression -> pyarrow filter DNF.

Reference: GpuParquetScan predicate pushdown via re-written footer filters
(GpuParquetScan.scala) and OrcFilters.  Here translatable conjuncts become
pyarrow dataset filters (row-group/stripe pruning happens inside pyarrow);
the engine keeps the full Filter above the scan, so partial translation is
always safe — exactly the reference's belt-and-suspenders model.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..expr import core as ec
from ..expr import predicates as ep

_OPS = {
    ep.EqualTo: "==", ep.LessThan: "<", ep.LessThanOrEqual: "<=",
    ep.GreaterThan: ">", ep.GreaterThanOrEqual: ">=",
}


def _comparable(attr: ec.AttributeReference, lit: ec.Literal) -> bool:
    """Only push comparisons whose pyarrow row-level semantics match the
    engine's.  Floats are excluded entirely: the engine compares with
    Spark total order (NaN greatest, NaN == NaN, kernels/canon.py) while
    pyarrow uses IEEE semantics, so a pushed `f > 0.0` would drop NaN rows
    the engine's Filter keeps."""
    if isinstance(lit.value, float):
        return False
    dt = attr._dtype
    return not (dt is not None and dt.is_fractional)


def _leaf(e: ec.Expression) -> Optional[Tuple[str, str, object]]:
    cls = type(e)
    if cls in _OPS:
        a, b = e.children
        if isinstance(a, ec.AttributeReference) and \
                isinstance(b, ec.Literal) and b.value is not None and \
                _comparable(a, b):
            return (a.col_name, _OPS[cls], b.value)
        if isinstance(b, ec.AttributeReference) and \
                isinstance(a, ec.Literal) and a.value is not None and \
                _comparable(b, a):
            flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                    "==": "=="}
            return (b.col_name, flip[_OPS[cls]], a.value)
    if isinstance(e, ep.IsNotNull) and isinstance(
            e.children[0], ec.AttributeReference):
        return (e.children[0].col_name, "is_not_null", None)
    if isinstance(e, ep.In) and isinstance(e.children[0],
                                           ec.AttributeReference):
        vals = [v for v in e.values if v is not None]
        if vals and not any(isinstance(v, float) for v in vals):
            return (e.children[0].col_name, "in", vals)
    return None


def to_arrow_filters(cond: ec.Expression) -> Optional[List[Tuple]]:
    """Translate the AND-conjuncts we can; None if nothing translates."""
    conjuncts: List[ec.Expression] = []

    def flatten(x):
        if isinstance(x, ep.And):
            flatten(x.children[0])
            flatten(x.children[1])
        else:
            conjuncts.append(x)
    flatten(cond)
    out = []
    for c in conjuncts:
        leaf = _leaf(c)
        if leaf is not None:
            out.append(leaf)
    return out or None


