"""IO layer: file scans (reader strategies) and writers (SURVEY.md §2.6)."""
