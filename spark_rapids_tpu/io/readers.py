"""File scans: Parquet / ORC / CSV with the reference's reader strategies.

Reference: GpuParquetScan.scala:84-1757 — three strategies:
  PERFILE       one file per read (ParquetPartitionReader)
  MULTITHREADED thread-pool prefetch of host buffers per file, overlapping
                I/O with device transfer (MultiFileCloudParquetPartitionReader)
  COALESCING    many small files combined into one host buffer and decoded
                in a single pass (MultiFileParquetPartitionReader)

TPU adaptation: pyarrow does the host-side decode (the cuDF-parser role is
host-side here since TPUs cannot parse Parquet), producing arrow tables
that are transferred to the device as columnar batches.  The strategy
machinery (prefetch threads, coalescing small files, batch-size caps) is
preserved.
"""
from __future__ import annotations

import concurrent.futures
import glob as globmod
import os
from typing import Iterator, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as papq

try:
    import pyarrow.orc as paorc
    HAVE_ORC = True
except Exception:  # pragma: no cover
    HAVE_ORC = False

from ..columnar.arrow import from_arrow, schema_from_arrow
from ..columnar.schema import Schema


def expand_paths(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for f in sorted(os.listdir(p)):
                if not f.startswith(("_", ".")):
                    out.append(os.path.join(p, f))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globmod.glob(p)))
        else:
            out.append(p)
    return out


def _read_file(fmt: str, path: str, columns: Optional[List[str]] = None,
               options=None) -> pa.Table:
    if fmt == "parquet":
        return papq.read_table(path, columns=columns, use_threads=False)
    if fmt == "orc":
        if not HAVE_ORC:
            raise RuntimeError("pyarrow.orc unavailable")
        t = paorc.ORCFile(path).read(columns=columns)
        return t
    if fmt == "csv":
        opts = options or {}
        read_opts = pacsv.ReadOptions(
            column_names=opts.get("column_names"),
            skip_rows=1 if opts.get("header", True) and
            not opts.get("column_names") else 0)
        if opts.get("header", True) and not opts.get("column_names"):
            read_opts = pacsv.ReadOptions()
        parse_opts = pacsv.ParseOptions(
            delimiter=opts.get("sep", ","))
        conv = pacsv.ConvertOptions(column_types=opts.get("column_types"))
        t = pacsv.read_csv(path, read_options=read_opts,
                           parse_options=parse_opts, convert_options=conv)
        if columns:
            t = t.select(columns)
        return t
    if fmt == "json":
        import pyarrow.json as pajson
        t = pajson.read_json(path)
        if columns:
            t = t.select(columns)
        return t
    raise ValueError(f"unknown format {fmt}")


def infer_schema(fmt: str, paths: List[str], options=None) -> Schema:
    files = expand_paths(paths)
    if not files:
        raise FileNotFoundError(f"no files match {paths}")
    if fmt == "parquet":
        return schema_from_arrow(papq.read_schema(files[0]))
    t = _read_file(fmt, files[0], options=options)
    return schema_from_arrow(t.schema)


class FilePartitionReader:
    """Iterator of host arrow tables for a set of files under a strategy."""

    def __init__(self, fmt: str, files: List[str],
                 columns: Optional[List[str]] = None,
                 strategy: str = "PERFILE", num_threads: int = 4,
                 coalesce_target_rows: int = 1 << 20, options=None,
                 pushed_filters=None):
        self.fmt = fmt
        self.files = files
        self.columns = columns
        self.strategy = strategy
        self.num_threads = num_threads
        self.coalesce_target_rows = coalesce_target_rows
        self.options = options
        self.pushed_filters = pushed_filters

    def _read(self, path: str) -> pa.Table:
        if self.fmt == "parquet" and self.pushed_filters:
            import pyarrow.parquet as papq
            return papq.read_table(path, columns=self.columns,
                                   use_threads=False,
                                   filters=self.pushed_filters)
        return _read_file(self.fmt, path, self.columns, self.options)

    def __iter__(self) -> Iterator[pa.Table]:
        if self.strategy == "MULTITHREADED" and len(self.files) > 1:
            yield from self._multithreaded()
        elif self.strategy == "COALESCING" and len(self.files) > 1:
            yield from self._coalescing()
        else:
            for f in self.files:
                yield self._read(f)

    def _multithreaded(self):
        """Prefetch host buffers with a thread pool; preserve file order.

        (MultiFileCloudParquetPartitionReader role.)"""
        with concurrent.futures.ThreadPoolExecutor(self.num_threads) as pool:
            futures = [pool.submit(self._read, f) for f in self.files]
            for fut in futures:
                yield fut.result()

    def _coalescing(self):
        """Combine small files into bigger host tables before device

        transfer (MultiFileParquetPartitionReader role)."""
        pending: List[pa.Table] = []
        rows = 0
        for f in self.files:
            t = self._read(f)
            pending.append(t)
            rows += t.num_rows
            if rows >= self.coalesce_target_rows:
                yield pa.concat_tables(pending, promote_options="permissive")
                pending, rows = [], 0
        if pending:
            yield pa.concat_tables(pending, promote_options="permissive")


def split_files_into_partitions(files: List[str],
                                num_partitions: int) -> List[List[str]]:
    """Greedy size-balanced assignment of files to partitions."""
    sizes = [(f, os.path.getsize(f) if os.path.exists(f) else 0)
             for f in files]
    sizes.sort(key=lambda x: -x[1])
    num_partitions = max(1, min(num_partitions, len(files) or 1))
    buckets: List[List[str]] = [[] for _ in range(num_partitions)]
    loads = [0] * num_partitions
    for f, s in sizes:
        i = loads.index(min(loads))
        buckets[i].append(f)
        loads[i] += s
    return buckets
