"""File scans: Parquet / ORC / CSV with the reference's reader strategies.

Reference: GpuParquetScan.scala:84-1757 — three strategies:
  PERFILE       one file per read (ParquetPartitionReader)
  MULTITHREADED thread-pool prefetch of host buffers per file, overlapping
                I/O with device transfer (MultiFileCloudParquetPartitionReader)
  COALESCING    many small files combined into one host buffer and decoded
                in a single pass (MultiFileParquetPartitionReader)

TPU adaptation: pyarrow does the host-side decode (the cuDF-parser role is
host-side here since TPUs cannot parse Parquet), producing arrow tables
that are transferred to the device as columnar batches.  The strategy
machinery (prefetch threads, coalescing small files, batch-size caps) is
preserved.
"""
from __future__ import annotations

import concurrent.futures
import glob as globmod
import os
from typing import Iterator, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as papq

try:
    import pyarrow.orc as paorc
    HAVE_ORC = True
except Exception:  # pragma: no cover
    HAVE_ORC = False

from ..columnar.arrow import from_arrow, schema_from_arrow
from ..columnar.schema import Schema


def rewrite_paths(paths: List[str], conf=None) -> List[str]:
    """Alluxio-role path rewrite (RapidsConf.scala:1072): apply
    'from->to' prefix rules from spark.rapids.tpu.alluxio.pathsToReplace
    so scans read the configured mirror.  ``conf`` is the scan's own
    TpuConf when available (the active conf is last-session-wins and
    would apply the WRONG session's rules)."""
    from ..config import get_active, ALLUXIO_PATHS_TO_REPLACE
    spec = str((conf or get_active()).get(ALLUXIO_PATHS_TO_REPLACE)
               or "")
    if not spec.strip():
        return paths
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if part and "->" in part:
            src, dst = part.split("->", 1)
            if not src.strip():
                raise ValueError(
                    "spark.rapids.tpu.alluxio.pathsToReplace rule has "
                    f"an empty 'from' side: {part!r}")
            rules.append((src.strip(), dst.strip()))
    out = []
    for p in paths:
        for src, dst in rules:
            if p.startswith(src):
                p = dst + p[len(src):]
                break
        out.append(p)
    return out


def expand_paths_with_partitions(paths: List[str], conf=None):
    """Expand dirs/globs to files with Hive-style ``key=value`` directory
    components decoded as partition values (reference:
    ColumnarPartitionReaderWithPartitionValues — partition values are
    appended as columns after the file read)."""
    out = []
    for p in rewrite_paths(paths, conf):
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                # hidden/system dirs (in-flight _temporary-* attempt
                # dirs from the write commit protocol) are not data
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(("_", ".")))
                pvals = {}
                rel = os.path.relpath(root, p)
                if rel != ".":
                    from urllib.parse import unquote
                    for comp in rel.split(os.sep):
                        if "=" in comp:
                            k, v = comp.split("=", 1)
                            pvals[k] = None \
                                if v == "__HIVE_DEFAULT_PARTITION__" \
                                else unquote(v)
                for f in sorted(files):
                    if not f.startswith(("_", ".")):
                        out.append((os.path.join(root, f), pvals))
        elif any(ch in p for ch in "*?["):
            out.extend((f, {}) for f in sorted(globmod.glob(p)))
        else:
            out.append((p, {}))
    return out


def expand_paths(paths: List[str], conf=None) -> List[str]:
    return [f for f, _ in expand_paths_with_partitions(paths, conf)]


def _read_file(fmt: str, path: str, columns: Optional[List[str]] = None,
               options=None) -> pa.Table:
    if fmt == "parquet":
        return papq.read_table(path, columns=columns, use_threads=False)
    if fmt == "orc":
        if not HAVE_ORC:
            raise RuntimeError("pyarrow.orc unavailable")
        t = paorc.ORCFile(path).read(columns=columns)
        return t
    if fmt == "csv":
        opts = options or {}
        read_opts = pacsv.ReadOptions(
            column_names=opts.get("column_names"),
            skip_rows=1 if opts.get("header", True) and
            not opts.get("column_names") else 0)
        if opts.get("header", True) and not opts.get("column_names"):
            read_opts = pacsv.ReadOptions()
        parse_opts = pacsv.ParseOptions(
            delimiter=opts.get("sep", ","))
        conv = pacsv.ConvertOptions(column_types=opts.get("column_types"))
        t = pacsv.read_csv(path, read_options=read_opts,
                           parse_options=parse_opts, convert_options=conv)
        if columns:
            t = t.select(columns)
        return t
    if fmt == "json":
        import pyarrow.json as pajson
        t = pajson.read_json(path)
        if columns:
            t = t.select(columns)
        return t
    raise ValueError(f"unknown format {fmt}")


def _partition_fields(pairs) -> List:
    """Infer partition-column fields from Hive path values (int64 when
    every value parses as an integer, else string)."""
    from ..columnar.schema import Field
    from ..columnar import dtypes as T
    keys: List[str] = []
    values: dict = {}
    for _, pvals in pairs:
        for k, v in pvals.items():
            if k not in values:
                keys.append(k)
                values[k] = []
            values[k].append(v)
    fields = []
    for k in keys:
        dt = T.INT64
        for v in values[k]:
            if v is None:
                continue
            try:
                int(v)
            except ValueError:
                dt = T.STRING
                break
        fields.append(Field(k, dt, True))
    return fields


def infer_schema(fmt: str, paths: List[str], options=None,
                 conf=None) -> Schema:
    pairs = expand_paths_with_partitions(paths, conf)
    if not pairs:
        raise FileNotFoundError(f"no files match {paths}")
    first = pairs[0][0]
    if fmt == "parquet":
        base = schema_from_arrow(papq.read_schema(first))
    else:
        base = schema_from_arrow(
            _read_file(fmt, first, options=options).schema)
    pf = _partition_fields(pairs)
    if not pf:
        return base
    names = set(base.names)
    return Schema(list(base.fields) +
                  [f for f in pf if f.name not in names])


class FilePartitionReader:
    """Iterator of host arrow tables for a set of files under a strategy."""

    def __init__(self, fmt: str, files: List,
                 columns: Optional[List[str]] = None,
                 strategy: str = "PERFILE", num_threads: int = 4,
                 coalesce_target_rows: int = 1 << 20, options=None,
                 pushed_filters=None, partition_dtypes=None):
        self.fmt = fmt
        # files: plain paths or (path, {partition_col: raw_value}) pairs
        self.files = [(f, {}) if isinstance(f, str) else f for f in files]
        self.columns = columns
        self.strategy = strategy
        self.num_threads = num_threads
        self.coalesce_target_rows = coalesce_target_rows
        self.options = options
        self.pushed_filters = pushed_filters
        self.partition_dtypes = partition_dtypes or {}

    def _read(self, pair) -> pa.Table:
        path, pvals = pair
        # partition-value columns live in the directory layout, not the
        # file: never ask the file reader for them
        cols = self.columns
        if cols is not None and pvals:
            cols = [c for c in cols if c not in pvals]
        if self.fmt == "parquet" and self.pushed_filters:
            import pyarrow.parquet as papq
            try:
                t = papq.read_table(path, columns=cols,
                                    use_threads=False,
                                    filters=self.pushed_filters)
            except Exception:
                # e.g. a pushed predicate on a partition column that is
                # not in the file: fall back to the plain read
                t = _read_file(self.fmt, path, cols, self.options)
        else:
            t = _read_file(self.fmt, path, cols, self.options)
        for k, v in pvals.items():
            if k in t.column_names:
                continue
            dt = self.partition_dtypes.get(k)
            from ..columnar.arrow import to_arrow_type
            at = to_arrow_type(dt) if dt is not None else pa.string()
            if v is None:
                val = None
            elif pa.types.is_integer(at):
                val = int(v)
            else:
                val = v
            t = t.append_column(
                k, pa.array([val] * t.num_rows, type=at))
        if self.columns is not None:
            # restore the requested order (partition values append last)
            sel = [c for c in self.columns if c in t.column_names]
            if sel != t.column_names:
                t = t.select(sel)
        return t

    def __iter__(self) -> Iterator[pa.Table]:
        if self.strategy == "MULTITHREADED" and len(self.files) > 1:
            yield from self._multithreaded()
        elif self.strategy == "COALESCING" and len(self.files) > 1:
            yield from self._coalescing()
        else:
            for f in self.files:
                yield self._read(f)

    def _multithreaded(self):
        """Prefetch host buffers with a thread pool; preserve file order.

        (MultiFileCloudParquetPartitionReader role.)"""
        with concurrent.futures.ThreadPoolExecutor(self.num_threads) as pool:
            futures = [pool.submit(self._read, f) for f in self.files]
            for fut in futures:
                yield fut.result()

    def _coalescing(self):
        """Combine small files into bigger host tables before device

        transfer (MultiFileParquetPartitionReader role)."""
        pending: List[pa.Table] = []
        rows = 0
        for f in self.files:
            t = self._read(f)
            pending.append(t)
            rows += t.num_rows
            if rows >= self.coalesce_target_rows:
                yield pa.concat_tables(pending, promote_options="permissive")
                pending, rows = [], 0
        if pending:
            yield pa.concat_tables(pending, promote_options="permissive")


def split_files_into_partitions(files: List,
                                num_partitions: int) -> List[List]:
    """Greedy size-balanced assignment of files to partitions (accepts
    plain paths or (path, partition_values) pairs)."""
    def path_of(f):
        return f[0] if isinstance(f, tuple) else f
    sizes = [(f, os.path.getsize(path_of(f))
              if os.path.exists(path_of(f)) else 0)
             for f in files]
    sizes.sort(key=lambda x: -x[1])
    num_partitions = max(1, min(num_partitions, len(files) or 1))
    buckets: List[List[str]] = [[] for _ in range(num_partitions)]
    loads = [0] * num_partitions
    for f, s in sizes:
        i = loads.index(min(loads))
        buckets[i].append(f)
        loads[i] += s
    return buckets
