"""Self-generated documentation.

Reference: RapidsConf.help/main -> docs/configs.md (RapidsConf.scala:1229)
and SupportedOpsDocs -> docs/supported_ops.md (TypeChecks.scala:1611).

Usage: python -m spark_rapids_tpu.tools.docgen [output_dir]
"""
from __future__ import annotations

import jax as _jax

# host-side CLI: never touch the accelerator backend
_jax.config.update("jax_platforms", "cpu")

import os
import sys

from ..config import generate_docs
from ..plan import overrides as ov
from ..plan import typesig as TS

TS_CAST_FAMILIES = ["bool", "integral", "fp", "decimal", "string",
                    "date", "timestamp", "null"]


def supported_ops_doc() -> str:
    lines = [
        "# Supported expressions on TPU",
        "",
        "Generated from the planner's expression registry "
        "(plan/overrides.py), the analogue of the reference's "
        "supported_ops.md generated from TypeChecks.scala.",
        "",
        "| Expression | Signature (per-parameter where declared) | "
        "Notes |",
        "|---|---|---|",
    ]
    for cls, sig in sorted(ov._EXPR_RULES.items(),
                           key=lambda kv: kv[0].__name__):
        note = getattr(sig, "note", "") or ""
        lines.append(f"| `{cls.__name__}` | {sig.describe()} | {note} |")
    lines += [
        "",
        "# Cast support matrix",
        "",
        "CAST pairs the TPU engine implements (absent pairs fall back "
        "to the CPU engine; TypeChecks.scala:367 CastChecks role):",
        "",
        "| from \\\\ to | " + " | ".join(TS_CAST_FAMILIES) + " |",
        "|---|" + "---|" * len(TS_CAST_FAMILIES),
    ]
    for src in TS_CAST_FAMILIES:
        row = [f"| {src} "]
        for dst in TS_CAST_FAMILIES:
            ok = (src, dst) in TS.CAST_MATRIX or src == dst
            row.append("| S " if ok else "|   ")
        lines.append("".join(row) + "|")
    lines += [
        "",
        "# Supported operators on TPU",
        "",
        "| Logical operator | TPU physical operator | Notes |",
        "|---|---|---|",
        "| LocalRelation | TpuLocalScan | |",
        "| Range | TpuRange | |",
        "| Scan (parquet/orc/csv/json) | TpuFileScan | PERFILE / "
        "MULTITHREADED / COALESCING reader strategies |",
        "| Project | TpuProject | |",
        "| Filter | TpuFilter | |",
        "| Aggregate | TpuHashAggregate | partial/final around exchanges; "
        "sort+segmented-reduce design |",
        "| Distinct | TpuHashAggregate | keys-only aggregate |",
        "| Join | TpuShuffledHashJoin / TpuBroadcastHashJoin / "
        "TpuNestedLoopJoin | inner/left/right/full/semi/anti/cross |",
        "| Sort | TpuSort (+ RangePartitioner exchange for global) | |",
        "| Limit | TpuLocalLimit + TpuGlobalLimit; TopN fusion over "
        "Sort+Limit | |",
        "| Union | TpuUnion | |",
        "| Repartition | TpuShuffleExchange (hash / round-robin) | |",
        "| Window | TpuWindow | row frames; rank/dense_rank/row_number/"
        "lead/lag/sum/count/min/max/avg |",
        "| Expand | TpuExpand | grouping sets |",
        "| WriteFile | TpuFileWrite | parquet/orc/csv |",
        "",
        "Unsupported constructs fall back to the CPU (pyarrow) engine "
        "per-operator with automatic RowToColumnar/ColumnarToRow "
        "transitions; `spark.rapids.tpu.sql.explain=NOT_ON_TPU` prints "
        "the reasons.",
    ]
    return "\n".join(lines) + "\n"


def main(argv=None):
    argv = argv or sys.argv[1:]
    out_dir = argv[0] if argv else "docs"
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "configs.md"), "w") as f:
        f.write(generate_docs())
    with open(os.path.join(out_dir, "supported_ops.md"), "w") as f:
        f.write(supported_ops_doc())
    print(f"wrote {out_dir}/configs.md and {out_dir}/supported_ops.md")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
