"""Tooling: event logs, qualification and profiling CLIs

(reference: tools/ module, SURVEY.md §2.9)."""
from .events import QueryEventLogger, read_event_log  # noqa: F401
