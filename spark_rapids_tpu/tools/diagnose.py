"""Diagnostic-bundle renderer — turns one ``diag-*.json`` incident
bundle (obs/diagnostics.py) into a human-readable report.

A bundle is the automatic post-mortem the service writes on query
failure, device OOM, deadline expiry, cancellation, or a stall-watchdog
trigger.  This tool is the reading side: the incident timeline from the
flight-recorder tail, the stacks of every thread at capture time, the
arena and shuffle occupancy, the plan tree with verifier verdicts, and
the (redacted) conf — one artifact, no repro needed.

Usage:
  python -m spark_rapids_tpu.tools.diagnose <bundle.json>
      [--events N] [--no-stacks]
  python -m spark_rapids_tpu.tools.diagnose --list <bundle_dir>
"""
from __future__ import annotations

import json
import os
import sys
from typing import Dict, List


def _fmt_bytes(n) -> str:
    try:
        n = int(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return str(n)


def _flight_lines(bundle: Dict, max_events: int) -> List[str]:
    fl = bundle.get("flight") or {}
    out = []
    occ = fl.get("occupancy")
    if occ:
        out.append(f"recorder: threads={occ.get('threads')} "
                   f"buffered={occ.get('events_buffered')} "
                   f"recorded={occ.get('events_recorded')} "
                   f"cap/thread={occ.get('capacity_per_thread')}")
    events = fl.get("query_events") or []
    source = "query"
    if not events:
        events = fl.get("recent_events") or []
        source = "recent (no query-attributed events)"
    if not events:
        out.append("  <no flight-recorder events captured>")
        return out
    shown = events[-max_events:]
    out.append(f"last {len(shown)} of {len(events)} {source} events "
               "(oldest first; t=0 at first shown):")
    t_base = shown[0].get("ts_ns", 0)
    for e in shown:
        dt_ms = (e.get("ts_ns", 0) - t_base) / 1e6
        extra = ""
        if e.get("a"):
            extra += f" a={e['a']}"
        if e.get("b"):
            extra += f" b={e['b']}"
        out.append(f"  +{dt_ms:10.3f}ms  {e.get('thread', ''):<24s}"
                   f"{e.get('kind', ''):<12s}{e.get('name', '')}{extra}")
    return out


def _thread_lines(bundle: Dict) -> List[str]:
    out = []
    for t in bundle.get("threads") or []:
        if "error" in t and "name" not in t:
            out.append(f"  <stack capture error: {t['error']}>")
            continue
        out.append(f"thread {t.get('name')} (ident={t.get('ident')}"
                   f"{', daemon' if t.get('daemon') else ''}):")
        for frame in t.get("stack") or []:
            out.append("  " + frame.replace("\n", "\n  "))
    return out


def _arena_lines(bundle: Dict) -> List[str]:
    arena = bundle.get("arena") or {}
    out = []
    stats = arena.get("stats") or {}
    if stats:
        out.append("  ".join(f"{k}={_fmt_bytes(v) if 'bytes' in k else v}"
                             for k, v in sorted(stats.items())))
    sem = arena.get("semaphore")
    if sem:
        out.append(f"semaphore: permits={sem.get('permits')} "
                   f"available={sem.get('available')} "
                   f"holders={sem.get('holders')}")
    entries = arena.get("entries") or []
    if entries:
        out.append(f"{len(entries)} catalog entries (largest first):")
        for e in entries[:20]:
            out.append(f"  {e.get('tier', ''):<8s}"
                       f"{_fmt_bytes(e.get('nbytes')):>12s}  "
                       f"prio={e.get('priority')}  {e.get('buffer_id')}")
        if len(entries) > 20:
            out.append(f"  ... {len(entries) - 20} more")
    if "error" in arena:
        out.append(f"  <arena capture error: {arena['error']}>")
    return out


def render_bundle(bundle: Dict, max_events: int = 64,
                  show_stacks: bool = True) -> str:
    lines = ["=" * 72,
             f"incident bundle: trigger={bundle.get('trigger')} "
             f"query={bundle.get('query_id')} "
             f"captured={bundle.get('captured_at')}",
             "=" * 72]
    err = bundle.get("error")
    if err:
        lines.append(f"error: {err.get('type')}: {err.get('message')}")
        tb = err.get("traceback") or []
        if tb:
            lines.append("-- traceback --")
            lines.extend("  " + ln.rstrip("\n") for ln in tb)
    q = bundle.get("query")
    if q:
        lines.append(f"query: status={q.get('status')} "
                     f"tenant={q.get('tenant')} "
                     f"attempts={q.get('attempts')}")
        rec = q.get("record") or {}
        if rec:
            lines.append(f"  outcome={rec.get('outcome')} "
                         f"queue_wait_ms={rec.get('queue_wait_ms')} "
                         f"execute_ms={rec.get('execute_ms')} "
                         f"sem_wait_ms={rec.get('sem_wait_ms')} "
                         f"spill_bytes={rec.get('spill_bytes')}")
    c = bundle.get("cancel")
    if c:
        lines.append(f"cancel token: cancelled={c.get('cancelled')} "
                     f"reason={c.get('reason')} "
                     f"observed={c.get('observed')}")
    svc = bundle.get("service")
    if svc:
        lines.append("-- service snapshot --")
        lines.append("  " + "  ".join(
            f"{k}={v}" for k, v in sorted(svc.items())
            if not isinstance(v, (dict, list))))
        wd = svc.get("watchdog")
        if isinstance(wd, dict):
            lines.append(f"  watchdog: {wd}")
    lines.append("-- flight recorder --")
    lines.extend("  " + ln for ln in _flight_lines(bundle, max_events))
    lines.append("-- arena --")
    lines.extend("  " + ln for ln in _arena_lines(bundle))
    sh = bundle.get("shuffle")
    if sh:
        lines.append("-- shuffle --")
        lines.append("  " + "  ".join(f"{k}={v}"
                                      for k, v in sorted(sh.items())))
    plan = bundle.get("plan")
    if plan:
        lines.append("-- plan --")
        for ln in (plan.get("tree") or "").splitlines():
            lines.append("  " + ln)
        pv = plan.get("verify")
        if pv:
            if pv.get("ok"):
                lines.append("  verifier: ok")
            else:
                lines.append("  verifier violations:")
                for v in pv.get("violations") or []:
                    lines.append(f"    node {v.get('node_index')}: "
                                 f"{v.get('rule')}: {v.get('message')}")
    if show_stacks:
        lines.append("-- thread stacks --")
        lines.extend("  " + ln for ln in _thread_lines(bundle))
    conf = bundle.get("conf")
    if conf:
        lines.append("-- conf (explicit settings, secrets redacted) --")
        for k, v in sorted(conf.items()):
            lines.append(f"  {k} = {v}")
    metrics = bundle.get("metrics")
    if isinstance(metrics, dict) and "error" not in metrics:
        lines.append(f"-- metrics snapshot: {len(metrics)} series "
                     "(full values in the JSON) --")
    return "\n".join(lines)


def list_bundles(directory: str) -> List[str]:
    """Bundle paths in ``directory``, oldest first (the rotation
    order)."""
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith("diag-") and n.endswith(".json"))
    except OSError:
        return []
    return [os.path.join(directory, n) for n in names]


def main(argv=None):
    argv = list(argv if argv is not None else sys.argv[1:])
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: diagnose <bundle.json> [--events N] [--no-stacks]\n"
              "       diagnose --list <bundle_dir>", file=sys.stderr)
        return 1
    if argv[0] == "--list":
        paths = list_bundles(argv[1]) if len(argv) > 1 else []
        for p in paths:
            print(p)
        return 0 if paths else 1

    def _opt(flag, default=None):
        if flag in argv:
            i = argv.index(flag)
            v = argv[i + 1]
            del argv[i:i + 2]
            return v
        return default

    max_events = int(_opt("--events", 64))
    show_stacks = "--no-stacks" not in argv
    if not show_stacks:
        argv.remove("--no-stacks")
    with open(argv[0], encoding="utf-8") as f:
        bundle = json.load(f)
    print(render_bundle(bundle, max_events=max_events,
                        show_stacks=show_stacks))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
