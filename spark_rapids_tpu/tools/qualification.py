"""Qualification tool — reference: tools/.../qualification/

QualificationMain.scala:29: parses event logs and scores workloads for
accelerator fit (what fraction of query time could go to the TPU).

Usage:  python -m spark_rapids_tpu.tools.qualification <event_log.jsonl>
"""
from __future__ import annotations

import jax as _jax

# host-side CLI: never touch the accelerator backend
_jax.config.update("jax_platforms", "cpu")

import json
import sys
from typing import Dict, List

from .events import read_event_log

# operators with TPU implementations (mirrors the planner registry)
TPU_NODES = {
    "TpuLocalScan", "TpuRange", "TpuProject", "TpuFilter",
    "TpuHashAggregate", "TpuShuffledHashJoin", "TpuBroadcastHashJoin",
    "TpuNestedLoopJoin", "TpuSort", "TpuTopN", "TpuWindow", "TpuExpand",
    "TpuLocalLimit", "TpuGlobalLimit", "TpuUnion", "TpuShuffleExchange",
    "TpuBroadcastExchange", "TpuCoalescePartitions", "TpuCoalesceBatches",
    "TpuFileScan", "TpuFileWrite", "RowToColumnar", "ColumnarToRow",
}


def qualify(records: List[Dict]) -> Dict:
    """Score each query + the app overall for TPU acceleration fit."""
    per_query = []
    total_ms = 0.0
    accel_ms = 0.0
    for r in records:
        nodes = r.get("nodes", [])
        n_tpu = sum(1 for n in nodes if n in TPU_NODES)
        frac = n_tpu / len(nodes) if nodes else 0.0
        wall = r.get("wall_ms", 0.0)
        total_ms += wall
        accel_ms += wall * frac
        per_query.append({
            "query_id": r.get("query_id"),
            "wall_ms": wall,
            "tpu_operator_fraction": round(frac, 3),
            "fallbacks": r.get("fallbacks", []),
            "recommendation": (
                "STRONGLY RECOMMENDED" if frac >= 0.9 else
                "RECOMMENDED" if frac >= 0.5 else
                "NOT RECOMMENDED"),
        })
    score = accel_ms / total_ms if total_ms else 0.0
    return {
        "app_score": round(score, 3),
        "estimated_accelerable_ms": round(accel_ms, 1),
        "total_ms": round(total_ms, 1),
        "recommendation": ("STRONGLY RECOMMENDED" if score >= 0.9 else
                           "RECOMMENDED" if score >= 0.5 else
                           "NOT RECOMMENDED"),
        "queries": per_query,
    }


def main(argv=None):
    argv = argv or sys.argv[1:]
    if not argv:
        print("usage: qualification <event_log.jsonl>", file=sys.stderr)
        return 1
    records = read_event_log(argv[0])
    print(json.dumps(qualify(records), indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
