"""Qualification tool — reference: tools/.../qualification/

QualificationMain.scala:29: parses event logs and scores workloads for
accelerator fit (what fraction of query time could go to the TPU).

Usage:  python -m spark_rapids_tpu.tools.qualification <event_log.jsonl>
"""
from __future__ import annotations

import jax as _jax

# host-side CLI: never touch the accelerator backend
_jax.config.update("jax_platforms", "cpu")

import json
import sys
from typing import Dict, List

from .events import read_event_log

# operators with TPU implementations (mirrors the planner registry)
TPU_NODES = {
    "TpuLocalScan", "TpuRange", "TpuProject", "TpuFilter",
    "TpuHashAggregate", "TpuShuffledHashJoin", "TpuBroadcastHashJoin",
    "TpuNestedLoopJoin", "TpuSort", "TpuTopN", "TpuWindow", "TpuExpand",
    "TpuLocalLimit", "TpuGlobalLimit", "TpuUnion", "TpuShuffleExchange",
    "TpuBroadcastExchange", "TpuCoalescePartitions", "TpuCoalesceBatches",
    "TpuFileScan", "TpuFileWrite", "RowToColumnar", "ColumnarToRow",
    "TpuMapInPandas", "TpuGroupedMapInPandas", "TpuCogroupedMapInPandas",
    "TpuWindowInPandas", "TpuMeshAggregate", "TpuMeshShuffledJoin",
    "TpuMeshSort", "TpuStagedCompute", "TpuAQEShuffleRead",
    "TpuAdaptiveShuffledJoin", "TpuGenerate", "TpuCachedExec",
}


#: per-operator speedup estimates — the operatorsScore.csv role
#: (reference tools score each exec/expr with an expected GPU speedup;
#: these numbers are the CBO's calibrated TPU factors)
OPERATOR_SPEEDUP = {
    "TpuHashAggregate": 10.0, "TpuShuffledHashJoin": 10.0,
    "TpuBroadcastHashJoin": 10.0, "TpuSort": 8.0, "TpuTopN": 8.0,
    "TpuWindow": 10.0, "TpuProject": 6.0, "TpuFilter": 6.0,
    "TpuExpand": 6.0, "TpuFileScan": 3.0, "TpuFileWrite": 3.0,
    "TpuShuffleExchange": 4.0, "TpuBroadcastExchange": 4.0,
}
DEFAULT_SPEEDUP = 3.0
#: transitions are overhead, not acceleration
TRANSITION_NODES = {"RowToColumnar", "ColumnarToRow"}


#: foreign CPU-Spark physical operator -> the TPU exec that would
#: replace it.  This is the tool's real purpose (QualificationMain
#: analyzes CPU Spark event logs to forecast migration value; scoring
#: this engine's own logs is circular).  Unmapped operators count as
#: unsupported, exactly like the reference's unsupported-ops report.
SPARK_CPU_NODE_MAP = {
    "HashAggregate": "TpuHashAggregate",
    "ObjectHashAggregate": "TpuHashAggregate",
    "SortAggregate": "TpuHashAggregate",
    "SortMergeJoin": "TpuShuffledHashJoin",
    "ShuffledHashJoin": "TpuShuffledHashJoin",
    "BroadcastHashJoin": "TpuBroadcastHashJoin",
    "BroadcastNestedLoopJoin": "TpuNestedLoopJoin",
    "CartesianProduct": "TpuNestedLoopJoin",
    "Project": "TpuProject",
    "Filter": "TpuFilter",
    "Sort": "TpuSort",
    "TakeOrderedAndProject": "TpuTopN",
    "Window": "TpuWindow",
    "Expand": "TpuExpand",
    "Generate": "TpuGenerate",
    "Union": "TpuUnion",
    "LocalLimit": "TpuLocalLimit",
    "GlobalLimit": "TpuGlobalLimit",
    "Exchange": "TpuShuffleExchange",
    "ShuffleExchange": "TpuShuffleExchange",
    "BroadcastExchange": "TpuBroadcastExchange",
    "AQEShuffleRead": "TpuAQEShuffleRead",
    "CustomShuffleReader": "TpuAQEShuffleRead",
    "FileSourceScan": "TpuFileScan",
    "Scan parquet": "TpuFileScan",
    "Scan orc": "TpuFileScan",
    "Scan csv": "TpuFileScan",
    "BatchScan": "TpuFileScan",
    "LocalTableScan": "TpuLocalScan",
    "Range": "TpuRange",
    "Coalesce": "TpuCoalescePartitions",
    "InMemoryTableScan": "TpuCachedExec",
    "DataWritingCommand": "TpuFileWrite",
    "InsertIntoHadoopFsRelationCommand": "TpuFileWrite",
    "MapInPandas": "TpuMapInPandas",
    "FlatMapGroupsInPandas": "TpuGroupedMapInPandas",
    "ArrowEvalPython": "TpuMapInPandas",
    "WindowInPandas": "TpuWindowInPandas",
    "ColumnarToRow": "ColumnarToRow",
    "RowToColumnar": "RowToColumnar",
}

#: structural containers in CPU Spark plans that are not operators
_FOREIGN_CONTAINERS = {"WholeStageCodegen", "InputAdapter",
                       "AdaptiveSparkPlan", "ReusedExchange", "Subquery",
                       "SubqueryBroadcast", "ReusedSubquery"}


def _node_name(node: str) -> str:
    return node.split("[", 1)[0].split("(", 1)[0].strip()


def normalize_records(records: List[Dict]) -> List[Dict]:
    """Map foreign (CPU Spark) operator names to their would-be TPU
    execs so the same scoring applies; native Tpu* records pass
    through.  Containers (WholeStageCodegen...) drop out."""
    out = []
    for r in records:
        nodes = []
        for n in r.get("nodes", []):
            name = _node_name(str(n))
            if name.startswith("Tpu") or name in TRANSITION_NODES:
                nodes.append(name)
                continue
            base = name.split("#", 1)[0].strip()
            if base in _FOREIGN_CONTAINERS or \
                    any(base.startswith(c) for c in _FOREIGN_CONTAINERS):
                continue
            mapped = SPARK_CPU_NODE_MAP.get(base)
            if mapped is None:
                # plan lines carry detail suffixes ("Exchange
                # hashpartitioning(...)", "Scan parquet db.t"): longest
                # matching prefix wins
                for key in sorted(SPARK_CPU_NODE_MAP, key=len,
                                  reverse=True):
                    if base.startswith(key):
                        mapped = SPARK_CPU_NODE_MAP[key]
                        break
            nodes.append(mapped if mapped is not None else base)
        r2 = dict(r)
        r2["nodes"] = nodes
        if "wall_ms" not in r2:
            r2["wall_ms"] = float(r2.pop("duration_ms",
                                         r2.pop("durationMs", 0.0)))
        out.append(r2)
    return out


_SQL_START = ("org.apache.spark.sql.execution.ui."
              "SparkListenerSQLExecutionStart")
_SQL_END = ("org.apache.spark.sql.execution.ui."
            "SparkListenerSQLExecutionEnd")
_SQL_AQE = ("org.apache.spark.sql.execution.ui."
            "SparkListenerSQLAdaptiveExecutionUpdate")


def _flatten_plan_info(info: Dict, out: List[str]) -> None:
    """sparkPlanInfo {nodeName, simpleString, children[...]} -> node
    name list, depth-first (the structured tree Spark serializes with
    every SQLExecutionStart — no plan-string parsing needed)."""
    name = str(info.get("nodeName", "")).strip()
    if name:
        out.append(name)
    for child in info.get("children", []) or []:
        _flatten_plan_info(child, out)


def read_spark_eventlog(path: str) -> List[Dict]:
    """Parse a REAL Apache Spark event log (the JSON-lines file the
    history server reads; plain or .gz) into qualification records.

    Reference: EventsProcessor.scala:1 / ApplicationInfo.scala — the
    reference qualification tool consumes exactly these events.  Per
    SQL execution: the LAST plan wins (AQE re-plans replace the
    original via SparkListenerSQLAdaptiveExecutionUpdate), and wall
    time is SQLExecutionEnd.time - SQLExecutionStart.time.
    """
    import gzip
    import io as _io
    opener = gzip.open if path.endswith(".gz") else open
    plans: Dict[int, List[str]] = {}
    descs: Dict[int, str] = {}
    starts: Dict[int, float] = {}
    ends: Dict[int, float] = {}
    app_name = None
    with opener(path, "rt", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            kind = ev.get("Event")
            if kind == "SparkListenerApplicationStart":
                app_name = ev.get("App Name")
            elif kind == _SQL_START:
                eid = ev.get("executionId")
                if eid is None:
                    continue
                nodes: List[str] = []
                _flatten_plan_info(ev.get("sparkPlanInfo") or {}, nodes)
                plans[eid] = nodes
                descs[eid] = str(ev.get("description") or "")[:200]
                if "time" in ev:
                    starts[eid] = float(ev["time"])
            elif kind == _SQL_AQE:
                eid = ev.get("executionId")
                if eid is None:
                    continue
                nodes = []
                _flatten_plan_info(ev.get("sparkPlanInfo") or {}, nodes)
                if nodes:
                    plans[eid] = nodes
            elif kind == _SQL_END:
                eid = ev.get("executionId")
                if eid is not None and "time" in ev:
                    ends[eid] = float(ev["time"])
    records = []
    for eid, nodes in sorted(plans.items()):
        # rolled/compacted logs can hold an End without its Start (or
        # vice versa): only a complete pair yields a wall time
        if eid in starts and eid in ends:
            wall = max(ends[eid] - starts[eid], 0.0)
        else:
            wall = 0.0
        records.append({
            "query_id": f"{app_name or 'app'}:sql-{eid}",
            "description": descs.get(eid, ""),
            "wall_ms": wall,
            "nodes": nodes,
        })
    return records


def _looks_like_spark_eventlog(path: str) -> bool:
    """First parseable line carries Spark's {"Event": ...} envelope."""
    import gzip
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rt", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    return False
                return isinstance(ev, dict) and "Event" in ev
    except OSError:
        return False
    return False


def read_foreign_json(path: str) -> List[Dict]:
    """Foreign trace format: a JSON file with either a list of
    {query_id, wall_ms|duration_ms, nodes:[operator names]} or
    {"queries": [...]} — the simple operator-names+times contract any
    CPU run can produce (from explain output + query timings)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = doc.get("queries", [])
    return list(doc)


def qualify(records: List[Dict]) -> Dict:
    """Score each query + the app overall for TPU acceleration fit.

    Reference: QualificationMain/QualificationAppInfo — reports the
    accelerable fraction, an ESTIMATED accelerated runtime using
    per-operator speedup factors, the concrete unsupported operators
    with their tag reasons, and per-query + app recommendations.
    """
    per_query = []
    total_ms = 0.0
    accel_ms = 0.0
    est_ms = 0.0
    unsupported: Dict[str, int] = {}
    for r in normalize_records(records):
        nodes = [_node_name(n) for n in r.get("nodes", [])]
        core = [n for n in nodes if n not in TRANSITION_NODES]
        n_tpu = sum(1 for n in core if n in TPU_NODES)
        frac = n_tpu / len(core) if core else 0.0
        wall = r.get("wall_ms", 0.0)
        # estimated accelerated wall: accelerable share shrinks by the
        # weighted operator speedup; the CPU share stays
        speedups = [OPERATOR_SPEEDUP.get(n, DEFAULT_SPEEDUP)
                    for n in core if n in TPU_NODES]
        avg_speedup = (sum(speedups) / len(speedups)) if speedups \
            else 1.0
        est = wall * (1 - frac) + wall * frac / avg_speedup
        total_ms += wall
        accel_ms += wall * frac
        est_ms += est
        for n in core:
            if n not in TPU_NODES:
                unsupported[n] = unsupported.get(n, 0) + 1
        per_query.append({
            "query_id": r.get("query_id"),
            "wall_ms": wall,
            "tpu_operator_fraction": round(frac, 3),
            "estimated_speedup": round(wall / est, 2) if est else None,
            "estimated_accelerated_ms": round(est, 1),
            "unsupported_ops": sorted({n for n in core
                                       if n not in TPU_NODES}),
            "fallbacks": r.get("fallbacks", []),
            "recommendation": (
                "STRONGLY RECOMMENDED" if frac >= 0.9 else
                "RECOMMENDED" if frac >= 0.5 else
                "NOT RECOMMENDED"),
        })
    score = accel_ms / total_ms if total_ms else 0.0
    return {
        "app_score": round(score, 3),
        "estimated_accelerable_ms": round(accel_ms, 1),
        "estimated_accelerated_ms": round(est_ms, 1),
        "estimated_app_speedup": round(total_ms / est_ms, 2)
        if est_ms else None,
        "total_ms": round(total_ms, 1),
        "unsupported_operators": dict(sorted(unsupported.items())),
        "recommendation": ("STRONGLY RECOMMENDED" if score >= 0.9 else
                           "RECOMMENDED" if score >= 0.5 else
                           "NOT RECOMMENDED"),
        "queries": per_query,
    }


def to_csv(report: Dict) -> str:
    """Per-query CSV (the reference writes qualification CSVs for
    spreadsheet triage)."""
    import io
    import csv
    buf = io.StringIO()
    w = csv.writer(buf)
    w.writerow(["query_id", "wall_ms", "tpu_operator_fraction",
                "estimated_speedup", "estimated_accelerated_ms",
                "recommendation", "unsupported_ops"])
    for q in report["queries"]:
        w.writerow([q["query_id"], q["wall_ms"],
                    q["tpu_operator_fraction"], q["estimated_speedup"],
                    q["estimated_accelerated_ms"], q["recommendation"],
                    ";".join(q["unsupported_ops"])])
    return buf.getvalue()


def main(argv=None):
    argv = argv or sys.argv[1:]
    if not argv:
        print("usage: qualification <event_log.jsonl|foreign.json> "
              "[--csv]", file=sys.stderr)
        return 1
    path = argv[0]
    if _looks_like_spark_eventlog(path):
        records = read_spark_eventlog(path)
    elif path.endswith(".json"):
        records = read_foreign_json(path)
    else:
        records = read_event_log(path)
    report = qualify(records)
    if "--csv" in argv:
        print(to_csv(report), end="")
    else:
        print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
