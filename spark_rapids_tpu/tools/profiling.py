"""Profiling tool — reference: tools/.../profiling/ProfileMain.scala:31 +

Analysis.scala + GenerateDot.scala:40: extracts per-operator info from
event logs, compares runs, and renders DOT plan graphs.

Usage:
  python -m spark_rapids_tpu.tools.profiling <event_log.jsonl> [--dot]
  python -m spark_rapids_tpu.tools.profiling --compare a.jsonl b.jsonl
"""
from __future__ import annotations

import jax as _jax

# host-side CLI: never touch the accelerator backend
_jax.config.update("jax_platforms", "cpu")

import json
import sys
from typing import Dict, List

from .events import read_event_log


def analyze(records: List[Dict]) -> Dict:
    """Per-operator aggregated metrics across queries (Analysis.scala)."""
    op_totals: Dict[str, Dict[str, float]] = {}
    for r in records:
        for node_key, metrics in r.get("node_metrics", {}).items():
            name = node_key.split(":", 1)[1] if ":" in node_key else node_key
            agg = op_totals.setdefault(name, {"occurrences": 0})
            agg["occurrences"] += 1
            for m, v in metrics.items():
                agg[m] = agg.get(m, 0) + v
    slowest = sorted(records, key=lambda r: -r.get("wall_ms", 0))[:10]
    return {
        "num_queries": len(records),
        "total_wall_ms": round(sum(r.get("wall_ms", 0) for r in records), 1),
        "operator_totals": op_totals,
        "slowest_queries": [
            {"query_id": r.get("query_id"), "wall_ms": r.get("wall_ms"),
             "fallbacks": r.get("fallbacks", [])} for r in slowest],
    }


#: metric name suffix -> category (the reference's Analysis groups
#: nanosecond timings apart from row/batch/byte counters)
_TIME_SUFFIXES = ("Time", "time")


def breakdown(records: List[Dict]) -> Dict:
    """Where did the time go? (Analysis.scala stage/SQL breakdown.)

    Splits aggregated node metrics into time (ns -> ms) vs counter
    categories, computes per-operator shares of total attributed time,
    and isolates the shuffle/io story (exchange + scan + transition
    nodes) — the first things the reference's profiler surfaces.
    """
    time_by_op: Dict[str, float] = {}
    counters_by_op: Dict[str, Dict[str, float]] = {}
    for r in records:
        for node_key, metrics in r.get("node_metrics", {}).items():
            name = node_key.split(":", 1)[1] if ":" in node_key \
                else node_key
            name = name.split("[", 1)[0].strip()
            for m, v in metrics.items():
                if m.endswith(_TIME_SUFFIXES):
                    time_by_op[name] = time_by_op.get(name, 0.0) + \
                        v / 1e6
                else:
                    c = counters_by_op.setdefault(name, {})
                    c[m] = c.get(m, 0) + v
    total_t = sum(time_by_op.values()) or 1.0
    shuffle_ops = {k: v for k, v in time_by_op.items()
                   if "Exchange" in k or "Shuffle" in k}
    io_ops = {k: v for k, v in time_by_op.items()
              if "Scan" in k or "Write" in k}
    return {
        "attributed_time_ms": round(total_t, 1),
        "time_by_operator_ms": {k: round(v, 1) for k, v in sorted(
            time_by_op.items(), key=lambda kv: -kv[1])},
        "time_share": {k: round(v / total_t, 3) for k, v in sorted(
            time_by_op.items(), key=lambda kv: -kv[1])},
        "shuffle_time_ms": round(sum(shuffle_ops.values()), 1),
        "io_time_ms": round(sum(io_ops.values()), 1),
        "counters_by_operator": counters_by_op,
    }


def compare(a: List[Dict], b: List[Dict]) -> Dict:
    """Compare two runs query-by-query (reference: compare mode)."""
    bm = {r.get("query_id"): r for r in b}
    rows = []
    for r in a:
        other = bm.get(r.get("query_id"))
        if other is None:
            continue
        wa, wb = r.get("wall_ms", 0), other.get("wall_ms", 0)
        rows.append({"query_id": r.get("query_id"), "a_ms": wa, "b_ms": wb,
                     "speedup": round(wa / wb, 3) if wb else None})
    return {"queries": rows}


def generate_dot(record: Dict) -> str:
    """Render one query's physical plan as DOT (GenerateDot.scala:40)."""
    lines = ["digraph plan {", "  rankdir=BT;",
             "  node [shape=box, fontname=monospace];"]
    plan = record.get("physical_plan", "")
    nodes = []
    for ln in plan.splitlines():
        depth = (len(ln) - len(ln.lstrip())) // 2
        nodes.append((depth, ln.strip()))
    metrics = record.get("node_metrics", {})
    keys = list(metrics.keys())
    stack: List[int] = []
    for i, (depth, label) in enumerate(nodes):
        m = metrics.get(keys[i], {}) if i < len(keys) else {}
        mtxt = "\\n".join(f"{k}={v}" for k, v in sorted(m.items())
                          if k in ("numOutputRows", "opTime"))
        color = "lightgreen" if label.startswith("Tpu") or \
            label.startswith("RowToColumnar") else "lightsalmon" \
            if label.startswith("Cpu") else "white"
        lines.append(
            f'  n{i} [label="{label}\\n{mtxt}", style=filled, '
            f'fillcolor={color}];')
        while stack and nodes[stack[-1]][0] >= depth:
            stack.pop()
        if stack:
            lines.append(f"  n{i} -> n{stack[-1]};")
        stack.append(i)
    lines.append("}")
    return "\n".join(lines)


def main(argv=None):
    argv = argv or sys.argv[1:]
    if not argv:
        print("usage: profiling <log.jsonl> [--dot] | "
              "--compare a.jsonl b.jsonl", file=sys.stderr)
        return 1
    if argv[0] == "--compare":
        a = read_event_log(argv[1])
        b = read_event_log(argv[2])
        print(json.dumps(compare(a, b), indent=2))
        return 0
    records = read_event_log(argv[0])
    if "--dot" in argv:
        for r in records:
            print(generate_dot(r))
    elif "--breakdown" in argv:
        print(json.dumps(breakdown(records), indent=2))
    else:
        out = analyze(records)
        out["breakdown"] = breakdown(records)
        print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
