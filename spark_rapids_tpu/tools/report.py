"""Per-query report generator — the SQL-UI / profiling-report stand-in.

Joins the structured event log (engine ``query`` records + service
lifecycle lines, both keyed by the stable ``query_id``) and, when given,
the span tracer's Chrome trace JSON, into one readable per-query story:

- the physical plan tree annotated with each operator's attributed time
  and share of the total (the SQL UI's "time in operator" view);
- the retry/spill story: admission, queue wait, each attempt's outcome,
  backoffs, semaphore wait and spill bytes;
- the critical-path spans from the trace (longest exclusive regions);
- with ``--stats``, the runtime stats plane (obs/stats.py): per-member
  device-time shares inside fused superstages, the per-exchange
  partition/skew/distinct table, and dispatch-duration percentiles
  (degrades to a one-line notice on logs without a StatsProfile).

Tolerant of older logs: records missing newer fields (``flushes``,
``stats_profile``, ``sem_wait_ms``...) render with "-" placeholders
rather than failing.

Usage:
  python -m spark_rapids_tpu.tools.report <event_log.jsonl>
      [--query QID] [--trace trace.json] [--html out.html] [--stats]
"""
from __future__ import annotations

import jax as _jax

# host-side CLI: never touch the accelerator backend
_jax.config.update("jax_platforms", "cpu")

import html as _html
import json
import sys
from typing import Dict, List, Optional

from .events import read_event_log

#: lifecycle kinds emitted by the query service, in story order
_LIFECYCLE = ("admitted", "shed", "retry", "watchdog", "cancelled",
              "completed", "failed")


# ---------------------------------------------------------------------------
# event-log join
# ---------------------------------------------------------------------------

def load_query_stories(path: str) -> Dict:
    """{query_id: {"engine": [query records], "service": [lifecycle
    records]}} across the log and its rotation segments, preserving
    file order within each stream."""
    stories: Dict = {}
    for rec in read_event_log(path, events=None, include_rotated=True):
        qid = rec.get("query_id")
        story = stories.setdefault(
            qid, {"engine": [], "service": []})
        if rec.get("event", "query") == "query":
            story["engine"].append(rec)
        else:
            story["service"].append(rec)
    return stories


# ---------------------------------------------------------------------------
# plan tree with time shares
# ---------------------------------------------------------------------------

def plan_time_shares(record: Dict) -> List[Dict]:
    """One row per plan node: {depth, label, time_ms, share} — the
    node_metrics keys are "<preorder-index>:<Name>" in the same order
    the tree string prints, so the join is positional (the
    generate_dot discipline)."""
    nodes = []
    for ln in record.get("physical_plan", "").splitlines():
        depth = (len(ln) - len(ln.lstrip())) // 2
        nodes.append((depth, ln.strip()))
    metrics = record.get("node_metrics", {})
    keys = list(metrics.keys())
    # per-node verifier verdicts (analysis/plan_verify via the event
    # logger): node_index keys the same preorder the tree prints
    pv = record.get("plan_verify")
    by_node: Dict[int, List[str]] = {}
    if pv:
        for v in pv.get("violations", []):
            by_node.setdefault(int(v["node_index"]), []).append(
                f"{v['rule']}: {v['message']}")
    rows = []
    for i, (depth, label) in enumerate(nodes):
        m = metrics.get(keys[i], {}) if i < len(keys) else {}
        t_ns = sum(v for k, v in m.items()
                   if k.endswith("Time") or k.endswith("time"))
        verify = None
        if pv:
            verify = "[!! " + "; ".join(by_node[i]) + "]" \
                if i in by_node else "[ok]"
        rows.append({"depth": depth, "label": label,
                     "time_ms": t_ns / 1e6,
                     "rows": m.get("numOutputRows"),
                     "verify": verify})
    total = sum(r["time_ms"] for r in rows)
    for r in rows:
        r["share"] = (r["time_ms"] / total) if total else 0.0
    return rows


def _format_plan(rows: List[Dict]) -> List[str]:
    out = []
    for r in rows:
        bar = "#" * int(round(r["share"] * 20))
        annot = f"{r['share'] * 100:5.1f}% {r['time_ms']:9.2f}ms"
        if r.get("rows") is not None:
            annot += f"  rows={r['rows']}"
        line = (f"  {annot:<44s} {bar:<20s} "
                f"{'  ' * r['depth']}{r['label']}")
        if r.get("verify"):
            line += f"  {r['verify']}"
        out.append(line)
    return out


# ---------------------------------------------------------------------------
# trace join (critical-path spans)
# ---------------------------------------------------------------------------

def load_trace(path: str) -> List[Dict]:
    with open(path) as f:
        doc = json.load(f)
    return [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]


def critical_spans(events: List[Dict], query_id,
                   top: int = 12) -> List[Dict]:
    """Longest spans attributed to ``query_id`` (or unattributed when
    the trace holds a single query), grouped by (name, cat)."""
    qid = str(query_id)
    mine = [e for e in events
            if str(e.get("args", {}).get("query_id", qid)) == qid]
    agg: Dict = {}
    for e in mine:
        key = (e["name"], e.get("cat", ""))
        a = agg.setdefault(key, {"name": e["name"],
                                 "cat": e.get("cat", ""),
                                 "count": 0, "total_ms": 0.0,
                                 "max_ms": 0.0})
        dur_ms = e.get("dur", 0.0) / 1e3
        a["count"] += 1
        a["total_ms"] += dur_ms
        a["max_ms"] = max(a["max_ms"], dur_ms)
    out = sorted(agg.values(), key=lambda a: -a["total_ms"])[:top]
    for a in out:
        a["total_ms"] = round(a["total_ms"], 3)
        a["max_ms"] = round(a["max_ms"], 3)
    return out


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def _service_story(service: List[Dict]) -> List[str]:
    """The retry/spill story in chronological lines."""
    out = []
    for rec in sorted(service, key=lambda r: r.get("ts", 0)):
        kind = rec.get("event")
        if kind == "admitted":
            out.append(f"admitted    tenant={rec.get('tenant')} "
                       f"priority={rec.get('priority')} "
                       f"queue_depth={rec.get('queue_depth')} "
                       f"deadline_ms={rec.get('deadline_ms')}")
        elif kind == "retry":
            out.append(f"retry #{rec.get('attempt')}    "
                       f"reason={rec.get('reason')} "
                       f"backoff_ms={rec.get('backoff_ms')} "
                       f"overlay={rec.get('conf_overlay')}")
        elif kind == "shed":
            line = f"shed        {rec.get('reason')}"
            if rec.get("diag_bundle"):
                line += f"  bundle={rec['diag_bundle']}"
            out.append(line)
        elif kind == "watchdog":
            out.append(f"watchdog    stalled_s={rec.get('stalled_s')}"
                       + (f"  bundle={rec['diag_bundle']}"
                          if rec.get("diag_bundle") else ""))
        elif kind in ("completed", "failed", "cancelled"):
            # wall-clock split: queue wait / execution / the inline
            # compile time hidden inside execution (perf plane)
            line = (
                f"{kind:<11s} attempts={rec.get('attempts')} "
                f"queue_wait_ms={rec.get('queue_wait_ms')} "
                f"execute_ms={rec.get('execute_ms')} "
                f"inline_compile_ms={_fmt(rec.get('inline_compile_ms'))} "
                f"sem_wait_ms={rec.get('sem_wait_ms')} "
                f"spill_bytes={rec.get('spill_bytes')} "
                f"spill_ms={_fmt(rec.get('spill_ms'))} "
                f"unspill_count={_fmt(rec.get('unspill_count'))}"
                + (f" leaked_entries={rec.get('leaked_entries')}"
                   if rec.get("leaked_entries") else "")
                + (f" error={rec.get('error')}"
                   if rec.get("error") else ""))
            if rec.get("diag_bundle"):
                # the incident artifact for this outcome (render it
                # with tools/diagnose.py)
                line += f"  bundle={rec['diag_bundle']}"
            out.append(line)
            pred_ms = rec.get("predicted_exec_ms")
            if pred_ms is not None:
                # admission-time prediction vs what actually happened
                # (service/scheduler.py honesty metric)
                pline = f"predicted   exec_ms={pred_ms}"
                actual = rec.get("execute_ms")
                if kind == "completed" and isinstance(
                        actual, (int, float)) and actual > 0:
                    err = abs(float(pred_ms) - float(actual)) \
                        / float(actual) * 100.0
                    pline += (f" actual_ms={actual} "
                              f"err={err:.1f}%")
                out.append(pline)
    return out


def _fmt(v):
    """Missing-field placeholder: older event logs predate newer record
    fields (flushes, sem_wait_ms, stats_profile) and must still render."""
    return "-" if v is None else v


def util_lines(rec: Dict) -> List[str]:
    """The device-utilization lane of one engine record: busy share of
    the query window plus the idle-gap attribution breakdown
    (obs/timeline.py gap taxonomy)."""
    util = rec.get("device_util_pct")
    if util is None:
        return []
    lines = ["-- device utilization --"]
    bar = "#" * int(round(util / 5.0))
    lines.append(f"  busy {util:6.1f}%  {bar:<20s} "
                 f"busy_ms={_fmt(rec.get('device_busy_ms'))}")
    gaps = rec.get("util_gap_breakdown") or {}
    for cause, pct in sorted(gaps.items(), key=lambda kv: -kv[1]):
        if pct > 0:
            bar = "." * int(round(pct / 5.0))
            lines.append(f"  {cause:<21s}{pct:6.1f}%  {bar}")
    return lines


def obs_lines(rec: Dict) -> List[str]:
    """The observability self-cost line of one engine record: host ms
    the default-on planes billed to THEMSELVES inside this query's
    window, with the per-plane split (obs/overhead.py self-meter).
    Pre-r17 logs carry no ``obs_self`` key and render nothing — the
    same tolerance convention as the other per-plane sections."""
    obs = rec.get("obs_self")
    if not obs:
        return []
    planes = obs.get("planes") or {}
    split = " ".join(f"{k}={_fmt(planes.get(k))}" for k in planes)
    return ["-- observability self-cost (obs tax) --",
            f"  obs_self_ms={_fmt(obs.get('total_ms'))}  {split}"]


def compile_lines(rec: Dict) -> List[str]:
    """The compile story of one engine record: every compile that
    landed in the query's window, slowest first — the same dur_ms the
    tpu_compile_seconds histogram observed."""
    compiles = rec.get("compiles") or []
    if not compiles:
        return []
    lines = ["-- compiles in query window --"]
    lines.append(f"  {'cache':<22s}{'dur_ms':>10s}  {'origin':<11s}"
                 f"{'bucket':>8s}  signature")
    for c in sorted(compiles, key=lambda c: -(c.get("dur_ms") or 0)):
        # AOT dimensions (compile/aot.py); pre-r13 records carry
        # neither key — inline flag maps to origin, bucket renders "-"
        origin = c.get("origin") or (
            "inline" if c.get("inline") else "warm")
        bucket = c.get("bucket")
        lines.append(f"  {str(c.get('cache')):<22s}"
                     f"{_fmt(c.get('dur_ms')):>10}  "
                     f"{str(origin):<11s}"
                     f"{('-' if bucket is None else str(bucket)):>8s}  "
                     f"{str(c.get('signature', ''))[:60]}")
    return lines


def shuffle_lines(rec: Dict) -> List[str]:
    """The shuffle-transport (netplane) section of one engine record:
    the four-phase host-drop split (summing to the exchange wall by
    construction), the per-edge heat table and the per-peer fetch
    latency aggregate — obs/netplane.py's event-log surface."""
    net = rec.get("shuffle_netplane")
    if not net:
        return ["  (no shuffle netplane recorded — older log or "
                "spark.rapids.tpu.obs.net.enabled=false)"]
    lines = ["-- shuffle transport (netplane) --"]
    lines.append(
        f"  host_drop_tax_ms={_fmt(net.get('host_drop_tax_ms'))} "
        f"exchange_wall_ms={_fmt(net.get('exchange_wall_ms'))} "
        f"wire_MBps={_fmt(net.get('wire_MBps'))} "
        f"edge_skew={_fmt(net.get('edge_skew'))} "
        f"edges={_fmt(net.get('edges'))} "
        f"blocks={_fmt(net.get('blocks'))}")
    phases = net.get("phases_ms") or {}
    wall = float(net.get("exchange_wall_ms") or 0.0)
    for phase in ("serialize", "dwell", "wire", "deserialize"):
        ms = phases.get(phase)
        if ms is None:
            continue
        share = (ms / wall * 100.0) if wall else 0.0
        bar = "#" * int(round(share / 5.0))
        lines.append(f"  {phase:<13s}{share:6.1f}%{ms:>12.3f}ms  {bar}")
    comp = net.get("compression") or {}
    if comp.get("raw_bytes"):
        codecs = ",".join(comp.get("codecs") or []) or "-"
        lines.append(
            f"  compression [{codecs}]: "
            f"raw={_fmt(comp.get('raw_bytes'))} "
            f"compressed={_fmt(comp.get('compressed_bytes'))} "
            f"ratio={_fmt(comp.get('ratio'))}x")
    edges = net.get("top_edges") or []
    if edges:
        lines.append("  top edges (map -> reduce):")
        lines.append(f"    {'shuffle':>7s}{'map':>6s}{'reduce':>8s}"
                     f"{'rows':>10s}{'bytes':>12s}{'batches':>9s}")
        for e in edges:
            lines.append(f"    {_fmt(e.get('shuffle_id')):>7}"
                         f"{_fmt(e.get('map_id')):>6}"
                         f"{_fmt(e.get('reduce_id')):>8}"
                         f"{_fmt(e.get('rows')):>10}"
                         f"{_fmt(e.get('bytes')):>12}"
                         f"{_fmt(e.get('batches')):>9}")
    peers = net.get("fetch_peers") or {}
    if peers:
        lines.append("  per-peer fetch latency:")
        lines.append(f"    {'peer':<18s}{'count':>6s}{'avg_ms':>10s}"
                     f"{'max_ms':>10s}{'bytes':>12s}")
        for peer in sorted(peers):
            p = peers[peer]
            lines.append(f"    {peer:<18s}{_fmt(p.get('count')):>6}"
                         f"{_fmt(p.get('avg_ms')):>10}"
                         f"{_fmt(p.get('max_ms')):>10}"
                         f"{_fmt(p.get('bytes')):>12}")
    return lines


def memory_lines(rec: Dict) -> List[str]:
    """The HBM memory (memplane) section of one engine record: peak
    device bytes with the owner set at peak time, the per-direction
    spill totals, the priced ledger tail and any retention leaks —
    obs/memplane.py's event-log surface."""
    mem = rec.get("memplane")
    if not mem:
        return ["  (no memplane recorded — older log or "
                "spark.rapids.tpu.obs.mem.enabled=false)"]
    lines = ["-- HBM memory (memplane) --"]
    lines.append(
        f"  peak_device_bytes={_fmt(mem.get('peak_device_bytes'))} "
        f"spill_ms={_fmt(mem.get('spill_ms'))} "
        f"unspill_ms={_fmt(mem.get('unspill_ms'))} "
        f"unspill_count={_fmt(mem.get('unspill_count'))} "
        f"spill_skipped={_fmt(mem.get('spill_skipped'))} "
        f"leaked_entries={_fmt(mem.get('leaked_entries'))}")
    peak_sites = mem.get("peak_by_site") or {}
    peak = float(mem.get("peak_device_bytes") or 0)
    if peak_sites:
        lines.append("  live bytes at peak, by site:")
        for site, nbytes in sorted(peak_sites.items(),
                                   key=lambda kv: -kv[1]):
            share = (nbytes / peak * 100.0) if peak else 0.0
            bar = "#" * int(round(share / 5.0))
            lines.append(f"    {site:<14s}{share:6.1f}%"
                         f"{nbytes:>14,d}  {bar}")
    owners = mem.get("peak_owners") or []
    if owners:
        lines.append("  owners at peak:")
        for o in owners[:8]:
            lines.append(f"    {str(o.get('query_id')):<22s}"
                         f"{str(o.get('site')):<12s}"
                         f"{str(o.get('op'))[:24]:<26s}"
                         f"{_fmt(o.get('bytes')):>14}")
    spill = mem.get("spill") or {}
    if any((spill.get(d) or {}).get("count") for d in spill):
        lines.append("  tier moves:")
        lines.append(f"    {'direction':<16s}{'count':>6s}"
                     f"{'bytes':>14s}{'ms':>10s}")
        for d in ("device_to_host", "host_to_disk", "unspill"):
            row = spill.get(d) or {}
            lines.append(f"    {d:<16s}{_fmt(row.get('count')):>6}"
                         f"{_fmt(row.get('bytes')):>14}"
                         f"{_fmt(row.get('ms')):>10}")
    ledger = mem.get("ledger") or []
    if ledger:
        shown = len(ledger)
        total = mem.get("ledger_records") or shown
        lines.append(f"  spill ledger (last {shown} of {total}):")
        lines.append(f"    {'direction':<16s}{'site':<12s}"
                     f"{'op':<22s}{'bytes':>12s}{'reason':<10s}"
                     f"{'rank':>5s}{'ms':>9s}")
        for r in ledger:
            lines.append(f"    {str(r.get('direction')):<16s}"
                         f"{str(r.get('site')):<12s}"
                         f"{str(r.get('op'))[:20]:<22s}"
                         f"{_fmt(r.get('nbytes')):>12}"
                         f" {str(r.get('reason')):<9s}"
                         f"{_fmt(r.get('rank')):>5}"
                         f"{_fmt(r.get('ms')):>9}")
    leaks = mem.get("leaks") or []
    if leaks:
        lines.append("  !! leaked registrations at query end:")
        for lk in leaks[:8]:
            lines.append(f"    buffer={lk.get('buffer_id')} "
                         f"tier={lk.get('tier')} "
                         f"bytes={lk.get('nbytes')} "
                         f"site={lk.get('site')} op={lk.get('op')} "
                         f"refcount={lk.get('refcount')} "
                         f"registered_at={lk.get('tag')}")
    return lines


def doctor_lines(rec: Dict) -> List[str]:
    """The cross-plane doctor section of one engine record: the
    primary-bottleneck verdict, the sum-to-100 contribution shares and
    the ranked Amdahl-headroom candidates mapped onto ROADMAP items —
    obs/doctor.py's event-log surface.  Placeholder-tolerant on
    pre-r12 logs (same convention as ``--memory`` on pre-r11 logs)."""
    doc = rec.get("doctor")
    if not doc:
        return ["  (no doctor verdict recorded — older log or "
                "spark.rapids.tpu.obs.doctor.enabled=false)"]
    lines = ["-- query doctor (cross-plane verdict) --"]
    lines.append(
        f"  primary bottleneck: {doc.get('primary_cause')} at "
        f"{_fmt(doc.get('primary_share_pct'))}% of the query window")
    shares = doc.get("shares") or {}
    if shares:
        lines.append("  contribution shares (sum to 100):")
        for cause, pct in sorted(shares.items(), key=lambda kv: -kv[1]):
            if not pct:
                continue
            bar = "#" * int(round(float(pct) / 5.0))
            lines.append(f"    {cause:<20s}{float(pct):6.1f}%  {bar}")
    cands = doc.get("headroom") or []
    if cands:
        lines.append("  modeled headroom per candidate fix "
                     "(Amdahl bound):")
        lines.append(f"    {'cause':<20s}{'share':>7s}{'bound':>8s}"
                     f"  {'roadmap':<9s}fix")
        for c in cands:
            item = c.get("roadmap_item")
            lines.append(
                f"    {str(c.get('cause')):<20s}"
                f"{_fmt(c.get('share_pct')):>6}%"
                f"  <={_fmt(c.get('bound_x'))}x"
                f"  {('item ' + str(item)) if item else '-':<9s}"
                f"{str(c.get('fix'))[:46]}")
            if c.get("evidence"):
                lines.append(f"      evidence: {c['evidence']}")
    flushes, pred = doc.get("flushes"), doc.get("predicted_flushes")
    if flushes is not None:
        line = f"  flushes={flushes} predicted={_fmt(pred)}"
        if pred is not None and pred != flushes:
            line += " [!! PV-FLUSH mismatch]"
        lines.append(line)
    if doc.get("stats_digest"):
        lines.append(f"  stats_digest={doc['stats_digest'][:16]}…")
    return lines


def cost_lines(rec: Dict) -> List[str]:
    """The device-compute cost (costplane) section of one engine
    record: per-program roofline rows (achieved rates, arithmetic
    intensity, verdict), padding-waste bars against the padded bucket
    capacities, and the doctor's device_compute sub-verdict split —
    obs/costplane.py's event-log surface.  Placeholder-tolerant on
    pre-r14 logs (same convention as ``--memory``/``--doctor``)."""
    cost = rec.get("costplane")
    if not cost:
        return ["  (no costplane recorded — older log or "
                "spark.rapids.tpu.obs.cost.enabled=false)"]
    lines = ["-- device-compute cost (roofline) --"]
    lines.append(
        f"  verdict={cost.get('verdict')} "
        f"achieved={_fmt(cost.get('achieved_gflops'))}GF/s,"
        f"{_fmt(cost.get('achieved_gbps'))}GB/s "
        f"padding_waste={_fmt(cost.get('padding_waste_pct'))}% "
        f"(peaks {_fmt(cost.get('peak_tflops'))}TF/s,"
        f"{_fmt(cost.get('peak_gbps'))}GB/s "
        f"ridge={_fmt(cost.get('ridge_intensity'))} flop/B)")
    progs = cost.get("programs") or []
    if progs:
        lines.append(f"  {'program':<26s}{'bucket':>8s}{'disp':>6s}"
                     f"{'intensity':>10s}{'GF/s':>9s}{'GB/s':>9s}"
                     f"{'share':>9s}  {'verdict':<14s}src")
        for p in progs:
            lines.append(
                f"  {str(p.get('program')):<26s}"
                f"{_fmt(p.get('bucket')):>8}"
                f"{_fmt(p.get('dispatches')):>6}"
                f"{_fmt(p.get('intensity')):>10}"
                f"{_fmt(p.get('achieved_gflops')):>9}"
                f"{_fmt(p.get('achieved_gbps')):>9}"
                f"{_fmt(p.get('est_share_pct')):>8}%"
                f"  {str(p.get('verdict') or '-'):<14s}"
                f"{str(p.get('source') or '-')}")
        wasted = [p for p in progs
                  if p.get("padding_waste_pct") is not None]
        if wasted:
            lines.append("  padding waste (padded rows beyond the "
                         "effective batch), by program:")
            for p in sorted(wasted,
                            key=lambda q: -q["padding_waste_pct"]):
                pct = float(p["padding_waste_pct"])
                bar = "#" * int(round(pct / 5.0))
                lines.append(f"    {str(p.get('program')):<26s}"
                             f"{pct:6.1f}%  {bar}")
    uncosted = cost.get("uncosted_dispatches")
    if uncosted:
        lines.append(f"  uncosted_dispatches={uncosted} "
                     "(no static cost captured for these buckets)")
    doc = rec.get("doctor") or {}
    sub = doc.get("device_compute_breakdown")
    if sub:
        d = (doc.get("shares") or {}).get("device_compute")
        lines.append(
            f"  doctor device_compute={_fmt(d)}% splits: "
            f"compute_bound={_fmt(sub.get('compute_bound'))}% "
            f"memory_bound={_fmt(sub.get('memory_bound'))}% "
            f"padding_waste={_fmt(sub.get('padding_waste'))}%")
    return lines


def stats_lines(prof: Dict) -> List[str]:
    """Text sections for one record's StatsProfile (obs/stats.py)."""
    lines: List[str] = []
    stages = prof.get("superstages") or []
    if stages:
        lines.append("-- superstage device-time attribution --")
        for s in stages:
            lines.append(f"  {s.get('node')} (node "
                         f"{s.get('node_index')}): "
                         f"device_ms={_fmt(s.get('device_ms'))} "
                         f"flushes={_fmt(s.get('flushes'))}")
            shares = s.get("member_share") or {}
            dms = s.get("member_device_ms") or {}
            for k, share in shares.items():
                lines.append(f"    {k:<38s}{share * 100:6.1f}%"
                             f"{dms.get(k, 0.0):>11.2f}ms")
    exchanges = prof.get("exchanges") or []
    if exchanges:
        lines.append("-- exchange data statistics --")
        lines.append(f"  {'node':<26s}{'kind':<11s}{'rows':>10s}"
                     f"{'est_bytes':>12s}{'nulls':>8s}"
                     f"{'distinct':>10s}{'skew':>9s}")
        for e in exchanges:
            skew = e.get("skew") or {}
            ratio = skew.get("ratio")
            skew_cell = "-" if ratio is None else (
                f"{ratio}{'!' if skew.get('skewed') else ''}")
            lines.append(f"  {str(e.get('node')):<26s}"
                         f"{str(e.get('kind')):<11s}"
                         f"{_fmt(e.get('rows')):>10}"
                         f"{_fmt(e.get('est_bytes')):>12}"
                         f"{_fmt(e.get('null_count')):>8}"
                         f"{_fmt(e.get('distinct_est')):>10}"
                         f"{skew_cell:>9s}")
            if skew.get("skewed"):
                rows = [p.get("rows") for p in e.get("partitions", [])]
                lines.append(f"    partition rows: {rows}")
    disp = prof.get("dispatches") or {}
    if disp:
        lines.append("-- dispatch durations --")
        for site, d in disp.items():
            lines.append(f"  {site:<12s} count={d.get('count', 0):<6d} "
                         f"p50={_fmt(d.get('p50_ms'))}ms "
                         f"p95={_fmt(d.get('p95_ms'))}ms")
    return lines


def render_query_report(query_id, story: Dict,
                        trace_events: Optional[List[Dict]] = None,
                        show_stats: bool = False,
                        show_shuffle: bool = False,
                        show_memory: bool = False,
                        show_doctor: bool = False,
                        show_cost: bool = False) -> str:
    """One query's full text report."""
    lines = [f"=== query {query_id} " + "=" * 40]
    engine = story.get("engine", [])
    service = story.get("service", [])
    if service:
        lines.append("-- service story --")
        lines.extend("  " + s for s in _service_story(service))
    for i, rec in enumerate(engine):
        tag = f" (attempt record {i + 1}/{len(engine)})" \
            if len(engine) > 1 else ""
        head = (f"-- plan + time shares{tag}: "
                f"wall_ms={_fmt(rec.get('wall_ms'))} "
                f"sem_wait_ms={_fmt(rec.get('sem_wait_ms'))} "
                f"spill_bytes={_fmt(rec.get('spill_bytes'))}")
        if rec.get("flushes") is not None:
            # device round trips this query — THE cost model on
            # remote-dispatch backends (columnar/pending.py)
            head += f" flushes={rec.get('flushes')}"
        pred = rec.get("predicted_flushes")
        if pred is not None:
            head += f" predicted_flushes={pred}"
            if rec.get("flushes") is not None and \
                    pred != rec.get("flushes"):
                # the static PV-FLUSH model disagreed with the runtime
                # counter — either the plan dispatched an unmodeled
                # barrier or the predictor regressed; both are bugs
                head += " [!! PV-FLUSH mismatch]"
        if rec.get("inline_compile_ms") is not None:
            head += (f" inline_compile_ms="
                     f"{rec.get('inline_compile_ms')}")
        if rec.get("device_util_pct") is not None:
            head += f" device_util_pct={rec.get('device_util_pct')}"
        if rec.get("plan_cache") is not None:
            # plan-cache disposition (cache/plan_cache.py): hit =
            # verify + PV-FLUSH replayed from the shape's stored
            # certificates; warm planner_path_ms ≪ cold is the win
            head += (f" plan_cache={rec.get('plan_cache')} "
                     f"planner_path_ms="
                     f"{_fmt(rec.get('planner_path_ms'))}")
        lines.append(head + " --")
        lines.extend(_format_plan(plan_time_shares(rec)))
        if rec.get("fallbacks"):
            lines.append("  CPU fallbacks:")
            lines.extend(f"    {f}" for f in rec["fallbacks"])
        lines.extend(util_lines(rec))
        lines.extend(obs_lines(rec))
        lines.extend(compile_lines(rec))
        if show_shuffle:
            lines.extend(shuffle_lines(rec))
        if show_memory:
            lines.extend(memory_lines(rec))
        if show_doctor:
            lines.extend(doctor_lines(rec))
        if show_cost:
            lines.extend(cost_lines(rec))
        if show_stats:
            prof = rec.get("stats_profile")
            if prof:
                lines.extend(stats_lines(prof))
            else:
                lines.append("  (no StatsProfile recorded — run with "
                             "spark.rapids.tpu.obs.stats.enabled=true)")
    if trace_events:
        spans = critical_spans(trace_events, query_id)
        if spans:
            lines.append("-- critical-path spans --")
            lines.append(f"  {'name':<28s}{'cat':<10s}"
                         f"{'count':>6s}{'total_ms':>12s}{'max_ms':>10s}")
            for s in spans:
                lines.append(f"  {s['name']:<28s}{s['cat']:<10s}"
                             f"{s['count']:>6d}{s['total_ms']:>12.3f}"
                             f"{s['max_ms']:>10.3f}")
    return "\n".join(lines)


def slo_header(stories: Dict) -> List[str]:
    """Per-tenant latency header over every terminal service record in
    the log: nearest-rank p50/p95/p99 of queue_wait + execute (the same
    end-to-end definition obs/slo.py uses)."""
    by_tenant: Dict[str, List[float]] = {}
    for story in stories.values():
        for rec in story.get("service", []):
            if rec.get("event") not in ("completed", "failed",
                                        "cancelled"):
                continue
            total = (float(rec.get("queue_wait_ms") or 0.0) +
                     float(rec.get("execute_ms") or 0.0))
            by_tenant.setdefault(
                str(rec.get("tenant") or "default"), []).append(total)
    if not by_tenant:
        return []

    def pctl(xs, q):
        i = min(len(xs) - 1, int(q * (len(xs) - 1) + 0.5))
        return xs[i]

    lines = ["=== per-tenant latency (SLO plane) " + "=" * 27]
    lines.append(f"  {'tenant':<16s}{'queries':>8s}{'p50_ms':>10s}"
                 f"{'p95_ms':>10s}{'p99_ms':>10s}")
    for tenant in sorted(by_tenant):
        xs = sorted(by_tenant[tenant])
        lines.append(f"  {tenant:<16s}{len(xs):>8d}"
                     f"{pctl(xs, 0.5):>10.1f}{pctl(xs, 0.95):>10.1f}"
                     f"{pctl(xs, 0.99):>10.1f}")
    return lines


def render_report(stories: Dict,
                  trace_events: Optional[List[Dict]] = None,
                  query_id=None, show_stats: bool = False,
                  show_shuffle: bool = False,
                  show_memory: bool = False,
                  show_doctor: bool = False,
                  show_cost: bool = False) -> str:
    ids = [query_id] if query_id is not None else sorted(
        stories, key=lambda q: str(q))
    parts = []
    if query_id is None:
        header = slo_header(stories)
        if header:
            parts.append("\n".join(header))
    for qid in ids:
        if qid not in stories:
            raise KeyError(f"query {qid!r} not in event log")
        parts.append(render_query_report(qid, stories[qid], trace_events,
                                         show_stats=show_stats,
                                         show_shuffle=show_shuffle,
                                         show_memory=show_memory,
                                         show_doctor=show_doctor,
                                         show_cost=show_cost))
    return "\n\n".join(parts)


def render_html(stories: Dict,
                trace_events: Optional[List[Dict]] = None,
                query_id=None, show_stats: bool = False,
                show_shuffle: bool = False,
                show_memory: bool = False,
                show_doctor: bool = False,
                show_cost: bool = False) -> str:
    """Self-contained single-file HTML wrapping the text report
    per-query (monospace <pre> sections with a query index)."""
    ids = [query_id] if query_id is not None else sorted(
        stories, key=lambda q: str(q))
    body = ["<h1>spark_rapids_tpu query report</h1>",
            "<ul>" + "".join(
                f'<li><a href="#q{_html.escape(str(q))}">'
                f"{_html.escape(str(q))}</a></li>" for q in ids) + "</ul>"]
    for qid in ids:
        txt = render_query_report(qid, stories[qid], trace_events,
                                  show_stats=show_stats,
                                  show_shuffle=show_shuffle,
                                  show_memory=show_memory,
                                  show_doctor=show_doctor,
                                  show_cost=show_cost)
        body.append(f'<h2 id="q{_html.escape(str(qid))}">'
                    f"query {_html.escape(str(qid))}</h2>")
        body.append(f"<pre>{_html.escape(txt)}</pre>")
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>query report</title><style>"
            "body{font-family:sans-serif;margin:2em}"
            "pre{background:#f6f8fa;padding:1em;overflow-x:auto}"
            "</style></head><body>" + "\n".join(body) + "</body></html>")


def render_soak_report(report: Dict) -> str:
    """The ``--soak`` view: one soak run's QPS/p99 timeline with the
    injected fault windows annotated in-line, the per-tenant burn
    table, the steady-state verdict and the per-fault impact/recovery
    correlation — rendered from a ``SoakReport`` JSON artifact
    (service/soak.py, written by ``tools/soak.py --out``)."""
    lines = ["=== soak run " + "=" * 49]
    cfg = report.get("config") or {}
    lines.append(
        f"  duration_s={_fmt(cfg.get('duration_s'))} "
        f"qps_target={_fmt(cfg.get('qps'))} "
        f"rows={_fmt(cfg.get('rows'))} "
        f"tenants={','.join(cfg.get('tenants') or [])} "
        f"seed={_fmt(cfg.get('seed'))} "
        f"faults={len(cfg.get('faults') or [])}")
    tot = report.get("totals") or {}
    lines.append(
        f"  submitted={_fmt(tot.get('submitted'))} "
        f"completed={_fmt(tot.get('completed'))} "
        f"failed={_fmt(tot.get('failed'))} "
        f"shed={_fmt(tot.get('shed'))} "
        f"sha_mismatch={_fmt(tot.get('sha_mismatch'))} "
        f"qps_actual={_fmt(tot.get('qps_actual'))} "
        f"sustained_rows_s={_fmt(tot.get('sustained_rows_s'))}")
    lat = report.get("latency") or {}
    lines.append(
        f"  p50_ms={_fmt(lat.get('p50_ms'))} "
        f"p95_ms={_fmt(lat.get('p95_ms'))} "
        f"p99_ms={_fmt(lat.get('p99_ms'))} "
        f"shed_rate_pct={_fmt(report.get('shed_rate_pct'))} "
        f"leak_drift_bytes={_fmt(report.get('leak_drift_bytes'))}")
    steady = report.get("steady") or {}
    lines.append(
        f"  steady_state={'yes' if steady.get('steady') else 'no'} "
        f"ewma_ms={_fmt(steady.get('ewma_ms'))} "
        f"slope_pct={_fmt(steady.get('slope_pct'))} "
        f"converged={_fmt(steady.get('converge_count'))}x "
        f"losses={_fmt(steady.get('losses'))}")
    anomaly = report.get("anomaly") or {}
    lines.append(
        f"  anomaly breaches={_fmt(anomaly.get('breach_total'))} "
        f"false_positives={_fmt(anomaly.get('fp_total'))} "
        f"fp_rate_pct={_fmt(anomaly.get('fp_rate_pct'))}")

    tenants = (report.get("burn") or {}).get("tenants") or {}
    if tenants:
        lines.append("-- per-tenant burn rate --")
        lines.append(f"  {'tenant':<16s}{'queries':>8s}{'breaches':>9s}"
                     f"{'fast':>8s}{'slow':>8s}")
        for name in sorted(tenants):
            t = tenants[name]
            fast = float(t.get("fast") or 0.0)
            mark = "  [!! budget]" if fast >= 1.0 else ""
            lines.append(f"  {name:<16s}{_fmt(t.get('count')):>8}"
                         f"{_fmt(t.get('breaches')):>9}"
                         f"{fast:>8.2f}"
                         f"{float(t.get('slow') or 0.0):>8.2f}{mark}")

    timeline = report.get("timeline") or []
    if timeline:
        lines.append("-- timeline (per-bucket QPS / p99, faults "
                     "annotated) --")
        lines.append(f"  {'t_s':>6s}{'n':>5s}{'qps':>8s}"
                     f"{'p50_ms':>9s}{'p99_ms':>9s}{'shed':>6s}"
                     f"{'fail':>6s}  {'p99':<22s}faults")
        peak_p99 = max((float(b.get("p99_ms") or 0.0)
                        for b in timeline), default=0.0) or 1.0
        for b in timeline:
            p99 = float(b.get("p99_ms") or 0.0)
            bar = "#" * int(round(p99 / peak_p99 * 20))
            faults = ",".join(b.get("faults") or [])
            lines.append(
                f"  {float(b.get('t_s') or 0.0):>6.1f}"
                f"{_fmt(b.get('n')):>5}"
                f"{float(b.get('qps') or 0.0):>8.1f}"
                f"{_fmt(b.get('p50_ms')):>9}"
                f"{_fmt(b.get('p99_ms')):>9}"
                f"{_fmt(b.get('shed')):>6}"
                f"{_fmt(b.get('failed')):>6}  {bar:<22s}"
                + (f"[{faults}]" if faults else ""))

    windows = report.get("faults") or []
    lines.append("-- fault windows --")
    if windows:
        lines.append(f"  {'id':<32s}{'kind':<22s}{'at_s':>7s}"
                     f"{'end_s':>7s}{'p99_before':>11s}"
                     f"{'p99_during':>11s}{'p99_after':>10s}"
                     f"{'recovered':>10s}{'rec_s':>7s}")
        for w in windows:
            lines.append(
                f"  {str(w.get('id')):<32s}"
                f"{str(w.get('kind')):<22s}"
                f"{_fmt(w.get('at_s')):>7}"
                f"{_fmt(w.get('end_s')):>7}"
                f"{_fmt(w.get('p99_before_ms')):>11}"
                f"{_fmt(w.get('p99_during_ms')):>11}"
                f"{_fmt(w.get('p99_after_ms')):>10}"
                f"{'yes' if w.get('recovered') else 'NO':>10}"
                f"{_fmt(w.get('recovery_s')):>7}")
            if w.get("diag_bundle"):
                lines.append(f"    bundle={w['diag_bundle']}")
        lines.append(
            f"  fault_recovery_ratio="
            f"{_fmt(report.get('fault_recovery_ratio'))}")
    else:
        lines.append("  (no faults injected)")
    return "\n".join(lines)


def main(argv=None):
    argv = list(argv if argv is not None else sys.argv[1:])
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: report <event_log.jsonl> [--query QID] "
              "[--trace trace.json] [--html out.html] [--stats] "
              "[--shuffle] [--memory] [--doctor] [--cost] [--all]\n"
              "       report <soak_report.json> --soak",
              file=sys.stderr)
        return 1

    def _opt(flag):
        if flag in argv:
            i = argv.index(flag)
            v = argv[i + 1]
            del argv[i:i + 2]
            return v
        return None

    def _flag(flag):
        if flag in argv:
            argv.remove(flag)
            return True
        return False

    if _flag("--soak"):
        # the positional is a SoakReport JSON artifact, not an event
        # log — one self-contained view, no joins needed
        with open(argv[0]) as f:
            print(render_soak_report(json.load(f)))
        return 0

    qid = _opt("--query")
    trace_path = _opt("--trace")
    html_out = _opt("--html")
    # --all turns on every per-plane section in one go (each section
    # stays placeholder-tolerant, so --all is safe on any-age log)
    show_all = _flag("--all")
    show_stats = _flag("--stats") or show_all
    show_shuffle = _flag("--shuffle") or show_all
    show_memory = _flag("--memory") or show_all
    show_doctor = _flag("--doctor") or show_all
    show_cost = _flag("--cost") or show_all
    log_path = argv[0]
    stories = load_query_stories(log_path)
    trace_events = load_trace(trace_path) if trace_path else None
    # query ids are ints for session-local logs, strings for service ones
    if qid is not None and qid not in stories:
        try:
            if int(qid) in stories:
                qid = int(qid)
        except ValueError:
            pass
    if html_out:
        with open(html_out, "w") as f:
            f.write(render_html(stories, trace_events, qid,
                                show_stats=show_stats,
                                show_shuffle=show_shuffle,
                                show_memory=show_memory,
                                show_doctor=show_doctor,
                                show_cost=show_cost))
        print(f"wrote {html_out}")
    else:
        print(render_report(stories, trace_events, qid,
                            show_stats=show_stats,
                            show_shuffle=show_shuffle,
                            show_memory=show_memory,
                            show_doctor=show_doctor,
                            show_cost=show_cost))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
