"""API-validation audit: committed docs vs the LIVE registry.

Reference role: api_validation/.../ApiValidation.scala — a build-time
audit that the plugin's claimed API surface matches what actually
exists.  Here the claims are docs/supported_ops.md and docs/configs.md
(both generated); the audit regenerates them from the live registries
(plan/overrides expression rules, the cast matrix, config entries) and
reports any drift line by line, so stale docs fail CI instead of
misleading users.

Usage: python -m spark_rapids_tpu.tools.api_validation [docs_dir]
Exit status 1 on drift.
"""
from __future__ import annotations

import difflib
import os
import sys
from typing import List

from ..config import generate_docs
from .docgen import supported_ops_doc


def audit(docs_dir: str) -> List[str]:
    """Drift lines between committed docs and the live registry."""
    problems: List[str] = []
    checks = [
        ("supported_ops.md", supported_ops_doc()),
        ("configs.md", generate_docs()),
    ]
    for fname, live in checks:
        path = os.path.join(docs_dir, fname)
        if not os.path.exists(path):
            problems.append(f"{fname}: MISSING (never generated?)")
            continue
        with open(path) as f:
            committed = f.read()
        if committed == live:
            continue
        diff = list(difflib.unified_diff(
            committed.splitlines(), live.splitlines(),
            fromfile=f"docs/{fname} (committed)",
            tofile=f"{fname} (live registry)", lineterm="", n=0))
        # cap the report; the point is that drift EXISTS and where
        problems.append(f"{fname}: drift ({len(diff) - 2} diff lines)")
        problems.extend(diff[2:40])
    return problems


def main(argv=None):
    # host-side CLI: never touch the accelerator backend.  Done HERE,
    # not at import (tests import audit(); pinning the platform as an
    # import side effect would silently move a whole TPU run to CPU).
    import jax
    jax.config.update("jax_platforms", "cpu")
    argv = argv or sys.argv[1:]
    docs_dir = argv[0] if argv else os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "docs")
    problems = audit(docs_dir)
    if problems:
        print("api_validation: docs drift from the live registry "
              "(regenerate with python -m spark_rapids_tpu.tools.docgen)")
        for p in problems:
            print("  " + p)
        return 1
    print("api_validation: docs match the live registry")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
