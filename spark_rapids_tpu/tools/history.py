"""Offline fleet-history explorer — reads the JSONL segments the
query-history store (obs/history.py) persisted under
``spark.rapids.tpu.obs.history.dir`` and answers the longitudinal
questions without a live service:

  summary  — per-fingerprint fleet table (runs, outcome mix, latency
             percentiles, doctor causes, tenants), worst-latency first
  trend    — one fingerprint's key over time, bucketed into equal-count
             windows with a sparkline-style bar per bucket
  compare  — before/after split of the whole history (by timestamp or
             by fraction) with per-key deltas — the "did the rollout
             regress fingerprint X" question
  soak     — soak-run trend over the whole history: per-window p99,
             throughput and outcome mix across equal-count time
             windows, plus an optional before/after fault compare
             (``--fault-ts`` reuses the compare split at a fault
             window's timestamp)

Usage:
  python -m spark_rapids_tpu.tools.history summary <history_dir> [--top N]
  python -m spark_rapids_tpu.tools.history trend <history_dir>
      --fingerprint FP [--key exec_ms] [--buckets N]
  python -m spark_rapids_tpu.tools.history compare <history_dir>
      [--fingerprint FP] [--split-frac F | --split-ts TS]
      [--keys k1,k2,...]
  python -m spark_rapids_tpu.tools.history soak <history_dir>
      [--buckets N] [--fault-ts TS] [--keys k1,k2,...]

Stdlib-only and read-only; timestamps come from the rows themselves
(this tool never consults the wall clock).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

_DEFAULT_COMPARE_KEYS = ("exec_ms", "queue_ms", "host_drop_tax_ms",
                         "spill_ms", "device_util_pct", "flushes")


def load_rows(history_dir: str,
              fingerprint: Optional[str] = None) -> List[Dict]:
    """Every parseable row from every ``history-*.jsonl`` segment,
    oldest segment first, ordered by row timestamp within the load."""
    rows: List[Dict] = []
    pattern = os.path.join(history_dir, "history-*.jsonl")
    for path in sorted(glob.glob(pattern)):
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    if fingerprint and \
                            row.get("fingerprint") != fingerprint:
                        continue
                    rows.append(row)
        except OSError:
            continue
    rows.sort(key=lambda r: float(r.get("ts") or 0.0))
    return rows


def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def _mix(counts: Dict[str, int]) -> str:
    return " ".join(f"{k}:{v}" for k, v in sorted(counts.items())) or "-"


def _vals(rows: List[Dict], key: str) -> List[float]:
    out = []
    for r in rows:
        v = r.get(key)
        if isinstance(v, (int, float)):
            out.append(float(v))
    return out


# ---------------------------------------------------------------------------
# summary
# ---------------------------------------------------------------------------

def summarize(rows: List[Dict]) -> Dict[str, Dict]:
    """Per-fingerprint aggregate over the loaded rows (the offline
    twin of obs/history.fleet_aggregates, but unbounded)."""
    by_fp: Dict[str, List[Dict]] = {}
    for r in rows:
        by_fp.setdefault(str(r.get("fingerprint") or "unknown"),
                         []).append(r)
    out: Dict[str, Dict] = {}
    for fp, rs in by_fp.items():
        execs = sorted(_vals(rs, "exec_ms"))
        outcomes: Dict[str, int] = {}
        tenants: Dict[str, int] = {}
        causes: Dict[str, int] = {}
        for r in rs:
            o = str(r.get("outcome") or "?")
            outcomes[o] = outcomes.get(o, 0) + 1
            t = str(r.get("tenant") or "default")
            tenants[t] = tenants.get(t, 0) + 1
            c = r.get("doctor_cause")
            if c:
                causes[str(c)] = causes.get(str(c), 0) + 1
        out[fp] = {
            "count": len(rs),
            "outcomes": outcomes,
            "exec_p50_ms": round(_pctl(execs, 0.5), 3),
            "exec_p95_ms": round(_pctl(execs, 0.95), 3),
            "tenants": tenants,
            "doctor_causes": causes,
        }
    return out


def _cmd_summary(args) -> int:
    rows = load_rows(args.history_dir)
    if not rows:
        print(f"no history rows under {args.history_dir}")
        return 1
    summ = summarize(rows)
    order = sorted(summ, key=lambda fp: -summ[fp]["exec_p95_ms"])
    print(f"{len(rows)} rows, {len(summ)} fingerprints "
          f"(worst exec p95 first)")
    hdr = (f"{'fingerprint':<18} {'runs':>5} {'p50ms':>9} {'p95ms':>9}"
           f"  {'outcomes':<24} {'doctor causes':<28} tenants")
    print(hdr)
    print("-" * len(hdr))
    for fp in order[:args.top]:
        s = summ[fp]
        print(f"{fp:<18} {s['count']:>5} {s['exec_p50_ms']:>9.2f} "
              f"{s['exec_p95_ms']:>9.2f}  {_mix(s['outcomes']):<24} "
              f"{_mix(s['doctor_causes']):<28} {_mix(s['tenants'])}")
    return 0


# ---------------------------------------------------------------------------
# trend
# ---------------------------------------------------------------------------

def trend(rows: List[Dict], key: str,
          buckets: int = 10) -> List[Dict]:
    """The key's trajectory over the (time-ordered) rows, split into
    up to ``buckets`` equal-count windows."""
    vals = [(float(r.get("ts") or 0.0), float(r[key])) for r in rows
            if isinstance(r.get(key), (int, float))]
    if not vals:
        return []
    n = len(vals)
    buckets = max(1, min(buckets, n))
    size = n / buckets
    out = []
    for b in range(buckets):
        chunk = vals[int(b * size):int((b + 1) * size)] or \
            [vals[min(n - 1, int(b * size))]]
        ys = sorted(v for _, v in chunk)
        out.append({"first_ts": chunk[0][0], "last_ts": chunk[-1][0],
                    "n": len(chunk), "p50": round(_pctl(ys, 0.5), 3),
                    "max": round(ys[-1], 3)})
    return out


def _cmd_trend(args) -> int:
    rows = load_rows(args.history_dir, fingerprint=args.fingerprint)
    if not rows:
        print(f"no rows for fingerprint {args.fingerprint} under "
              f"{args.history_dir}")
        return 1
    series = trend(rows, args.key, buckets=args.buckets)
    if not series:
        print(f"no numeric values for key {args.key!r}")
        return 1
    peak = max(b["p50"] for b in series) or 1.0
    first = series[0]["p50"]
    last = series[-1]["p50"]
    drift = ((last - first) / first * 100.0) if first else 0.0
    print(f"{args.fingerprint} {args.key}: {len(rows)} rows in "
          f"{len(series)} windows, p50 {first} -> {last} "
          f"({drift:+.1f}%)")
    for b in series:
        bar = "#" * max(1, int(round(b["p50"] / peak * 40))) \
            if peak > 0 else ""
        print(f"  n={b['n']:>4} p50={b['p50']:>10.3f} "
              f"max={b['max']:>10.3f} {bar}")
    return 0


# ---------------------------------------------------------------------------
# compare
# ---------------------------------------------------------------------------

def compare_windows(rows: List[Dict], keys=_DEFAULT_COMPARE_KEYS,
                    split_frac: float = 0.5,
                    split_ts: Optional[float] = None) -> Dict:
    """Before/after medians per key; the split is a timestamp or a
    fraction of the (time-ordered) row count."""
    if split_ts is not None:
        before = [r for r in rows
                  if float(r.get("ts") or 0.0) < split_ts]
        after = [r for r in rows
                 if float(r.get("ts") or 0.0) >= split_ts]
    else:
        cut = int(len(rows) * split_frac)
        before, after = rows[:cut], rows[cut:]
    out = {"before_n": len(before), "after_n": len(after), "keys": {}}
    for key in keys:
        b = sorted(_vals(before, key))
        a = sorted(_vals(after, key))
        if not b or not a:
            continue
        bp, ap = _pctl(b, 0.5), _pctl(a, 0.5)
        out["keys"][key] = {
            "before_p50": round(bp, 3), "after_p50": round(ap, 3),
            "delta_pct": round((ap - bp) / bp * 100.0, 2) if bp
            else 0.0,
        }
    return out


def _cmd_compare(args) -> int:
    rows = load_rows(args.history_dir, fingerprint=args.fingerprint)
    if len(rows) < 2:
        print("not enough rows to compare")
        return 1
    keys = tuple(k.strip() for k in args.keys.split(",") if k.strip())
    res = compare_windows(rows, keys=keys or _DEFAULT_COMPARE_KEYS,
                          split_frac=args.split_frac,
                          split_ts=args.split_ts)
    scope = args.fingerprint or "all fingerprints"
    print(f"{scope}: before n={res['before_n']} / "
          f"after n={res['after_n']}")
    for key, d in res["keys"].items():
        print(f"  {key:<18} p50 {d['before_p50']:>10.3f} -> "
              f"{d['after_p50']:>10.3f}  ({d['delta_pct']:+.2f}%)")
    return 0


# ---------------------------------------------------------------------------
# soak
# ---------------------------------------------------------------------------

def soak_windows(rows: List[Dict], buckets: int = 10) -> List[Dict]:
    """Soak-grade longitudinal windows over ALL fingerprints: each
    equal-count window's end-to-end p50/p99 (queue + exec, the SLO
    plane's definition), its throughput from the rows' own timestamp
    span, and its outcome mix — the offline twin of the live burn
    plane's view."""
    if not rows:
        return []
    n = len(rows)
    buckets = max(1, min(buckets, n))
    size = n / buckets
    out = []
    for b in range(buckets):
        chunk = rows[int(b * size):int((b + 1) * size)] or \
            [rows[min(n - 1, int(b * size))]]
        totals = sorted(
            float(r.get("queue_ms") or 0.0) + float(r.get("exec_ms")
                                                    or 0.0)
            for r in chunk)
        outcomes: Dict[str, int] = {}
        for r in chunk:
            o = str(r.get("outcome") or "?")
            outcomes[o] = outcomes.get(o, 0) + 1
        first = float(chunk[0].get("ts") or 0.0)
        last = float(chunk[-1].get("ts") or 0.0)
        span = max(last - first, 1e-9)
        out.append({
            "first_ts": first, "last_ts": last, "n": len(chunk),
            "qps": round(len(chunk) / span, 2) if len(chunk) > 1
            else 0.0,
            "p50_ms": round(_pctl(totals, 0.5), 3),
            "p99_ms": round(_pctl(totals, 0.99), 3),
            "outcomes": outcomes,
        })
    return out


def _cmd_soak(args) -> int:
    rows = load_rows(args.history_dir)
    if not rows:
        print(f"no history rows under {args.history_dir}")
        return 1
    series = soak_windows(rows, buckets=args.buckets)
    t0 = float(rows[0].get("ts") or 0.0)
    peak = max(b["p99_ms"] for b in series) or 1.0
    print(f"{len(rows)} rows in {len(series)} windows "
          f"(p99 = queue + exec, end-to-end)")
    print(f"  {'t_s':>8} {'n':>5} {'qps':>8} {'p50ms':>9} {'p99ms':>9}"
          f"  {'p99':<20} outcomes")
    for b in series:
        bar = "#" * max(1, int(round(b["p99_ms"] / peak * 20))) \
            if peak > 0 else ""
        print(f"  {b['first_ts'] - t0:>8.1f} {b['n']:>5} "
              f"{b['qps']:>8.1f} {b['p50_ms']:>9.2f} "
              f"{b['p99_ms']:>9.2f}  {bar:<20} {_mix(b['outcomes'])}")
    if args.fault_ts is not None:
        # before/after the fault window, via the compare split — the
        # "did the service recover to its pre-fault operating point"
        # question
        keys = tuple(k.strip() for k in args.keys.split(",")
                     if k.strip())
        res = compare_windows(rows, keys=keys or _DEFAULT_COMPARE_KEYS,
                              split_ts=args.fault_ts)
        print(f"before/after fault at ts={args.fault_ts}: "
              f"n={res['before_n']}/{res['after_n']}")
        for key, d in res["keys"].items():
            print(f"  {key:<18} p50 {d['before_p50']:>10.3f} -> "
                  f"{d['after_p50']:>10.3f}  ({d['delta_pct']:+.2f}%)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools.history",
        description="Offline explorer for the persistent query-history "
                    "store (obs/history.py JSONL segments)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("summary", help="per-fingerprint fleet table")
    p.add_argument("history_dir")
    p.add_argument("--top", type=int, default=20)
    p.set_defaults(fn=_cmd_summary)

    p = sub.add_parser("trend", help="one fingerprint's key over time")
    p.add_argument("history_dir")
    p.add_argument("--fingerprint", required=True)
    p.add_argument("--key", default="exec_ms")
    p.add_argument("--buckets", type=int, default=10)
    p.set_defaults(fn=_cmd_trend)

    p = sub.add_parser("compare", help="before/after window deltas")
    p.add_argument("history_dir")
    p.add_argument("--fingerprint", default=None)
    p.add_argument("--split-frac", type=float, default=0.5)
    p.add_argument("--split-ts", type=float, default=None)
    p.add_argument("--keys", default=",".join(_DEFAULT_COMPARE_KEYS))
    p.set_defaults(fn=_cmd_compare)

    p = sub.add_parser("soak", help="soak-run p99/throughput/outcome "
                                    "trend + before/after-fault compare")
    p.add_argument("history_dir")
    p.add_argument("--buckets", type=int, default=10)
    p.add_argument("--fault-ts", type=float, default=None)
    p.add_argument("--keys", default=",".join(_DEFAULT_COMPARE_KEYS))
    p.set_defaults(fn=_cmd_soak)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
