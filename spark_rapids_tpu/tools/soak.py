"""Soak-run CLI: drive the sustained mixed-traffic harness
(service/soak.py) from the command line and write the SoakReport.

    python -m spark_rapids_tpu.tools.soak --duration 60 --qps 20 \
        --out soak.json --chaos

    python -m spark_rapids_tpu.tools.soak --queries 200 --qps 50 \
        --fault 2.0:kill_pipeline_worker --fault 4.0:poison_query

The run's artifacts land where the confs point: ``--history-dir``
(fleet rows), ``--event-log`` (fault + terminal events, the input to
``tools/report.py --soak``) and ``--diag-dir`` (per-fault bundles).
Defaults put all three in a fresh temp directory, printed on exit.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

from ..service.faults import FAULT_KINDS, build_schedule
from ..service.soak import SoakConfig, run_soak


def _parse_fault(spec: str):
    try:
        at, kind = spec.split(":", 1)
        at = float(at)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"fault spec {spec!r} is not AT_SECONDS:KIND")
    if kind not in FAULT_KINDS:
        raise argparse.ArgumentTypeError(
            f"unknown fault kind {kind!r}; expected one of "
            f"{', '.join(FAULT_KINDS)}")
    return (at, kind)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="spark_rapids_tpu.tools.soak",
        description="sustained mixed-traffic soak through QueryService")
    p.add_argument("--duration", type=float, default=30.0,
                   help="run length in seconds (ignored with --queries)")
    p.add_argument("--queries", type=int, default=0,
                   help="exact submission count (deterministic runs)")
    p.add_argument("--qps", type=float, default=20.0,
                   help="open-loop target submissions/second")
    p.add_argument("--rows", type=int, default=4096)
    p.add_argument("--partitions", type=int, default=2)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--tenants", default="tenant-a,tenant-b,tenant-c")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--fault", action="append", type=_parse_fault,
                   default=[], metavar="AT:KIND",
                   help="inject KIND at AT seconds (repeatable); kinds: "
                        + ", ".join(FAULT_KINDS))
    p.add_argument("--chaos", action="store_true",
                   help="seeded default schedule: one fault of each "
                        "kind spread over the middle of the run")
    p.add_argument("--slo-target-ms", type=float, default=0.0,
                   help="obs.slo.targetMs for breach/burn accounting")
    p.add_argument("--out", default="",
                   help="write the SoakReport JSON here")
    p.add_argument("--history-dir", default="")
    p.add_argument("--event-log", default="")
    p.add_argument("--diag-dir", default="")
    p.add_argument("--quiet", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS", "cpu"))
    from ..api import TpuSession
    from ..config import TpuConf
    td = tempfile.mkdtemp(prefix="soak_")
    history_dir = args.history_dir or os.path.join(td, "history")
    event_log = args.event_log or os.path.join(td, "events.jsonl")
    diag_dir = args.diag_dir or os.path.join(td, "diag")
    confs = {
        "spark.rapids.tpu.obs.history.dir": history_dir,
        "spark.rapids.tpu.eventLog.path": event_log,
        "spark.rapids.tpu.obs.diagnostics.dir": diag_dir,
    }
    if args.slo_target_ms > 0:
        confs["spark.rapids.tpu.obs.slo.targetMs"] = args.slo_target_ms
    session = TpuSession(TpuConf(confs))
    faults = list(args.fault)
    if args.chaos:
        span = (args.queries / args.qps
                if args.queries else args.duration)
        faults += build_schedule(args.seed, span)
    cfg = SoakConfig(
        duration_s=args.duration, total_queries=args.queries,
        qps=args.qps, rows=args.rows, partitions=args.partitions,
        tenants=[t for t in args.tenants.split(",") if t],
        seed=args.seed, faults=faults, num_workers=args.workers)

    last = {"n": -1}

    def _tick(t):
        if args.quiet or t["completed"] == last["n"]:
            return
        last["n"] = t["completed"]
        sys.stderr.write(
            f"\rt+{t['elapsed_s']:7.1f}s  submitted={t['submitted']} "
            f"completed={t['completed']} shed={t['shed']} "
            f"inflight={t['inflight']} "
            f"faults={t['faults_fired']}"
            + (f" ACTIVE:{','.join(t['active_faults'])}"
               if t["active_faults"] else "") + "   ")
        sys.stderr.flush()
    report = run_soak(session, cfg, on_tick=_tick)
    if not args.quiet:
        sys.stderr.write("\n")
    d = report.to_dict()
    tot, lat = d["totals"], d["latency"]
    print(f"soak: {tot['completed']}/{tot['submitted']} completed, "
          f"{tot['shed']} shed, {tot['failed']} failed, "
          f"{tot['sha_mismatch']} sha mismatches over "
          f"{tot['duration_s']}s ({tot['qps_actual']} qps)")
    print(f"latency: p50={lat['p50_ms']}ms p95={lat['p95_ms']}ms "
          f"p99={lat['p99_ms']}ms; shed_rate={d['shed_rate_pct']}%")
    st = d["steady"]
    print(f"steady-state: {'YES' if st['steady'] else 'no'} "
          f"(converged {st['converge_count']}x, losses {st['losses']}, "
          f"slope {st['slope_pct']}%); "
          f"leak_drift={d['leak_drift_bytes']}B")
    for w in d["faults"]:
        print(f"fault {w['id']}: t+{w['at_s']}s "
              f"p99 {w['p99_before_ms']} -> {w['p99_during_ms']} -> "
              f"{w['p99_after_ms']}ms, "
              f"recovered={'yes' if w['recovered'] else 'NO'}"
              + (f" in {w['recovery_s']}s" if w["recovery_s"] else "")
              + (f", bundle={w['diag_bundle']}"
                 if w["diag_bundle"] else ""))
    print(f"artifacts: history={history_dir} events={event_log} "
          f"diag={diag_dir}")
    if args.out:
        report.write(args.out)
        print(f"report: {args.out}")
    bad = (tot["failed"] or tot["sha_mismatch"]
           or any(not w["recovered"] for w in d["faults"]))
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
