"""Query event logging — the substrate for the qualification/profiling

tools (reference: Spark event logs consumed by tools/, SURVEY.md §2.9) and
for the metrics/observability story (GpuMetric -> SQL UI role).

Every executed query appends one JSON line to the event log:
  {"query_id", "wall_ms", "physical_plan", "fallbacks": [...],
   "node_metrics": {node: {metric: value}}, "conf": {...}}
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

_LOCK = threading.Lock()


class QueryEventLogger:
    def __init__(self, path: Optional[str] = None):
        self.path = path or os.environ.get(
            "SPARK_RAPIDS_TPU_EVENT_LOG", "")
        self._next_id = 0
        self._id_lock = threading.Lock()

    def enabled(self) -> bool:
        return bool(self.path)

    def log_query(self, phys_plan, wall_ms: float, fallbacks: List[str],
                  conf_dict: Dict, metrics_level: str = "MODERATE",
                  query_id=None, extra: Optional[Dict] = None):
        """One engine-execution record.  ``query_id``, when provided by
        the caller (the query service), is STABLE across every event of
        that query — admission, each retry attempt, engine metrics,
        final outcome — so the qualification/profiling tools can join
        attempts of the same query; otherwise a logger-local id is
        assigned."""
        if query_id is None:
            with self._id_lock:
                self._next_id += 1
                query_id = self._next_id
        record = {
            "event": "query",
            "query_id": query_id,
            "ts": time.time(),
            "wall_ms": round(wall_ms, 3),
            "physical_plan": phys_plan.tree_string(),
            "nodes": [n.name for n in phys_plan.collect_nodes()],
            "fallbacks": fallbacks,
            "node_metrics": {
                f"{i}:{n.name}": n.metrics.snapshot(metrics_level)
                for i, n in enumerate(phys_plan.collect_nodes())},
            "conf": {k: v for k, v in conf_dict.items()},
        }
        if extra:
            record.update(extra)
        self._append(record)
        return record

    def log_service_event(self, kind: str, query_id, **fields):
        """One service-lifecycle line: kind is admitted | shed | retry |
        cancelled | completed | failed.  Shares the query's stable
        ``query_id`` with the engine records."""
        record = {"event": kind, "query_id": query_id, "ts": time.time()}
        record.update(fields)
        self._append(record)
        return record

    def _append(self, record: Dict):
        if not self.enabled():
            return
        with _LOCK:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "a") as f:
                f.write(json.dumps(record) + "\n")


def read_event_log(path: str, events: Optional[str] = "query") -> List[Dict]:
    """Parsed event-log records.

    ``events`` filters by record kind: the default "query" returns only
    engine-execution records (what the qualification/profiling tools
    consume — service lifecycle lines would skew their per-query
    statistics); pass a specific kind ("retry", "shed", ...) or None
    for everything."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            kind = rec.get("event", "query")
            if events is not None and kind != events:
                continue
            out.append(rec)
    return out
