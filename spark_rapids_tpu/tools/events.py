"""Query event logging — the substrate for the qualification/profiling

tools (reference: Spark event logs consumed by tools/, SURVEY.md §2.9) and
for the metrics/observability story (GpuMetric -> SQL UI role).

Every executed query appends one JSON line to the event log:
  {"query_id", "wall_ms", "physical_plan", "fallbacks": [...],
   "node_metrics": {node: {metric: value}}, "conf": {...}}
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

_LOCK = threading.Lock()


class QueryEventLogger:
    def __init__(self, path: Optional[str] = None):
        self.path = path or os.environ.get(
            "SPARK_RAPIDS_TPU_EVENT_LOG", "")
        self._next_id = 0

    def enabled(self) -> bool:
        return bool(self.path)

    def log_query(self, phys_plan, wall_ms: float, fallbacks: List[str],
                  conf_dict: Dict, metrics_level: str = "MODERATE"):
        self._next_id += 1
        record = {
            "query_id": self._next_id,
            "ts": time.time(),
            "wall_ms": round(wall_ms, 3),
            "physical_plan": phys_plan.tree_string(),
            "nodes": [n.name for n in phys_plan.collect_nodes()],
            "fallbacks": fallbacks,
            "node_metrics": {
                f"{i}:{n.name}": n.metrics.snapshot(metrics_level)
                for i, n in enumerate(phys_plan.collect_nodes())},
            "conf": {k: v for k, v in conf_dict.items()},
        }
        if not self.enabled():
            return record
        with _LOCK:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "a") as f:
                f.write(json.dumps(record) + "\n")
        return record


def read_event_log(path: str) -> List[Dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
