"""Query event logging — the substrate for the qualification/profiling

tools (reference: Spark event logs consumed by tools/, SURVEY.md §2.9) and
for the metrics/observability story (GpuMetric -> SQL UI role).

Every executed query appends one JSON line to the event log:
  {"query_id", "wall_ms", "physical_plan", "fallbacks": [...],
   "node_metrics": {node: {metric: value}}, "conf": {...}}

Durability: the logger keeps a persistent append-mode handle, flushes
per record by default (conf ``eventLog.flushPerRecord`` / env
``SPARK_RAPIDS_TPU_EVENT_LOG_FLUSH``), and rotates size-bounded files
(conf ``eventLog.rotation.maxBytes`` / env
``SPARK_RAPIDS_TPU_EVENT_LOG_MAX_BYTES``: current file renamed to
``<path>.N``, N increasing) so long service runs never grow one
unbounded JSONL file.  Multiple logger instances on the same path (the
session's and the service's) serialize through a module lock and
re-open after a peer's rotation (the WatchedFileHandler discipline).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

_LOCK = threading.Lock()


def _plan_verify_record(phys_plan, conf_dict: Dict) -> Optional[Dict]:
    """Structured verifier verdicts for the event log — {"ok", "violations":
    [{"node_index", "rule", "message"}]} — so tools/report.py can annotate
    the recorded plan tree per node.  Only when the verifier is enabled
    (conf or the test-harness force env); never fails the log path."""
    on = os.environ.get("SPARK_RAPIDS_TPU_FORCE_PLAN_VERIFY") or \
        str(conf_dict.get("spark.rapids.tpu.sql.planVerify", "")
            ).strip().lower() in ("true", "1", "yes")
    if not on:
        return None
    try:
        from ..analysis.plan_verify import verify_plan
        rep = verify_plan(phys_plan)
        return {"ok": rep.ok,
                "violations": [{"node_index": v.node_index,
                                "rule": v.rule,
                                "message": v.message}
                               for v in rep.violations]}
    except Exception:
        return None


def _env_bytes(name: str) -> Optional[int]:
    raw = os.environ.get(name)
    if not raw:
        return None
    s = str(raw).strip().lower()
    mult = 1
    for suffix, m in (("k", 2**10), ("m", 2**20), ("g", 2**30)):
        if s.endswith(suffix + "b"):
            s, mult = s[:-2], m
            break
        if s.endswith(suffix):
            s, mult = s[:-1], m
            break
    return int(float(s) * mult)


class QueryEventLogger:
    def __init__(self, path: Optional[str] = None,
                 max_bytes: Optional[int] = None,
                 flush_each: Optional[bool] = None):
        self.path = path or os.environ.get(
            "SPARK_RAPIDS_TPU_EVENT_LOG", "")
        # precedence: explicit arg > env > active conf
        from ..config import (get_active, EVENT_LOG_ROTATE_BYTES,
                              EVENT_LOG_FLUSH_PER_RECORD)
        if max_bytes is None:
            max_bytes = _env_bytes("SPARK_RAPIDS_TPU_EVENT_LOG_MAX_BYTES")
        if max_bytes is None:
            max_bytes = get_active().get(EVENT_LOG_ROTATE_BYTES)
        if flush_each is None:
            env = os.environ.get("SPARK_RAPIDS_TPU_EVENT_LOG_FLUSH")
            flush_each = env.strip().lower() in ("true", "1", "yes") \
                if env else get_active().get(EVENT_LOG_FLUSH_PER_RECORD)
        self.max_bytes = int(max_bytes or 0)
        self.flush_each = bool(flush_each)
        self.rotations = 0
        self._file = None
        self._next_id = 0
        self._id_lock = threading.Lock()

    def enabled(self) -> bool:
        return bool(self.path)

    def log_query(self, phys_plan, wall_ms: float, fallbacks: List[str],
                  conf_dict: Dict, metrics_level: str = "MODERATE",
                  query_id=None, extra: Optional[Dict] = None):
        """One engine-execution record.  ``query_id``, when provided by
        the caller (the query service), is STABLE across every event of
        that query — admission, each retry attempt, engine metrics,
        final outcome — so the qualification/profiling tools can join
        attempts of the same query; otherwise a logger-local id is
        assigned."""
        if query_id is None:
            with self._id_lock:
                self._next_id += 1
                query_id = self._next_id
        record = {
            "event": "query",
            "query_id": query_id,
            "ts": time.time(),
            "wall_ms": round(wall_ms, 3),
            "physical_plan": phys_plan.tree_string(),
            "nodes": [n.name for n in phys_plan.collect_nodes()],
            "fallbacks": fallbacks,
            "node_metrics": {
                f"{i}:{n.name}": n.metrics.snapshot(metrics_level)
                for i, n in enumerate(phys_plan.collect_nodes())},
            "conf": {k: v for k, v in conf_dict.items()},
        }
        verdicts = _plan_verify_record(phys_plan, conf_dict)
        if verdicts is not None:
            record["plan_verify"] = verdicts
        if extra:
            record.update(extra)
        self._append(record)
        return record

    def log_service_event(self, kind: str, query_id, **fields):
        """One service-lifecycle line: kind is admitted | shed | retry |
        watchdog | cancelled | completed | failed.  Shares the query's
        stable ``query_id`` with the engine records.  Failure-class
        records (shed/cancelled/failed/watchdog) carry ``diag_bundle``
        — the path of the automatic diagnostic bundle written for the
        incident (obs/diagnostics.py; None when diagnostics are
        disabled) — which tools/report.py surfaces as the bundle link
        in the retry/failure story."""
        record = {"event": kind, "query_id": query_id, "ts": time.time()}
        record.update(fields)
        self._append(record)
        return record

    # -- durable append with size-based rotation ---------------------------
    def _open_locked(self):
        """(Re)open the append handle; detects a peer instance's
        rotation by inode mismatch and follows the fresh file."""
        if self._file is not None and not self._file.closed:
            try:
                if os.path.exists(self.path) and \
                        os.stat(self.path).st_ino == \
                        os.fstat(self._file.fileno()).st_ino:
                    return self._file
            except OSError:
                pass
            self._file.close()
            self._file = None
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._file = open(self.path, "a")
        return self._file

    def _rotate_locked(self):
        if self._file is not None:
            self._file.close()
            self._file = None
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        os.replace(self.path, f"{self.path}.{n}")
        self.rotations += 1

    def _append(self, record: Dict):
        if not self.enabled():
            return
        line = json.dumps(record) + "\n"
        with _LOCK:
            f = self._open_locked()
            if self.max_bytes:
                try:
                    size = os.fstat(f.fileno()).st_size
                except OSError:
                    size = 0
                if size and size + len(line) > self.max_bytes:
                    self._rotate_locked()
                    f = self._open_locked()
            f.write(line)
            if self.flush_each:
                f.flush()

    def close(self):
        with _LOCK:
            if self._file is not None and not self._file.closed:
                self._file.close()
            self._file = None


def rotated_paths(path: str) -> List[str]:
    """Every file of a (possibly rotated) event log, oldest first:
    ``path.1``, ``path.2``, ..., then the live ``path``."""
    out = []
    n = 1
    while os.path.exists(f"{path}.{n}"):
        out.append(f"{path}.{n}")
        n += 1
    if os.path.exists(path):
        out.append(path)
    return out


def read_event_log(path: str, events: Optional[str] = "query",
                   include_rotated: bool = False) -> List[Dict]:
    """Parsed event-log records.

    ``events`` filters by record kind: the default "query" returns only
    engine-execution records (what the qualification/profiling tools
    consume — service lifecycle lines would skew their per-query
    statistics); pass a specific kind ("retry", "shed", ...) or None
    for everything.  ``include_rotated`` also reads ``path.N`` rotation
    segments, oldest first."""
    out = []
    paths = rotated_paths(path) if include_rotated else [path]
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind = rec.get("event", "query")
                if events is not None and kind != events:
                    continue
                out.append(rec)
    return out
