"""DataFrameReader / DataFrameWriter — spark.read / df.write surface."""
from __future__ import annotations

from typing import Dict, List, Optional, Union

from ..columnar.schema import Schema
from ..plan import logical as L


class DataFrameReader:
    def __init__(self, session):
        self.session = session
        self._options: Dict[str, object] = {}
        self._schema: Optional[Schema] = None
        self._format: str = "parquet"

    def format(self, fmt: str) -> "DataFrameReader":  # noqa: A003
        self._format = fmt
        return self

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[key] = value
        return self

    def schema(self, schema: Schema) -> "DataFrameReader":
        self._schema = schema
        return self

    def load(self, path: Union[str, List[str]]):
        from .dataframe import DataFrame
        from ..io.readers import infer_schema
        paths = [path] if isinstance(path, str) else list(path)
        schema = self._schema or infer_schema(self._format, paths,
                                              self._options,
                                              conf=self.session.conf)
        return DataFrame(
            L.Scan(self._format, paths, schema, self._options), self.session)

    def parquet(self, *paths: str):
        return self.format("parquet").load(list(paths))

    def orc(self, *paths: str):
        return self.format("orc").load(list(paths))

    def csv(self, path, header: bool = True, sep: str = ","):
        return (self.format("csv").option("header", header)
                .option("sep", sep).load(path))

    def json(self, path):
        return self.format("json").load(path)


class DataFrameWriter:
    def __init__(self, df):
        self.df = df
        self._mode = "overwrite"
        self._options: Dict[str, object] = {}
        self._partition_by: List[str] = []

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def option(self, key: str, value) -> "DataFrameWriter":
        self._options[key] = value
        return self

    def partition_by(self, *cols: str) -> "DataFrameWriter":
        """Hive-style dynamic partitioning: one col=value directory per
        key combination (reference: GpuFileFormatWriter dynamic
        partitioning)."""
        self._partition_by = list(cols)
        return self

    partitionBy = partition_by

    def _write(self, fmt: str, path: str):
        plan = L.WriteFile(fmt, path, self.df._plan, self._mode,
                           self._options, self._partition_by)
        phys = self.df.session._plan(plan)
        for part in phys.execute():
            for _ in part:
                pass

    def parquet(self, path: str):
        self._write("parquet", path)

    def orc(self, path: str):
        self._write("orc", path)

    def csv(self, path: str):
        self._write("csv", path)
