"""Column API wrapper — PySpark-style ``Column`` over expression trees."""
from __future__ import annotations

from typing import Any

from ..columnar import dtypes as T
from ..expr import core as ec
from ..expr import (arithmetic as ea, predicates as ep, conditional as econd,
                    cast as ecast, string_ops as es)


def _expr(v) -> ec.Expression:
    if isinstance(v, Col):
        return v.expr
    if isinstance(v, ec.Expression):
        return v
    return ec.Literal(v)


class Col:
    def __init__(self, expr: ec.Expression):
        self.expr = expr

    # arithmetic
    def __add__(self, o):
        return Col(ea.Add(self.expr, _expr(o)))

    def __radd__(self, o):
        return Col(ea.Add(_expr(o), self.expr))

    def __sub__(self, o):
        return Col(ea.Subtract(self.expr, _expr(o)))

    def __rsub__(self, o):
        return Col(ea.Subtract(_expr(o), self.expr))

    def __mul__(self, o):
        return Col(ea.Multiply(self.expr, _expr(o)))

    def __rmul__(self, o):
        return Col(ea.Multiply(_expr(o), self.expr))

    def __truediv__(self, o):
        return Col(ea.Divide(self.expr, _expr(o)))

    def __rtruediv__(self, o):
        return Col(ea.Divide(_expr(o), self.expr))

    def __mod__(self, o):
        return Col(ea.Remainder(self.expr, _expr(o)))

    def __neg__(self):
        return Col(ea.UnaryMinus(self.expr))

    # comparisons
    def __eq__(self, o):  # type: ignore[override]
        return Col(ep.EqualTo(self.expr, _expr(o)))

    def __ne__(self, o):  # type: ignore[override]
        return Col(ep.Not(ep.EqualTo(self.expr, _expr(o))))

    def __lt__(self, o):
        return Col(ep.LessThan(self.expr, _expr(o)))

    def __le__(self, o):
        return Col(ep.LessThanOrEqual(self.expr, _expr(o)))

    def __gt__(self, o):
        return Col(ep.GreaterThan(self.expr, _expr(o)))

    def __ge__(self, o):
        return Col(ep.GreaterThanOrEqual(self.expr, _expr(o)))

    # boolean
    def __and__(self, o):
        return Col(ep.And(self.expr, _expr(o)))

    def __or__(self, o):
        return Col(ep.Or(self.expr, _expr(o)))

    def __invert__(self):
        return Col(ep.Not(self.expr))

    # pyspark-style methods
    def alias(self, name: str) -> "Col":
        return Col(ec.Alias(self.expr, name))

    def cast(self, to) -> "Col":
        if isinstance(to, str):
            to = T.dtype_from_name(to)
        return Col(ecast.Cast(self.expr, to))

    def is_null(self):
        return Col(ep.IsNull(self.expr))

    isNull = is_null

    def is_not_null(self):
        return Col(ep.IsNotNull(self.expr))

    isNotNull = is_not_null

    def isin(self, *values):
        vals = values[0] if len(values) == 1 and \
            isinstance(values[0], (list, tuple)) else list(values)
        return Col(ep.In(self.expr, list(vals)))

    def eq_null_safe(self, o):
        return Col(ep.EqualNullSafe(self.expr, _expr(o)))

    eqNullSafe = eq_null_safe

    def like(self, pattern: str):
        return Col(es.Like(self.expr, ec.Literal(pattern)))

    def rlike(self, pattern: str):
        return Col(es.RLike(self.expr, ec.Literal(pattern)))

    def startswith(self, s):
        return Col(es.StartsWith(self.expr, _expr(s)))

    def endswith(self, s):
        return Col(es.EndsWith(self.expr, _expr(s)))

    def contains(self, s):
        return Col(es.Contains(self.expr, _expr(s)))

    def substr(self, start: int, length: int):
        return Col(es.Substring(self.expr, ec.Literal(start),
                                ec.Literal(length)))

    def getItem(self, key):
        from ..expr import collections as ecoll
        if isinstance(key, Col):
            key = key.expr
        return Col(ecoll.ExtractValue(self.expr, key))

    def getField(self, name: str):
        from ..expr import collections as ecoll
        return Col(ecoll.GetStructField(self.expr, name))

    def __getitem__(self, key):
        return self.getItem(key)

    def when(self, *a, **k):
        raise AttributeError("use functions.when")

    def otherwise(self, *a, **k):
        raise AttributeError("use functions.when(...).otherwise(...)")

    def asc(self):
        from ..plan.logical import SortOrder
        return SortOrder(self.expr, ascending=True)

    def desc(self):
        from ..plan.logical import SortOrder
        return SortOrder(self.expr, ascending=False)

    def asc_nulls_last(self):
        from ..plan.logical import SortOrder
        return SortOrder(self.expr, ascending=True, nulls_first=False)

    def desc_nulls_first(self):
        from ..plan.logical import SortOrder
        return SortOrder(self.expr, ascending=False, nulls_first=True)

    def __repr__(self):
        return f"Col({self.expr!r})"

    def __hash__(self):
        return id(self)

    def __bool__(self):
        raise ValueError(
            "Cannot convert Col to bool; use & | ~ for boolean logic")
