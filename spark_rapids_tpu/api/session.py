"""TpuSession — the user entry point.

Role parity: in the reference, users keep their SparkSession and the
plugin hooks in via ``spark.plugins=com.nvidia.spark.SQLPlugin``
(Plugin.scala:57).  Standalone, TpuSession plays both roles: it owns the
conf, initializes the device (executor-plugin init, Plugin.scala:175 ->
GpuDeviceManager), and runs the planner on every action.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from ..config import TpuConf, set_active, SQL_ENABLED
from ..columnar.schema import Schema
from ..memory.arena import DeviceManager
from ..obs import trace as _obs_trace
from ..plan import logical as L
from ..plan.overrides import Planner


_CACHE_ENABLED = False


def _enable_compilation_cache():
    """Persistent XLA compilation cache: kernels are compiled per

    (schema, capacity-bucket), so cross-process reuse pays off immediately
    (first TPU compile is expensive; SURVEY.md §7 compile-cache note)."""
    global _CACHE_ENABLED
    if _CACHE_ENABLED:
        return
    try:
        import getpass
        import tempfile
        import jax
        cache_dir = os.environ.get("SPARK_RAPIDS_TPU_XLA_CACHE")
        if not cache_dir:
            # computed lazily: getuser() can raise in uid-less containers,
            # and must not take down an explicitly configured cache
            cache_dir = os.path.join(
                tempfile.gettempdir(),
                f"spark_rapids_tpu_xla_cache_{getpass.getuser()}")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        _CACHE_ENABLED = True
    except Exception:
        pass


class TpuSessionBuilder:
    def __init__(self):
        self._conf: Dict[str, object] = {}

    def config(self, key: str, value) -> "TpuSessionBuilder":
        self._conf[key] = value
        return self

    def get_or_create(self) -> "TpuSession":
        return TpuSession(TpuConf(self._conf))


class TpuSession:
    # Active-session registry: per-thread with a lock-guarded global
    # fallback, so concurrent client threads each see the session THEY
    # activated (and its conf) rather than whichever thread activated
    # last.  The conf registry (config.set_active) follows the same
    # thread-local-with-global-fallback discipline.
    _active: Optional["TpuSession"] = None
    _active_tls = threading.local()
    _active_lock = threading.Lock()

    def __init__(self, conf: Optional[TpuConf] = None):
        self.conf = conf or TpuConf()
        set_active(self.conf)
        _enable_compilation_cache()
        _obs_trace.configure(self.conf)
        from ..obs import flight as _obs_flight
        _obs_flight.configure(self.conf)
        from ..obs import overhead as _obs_overhead
        _obs_overhead.configure(self.conf)
        from ..compile import aot as _aot
        _aot.configure(self.conf)
        with TpuSession._active_lock:
            # device (re)init mutates process-wide state (catalog,
            # semaphore); serialize concurrent session construction
            DeviceManager.initialize(self.conf)
            TpuSession._active = self
        TpuSession._active_tls.session = self
        self._last_planner: Optional[Planner] = None
        self._views: dict = {}
        self._logger_lock = threading.Lock()
        # plan-cache disposition of the most recent collect:
        # ("hit"|"miss", planner_path_ms) or None (cache off)
        self.last_query_plan_cache = None

    builder = TpuSessionBuilder

    @classmethod
    def active(cls) -> "TpuSession":
        s = getattr(cls._active_tls, "session", None)
        if s is not None:
            return s
        with cls._active_lock:
            if cls._active is not None:
                return cls._active
        return TpuSession()   # constructor registers itself

    # -- conf ----------------------------------------------------------------
    def set_conf(self, key: str, value):
        self.conf = self.conf.set(key, value)
        set_active(self.conf)

    def get_conf(self, key: str):
        return self.conf.get_key(key)

    # -- data sources --------------------------------------------------------
    def create_dataframe(self, data, schema: Optional[Schema] = None,
                         num_partitions: int = 1):
        from .dataframe import DataFrame
        if isinstance(data, pa.Table):
            table = data
        elif isinstance(data, dict):
            if schema is None:
                # pyarrow inference handles date/datetime/decimal object
                # arrays that numpy would stringify
                table = pa.table({k: pa.array(v) for k, v in data.items()})
            else:
                from ..columnar.arrow import schema_to_arrow
                target = schema_to_arrow(schema)
                table = pa.table(
                    {f.name: pa.array(data[f.name], type=target.field(
                        f.name).type) for f in schema})
        elif isinstance(data, list):
            # list of tuples + schema
            assert schema is not None, "list data requires a schema"
            cols = {f.name: [row[i] for row in data]
                    for i, f in enumerate(schema)}
            from ..columnar.batch import ColumnarBatch
            from ..columnar.arrow import to_arrow
            batch = ColumnarBatch.from_pydict(cols, schema=schema)
            table = to_arrow(batch)
        else:
            raise TypeError(f"cannot create dataframe from {type(data)}")
        return DataFrame(L.LocalRelation(table, num_partitions), self)

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              num_partitions: int = 1):
        from .dataframe import DataFrame
        if end is None:
            start, end = 0, start
        return DataFrame(L.Range(start, end, step, num_partitions), self)

    @property
    def read(self):
        from .reader import DataFrameReader
        return DataFrameReader(self)

    # -- SQL -----------------------------------------------------------------
    def sql(self, query: str):
        """Parse + lower a SQL query against registered temp views.

        Reference role: Spark's own parser/analyzer feed the plugin its
        plans; standalone, api/sql.py supplies that front end."""
        from .dataframe import DataFrame
        from .sql import sql_to_plan
        plan = sql_to_plan(query, self, self._views)
        return DataFrame(plan, self)

    def register_table(self, name: str, df) -> None:
        self._views[name.lower()] = df._plan

    def drop_temp_view(self, name: str) -> None:
        self._views.pop(name.lower(), None)

    # -- execution -----------------------------------------------------------
    def _plan(self, logical: L.LogicalPlan, conf: Optional[TpuConf] = None):
        # plan through the fingerprint-keyed cache (cache/plan_cache.py)
        # so repeat shapes skip the planner tail in standalone sessions
        # exactly as they do under the query service
        from ..cache import plan_cache as _plan_cache
        phys, planner = _plan_cache.plan_with_cache(
            logical, conf or self.conf)
        self._last_planner = planner
        return phys

    def execute_to_arrow(self, logical: L.LogicalPlan) -> pa.Table:
        """Run a logical plan and collect everything as one arrow table."""
        import time as _time
        from ..columnar.arrow import to_arrow, schema_to_arrow
        from ..config import PROFILE_TRACE_DIR
        trace_dir = self.conf.get(PROFILE_TRACE_DIR)
        if trace_dir:
            # xprof trace of the whole query — the NVTX+Nsight role
            # (SURVEY.md §5); view with tensorboard / xprof
            import jax
            with jax.profiler.trace(trace_dir):
                return self._execute_to_arrow_inner(logical)
        return self._execute_to_arrow_inner(logical)

    def _execute_to_arrow_inner(self, logical: L.LogicalPlan) -> pa.Table:
        phys = self._plan(logical)
        return self.execute_physical(phys)

    def execute_physical(self, phys, conf: Optional[TpuConf] = None,
                         fallbacks: Optional[List[str]] = None) -> pa.Table:
        """Run an ALREADY-PLANNED physical tree and collect one arrow
        table (the distributed runner plans once, attaches executor
        contexts to exchange nodes, then executes that exact tree).

        ``conf``/``fallbacks`` override the session's own for callers
        that planned with an overlay (the query service executes many
        queries with per-query confs on worker threads; passing them
        explicitly keeps this method thread-safe against session-level
        mutation).  Execution drains through cancellation checkpoints
        and surfaces per-query semaphore-wait and spill-bytes metrics
        in the event log.  With tracing on, the whole collect is one
        "query" span (exec-node/kernel/memory spans nest under it) and
        the span buffer flushes to the configured trace path."""
        with _obs_trace.span("query", "engine", root=phys.name):
            out = self._execute_physical_traced(phys, conf, fallbacks)
        if _obs_trace.is_enabled():
            _obs_trace.flush()
        return out

    def _execute_physical_traced(self, phys, conf: Optional[TpuConf] = None,
                                 fallbacks: Optional[List[str]] = None
                                 ) -> pa.Table:
        import time as _time
        from ..columnar.arrow import to_arrow, schema_to_arrow
        from ..columnar.arrow import stage_batch
        from ..memory.arena import DeviceManager
        from ..memory.catalog import BufferCatalog
        from ..service.cancellation import current_token, observe
        conf = conf or self.conf
        # the executing query's conf is the ambient conf for THIS
        # thread for the duration of the drain: with several live
        # sessions, "last constructed wins" would hand exec-layer
        # get_active() callers (shuffle staging budget, stats plane)
        # another session's settings
        set_active(conf, thread_only=True)
        if fallbacks is None:
            fallbacks = self._last_planner.fallbacks \
                if self._last_planner else []
        t0 = _time.perf_counter()
        self.last_physical_plan = phys
        # static PV-FLUSH prediction, computed BEFORE any execution so
        # the predicted-vs-observed comparison below cannot be informed
        # by the run it predicts.  A predictor gap must never block a
        # query: the comparison is observability, the exactness contract
        # is enforced by ci/compile_smoke.py + tests/test_audit.py.
        _flush_pred = None
        try:
            # a plan that came through the plan cache carries its
            # prediction already (replayed from the stored certificate
            # on a hit, computed once at store time on a miss) — the
            # PV-FLUSH exactness contract holds on both paths
            _flush_pred = getattr(phys, "_plan_cache_flush_pred", None)
            if _flush_pred is None:
                from ..analysis.flush_budget import predict_flushes
                _flush_pred = predict_flushes(phys, conf=conf)
        except Exception:  # noqa: BLE001 - observability only
            pass
        sem = DeviceManager.get().semaphore
        sem.pop_wait_ns()                     # reset this thread's counter
        cat = BufferCatalog.get()
        spill0 = cat.spilled_device_to_host + cat.spilled_host_to_disk
        # device round trips this query (process-wide counter delta:
        # concurrent peers' flushes land in whichever query's window
        # they fall — exact when queries run serially, which is how the
        # flush budget is benchmarked)
        from ..analysis import residency as _residency
        from ..columnar import pending
        from ..obs import compile_watch as _cwatch
        from ..obs import costplane as _costplane
        from ..obs import doctor as _doctor
        from ..obs import memplane as _memplane
        from ..obs import netplane as _netplane
        from ..obs import overhead as _overhead
        from ..obs import profile as _profile
        from ..obs import stats as _stats
        from ..obs import timeline as _timeline
        flushes0 = pending.FLUSH_COUNT
        # declared device->host transfers this query (same counter-delta
        # discipline; analysis/residency.py) — the runtime half of the
        # residency contract
        res_marker = _residency.snapshot()
        # self-meter window (obs/overhead.py): per-plane observability
        # self-cost accrued inside this query, same process-wide
        # counter-delta discipline as FLUSH_COUNT
        obs_marker = _overhead.snapshot()
        disp_marker = _profile.begin_query()
        np_marker = _netplane.begin_query()
        mem_marker = _memplane.begin_query()
        cost_marker = _costplane.begin_query()
        # performance-plane windows: compile ns + busy intervals are
        # process-wide counters deltaed around this execution (the
        # FLUSH_COUNT discipline — exact when queries run serially)
        compile0 = _cwatch.total_ns()
        cw_marker = _cwatch.begin_query()
        tl_marker = _timeline.begin_query()
        # collect-sink flushes belong to the root-most fused superstage
        # when the plan has one (obs/profile.py attribution scopes)
        _attrib = next((n for n in phys.collect_nodes()
                        if getattr(n, "lowering", None) is not None),
                       phys)
        token = current_token()
        try:
            # drain all partitions first (device work + staged pulls),
            # then one fused flush serves every batch's counts/buffers
            # (columnar/pending).  The drain is morsel-parallel
            # (exec/pipeline.py): partitions are pulled + resolved on
            # the pipeline pool, reassembled here in partition order —
            # same items, same order as the serial loop it replaced
            from ..columnar.batch import resolve_speculative
            from ..exec.pipeline import drain_parallel

            def _resolve(item):
                if isinstance(item, pa.Table):
                    return item
                # stage output buffers BEFORE the fit-flag check: the
                # flush the verification forces then carries the values
                # too, so a fully speculative chain (superstage join ->
                # agg -> sort -> limit) collects in ONE round trip
                with _profile.attrib_scope(_attrib):
                    stage_batch(item)
                    fixed = resolve_speculative(item)
                    if fixed is not item:
                        stage_batch(fixed)
                return fixed
            # the scoped transfer guard (analysis/residency.py): any
            # device->host pull on this thread that is not inside a
            # declared_transfer region fails loudly.  Pool workers arm
            # the same guard per-thread in _ParallelDrain._serve.
            with _residency.guard_scope(conf):
                items = [item for _pid, item in drain_parallel(
                    phys.execute_checkpointed(), sink=_resolve,
                    token=token, label="collect")]
                tables: List[pa.Table] = []
                for item in items:
                    if isinstance(item, pa.Table):
                        t = item
                    else:
                        with _residency.declared_transfer(
                                site="collect_sink"):
                            t = to_arrow(item)
                    if t.num_rows:
                        tables.append(t)
        finally:
            # end-of-query shuffle release (ContextCleaner role): map
            # outputs are per-query; holding them across a long sweep
            # exhausts the real allocator.  Under a query context only
            # THIS query's shuffles are dropped (concurrent peers may
            # still be draining theirs); distributed-attached exchanges
            # keep their executor-context outputs (peers may still
            # fetch).
            from ..shuffle.manager import ShuffleManager
            mgr = ShuffleManager._instance
            if mgr is not None:
                if token is not None:
                    for sid in token.pop_owned_shuffles():
                        mgr.cleanup(sid)
                else:
                    mgr.clear_all()
        sem_wait_ms = sem.pop_wait_ns() / 1e6
        spill_bytes = (cat.spilled_device_to_host +
                       cat.spilled_host_to_disk) - spill0
        observe("sem_wait_ms", sem_wait_ms)
        observe("spill_bytes", spill_bytes)
        flushes = pending.FLUSH_COUNT - flushes0
        self.last_query_flushes = flushes
        observe("flushes", flushes)
        declared_total, declared_sites = _residency.delta(res_marker)
        self.last_query_declared_transfers = declared_sites
        observe("declared_transfers", declared_total)
        # compile telemetry: compiles that landed in this query's window
        # (engine path; the service separately harvests the token's
        # inline_compile_ms observed at compile time)
        inline_compile_ms = (_cwatch.total_ns() - compile0) / 1e6
        self.last_query_inline_compile_ms = inline_compile_ms
        # device-utilization lane for this query's window
        tl = _timeline.query_summary(tl_marker)
        self.last_query_timeline = tl
        # shuffle host-drop roll-up for this query's window (same
        # process-wide-counter-delta discipline as FLUSH_COUNT); the
        # edge heat rows + per-peer fetch aggregate ride the record so
        # tools/report.py --shuffle renders offline
        net = _netplane.query_summary(np_marker)
        net["top_edges"] = _netplane.query_edges(np_marker, limit=8)
        peers = _netplane.fetch_peer_stats()
        if peers:
            net["fetch_peers"] = peers
        self.last_query_netplane = net
        # the service harvests this into the completed-outcome record
        # (service/metrics.py), like sem_wait_ms above
        observe("host_drop_tax_ms", net["host_drop_tax_ms"])
        # retention check (obs/memplane.py): anything still owned by
        # this query past the shuffle release above that is not an
        # expected survivor (scan cache, shuffle materializations a
        # live reader may still fetch) leaked its registration
        leaks = []
        if token is not None and _memplane.is_enabled():
            from ..shuffle.manager import live_spill_buffer_ids
            leaks = _memplane.leak_check(
                token.query_id, survivors=live_spill_buffer_ids())
        # memory roll-up for this query's window: peak + owner set at
        # peak, per-direction spill totals, the ledger slice
        mem = _memplane.query_summary(mem_marker)
        if leaks:
            mem["leaks"] = leaks
        self.last_query_memplane = mem
        observe("spill_ms", mem["spill_ms"])
        observe("unspill_count", mem["unspill_count"])
        observe("leaked_entries", mem["leaked_entries"])
        result_rows = sum(t.num_rows for t in tables)
        predicted_flushes = None
        if _flush_pred is not None:
            predicted_flushes = _flush_pred.expected(result_rows)
        self.last_query_predicted_flushes = predicted_flushes
        # device-compute cost roll-up (obs/costplane.py): joins the
        # static XLA costs already captured at compile time with this
        # window's dispatch ledger and the timeline busy span — pure
        # host arithmetic, after the final flush, zero extra round trips
        cost = None
        if _costplane.enabled(conf):
            try:
                cost = _costplane.query_summary(
                    cost_marker, busy_ms=float(tl["busy_ms"]))
            except Exception:  # noqa: BLE001 — cost never fails a query
                import logging
                logging.getLogger("spark_rapids_tpu.obs.costplane").warning(
                    "cost summary failed", exc_info=True)
        self.last_query_costplane = cost
        extra = {"sem_wait_ms": round(sem_wait_ms, 3),
                 "spill_bytes": int(spill_bytes),
                 "flushes": int(flushes),
                 "predicted_flushes": predicted_flushes,
                 "declared_transfers": int(declared_total),
                 "declared_transfer_sites": dict(declared_sites),
                 "inline_compile_ms": round(inline_compile_ms, 3),
                 "device_busy_ms": tl["busy_ms"],
                 "device_util_pct": tl["util_pct"],
                 "util_gap_breakdown": tl["gaps"],
                 "host_drop_tax_ms": net["host_drop_tax_ms"],
                 "shuffle_netplane": net,
                 "peak_device_bytes": mem["peak_device_bytes"],
                 "spill_ms": mem["spill_ms"],
                 "unspill_count": mem["unspill_count"],
                 "leaked_entries": mem["leaked_entries"],
                 "memplane": mem}
        from ..config import RESIDENCY_IN_EVENT_LOG
        if not conf.get(RESIDENCY_IN_EVENT_LOG):
            extra.pop("declared_transfers")
            extra.pop("declared_transfer_sites")
        if cost is not None:
            extra["costplane"] = cost
        # plan-cache disposition (cache/plan_cache.py): stamped on the
        # physical root by plan_with_cache — hit/miss plus the wall ms
        # the planner path actually took for THIS query
        pc_status = getattr(phys, "_plan_cache_status", None)
        self.last_query_plan_cache = pc_status
        if pc_status is not None:
            extra["plan_cache"] = pc_status[0]
            extra["planner_path_ms"] = round(pc_status[1], 3)
        compiles = _cwatch.records_since(cw_marker)
        if compiles:
            extra["compiles"] = [
                {"cache": r["cache"], "dur_ms": r["dur_ms"],
                 "inline": r["inline"], "signature": r["signature"],
                 # AOT dimensions (compile/aot.py): which capacity
                 # bucket the compile was for and who paid for it
                 # (inline/warm/warmup/persistent)
                 "origin": r.get("origin", "inline"),
                 "bucket": r.get("bucket")}
                for r in compiles]
        # the recorded wall clock STOPS here: everything below is
        # observability artifact assembly (StatsProfile, the doctor
        # verdict, the fingerprint/history deposit) deferred to
        # event-log write time — it runs off the measured query path,
        # each piece billed to its plane by obs/overhead.py, and the
        # event-log wall_ms no longer pays for its own reporting
        wall_ms = (_time.perf_counter() - t0) * 1000
        # per-query StatsProfile (obs/stats.py): read-only over resolved
        # values — built AFTER the final flush, never adds a round trip
        self.last_stats_profile = None
        if _stats.enabled(conf):
            from ..config import OBS_STATS_IN_EVENT_LOG
            try:
                prof = _stats.build_profile(
                    phys,
                    query_id=token.query_id if token is not None else None,
                    flushes=int(flushes), dispatch_marker=disp_marker)
                self.last_stats_profile = prof
                if conf.get(OBS_STATS_IN_EVENT_LOG):
                    extra["stats_profile"] = prof.to_dict()
            except Exception:  # noqa: BLE001 — stats never fail a query
                import logging
                logging.getLogger("spark_rapids_tpu.obs.stats").warning(
                    "stats profile build failed", exc_info=True)
        # cross-plane query doctor (obs/doctor.py): joins the summaries
        # gathered above into one primary-bottleneck verdict — pure
        # host arithmetic over dicts already in hand, after the final
        # flush, so the FLUSH_COUNT delta above is unchanged
        self.last_query_diagnosis = None
        if _doctor.enabled(conf):
            try:
                diag = _doctor.diagnose(
                    tl, inline_compile_ms=inline_compile_ms,
                    netplane=net, memplane=mem, flushes=int(flushes),
                    predicted_flushes=predicted_flushes,
                    declared_transfers=declared_sites,
                    sem_wait_ms=sem_wait_ms,
                    stats_profile=self.last_stats_profile,
                    query_id=token.query_id if token is not None
                    else None,
                    compiles=extra.get("compiles"),
                    costplane=cost)
                self.last_query_diagnosis = diag
                extra["doctor"] = diag.to_dict()
            except Exception:  # noqa: BLE001 — doctor never fails a query
                import logging
                logging.getLogger("spark_rapids_tpu.obs.doctor").warning(
                    "query diagnosis failed", exc_info=True)
        # longitudinal fleet plane: the stable plan fingerprint groups
        # this query with every recurrence of its shape
        # (obs/fingerprint.py), and the engine-side artifacts are
        # deposited for the history store's terminal join keyed by the
        # same query_id the service folds at the terminal transition
        # (obs/history.py).  Pure host arithmetic after the final
        # flush: the FLUSH_COUNT delta above is unchanged.
        self.last_query_fingerprint = None
        try:
            from ..obs import fingerprint as _fingerprint
            from ..obs import history as _qhistory
            fp = _fingerprint.plan_fingerprint(phys, conf)
            self.last_query_fingerprint = fp
            extra["plan_fingerprint"] = fp
            if token is not None and _qhistory.enabled():
                art = {
                    "fingerprint": fp,
                    "flushes": int(flushes),
                    "flushes_predicted": predicted_flushes,
                    "device_util_pct": tl["util_pct"],
                    "gaps": tl["gaps"],
                }
                if cost is not None:
                    art["roofline_verdict"] = cost.get("verdict")
                    art["achieved_GBps"] = cost.get("achieved_gbps")
                    art["padding_waste_pct"] = \
                        cost.get("padding_waste_pct")
                if self.last_query_diagnosis is not None:
                    d = self.last_query_diagnosis.to_dict()
                    art["doctor_cause"] = d.get("primary_cause")
                    art["doctor_share_pct"] = d.get("primary_share_pct")
                _qhistory.note_query(token.query_id, art)
        except Exception:  # noqa: BLE001 — fleet plane never fails a query
            import logging
            logging.getLogger("spark_rapids_tpu.obs.history").warning(
                "fingerprint/history deposit failed", exc_info=True)
        # the self-meter's verdict on everything the planes above spent
        # inside this query (including the deferred assembly just run)
        if _overhead.is_enabled():
            obs_self = _overhead.delta_ms(obs_marker)
            extra["obs_self"] = {
                "total_ms": round(sum(obs_self.values()), 3),
                "planes": obs_self}
        self._log_query(phys, wall_ms, conf=conf, fallbacks=fallbacks,
                        extra=extra)
        target = schema_to_arrow(phys.output_schema) if len(
            phys.output_schema) else None
        if not tables:
            return target.empty_table() if target is not None else \
                pa.table({})
        out = pa.concat_tables(tables, promote_options="permissive")
        if target is not None and out.schema != target:
            import pyarrow.compute as pc
            out = pa.Table.from_arrays(
                [pc.cast(out.column(i).combine_chunks(), f.type, safe=False)
                 for i, f in enumerate(target)], schema=target)
        return out

    def _log_query(self, phys, wall_ms: float,
                   conf: Optional[TpuConf] = None,
                   fallbacks: Optional[List[str]] = None,
                   extra: Optional[Dict] = None):
        from ..config import EVENT_LOG_PATH, METRICS_LEVEL
        from ..service.cancellation import current_token
        from ..tools.events import QueryEventLogger
        conf = conf or self.conf
        path = conf.get(EVENT_LOG_PATH)
        with self._logger_lock:
            if not hasattr(self, "_event_logger") or \
                    (self._event_logger.path or "") != (path or ""):
                self._event_logger = QueryEventLogger(path or None)
            logger = self._event_logger
        # a service-managed query logs under its stable service query_id
        # so admission / retry / outcome lines join with engine metrics
        token = current_token()
        self.last_query_event = logger.log_query(
            phys, wall_ms,
            fallbacks if fallbacks is not None else (
                self._last_planner.fallbacks if self._last_planner else []),
            dict(conf._settings),
            metrics_level=conf.get(METRICS_LEVEL),
            query_id=token.query_id if token is not None else None,
            extra=extra)

    def explain(self, logical: L.LogicalPlan) -> str:
        """Planner explain: physical tree + fallback reasons."""
        phys = self._plan(logical)
        text = phys.tree_string()
        if self._last_planner.fallbacks:
            text += "\n-- CPU fallbacks --\n" + "\n".join(
                self._last_planner.fallbacks)
        return text
