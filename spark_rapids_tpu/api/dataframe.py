"""DataFrame API — the user surface (PySpark-flavored).

Role note: the reference accelerates Spark's DataFrame/SQL API without
owning it; standalone, this module IS that surface, building the logical
plans the planner consumes.  Method names follow PySpark so existing
Spark jobs translate mechanically.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import pyarrow as pa

from ..columnar import dtypes as T
from ..columnar.schema import Schema
from ..expr import core as ec
from ..expr import aggregates as eagg
from ..plan import logical as L
from .column import Col, _expr


def _as_out_schema(schema) -> Schema:
    """Accept a Schema or a Spark-style DDL string ("a long, b double")."""
    if isinstance(schema, Schema):
        return schema
    if isinstance(schema, str):
        return Schema.from_ddl(schema)
    raise TypeError(f"expected Schema or DDL string, got {type(schema)}")


def _resolve(expr: ec.Expression, schema: Schema) -> ec.Expression:
    """Resolve AttributeReferences to typed refs against a schema."""
    if isinstance(expr, ec.AttributeReference) and expr._dtype is None:
        return expr.resolve(schema)
    return expr.map_children(lambda c: _resolve(c, schema))


def _to_expr(c, schema: Schema) -> ec.Expression:
    if isinstance(c, str):
        return ec.AttributeReference(c).resolve(schema)
    if isinstance(c, Col):
        return _resolve(c.expr, schema)
    if isinstance(c, ec.Expression):
        return _resolve(c, schema)
    return ec.Literal(c)


class DataFrame:
    def __init__(self, logical: L.LogicalPlan, session):
        self._plan = logical
        self.session = session

    # -- metadata ------------------------------------------------------------
    @property
    def schema(self) -> Schema:
        return self._plan.schema

    @property
    def columns(self) -> List[str]:
        return self.schema.names

    def __getitem__(self, name: str) -> Col:
        f = self.schema[name]
        return Col(ec.AttributeReference(f.name, f.dtype, f.nullable))

    # -- transformations -----------------------------------------------------
    def select(self, *cols) -> "DataFrame":
        exprs = []
        for c in cols:
            if isinstance(c, str) and c == "*":
                exprs.extend(
                    ec.AttributeReference(f.name, f.dtype, f.nullable)
                    for f in self.schema)
            else:
                exprs.append(_to_expr(c, self.schema))
        gen_plan, exprs = self._plan_generators(exprs)
        return DataFrame(L.Project(exprs, gen_plan), self.session)

    def _plan_generators(self, exprs):
        """Pull top-level Explode generators into a Generate node.

        Mirrors Spark's analyzer: SELECT with a generator becomes
        Generate(generator, child) + Project over its output
        (reference: GpuGenerateExec planning).
        """
        from ..expr import collections as ecoll
        gens = [e for e in exprs
                if isinstance(e, ecoll.Explode) or
                (isinstance(e, ec.Alias) and
                 isinstance(e.children[0], ecoll.Explode))]
        if not gens:
            return self._plan, exprs
        if len(gens) > 1:
            raise ValueError("only one generator allowed per select")
        g = gens[0]
        gen = g.children[0] if isinstance(g, ec.Alias) else g
        val_name = g.alias if isinstance(g, ec.Alias) else "col"
        names = (["pos", val_name] if gen.pos else [val_name])
        plan = L.Generate(gen, names, self._plan)
        out = []
        for e in exprs:
            if e is g:
                if gen.pos:
                    out.append(ec.AttributeReference("pos", T.INT32,
                                                     gen.outer))
                out.append(ec.AttributeReference(val_name, gen.dtype(),
                                                 True))
            else:
                out.append(e)
        return plan, out

    def with_column(self, name: str, col) -> "DataFrame":
        exprs = []
        replaced = False
        e = _to_expr(col, self.schema)
        for f in self.schema:
            if f.name == name:
                exprs.append(ec.Alias(e, name))
                replaced = True
            else:
                exprs.append(
                    ec.AttributeReference(f.name, f.dtype, f.nullable))
        if not replaced:
            exprs.append(ec.Alias(e, name))
        gen_plan, exprs = self._plan_generators(exprs)
        return DataFrame(L.Project(exprs, gen_plan), self.session)

    withColumn = with_column

    def cache(self) -> "DataFrame":
        """Mark for parquet-encoded columnar caching (reference:
        ParquetCachedBatchSerializer behind df.cache())."""
        from ..plan.logical import CachedRelation
        from ..exec.cache import CacheStorage
        if not isinstance(self._plan, CachedRelation):
            self._plan = CachedRelation(self._plan, CacheStorage())
        return self

    persist = cache

    def unpersist(self) -> "DataFrame":
        from ..plan.logical import CachedRelation
        if isinstance(self._plan, CachedRelation):
            self._plan.storage.invalidate()
            self._plan = self._plan.children[0]
        return self

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        exprs = []
        for f in self.schema:
            ref = ec.AttributeReference(f.name, f.dtype, f.nullable)
            exprs.append(ec.Alias(ref, new) if f.name == old else ref)
        return DataFrame(L.Project(exprs, self._plan), self.session)

    withColumnRenamed = with_column_renamed

    def drop(self, *names: str) -> "DataFrame":
        keep = [f for f in self.schema if f.name not in names]
        exprs = [ec.AttributeReference(f.name, f.dtype, f.nullable)
                 for f in keep]
        return DataFrame(L.Project(exprs, self._plan), self.session)

    def filter(self, cond) -> "DataFrame":
        return DataFrame(L.Filter(_to_expr(cond, self.schema), self._plan),
                         self.session)

    where = filter

    def group_by(self, *cols) -> "GroupedData":
        keys = [_to_expr(c, self.schema) for c in cols]
        return GroupedData(self, keys)

    groupBy = group_by
    groupby = group_by

    def rollup(self, *cols) -> "GroupedData":
        keys = [_to_expr(c, self.schema) for c in cols]
        sets = L.rollup_sets([ec.output_name(e) for e in keys])
        return GroupedData(self, keys, grouping_sets=sets)

    def cube(self, *cols) -> "GroupedData":
        keys = [_to_expr(c, self.schema) for c in cols]
        sets = L.cube_sets([ec.output_name(e) for e in keys])
        return GroupedData(self, keys, grouping_sets=sets)

    def agg(self, *aggs, **named) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs, **named)

    def join(self, other: "DataFrame", on=None, how: str = "inner"
             ) -> "DataFrame":
        how = {"left_outer": "left", "right_outer": "right",
               "outer": "full", "full_outer": "full", "leftsemi": "semi",
               "left_semi": "semi", "leftanti": "anti",
               "left_anti": "anti", "crossjoin": "cross"}.get(how, how)
        if how == "cross" or on is None:
            return DataFrame(
                L.Join(self._plan, other._plan, "cross", [], [], None),
                self.session)
        if isinstance(on, str):
            on = [on]
        if isinstance(on, (list, tuple)) and all(
                isinstance(x, str) for x in on):
            lkeys = [_to_expr(k, self.schema) for k in on]
            rkeys = [_to_expr(k, other.schema) for k in on]
            joined = L.Join(self._plan, other._plan, how, lkeys, rkeys, None)
            df = DataFrame(joined, self.session)
            if how in ("semi", "anti"):
                return df
            # spark semantics: dedupe the join columns (keep left's)
            out_exprs = []
            seen_right = set(on)
            lsch = self._plan.schema
            joined_schema = joined.schema
            for i, f in enumerate(joined_schema):
                if i < len(lsch):
                    out_exprs.append(
                        ec.BoundReference(i, f.dtype, f.nullable, f.name))
                    continue
                if f.name in seen_right:
                    seen_right.discard(f.name)
                    continue
                out_exprs.append(ec.BoundReference(i, f.dtype, f.nullable,
                                                   f.name))
            return DataFrame(L.Project(out_exprs, joined), self.session)
        # Col condition: only equi-joins extracted in v0
        cond = on.expr if isinstance(on, Col) else on
        lkeys, rkeys, residual = _extract_equi_keys(
            cond, self._plan.schema, other._plan.schema)
        return DataFrame(
            L.Join(self._plan, other._plan, how, lkeys, rkeys, residual),
            self.session)

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(L.Union([self._plan, other._plan]), self.session)

    unionAll = union

    def distinct(self) -> "DataFrame":
        return DataFrame(L.Distinct(self._plan), self.session)

    def drop_duplicates(self, subset: Optional[List[str]] = None
                        ) -> "DataFrame":
        if subset is None:
            return self.distinct()
        keys = [_to_expr(c, self.schema) for c in subset]
        aggs = [L.AggExpr(eagg.First(
            ec.AttributeReference(f.name, f.dtype, f.nullable)), f.name)
            for f in self.schema if f.name not in subset]
        agg_plan = L.Aggregate(keys, aggs, self._plan)
        # restore column order
        out = DataFrame(agg_plan, self.session)
        return out.select(*self.schema.names)

    dropDuplicates = drop_duplicates

    def sort(self, *cols, ascending=None) -> "DataFrame":
        orders = []
        for c in cols:
            if isinstance(c, L.SortOrder):
                orders.append(L.SortOrder(
                    _resolve(c.expr, self.schema), c.ascending,
                    c.nulls_first))
            else:
                orders.append(L.SortOrder(_to_expr(c, self.schema)))
        if ascending is not None:
            flags = ascending if isinstance(ascending, (list, tuple)) else \
                [ascending] * len(orders)
            orders = [L.SortOrder(o.expr, bool(a), o.nulls_first)
                      for o, a in zip(orders, flags)]
        return DataFrame(L.Sort(orders, self._plan, is_global=True),
                         self.session)

    orderBy = sort
    order_by = sort

    def sort_within_partitions(self, *cols) -> "DataFrame":
        orders = [L.SortOrder(_to_expr(c, self.schema)) for c in cols]
        return DataFrame(L.Sort(orders, self._plan, is_global=False),
                         self.session)

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(L.Limit(n, self._plan), self.session)

    def offset(self, n: int) -> "DataFrame":
        return DataFrame(L.Limit(1 << 60, self._plan, offset=n),
                         self.session)

    def repartition(self, num: int, *cols) -> "DataFrame":
        by = [_to_expr(c, self.schema) for c in cols] or None
        return DataFrame(L.Repartition(num, self._plan, by), self.session)

    def coalesce(self, num: int) -> "DataFrame":
        return DataFrame(L.Repartition(num, self._plan, None), self.session)

    def with_window_pandas(self, alias: str, fn, cols, out_dtype,
                           partition_by=None) -> "DataFrame":
        """Pandas aggregate UDF over an UNBOUNDED window partition:
        every row receives ``fn(series...)`` computed over its whole
        partition (GpuWindowInPandasExec role; bounded frames are not
        lowered yet)."""
        pb = [_to_expr(c, self.schema) for c in (partition_by or [])]
        cols = [c if isinstance(c, str) else c.expr.col_name
                for c in cols]
        if isinstance(out_dtype, str):
            out_dtype = Schema.from_ddl(f"x {out_dtype}").fields[0].dtype
        return DataFrame(
            L.WindowInPandas(alias, fn, cols, out_dtype, pb, self._plan),
            self.session)

    def with_window(self, alias: str, func, partition_by=None,
                    order_by=None, frame=("rows", None, 0)) -> "DataFrame":
        """Add a window-function column (functions.window helpers)."""
        pb = [_to_expr(c, self.schema) for c in (partition_by or [])]
        ob = []
        for c in (order_by or []):
            if isinstance(c, L.SortOrder):
                ob.append(L.SortOrder(_resolve(c.expr, self.schema),
                                      c.ascending, c.nulls_first))
            else:
                ob.append(L.SortOrder(_to_expr(c, self.schema)))
        f = func.expr if isinstance(func, Col) else func
        f = _resolve(f, self.schema)
        spec = L.WindowSpec(pb, ob, frame)
        wf = L.WindowFunc(f, spec, alias)
        return DataFrame(L.Window([wf], self._plan), self.session)

    # -- actions -------------------------------------------------------------
    def collect(self) -> List[tuple]:
        t = self.session.execute_to_arrow(self._plan)
        # the executed physical plan, for tests/tools inspecting runtime
        # decisions (AQE strategies, fallbacks)
        self._last_physical_plan = self.session.last_physical_plan
        cols = [t.column(i).to_pylist() for i in range(t.num_columns)]
        return list(zip(*cols)) if cols else []

    def to_arrow(self) -> pa.Table:
        return self.session.execute_to_arrow(self._plan)

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    toPandas = to_pandas

    def count(self) -> int:
        agg = L.Aggregate([], [L.AggExpr(eagg.Count(), "count")], self._plan)
        t = self.session.execute_to_arrow(agg)
        return t.column(0)[0].as_py()

    def show(self, n: int = 20):
        t = self.limit(n).to_arrow()
        print(t.to_pandas().to_string())

    def explain(self, extended: bool = False):
        print(self.session.explain(self._plan))

    def first(self):
        rows = self.limit(1).collect()
        return rows[0] if rows else None

    def head(self, n: int = 1):
        return self.limit(n).collect()

    def take(self, n: int):
        return self.limit(n).collect()

    @property
    def write(self):
        from .reader import DataFrameWriter
        return DataFrameWriter(self)

    def create_or_replace_temp_view(self, name: str) -> None:
        self.session.register_table(name, self)

    createOrReplaceTempView = create_or_replace_temp_view

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """fn(Iterator[pd.DataFrame]) -> Iterator[pd.DataFrame].

        Reference: GpuMapInPandasExec (SURVEY.md §2.4 Python execs)."""
        return DataFrame(
            L.MapInPandas(fn, _as_out_schema(schema), self._plan),
            self.session)

    mapInPandas = map_in_pandas

    def to_device_batches(self):
        """Zero-copy export of device ColumnarBatches for ML libraries.

        Reference: ColumnarRdd.scala:41 / InternalColumnarRddConverter —
        the ml-integration handoff that gives XGBoost the raw device
        tables.  Here the consumer gets jax arrays already resident on
        device; no host round-trip.
        """
        phys = self.session._plan(self._plan)
        from ..columnar.arrow import from_arrow
        import pyarrow as pa
        batches = []
        for part in phys.execute():
            for item in part:
                b = from_arrow(item) if isinstance(item, pa.Table) else item
                if b.num_rows:
                    batches.append(b)
        return batches

    def to_jax(self):
        """Collect numeric columns as a dict of dense jax arrays

        (validity-masked rows dropped), ready for jit-ted ML training —
        the XGBoost-style consumption path of to_device_batches."""
        import jax.numpy as jnp
        from ..columnar.batch import concat_batches
        batches = self.to_device_batches()
        if not batches:
            return {}
        b = concat_batches(batches) if len(batches) > 1 else batches[0]
        out = {}
        for f, c in zip(b.schema, b.columns):
            if f.dtype.np_dtype is None:
                continue
            out[f.name] = c.data[:b.num_rows]
        return out

def _extract_equi_keys(cond: ec.Expression, lschema: Schema,
                       rschema: Schema):
    """Split a join condition into equi-key pairs + residual."""
    from ..expr import predicates as ep
    conjuncts: List[ec.Expression] = []

    def flatten(e):
        if isinstance(e, ep.And):
            flatten(e.children[0])
            flatten(e.children[1])
        else:
            conjuncts.append(e)
    flatten(cond)
    lkeys, rkeys, residual = [], [], []
    lnames = set(lschema.names)
    rnames = set(rschema.names)
    for c in conjuncts:
        if isinstance(c, ep.EqualTo):
            a, b = c.children
            an = _ref_names(a)
            bn = _ref_names(b)
            if an and bn and an <= lnames and bn <= rnames:
                lkeys.append(_resolve(a, lschema))
                rkeys.append(_resolve(b, rschema))
                continue
            if an and bn and an <= rnames and bn <= lnames:
                lkeys.append(_resolve(b, lschema))
                rkeys.append(_resolve(a, rschema))
                continue
        residual.append(c)
    res: Optional[ec.Expression] = None
    for r in residual:
        res = r if res is None else ep.And(res, r)
    return lkeys, rkeys, res


def _ref_names(e: ec.Expression) -> set:
    return {x.col_name for x in e.collect(
        lambda n: isinstance(n, ec.AttributeReference))}


class GroupedData:
    def __init__(self, df: DataFrame, keys: List[ec.Expression],
                 grouping_sets=None):
        self.df = df
        self.keys = keys
        self.grouping_sets = grouping_sets

    def cogroup(self, other: "GroupedData") -> "CogroupedData":
        """Spark's ``df1.groupBy(k).cogroup(df2.groupBy(k))``: pairs of
        key groups from both sides feed one pandas fn
        (GpuFlatMapCoGroupsInPandasExec role)."""
        return CogroupedData(self, other)

    def pivot(self, pivot_col, values) -> "PivotedData":
        """Spark's ``groupBy(...).pivot(col, values).agg(f(x))``.

        Lowered the way the reference's PivotFirst ultimately evaluates
        (AggregateFunctions.scala PivotFirst): one conditional aggregate
        per pivot value — ``f(when(col == v, x)) AS v`` — which runs on
        the existing device aggregation paths with no new kernel.
        Explicit ``values`` are required (the reference's implicit mode
        runs a distinct query first; pass that yourself)."""
        return PivotedData(self, _to_expr(pivot_col, self.df.schema),
                           list(values))

    def agg(self, *aggs, **named) -> DataFrame:
        from ..udf.python_udf import PandasAggUDFExpr
        agg_exprs: List[L.AggExpr] = []
        schema = self.df.schema
        pandas_aggs: List[tuple] = []
        for a in aggs:
            e = a.expr if isinstance(a, Col) else a
            alias = None
            if isinstance(e, ec.Alias):
                alias = e.alias
                e = e.children[0]
            e = _resolve(e, schema)
            if isinstance(e, PandasAggUDFExpr):
                pandas_aggs.append((alias or e.name, e))
                continue
            assert isinstance(e, eagg.AggregateFunction), \
                f"agg() requires aggregate functions, got {e!r}"
            agg_exprs.append(L.AggExpr(e, alias or repr(e),
                                       distinct=getattr(e, "_distinct",
                                                        False)))
        if pandas_aggs:
            assert not agg_exprs and not named, \
                "pandas grouped-agg UDFs cannot mix with builtin aggregates"
            return self._agg_pandas(pandas_aggs)
        if self.grouping_sets is not None:
            named_exprs = []
            for alias, a in named.items():
                e = a.expr if isinstance(a, Col) else a
                if isinstance(e, ec.Alias):
                    e = e.children[0]
                e = _resolve(e, schema)
                named_exprs.append(L.AggExpr(
                    e, alias, distinct=getattr(e, "_distinct", False)))
            return DataFrame(
                L.build_grouping_sets(self.keys, self.grouping_sets,
                                      agg_exprs + named_exprs,
                                      self.df._plan),
                self.df.session)
        for alias, a in named.items():
            e = a.expr if isinstance(a, Col) else a
            if isinstance(e, ec.Alias):
                e = e.children[0]
            e = _resolve(e, schema)
            agg_exprs.append(L.AggExpr(e, alias,
                                       distinct=getattr(e, "_distinct",
                                                        False)))
        return DataFrame(
            L.build_aggregate(self.keys, agg_exprs, self.df._plan),
            self.df.session)

    def count(self) -> DataFrame:
        return self.agg(count=Col(eagg.Count()))

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """fn(pdf) -> pdf (or fn(key_tuple, pdf) -> pdf) per key group.

        Reference: GpuFlatMapGroupsInPandasExec (SURVEY.md §2.8)."""
        return DataFrame(
            L.GroupedMapInPandas(self.keys, fn, _as_out_schema(schema),
                                 self.df._plan),
            self.df.session)

    applyInPandas = apply_in_pandas
    apply = apply_in_pandas

    def _agg_pandas(self, pandas_aggs) -> DataFrame:
        """GROUPED_AGG pandas UDFs, routed through applyInPandas: the
        generated group fn emits one row of keys + aggregated values.

        Reference: GpuAggregateInPandasExec."""
        from ..columnar.schema import Field, Schema
        from ..expr.core import output_name
        key_fields = []
        key_names = []
        for k in self.keys:
            assert isinstance(k, ec.AttributeReference), \
                "pandas grouped-agg requires plain column group keys"
            key_fields.append(Field(k.col_name, k.dtype(), k.nullable))
            key_names.append(k.col_name)
        out_fields = list(key_fields)
        specs = []
        for alias, e in pandas_aggs:
            for c in e.children:
                assert isinstance(c, ec.AttributeReference), \
                    "pandas grouped-agg arguments must be plain columns"
            specs.append((alias, e.fn,
                          [c.col_name for c in e.children]))
            out_fields.append(Field(alias, e.return_type, True))

        def grouped_agg(key, pdf):
            import pandas as pd
            row = {n: [v] for n, v in zip(key_names, key)}
            for alias, fn, argcols in specs:
                row[alias] = [fn(*[pdf[c] for c in argcols])]
            return pd.DataFrame(row)

        return DataFrame(
            L.GroupedMapInPandas(self.keys, grouped_agg,
                                 Schema(out_fields), self.df._plan),
            self.df.session)

    def _simple(self, fn, cols) -> DataFrame:
        schema = self.df.schema
        targets = cols or [f.name for f in schema if f.dtype.is_numeric]
        aggs = []
        for c in targets:
            e = _to_expr(c, schema)
            aggs.append(Col(ec.Alias(fn(e), f"{fn.__name__.lower()}({c})")))
        return self.agg(*aggs)

    def sum(self, *cols) -> DataFrame:
        return self._simple(eagg.Sum, cols)

    def min(self, *cols) -> DataFrame:
        return self._simple(eagg.Min, cols)

    def max(self, *cols) -> DataFrame:
        return self._simple(eagg.Max, cols)

    def avg(self, *cols) -> DataFrame:
        return self._simple(eagg.Average, cols)

    mean = avg


class CogroupedData:
    """groupBy().cogroup(groupBy()) — applyInPandas over key pairs."""

    def __init__(self, left: GroupedData, right: GroupedData):
        if len(left.keys) != len(right.keys):
            raise ValueError(
                "cogroup requires the same number of grouping keys "
                f"({len(left.keys)} vs {len(right.keys)})")
        self.left = left
        self.right = right

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        plan = L.CogroupedMapInPandas(
            self.left.keys, self.right.keys, fn, _as_out_schema(schema),
            self.left.df._plan, self.right.df._plan)
        return DataFrame(plan, self.left.df.session)

    applyInPandas = apply_in_pandas


class PivotedData:
    """groupBy().pivot(col, values) — rewrites agg() into one
    conditional aggregate per pivot value (the PivotFirst lowering)."""

    def __init__(self, grouped: GroupedData, pivot_expr: ec.Expression,
                 values: list):
        self.grouped = grouped
        self.pivot_expr = pivot_expr
        self.values = values

    def agg(self, *aggs) -> DataFrame:
        from ..expr import conditional as econd
        from ..expr import predicates as epred
        schema = self.grouped.df.schema
        specs = []
        for a in aggs:
            e = a.expr if isinstance(a, Col) else a
            alias = None
            if isinstance(e, ec.Alias):
                alias = e.alias
                e = e.children[0]
            e = _resolve(e, schema)
            assert isinstance(e, eagg.AggregateFunction), \
                f"pivot().agg() requires aggregate functions, got {e!r}"
            specs.append((alias, e))
        out = []
        for v in self.values:
            cond = epred.EqualTo(self.pivot_expr, ec.Literal(v))
            for alias, f in specs:
                child = f.children[0] if f.children else ec.Literal(1)
                guarded = econd.CaseWhen([(cond, child)], None)
                nf = f.with_children([guarded])
                name = str(v) if len(specs) == 1 else \
                    f"{v}_{alias or f.name.lower()}"
                out.append(Col(ec.Alias(nf, name)))
        return self.grouped.agg(*out)

    def first(self, col) -> DataFrame:
        """pivot_first shape: first(value) per pivot value."""
        return self.agg(Col(eagg.First(_to_expr(col,
                                                self.grouped.df.schema))))
