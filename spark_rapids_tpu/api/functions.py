"""functions — PySpark-style functions module (col, lit, sum, when, ...)."""
from __future__ import annotations

from typing import Optional

from ..columnar import dtypes as T
from ..expr import core as ec
from ..expr import (aggregates as eagg, arithmetic as ea, cast as ecast,
                    conditional as econd, datetime as edt, misc as emisc,
                    predicates as ep, string_ops as es, window_funcs as wf)
from .column import Col, _expr


def col(name: str) -> Col:
    return Col(ec.AttributeReference(name))


column = col


def lit(v) -> Col:
    return Col(ec.Literal(v)) if not isinstance(v, Col) else v


def expr_col(e: ec.Expression) -> Col:
    return Col(e)


# -- aggregates ---------------------------------------------------------------

def sum(c) -> Col:  # noqa: A001
    return Col(eagg.Sum(_expr(c if not isinstance(c, str) else col(c))))


def count(c="*") -> Col:
    if c == "*":
        return Col(eagg.Count())
    return Col(eagg.Count(_expr(c if not isinstance(c, str) else col(c))))


def min(c) -> Col:  # noqa: A001
    return Col(eagg.Min(_expr(c if not isinstance(c, str) else col(c))))


def max(c) -> Col:  # noqa: A001
    return Col(eagg.Max(_expr(c if not isinstance(c, str) else col(c))))


def avg(c) -> Col:
    return Col(eagg.Average(_expr(c if not isinstance(c, str) else col(c))))


mean = avg


def first(c, ignore_nulls: bool = True) -> Col:
    return Col(eagg.First(_expr(c if not isinstance(c, str) else col(c)),
                          ignore_nulls))


def last(c, ignore_nulls: bool = True) -> Col:
    return Col(eagg.Last(_expr(c if not isinstance(c, str) else col(c)),
                         ignore_nulls))


def stddev(c) -> Col:
    return Col(eagg.StddevSamp(_expr(c if not isinstance(c, str)
                                     else col(c))))


stddev_samp = stddev


def stddev_pop(c) -> Col:
    return Col(eagg.StddevPop(_expr(c if not isinstance(c, str)
                                    else col(c))))


def variance(c) -> Col:
    return Col(eagg.VarianceSamp(_expr(c if not isinstance(c, str)
                                       else col(c))))


var_samp = variance


def var_pop(c) -> Col:
    return Col(eagg.VariancePop(_expr(c if not isinstance(c, str)
                                      else col(c))))


def collect_list(c) -> Col:
    return Col(eagg.CollectList(_expr(c if not isinstance(c, str)
                                      else col(c))))


def collect_set(c) -> Col:
    return Col(eagg.CollectSet(_expr(c if not isinstance(c, str)
                                     else col(c))))


def count_distinct(c) -> Col:
    e = eagg.Count(_expr(c if not isinstance(c, str) else col(c)))
    e._distinct = True
    return Col(e)


def sum_distinct(c) -> Col:
    e = eagg.Sum(_expr(c if not isinstance(c, str) else col(c)))
    e._distinct = True
    return Col(e)


countDistinct = count_distinct


# -- conditional --------------------------------------------------------------

class WhenBuilder(Col):
    def __init__(self, branches):
        self._branches = branches
        super().__init__(econd.CaseWhen(branches, None))

    def when(self, cond, value) -> "WhenBuilder":
        return WhenBuilder(self._branches + [(_expr(cond), _expr(value))])

    def otherwise(self, value) -> Col:
        return Col(econd.CaseWhen(self._branches, _expr(value)))


def when(cond, value) -> WhenBuilder:
    return WhenBuilder([(_expr(cond), _expr(value))])


def coalesce(*cols) -> Col:
    return Col(econd.Coalesce(*[_expr(c if not isinstance(c, str)
                                      else col(c)) for c in cols]))


def isnull(c) -> Col:
    return Col(ep.IsNull(_expr(c if not isinstance(c, str) else col(c))))


def isnan(c) -> Col:
    return Col(ep.IsNaN(_expr(c if not isinstance(c, str) else col(c))))


def nanvl(a, b) -> Col:
    return Col(econd.NaNvl(_expr(a), _expr(b)))


# -- math ---------------------------------------------------------------------

def _u(cls):
    def f(c):
        return Col(cls(_expr(c if not isinstance(c, str) else col(c))))
    f.__name__ = cls.__name__.lower()
    return f


sqrt = _u(ea.Sqrt)
exp = _u(ea.Exp)
log = _u(ea.Log)
log2 = _u(ea.Log2)
log10 = _u(ea.Log10)
sin = _u(ea.Sin)
cos = _u(ea.Cos)
tan = _u(ea.Tan)
asin = _u(ea.Asin)
acos = _u(ea.Acos)
atan = _u(ea.Atan)
floor = _u(ea.Floor)
ceil = _u(ea.Ceil)
abs = _u(ea.Abs)  # noqa: A001
signum = _u(ea.Signum)
degrees = _u(ea.ToDegrees)
radians = _u(ea.ToRadians)


def round(c, scale: int = 0) -> Col:  # noqa: A001
    return Col(ea.Round(_expr(c if not isinstance(c, str) else col(c)),
                        scale))


def pow(a, b) -> Col:  # noqa: A001
    return Col(ea.Pow(_expr(a), _expr(b)))


def greatest(*cols) -> Col:
    return Col(ea.Greatest(*[_expr(c if not isinstance(c, str) else col(c))
                             for c in cols]))


def least(*cols) -> Col:
    return Col(ea.Least(*[_expr(c if not isinstance(c, str) else col(c))
                          for c in cols]))


# -- strings ------------------------------------------------------------------

upper = _u(es.Upper)
lower = _u(es.Lower)
length = _u(es.Length)
trim = _u(es.StringTrim)
ltrim = _u(es.StringTrimLeft)
rtrim = _u(es.StringTrimRight)


def substring(c, pos: int, length_: int) -> Col:
    return Col(es.Substring(_expr(c if not isinstance(c, str) else col(c)),
                            ec.Literal(pos), ec.Literal(length_)))


def concat(*cols) -> Col:
    return Col(es.ConcatStrings(
        *[_expr(c if not isinstance(c, str) else col(c)) for c in cols]))


def replace(c, search: str, rep: str) -> Col:
    return Col(es.Replace(_expr(c if not isinstance(c, str) else col(c)),
                          ec.Literal(search), ec.Literal(rep)))


def reverse(c) -> Col:
    return Col(es.Reverse(_expr(c if not isinstance(c, str) else col(c))))


def repeat(c, n: int) -> Col:
    return Col(es.StringRepeat(_expr(c if not isinstance(c, str)
                                     else col(c)), ec.Literal(n)))


def lpad(c, n: int, pad: str = " ") -> Col:
    return Col(es.Lpad(_expr(c if not isinstance(c, str) else col(c)),
                       ec.Literal(n), ec.Literal(pad)))


def rpad(c, n: int, pad: str = " ") -> Col:
    return Col(es.Rpad(_expr(c if not isinstance(c, str) else col(c)),
                       ec.Literal(n), ec.Literal(pad)))


def initcap(c) -> Col:
    return Col(es.InitCap(_expr(c if not isinstance(c, str) else col(c))))


def instr(c, substr: str) -> Col:
    return Col(es.StringLocate(ec.Literal(substr),
                               _expr(c if not isinstance(c, str)
                                     else col(c))))


def locate(substr: str, c, pos: int = 1) -> Col:
    return instr(c, substr)


def concat_ws(sep: str, *cols) -> Col:
    return Col(es.ConcatWs(sep, *[_expr(c if not isinstance(c, str)
                                        else col(c)) for c in cols]))


def regexp_replace(c, pattern: str, rep: str) -> Col:
    return Col(es.RegexpReplace(_expr(c if not isinstance(c, str)
                                      else col(c)),
                                ec.Literal(pattern), ec.Literal(rep)))


def regexp_extract(c, pattern: str, group: int = 1) -> Col:
    return Col(es.RegexpExtract(_expr(c if not isinstance(c, str)
                                      else col(c)),
                                ec.Literal(pattern), group))


def md5(c) -> Col:
    return Col(emisc.Md5(_expr(c if not isinstance(c, str) else col(c))))


# -- datetime -----------------------------------------------------------------

year = _u(edt.Year)
month = _u(edt.Month)
dayofmonth = _u(edt.DayOfMonth)
quarter = _u(edt.Quarter)
dayofweek = _u(edt.DayOfWeek)
weekday = _u(edt.WeekDay)
dayofyear = _u(edt.DayOfYear)
hour = _u(edt.Hour)
minute = _u(edt.Minute)
second = _u(edt.Second)
last_day = _u(edt.LastDay)
to_date = _u(edt.ToDate)


def date_add(c, days: int) -> Col:
    return Col(edt.DateAdd(_expr(c if not isinstance(c, str) else col(c)),
                           _expr(days)))


def date_sub(c, days: int) -> Col:
    return Col(edt.DateSub(_expr(c if not isinstance(c, str) else col(c)),
                           _expr(days)))


def datediff(end, start) -> Col:
    return Col(edt.DateDiff(_expr(end if not isinstance(end, str)
                                  else col(end)),
                            _expr(start if not isinstance(start, str)
                                  else col(start))))


# -- misc ---------------------------------------------------------------------

def hash(*cols) -> Col:  # noqa: A001
    return Col(emisc.Murmur3Hash(
        *[_expr(c if not isinstance(c, str) else col(c)) for c in cols]))


def monotonically_increasing_id() -> Col:
    return Col(emisc.MonotonicallyIncreasingID())


def spark_partition_id() -> Col:
    return Col(emisc.SparkPartitionID())


def rand(seed: int = 0) -> Col:
    return Col(emisc.Rand(seed))


# -- window functions ---------------------------------------------------------

def row_number() -> Col:
    return Col(wf.RowNumber())


def rank() -> Col:
    return Col(wf.Rank())


def dense_rank() -> Col:
    return Col(wf.DenseRank())


def ntile(n: int) -> Col:
    return Col(wf.NTile(n))


def percent_rank() -> Col:
    return Col(wf.PercentRank())


def cume_dist() -> Col:
    return Col(wf.CumeDist())


def lead(c, offset: int = 1) -> Col:
    return Col(wf.Lead(_expr(c if not isinstance(c, str) else col(c)),
                       offset))


def lag(c, offset: int = 1) -> Col:
    return Col(wf.Lag(_expr(c if not isinstance(c, str) else col(c)),
                      offset))


# -- collections (collectionOperations.scala role) ----------------------------

def _c(c):
    return _expr(col(c) if isinstance(c, str) else c)


def array(*cols) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.CreateArray(*[_c(c) for c in cols]))


def size(c) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.Size(_c(c)))


def element_at(c, index) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.ElementAt(_c(c), _expr(index)))


def array_contains(c, value) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.ArrayContains(_c(c), _expr(value)))


def sort_array(c, asc: bool = True) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.SortArray(_c(c), asc))


def array_min(c) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.ArrayMin(_c(c)))


def array_max(c) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.ArrayMax(_c(c)))


def explode(c) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.Explode(_c(c)))


def explode_outer(c) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.Explode(_c(c), outer=True))


def posexplode(c) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.Explode(_c(c), pos=True))


def posexplode_outer(c) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.Explode(_c(c), pos=True, outer=True))


def struct(*cols) -> Col:
    from ..expr import collections as ecoll
    from ..expr.core import output_name
    exprs = [_c(c) for c in cols]
    names = [c if isinstance(c, str) else output_name(e)
             for c, e in zip(cols, exprs)]
    return Col(ecoll.CreateNamedStruct(names, *exprs))


def named_struct(*name_col_pairs) -> Col:
    from ..expr import collections as ecoll
    if len(name_col_pairs) % 2:
        raise ValueError("named_struct expects name/value pairs")
    names = [str(n) for n in name_col_pairs[0::2]]
    exprs = [_c(c) for c in name_col_pairs[1::2]]
    return Col(ecoll.CreateNamedStruct(names, *exprs))


def create_map(*cols) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.CreateMap(*[_c(c) for c in cols]))


def map_keys(c) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.MapKeys(_c(c)))


def map_values(c) -> Col:
    from ..expr import collections as ecoll
    return Col(ecoll.MapValues(_c(c)))


def pmod(a, b) -> Col:
    return Col(ea.Pmod(_c(a), _expr(b)))
