"""User-facing API: TpuSession, DataFrame, Col, functions."""
from .session import TpuSession  # noqa: F401
from .dataframe import DataFrame  # noqa: F401
from .column import Col  # noqa: F401
from . import functions  # noqa: F401
