"""SQL front end: text -> AST -> logical plan.

Role note: the reference rides on Spark's SQL parser/analyzer and only
rewrites *physical* plans (SURVEY.md §1: "Everything else ... SQL parser,
optimizer ... is stock Spark").  Standalone, this module supplies that
front end: a hand-written lexer + recursive-descent/Pratt parser for the
SQL dialect the reference's integration tests exercise
(qa_nightly_select_test.py-style SELECTs, TPC-H/TPC-DS query shapes),
lowered onto the same logical IR the DataFrame API builds
(plan/logical.py), so both surfaces share one planner and both engines.

Supported: WITH (CTEs), SELECT [DISTINCT], expressions (arithmetic,
comparison, AND/OR/NOT, BETWEEN, IN (list | subquery), EXISTS, LIKE,
IS [NOT] NULL, CASE, CAST, ||, scalar subqueries), FROM with table
refs / subqueries / comma cross joins / explicit JOIN ... ON,
GROUP BY (exprs, ordinals, aliases) + HAVING, window functions with
OVER (PARTITION BY / ORDER BY / ROWS|RANGE frames), ORDER BY
(exprs, ordinals, aliases), LIMIT/OFFSET, UNION [ALL], INTERSECT, EXCEPT,
DATE/TIMESTAMP/INTERVAL literals.
"""
from __future__ import annotations

import dataclasses
import datetime as _dt
import re
from typing import Any, List, Optional, Tuple

from ..columnar import dtypes as T
from ..columnar.schema import Field, Schema
from ..expr import aggregates as eagg
from ..expr import arithmetic as ea
from ..expr import cast as ecast
from ..expr import conditional as econd
from ..expr import core as ec
from ..expr import datetime as edt
from ..expr import misc as emisc
from ..expr import predicates as ep
from ..expr import string_ops as es
from ..expr import window_funcs as ewin
from ..plan import logical as L


class SqlError(Exception):
    pass


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|--[^\n]*\n?|/\*.*?\*/)
  | (?P<num>(\d+\.\d*|\.\d+|\d+)([eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<qid>"[^"]*"|`[^`]*`)
  | (?P<id>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><>|!=|>=|<=|\|\||[(),.*+\-/%<>=])
""", re.VERBOSE | re.DOTALL)


@dataclasses.dataclass
class Tok:
    kind: str      # num | str | id | qid | op | end
    text: str
    pos: int


def _lex(sql: str) -> List[Tok]:
    out: List[Tok] = []
    i = 0
    while i < len(sql):
        m = _TOKEN_RE.match(sql, i)
        if not m:
            raise SqlError(f"unexpected character {sql[i]!r} at {i}")
        i = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append(Tok(kind, m.group(), m.start()))
    out.append(Tok("end", "", len(sql)))
    return out


# ---------------------------------------------------------------------------
# AST (tuples everywhere so nodes compare structurally with ==)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Ast:
    pass


@dataclasses.dataclass(frozen=True)
class Lit(Ast):
    value: Any


@dataclasses.dataclass(frozen=True)
class Interval(Ast):
    n: int
    unit: str  # day | month | year


@dataclasses.dataclass(frozen=True)
class Ident(Ast):
    parts: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class Star(Ast):
    table: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class Res(Ast):
    """A reference already resolved to an ACTUAL column name in the
    current plan's schema (produced by lowering, never by the parser)."""
    cname: str


@dataclasses.dataclass(frozen=True)
class Func(Ast):
    fname: str
    args: Tuple[Ast, ...]
    distinct: bool = False


@dataclasses.dataclass(frozen=True)
class Bin(Ast):
    op: str
    left: Ast
    right: Ast


@dataclasses.dataclass(frozen=True)
class Un(Ast):
    op: str
    operand: Ast


@dataclasses.dataclass(frozen=True)
class Between(Ast):
    operand: Ast
    lo: Ast
    hi: Ast
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InList(Ast):
    operand: Ast
    items: Tuple[Ast, ...]
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InSub(Ast):
    operand: Ast
    query: "SelectStmt"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Exists(Ast):
    query: "SelectStmt"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class ScalarSub(Ast):
    query: "SelectStmt"


@dataclasses.dataclass(frozen=True)
class LikeE(Ast):
    operand: Ast
    pattern: str
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class IsNullE(Ast):
    operand: Ast
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Case(Ast):
    operand: Optional[Ast]
    whens: Tuple[Tuple[Ast, Ast], ...]
    els: Optional[Ast]


@dataclasses.dataclass(frozen=True)
class CastE(Ast):
    operand: Ast
    typename: str
    p1: Optional[int] = None
    p2: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class OrderItem(Ast):
    e: Ast
    asc: bool = True
    nulls_first: Optional[bool] = None


@dataclasses.dataclass(frozen=True)
class WindowE(Ast):
    func: Func
    partition: Tuple[Ast, ...]
    order: Tuple[OrderItem, ...]
    # (kind, lo, hi): None = unbounded; ints relative to current row
    frame: Optional[Tuple[str, Optional[int], Optional[int]]] = None


@dataclasses.dataclass(frozen=True)
class SelectItem(Ast):
    e: Ast
    alias: Optional[str]


@dataclasses.dataclass(frozen=True)
class TableRef(Ast):
    tname: str
    alias: Optional[str]


@dataclasses.dataclass(frozen=True)
class SubqueryRef(Ast):
    query: "SelectStmt"
    alias: str


@dataclasses.dataclass(frozen=True)
class JoinItem(Ast):
    left: Ast
    right: Ast
    how: str                     # inner|left|right|full|cross
    on: Optional[Ast]


@dataclasses.dataclass(frozen=True)
class SelectStmt(Ast):
    ctes: Tuple[Tuple[str, "SelectStmt"], ...]
    distinct: bool
    items: Tuple[SelectItem, ...]
    from_item: Optional[Ast]
    where: Optional[Ast]
    group_by: Tuple[Ast, ...]
    having: Optional[Ast]
    order_by: Tuple[OrderItem, ...]
    limit: Optional[int]
    offset: Optional[int]
    parenthesized: bool = False
    # ROLLUP/CUBE/GROUPING SETS: tuples of subsets of group_by idents
    group_sets: Optional[Tuple[Tuple[Ast, ...], ...]] = None


@dataclasses.dataclass(frozen=True)
class SetOp(Ast):
    op: str                      # union|intersect|except
    all: bool
    left: Ast
    right: Ast
    order_by: Tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    offset: Optional[int] = None


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "is", "null", "like",
    "between", "case", "when", "then", "else", "end", "cast", "distinct",
    "join", "inner", "left", "right", "full", "outer", "cross", "on",
    "union", "all", "intersect", "except", "exists", "with", "asc", "desc",
    "nulls", "first", "last", "true", "false", "over", "partition", "rows",
    "range", "unbounded", "preceding", "following", "current", "row",
    "interval", "date", "timestamp", "semi", "anti",
}

_AGG_FUNCS = {"sum", "count", "min", "max", "avg", "mean", "first", "last",
              "first_value", "last_value", "collect_list", "collect_set",
              "count_distinct", "stddev", "stddev_samp", "std",
              "stddev_pop", "variance", "var_samp", "var_pop"}
_WINDOW_ONLY_FUNCS = {"row_number", "rank", "dense_rank", "ntile", "lead",
                      "lag", "percent_rank", "cume_dist"}


class _Parser:
    def __init__(self, sql: str):
        self.toks = _lex(sql)
        self.i = 0

    # -- token helpers ------------------------------------------------------
    def peek(self, ahead: int = 0) -> Tok:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "id" and t.text.lower() in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str):
        if not self.eat_kw(kw):
            raise SqlError(
                f"expected {kw.upper()} at {self.peek().pos}, "
                f"got {self.peek().text!r}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.text in ops

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str):
        if not self.eat_op(op):
            raise SqlError(
                f"expected {op!r} at {self.peek().pos}, "
                f"got {self.peek().text!r}")

    def ident(self) -> str:
        t = self.peek()
        if t.kind == "id":
            if t.text.lower() in _KEYWORDS:
                raise SqlError(f"unexpected keyword {t.text!r} at {t.pos}")
            self.next()
            return t.text
        if t.kind == "qid":
            self.next()
            return t.text[1:-1]
        raise SqlError(f"expected identifier at {t.pos}, got {t.text!r}")

    # -- statements ---------------------------------------------------------
    def parse(self) -> Ast:
        stmt = self.query_expr()
        if self.peek().kind != "end":
            raise SqlError(
                f"trailing input at {self.peek().pos}: {self.peek().text!r}")
        return stmt

    def query_expr(self) -> Ast:
        """select ((UNION [ALL] | INTERSECT | EXCEPT) select)* with an
        optional trailing ORDER BY/LIMIT owned by the whole set-op."""
        left = self.query_term()
        while self.at_kw("union", "intersect", "except"):
            op = self.next().text.lower()
            is_all = self.eat_kw("all")
            right = self.query_term()
            left = SetOp(op, is_all, left, right)
        if isinstance(left, SetOp):
            order = ()
            limit = offset = None
            if self.eat_kw("order"):
                self.expect_kw("by")
                order = tuple(self.order_items())
            if self.eat_kw("limit"):
                limit = int(self.next().text)
            if self.eat_kw("offset"):
                offset = int(self.next().text)
            # an unparenthesized final SELECT grabs the trailing ORDER
            # BY/LIMIT/OFFSET during its own parse; grammatically they
            # belong to the whole set operation — hoist them
            if not order and limit is None and offset is None:
                rb = left.right
                if isinstance(rb, SelectStmt) and not rb.parenthesized \
                        and (rb.order_by or rb.limit is not None or
                             rb.offset is not None):
                    order = rb.order_by
                    limit = rb.limit
                    offset = rb.offset
                    left = dataclasses.replace(
                        left, right=dataclasses.replace(
                            rb, order_by=(), limit=None, offset=None))
            left = dataclasses.replace(left, order_by=order, limit=limit,
                                       offset=offset)
        return left

    def query_term(self) -> Ast:
        if self.eat_op("("):
            q = self.query_expr()
            self.expect_op(")")
            if isinstance(q, SelectStmt):
                # remember the parens: a trailing ORDER BY/LIMIT inside
                # them belongs to this branch, not the enclosing set op
                q = dataclasses.replace(q, parenthesized=True)
            return q
        return self.select_stmt()

    def select_stmt(self) -> SelectStmt:
        ctes: List[Tuple[str, SelectStmt]] = []
        if self.eat_kw("with"):
            while True:
                name = self.ident()
                self.expect_kw("as")
                self.expect_op("(")
                sub = self.query_expr()
                self.expect_op(")")
                ctes.append((name, sub))
                if not self.eat_op(","):
                    break
        self.expect_kw("select")
        distinct = self.eat_kw("distinct")
        self.eat_kw("all")
        items = [self.select_item()]
        while self.eat_op(","):
            items.append(self.select_item())
        from_item = None
        if self.eat_kw("from"):
            from_item = self.from_clause()
        where = self.expr() if self.eat_kw("where") else None
        group_by: List[Ast] = []
        group_sets = None
        if self.eat_kw("group"):
            self.expect_kw("by")
            low = self.peek().text.lower()
            if self.peek().kind == "id" and low in ("rollup", "cube") and \
                    self.peek(1).text == "(":
                self.next()
                self.expect_op("(")
                cols = [self.expr()]
                while self.eat_op(","):
                    cols.append(self.expr())
                self.expect_op(")")
                group_by = cols
                from ..plan.logical import cube_sets, rollup_sets
                mk = rollup_sets if low == "rollup" else cube_sets
                group_sets = tuple(tuple(cols[i] for i in t)
                                   for t in mk(list(range(len(cols)))))
            elif self.peek().kind == "id" and low == "grouping" and \
                    self.peek(1).text.lower() == "sets":
                self.next()
                self.next()
                self.expect_op("(")
                sets = []
                cols_seen: List[Ast] = []
                while True:
                    if self.eat_op("("):
                        one = []
                        if not self.at_op(")"):
                            one.append(self.expr())
                            while self.eat_op(","):
                                one.append(self.expr())
                        self.expect_op(")")
                    else:
                        one = [self.expr()]   # bare member: SETS (k, ())
                    sets.append(tuple(one))
                    for c in one:
                        if c not in cols_seen:
                            cols_seen.append(c)
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
                group_by = cols_seen
                group_sets = tuple(sets)
            else:
                group_by.append(self.expr())
                while self.eat_op(","):
                    group_by.append(self.expr())
        having = self.expr() if self.eat_kw("having") else None
        order_by: List[OrderItem] = []
        if self.eat_kw("order"):
            self.expect_kw("by")
            order_by = self.order_items()
        limit = offset = None
        if self.eat_kw("limit"):
            limit = int(self.next().text)
        if self.eat_kw("offset"):
            offset = int(self.next().text)
        return SelectStmt(tuple(ctes), distinct, tuple(items), from_item,
                          where, tuple(group_by), having, tuple(order_by),
                          limit, offset, group_sets=group_sets)

    def order_items(self) -> List[OrderItem]:
        out = [self.order_item()]
        while self.eat_op(","):
            out.append(self.order_item())
        return out

    def order_item(self) -> OrderItem:
        e = self.expr()
        asc = True
        if self.eat_kw("desc"):
            asc = False
        else:
            self.eat_kw("asc")
        nulls_first = None
        if self.eat_kw("nulls"):
            if self.eat_kw("first"):
                nulls_first = True
            else:
                self.expect_kw("last")
                nulls_first = False
        return OrderItem(e, asc, nulls_first)

    def select_item(self) -> SelectItem:
        if self.at_op("*"):
            self.next()
            return SelectItem(Star(), None)
        # t.*
        if (self.peek().kind in ("id", "qid") and
                self.peek().text.lower() not in _KEYWORDS and
                self.peek(1).kind == "op" and self.peek(1).text == "." and
                self.peek(2).kind == "op" and self.peek(2).text == "*"):
            t = self.ident()
            self.next()
            self.next()
            return SelectItem(Star(t.lower()), None)
        e = self.expr()
        alias = None
        if self.eat_kw("as"):
            alias = self.ident()
        elif (self.peek().kind in ("id", "qid") and
              self.peek().text.lower() not in _KEYWORDS):
            alias = self.ident()
        return SelectItem(e, alias)

    # -- FROM ---------------------------------------------------------------
    def from_clause(self) -> Ast:
        item = self.join_chain()
        while self.eat_op(","):
            right = self.join_chain()
            item = JoinItem(item, right, "cross", None)
        return item

    def join_chain(self) -> Ast:
        left = self.table_primary()
        while True:
            how = None
            if self.eat_kw("cross"):
                self.expect_kw("join")
                how = "cross"
            elif self.at_kw("join"):
                self.next()
                how = "inner"
            elif self.at_kw("inner") and \
                    self.peek(1).text.lower() == "join":
                self.next()
                self.next()
                how = "inner"
            elif self.at_kw("left", "right", "full"):
                how = self.next().text.lower()
                self.eat_kw("outer")
                if self.eat_kw("semi"):
                    how = "semi"
                elif self.eat_kw("anti"):
                    how = "anti"
                self.expect_kw("join")
            else:
                break
            right = self.table_primary()
            on = None
            if how != "cross":
                self.expect_kw("on")
                on = self.expr()
            left = JoinItem(left, right, how, on)
        return left

    def table_primary(self) -> Ast:
        if self.eat_op("("):
            q = self.query_expr()
            self.expect_op(")")
            self.eat_kw("as")
            alias = self.ident()
            return SubqueryRef(q, alias.lower())
        name = self.ident()
        alias = None
        if self.eat_kw("as"):
            alias = self.ident()
        elif (self.peek().kind in ("id", "qid") and
              self.peek().text.lower() not in _KEYWORDS):
            alias = self.ident()
        return TableRef(name.lower(), alias.lower() if alias else None)

    # -- expressions (precedence climbing) ----------------------------------
    def expr(self) -> Ast:
        return self.or_expr()

    def or_expr(self) -> Ast:
        left = self.and_expr()
        while self.eat_kw("or"):
            left = Bin("or", left, self.and_expr())
        return left

    def and_expr(self) -> Ast:
        left = self.not_expr()
        while self.eat_kw("and"):
            left = Bin("and", left, self.not_expr())
        return left

    def not_expr(self) -> Ast:
        if self.eat_kw("not"):
            return Un("not", self.not_expr())
        return self.predicate()

    def predicate(self) -> Ast:
        if self.at_kw("exists"):
            self.next()
            self.expect_op("(")
            q = self.query_expr()
            self.expect_op(")")
            return Exists(q)
        left = self.additive()
        while True:
            negated = False
            if self.at_kw("not") and self.peek(1).text.lower() in (
                    "in", "like", "between"):
                self.next()
                negated = True
            if self.eat_kw("between"):
                lo = self.additive()
                self.expect_kw("and")
                hi = self.additive()
                left = Between(left, lo, hi, negated)
                continue
            if self.eat_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.query_expr()
                    self.expect_op(")")
                    left = InSub(left, q, negated)
                else:
                    items = [self.expr()]
                    while self.eat_op(","):
                        items.append(self.expr())
                    self.expect_op(")")
                    left = InList(left, tuple(items), negated)
                continue
            if self.eat_kw("like"):
                pat = self.additive()
                if not isinstance(pat, Lit) or not isinstance(pat.value, str):
                    raise SqlError("LIKE pattern must be a string literal")
                left = LikeE(left, pat.value, negated)
                continue
            if self.eat_kw("is"):
                neg = self.eat_kw("not")
                self.expect_kw("null")
                left = IsNullE(left, neg)
                continue
            if negated:
                raise SqlError(f"dangling NOT at {self.peek().pos}")
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().text
                right = self.additive()
                left = Bin({"!=": "<>"}.get(op, op), left, right)
                continue
            return left

    def additive(self) -> Ast:
        left = self.multiplicative()
        while self.at_op("+", "-", "||"):
            op = self.next().text
            left = Bin(op, left, self.multiplicative())
        return left

    def multiplicative(self) -> Ast:
        left = self.unary()
        while self.at_op("*", "/", "%"):
            op = self.next().text
            left = Bin(op, left, self.unary())
        return left

    def unary(self) -> Ast:
        if self.eat_op("-"):
            return Un("-", self.unary())
        if self.eat_op("+"):
            return self.unary()
        return self.primary()

    def primary(self) -> Ast:
        t = self.peek()
        if t.kind == "num":
            self.next()
            txt = t.text
            if "." in txt or "e" in txt.lower():
                return Lit(float(txt))
            return Lit(int(txt))
        if t.kind == "str":
            self.next()
            return Lit(t.text[1:-1].replace("''", "'"))
        if self.eat_op("("):
            if self.at_kw("select", "with"):
                q = self.query_expr()
                self.expect_op(")")
                return ScalarSub(q)
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind not in ("id", "qid"):
            raise SqlError(f"unexpected token {t.text!r} at {t.pos}")
        low = t.text.lower()
        if low == "null":
            self.next()
            return Lit(None)
        if low in ("true", "false"):
            self.next()
            return Lit(low == "true")
        if low == "case":
            return self.case_expr()
        if low == "cast":
            self.next()
            self.expect_op("(")
            e = self.expr()
            self.expect_kw("as")
            tn = self.next().text.lower()
            p1 = p2 = None
            if self.eat_op("("):
                p1 = int(self.next().text)
                if self.eat_op(","):
                    p2 = int(self.next().text)
                self.expect_op(")")
            self.expect_op(")")
            return CastE(e, tn, p1, p2)
        if low == "interval":
            self.next()
            v = self.next()
            n = int(v.text[1:-1] if v.kind == "str" else v.text)
            unit = self.next().text.lower().rstrip("s")
            return Interval(n, unit)
        if low in ("date", "timestamp") and self.peek(1).kind == "str":
            self.next()
            s = self.next().text[1:-1]
            if low == "date":
                return Lit(_dt.date.fromisoformat(s))
            return Lit(_dt.datetime.fromisoformat(s))
        # function call?
        if (self.peek(1).kind == "op" and self.peek(1).text == "(" and
                (low not in _KEYWORDS or low in ("first", "last"))):
            fname = self.next().text.lower()
            self.expect_op("(")
            distinct = False
            args: List[Ast] = []
            if self.at_op("*"):
                self.next()
                args = [Star()]
            elif not self.at_op(")"):
                distinct = self.eat_kw("distinct")
                args.append(self.expr())
                while self.eat_op(","):
                    args.append(self.expr())
            self.expect_op(")")
            f = Func(fname, tuple(args), distinct)
            if self.at_kw("over"):
                return self.over_clause(f)
            return f
        # qualified / bare identifier
        parts = [self.ident()]
        while self.at_op(".") and self.peek(1).kind in ("id", "qid"):
            self.next()
            parts.append(self.ident())
        return Ident(tuple(p.lower() for p in parts))

    def case_expr(self) -> Ast:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.expr()
        whens: List[Tuple[Ast, Ast]] = []
        while self.eat_kw("when"):
            c = self.expr()
            self.expect_kw("then")
            v = self.expr()
            whens.append((c, v))
        els = self.expr() if self.eat_kw("else") else None
        self.expect_kw("end")
        return Case(operand, tuple(whens), els)

    def over_clause(self, f: Func) -> WindowE:
        self.expect_kw("over")
        self.expect_op("(")
        partition: List[Ast] = []
        order: List[OrderItem] = []
        frame = None
        if self.eat_kw("partition"):
            self.expect_kw("by")
            partition.append(self.expr())
            while self.eat_op(","):
                partition.append(self.expr())
        if self.eat_kw("order"):
            self.expect_kw("by")
            order = self.order_items()
        if self.at_kw("rows", "range"):
            kind = self.next().text.lower()
            self.expect_kw("between")
            lo = self.frame_bound()
            self.expect_kw("and")
            hi = self.frame_bound()
            frame = (kind, lo, hi)
        self.expect_op(")")
        return WindowE(f, tuple(partition), tuple(order), frame)

    def frame_bound(self) -> Optional[int]:
        if self.eat_kw("unbounded"):
            if not self.eat_kw("preceding"):
                self.expect_kw("following")
            return None
        if self.eat_kw("current"):
            self.expect_kw("row")
            return 0
        n = int(self.next().text)
        if self.eat_kw("preceding"):
            return -n
        self.expect_kw("following")
        return n


def parse_sql(sql: str) -> Ast:
    return _Parser(sql).parse()


# ---------------------------------------------------------------------------
# Lowering: AST -> logical plan
# ---------------------------------------------------------------------------

class _Scope:
    """Name-resolution environment over the current plan's schema.

    entries: ordered (alias, {col_lower: (display_name, Field)}) — the
    Field carries the ACTUAL (possibly dedup-renamed) column name in the
    combined schema; display_name is what SELECT * / output shows.
    """

    def __init__(self, entries):
        self.entries = entries

    @staticmethod
    def of(schema: Schema, alias: Optional[str] = None) -> "_Scope":
        cols = {f.name.lower(): (f.name, f) for f in schema}
        return _Scope([(alias, cols)])

    def resolve(self, parts: Tuple[str, ...]) -> ec.AttributeReference:
        f = self.resolve_field(parts)
        return ec.AttributeReference(f.name, f.dtype, f.nullable)

    def resolve_actual(self, cname: str) -> ec.AttributeReference:
        for _, cols in self.entries:
            for _, (_, f) in cols.items():
                if f.name == cname:
                    return ec.AttributeReference(f.name, f.dtype, f.nullable)
        raise SqlError(f"unknown column {cname}")

    def resolve_field(self, parts: Tuple[str, ...]) -> Field:
        if len(parts) == 2:
            tab, col = parts
            for alias, cols in self.entries:
                if alias == tab and col in cols:
                    return cols[col][1]
            raise SqlError(f"unknown column {tab}.{col}")
        col = parts[-1]
        hits = [cols[col][1] for _, cols in self.entries if col in cols]
        if not hits:
            raise SqlError(f"unknown column {col}")
        if len(hits) > 1:
            raise SqlError(f"ambiguous column {col}")
        return hits[0]

    def star_fields(self, table: Optional[str]):
        out = []
        for alias, cols in self.entries:
            if table is not None and alias != table:
                continue
            for _, (display, f) in cols.items():
                out.append((display, f))
        if not out:
            raise SqlError(f"unknown table {table} in star")
        return out


def _walk(ast: Ast):
    """Yield ast and descendants, NOT descending into sub-query nodes."""
    yield ast
    if isinstance(ast, (ScalarSub, InSub, Exists)):
        if isinstance(ast, InSub):
            yield from _walk(ast.operand)
        return
    for fld in dataclasses.fields(ast):
        v = getattr(ast, fld.name)
        if isinstance(v, Ast) and not isinstance(v, SelectStmt):
            yield from _walk(v)
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, Ast) and not isinstance(x, SelectStmt):
                    yield from _walk(x)
                elif (isinstance(x, tuple) and len(x) == 2 and
                      isinstance(x[0], Ast)):
                    yield from _walk(x[0])
                    yield from _walk(x[1])


def _transform(ast: Ast, fn) -> Ast:
    """Bottom-up rebuild; fn applied to every node (not into subqueries)."""
    if isinstance(ast, (ScalarSub, Exists)):
        return fn(ast)
    if isinstance(ast, InSub):
        return fn(dataclasses.replace(
            ast, operand=_transform(ast.operand, fn)))
    kw = {}
    changed = False
    for fld in dataclasses.fields(ast):
        v = getattr(ast, fld.name)
        if isinstance(v, Ast) and not isinstance(v, SelectStmt):
            nv = _transform(v, fn)
            changed |= nv is not v
            kw[fld.name] = nv
        elif isinstance(v, tuple) and any(isinstance(x, Ast) for x in v):
            nv = tuple(_transform(x, fn)
                       if isinstance(x, Ast) and not isinstance(x, SelectStmt)
                       else x for x in v)
            changed |= nv != v
            kw[fld.name] = nv
        elif (isinstance(v, tuple) and v and isinstance(v[0], tuple) and
              len(v[0]) == 2 and isinstance(v[0][0], Ast)):
            nv = tuple((_transform(a, fn), _transform(b, fn)) for a, b in v)
            changed |= nv != v
            kw[fld.name] = nv
    if changed:
        ast = dataclasses.replace(ast, **kw)
    return fn(ast)


def _display_name(ast: Ast, alias: Optional[str]) -> str:
    if alias:
        return alias
    if isinstance(ast, Ident):
        return ast.parts[-1]
    if isinstance(ast, Res):
        return ast.cname
    if isinstance(ast, Func):
        return f"{ast.fname}({', '.join(_display_name(a, None) for a in ast.args)})"
    if isinstance(ast, WindowE):
        return _display_name(ast.func, None)
    if isinstance(ast, Lit):
        return str(ast.value)
    if isinstance(ast, Star):
        return "*"
    if isinstance(ast, CastE):
        return _display_name(ast.operand, None)
    if isinstance(ast, Bin):
        return (f"({_display_name(ast.left, None)} {ast.op} "
                f"{_display_name(ast.right, None)})")
    if isinstance(ast, Un):
        return f"({ast.op} {_display_name(ast.operand, None)})"
    return type(ast).__name__.lower()


def _pyval(e: ec.Expression):
    if isinstance(e, ec.Literal):
        return e.value
    if isinstance(e, ec.Alias):
        return _pyval(e.children[0])
    raise SqlError("expected a literal argument")


_TYPE_MAP = {
    "boolean": T.BOOL, "bool": T.BOOL,
    "tinyint": T.INT8, "byte": T.INT8,
    "smallint": T.INT16, "short": T.INT16,
    "int": T.INT32, "integer": T.INT32,
    "bigint": T.INT64, "long": T.INT64,
    "float": T.FLOAT32, "real": T.FLOAT32,
    "double": T.FLOAT64,
    "string": T.STRING, "varchar": T.STRING, "char": T.STRING,
    "date": T.DATE, "timestamp": T.TIMESTAMP,
}


def _sql_type(name: str, p1, p2) -> T.DType:
    if name in ("decimal", "numeric"):
        return T.DecimalType(p1 if p1 is not None else 10,
                             p2 if p2 is not None else 0)
    if name in _TYPE_MAP:
        return _TYPE_MAP[name]
    raise SqlError(f"unsupported type {name}")


def _make_agg(f: Func, lower) -> eagg.AggregateFunction:
    n = f.fname
    if n == "count" and (not f.args or isinstance(f.args[0], Star)):
        if f.distinct:
            raise SqlError("COUNT(DISTINCT *) is not valid")
        return eagg.Count()
    arg = lower(f.args[0]) if f.args else None
    if n == "sum":
        return eagg.Sum(arg)
    if n == "count":
        return eagg.Count(arg)
    if n == "min":
        return eagg.Min(arg)
    if n == "max":
        return eagg.Max(arg)
    if n in ("avg", "mean"):
        return eagg.Average(arg)
    if n in ("first", "first_value"):
        return eagg.First(arg)
    if n in ("last", "last_value"):
        return eagg.Last(arg)
    if n == "collect_list":
        return eagg.CollectList(arg)
    if n == "collect_set":
        return eagg.CollectSet(arg)
    if n in ("stddev", "stddev_samp", "std"):
        return eagg.StddevSamp(arg)
    if n == "stddev_pop":
        return eagg.StddevPop(arg)
    if n in ("variance", "var_samp"):
        return eagg.VarianceSamp(arg)
    if n == "var_pop":
        return eagg.VariancePop(arg)
    raise SqlError(f"unknown aggregate {n}")




def _split_conjuncts(a: Ast) -> List[Ast]:
    """AND-flatten a predicate AST (shared by WHERE lowering and both
    decorrelators)."""
    if isinstance(a, Bin) and a.op == "and":
        return _split_conjuncts(a.left) + _split_conjuncts(a.right)
    return [a]


def _split_disjuncts(a: Ast) -> List[Ast]:
    """OR-flatten a predicate AST."""
    if isinstance(a, Bin) and a.op == "or":
        return _split_disjuncts(a.left) + _split_disjuncts(a.right)
    return [a]


def _conj(parts: List[Ast]) -> Optional[Ast]:
    if not parts:
        return None
    e = parts[0]
    for p in parts[1:]:
        e = Bin("and", e, p)
    return e


def _factor_or(a: Ast) -> Ast:
    """``(A and P1) or (A and P2) -> A and (P1 or P2)``, recursively.

    Exact in three-valued logic (AND distributes over OR).  TPC-DS q41
    hides its correlation equality ``i_manufact = i1.i_manufact`` inside
    both branches of a top-level OR; factoring it out lets the
    decorrelators see it as a plain correlation conjunct."""
    if isinstance(a, Bin) and a.op == "and":
        return Bin("and", _factor_or(a.left), _factor_or(a.right))
    if not (isinstance(a, Bin) and a.op == "or"):
        return a
    branches = [_split_conjuncts(_factor_or(d))
                for d in _split_disjuncts(a)]
    common = [c for c in branches[0]
              if all(any(c == d for d in b) for b in branches[1:])]
    if not common:
        return a
    rests = []
    for b in branches:
        rest = list(b)
        for c in common:
            for i, d in enumerate(rest):
                if c == d:
                    del rest[i]
                    break
        rests.append(_conj(rest))
    if any(r is None for r in rests):
        # (A) or (A and P) == A
        return _conj(common)
    disj = rests[0]
    for r in rests[1:]:
        disj = Bin("or", disj, r)
    return _conj(common + [disj])


def _canon_idents(scope_: "_Scope", ast: Ast) -> Ast:
    """Resolve raw Idents against a scope (raises SqlError on unknown
    columns) — shared by both decorrelators."""
    def fn(n):
        if isinstance(n, Ident):
            return Res(scope_.resolve_field(n.parts).name)
        return n
    return _transform(ast, fn)


class _Lowerer:
    def __init__(self, session, views):
        self.session = session
        self.views = dict(views)   # name_lower -> LogicalPlan
        self._uid = 0

    def fresh(self, prefix: str) -> str:
        self._uid += 1
        return f"__{prefix}{self._uid}"

    def _exec_sub(self, plan: L.LogicalPlan):
        """Eagerly execute a lowered subquery (scalar / IN / EXISTS
        position).  Runs the same logical optimizer as ``sql_to_plan``
        first — without it the plan is raw cross-joins + filters and a
        three-table subquery (TPC-DS q23's max_store_sales) explodes."""
        from ..plan.logical_opt import optimize
        return self.session.execute_to_arrow(optimize(plan))

    # -- statements ---------------------------------------------------------
    def lower(self, ast: Ast) -> L.LogicalPlan:
        if isinstance(ast, SetOp):
            return self.lower_setop(ast)
        assert isinstance(ast, SelectStmt), ast
        return self.lower_select(ast)

    def lower_setop(self, s: SetOp) -> L.LogicalPlan:
        # a WITH on the leftmost SELECT scopes over the entire set
        # operation; hoist its CTEs for the whole lowering
        leftmost = s.left
        while isinstance(leftmost, SetOp):
            leftmost = leftmost.left
        if isinstance(leftmost, SelectStmt) and leftmost.ctes:
            saved = self.views
            self.views = dict(saved)
            for name, sub in leftmost.ctes:
                self.views[name.lower()] = self.lower(sub)
            try:
                stripped = self._strip_leftmost_ctes(s)
                return self.lower_setop(stripped)
            finally:
                self.views = saved
        left = self.lower(s.left)
        right = self.lower(s.right)
        if len(left.schema) != len(right.schema):
            raise SqlError("set operation column counts differ")
        if s.op == "union":
            # align right's column names to left's
            if right.schema.names != left.schema.names:
                right = L.Project(
                    [ec.Alias(ec.AttributeReference(rf.name, rf.dtype,
                                                    rf.nullable), lf.name)
                     for lf, rf in zip(left.schema, right.schema)], right)
            plan = L.Union([left, right])
            if not s.all:
                plan = L.Distinct(plan)
        else:
            jt = "semi" if s.op == "intersect" else "anti"
            # null-safe comparison (IS NOT DISTINCT FROM): equi-join keys
            # reject nulls, so each column becomes (is-null flag,
            # null-defaulted value) — NULL rows then match each other
            either_nullable = [lf.nullable or rf.nullable for lf, rf in
                               zip(left.schema, right.schema)]

            def null_safe_keys(schema):
                keys = []
                for f, nullable in zip(schema, either_nullable):
                    ref = ec.AttributeReference(f.name, f.dtype, f.nullable)
                    if not nullable:
                        keys.append(ref)
                        continue
                    keys.append(ep.IsNull(ref))
                    default = f.dtype.default_value
                    if default is not None:
                        default = default.item() \
                            if hasattr(default, "item") else default
                    # the default must be a value of the column's PYTHON
                    # type — the CPU oracle evaluates the Coalesce with
                    # pyarrow, which rejects e.g. int fills on
                    # string/date columns
                    if f.dtype == T.STRING:
                        default = ""
                    elif f.dtype == T.DATE:
                        import datetime as _dt
                        default = _dt.date(1970, 1, 1)
                    elif f.dtype == T.TIMESTAMP:
                        import datetime as _dt
                        default = _dt.datetime(1970, 1, 1)
                    keys.append(econd.Coalesce(
                        ref, ec.Literal(default if default is not None
                                        else 0, f.dtype)))
                return keys
            plan = L.Distinct(L.Join(left, right, jt,
                                     null_safe_keys(left.schema),
                                     null_safe_keys(right.schema), None))
        if s.order_by:
            scope = _Scope.of(plan.schema)
            orders = [L.SortOrder(self.lower_expr(o.e, scope), o.asc,
                                  o.nulls_first) for o in s.order_by]
            plan = L.Sort(orders, plan, is_global=True)
        if s.limit is not None or s.offset:
            plan = L.Limit(s.limit if s.limit is not None else 1 << 60,
                           plan, offset=s.offset or 0)
        return plan

    @staticmethod
    def _strip_leftmost_ctes(s: SetOp) -> SetOp:
        if isinstance(s.left, SetOp):
            return dataclasses.replace(
                s, left=_Lowerer._strip_leftmost_ctes(s.left))
        return dataclasses.replace(
            s, left=dataclasses.replace(s.left, ctes=()))

    def lower_select(self, s: SelectStmt) -> L.LogicalPlan:
        views = self.views
        if s.ctes:
            self.views = dict(views)
            for name, sub in s.ctes:
                self.views[name.lower()] = self.lower(sub)
        try:
            return self._lower_select_body(s)
        finally:
            self.views = views

    def _lower_select_body(self, s: SelectStmt) -> L.LogicalPlan:
        # 1. FROM
        if s.from_item is None:
            plan: L.LogicalPlan = L.Range(0, 1)
            scope = _Scope([(None, {})])
        else:
            plan, scope = self.lower_from(s.from_item)

        # 2. canonicalize identifiers to actual column names
        def canon(ast: Ast) -> Ast:
            def fn(n):
                if isinstance(n, Ident):
                    return Res(scope.resolve_field(n.parts).name)
                return n
            return _transform(ast, fn)

        # expand stars; display names come from the ORIGINAL asts (the
        # join dedup-rename must not leak into output column names)
        items: List[SelectItem] = []
        display_names: List[str] = []
        for it in s.items:
            if isinstance(it.e, Star):
                for display, f in scope.star_fields(it.e.table):
                    items.append(SelectItem(Res(f.name), display))
                    display_names.append(display)
            else:
                items.append(SelectItem(canon(it.e), it.alias))
                display_names.append(it.alias or _display_name(it.e, None))
        seen: dict = {}
        for i, d in enumerate(display_names):
            if d in seen:
                seen[d] += 1
                display_names[i] = f"{d}_{seen[d]}"
            else:
                seen[d] = 0

        # 3. WHERE (incl. IN-subquery / EXISTS transforms)
        if s.where is not None:
            plan = self.lower_where(canon(s.where), plan, scope)
            scope = self._rescope(plan, scope)

        item_asts = [it.e for it in items]
        having_ast = canon(s.having) if s.having is not None else None
        # ORDER BY: ordinal / select-alias substitution BEFORE canon (an
        # alias is not a source column, canon would reject it)
        fixed_orders: List[OrderItem] = []
        for o in s.order_by:
            e = o.e
            if isinstance(e, Lit) and isinstance(e.value, int):
                if not (1 <= e.value <= len(item_asts)):
                    raise SqlError(f"ORDER BY ordinal {e.value} out of range")
                e = item_asts[e.value - 1]
            elif isinstance(e, Ident) and len(e.parts) == 1:
                for it, disp in zip(items, display_names):
                    if disp.lower() == e.parts[0].lower():
                        e = it.e
                        break
                else:
                    e = canon(e)
            else:
                try:
                    e = canon(e)
                except SqlError:
                    # select-list aliases may appear INSIDE an ORDER BY
                    # expression (TPC-DS q70: ``order by case when
                    # lochierarchy = 0 then s_state end``) — substitute
                    # aliases through the tree, then canonicalize
                    alias_map = {disp.lower(): it.e
                                 for it, disp in zip(items, display_names)}

                    def sub_alias(n):
                        if isinstance(n, Ident) and len(n.parts) == 1 \
                                and n.parts[0].lower() in alias_map:
                            return alias_map[n.parts[0].lower()]
                        return n
                    e = canon(_transform(e, sub_alias))
            fixed_orders.append(dataclasses.replace(o, e=e))
        order_asts = fixed_orders

        # GROUP BY keys: ordinals and select aliases allowed
        key_asts: List[Ast] = []
        for g in s.group_by:
            if isinstance(g, Lit) and isinstance(g.value, int):
                key_asts.append(item_asts[g.value - 1])
                continue
            if isinstance(g, Ident) and len(g.parts) == 1:
                matched = None
                for it, disp in zip(items, display_names):
                    if disp.lower() == g.parts[0].lower():
                        matched = it.e
                        break
                try:
                    key_asts.append(canon(g))
                except SqlError:
                    if matched is None:
                        raise
                    key_asts.append(matched)
                continue
            key_asts.append(canon(g))

        def has_agg(ast: Optional[Ast]) -> bool:
            if ast is None:
                return False
            return any(isinstance(n, Func) and n.fname in _AGG_FUNCS
                       for n in _walk(ast)
                       if not isinstance(n, WindowE))

        # a window func's direct Func node must not count as an aggregate
        def agg_calls(ast: Ast) -> List[Func]:
            out = []
            win_funcs = {id(n.func) for n in _walk(ast)
                         if isinstance(n, WindowE)}
            for n in _walk(ast):
                if (isinstance(n, Func) and n.fname in _AGG_FUNCS and
                        id(n) not in win_funcs):
                    out.append(n)
            return out

        need_agg = bool(key_asts) or any(
            agg_calls(a) for a in item_asts + ([having_ast] if having_ast
                                              else []))

        # 4. aggregation stage
        if need_agg:
            lower_in = lambda a: self.lower_expr(a, scope)  # noqa: E731
            key_names: List[str] = []
            group_exprs: List[ec.Expression] = []
            key_map: List[Tuple[Ast, str]] = []
            for k in key_asts:
                e = self.lower_expr(k, scope)
                if isinstance(k, Res):
                    name = k.cname
                else:
                    name = self.fresh("grp")
                    e = ec.Alias(e, name)
                key_names.append(name)
                group_exprs.append(e)
                key_map.append((k, name))
            aggs: List[L.AggExpr] = []
            agg_map: List[Tuple[Func, str]] = []
            roots = item_asts + ([having_ast] if having_ast else []) + \
                [o.e for o in order_asts]
            for root in roots:
                for call in agg_calls(root):
                    if any(call == c for c, _ in agg_map):
                        continue
                    name = self.fresh("agg")
                    aggs.append(L.AggExpr(
                        _make_agg(call, lower_in), name,
                        distinct=call.distinct))
                    agg_map.append((call, name))
            if s.group_sets is not None:
                # set members are the same ASTs as the GROUP BY columns,
                # which already went through alias/ordinal substitution
                # into key_asts — align by position
                subst = {g: k for g, k in zip(s.group_by, key_asts)}
                name_of = {k: n for (k, n) in key_map}
                sets = []
                for gset in s.group_sets:
                    members = []
                    for gcol in gset:
                        k = subst.get(gcol)
                        if k is None:
                            try:
                                k = canon(gcol)
                            except SqlError:
                                k = None
                        if k is None or k not in name_of:
                            raise SqlError(
                                "grouping set member must appear in "
                                "GROUP BY")
                        members.append(name_of[k])
                    sets.append(tuple(members))
                # grouping(col) -> 1 on subtotal rows where col is
                # rolled up, else 0 (computed from the expand set id)
                grouping_calls = []
                for root in item_asts + [o.e for o in order_asts] + \
                        ([having_ast] if having_ast is not None else []):
                    for nd in _walk(root):
                        if isinstance(nd, Func) and \
                                nd.fname == "grouping" and \
                                len(nd.args) == 1 and \
                                not any(nd == g for g in grouping_calls):
                            grouping_calls.append(nd)
                gsub = {}
                for gc in grouping_calls:
                    k = subst.get(gc.args[0])
                    if k is None:
                        k = canon(gc.args[0])
                    nm = name_of.get(k)
                    if nm is None:
                        raise SqlError(
                            "grouping() argument must be a GROUP BY key")
                    rolled = tuple(Lit(i) for i, st in enumerate(sets)
                                   if nm not in st)
                    gsub[gc] = Case(None, ((InList(Res("__gid"), rolled),
                                            Lit(1)),), Lit(0))

                def rwg(ast: Ast) -> Ast:
                    def fn(n):
                        return gsub.get(n, n)
                    return _transform(ast, fn)
                if gsub:
                    item_asts = [rwg(a) for a in item_asts]
                    order_asts = [dataclasses.replace(o, e=rwg(o.e))
                                  for o in order_asts]
                    if having_ast is not None:
                        having_ast = rwg(having_ast)
                plan = L.build_grouping_sets(group_exprs, sets, aggs,
                                             plan, keep_gid=bool(gsub))
            else:
                plan = L.build_aggregate(group_exprs, aggs, plan)
            scope = _Scope.of(plan.schema)

            def rw(ast: Ast) -> Ast:
                def fn(n):
                    for k, name in key_map:
                        if n == k:
                            return Res(name)
                    for c, name in agg_map:
                        if n == c:
                            return Res(name)
                    return n
                return _transform(ast, fn)

            item_asts = [rw(a) for a in item_asts]
            if having_ast is not None:
                having_ast = rw(having_ast)
            order_asts = [dataclasses.replace(o, e=rw(o.e))
                          for o in order_asts]

        # 5. HAVING
        if having_ast is not None:
            plan = L.Filter(self.lower_expr(having_ast, scope), plan)

        # 6. window functions
        win_nodes: List[Tuple[WindowE, str]] = []
        for root in item_asts + [o.e for o in order_asts]:
            for n in _walk(root):
                if isinstance(n, WindowE) and not any(
                        n == w for w, _ in win_nodes):
                    win_nodes.append((n, self.fresh("win")))
        if win_nodes:
            wfs = []
            for w, name in win_nodes:
                wfs.append(self.lower_window(w, name, scope))
            plan = L.Window(wfs, plan)
            scope = _Scope.of(plan.schema)

            def rww(ast: Ast) -> Ast:
                def fn(n):
                    for w, name in win_nodes:
                        if n == w:
                            return Res(name)
                    return n
                return _transform(ast, fn)
            item_asts = [rww(a) for a in item_asts]
            order_asts = [dataclasses.replace(o, e=rww(o.e))
                          for o in order_asts]

        # 7. sort below the final projection (hidden sort columns stay
        #    available), except DISTINCT which must sort its output
        if order_asts and not s.distinct:
            orders = [L.SortOrder(self.lower_expr(o.e, scope), o.asc,
                                  o.nulls_first) for o in order_asts]
            plan = L.Sort(orders, plan, is_global=True)

        # 8. final projection
        out_exprs = []
        for ast, disp in zip(item_asts, display_names):
            e = self.lower_expr(ast, scope)
            out_exprs.append(ec.Alias(e, disp))
        plan = L.Project(out_exprs, plan)

        if s.distinct:
            plan = L.Distinct(plan)
            if order_asts:
                oscope = _Scope.of(plan.schema)
                orders = []
                for o in order_asts:
                    orders.append(L.SortOrder(
                        self.lower_expr(o.e, oscope), o.asc, o.nulls_first))
                plan = L.Sort(orders, plan, is_global=True)

        # 9. limit / offset
        if s.limit is not None or s.offset:
            plan = L.Limit(s.limit if s.limit is not None else 1 << 60,
                           plan, offset=s.offset or 0)
        return plan

    def _rescope(self, plan: L.LogicalPlan, scope: _Scope) -> _Scope:
        """After a plan change that keeps the schema, keep the scope."""
        return scope

    # -- FROM ---------------------------------------------------------------
    def lower_from(self, item: Ast):
        if isinstance(item, TableRef):
            plan = self.views.get(item.tname)
            if plan is None:
                raise SqlError(f"unknown table {item.tname}")
            alias = item.alias or item.tname
            return plan, _Scope.of(plan.schema, alias)
        if isinstance(item, SubqueryRef):
            plan = self.lower(item.query)
            return plan, _Scope.of(plan.schema, item.alias)
        assert isinstance(item, JoinItem), item
        lplan, lscope = self.lower_from(item.left)
        rplan, rscope = self.lower_from(item.right)
        # dedup-rename right columns that collide with the left side
        taken = {f.name for f in lplan.schema}
        renames = {}
        for _, cols in rscope.entries:
            for low, (disp, f) in cols.items():
                if f.name in taken:
                    alias0 = next((a for a, c in rscope.entries
                                   if low in c and c[low][1] is f), None)
                    nn = f"__{alias0 or 'r'}_{f.name}"
                    while nn in taken:
                        nn += "_"
                    renames[f.name] = nn
                taken.add(renames.get(f.name, f.name))
        if renames:
            rplan = L.Project(
                [ec.Alias(ec.AttributeReference(f.name, f.dtype, f.nullable),
                          renames[f.name]) if f.name in renames else
                 ec.AttributeReference(f.name, f.dtype, f.nullable)
                 for f in rplan.schema], rplan)
            new_entries = []
            for alias, cols in rscope.entries:
                nc = {}
                for low, (disp, f) in cols.items():
                    nn = renames.get(f.name, f.name)
                    nc[low] = (disp, Field(nn, f.dtype, f.nullable))
                new_entries.append((alias, nc))
            rscope = _Scope(new_entries)
        combined = _Scope(lscope.entries + rscope.entries)
        how = item.how
        if how == "cross" or item.on is None:
            join = L.Join(lplan, rplan, "cross", [], [], None)
            return join, combined

        def canon_on(ast: Ast) -> Ast:
            def fn(n):
                if isinstance(n, Ident):
                    return Res(combined.resolve_field(n.parts).name)
                return n
            return _transform(ast, fn)
        cond = self.lower_expr(canon_on(item.on), combined)
        from .dataframe import _extract_equi_keys
        lkeys, rkeys, residual = _extract_equi_keys(
            cond, lplan.schema, rplan.schema)
        join = L.Join(lplan, rplan, how, lkeys, rkeys, residual)
        if how in ("semi", "anti"):
            return join, _Scope(lscope.entries)
        # outer joins make the other side nullable; rebuild the scope from
        # the join's output schema, preserving alias partitions
        out_fields = {f.name: f for f in join.schema}
        new_entries = []
        for alias, cols in combined.entries:
            nc = {low: (disp, out_fields[f.name])
                  for low, (disp, f) in cols.items()}
            new_entries.append((alias, nc))
        return join, _Scope(new_entries)

    # -- WHERE with subquery predicates -------------------------------------
    def lower_where(self, where: Ast, plan: L.LogicalPlan,
                    scope: _Scope) -> L.LogicalPlan:
        def conjuncts(a: Ast) -> List[Ast]:
            if isinstance(a, Bin) and a.op == "and":
                return conjuncts(a.left) + conjuncts(a.right)
            return [a]
        rest: List[ec.Expression] = []
        for c in conjuncts(where):
            # NOT EXISTS / NOT IN arrive as Un("not", ...) from the parser
            if isinstance(c, Un) and c.op == "not" and \
                    isinstance(c.operand, (InSub, Exists)):
                c = dataclasses.replace(c.operand,
                                        negated=not c.operand.negated)
            if isinstance(c, InSub):
                sub = self.lower(c.query)
                if len(sub.schema) != 1:
                    raise SqlError("IN subquery must return one column")
                sf = sub.schema.fields[0]
                lkey = self.lower_expr(c.operand, scope)
                rkey = ec.AttributeReference(sf.name, sf.dtype, sf.nullable)
                if c.negated:
                    # SQL three-valued NOT IN: empty set -> everything
                    # qualifies (even NULL); any NULL in the set ->
                    # nothing qualifies; else NULL operands never match
                    if self._exec_sub(
                            L.Limit(1, sub)).num_rows == 0:
                        continue
                    if sf.nullable:
                        nulls = self._exec_sub(L.Limit(
                            1, L.Filter(ep.IsNull(rkey), sub))).num_rows
                        if nulls:
                            plan = L.Filter(ec.Literal(False, T.BOOL), plan)
                            continue
                    if lkey.nullable:
                        plan = L.Filter(ep.IsNotNull(lkey), plan)
                    plan = L.Join(plan, sub, "anti", [lkey], [rkey], None)
                else:
                    plan = L.Join(plan, sub, "semi", [lkey], [rkey], None)
                continue
            disj = _split_disjuncts(c)
            if len(disj) > 1 and all(isinstance(d, Exists)
                                     and not d.negated for d in disj):
                plan = self._decorrelate_exists_or(disj, plan, scope)
                continue
            if isinstance(c, Exists):
                try:
                    sub = self.lower(c.query)
                except SqlError:
                    # outer references: decorrelate to a semi/anti join
                    plan = self._decorrelate_exists(c, plan, scope)
                    continue
                # uncorrelated EXISTS: evaluate eagerly to a constant
                n = self._exec_sub(
                    L.Limit(1, sub)).num_rows
                truth = (n > 0) != c.negated
                if not truth:
                    plan = L.Filter(ec.Literal(False, T.BOOL), plan)
                continue
            if isinstance(c, Bin) and c.op in ("<", "<=", ">", ">=",
                                               "=", "<>") and \
                    (isinstance(c.left, ScalarSub) ^
                     isinstance(c.right, ScalarSub)):
                sub_ast = c.right if isinstance(c.right, ScalarSub) \
                    else c.left
                try:
                    sub_plan = self.lower(sub_ast.query)
                except SqlError as probe_err:
                    plan = self._decorrelate_scalar_cmp(
                        c, plan, scope, probe_err)
                    continue
                # uncorrelated: fold the ALREADY-lowered plan to a
                # literal here (handing the raw AST to lower_expr
                # would lower + execute the whole subquery a second
                # time, including any nested subqueries)
                lit = self._scalar_literal(sub_plan)
                lhs = self.lower_expr(
                    c.left if isinstance(c.right, ScalarSub) else
                    c.right, scope)
                a, b = (lhs, lit) if isinstance(c.right, ScalarSub) \
                    else (lit, lhs)
                cmp_cls = {"<": ep.LessThan, "<=": ep.LessThanOrEqual,
                           ">": ep.GreaterThan,
                           ">=": ep.GreaterThanOrEqual, "=": ep.EqualTo}
                rest.append(ep.Not(ep.EqualTo(a, b)) if c.op == "<>"
                            else cmp_cls[c.op](a, b))
                continue
            rest.append(self.lower_expr(c, scope))
        if rest:
            cond = rest[0]
            for r in rest[1:]:
                cond = ep.And(cond, r)
            plan = L.Filter(cond, plan)
        return plan

    def _decorrelate_exists(self, c: Exists, plan: L.LogicalPlan,
                            outer_scope: _Scope) -> L.LogicalPlan:
        """Correlated [NOT] EXISTS -> semi/anti join.

        Reference shape: TPC-DS q16/q94 ``exists (select * from t2 where
        t1.k = t2.k and ...)``.  Equality conjuncts that straddle the
        scopes become join keys; purely-inner conjuncts stay as a filter
        under the join; anything else is unsupported."""
        outer_keys, inner_proj, rrefs, condition = \
            self._exists_parts(c, outer_scope)
        return L.Join(plan, inner_proj, "anti" if c.negated else "semi",
                      outer_keys, rrefs, condition)

    def _exists_parts(self, c: Exists, outer_scope: _Scope):
        """Split a correlated EXISTS into (outer_keys, projected inner
        plan, right key refs, residual condition).  Equality conjuncts
        that straddle the scopes become join keys; purely-inner
        conjuncts filter under the join; other straddling conjuncts
        (q16/q94's ``cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk``)
        become a residual pair-level condition with the referenced
        inner columns projected alongside the keys."""
        sub = c.query
        if not isinstance(sub, SelectStmt) or sub.from_item is None or \
                sub.group_by or sub.having or sub.distinct or sub.ctes:
            raise SqlError("unsupported correlated EXISTS subquery")
        inner_plan, inner_scope = self.lower_from(sub.from_item)
        inner_rest: List[Ast] = []
        residual_asts: List[Ast] = []
        outer_keys: List[ec.Expression] = []
        inner_keys: List[ec.Expression] = []
        where_ast = _factor_or(sub.where) if sub.where is not None \
            else None
        for cj in (_split_conjuncts(where_ast)
                   if where_ast is not None else []):
            try:
                inner_rest.append(_canon_idents(inner_scope, cj))
                continue
            except SqlError:
                pass
            matched = False
            if isinstance(cj, Bin) and cj.op == "=":
                for a, b in ((cj.left, cj.right), (cj.right, cj.left)):
                    try:
                        ia = _canon_idents(inner_scope, a)
                        ob = _canon_idents(outer_scope, b)
                    except SqlError:
                        continue
                    inner_keys.append(self.lower_expr(ia, inner_scope))
                    outer_keys.append(self.lower_expr(ob, outer_scope))
                    matched = True
                    break
            if not matched:
                residual_asts.append(cj)
        if not inner_keys:
            raise SqlError("EXISTS subquery references unknown columns")
        if inner_rest:
            cond = self.lower_expr(inner_rest[0], inner_scope)
            for r in inner_rest[1:]:
                cond = ep.And(cond, self.lower_expr(r, inner_scope))
            inner_plan = L.Filter(cond, inner_plan)
        proj = [ec.Alias(k, f"__ck{i}")
                for i, k in enumerate(inner_keys)]
        # residual conjuncts: inner-resolvable idents are projected as
        # extra __rc columns; the rewritten predicate then lowers
        # against outer-scope + projected-inner and binds to the join's
        # pair schema at execution
        condition = None
        if residual_asts:
            extra: List[ec.Expression] = []
            extra_fields: List[Field] = []

            def sub_inner(n):
                if isinstance(n, Ident):
                    try:
                        ie = self.lower_expr(
                            _canon_idents(inner_scope, n), inner_scope)
                    except SqlError:
                        return n
                    name = f"__rc{len(extra)}"
                    extra.append(ec.Alias(ie, name))
                    extra_fields.append(Field(name, ie.dtype(), True))
                    return Res(name)
                return n
            lowered = []
            for r in residual_asts:
                r2 = _transform(r, sub_inner)
                comb = _Scope(outer_scope.entries + [
                    (None, {f.name.lower(): (f.name, f)
                            for f in extra_fields})])
                lowered.append(self.lower_expr(_canon_idents(comb, r2),
                                               comb))
            proj = proj + extra
            condition = lowered[0]
            for r in lowered[1:]:
                condition = ep.And(condition, r)
        inner_proj = L.Project(proj, inner_plan)
        rrefs = [ec.AttributeReference(f"__ck{i}", k.dtype(), True)
                 for i, k in enumerate(inner_keys)]
        return outer_keys, inner_proj, rrefs, condition

    def _decorrelate_exists_or(self, disj: List[Exists],
                               plan: L.LogicalPlan,
                               outer_scope: _Scope) -> L.LogicalPlan:
        """``exists(E1) or exists(E2) ...`` where every disjunct
        correlates on the SAME outer key expressions -> one semi join
        against the UNION ALL of the inner key sets (TPC-DS q10's
        web-or-catalog shape)."""
        parts = [self._exists_parts(d, outer_scope) for d in disj]
        ok0, _, rrefs0, cond0 = parts[0]
        if cond0 is not None or any(p[3] is not None for p in parts):
            raise SqlError("OR of EXISTS with residual conditions "
                           "unsupported")
        key_repr = [repr(k) for k in ok0]
        for ok, _, _, _ in parts[1:]:
            if [repr(k) for k in ok] != key_repr:
                raise SqlError(
                    "OR of EXISTS requires identical correlation keys "
                    "in every disjunct")
        inner = L.Union([p[1] for p in parts])
        return L.Join(plan, inner, "semi", ok0, rrefs0, None)

    def _scalar_literal(self, sub_plan: L.LogicalPlan) -> ec.Literal:
        """Execute an (already lowered) uncorrelated scalar subquery to
        a literal (at most one row, one column)."""
        if len(sub_plan.schema) != 1:
            raise SqlError("scalar subquery must return one column")
        tbl = self._exec_sub(sub_plan)
        if tbl.num_rows > 1:
            raise SqlError("scalar subquery returned more than one row")
        val = tbl.column(0)[0].as_py() if tbl.num_rows else None
        return ec.Literal(val, sub_plan.schema.fields[0].dtype)

    def _decorrelate_scalar_cmp(self, c: Bin, plan: L.LogicalPlan,
                                outer_scope: _Scope,
                                probe_err=None) -> L.LogicalPlan:
        """``x CMP (correlated scalar aggregate subquery)`` ->
        group-by-correlation-keys + inner join + comparison filter.

        Reference shape: TPC-DS q1/q6/q32/q81/q92 —
        ``where ctr_total_return > (select avg(ctr_total_return)*1.2
        from ctr ctr2 where ctr1.ctr_store_sk = ctr2.ctr_store_sk)``.
        The subquery becomes ``select k, AGG as __sv ... group by k``;
        each outer row joins its group's scalar and the comparison
        filters.  Rows with no group drop either way (NULL compare),
        so an inner join is exact."""
        sub_ast = c.right if isinstance(c.right, ScalarSub) else c.left
        outer_ast = c.left if isinstance(c.right, ScalarSub) else c.right
        op = c.op
        if isinstance(c.left, ScalarSub):
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        sub = sub_ast.query
        if not isinstance(sub, SelectStmt) or sub.from_item is None or \
                sub.group_by or sub.having or sub.distinct or sub.ctes \
                or len(sub.items) != 1:
            raise SqlError("unsupported correlated scalar subquery "
                           "(single aggregate item expected)")

        def has_agg(a: Ast) -> bool:
            found = []

            def fn(n):
                if isinstance(n, Func) and n.fname in _AGG_FUNCS:
                    found.append(n)
                return n
            _transform(a, fn)
            return bool(found)
        if not has_agg(sub.items[0].e):
            # a non-aggregate correlated scalar would need runtime
            # more-than-one-row enforcement; the group-by rewrite would
            # silently dedup instead — refuse
            raise SqlError(
                "correlated scalar subquery must select a single "
                "aggregate expression")
        # probe scope: which conjuncts are inner-only vs correlation
        # equalities (same split as _decorrelate_exists, but keeping
        # the RAW inner asts so the rewritten SelectStmt re-lowers)
        _, inner_scope = self.lower_from(sub.from_item)
        inner_rest: List[Ast] = []
        inner_key_asts: List[Ast] = []
        outer_keys: List[ec.Expression] = []
        for cj in (_split_conjuncts(_factor_or(sub.where))
                   if sub.where is not None else []):
            try:
                _canon_idents(inner_scope, cj)
                inner_rest.append(cj)
                continue
            except SqlError:
                pass
            matched = False
            if isinstance(cj, Bin) and cj.op == "=":
                for a, b in ((cj.left, cj.right), (cj.right, cj.left)):
                    try:
                        _canon_idents(inner_scope, a)
                        ob = _canon_idents(outer_scope, b)
                    except SqlError:
                        continue
                    inner_key_asts.append(a)
                    outer_keys.append(self.lower_expr(ob, outer_scope))
                    matched = True
                    break
            if not matched:
                raise SqlError(
                    "correlated scalar subquery predicates must be "
                    "equalities between inner and outer columns (plus "
                    "inner-only conjuncts)"
                    + (f"; original subquery error: {probe_err}"
                       if probe_err else ""))
        if not inner_key_asts:
            raise SqlError(
                "scalar subquery references unknown columns"
                + (f"; original subquery error: {probe_err}"
                   if probe_err else ""))
        # rebuild: select k0.., AGG as __sv from ... where inner_rest
        # group by k0.. — then re-lower through the normal pipeline
        where_ast = None
        for r in inner_rest:
            where_ast = r if where_ast is None else \
                Bin("and", where_ast, r)
        new_items = tuple(
            SelectItem(a, f"__ck{i}")
            for i, a in enumerate(inner_key_asts)
        ) + (SelectItem(sub.items[0].e, "__sv"),)
        new_sub = dataclasses.replace(
            sub, items=new_items, where=where_ast,
            group_by=tuple(inner_key_asts), group_sets=None,
            order_by=(), limit=None, offset=None)
        inner = self.lower(new_sub)
        fields = list(inner.schema)
        rrefs = [ec.AttributeReference(f.name, f.dtype, f.nullable)
                 for f in fields[:-1]]
        sv = fields[-1]
        sv_ref = ec.AttributeReference(sv.name, sv.dtype, sv.nullable)
        joined = L.Join(plan, inner, "inner", outer_keys, rrefs, None)
        lhs = self.lower_expr(outer_ast, outer_scope)
        cmp_cls = {"<": ep.LessThan, "<=": ep.LessThanOrEqual,
                   ">": ep.GreaterThan, ">=": ep.GreaterThanOrEqual,
                   "=": ep.EqualTo}
        cond = ep.Not(ep.EqualTo(lhs, sv_ref)) if op == "<>" else \
            cmp_cls[op](lhs, sv_ref)
        filtered = L.Filter(cond, joined)
        # restore the outer schema (the helper columns must not leak
        # into star expansion or set operations downstream)
        proj = [ec.AttributeReference(f.name, f.dtype, f.nullable)
                for f in plan.schema]
        return L.Project(proj, filtered)

    # -- window -------------------------------------------------------------
    def lower_window(self, w: WindowE, alias: str,
                     scope: _Scope) -> L.WindowFunc:
        f = w.func
        lower = lambda a: self.lower_expr(a, scope)  # noqa: E731
        n = f.fname
        if n == "row_number":
            func: ec.Expression = ewin.RowNumber()
        elif n == "rank":
            func = ewin.Rank()
        elif n == "dense_rank":
            func = ewin.DenseRank()
        elif n == "ntile":
            func = ewin.NTile(_pyval(lower(f.args[0])))
        elif n == "percent_rank":
            func = ewin.PercentRank()
        elif n == "cume_dist":
            func = ewin.CumeDist()
        elif n in ("lead", "lag"):
            off = _pyval(lower(f.args[1])) if len(f.args) > 1 else 1
            dflt = _pyval(lower(f.args[2])) if len(f.args) > 2 else None
            cls = ewin.Lead if n == "lead" else ewin.Lag
            func = cls(lower(f.args[0]), off, dflt)
        elif n in _AGG_FUNCS:
            func = _make_agg(f, lower)
        else:
            raise SqlError(f"unknown window function {n}")
        pb = [lower(p) for p in w.partition]
        ob = [L.SortOrder(lower(o.e), o.asc, o.nulls_first)
              for o in w.order]
        if w.frame is not None:
            frame = w.frame
        elif ob:
            frame = ("range", None, 0)
        else:
            frame = ("rows", None, None)
        return L.WindowFunc(func, L.WindowSpec(pb, ob, frame), alias)

    # -- expressions --------------------------------------------------------
    def lower_expr(self, ast: Ast, scope: _Scope) -> ec.Expression:
        lower = lambda a: self.lower_expr(a, scope)  # noqa: E731
        if isinstance(ast, Lit):
            return ec.Literal(ast.value)
        if isinstance(ast, Ident):
            return scope.resolve(ast.parts)
        if isinstance(ast, Res):
            return scope.resolve_actual(ast.cname)
        if isinstance(ast, Interval):
            raise SqlError("INTERVAL only valid next to +/- of a date")
        if isinstance(ast, Bin):
            return self.lower_bin(ast, scope)
        if isinstance(ast, Un):
            if ast.op == "not":
                return ep.Not(lower(ast.operand))
            return ea.UnaryMinus(lower(ast.operand))
        if isinstance(ast, Between):
            e = lower(ast.operand)
            cond = ep.And(ep.GreaterThanOrEqual(e, lower(ast.lo)),
                          ep.LessThanOrEqual(e, lower(ast.hi)))
            return ep.Not(cond) if ast.negated else cond
        if isinstance(ast, InList):
            e = lower(ast.operand)
            vals = []
            all_lits = all(isinstance(i, Lit) for i in ast.items)
            if all_lits:
                vals = [i.value for i in ast.items]
                out: ec.Expression = ep.In(e, vals)
            else:
                out = ep.EqualTo(e, lower(ast.items[0]))
                for i in ast.items[1:]:
                    out = ep.Or(out, ep.EqualTo(e, lower(i)))
            return ep.Not(out) if ast.negated else out
        if isinstance(ast, LikeE):
            out = es.Like(lower(ast.operand), ec.Literal(ast.pattern))
            return ep.Not(out) if ast.negated else out
        if isinstance(ast, IsNullE):
            return (ep.IsNotNull if ast.negated else ep.IsNull)(
                lower(ast.operand))
        if isinstance(ast, Case):
            if ast.operand is not None:
                op = lower(ast.operand)
                branches = [(ep.EqualTo(op, lower(c)), lower(v))
                            for c, v in ast.whens]
            else:
                branches = [(lower(c), lower(v)) for c, v in ast.whens]
            els = lower(ast.els) if ast.els is not None else None
            return econd.CaseWhen(branches, els)
        if isinstance(ast, CastE):
            return ecast.Cast(lower(ast.operand),
                              _sql_type(ast.typename, ast.p1, ast.p2))
        if isinstance(ast, ScalarSub):
            sub = self.lower(ast.query)
            if len(sub.schema) != 1:
                raise SqlError("scalar subquery must return one column")
            tbl = self._exec_sub(sub)
            if tbl.num_rows > 1:
                raise SqlError("scalar subquery returned more than one row")
            val = tbl.column(0)[0].as_py() if tbl.num_rows else None
            return ec.Literal(val, sub.schema.fields[0].dtype)
        if isinstance(ast, InSub):
            # expression position (inside OR / SELECT / CASE): an
            # UNCORRELATED subquery evaluates eagerly to an IN-list
            # (the q45 shape: ``... or i_item_id in (select ...)``);
            # correlated ones only decorrelate as top-level conjuncts
            try:
                sub = self.lower(ast.query)
            except SqlError as err:
                raise SqlError(
                    "IN (subquery) in expression position must be "
                    "uncorrelated (correlated IN only decorrelates as "
                    f"a top-level WHERE conjunct); subquery error: "
                    f"{err}") from err
            if len(sub.schema) != 1:
                raise SqlError("IN subquery must return one column")
            tbl = self._exec_sub(sub)
            vals = tbl.column(0).to_pylist()
            has_null = any(v is None for v in vals)
            vals = [v for v in vals if v is not None]
            e = ep.In(self.lower_expr(ast.operand, scope), vals)
            if ast.negated:
                if has_null:
                    # Spark 3VL: x NOT IN (set with NULL) is FALSE when
                    # x matches a non-null member, else NULL — never
                    # TRUE.  (Folding to plain FALSE would flip under
                    # an enclosing NOT.)
                    return econd.CaseWhen(
                        [(e, ec.Literal(False, T.BOOL))],
                        ec.Literal(None, T.BOOL))
                return ep.Not(e)
            return e
        if isinstance(ast, Exists):
            raise SqlError(
                "EXISTS only supported as a top-level WHERE conjunct")
        if isinstance(ast, WindowE):
            raise SqlError("window functions only allowed in SELECT/ORDER BY")
        if isinstance(ast, Func):
            return self.lower_func(ast, scope)
        if isinstance(ast, Star):
            raise SqlError("* only allowed in SELECT list or COUNT(*)")
        raise SqlError(f"cannot lower {ast!r}")

    def lower_bin(self, ast: Bin, scope: _Scope) -> ec.Expression:
        lower = lambda a: self.lower_expr(a, scope)  # noqa: E731
        op = ast.op
        # date +/- interval
        if op in ("+", "-") and isinstance(ast.right, Interval):
            iv = ast.right
            if iv.unit != "day":
                raise SqlError(f"INTERVAL unit {iv.unit} not supported")
            base = lower(ast.left)
            return (edt.DateAdd if op == "+" else edt.DateSub)(
                base, ec.Literal(iv.n))
        if op == "+" and isinstance(ast.left, Interval):
            iv = ast.left
            if iv.unit != "day":
                raise SqlError(f"INTERVAL unit {iv.unit} not supported")
            return edt.DateAdd(lower(ast.right), ec.Literal(iv.n))
        l, r = lower(ast.left), lower(ast.right)
        if op == "or":
            return ep.Or(l, r)
        if op == "and":
            return ep.And(l, r)
        if op == "=":
            return ep.EqualTo(l, r)
        if op == "<>":
            return ep.Not(ep.EqualTo(l, r))
        if op == "<":
            return ep.LessThan(l, r)
        if op == "<=":
            return ep.LessThanOrEqual(l, r)
        if op == ">":
            return ep.GreaterThan(l, r)
        if op == ">=":
            return ep.GreaterThanOrEqual(l, r)
        if op == "+":
            return ea.Add(l, r)
        if op == "-":
            return ea.Subtract(l, r)
        if op == "*":
            return ea.Multiply(l, r)
        if op == "/":
            return ea.Divide(l, r)
        if op == "%":
            return ea.Remainder(l, r)
        if op == "||":
            return es.ConcatStrings(l, r)
        raise SqlError(f"unknown operator {op}")

    def lower_func(self, f: Func, scope: _Scope) -> ec.Expression:
        from . import functions as F
        from .column import Col
        lower = lambda a: self.lower_expr(a, scope)  # noqa: E731
        n = f.fname
        if n in _AGG_FUNCS:
            raise SqlError(
                f"aggregate {n} not allowed here (no GROUP BY context)")
        args = [lower(a) for a in f.args]
        cargs = [Col(a) for a in args]

        def unwrap(x):
            return x.expr if isinstance(x, Col) else x

        simple = {
            "abs": F.abs, "sqrt": F.sqrt, "exp": F.exp, "ln": F.log,
            "log": F.log, "log2": F.log2, "log10": F.log10, "sin": F.sin,
            "cos": F.cos, "tan": F.tan, "asin": F.asin, "acos": F.acos,
            "atan": F.atan, "floor": F.floor, "ceil": F.ceil,
            "ceiling": F.ceil, "sign": F.signum, "signum": F.signum,
            "degrees": F.degrees, "radians": F.radians,
            "upper": F.upper, "ucase": F.upper, "lower": F.lower,
            "lcase": F.lower, "length": F.length,
            "char_length": F.length, "character_length": F.length,
            "trim": F.trim, "ltrim": F.ltrim, "rtrim": F.rtrim,
            "reverse": F.reverse, "initcap": F.initcap,
            "year": F.year, "month": F.month, "day": F.dayofmonth,
            "dayofmonth": F.dayofmonth, "quarter": F.quarter,
            "dayofweek": F.dayofweek, "weekday": F.weekday,
            "dayofyear": F.dayofyear, "hour": F.hour, "minute": F.minute,
            "second": F.second, "last_day": F.last_day,
            "to_date": F.to_date, "isnan": F.isnan, "md5": F.md5,
        }
        if n in simple:
            return unwrap(simple[n](*cargs))
        if n in ("pow", "power"):
            return unwrap(F.pow(cargs[0], cargs[1]))
        if n == "atan2":
            return ea.Atan2(args[0], args[1])
        if n in ("mod",):
            return ea.Remainder(args[0], args[1])
        if n == "pmod":
            return ea.Pmod(args[0], args[1])
        if n == "round":
            return unwrap(F.round(cargs[0],
                                  _pyval(args[1]) if len(args) > 1 else 0))
        if n == "greatest":
            return unwrap(F.greatest(*cargs))
        if n == "least":
            return unwrap(F.least(*cargs))
        if n in ("substring", "substr"):
            return unwrap(F.substring(cargs[0], _pyval(args[1]),
                                      _pyval(args[2])))
        if n == "concat":
            return unwrap(F.concat(*cargs))
        if n == "concat_ws":
            return unwrap(F.concat_ws(_pyval(args[0]), *cargs[1:]))
        if n == "replace":
            return unwrap(F.replace(cargs[0], _pyval(args[1]),
                                    _pyval(args[2])))
        if n == "repeat":
            return unwrap(F.repeat(cargs[0], _pyval(args[1])))
        if n == "lpad":
            return unwrap(F.lpad(cargs[0], _pyval(args[1]),
                                 _pyval(args[2]) if len(args) > 2 else " "))
        if n == "rpad":
            return unwrap(F.rpad(cargs[0], _pyval(args[1]),
                                 _pyval(args[2]) if len(args) > 2 else " "))
        if n == "instr":
            return unwrap(F.instr(cargs[0], _pyval(args[1])))
        if n == "locate":
            return unwrap(F.locate(_pyval(args[0]), cargs[1],
                                   _pyval(args[2]) if len(args) > 2 else 1))
        if n == "regexp_replace":
            return unwrap(F.regexp_replace(cargs[0], _pyval(args[1]),
                                           _pyval(args[2])))
        if n == "regexp_extract":
            return unwrap(F.regexp_extract(
                cargs[0], _pyval(args[1]),
                _pyval(args[2]) if len(args) > 2 else 1))
        if n == "date_add":
            return unwrap(F.date_add(cargs[0], _pyval(args[1])))
        if n == "date_sub":
            return unwrap(F.date_sub(cargs[0], _pyval(args[1])))
        if n == "datediff":
            return unwrap(F.datediff(cargs[0], cargs[1]))
        if n == "coalesce":
            return econd.Coalesce(*args)
        if n in ("nvl", "ifnull"):
            return econd.Coalesce(*args)
        if n == "nullif":
            return econd.If(ep.EqualTo(args[0], args[1]),
                            ec.Literal(None, args[0].dtype()), args[0])
        if n == "isnull":
            return ep.IsNull(args[0])
        if n == "isnotnull":
            return ep.IsNotNull(args[0])
        if n == "nanvl":
            return econd.NaNvl(args[0], args[1])
        if n == "if":
            return econd.If(args[0], args[1], args[2])
        if n == "hash":
            return emisc.Murmur3Hash(*args)
        raise SqlError(f"unknown function {n}")


def sql_to_plan(sql: str, session, views) -> L.LogicalPlan:
    ast = parse_sql(sql)
    plan = _Lowerer(session, views).lower(ast)
    from ..plan.logical_opt import optimize
    return optimize(plan)
