"""Profile one TPC-DS query's warm flushes on chip."""
import sys, time, traceback
sys.path.insert(0, "benchmarks")
import tpcds
from tpcds_queries import QUERIES
from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.columnar import pending

qname = sys.argv[1] if len(sys.argv) > 1 else "q3"
s = TpuSession(TpuConf({
    "spark.rapids.tpu.sql.enabled": True,
    "spark.rapids.tpu.sql.batchSizeRows": 1 << 22,
    "spark.rapids.tpu.sql.reader.batchSizeRows": 1 << 22,
}))
tpcds.register(s, "/tmp/tpcds_data/sf1.0_v5")
sql = QUERIES[qname]
t0 = time.perf_counter()
s.sql(sql).collect()
print(f"first {time.perf_counter()-t0:.1f}s", flush=True)

orig = pending.flush
events = []
def spy():
    t0 = time.perf_counter()
    orig()
    dt = time.perf_counter() - t0
    if dt > 0.005:
        st = [f"{f.name}:{f.lineno}" for f in
              traceback.extract_stack()[-8:-2]
              if "spark_rapids_tpu" in (f.filename or "")
              or "tpcds" in (f.filename or "")]
        events.append((dt, " <- ".join(reversed(st))))
pending.flush = spy

for i in range(2):
    events.clear()
    t0 = time.perf_counter()
    rows = s.sql(sql).collect()
    wall = time.perf_counter() - t0
    print(f"warm{i} {wall:.2f}s rows={len(rows)} flushes>5ms={len(events)}",
          flush=True)
for dt, st in events:
    print(f"  {dt*1e3:7.0f} ms  {st}", flush=True)
