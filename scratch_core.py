"""Grab the real ws agg core + args, time it standalone."""
import time, sys
import jax, jax.numpy as jnp
import numpy as np
from bench import build_df
from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.exec import tpu_aggregate as TA

captured = {}
orig = TA.TpuHashAggregate._fused_whole_stage_core
def spy(self, batch, emit_buffers=True, out_cap=None):
    r = orig(self, batch, emit_buffers, out_cap)
    if r is not None and "args" not in captured:
        captured["args"] = (tuple(c.data for c in batch.columns),
                            tuple(c.validity for c in batch.columns),
                            batch.rows_dev)
        captured["self"] = self
        captured["emit"] = emit_buffers
        captured["out_cap"] = out_cap
    return r
TA.TpuHashAggregate._fused_whole_stage_core = spy

s = TpuSession(TpuConf({
    "spark.rapids.tpu.sql.enabled": True,
    "spark.rapids.tpu.sql.batchSizeRows": 1 << 22,
    "spark.rapids.tpu.sql.reader.batchSizeRows": 1 << 22,
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": False,
}))
df = build_df(s, 4_000_000, 4)
df.to_arrow()
print("pipeline warm; core captured:", "args" in captured, flush=True)

# find the cached jitted core
self = captured["self"]
mkey = [k for k in self._ws_memo if isinstance(k, tuple) and k and k[0] != "fpo" and k != ("tprep",)]
core = None
for k, v in TA.TpuHashAggregate._CORE_CACHE.items():
    if v not in (None, False) and isinstance(k, tuple) and k and k[0] == "ws":
        core = v; ck = k
if core is None:
    print("no ws core found", list(TA.TpuHashAggregate._CORE_CACHE.keys())[:5])
    sys.exit(1)
datas, valids, nrows = captured["args"]

def force(out):
    ng, fit, pairs = out
    return float(jnp.sum(pairs[0][0].astype(jnp.float32)).item())

t0 = time.perf_counter(); force(core(datas, valids, nrows))
print(f"core 1st {time.perf_counter()-t0:.2f}s", flush=True)
for i in range(3):
    t0 = time.perf_counter()
    force(core(datas, valids, nrows))
    print(f"core run {time.perf_counter()-t0:.2f}s", flush=True)
