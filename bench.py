"""Benchmark driver: TPU engine vs CPU oracle engine on a representative

SQL workload (scan -> filter -> project -> hash-aggregate -> join), the
shape of the reference's headline mortgage-ETL / TPC queries
(BASELINE.md).  The aggregate output (~1000 groups) is joined against a
small dimension table, so the headline number exercises the join +
exchange machinery, not just filter/project/agg.  Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

value        = TPU engine throughput (M rows/s through the pipeline)
vs_baseline  = TPU time / CPU-engine time speedup (the reference's
               headline metric is end-to-end speedup vs CPU Spark;
               our CPU engine is the stand-in oracle)

Float mode: the HEADLINE numbers are the DEFAULT configuration
(variableFloatAgg off — exact-results parity with the reference's
default).  The opt-in f32-accumulation fast path is reported in the
secondary keys (variable_Mrows_s / variable_vs_baseline).

History note (the apparent r04 -> r05 "drop"): BENCH_r04's headline
value (32.15 Mrows/s) was measured in VARIABLE float mode — at r04 the
exact path ran at 1.29 Mrows/s and the headline reported the fast
path.  r05 switched the headline to the exact-mode default (17.63
Mrows/s) while the variable number *improved* to 33.59.  So the
32.2 -> 17.6 move is a headline *definition* change, not a regression:
across the same interval exact-mode throughput went 1.29 -> 17.63 (13x)
and variable-mode 32.15 -> 33.59.

Pipeline split: since r06 the engine drains partitions morsel-parallel
(spark.rapids.tpu.exec.pipeline.*, exec/pipeline.py).  The headline
runs with the pipeline ON (parallelism/prefetch pinned to 4, like the
batch-size tuning above — the auto default is min(4, cpu) and bench
hosts vary); pipeline_off_Mrows_s re-measures exact mode with the
pipeline disabled so each BENCH_r shows the on/off delta.  Output is
bit-identical either way (tests/test_pipeline.py).

Superstage split: since r06 the planner carves exchange-delimited
regions into one-dispatch superstages (spark.rapids.tpu.sql.superstage,
compile/).  superstage_off_Mrows_s re-measures exact mode with carving
disabled, and the flushes / superstage_off_flushes keys report the warm
per-query device round trips under each mode (the cost model the
compiler optimizes).  Output is bit-identical either way
(tests/test_compile.py).

Stats split: since r07 the runtime stats plane (obs/stats.py,
spark.rapids.tpu.obs.stats.*) is ON in the headline configuration —
it is designed to add zero device flushes, so its cost is pure host
work.  stats_off_Mrows_s re-measures the exact headline with stats
collection disabled and stats_overhead_pct reports the on/off overhead
(budget: <= 2%, asserted by ci/stats_smoke.py with a loose bound).
dispatch_p50_ms / dispatch_p95_ms are the warm query's device-dispatch
duration percentiles from the StatsProfile's "all" roll-up.

Memory split: since r11 the memory plane (obs/memplane.py,
spark.rapids.tpu.obs.mem.*) prices every tier move the catalog makes.
peak_device_bytes is the headline session's device-byte peak (set by
the cold warmup run — warm reruns free their buffers and do not
advance it), spill_ms the active spill time inside the warm window, and
spill_tax_pct the share of the headline wall spent moving buffers
between tiers (spill + unspill) — 0.0 on a bench host whose budget
fits the working set, which is itself the claim the key documents.

Fleet split: since r15 the service stage runs with a history dir
configured (obs/history.py, obs/anomaly.py), so the burst prices the
fleet longitudinal plane: history_rows must equal the submission
count exactly (gated "exact" — any drop or double-count is a
regression), anomaly_checks counts the sentinel's EWMA folds, and
history_write_p99_us bounds the background writer's append latency
(the plane's only I/O, strictly off the query path).

Obs tax split: since r17 the observability layer meters ITSELF
(obs/overhead.py).  all_planes_off_Mrows_s re-measures the exact
headline with every obs conf disabled, all_planes_on_vs_off is the
off/on time ratio the perf gate bounds at >= 0.98 (the <= 2% total
overhead budget) — measured as an interleaved on/off pair of fresh
runs so run-order drift cannot masquerade as tax — and obs_self_ms
is the self-meter's per-plane attribution of one warm headline query
— where the tax lives, not just what it sums to.  Results are
identical planes-on vs planes-off (tests/test_obs_overhead.py pins
the arrow sha), so the ratio prices pure host-side bookkeeping.
"""
import json
import sys
import time

import numpy as np


def build_df(session, n_rows: int, num_partitions: int):
    rng = np.random.default_rng(7)
    from spark_rapids_tpu.api import functions as F
    data = {
        "k": rng.integers(0, 1000, n_rows).astype(np.int64),
        "a": rng.integers(-100_000, 100_000, n_rows).astype(np.int64),
        "x": rng.random(n_rows),
        "y": rng.random(n_rows),
    }
    df = session.create_dataframe(data, num_partitions=num_partitions)
    # small dimension side: one row per group key, joined post-agg
    dim = session.create_dataframe({
        "dk": np.arange(1000, dtype=np.int64),
        "w": rng.random(1000),
    }, num_partitions=1)
    agg = (df.filter((F.col("x") > 0.1) & (F.col("a") % 7 != 0))
             .with_column("z", F.col("x") * F.col("y") + F.col("a"))
             .group_by("k")
             .agg(F.sum("z").alias("sz"), F.count().alias("c"),
                  F.max("x").alias("mx")))
    joined = (agg.join(dim, agg["k"] == dim["dk"], "inner")
                 .select(F.col("k"), F.col("sz"), F.col("c"),
                         (F.col("mx") * F.col("w")).alias("mw")))
    return joined


def run_engine(enabled: bool, n_rows: int, num_partitions: int,
               repeats: int, variable_float: bool = True,
               pipeline: bool = True, superstage: bool = True,
               stats: bool = True, obs_planes: bool = True):
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.obs import memplane as _memplane
    # tuned like the reference's benchmark guides tune Spark: large
    # scan batches keep the per-batch fixed costs (dispatch + transfer
    # round trips) amortized on the accelerator
    conf = {
        "spark.rapids.tpu.sql.enabled": enabled,
        "spark.rapids.tpu.sql.batchSizeRows": 1 << 22,
        "spark.rapids.tpu.sql.reader.batchSizeRows": 1 << 22,
        # f32 accumulation opt-in for the variable-mode measurement
        # (defaults off to match the reference's exact-results default;
        # the EXACT-mode number is measured separately and reported in
        # the same line as exact_vs_baseline)
        "spark.rapids.tpu.sql.variableFloatAgg.enabled": variable_float,
        # morsel pipeline pinned (not auto) so the measurement does not
        # depend on the bench host's core count; pipeline=False is the
        # pipeline_off_Mrows_s measurement
        "spark.rapids.tpu.exec.pipeline.enabled": pipeline,
        "spark.rapids.tpu.exec.pipelineParallelism": 4,
        "spark.rapids.tpu.exec.pipelinePrefetchDepth": 4,
        # superstage carving (compile/): superstage=False is the
        # superstage_off measurement of the same exact-mode query
        "spark.rapids.tpu.sql.superstage": superstage,
        # runtime stats plane (obs/stats.py): stats=False is the
        # stats_off measurement behind stats_overhead_pct
        "spark.rapids.tpu.obs.stats.enabled": stats,
    }
    if not obs_planes:
        # observability tax measurement: EVERY obs conf off — the
        # all_planes_on_vs_off denominator.  Results must be identical
        # to the planes-on run (tests/test_obs_overhead.py pins the
        # arrow sha), so the ratio prices pure host-side bookkeeping
        conf.update({
            "spark.rapids.tpu.obs.trace.enabled": False,
            "spark.rapids.tpu.obs.flightRecorder.enabled": False,
            "spark.rapids.tpu.obs.stats.enabled": False,
            "spark.rapids.tpu.obs.timeline.enabled": False,
            "spark.rapids.tpu.obs.compile.enabled": False,
            "spark.rapids.tpu.obs.slo.enabled": False,
            "spark.rapids.tpu.obs.net.enabled": False,
            "spark.rapids.tpu.obs.mem.enabled": False,
            "spark.rapids.tpu.obs.cost.enabled": False,
            "spark.rapids.tpu.obs.doctor.enabled": False,
            "spark.rapids.tpu.obs.history.enabled": False,
            "spark.rapids.tpu.obs.anomaly.enabled": False,
            "spark.rapids.tpu.obs.overhead.enabled": False,
        })
    s = TpuSession(TpuConf(conf))
    # build the query ONCE: the measurement is query execution over
    # loaded data (the reference's benchmark shape), not datagen/upload
    df = build_df(s, n_rows, num_partitions)
    # cold run: compile cache + device-resident input warmup.  For the
    # FIRST engine run in the process this is the true cold-start cost
    # (every jit cache empty) — cold_exact_Mrows_s / cold_vs_warm_ratio
    # report it for the headline config
    t0 = time.perf_counter()
    df.to_arrow()
    cold_t = time.perf_counter() - t0
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = df.to_arrow()
        dt = time.perf_counter() - t0
        best = min(best, dt)
    assert out.num_rows > 0
    # warm per-query device round trips (api/session.py counts the
    # pending-pool flush delta around each execution) — the flushes
    # column every BENCH_r now reports alongside throughput
    flushes = getattr(s, "last_query_flushes", None)
    prof = getattr(s, "last_stats_profile", None)
    # performance plane (obs/timeline.py, obs/compile_watch.py): the
    # warm query's device-utilization lane + inline-compile ms
    perf = {"timeline": getattr(s, "last_query_timeline", None),
            "inline_compile_ms": getattr(
                s, "last_query_inline_compile_ms", None),
            "netplane": getattr(s, "last_query_netplane", None),
            # memory plane (obs/memplane.py): the same warm query's
            # spill-pricing roll-up, plus the session's device-byte
            # peak (warm reruns do not advance the peak themselves —
            # the cold warmup run is what set it)
            "memplane": getattr(s, "last_query_memplane", None),
            "mem_peak_bytes": _memplane.stats_section()["peak"]["bytes"],
            # static PV-FLUSH prediction for the same warm query
            # (analysis/flush_budget.py — must equal `flushes`)
            "predicted_flushes": getattr(
                s, "last_query_predicted_flushes", None),
            # per-site declared-transfer counts of the same warm query
            # (analysis/residency.py registry — the event-log field
            # the doctor joins against host_staging)
            "declared_transfer_sites": dict(getattr(
                s, "last_query_declared_transfers", None) or {}),
            # device-compute cost roll-up (obs/costplane.py): the
            # warm query's roofline verdict, achieved rates and the
            # padding-waste tax of the AOT bucket lattice
            "costplane": getattr(s, "last_query_costplane", None),
            # cross-plane doctor verdict for the same warm query
            # (obs/doctor.py)
            "diagnosis": getattr(s, "last_query_diagnosis", None),
            # per-plane obs self-cost of the same warm query (the
            # obs_self block obs/overhead.py puts on the event record)
            "obs_self": (getattr(s, "last_query_event", None)
                         or {}).get("obs_self"),
            "cold_s": cold_t}
    return best, flushes, (prof.to_dict() if prof is not None
                           else None), perf


def audited_programs():
    """Run the jaxpr program audit (analysis/program_audit.py) and
    return the audited program names — the bench record documents WHICH
    device programs the numbers were measured over, statically vetted
    (no host callbacks / float surprises / data-dependent shapes).
    Mesh programs need >= 2 devices to build; on a single-device bench
    host the rest are still audited."""
    try:
        import jax
        from spark_rapids_tpu.analysis.program_audit import (audit_all,
                                                             collect_specs)
        specs = collect_specs()
        if jax.local_device_count() < 2:
            specs = [s for s in specs if not s.name.startswith("mesh_")]
        report = audit_all(specs)
        if not report.ok:
            return {"findings": [str(f) for f in report.findings]}
        return sorted(report.audited)
    except Exception:  # noqa: BLE001 - reporting only, never gate bench
        return None


def undeclared_transfers():
    """Static residency verdict for the measured build
    (analysis/residency.py): RES findings the interprocedural escape
    analysis proves on the execution spine, plus declared-site registry
    coverage gaps and parse errors.  Must be 0 — the perf baseline
    gates it exact, so a change that reintroduces a hidden device->host
    sync fails the perf gate, not a profiling session."""
    try:
        import os
        from spark_rapids_tpu.analysis import residency
        root = os.path.dirname(os.path.abspath(__file__))
        report = residency.analyze_project(root)
        gaps = residency.coverage_gaps(root)
        return len(report.findings) + len(report.errors) + len(gaps)
    except Exception:  # noqa: BLE001 - reporting only, never gate bench
        return None


def _aot_warmup_total():
    """Compiles the warmup daemon absorbed (compile/aot.py) — nonzero
    once the service stage has run with warmup enabled."""
    try:
        from spark_rapids_tpu.compile import aot
        return aot.warmup_total()
    except Exception:  # noqa: BLE001 - reporting only, never gate bench
        return None


def compile_cache_hit_pct():
    """Process-wide engine JIT cache hit rate (registry counter
    tpu_compile_cache_requests_total over every cache) — after a full
    bench run this is the share of compile-cache lookups the shape
    bucketing (compile/aot.py) kept on the hit path."""
    from spark_rapids_tpu.obs.registry import COMPILE_CACHE
    hits = misses = 0.0
    for c in COMPILE_CACHE.children():
        lab = dict(c.labels)
        if lab.get("outcome") == "hit":
            hits += c.value
        elif lab.get("outcome") == "miss":
            misses += c.value
    total = hits + misses
    return round(hits / total * 100, 2) if total else None


def planner_cold_ms():
    """The true cold planner-path latency: the first-in-process
    planning of the headline shape (the first ``run_engine`` call's
    plan-cache miss — every rule table, verifier pass and fingerprint
    walk first-touch included).  This is what a fresh serving
    process's first query of a shape pays; the certificate-replay hit
    latency (``planner_path_ms_warm``) is what every repeat pays.
    Must be read right after the FIRST engine run: later sessions'
    conf changes invalidate the entry and re-store it with a
    warm-process miss latency."""
    from spark_rapids_tpu.cache import plan_cache
    top = plan_cache.stats_section().get("top") or []
    return top[0]["cold_ms"] if top else None


def measure_service_p99(n_rows: int = 200_000, submissions: int = 8,
                        cold_ms: float = None):
    """Tenant p99 through the serving front-end (service/server.py):
    submit a small burst as tenant "bench" and read the SLO plane's
    reservoir percentile from stats().  Small rows on purpose — this
    measures the serving overhead distribution, not throughput.

    The same burst prices the fleet plane (obs/history.py,
    obs/anomaly.py): the service runs with a history dir configured,
    so every terminal query folds one JSONL row through the bounded
    background writer and the sentinel.  history_rows must equal the
    submission count exactly (nothing dropped, nothing double-counted),
    anomaly_checks counts the sentinel's per-(fingerprint, key) folds,
    and history_write_p99_us is the background append p99 — the
    off-query-path budget the perf gate bounds.

    Since r16 the burst ALSO prices the plan cache + predictive
    scheduler (cache/plan_cache.py, service/scheduler.py): the warmup
    ``to_arrow`` is the one plan-cache miss of the measured window,
    every service repeat replays the stored certificate, so
    plan_cache_hit_pct / planner_path_ms_warm come straight from the
    cache ledger (planner_path_ms_cold is the process-cold miss
    snapshot passed in as ``cold_ms`` — see :func:`planner_cold_ms`).
    The burst's ``submissions`` folds freeze the shape's exec_ms
    baseline (warmupMinRuns default 8), so the trailing predicted
    submissions carry exec_ms predictions and predicted_exec_err_pct
    is the scheduler's honesty window mean over them."""
    import tempfile
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.cache import plan_cache as _plan_cache
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.obs import anomaly as _anomaly
    from spark_rapids_tpu.obs import history as _history
    from spark_rapids_tpu.service.server import QueryService
    hist_dir = tempfile.mkdtemp(prefix="bench_history_")
    s = TpuSession(TpuConf({
        "spark.rapids.tpu.sql.enabled": True,
        "spark.rapids.tpu.obs.history.dir": hist_dir,
    }))
    df = build_df(s, n_rows, 2)
    # warm the compile caches AND seed the plan cache: with the ledger
    # reset first, this is the measured cold planner pass (the one
    # miss); every service submission below replays the certificate
    _plan_cache.reset()
    df.to_arrow()
    predicted_extra = 2
    with QueryService(session=s, num_workers=2) as svc:
        # only the measured burst below lands in the fleet counters
        _history.reset()
        _anomaly.reset()
        handles = [svc.submit(df, tenant="bench")
                   for _ in range(submissions)]
        for h in handles:
            h.result(timeout=120)
        # the burst's folds froze the shape's exec_ms baseline — these
        # trailing submissions are assessed WITH a prediction, and
        # their completion folds |predicted - actual| into the
        # scheduler's honesty window (predicted_exec_err_pct)
        for _ in range(predicted_extra):
            svc.submit(df, tenant="bench").result(timeout=120)
        snap = svc.stats().snapshot()
    # read fleet counters AFTER shutdown: stop() drains the writer
    # queue, so write_p99_us covers every appended row
    hist = _history.stats_section()
    anom = _anomaly.stats_section()
    pc = _plan_cache.stats_section()
    top = (pc.get("top") or [{}])[0]
    pred_err = snap.get("scheduler", {}).get("pred_err_pct", {})
    return {
        "service_p99_ms": snap.get("slo", {}).get("tenants", {}).get(
            "bench", {}).get("p99_ms"),
        "history_rows": hist.get("rows"),
        "history_write_p99_us": hist.get("write_p99_us"),
        "anomaly_checks": anom.get("checks"),
        "plan_cache_hit_pct": pc.get("hit_pct"),
        "planner_path_ms_cold": (cold_ms if cold_ms is not None
                                 else top.get("cold_ms")),
        "planner_path_ms_warm": top.get("warm_ms"),
        "predicted_exec_err_pct": pred_err.get("mean"),
    }


def measure_soak(total_queries: int = 80, qps: float = 10.0,
                 rows: int = 4096):
    """Sustained mixed-traffic stage (service/soak.py): drive the
    repeat-heavy fingerprint mix through the service at open-loop QPS
    with ONE seeded worker-kill fault, and read the soak plane's six
    gated keys from the report.  Quota-driven (total_queries) rather
    than wall-driven so the stage is seconds-scale and deterministic
    in shape; the fault lands at 2s — late enough for a measured
    pre-fault p99, early enough that every run exercises the kill ->
    recovery -> re-convergence path.  leak_drift_bytes is the
    pool-idle-floor regression over the run and MUST be exactly 0
    (scale-invariant in the perf gate); anomaly_fp_rate is the
    sentinel's false-positive share over the stationary traffic."""
    import tempfile
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.obs import anomaly as _anomaly
    from spark_rapids_tpu.obs import history as _history
    from spark_rapids_tpu.service.soak import SoakConfig, run_soak
    hist_dir = tempfile.mkdtemp(prefix="bench_soak_history_")
    s = TpuSession(TpuConf({
        "spark.rapids.tpu.sql.enabled": True,
        "spark.rapids.tpu.obs.history.dir": hist_dir,
    }))
    _history.reset()
    _anomaly.reset()
    cfg = SoakConfig(
        duration_s=60.0, total_queries=total_queries, qps=qps,
        rows=rows, partitions=2, seed=42,
        faults=((2.0, "kill_pipeline_worker"),), num_workers=2)
    report = run_soak(s, cfg).to_dict()
    anom = report.get("anomaly") or {}
    return {
        "sustained_Mrows_s": round(
            (report["totals"].get("sustained_rows_s") or 0.0) / 1e6, 4),
        "soak_p99_ms": report["latency"]["p99_ms"],
        "shed_rate_pct": report["shed_rate_pct"],
        "leak_drift_bytes": report["leak_drift_bytes"],
        "anomaly_fp_rate": anom.get("fp_rate_pct", 0.0),
        "fault_recovery_ratio": report["fault_recovery_ratio"],
    }


def main():
    # 64M rows: fixed dispatch/flush overhead (the ~90ms tunnel round
    # trips) amortizes and the measurement approaches the engines'
    # sustained throughput (TPU ~25 Mrows/s through this pipeline)
    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 64_000_000
    parts = 4
    repeats = 3
    # headline: the DEFAULT conf (exact float aggregation) — the 8-bit
    # chunk-lane / two-stage-u32 exact table path (exec/tpu_aggregate)
    tpu_exact_t, tpu_flushes, tpu_prof, tpu_perf = run_engine(
        True, n_rows, parts, repeats, variable_float=False)
    # per-plane self-cost of the LAST warm headline query (the
    # per-query obs_self block from obs/overhead.py on the event-log
    # record) — warmup compiles never pollute it, so this is the
    # steady-state per-query observability tax in ms
    obs_self_ms = (tpu_perf.get("obs_self") or {}).get("planes") or {}
    cold_exact_t = tpu_perf["cold_s"]
    # the first engine run's plan-cache miss recorded the TRUE cold
    # planner path (process-cold first-touch); snapshot it before the
    # next session's conf invalidates the entry
    planner_cold = planner_cold_ms()
    # stats-off runs ADJACENT to the headline: the on/off overhead is a
    # fixed ~10-15ms of host work per query, so at small n the pair
    # must share process cache state or session-order drift swamps it
    tpu_nostats_t, _, _, _ = run_engine(True, n_rows, parts, repeats,
                                        variable_float=False, stats=False)
    # ALL planes off, measured as an interleaved on/off pair of fresh
    # runs of the same query: the aggregate observability tax the r17
    # diet budgets at <= 2% (all_planes_on_vs_off gated >= 0.98) is
    # ~1%, so run-order drift (growing compile caches, host thermal
    # state) would swamp a single distant on/off comparison.  Each leg
    # is best-of-`repeats`; the ratio takes the best leg per mode
    # across both rounds
    tpu_onadj_t = float("inf")
    tpu_noobs_t = float("inf")
    for _ in range(2):
        t_on, _, _, _ = run_engine(True, n_rows, parts, repeats,
                                   variable_float=False)
        tpu_onadj_t = min(tpu_onadj_t, t_on)
        t_off, _, _, _ = run_engine(True, n_rows, parts, repeats,
                                    variable_float=False,
                                    obs_planes=False)
        tpu_noobs_t = min(tpu_noobs_t, t_off)
    tpu_off_t, _, _, _ = run_engine(True, n_rows, parts, repeats,
                                    variable_float=False, pipeline=False)
    tpu_nostage_t, nostage_flushes, _, _ = run_engine(
        True, n_rows, parts, repeats, variable_float=False,
        superstage=False)
    tpu_var_t, _, _, _ = run_engine(True, n_rows, parts, repeats,
                                    variable_float=True)
    cpu_t, _, _, _ = run_engine(False, n_rows, parts, repeats)
    svc_keys = measure_service_p99(cold_ms=planner_cold)
    service_p99 = svc_keys["service_p99_ms"]
    soak_keys = measure_soak()
    disp = (tpu_prof or {}).get("dispatches", {}).get("all", {})
    diag = tpu_perf.get("diagnosis")
    tl = tpu_perf.get("timeline") or {}
    net = tpu_perf.get("netplane") or {}
    mem = tpu_perf.get("memplane") or {}
    cost = tpu_perf.get("costplane") or {}
    tier_ms = (mem.get("spill_ms") or 0.0) + (mem.get("unspill_ms")
                                              or 0.0)
    print(json.dumps({
        "metric": "sql_pipeline_throughput",
        "value": round(n_rows / tpu_exact_t / 1e6, 3),
        "unit": "Mrows/s",
        "vs_baseline": round(cpu_t / tpu_exact_t, 3),
        "float_mode": "exact",
        # opt-in f32-accumulation fast path (variableFloatAgg=true)
        "variable_Mrows_s": round(n_rows / tpu_var_t / 1e6, 3),
        "variable_vs_baseline": round(cpu_t / tpu_var_t, 3),
        "exact_Mrows_s": round(n_rows / tpu_exact_t / 1e6, 3),
        "exact_vs_baseline": round(cpu_t / tpu_exact_t, 3),
        # AOT compile service (compile/aot.py + service/warmup.py):
        # cold-start throughput of the headline config (first execution
        # in the process, every jit cache empty), how much slower cold
        # is than warm, the process-wide JIT cache hit share after the
        # full run, and how many compiles the admission-aware warmup
        # daemon absorbed off the query path during the service stage
        "cold_exact_Mrows_s": round(n_rows / cold_exact_t / 1e6, 3),
        "cold_vs_warm_ratio": round(cold_exact_t / tpu_exact_t, 3),
        "compile_cache_hit_pct": compile_cache_hit_pct(),
        "warmup_compiles": _aot_warmup_total(),
        # exact mode with the morsel pipeline disabled: the on/off
        # delta of intra-query pipelined drains (exec/pipeline.py)
        "pipeline_off_Mrows_s": round(n_rows / tpu_off_t / 1e6, 3),
        "pipeline_on_vs_off": round(tpu_off_t / tpu_exact_t, 3),
        # exact mode with superstage carving disabled (compile/): the
        # on/off split of one-dispatch-per-stage execution, plus the
        # warm per-query device round trips under each mode
        "superstage_off_Mrows_s": round(n_rows / tpu_nostage_t / 1e6, 3),
        "superstage_on_vs_off": round(tpu_nostage_t / tpu_exact_t, 3),
        "flushes": tpu_flushes,
        "superstage_off_flushes": nostage_flushes,
        # static PV-FLUSH prediction for the warm headline query — the
        # cross-checked dispatch model (analysis/flush_budget.py)
        "predicted_flushes": tpu_perf.get("predicted_flushes"),
        # device residency (analysis/residency.py): the warm headline
        # query's per-site declared-transfer counts, and the static
        # escape analysis verdict over the execution spine — MUST be 0
        # (gated exact by PERF_BASELINE, so a reintroduced hidden sync
        # fails ci/perf_gate.py rather than a profiling session)
        "declared_transfer_sites": tpu_perf.get("declared_transfer_sites"),
        "undeclared_transfers": undeclared_transfers(),
        # device programs statically vetted by the jaxpr auditor
        "audited_programs": audited_programs(),
        # runtime stats plane (obs/stats.py): on/off overhead of the
        # exact headline (the plane adds zero flushes, so this is pure
        # host-side cost; budget <= 2%) + the warm query's dispatch
        # duration percentiles from the StatsProfile
        "stats_off_Mrows_s": round(n_rows / tpu_nostats_t / 1e6, 3),
        "stats_overhead_pct": round(
            (tpu_exact_t - tpu_nostats_t) / tpu_nostats_t * 100, 2),
        # observability tax diet (obs/overhead.py): the exact headline
        # re-measured with EVERY obs conf off, the on/off time ratio
        # the perf gate bounds at >= 0.98 (<= ~2% total overhead), and
        # the self-meter's per-plane attribution of the planes-on
        # window (host ms billed to each plane's record paths)
        "all_planes_off_Mrows_s": round(n_rows / tpu_noobs_t / 1e6, 3),
        "all_planes_on_vs_off": round(tpu_noobs_t / tpu_onadj_t, 3),
        "obs_self_ms": obs_self_ms,
        "dispatch_p50_ms": disp.get("p50_ms"),
        "dispatch_p95_ms": disp.get("p95_ms"),
        # serving-grade performance plane (obs/timeline, compile_watch,
        # slo): the warm query's device utilization + WHY idle time
        # exists, the inline-compile ms that landed in its window
        # (~0 warm — the cold cost lives in tpu_compile_seconds), and
        # the tenant p99 through the service front-end
        "device_util_pct": tl.get("util_pct"),
        "util_gap_breakdown": tl.get("gaps"),
        "inline_compile_ms": round(
            tpu_perf.get("inline_compile_ms") or 0.0, 3),
        "service_p99_ms": service_p99,
        # shuffle transport plane (obs/netplane.py): the warm query's
        # host-drop tax (active serialize+wire+deserialize ms — the
        # baseline ROADMAP item 2's ICI shuffle must beat), wire
        # throughput and the worst per-shuffle edge skew
        "host_drop_tax_ms": net.get("host_drop_tax_ms"),
        "shuffle_wire_MBps": net.get("wire_MBps"),
        "shuffle_edge_skew": net.get("edge_skew"),
        # memory plane (obs/memplane.py): the warm headline query's
        # device-byte peak and the share of its wall spent moving
        # buffers between tiers (spill + unspill active ms)
        "peak_device_bytes": tpu_perf.get("mem_peak_bytes"),
        "spill_ms": mem.get("spill_ms"),
        "spill_tax_pct": round(tier_ms / (tpu_exact_t * 1000) * 100, 2),
        # device-compute cost plane (obs/costplane.py): the warm
        # headline query's achieved HBM bandwidth against the
        # conf-declared peak, the padding-waste share of its padded
        # bucket dispatches (the bucketRatio tax), and the roofline
        # verdict the doctor's device_compute sub-split is built on
        "achieved_GBps": cost.get("achieved_gbps"),
        "padding_waste_pct": cost.get("padding_waste_pct"),
        "roofline_verdict": cost.get("verdict"),
        # cross-plane query doctor (obs/doctor.py): the warm headline
        # query's primary-bottleneck verdict and the Amdahl speedup
        # bound for eliminating it — the one-line answer the seven
        # plane keys above feed
        "doctor_primary_cause": (diag.primary_cause
                                 if diag is not None else None),
        "doctor_primary_share_pct": (diag.primary_share_pct
                                     if diag is not None else None),
        "doctor_headroom_x": (diag.headroom[0]["bound_x"]
                              if diag is not None and diag.headroom
                              else None),
        # fleet longitudinal plane (obs/history.py, obs/anomaly.py):
        # the service burst's history-row count (must equal the
        # submission count exactly — zero drops), the sentinel's
        # per-(fingerprint, key) fold count, and the background
        # writer's append p99 (the off-query-path budget)
        "history_rows": svc_keys["history_rows"],
        "anomaly_checks": svc_keys["anomaly_checks"],
        "history_write_p99_us": svc_keys["history_write_p99_us"],
        # plan cache + predictive scheduler (cache/plan_cache.py,
        # service/scheduler.py): repeat hit rate through the service
        # burst, the process-cold planner path (what a fresh serving
        # process's first query of the shape pays) vs the
        # certificate-replay warm path every repeat pays, and the
        # scheduler's predicted-vs-actual exec_ms honesty mean
        "plan_cache_hit_pct": svc_keys["plan_cache_hit_pct"],
        "planner_path_ms_cold": svc_keys["planner_path_ms_cold"],
        "planner_path_ms_warm": svc_keys["planner_path_ms_warm"],
        "predicted_exec_err_pct": svc_keys["predicted_exec_err_pct"],
        # soak plane (service/soak.py, obs/burn.py, service/faults.py):
        # sustained mixed-traffic throughput and p99 through the
        # service under ONE seeded worker-kill fault, the open-loop
        # shed share, the pool-idle-floor memory drift over the run
        # (gated exact 0 — a nonzero value IS a leak), the anomaly
        # sentinel's false-positive share over stationary traffic, and
        # the fraction of injected fault windows whose p99 recovered
        "sustained_Mrows_s": soak_keys["sustained_Mrows_s"],
        "soak_p99_ms": soak_keys["soak_p99_ms"],
        "shed_rate_pct": soak_keys["shed_rate_pct"],
        "leak_drift_bytes": soak_keys["leak_drift_bytes"],
        "anomaly_fp_rate": soak_keys["anomaly_fp_rate"],
        "fault_recovery_ratio": soak_keys["fault_recovery_ratio"],
    }))


if __name__ == "__main__":
    main()
