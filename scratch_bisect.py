"""Bisect the ws core: which aggregate combination costs 1.2s at 1M."""
import time
import numpy as np
import jax, jax.numpy as jnp
from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.kernels import canon, aggregate as agg_k
from spark_rapids_tpu.config import TpuConf, set_active
set_active(TpuConf({}))

N = 1 << 20
G = 1000
rng = np.random.default_rng(0)
kd = jnp.asarray(rng.integers(0, G, N).astype(np.int64))
xd = jnp.asarray(rng.random(N))
yd = jnp.asarray(rng.random(N))
ad = jnp.asarray(rng.integers(-100000, 100000, N).astype(np.int64))
valid = jnp.ones(N, bool)
nrows = jnp.int32(N)

def force(v):
    return float(jnp.sum(v).item())

def bench(name, fn, *args, reps=3):
    f = jax.jit(fn)
    t0 = time.perf_counter(); force(f(*args))
    tc = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(*args)
    force(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name}: {dt*1e3:.0f} ms (c {tc:.0f}s)", flush=True)

def preplan(kd, xd, yd, ad):
    # filter + project like bench: live = x>0.1 & a%7!=0; z = x*y+a
    live = (xd > 0.1) & (ad % 7 != 0)
    z = xd * yd + ad.astype(jnp.float64)
    kcol = [Column(T.INT64, kd, valid & live)]
    words = canon.batch_key_words(kcol, nrows)
    plan = agg_k.groupby_plan(words)
    return plan, z, live

def out16(plan, arr):
    take = jnp.where(jnp.arange(1 << 16) < plan.num_groups,
                     jnp.arange(1 << 16), 0)
    return jnp.take(arr, take).astype(jnp.float32)

bench("A plan only", lambda *a: out16(preplan(*a)[0],
      preplan(*a)[0].seg_id.astype(jnp.float32)), kd, xd, yd, ad)

def vB(kd, xd, yd, ad):
    plan, z, live = preplan(kd, xd, yd, ad)
    c = agg_k.seg_count(plan, valid & live)
    return out16(plan, c.astype(jnp.float32))
bench("B plan+count", vB, kd, xd, yd, ad)

def vC(kd, xd, yd, ad):
    plan, z, live = preplan(kd, xd, yd, ad)
    s = agg_k.seg_sum(plan, z, valid & live, out_dtype=jnp.float64)
    return out16(plan, s.astype(jnp.float32))
bench("C plan+pairsum", vC, kd, xd, yd, ad)

def vD(kd, xd, yd, ad):
    plan, z, live = preplan(kd, xd, yd, ad)
    v, ok = agg_k._sorted_vals(plan, z, valid & live)
    contrib = jnp.where(ok, v, 0.0)
    s = jax.ops.segment_sum(contrib, plan.seg_id, num_segments=N)
    return out16(plan, s.astype(jnp.float32))
bench("D plan+scatter-f64-sum", vD, kd, xd, yd, ad)

def vE(kd, xd, yd, ad):
    plan, z, live = preplan(kd, xd, yd, ad)
    m = agg_k.seg_max(plan, xd, valid & live)
    return out16(plan, m.astype(jnp.float32))
bench("E plan+f64max", vE, kd, xd, yd, ad)
