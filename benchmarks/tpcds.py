"""TPC-DS-style benchmark suite over the SQL front end.

Reference baseline configs (BASELINE.json): "TPC-DS SF100 — full 99-query
sweep, local shuffle".  This module generates the TPC-DS star schema
(store_sales fact + date/item/store/customer/demographics/promotion/time
dimensions) at a row-scaled factor, writes Parquet, registers the tables
as temp views, and runs real TPC-DS query texts (Q3, Q7, Q19, Q42, Q52,
Q55, Q96, Q98 — the star-join/agg/window shapes) through
``session.sql()`` on either engine.  Q27 exercises ROLLUP + grouping();
Q98 exercises window-over-aggregate.

Usage:
  python benchmarks/tpcds.py --scale 0.01 --engine tpu
  python benchmarks/tpcds.py --scale 0.01 --compare   # TPU vs CPU timings
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ROWS_PER_SF = {"store_sales": 2_880_000, "item": 18_000,
               "customer": 100_000, "customer_address": 50_000,
               "customer_demographics": 19_208, "store": 12,
               "household_demographics": 7_200, "promotion": 300,
               "catalog_sales": 1_440_000, "web_sales": 720_000,
               "store_returns": 288_000, "catalog_returns": 144_000,
               "web_returns": 72_000, "inventory": 1_000_000,
               "catalog_page": 11_718}

DATE_SK0 = 2450815          # 1998-01-01
N_DATES = 365 * 5           # 1998-2002


def generate(data_dir: str, scale: float, seed: int = 0):
    import pyarrow as pa
    import pyarrow.parquet as papq
    rng = np.random.default_rng(seed)
    os.makedirs(data_dir, exist_ok=True)

    def write(name, table):
        papq.write_table(table, os.path.join(data_dir, f"{name}.parquet"))

    n = {k: max(int(v * scale), 64) for k, v in ROWS_PER_SF.items()}
    n["store"] = max(int(ROWS_PER_SF["store"] * max(scale, 1)), 4)

    # date_dim: real calendar over 1998-2002
    days = (np.datetime64("1998-01-01") +
            np.arange(N_DATES).astype("timedelta64[D]"))
    ymd = days.astype("datetime64[D]")
    years = ymd.astype("datetime64[Y]").astype(int) + 1970
    months = ymd.astype("datetime64[M]").astype(int) % 12 + 1
    dom = (ymd - ymd.astype("datetime64[M]")).astype(int) + 1
    dow = ((ymd.astype("datetime64[D]").astype(int) + 4) % 7)  # 0=Sunday
    qoy = (months - 1) // 3 + 1
    month_seq = (years - 1900) * 12 + months - 1
    week_seq = ((ymd.astype(int) - ymd.astype(int).min()) // 7 + 5200)
    day_names = np.array(["Sunday", "Monday", "Tuesday", "Wednesday",
                          "Thursday", "Friday", "Saturday"])
    write("date_dim", pa.table({
        "d_date_sk": (DATE_SK0 + np.arange(N_DATES)).astype(np.int64),
        "d_date": ymd,
        "d_year": years.astype(np.int32),
        "d_moy": months.astype(np.int32),
        "d_dom": dom.astype(np.int32),
        "d_qoy": qoy.astype(np.int32),
        "d_dow": dow.astype(np.int32),
        "d_day_name": day_names[dow],
        "d_month_seq": month_seq.astype(np.int32),
        "d_week_seq": week_seq.astype(np.int32),
        "d_quarter_name": np.array(
            [f"{y}Q{q}" for y, q in zip(years, qoy)]),
    }))

    write("time_dim", pa.table({
        "t_time_sk": np.arange(86400, dtype=np.int64),
        "t_time": np.arange(86400, dtype=np.int64),
        "t_hour": (np.arange(86400) // 3600).astype(np.int32),
        "t_minute": ((np.arange(86400) % 3600) // 60).astype(np.int32),
    }))

    ni = n["item"]
    write("item", pa.table({
        "i_item_sk": np.arange(ni, dtype=np.int64),
        "i_item_id": np.array([f"AAAAAAAA{i:08d}" for i in range(ni)]),
        "i_item_desc": np.array([f"desc of item {i}" for i in range(ni)]),
        "i_brand_id": rng.integers(1000000, 1000100, ni).astype(np.int64),
        "i_brand": np.array([f"brand#{i % 100}" for i in range(ni)]),
        "i_class": rng.choice(
            ["dresses", "shirts", "pants", "football", "fishing",
             "classical", "rock"], ni),
        "i_class_id": rng.integers(1, 17, ni).astype(np.int64),
        "i_category": rng.choice(
            ["Women", "Men", "Sports", "Music", "Books", "Home"], ni),
        "i_category_id": rng.integers(1, 11, ni).astype(np.int64),
        "i_manufact_id": rng.integers(1, 1000, ni).astype(np.int64),
        "i_manufact": np.array([f"manufact#{i % 1000}" for i in range(ni)]),
        "i_manager_id": rng.integers(1, 100, ni).astype(np.int64),
        "i_current_price": (rng.random(ni) * 100).round(2),
        "i_wholesale_cost": (rng.random(ni) * 80).round(2),
        "i_color": rng.choice(
            ["red", "blue", "green", "yellow", "purple", "orange",
             "white", "black"], ni),
        "i_size": rng.choice(
            ["small", "medium", "large", "extra large", "petite",
             "economy"], ni),
        "i_units": rng.choice(["Each", "Dozen", "Case", "Pallet"], ni),
        "i_product_name": np.array([f"product{i}" for i in range(ni)]),
    }))

    ns = n["store"]
    write("store", pa.table({
        "s_store_sk": np.arange(ns, dtype=np.int64),
        "s_store_id": np.array([f"AAAAAAAA{i:04d}" for i in range(ns)]),
        "s_store_name": rng.choice(["ese", "ought", "able", "pri"], ns),
        "s_state": rng.choice(["TN", "SD", "AL", "GA"], ns),
        "s_county": rng.choice(
            ["Williamson County", "Ziebach County", "Walker County"], ns),
        "s_city": rng.choice(["Midway", "Fairview", "Oakland"], ns),
        "s_zip": np.array([f"{rng.integers(10000, 99999)}" for _ in
                           range(ns)]),
        "s_number_employees": rng.integers(200, 300, ns).astype(np.int32),
        "s_company_id": np.ones(ns, dtype=np.int32),
        "s_gmt_offset": np.full(ns, -5.0),
        "s_market_id": rng.integers(1, 11, ns).astype(np.int32),
    }))

    nc = n["customer"]
    first_names = rng.choice(["John", "Mary", "Ann", "Sam", "Pat",
                              "Lee", "Kim", "Dana"], nc)
    last_names = rng.choice(["Smith", "Jones", "Brown", "Lee",
                             "Walker", "Hill"], nc)
    write("customer", pa.table({
        "c_customer_sk": np.arange(nc, dtype=np.int64),
        "c_customer_id": np.array([f"AAAAAAAA{i:08d}" for i in
                                   range(nc)]),
        "c_current_addr_sk": rng.integers(
            0, n["customer_address"], nc).astype(np.int64),
        "c_current_cdemo_sk": rng.integers(
            0, n["customer_demographics"], nc).astype(np.int64),
        "c_current_hdemo_sk": rng.integers(
            0, n["household_demographics"], nc).astype(np.int64),
        "c_first_name": first_names,
        "c_last_name": last_names,
        "c_salutation": rng.choice(["Mr.", "Mrs.", "Ms.", "Dr."], nc),
        "c_birth_country": rng.choice(
            ["UNITED STATES", "CANADA", "MEXICO", "GERMANY"], nc),
        "c_birth_year": rng.integers(1930, 1995, nc).astype(np.int32),
        "c_birth_month": rng.integers(1, 13, nc).astype(np.int32),
        "c_preferred_cust_flag": rng.choice(["Y", "N"], nc),
        "c_email_address": np.array(
            [f"c{i}@example.com" for i in range(nc)]),
        "c_login": np.array([f"login{i}" for i in range(nc)]),
        "c_first_sales_date_sk": (DATE_SK0 + rng.integers(
            0, N_DATES, nc)).astype(np.int64),
        "c_first_shipto_date_sk": (DATE_SK0 + rng.integers(
            0, N_DATES, nc)).astype(np.int64),
    }))

    na = n["customer_address"]
    write("customer_address", pa.table({
        "ca_address_sk": np.arange(na, dtype=np.int64),
        "ca_zip": np.array([f"{rng.integers(10000, 99999)}"
                            for _ in range(na)]),
        "ca_state": rng.choice(["TN", "SD", "AL", "GA", "CA", "TX",
                                "NY", "OH"], na),
        "ca_city": rng.choice(["Midway", "Fairview", "Oakland",
                               "Springfield", "Salem"], na),
        "ca_county": rng.choice(
            ["Williamson County", "Ziebach County", "Walker County",
             "Rush County"], na),
        "ca_country": np.full(na, "United States"),
        "ca_gmt_offset": rng.choice([-5.0, -6.0, -7.0, -8.0], na),
        "ca_location_type": rng.choice(
            ["apartment", "condo", "single family"], na),
        "ca_street_number": np.array(
            [f"{rng.integers(1, 1000)}" for _ in range(na)]),
        "ca_street_name": rng.choice(
            ["Main", "Oak", "Elm", "Park", "First", "Second"], na),
    }))

    nd = n["customer_demographics"]
    write("customer_demographics", pa.table({
        "cd_demo_sk": np.arange(nd, dtype=np.int64),
        "cd_gender": rng.choice(["M", "F"], nd),
        "cd_marital_status": rng.choice(["S", "M", "D", "W", "U"], nd),
        "cd_education_status": rng.choice(
            ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"], nd),
        "cd_dep_count": rng.integers(0, 7, nd).astype(np.int32),
        "cd_purchase_estimate": (rng.integers(1, 12, nd) * 500)
        .astype(np.int32),
        "cd_credit_rating": rng.choice(
            ["Low Risk", "Good", "High Risk", "Unknown"], nd),
        "cd_dep_employed_count": rng.integers(0, 7, nd).astype(np.int32),
        "cd_dep_college_count": rng.integers(0, 7, nd).astype(np.int32),
    }))

    nh = n["household_demographics"]
    write("household_demographics", pa.table({
        "hd_demo_sk": np.arange(nh, dtype=np.int64),
        "hd_dep_count": rng.integers(0, 10, nh).astype(np.int32),
        "hd_vehicle_count": rng.integers(-1, 5, nh).astype(np.int32),
        "hd_income_band_sk": rng.integers(1, 21, nh).astype(np.int64),
        "hd_buy_potential": rng.choice(
            ["0-500", "501-1000", "1001-5000", "5001-10000", ">10000",
             "Unknown"], nh),
    }))

    write("income_band", pa.table({
        "ib_income_band_sk": np.arange(1, 21, dtype=np.int64),
        "ib_lower_bound": (np.arange(20) * 10000).astype(np.int32),
        "ib_upper_bound": ((np.arange(20) + 1) * 10000).astype(np.int32),
    }))

    npx = n["promotion"]
    write("promotion", pa.table({
        "p_promo_sk": np.arange(npx, dtype=np.int64),
        "p_channel_email": rng.choice(["Y", "N"], npx),
        "p_channel_event": rng.choice(["Y", "N"], npx),
        "p_channel_dmail": rng.choice(["Y", "N"], npx),
        "p_channel_tv": rng.choice(["Y", "N"], npx),
    }))

    write("warehouse", pa.table({
        "w_warehouse_sk": np.arange(5, dtype=np.int64),
        "w_warehouse_name": np.array([f"Warehouse {i}" for i in
                                      range(5)]),
        "w_warehouse_sq_ft": (np.arange(5) * 10000 + 50000)
        .astype(np.int32),
        "w_state": np.array(["TN", "SD", "AL", "GA", "CA"]),
        "w_country": np.full(5, "United States"),
        "w_city": np.array(["Midway", "Fairview", "Oakland",
                            "Springfield", "Salem"]),
        "w_county": np.full(5, "Williamson County"),
    }))

    write("ship_mode", pa.table({
        "sm_ship_mode_sk": np.arange(20, dtype=np.int64),
        "sm_type": np.array((["EXPRESS", "NEXT DAY", "OVERNIGHT",
                              "REGULAR", "TWO DAY"] * 4)[:20]),
        "sm_carrier": np.array((["UPS", "FEDEX", "AIRBORNE", "USPS",
                                 "DHL"] * 4)[:20]),
    }))

    write("reason", pa.table({
        "r_reason_sk": np.arange(35, dtype=np.int64),
        "r_reason_desc": np.array([f"reason {i}" for i in range(35)]),
    }))

    write("call_center", pa.table({
        "cc_call_center_sk": np.arange(6, dtype=np.int64),
        "cc_name": np.array([f"call center {i}" for i in range(6)]),
        "cc_manager": np.array([f"Manager {i}" for i in range(6)]),
        "cc_county": np.full(6, "Williamson County"),
    }))

    ncp = n["catalog_page"]
    write("catalog_page", pa.table({
        "cp_catalog_page_sk": np.arange(ncp, dtype=np.int64),
        "cp_catalog_page_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(ncp)]),
    }))

    write("web_site", pa.table({
        "web_site_sk": np.arange(30, dtype=np.int64),
        "web_site_id": np.array([f"AAAAAAAA{i:04d}" for i in range(30)]),
        "web_name": np.array([f"site_{i}" for i in range(30)]),
        "web_company_name": rng.choice(["pri", "ought", "able"], 30),
    }))

    write("web_page", pa.table({
        "wp_web_page_sk": np.arange(60, dtype=np.int64),
        "wp_char_count": rng.integers(4000, 6000, 60).astype(np.int32),
    }))

    nss = n["store_sales"]
    price = (rng.random(nss) * 200).round(2)
    qty = rng.integers(1, 100, nss)
    wcost = (rng.random(nss) * 100).round(2)
    ext_sales = (price * qty).round(2)
    ext_wcost = (wcost * qty).round(2)
    write("store_sales", pa.table({
        "ss_sold_date_sk": (DATE_SK0 + rng.integers(
            0, N_DATES, nss)).astype(np.int64),
        "ss_sold_time_sk": rng.integers(0, 86400, nss).astype(np.int64),
        "ss_item_sk": rng.integers(0, ni, nss).astype(np.int64),
        "ss_customer_sk": rng.integers(0, nc, nss).astype(np.int64),
        "ss_cdemo_sk": rng.integers(0, nd, nss).astype(np.int64),
        "ss_hdemo_sk": rng.integers(0, nh, nss).astype(np.int64),
        "ss_addr_sk": rng.integers(0, na, nss).astype(np.int64),
        "ss_store_sk": rng.integers(0, ns, nss).astype(np.int64),
        "ss_promo_sk": rng.integers(0, npx, nss).astype(np.int64),
        "ss_ticket_number": (rng.integers(0, nss, nss) // 4)
        .astype(np.int64),
        "ss_quantity": qty.astype(np.int32),
        "ss_wholesale_cost": wcost,
        "ss_list_price": (price * 1.2).round(2),
        "ss_sales_price": price,
        "ss_ext_discount_amt": (rng.random(nss) * 100).round(2),
        "ss_ext_sales_price": ext_sales,
        "ss_ext_wholesale_cost": ext_wcost,
        "ss_ext_list_price": (price * 1.2 * qty).round(2),
        "ss_ext_tax": (ext_sales * 0.08).round(2),
        "ss_coupon_amt": (rng.random(nss) * 50).round(2),
        "ss_net_paid": (ext_sales * 0.95).round(2),
        "ss_net_paid_inc_tax": (ext_sales * 1.03).round(2),
        "ss_net_profit": (ext_sales - ext_wcost).round(2),
    }))

    ncs = n["catalog_sales"]
    cprice = (rng.random(ncs) * 200).round(2)
    cqty = rng.integers(1, 100, ncs)
    cwcost = (rng.random(ncs) * 100).round(2)
    cext = (cprice * cqty).round(2)
    write("catalog_sales", pa.table({
        "cs_sold_date_sk": (DATE_SK0 + rng.integers(
            0, N_DATES, ncs)).astype(np.int64),
        "cs_sold_time_sk": rng.integers(0, 86400, ncs).astype(np.int64),
        "cs_ship_date_sk": (DATE_SK0 + rng.integers(
            0, N_DATES, ncs)).astype(np.int64),
        "cs_bill_customer_sk": rng.integers(0, nc, ncs).astype(np.int64),
        "cs_bill_cdemo_sk": rng.integers(0, nd, ncs).astype(np.int64),
        "cs_bill_hdemo_sk": rng.integers(0, nh, ncs).astype(np.int64),
        "cs_bill_addr_sk": rng.integers(0, na, ncs).astype(np.int64),
        "cs_ship_addr_sk": rng.integers(0, na, ncs).astype(np.int64),
        "cs_ship_mode_sk": rng.integers(0, 20, ncs).astype(np.int64),
        "cs_call_center_sk": rng.integers(0, 6, ncs).astype(np.int64),
        "cs_catalog_page_sk": rng.integers(
            0, n["catalog_page"], ncs).astype(np.int64),
        "cs_warehouse_sk": rng.integers(0, 5, ncs).astype(np.int64),
        "cs_item_sk": rng.integers(0, ni, ncs).astype(np.int64),
        "cs_promo_sk": rng.integers(0, npx, ncs).astype(np.int64),
        "cs_order_number": (rng.integers(0, ncs, ncs) // 3)
        .astype(np.int64),
        "cs_quantity": cqty.astype(np.int32),
        "cs_wholesale_cost": cwcost,
        "cs_list_price": (cprice * 1.2).round(2),
        "cs_sales_price": cprice,
        "cs_ext_discount_amt": (rng.random(ncs) * 100).round(2),
        "cs_ext_sales_price": cext,
        "cs_ext_wholesale_cost": (cwcost * cqty).round(2),
        "cs_ext_list_price": (cprice * 1.2 * cqty).round(2),
        "cs_ext_ship_cost": (cext * 0.05).round(2),
        "cs_coupon_amt": (rng.random(ncs) * 50).round(2),
        "cs_net_paid": (cext * 0.95).round(2),
        "cs_net_paid_inc_ship": (cext * 1.02).round(2),
        "cs_net_profit": (cext - cwcost * cqty).round(2),
    }))

    nws = n["web_sales"]
    wprice = (rng.random(nws) * 200).round(2)
    wqty = rng.integers(1, 100, nws)
    wwcost = (rng.random(nws) * 100).round(2)
    wext = (wprice * wqty).round(2)
    write("web_sales", pa.table({
        "ws_sold_date_sk": (DATE_SK0 + rng.integers(
            0, N_DATES, nws)).astype(np.int64),
        "ws_sold_time_sk": rng.integers(0, 86400, nws).astype(np.int64),
        "ws_ship_date_sk": (DATE_SK0 + rng.integers(
            0, N_DATES, nws)).astype(np.int64),
        "ws_item_sk": rng.integers(0, ni, nws).astype(np.int64),
        "ws_bill_customer_sk": rng.integers(0, nc, nws).astype(np.int64),
        "ws_bill_addr_sk": rng.integers(0, na, nws).astype(np.int64),
        "ws_ship_customer_sk": rng.integers(0, nc, nws).astype(np.int64),
        "ws_ship_addr_sk": rng.integers(0, na, nws).astype(np.int64),
        "ws_web_page_sk": rng.integers(0, 60, nws).astype(np.int64),
        "ws_web_site_sk": rng.integers(0, 30, nws).astype(np.int64),
        "ws_ship_mode_sk": rng.integers(0, 20, nws).astype(np.int64),
        "ws_warehouse_sk": rng.integers(0, 5, nws).astype(np.int64),
        "ws_promo_sk": rng.integers(0, npx, nws).astype(np.int64),
        "ws_order_number": (rng.integers(0, nws, nws) // 3)
        .astype(np.int64),
        "ws_quantity": wqty.astype(np.int32),
        "ws_wholesale_cost": wwcost,
        "ws_list_price": (wprice * 1.2).round(2),
        "ws_sales_price": wprice,
        "ws_ext_discount_amt": (rng.random(nws) * 100).round(2),
        "ws_ext_sales_price": wext,
        "ws_ext_wholesale_cost": (wwcost * wqty).round(2),
        "ws_ext_list_price": (wprice * 1.2 * wqty).round(2),
        "ws_ext_ship_cost": (wext * 0.05).round(2),
        "ws_net_paid": (wext * 0.95).round(2),
        "ws_net_profit": (wext - wwcost * wqty).round(2),
    }))

    nsr = n["store_returns"]
    ramt = (rng.random(nsr) * 150).round(2)
    write("store_returns", pa.table({
        "sr_returned_date_sk": (DATE_SK0 + rng.integers(
            0, N_DATES, nsr)).astype(np.int64),
        "sr_item_sk": rng.integers(0, ni, nsr).astype(np.int64),
        "sr_customer_sk": rng.integers(0, nc, nsr).astype(np.int64),
        "sr_cdemo_sk": rng.integers(0, nd, nsr).astype(np.int64),
        "sr_store_sk": rng.integers(0, ns, nsr).astype(np.int64),
        "sr_reason_sk": rng.integers(0, 35, nsr).astype(np.int64),
        "sr_ticket_number": (rng.integers(0, nss, nsr) // 4)
        .astype(np.int64),
        "sr_return_quantity": rng.integers(1, 50, nsr).astype(np.int32),
        "sr_return_amt": ramt,
        "sr_return_tax": (ramt * 0.08).round(2),
        "sr_return_amt_inc_tax": (ramt * 1.08).round(2),
        "sr_fee": (rng.random(nsr) * 20).round(2),
        "sr_return_ship_cost": (rng.random(nsr) * 10).round(2),
        "sr_refunded_cash": (ramt * 0.8).round(2),
        "sr_reversed_charge": (ramt * 0.1).round(2),
        "sr_store_credit": (ramt * 0.1).round(2),
        "sr_net_loss": (rng.random(nsr) * 60).round(2),
    }))

    ncr = n["catalog_returns"]
    cramt = (rng.random(ncr) * 150).round(2)
    write("catalog_returns", pa.table({
        "cr_returned_date_sk": (DATE_SK0 + rng.integers(
            0, N_DATES, ncr)).astype(np.int64),
        "cr_item_sk": rng.integers(0, ni, ncr).astype(np.int64),
        "cr_returning_customer_sk": rng.integers(
            0, nc, ncr).astype(np.int64),
        "cr_returning_addr_sk": rng.integers(0, na, ncr).astype(np.int64),
        "cr_call_center_sk": rng.integers(0, 6, ncr).astype(np.int64),
        "cr_catalog_page_sk": rng.integers(
            0, n["catalog_page"], ncr).astype(np.int64),
        "cr_reason_sk": rng.integers(0, 35, ncr).astype(np.int64),
        "cr_order_number": (rng.integers(0, ncs, ncr) // 3)
        .astype(np.int64),
        "cr_return_quantity": rng.integers(1, 50, ncr).astype(np.int32),
        "cr_return_amount": cramt,
        "cr_return_amt_inc_tax": (cramt * 1.08).round(2),
        "cr_net_loss": (rng.random(ncr) * 60).round(2),
        "cr_refunded_cash": (cramt * 0.8).round(2),
        "cr_reversed_charge": (cramt * 0.1).round(2),
        "cr_store_credit": (cramt * 0.1).round(2),
    }))

    nwr = n["web_returns"]
    wramt = (rng.random(nwr) * 150).round(2)
    write("web_returns", pa.table({
        "wr_returned_date_sk": (DATE_SK0 + rng.integers(
            0, N_DATES, nwr)).astype(np.int64),
        "wr_item_sk": rng.integers(0, ni, nwr).astype(np.int64),
        "wr_returning_customer_sk": rng.integers(
            0, nc, nwr).astype(np.int64),
        "wr_returning_addr_sk": rng.integers(0, na, nwr).astype(np.int64),
        "wr_web_page_sk": rng.integers(0, 60, nwr).astype(np.int64),
        "wr_reason_sk": rng.integers(0, 35, nwr).astype(np.int64),
        "wr_order_number": (rng.integers(0, nws, nwr) // 3)
        .astype(np.int64),
        "wr_refunded_cdemo_sk": rng.integers(0, nd, nwr)
        .astype(np.int64),
        "wr_returning_cdemo_sk": rng.integers(0, nd, nwr)
        .astype(np.int64),
        "wr_refunded_addr_sk": rng.integers(0, na, nwr)
        .astype(np.int64),
        "wr_return_quantity": rng.integers(1, 50, nwr).astype(np.int32),
        "wr_return_amt": wramt,
        "wr_refunded_cash": (wramt * 0.8).round(2),
        "wr_fee": (rng.random(nwr) * 20).round(2),
        "wr_net_loss": (rng.random(nwr) * 60).round(2),
    }))

    nin = n["inventory"]
    write("inventory", pa.table({
        "inv_date_sk": (DATE_SK0 + (rng.integers(0, N_DATES // 7, nin)
                                    * 7)).astype(np.int64),
        "inv_item_sk": rng.integers(0, ni, nin).astype(np.int64),
        "inv_warehouse_sk": rng.integers(0, 5, nin).astype(np.int64),
        "inv_quantity_on_hand": rng.integers(
            0, 1000, nin).astype(np.int32),
    }))
    return n


TABLES = ["date_dim", "time_dim", "item", "store", "customer",
          "customer_address", "customer_demographics",
          "household_demographics", "income_band", "promotion",
          "warehouse", "ship_mode", "reason", "call_center",
          "catalog_page", "web_site", "web_page", "store_sales",
          "catalog_sales", "web_sales", "store_returns",
          "catalog_returns", "web_returns", "inventory"]


def register(s, data_dir: str):
    for t in TABLES:
        s.read.parquet(os.path.join(data_dir, f"{t}.parquet")) \
            .create_or_replace_temp_view(t)


from tpcds_queries import QUERIES  # noqa: E402


def run(engine: str, data_dir: str, queries, repeats: int = 1):
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.config import TpuConf
    s = TpuSession(TpuConf({
        "spark.rapids.tpu.sql.enabled": engine == "tpu"}))
    register(s, data_dir)
    times = {}
    for name in queries:
        sql = QUERIES[name]
        s.sql(sql).collect()  # warmup/compile
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            rows = s.sql(sql).collect()
            best = min(best, time.perf_counter() - t0)
        times[name] = {"seconds": round(best, 4), "rows": len(rows)}
    return times


def _norm_rows(rows):
    out = []
    for r in rows:
        out.append(tuple("NaN" if isinstance(v, float) and v != v else v
                         for v in r))
    return sorted(out, key=lambda r: tuple(str(v) for v in r))


def _rows_equal(cpu_rows, tpu_rows, rel=1e-6):
    if len(cpu_rows) != len(tpu_rows):
        return False, f"row count {len(cpu_rows)} vs {len(tpu_rows)}"
    for i, (a, b) in enumerate(zip(cpu_rows, tpu_rows)):
        if len(a) != len(b):
            return False, f"row {i} width"
        for x, y in zip(a, b):
            if isinstance(x, float) and isinstance(y, float):
                if abs(x - y) > rel * max(abs(x), abs(y), 1.0):
                    return False, f"row {i}: {x!r} vs {y!r}"
            elif x != y:
                return False, f"row {i}: {x!r} vs {y!r}"
    return True, ""


def verify(data_dir: str, queries, out_path: str,
           resume: bool = False):
    """TPU-vs-CPU row comparison per query; writes the pass/fail
    matrix (the qa_nightly role: every query is an oracle check, not
    just a timing).  ``resume`` keeps prior passes from an existing
    matrix file and re-runs only failures/missing queries."""
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.config import TpuConf
    s_tpu = TpuSession(TpuConf({"spark.rapids.tpu.sql.enabled": True}))
    s_cpu = TpuSession(TpuConf({"spark.rapids.tpu.sql.enabled": False}))
    register(s_tpu, data_dir)
    register(s_cpu, data_dir)
    matrix = {}
    if resume and os.path.exists(out_path):
        try:
            with open(out_path) as f:
                prior = json.load(f).get("queries", {})
            matrix = {q: e for q, e in prior.items()
                      if e.get("status") == "pass" and q in queries}
        except Exception:
            matrix = {}
    def run_one(sql, entry):
        t0 = time.perf_counter()
        tpu_rows = _norm_rows(s_tpu.sql(sql).collect())
        entry["tpu_s"] = round(time.perf_counter() - t0, 4)
        t0 = time.perf_counter()
        cpu_rows = _norm_rows(s_cpu.sql(sql).collect())
        entry["cpu_s"] = round(time.perf_counter() - t0, 4)
        ok, why = _rows_equal(cpu_rows, tpu_rows)
        entry["rows"] = len(tpu_rows)
        entry["status"] = "pass" if ok else "FAIL"
        if not ok:
            entry["mismatch"] = why

    for name in queries:
        if name in matrix:
            continue
        sql = QUERIES[name]
        entry = {}
        try:
            run_one(sql, entry)
        except Exception as e:  # noqa: BLE001 - recorded per query
            if "RESOURCE_EXHAUSTED" in str(e):
                # real HBM exhaustion mid-sweep: drop the PROCESS-WIDE
                # shuffle/catalog state a failed query left behind
                # (clear_all only runs on success), rebuild sessions,
                # and retry once before recording a failure
                import gc
                from spark_rapids_tpu.shuffle.manager import \
                    ShuffleManager
                if ShuffleManager._instance is not None:
                    ShuffleManager._instance.clear_all()
                s_tpu = TpuSession(TpuConf(
                    {"spark.rapids.tpu.sql.enabled": True}))
                s_cpu = TpuSession(TpuConf(
                    {"spark.rapids.tpu.sql.enabled": False}))
                register(s_tpu, data_dir)
                register(s_cpu, data_dir)
                gc.collect()
                try:
                    entry = {}
                    run_one(sql, entry)
                    entry["oom_retried"] = True
                except Exception as e2:  # noqa: BLE001
                    entry["status"] = "ERROR"
                    entry["error"] = f"{type(e2).__name__}: {e2}"[:300]
            else:
                entry["status"] = "ERROR"
                entry["error"] = f"{type(e).__name__}: {e}"[:300]
        matrix[name] = entry
        print(f"{name}: {entry['status']}"
              + (f" ({entry.get('mismatch', entry.get('error', ''))})"
                 if entry["status"] != "pass" else ""),
              file=sys.stderr, flush=True)
        # write incrementally: a long sweep should leave partial
        # evidence if interrupted
        passed = sum(1 for e in matrix.values() if e["status"] == "pass")
        summary = {"passed": passed, "total": len(matrix),
                   "queries": matrix}
        with open(out_path, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
    # recompute outside the loop: with --resume everything may already
    # pass and the loop body never runs
    passed = sum(1 for e in matrix.values() if e["status"] == "pass")
    summary = {"passed": passed, "total": len(matrix),
               "queries": matrix}
    with open(out_path, "w") as f:
        json.dump(summary, f, indent=1, sort_keys=True)
    return summary


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.001)
    ap.add_argument("--engine", choices=["tpu", "cpu"], default="tpu")
    ap.add_argument("--compare", action="store_true")
    ap.add_argument("--verify", action="store_true")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--matrix-out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tpcds_matrix.json"))
    ap.add_argument("--queries", default=",".join(QUERIES))
    ap.add_argument("--data-dir", default="/tmp/tpcds_data")
    ap.add_argument("--repeats", type=int, default=2)
    args = ap.parse_args()
    tag = os.path.join(args.data_dir, f"sf{args.scale}_v5")
    if not os.path.exists(os.path.join(tag, "store_sales.parquet")):
        sizes = generate(tag, args.scale)
        print(f"generated {sizes}", file=sys.stderr)
    queries = args.queries.split(",")
    if args.verify:
        summary = verify(tag, queries, args.matrix_out,
                         resume=args.resume)
        print(json.dumps({"passed": summary["passed"],
                          "total": summary["total"]}))
        return
    if args.compare:
        tpu = run("tpu", tag, queries, args.repeats)
        cpu = run("cpu", tag, queries, args.repeats)
        out = {q: {"tpu_s": tpu[q]["seconds"], "cpu_s": cpu[q]["seconds"],
                   "speedup": round(cpu[q]["seconds"] /
                                    max(tpu[q]["seconds"], 1e-9), 2)}
               for q in queries}
        print(json.dumps(out, indent=2))
    else:
        print(json.dumps(run(args.engine, tag, queries, args.repeats),
                         indent=2))


if __name__ == "__main__":
    main()
