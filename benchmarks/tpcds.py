"""TPC-DS-style benchmark suite over the SQL front end.

Reference baseline configs (BASELINE.json): "TPC-DS SF100 — full 99-query
sweep, local shuffle".  This module generates the TPC-DS star schema
(store_sales fact + date/item/store/customer/demographics/promotion/time
dimensions) at a row-scaled factor, writes Parquet, registers the tables
as temp views, and runs real TPC-DS query texts (Q3, Q7, Q19, Q42, Q52,
Q55, Q96, Q98 — the star-join/agg/window shapes) through
``session.sql()`` on either engine.  Q27 exercises ROLLUP + grouping();
Q98 exercises window-over-aggregate.

Usage:
  python benchmarks/tpcds.py --scale 0.01 --engine tpu
  python benchmarks/tpcds.py --scale 0.01 --compare   # TPU vs CPU timings
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ROWS_PER_SF = {"store_sales": 2_880_000, "item": 18_000,
               "customer": 100_000, "customer_address": 50_000,
               "customer_demographics": 19_208, "store": 12,
               "household_demographics": 7_200, "promotion": 300}

DATE_SK0 = 2450815          # 1998-01-01
N_DATES = 365 * 5           # 1998-2002


def generate(data_dir: str, scale: float, seed: int = 0):
    import pyarrow as pa
    import pyarrow.parquet as papq
    rng = np.random.default_rng(seed)
    os.makedirs(data_dir, exist_ok=True)

    def write(name, table):
        papq.write_table(table, os.path.join(data_dir, f"{name}.parquet"))

    n = {k: max(int(v * scale), 64) for k, v in ROWS_PER_SF.items()}
    n["store"] = max(int(ROWS_PER_SF["store"] * max(scale, 1)), 4)

    # date_dim: real calendar over 1998-2002
    days = (np.datetime64("1998-01-01") +
            np.arange(N_DATES).astype("timedelta64[D]"))
    ymd = days.astype("datetime64[D]")
    years = ymd.astype("datetime64[Y]").astype(int) + 1970
    months = ymd.astype("datetime64[M]").astype(int) % 12 + 1
    dom = (ymd - ymd.astype("datetime64[M]")).astype(int) + 1
    write("date_dim", pa.table({
        "d_date_sk": (DATE_SK0 + np.arange(N_DATES)).astype(np.int64),
        "d_year": years.astype(np.int32),
        "d_moy": months.astype(np.int32),
        "d_dom": dom.astype(np.int32),
    }))

    write("time_dim", pa.table({
        "t_time_sk": np.arange(86400, dtype=np.int64),
        "t_hour": (np.arange(86400) // 3600).astype(np.int32),
        "t_minute": ((np.arange(86400) % 3600) // 60).astype(np.int32),
    }))

    ni = n["item"]
    write("item", pa.table({
        "i_item_sk": np.arange(ni, dtype=np.int64),
        "i_item_id": np.array([f"AAAAAAAA{i:08d}" for i in range(ni)]),
        "i_item_desc": np.array([f"desc of item {i}" for i in range(ni)]),
        "i_brand_id": rng.integers(1000000, 1000100, ni).astype(np.int64),
        "i_brand": np.array([f"brand#{i % 100}" for i in range(ni)]),
        "i_class": rng.choice(
            ["dresses", "shirts", "pants", "football", "fishing",
             "classical", "rock"], ni),
        "i_category": rng.choice(
            ["Women", "Men", "Sports", "Music", "Books", "Home"], ni),
        "i_category_id": rng.integers(1, 11, ni).astype(np.int64),
        "i_manufact_id": rng.integers(1, 1000, ni).astype(np.int64),
        "i_manufact": np.array([f"manufact#{i % 1000}" for i in range(ni)]),
        "i_manager_id": rng.integers(1, 100, ni).astype(np.int64),
        "i_current_price": (rng.random(ni) * 100).round(2),
    }))

    ns = n["store"]
    write("store", pa.table({
        "s_store_sk": np.arange(ns, dtype=np.int64),
        "s_store_name": rng.choice(["ese", "ought", "able", "pri"], ns),
        "s_state": rng.choice(["TN", "SD", "AL", "GA"], ns),
        "s_zip": np.array([f"{rng.integers(10000, 99999)}" for _ in
                           range(ns)]),
    }))

    nc = n["customer"]
    write("customer", pa.table({
        "c_customer_sk": np.arange(nc, dtype=np.int64),
        "c_current_addr_sk": rng.integers(
            0, n["customer_address"], nc).astype(np.int64),
    }))

    na = n["customer_address"]
    write("customer_address", pa.table({
        "ca_address_sk": np.arange(na, dtype=np.int64),
        "ca_zip": np.array([f"{rng.integers(10000, 99999)}"
                            for _ in range(na)]),
    }))

    nd = n["customer_demographics"]
    write("customer_demographics", pa.table({
        "cd_demo_sk": np.arange(nd, dtype=np.int64),
        "cd_gender": rng.choice(["M", "F"], nd),
        "cd_marital_status": rng.choice(["S", "M", "D", "W", "U"], nd),
        "cd_education_status": rng.choice(
            ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"], nd),
    }))

    nh = n["household_demographics"]
    write("household_demographics", pa.table({
        "hd_demo_sk": np.arange(nh, dtype=np.int64),
        "hd_dep_count": rng.integers(0, 10, nh).astype(np.int32),
    }))

    npx = n["promotion"]
    write("promotion", pa.table({
        "p_promo_sk": np.arange(npx, dtype=np.int64),
        "p_channel_email": rng.choice(["Y", "N"], npx),
        "p_channel_event": rng.choice(["Y", "N"], npx),
    }))

    nss = n["store_sales"]
    price = (rng.random(nss) * 200).round(2)
    write("store_sales", pa.table({
        "ss_sold_date_sk": (DATE_SK0 + rng.integers(
            0, N_DATES, nss)).astype(np.int64),
        "ss_sold_time_sk": rng.integers(0, 86400, nss).astype(np.int64),
        "ss_item_sk": rng.integers(0, ni, nss).astype(np.int64),
        "ss_customer_sk": rng.integers(0, nc, nss).astype(np.int64),
        "ss_cdemo_sk": rng.integers(0, nd, nss).astype(np.int64),
        "ss_hdemo_sk": rng.integers(0, nh, nss).astype(np.int64),
        "ss_store_sk": rng.integers(0, ns, nss).astype(np.int64),
        "ss_promo_sk": rng.integers(0, npx, nss).astype(np.int64),
        "ss_quantity": rng.integers(1, 100, nss).astype(np.int32),
        "ss_list_price": (price * 1.2).round(2),
        "ss_sales_price": price,
        "ss_ext_sales_price": (price * rng.integers(1, 100, nss)).round(2),
        "ss_coupon_amt": (rng.random(nss) * 50).round(2),
    }))
    return n


TABLES = ["date_dim", "time_dim", "item", "store", "customer",
          "customer_address", "customer_demographics",
          "household_demographics", "promotion", "store_sales"]


def register(s, data_dir: str):
    for t in TABLES:
        s.read.parquet(os.path.join(data_dir, f"{t}.parquet")) \
            .create_or_replace_temp_view(t)


QUERIES = {
    # TPC-DS Q3: brand revenue by year for one manufacturer in November
    "q3": """
        select d_year, i_brand_id brand_id, i_brand brand,
               sum(ss_ext_sales_price) sum_agg
        from date_dim, store_sales, item
        where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
          and i_manufact_id = 128 and d_moy = 11
        group by d_year, i_brand_id, i_brand
        order by d_year, sum_agg desc, brand_id
        limit 100""",
    # TPC-DS Q7: average sales metrics for one demographic + promotion
    "q7": """
        select i_item_id,
               avg(ss_quantity) agg1, avg(ss_list_price) agg2,
               avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
        from store_sales, customer_demographics, date_dim, item, promotion
        where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
          and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
          and cd_gender = 'M' and cd_marital_status = 'S'
          and cd_education_status = 'College'
          and (p_channel_email = 'N' or p_channel_event = 'N')
          and d_year = 2000
        group by i_item_id
        order by i_item_id
        limit 100""",
    # TPC-DS Q19: brand revenue where customer and store zips differ
    "q19": """
        select i_brand_id brand_id, i_brand brand, i_manufact_id,
               i_manufact, sum(ss_ext_sales_price) ext_price
        from date_dim, store_sales, item, customer, customer_address,
             store
        where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
          and i_manager_id = 8 and d_moy = 11 and d_year = 1998
          and ss_customer_sk = c_customer_sk
          and c_current_addr_sk = ca_address_sk
          and substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
          and ss_store_sk = s_store_sk
        group by i_brand_id, i_brand, i_manufact_id, i_manufact
        order by ext_price desc, brand_id
        limit 100""",
    # TPC-DS Q42: category revenue for one month
    "q42": """
        select d_year, i_category_id, i_category,
               sum(ss_ext_sales_price) total_sales
        from date_dim, store_sales, item
        where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
          and i_manager_id = 1 and d_moy = 11 and d_year = 2000
        group by d_year, i_category_id, i_category
        order by total_sales desc, d_year, i_category_id, i_category
        limit 100""",
    # TPC-DS Q52: brand revenue for one month
    "q52": """
        select d_year, i_brand_id brand_id, i_brand brand,
               sum(ss_ext_sales_price) ext_price
        from date_dim, store_sales, item
        where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
          and i_manager_id = 1 and d_moy = 11 and d_year = 2000
        group by d_year, i_brand_id, i_brand
        order by d_year, ext_price desc, brand_id
        limit 100""",
    # TPC-DS Q55: brand revenue for one manager/month
    "q55": """
        select i_brand_id brand_id, i_brand brand,
               sum(ss_ext_sales_price) ext_price
        from date_dim, store_sales, item
        where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
          and i_manager_id = 28 and d_moy = 11 and d_year = 1999
        group by i_brand_id, i_brand
        order by ext_price desc, brand_id
        limit 100""",
    # TPC-DS Q27: demographic item/state averages with ROLLUP subtotals
    "q27": """
        select i_item_id, s_state, grouping(s_state) g_state,
               avg(ss_quantity) agg1, avg(ss_list_price) agg2,
               avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
        from store_sales, customer_demographics, date_dim, store, item
        where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
          and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
          and cd_gender = 'M' and cd_marital_status = 'S'
          and cd_education_status = 'College' and d_year = 2002
        group by rollup (i_item_id, s_state)
        order by i_item_id, s_state
        limit 100""",
    # TPC-DS Q96: count of sales in a store/time/demographic slice
    "q96": """
        select count(*) cnt
        from store_sales, household_demographics, time_dim, store
        where ss_sold_time_sk = t_time_sk
          and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
          and t_hour = 20 and t_minute >= 30 and hd_dep_count = 7
          and s_store_name = 'ese'
        order by cnt
        limit 100""",
    # TPC-DS Q98: item revenue with class-partitioned revenue ratio
    # (aggregate + window-over-aggregate)
    "q98": """
        select i_item_id, i_item_desc, i_category, i_class,
               i_current_price,
               sum(ss_ext_sales_price) as itemrevenue,
               sum(ss_ext_sales_price) * 100.0 /
                 sum(sum(ss_ext_sales_price))
                   over (partition by i_class) as revenueratio
        from store_sales, item, date_dim
        where ss_item_sk = i_item_sk
          and i_category in ('Sports', 'Books', 'Home')
          and ss_sold_date_sk = d_date_sk
          and d_year = 1999 and d_moy between 2 and 3
        group by i_item_id, i_item_desc, i_category, i_class,
                 i_current_price
        order by i_category, i_class, i_item_id, i_item_desc,
                 revenueratio
        limit 100""",
}


def run(engine: str, data_dir: str, queries, repeats: int = 1):
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.config import TpuConf
    s = TpuSession(TpuConf({
        "spark.rapids.tpu.sql.enabled": engine == "tpu"}))
    register(s, data_dir)
    times = {}
    for name in queries:
        sql = QUERIES[name]
        s.sql(sql).collect()  # warmup/compile
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            rows = s.sql(sql).collect()
            best = min(best, time.perf_counter() - t0)
        times[name] = {"seconds": round(best, 4), "rows": len(rows)}
    return times


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.001)
    ap.add_argument("--engine", choices=["tpu", "cpu"], default="tpu")
    ap.add_argument("--compare", action="store_true")
    ap.add_argument("--queries", default=",".join(QUERIES))
    ap.add_argument("--data-dir", default="/tmp/tpcds_data")
    ap.add_argument("--repeats", type=int, default=2)
    args = ap.parse_args()
    tag = os.path.join(args.data_dir, f"sf{args.scale}_v2")
    if not os.path.exists(os.path.join(tag, "store_sales.parquet")):
        sizes = generate(tag, args.scale)
        print(f"generated {sizes}", file=sys.stderr)
    queries = args.queries.split(",")
    if args.compare:
        tpu = run("tpu", tag, queries, args.repeats)
        cpu = run("cpu", tag, queries, args.repeats)
        out = {q: {"tpu_s": tpu[q]["seconds"], "cpu_s": cpu[q]["seconds"],
                   "speedup": round(cpu[q]["seconds"] /
                                    max(tpu[q]["seconds"], 1e-9), 2)}
               for q in queries}
        print(json.dumps(out, indent=2))
    else:
        print(json.dumps(run(args.engine, tag, queries, args.repeats),
                         indent=2))


if __name__ == "__main__":
    main()
