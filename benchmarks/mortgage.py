"""Mortgage ETL benchmark — the reference's headline workload.

Reference parity: integration_tests/src/main/scala/.../tests/mortgage/
MortgageSpark.scala (ReadPerformanceCsv/ReadAcquisitionCsv/
CreatePerformanceDelinquency/CreateAcquisition/CleanAcquisitionPrime) and
BASELINE.md ("Mortgage ETL stage 1/2").  The pipeline below reproduces
that ETL's structure over synthetic FannieMae-shaped data:

  1. performance: per-loan delinquency aggregation (ever_30/90/180 from
     max/min over conditional projections),
  2. a 12-month window expansion via ``explode(array(0..11))`` — the
     reference's own trick ("explode ... is actually slightly more
     efficient than a cross join"),
  3. re-aggregation per (loan, 12-month bucket) with floor/pmod month
     arithmetic,
  4. acquisition: seller-name normalization join + coalesce,
  5. final multi-key inner join performance x acquisition.

Usage:
  python benchmarks/mortgage.py --scale 0.01 --engine tpu
  python benchmarks/mortgage.py --scale 0.01 --compare
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# rows per unit scale (FannieMae quarterly files are ~10-30M perf rows;
# scale=1.0 here is a laptop-sized stand-in, crank --scale for real runs)
PERF_ROWS = 2_000_000
ACQ_ROWS = 80_000

SELLERS = ["BANK OF AMERICA, N.A.", "WELLS FARGO BANK, N.A.",
           "JPMORGAN CHASE BANK, NA", "CITIMORTGAGE, INC.",
           "QUICKEN LOANS INC.", "SUNTRUST MORTGAGE INC.",
           "FLAGSTAR CAPITAL MARKETS CORPORATION", "OTHER"]

# the NameMapping normalization table (MortgageSpark.scala:120 role)
NAME_MAPPING = [
    ("BANK OF AMERICA, N.A.", "Bank of America"),
    ("WELLS FARGO BANK, N.A.", "Wells Fargo"),
    ("JPMORGAN CHASE BANK, NA", "JPMorgan Chase"),
    ("CITIMORTGAGE, INC.", "Citi"),
    ("QUICKEN LOANS INC.", "Quicken Loans"),
    ("SUNTRUST MORTGAGE INC.", "SunTrust"),
]


def generate(data_dir: str, scale: float, seed: int = 0):
    import pyarrow as pa
    import pyarrow.parquet as papq
    rng = np.random.default_rng(seed)
    os.makedirs(data_dir, exist_ok=True)

    n_acq = max(int(ACQ_ROWS * scale), 200)
    n_perf = max(int(PERF_ROWS * scale), 2000)

    loan_ids = np.arange(n_acq, dtype=np.int64) + 100_000_000
    quarters = rng.integers(1, 5, n_acq).astype(np.int32)

    acq = pa.table({
        "loan_id": loan_ids,
        "quarter": quarters,
        "seller_name": rng.choice(SELLERS, n_acq),
        "orig_interest_rate": (rng.random(n_acq) * 5 + 2).round(3),
        "orig_upb": rng.integers(50_000, 800_000, n_acq).astype(np.int64),
        "orig_loan_term": rng.choice([180, 240, 360], n_acq)
        .astype(np.int32),
        "orig_ltv": rng.integers(40, 98, n_acq).astype(np.int32),
        "dti": rng.integers(10, 50, n_acq).astype(np.int32),
        "borrower_credit_score": rng.integers(540, 830, n_acq)
        .astype(np.int32),
    })
    papq.write_table(acq, os.path.join(data_dir, "acquisition.parquet"))

    # each perf row is one monthly report for a loan
    rows_loan = rng.integers(0, n_acq, n_perf)
    year = rng.integers(2000, 2016, n_perf)
    month = rng.integers(1, 13, n_perf)
    # delinquency mostly 0, occasionally escalating
    delinq = np.minimum(
        rng.geometric(0.55, n_perf) - 1, 12).astype(np.int32)
    upb = np.maximum(
        rng.integers(0, 800_000, n_perf) - (delinq * 20_000), 0)
    perf = pa.table({
        "loan_id": loan_ids[rows_loan],
        "quarter": quarters[rows_loan],
        "timestamp_year": year.astype(np.int32),
        "timestamp_month": month.astype(np.int32),
        "current_loan_delinquency_status": delinq,
        "current_actual_upb": upb.astype(np.float64),
        "servicer": rng.choice(SELLERS, n_perf),
        "loan_age": rng.integers(0, 200, n_perf).astype(np.float64),
    })
    papq.write_table(perf, os.path.join(data_dir, "performance.parquet"))
    return {"performance": n_perf, "acquisition": n_acq}


def performance_delinquency(s, perf):
    """CreatePerformanceDelinquency (MortgageSpark.scala:213) shape."""
    from spark_rapids_tpu.api import functions as F
    # per-loan ever-delinquent flags
    agg = (perf
           .select("quarter", "loan_id",
                   F.col("current_loan_delinquency_status").alias("st"),
                   F.when(F.col("current_loan_delinquency_status") >= 1,
                          F.col("timestamp_year") * 12 +
                          F.col("timestamp_month"))
                   .alias("delinquency_30"),
                   F.when(F.col("current_loan_delinquency_status") >= 3,
                          F.col("timestamp_year") * 12 +
                          F.col("timestamp_month"))
                   .alias("delinquency_90"),
                   F.when(F.col("current_loan_delinquency_status") >= 6,
                          F.col("timestamp_year") * 12 +
                          F.col("timestamp_month"))
                   .alias("delinquency_180"))
           .group_by("quarter", "loan_id")
           .agg(F.max("st").alias("delinquency_12"),
                F.min("delinquency_30").alias("delinquency_30"),
                F.min("delinquency_90").alias("delinquency_90"),
                F.min("delinquency_180").alias("delinquency_180"))
           .select("quarter", "loan_id",
                   (F.col("delinquency_12") >= 1).alias("ever_30"),
                   (F.col("delinquency_12") >= 3).alias("ever_90"),
                   (F.col("delinquency_12") >= 6).alias("ever_180"),
                   F.col("delinquency_30"), F.col("delinquency_90"),
                   F.col("delinquency_180")))

    joined = (perf
              .select("quarter", "loan_id", "timestamp_year",
                      "timestamp_month",
                      F.col("current_loan_delinquency_status")
                      .alias("delinquency_12"),
                      F.col("current_actual_upb").alias("upb_12"))
              .join(agg, on=["loan_id", "quarter"], how="left"))

    # 12-month bucket expansion: explode(array(0..11)) — the reference's
    # "explode beats a cross join" idiom; exercises CreateArray+Generate
    months = 12
    month_y = F.explode(F.array(*[F.lit(i) for i in range(months)]))
    expanded = (joined
                .select("*", month_y.alias("month_y"))
                .select(
                    "quarter", "loan_id", "ever_30", "ever_90", "ever_180",
                    "delinquency_30", "delinquency_90", "delinquency_180",
                    "month_y", "delinquency_12", "upb_12",
                    F.floor(((F.col("timestamp_year") * 12 +
                              F.col("timestamp_month")) - 24000 -
                             F.col("month_y")) / months)
                    .alias("josh_mody_n"))
                .group_by("quarter", "loan_id", "josh_mody_n", "ever_30",
                          "ever_90", "ever_180", "month_y")
                .agg(F.max("delinquency_12").alias("delinquency_12"),
                     F.min("upb_12").alias("upb_12"))
                .with_column(
                    "timestamp_year",
                    F.floor((24000 + F.col("josh_mody_n") * months +
                             F.col("month_y") - 1) / 12))
                .with_column(
                    "timestamp_month_tmp",
                    F.pmod(24000 + F.col("josh_mody_n") * months +
                           F.col("month_y"), F.lit(12)))
                .with_column(
                    "timestamp_month",
                    F.when(F.col("timestamp_month_tmp") == 0, F.lit(12))
                    .otherwise(F.col("timestamp_month_tmp"))
                    .cast("int"))
                .with_column(
                    "delinquency_12",
                    (F.col("delinquency_12") > 3).cast("int") +
                    (F.col("upb_12") == 0).cast("int"))
                .drop("timestamp_month_tmp", "josh_mody_n", "month_y"))
    return expanded


def acquisition_clean(s, acq):
    """CreateAcquisition (MortgageSpark.scala:301) shape."""
    import pyarrow as pa
    from spark_rapids_tpu.api import functions as F
    mapping = s.create_dataframe(pa.table({
        "from_seller_name": [m[0] for m in NAME_MAPPING],
        "to_seller_name": [m[1] for m in NAME_MAPPING],
    }))
    return (acq
            .join(mapping,
                  F.col("seller_name") == F.col("from_seller_name"),
                  "left")
            .drop("from_seller_name")
            .with_column("seller_name",
                         F.coalesce(F.col("to_seller_name"),
                                    F.col("seller_name")))
            .drop("to_seller_name"))


def etl(s, data_dir: str):
    """CleanAcquisitionPrime: perf-delinquency x clean-acquisition."""
    perf = s.read.parquet(os.path.join(data_dir, "performance.parquet"))
    acq = s.read.parquet(os.path.join(data_dir, "acquisition.parquet"))
    perf_d = performance_delinquency(s, perf)
    acq_c = acquisition_clean(s, acq)
    return perf_d.join(acq_c, on=["loan_id", "quarter"], how="inner") \
        .drop("quarter")


def run(engine: str, data_dir: str, partitions: int = 4):
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.config import TpuConf
    conf = TpuConf({
        "spark.rapids.tpu.sql.enabled": engine == "tpu",
        "spark.rapids.tpu.sql.shuffle.partitions": partitions,
    })
    s = TpuSession(conf)
    t0 = time.perf_counter()
    out = etl(s, data_dir)
    n = out.count()
    wall = time.perf_counter() - t0
    return n, wall


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--engine", choices=["tpu", "cpu"], default="tpu")
    ap.add_argument("--compare", action="store_true")
    ap.add_argument("--data-dir", default="/tmp/mortgage_bench")
    ap.add_argument("--partitions", type=int, default=4)
    args = ap.parse_args()

    marker = os.path.join(args.data_dir, f".scale_{args.scale}")
    if not os.path.exists(marker):
        counts = generate(args.data_dir, args.scale)
        open(marker, "w").write(json.dumps(counts))
        print(f"generated {counts}", file=sys.stderr)

    if args.compare:
        n_t, t_tpu = run("tpu", args.data_dir, args.partitions)
        n_c, t_cpu = run("cpu", args.data_dir, args.partitions)
        assert n_t == n_c, f"row mismatch tpu={n_t} cpu={n_c}"
        print(json.dumps({
            "metric": "mortgage_etl_speedup", "value": round(t_cpu / t_tpu, 3),
            "unit": "x_vs_cpu", "rows": n_t,
            "tpu_s": round(t_tpu, 3), "cpu_s": round(t_cpu, 3)}))
    else:
        n, wall = run(args.engine, args.data_dir, args.partitions)
        print(json.dumps({
            "metric": "mortgage_etl_wall", "value": round(wall, 3),
            "unit": "s", "engine": args.engine, "rows": n}))


if __name__ == "__main__":
    main()
