"""TPC-H-style benchmark suite (scaled-down schema + queries).

Reference baseline configs (BASELINE.json): "TPC-H SF10 — scan +
hash-join + aggregate on Parquet".  This module generates lineitem /
orders / customer tables at a row-scaled factor, writes them to Parquet,
and runs representative queries (Q1 pricing summary, Q3 shipping
priority, Q5-style join-agg, Q6 forecast filter) on either engine.

Usage:
  python benchmarks/tpch.py --scale 0.01 --engine tpu
  python benchmarks/tpch.py --scale 0.01 --compare   # TPU vs CPU timings
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ROWS_PER_SF = {"lineitem": 6_000_000, "orders": 1_500_000,
               "customer": 150_000}


def generate(data_dir: str, scale: float, seed: int = 0):
    import pyarrow as pa
    import pyarrow.parquet as papq
    rng = np.random.default_rng(seed)
    os.makedirs(data_dir, exist_ok=True)

    n_li = max(int(ROWS_PER_SF["lineitem"] * scale), 1000)
    n_ord = max(int(ROWS_PER_SF["orders"] * scale), 250)
    n_cust = max(int(ROWS_PER_SF["customer"] * scale), 25)

    cust = pa.table({
        "c_custkey": np.arange(n_cust, dtype=np.int64),
        "c_mktsegment": rng.choice(
            ["BUILDING", "AUTOMOBILE", "MACHINERY", "HOUSEHOLD",
             "FURNITURE"], n_cust),
        "c_nationkey": rng.integers(0, 25, n_cust),
    })
    papq.write_table(cust, os.path.join(data_dir, "customer.parquet"))

    o_date = rng.integers(8035, 10591, n_ord)  # 1992-01..1998-12 in days
    orders = pa.table({
        "o_orderkey": np.arange(n_ord, dtype=np.int64),
        "o_custkey": rng.integers(0, n_cust, n_ord),
        "o_orderdate": o_date.astype(np.int32),
        "o_totalprice": (rng.random(n_ord) * 500000).round(2),
        "o_orderpriority": rng.choice(
            ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED",
             "5-LOW"], n_ord),
    })
    papq.write_table(orders, os.path.join(data_dir, "orders.parquet"))

    li_order = rng.integers(0, n_ord, n_li)
    ship = o_date[li_order] + rng.integers(1, 122, n_li)
    li = pa.table({
        "l_orderkey": li_order.astype(np.int64),
        "l_quantity": rng.integers(1, 51, n_li).astype(np.float64),
        "l_extendedprice": (rng.random(n_li) * 100000).round(2),
        "l_discount": (rng.integers(0, 11, n_li) / 100.0),
        "l_tax": (rng.integers(0, 9, n_li) / 100.0),
        "l_returnflag": rng.choice(["A", "N", "R"], n_li),
        "l_linestatus": rng.choice(["O", "F"], n_li),
        "l_shipdate": ship.astype(np.int32),
    })
    papq.write_table(li, os.path.join(data_dir, "lineitem.parquet"))
    return {"lineitem": n_li, "orders": n_ord, "customer": n_cust}


def q1(s, d):
    """Pricing summary report (TPC-H Q1 shape)."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.columnar import dtypes as T
    li = s.read.parquet(os.path.join(d, "lineitem.parquet"))
    return (li.filter(F.col("l_shipdate") <= 10471)
            .with_column("disc_price",
                         F.col("l_extendedprice") * (1 - F.col("l_discount")))
            .with_column("charge",
                         F.col("l_extendedprice") *
                         (1 - F.col("l_discount")) * (1 + F.col("l_tax")))
            .group_by("l_returnflag", "l_linestatus")
            .agg(F.sum("l_quantity").alias("sum_qty"),
                 F.sum("l_extendedprice").alias("sum_base_price"),
                 F.sum("disc_price").alias("sum_disc_price"),
                 F.sum("charge").alias("sum_charge"),
                 F.avg("l_quantity").alias("avg_qty"),
                 F.avg("l_extendedprice").alias("avg_price"),
                 F.avg("l_discount").alias("avg_disc"),
                 F.count().alias("count_order"))
            .sort("l_returnflag", "l_linestatus"))


def q3(s, d):
    """Shipping priority (join customer x orders x lineitem + agg + topN)."""
    from spark_rapids_tpu.api import functions as F
    cust = s.read.parquet(os.path.join(d, "customer.parquet"))
    orders = s.read.parquet(os.path.join(d, "orders.parquet"))
    li = s.read.parquet(os.path.join(d, "lineitem.parquet"))
    return (cust.filter(F.col("c_mktsegment") == "BUILDING")
            .join(orders, left_on_right_on(cust, orders), how="inner")
            .join(li.with_column_renamed("l_orderkey", "o_orderkey"),
                  on="o_orderkey")
            .filter(F.col("o_orderdate") < 9204)
            .with_column("revenue",
                         F.col("l_extendedprice") *
                         (1 - F.col("l_discount")))
            .group_by("o_orderkey", "o_orderdate")
            .agg(F.sum("revenue").alias("revenue"))
            .sort(F.col("revenue").desc())
            .limit(10))


def left_on_right_on(cust, orders):
    # helper for the custkey equi-join through the string-keys API
    return None


def q3_simple(s, d):
    from spark_rapids_tpu.api import functions as F
    cust = s.read.parquet(os.path.join(d, "customer.parquet")) \
        .with_column_renamed("c_custkey", "o_custkey")
    orders = s.read.parquet(os.path.join(d, "orders.parquet"))
    li = s.read.parquet(os.path.join(d, "lineitem.parquet")) \
        .with_column_renamed("l_orderkey", "o_orderkey")
    return (cust.filter(F.col("c_mktsegment") == "BUILDING")
            .join(orders, on="o_custkey")
            .join(li, on="o_orderkey")
            .filter(F.col("o_orderdate") < 9204)
            .with_column("revenue",
                         F.col("l_extendedprice") *
                         (1 - F.col("l_discount")))
            .group_by("o_orderkey", "o_orderdate")
            .agg(F.sum("revenue").alias("revenue"))
            .sort(F.col("revenue").desc(), F.col("o_orderkey").asc())
            .limit(10))


def q5_like(s, d):
    """Join-heavy aggregate across all three tables."""
    from spark_rapids_tpu.api import functions as F
    cust = s.read.parquet(os.path.join(d, "customer.parquet")) \
        .with_column_renamed("c_custkey", "o_custkey")
    orders = s.read.parquet(os.path.join(d, "orders.parquet"))
    li = s.read.parquet(os.path.join(d, "lineitem.parquet")) \
        .with_column_renamed("l_orderkey", "o_orderkey")
    return (li.join(orders, on="o_orderkey")
            .join(cust, on="o_custkey")
            .with_column("revenue",
                         F.col("l_extendedprice") *
                         (1 - F.col("l_discount")))
            .group_by("c_nationkey")
            .agg(F.sum("revenue").alias("revenue"),
                 F.count().alias("n"))
            .sort(F.col("revenue").desc()))


def q6(s, d):
    """Forecasting revenue change (pure filter + global agg)."""
    from spark_rapids_tpu.api import functions as F
    li = s.read.parquet(os.path.join(d, "lineitem.parquet"))
    return (li.filter((F.col("l_shipdate") >= 8766) &
                      (F.col("l_shipdate") < 9131) &
                      (F.col("l_discount") >= 0.05) &
                      (F.col("l_discount") <= 0.07) &
                      (F.col("l_quantity") < 24))
            .with_column("revenue",
                         F.col("l_extendedprice") * F.col("l_discount"))
            .agg(F.sum("revenue").alias("revenue")))


QUERIES = {"q1": q1, "q3": q3_simple, "q5": q5_like, "q6": q6}


def run(engine: str, data_dir: str, queries, repeats: int = 1):
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.config import TpuConf
    s = TpuSession(TpuConf({
        "spark.rapids.tpu.sql.enabled": engine == "tpu"}))
    times = {}
    for name in queries:
        fn = QUERIES[name]
        fn(s, data_dir).collect()  # warmup/compile
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            rows = fn(s, data_dir).collect()
            best = min(best, time.perf_counter() - t0)
        times[name] = {"seconds": round(best, 4), "rows": len(rows)}
    return times


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.001)
    ap.add_argument("--engine", choices=["tpu", "cpu"], default="tpu")
    ap.add_argument("--compare", action="store_true")
    ap.add_argument("--queries", default="q1,q3,q5,q6")
    ap.add_argument("--data-dir", default="/tmp/tpch_data")
    ap.add_argument("--repeats", type=int, default=2)
    args = ap.parse_args()
    tag = os.path.join(args.data_dir, f"sf{args.scale}")
    if not os.path.exists(os.path.join(tag, "lineitem.parquet")):
        sizes = generate(tag, args.scale)
        print(f"generated {sizes}", file=sys.stderr)
    queries = args.queries.split(",")
    if args.compare:
        tpu = run("tpu", tag, queries, args.repeats)
        cpu = run("cpu", tag, queries, args.repeats)
        out = {q: {"tpu_s": tpu[q]["seconds"], "cpu_s": cpu[q]["seconds"],
                   "speedup": round(cpu[q]["seconds"] /
                                    max(tpu[q]["seconds"], 1e-9), 2)}
               for q in queries}
        print(json.dumps(out, indent=2))
    else:
        print(json.dumps(run(args.engine, tag, queries, args.repeats),
                         indent=2))


if __name__ == "__main__":
    main()
