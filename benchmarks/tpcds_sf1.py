"""TPC-DS SF1 per-query perf: TPU engine vs the CPU oracle, EXACT
float mode (variableFloatAgg stays at its default OFF).

The round-3 verdict's bar: geomean TPU >= CPU oracle at SF1 across
>= 20 TPC-DS queries, exact mode, numbers committed in the repo.
Writes benchmarks/tpcds_sf1_times.json incrementally (a long sweep
interrupted mid-way still leaves every finished query's numbers).

Usage:
  python benchmarks/tpcds_sf1.py [--queries q3,q7,...] [--scale 1.0]
"""
import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import tpcds                                    # noqa: E402
from tpcds_queries import QUERIES               # noqa: E402

# fact-table-heavy queries whose CPU-oracle runtime at SF1 stays
# tractable (the oracle is single-process pyarrow): star-join
# aggregates, window reports, returns joins — 26 queries
DEFAULT_QUERIES = [
    "q3", "q7", "q12", "q13", "q15", "q19", "q20", "q21", "q26",
    "q27", "q34", "q36", "q42", "q43", "q46", "q48", "q52", "q53",
    "q55", "q59", "q63", "q65", "q68", "q73", "q79", "q89", "q96",
    "q98",
]


def _rows_equal(cpu_rows, tpu_rows, rel=1e-9):
    """Canon-rows multiset equality with ulp-level float tolerance —
    the tests/harness.py contract applied at real scale."""
    import math as m
    if len(cpu_rows) != len(tpu_rows):
        return False

    def norm(v):
        if isinstance(v, float):
            return "NaN" if m.isnan(v) else v
        return v

    def key(row):
        # floats key on a 9-significant-digit rendering so ulp-level
        # engine differences don't reorder one side's sort and
        # misalign the row pairing
        return tuple(f"{v:.9e}" if isinstance(v, float) and
                     not m.isnan(v) else str(norm(v)) for v in row)
    a = sorted(cpu_rows, key=key)
    b = sorted(tpu_rows, key=key)
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if m.isnan(va) and m.isnan(vb):
                    continue
                if va == vb or abs(va - vb) <= rel * max(
                        abs(va), abs(vb), 1.0):
                    continue
                return False
            elif va != vb:
                return False
    return True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--queries", default=",".join(DEFAULT_QUERIES))
    ap.add_argument("--data-dir", default="/tmp/tpcds_data")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tpcds_sf1_times.json"))
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--verify", action="store_true",
                    help="compare TPU vs CPU canon rows per query "
                         "(ulp-level float tolerance) and record "
                         "verified: true/false")
    args = ap.parse_args()
    tag = os.path.join(args.data_dir, f"sf{args.scale}_v5")
    if not os.path.exists(os.path.join(tag, "store_sales.parquet")):
        tpcds.generate(tag, args.scale)
        print("generated", file=sys.stderr)

    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.config import TpuConf

    def mk(enabled):
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.sql.enabled": enabled,
            # large batches amortize dispatch at SF1 (exact float mode
            # stays DEFAULT OFF — this is the apples-to-apples run)
            "spark.rapids.tpu.sql.batchSizeRows": 1 << 22,
            "spark.rapids.tpu.sql.reader.batchSizeRows": 1 << 22,
        }))
        tpcds.register(s, tag)
        return s

    results = {}
    if args.resume and os.path.exists(args.out):
        with open(args.out) as f:
            results = json.load(f).get("queries", {})

    queries = [q for q in args.queries.split(",") if q]
    s_tpu = mk(True)
    s_cpu = mk(False)
    for name in queries:
        if name in results:
            continue
        sql = QUERIES[name]
        entry = {}
        try:
            from spark_rapids_tpu.columnar import pending
            t0 = time.perf_counter()
            rows1 = s_tpu.sql(sql).collect()
            entry["tpu_first_s"] = round(time.perf_counter() - t0, 3)
            f0 = pending.FLUSH_COUNT
            t0 = time.perf_counter()
            rows = s_tpu.sql(sql).collect()
            entry["tpu_s"] = round(time.perf_counter() - t0, 3)
            entry["flushes"] = pending.FLUSH_COUNT - f0
            entry["rows"] = len(rows)
            t0 = time.perf_counter()
            cpu_rows = s_cpu.sql(sql).collect()
            entry["cpu_s"] = round(time.perf_counter() - t0, 3)
            entry["speedup"] = round(entry["cpu_s"] /
                                     max(entry["tpu_s"], 1e-9), 3)
            if args.verify:
                entry["verified"] = _rows_equal(cpu_rows, rows)
                if not entry["verified"]:
                    entry["error"] = "VERIFY MISMATCH"
        except Exception as e:  # noqa: BLE001 - recorded per query
            entry["error"] = f"{type(e).__name__}: {e}"[:200]
        results[name] = entry
        ok = [r for r in results.values() if "speedup" in r]
        geo = math.exp(sum(math.log(r["speedup"]) for r in ok)
                       / len(ok)) if ok else None
        doc = {"scale": args.scale, "float_mode": "exact",
               "geomean_speedup": round(geo, 3) if geo else None,
               "n_queries": len(ok), "queries": results}
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        print(f"{name}: {entry}", file=sys.stderr, flush=True)
    print(json.dumps({"geomean_speedup": doc["geomean_speedup"],
                      "n_queries": doc["n_queries"]}))


if __name__ == "__main__":
    main()
