"""TPC-DS query corpus for the scaled star schema in tpcds.py.

Faithful renditions of the official query shapes (qualification
parameter choices) over the columns the generator produces; queries
including the correlated-SCALAR-subquery family (q1/q6/q32/q81/q92),
which the front end decorrelates to group-by + join.  Reference
surface:
integration_tests qa_nightly + the official tpcds queries directory.

Every query is verified TPU-vs-CPU by ``tpcds.py --verify`` (rows
compared with float tolerance); the pass/fail matrix is written to
``benchmarks/tpcds_matrix.json``.
"""

QUERIES = {}

# --------------------------------------------------------------------------
# star-join aggregates
# --------------------------------------------------------------------------

QUERIES["q3"] = """
    select d_year, i_brand_id brand_id, i_brand brand,
           sum(ss_ext_sales_price) sum_agg
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manufact_id = 128 and d_moy = 11
    group by d_year, i_brand_id, i_brand
    order by d_year, sum_agg desc, brand_id
    limit 100"""

QUERIES["q7"] = """
    select i_item_id,
           avg(ss_quantity) agg1, avg(ss_list_price) agg2,
           avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
    from store_sales, customer_demographics, date_dim, item, promotion
    where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
      and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
      and cd_gender = 'M' and cd_marital_status = 'S'
      and cd_education_status = 'College'
      and (p_channel_email = 'N' or p_channel_event = 'N')
      and d_year = 2000
    group by i_item_id
    order by i_item_id
    limit 100"""

QUERIES["q12"] = """
    select i_item_id, i_item_desc, i_category, i_class, i_current_price,
           sum(ws_ext_sales_price) as itemrevenue,
           sum(ws_ext_sales_price) * 100.0 /
             sum(sum(ws_ext_sales_price)) over (partition by i_class)
             as revenueratio
    from web_sales, item, date_dim
    where ws_item_sk = i_item_sk
      and i_category in ('Sports', 'Books', 'Home')
      and ws_sold_date_sk = d_date_sk
      and d_year = 1999 and d_moy between 2 and 3
    group by i_item_id, i_item_desc, i_category, i_class,
             i_current_price
    order by i_category, i_class, i_item_id, i_item_desc, revenueratio
    limit 100"""

QUERIES["q13"] = """
    select avg(ss_quantity) avg_q, avg(ss_ext_sales_price) avg_esp,
           avg(ss_ext_wholesale_cost) avg_ewc,
           sum(ss_ext_wholesale_cost) sum_ewc
    from store_sales, store, customer_demographics,
         household_demographics, customer_address, date_dim
    where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
      and d_year = 2001
      and ((ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
            and cd_marital_status = 'M'
            and cd_education_status = 'Advanced Degree'
            and ss_sales_price between 100.00 and 150.00
            and hd_dep_count = 3)
        or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
            and cd_marital_status = 'S'
            and cd_education_status = 'College'
            and ss_sales_price between 50.00 and 100.00
            and hd_dep_count = 1)
        or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
            and cd_marital_status = 'W'
            and cd_education_status = '2 yr Degree'
            and ss_sales_price between 150.00 and 200.00
            and hd_dep_count = 1))
      and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('TX', 'OH', 'TX')
            and ss_net_profit between 100 and 200)
        or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('OR', 'NM', 'KY')
            and ss_net_profit between 150 and 300)
        or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('VA', 'TX', 'MS')
            and ss_net_profit between 50 and 250))"""

QUERIES["q15"] = """
    select ca_zip, sum(cs_sales_price) sum_sales
    from catalog_sales, customer, customer_address, date_dim
    where cs_bill_customer_sk = c_customer_sk
      and c_current_addr_sk = ca_address_sk
      and (substring(ca_zip, 1, 5) in
             ('85669', '86197', '88274', '83405', '86475', '85392',
              '85460', '80348', '81792')
           or ca_state in ('CA', 'WA', 'GA')
           or cs_sales_price > 500)
      and cs_sold_date_sk = d_date_sk
      and d_qoy = 2 and d_year = 2001
    group by ca_zip
    order by ca_zip
    limit 100"""

QUERIES["q19"] = """
    select i_brand_id brand_id, i_brand brand, i_manufact_id,
           i_manufact, sum(ss_ext_sales_price) ext_price
    from date_dim, store_sales, item, customer, customer_address, store
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manager_id = 8 and d_moy = 11 and d_year = 1998
      and ss_customer_sk = c_customer_sk
      and c_current_addr_sk = ca_address_sk
      and substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
      and ss_store_sk = s_store_sk
    group by i_brand_id, i_brand, i_manufact_id, i_manufact
    order by ext_price desc, brand_id
    limit 100"""

QUERIES["q20"] = """
    select i_item_id, i_item_desc, i_category, i_class, i_current_price,
           sum(cs_ext_sales_price) as itemrevenue,
           sum(cs_ext_sales_price) * 100.0 /
             sum(sum(cs_ext_sales_price)) over (partition by i_class)
             as revenueratio
    from catalog_sales, item, date_dim
    where cs_item_sk = i_item_sk
      and i_category in ('Sports', 'Books', 'Home')
      and cs_sold_date_sk = d_date_sk
      and d_year = 1999 and d_moy between 2 and 3
    group by i_item_id, i_item_desc, i_category, i_class,
             i_current_price
    order by i_category, i_class, i_item_id, i_item_desc, revenueratio
    limit 100"""

QUERIES["q21"] = """
    select w_warehouse_name, i_item_id,
           sum(case when d_moy < 3 then inv_quantity_on_hand else 0 end)
             as inv_before,
           sum(case when d_moy >= 3 then inv_quantity_on_hand else 0 end)
             as inv_after
    from inventory, warehouse, item, date_dim
    where i_current_price between 0.99 and 1.49
      and i_item_sk = inv_item_sk
      and inv_warehouse_sk = w_warehouse_sk
      and inv_date_sk = d_date_sk
      and d_year = 2000
    group by w_warehouse_name, i_item_id
    having sum(case when d_moy < 3 then inv_quantity_on_hand else 0
               end) > 0
    order by w_warehouse_name, i_item_id
    limit 100"""

QUERIES["q22"] = """
    select i_product_name, i_brand, i_class, i_category,
           avg(inv_quantity_on_hand) qoh
    from inventory, date_dim, item
    where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
      and d_month_seq between 1200 and 1200 + 11
    group by rollup(i_product_name, i_brand, i_class, i_category)
    order by qoh, i_product_name, i_brand, i_class, i_category
    limit 100"""

QUERIES["q25"] = """
    select i_item_id, i_item_desc, s_store_id, s_store_name,
           sum(ss_net_profit) as store_sales_profit,
           sum(sr_net_loss) as store_returns_loss,
           sum(cs_net_profit) as catalog_sales_profit
    from store_sales, store_returns, catalog_sales, date_dim d1,
         date_dim d2, date_dim d3, store, item
    where d1.d_moy = 4 and d1.d_year = 2001
      and d1.d_date_sk = ss_sold_date_sk
      and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
      and ss_customer_sk = sr_customer_sk
      and ss_item_sk = sr_item_sk
      and ss_ticket_number = sr_ticket_number
      and sr_returned_date_sk = d2.d_date_sk
      and d2.d_moy between 4 and 10 and d2.d_year = 2001
      and sr_customer_sk = cs_bill_customer_sk
      and sr_item_sk = cs_item_sk
      and cs_sold_date_sk = d3.d_date_sk
      and d3.d_moy between 4 and 10 and d3.d_year = 2001
    group by i_item_id, i_item_desc, s_store_id, s_store_name
    order by i_item_id, i_item_desc, s_store_id, s_store_name
    limit 100"""

QUERIES["q26"] = """
    select i_item_id,
           avg(cs_quantity) agg1, avg(cs_list_price) agg2,
           avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
    from catalog_sales, customer_demographics, date_dim, item, promotion
    where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
      and cs_bill_cdemo_sk = cd_demo_sk and cs_promo_sk = p_promo_sk
      and cd_gender = 'M' and cd_marital_status = 'S'
      and cd_education_status = 'College'
      and (p_channel_email = 'N' or p_channel_event = 'N')
      and d_year = 2000
    group by i_item_id
    order by i_item_id
    limit 100"""

QUERIES["q27"] = """
    select i_item_id, s_state, grouping(s_state) g_state,
           avg(ss_quantity) agg1, avg(ss_list_price) agg2,
           avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
    from store_sales, customer_demographics, date_dim, store, item
    where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
      and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
      and cd_gender = 'M' and cd_marital_status = 'S'
      and cd_education_status = 'College' and d_year = 2002
    group by rollup (i_item_id, s_state)
    order by i_item_id, s_state
    limit 100"""

QUERIES["q29"] = """
    select i_item_id, i_item_desc, s_store_id, s_store_name,
           sum(ss_quantity) as store_sales_quantity,
           sum(sr_return_quantity) as store_returns_quantity,
           sum(cs_quantity) as catalog_sales_quantity
    from store_sales, store_returns, catalog_sales, date_dim d1,
         date_dim d2, date_dim d3, store, item
    where d1.d_moy = 9 and d1.d_year = 1999
      and d1.d_date_sk = ss_sold_date_sk
      and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
      and ss_customer_sk = sr_customer_sk
      and ss_item_sk = sr_item_sk
      and ss_ticket_number = sr_ticket_number
      and sr_returned_date_sk = d2.d_date_sk
      and d2.d_moy between 9 and 12 and d2.d_year = 1999
      and sr_customer_sk = cs_bill_customer_sk
      and sr_item_sk = cs_item_sk
      and cs_sold_date_sk = d3.d_date_sk
      and d3.d_year in (1999, 2000, 2001)
    group by i_item_id, i_item_desc, s_store_id, s_store_name
    order by i_item_id, i_item_desc, s_store_id, s_store_name
    limit 100"""

QUERIES["q33"] = """
    with ss as (
      select i_manufact_id, sum(ss_ext_sales_price) total_sales
      from store_sales, date_dim, customer_address, item
      where i_manufact_id in (
              select i_manufact_id from item where i_category = 'Books')
        and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 1
        and ss_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_manufact_id),
    cs as (
      select i_manufact_id, sum(cs_ext_sales_price) total_sales
      from catalog_sales, date_dim, customer_address, item
      where i_manufact_id in (
              select i_manufact_id from item where i_category = 'Books')
        and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 1
        and cs_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_manufact_id),
    ws as (
      select i_manufact_id, sum(ws_ext_sales_price) total_sales
      from web_sales, date_dim, customer_address, item
      where i_manufact_id in (
              select i_manufact_id from item where i_category = 'Books')
        and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 1
        and ws_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_manufact_id)
    select i_manufact_id, sum(total_sales) total_sales
    from (select * from ss union all
          select * from cs union all
          select * from ws) tmp1
    group by i_manufact_id
    order by total_sales, i_manufact_id
    limit 100"""

QUERIES["q34"] = """
    select c_last_name, c_first_name, c_salutation,
           c_preferred_cust_flag, ss_ticket_number, cnt
    from (select ss_ticket_number, ss_customer_sk, count(*) cnt
          from store_sales, date_dim, store, household_demographics
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_hdemo_sk = hd_demo_sk
            and (d_dom between 1 and 3 or d_dom between 25 and 28)
            and (hd_buy_potential = '>10000'
                 or hd_buy_potential = 'Unknown')
            and hd_vehicle_count > 0
            and d_year in (1999, 2000, 2001)
            and s_county in ('Williamson County', 'Ziebach County',
                             'Walker County', 'Rush County')
          group by ss_ticket_number, ss_customer_sk) dn, customer
    where ss_customer_sk = c_customer_sk and cnt between 15 and 20
    order by c_last_name, c_first_name, c_salutation,
             c_preferred_cust_flag desc, ss_ticket_number
    limit 1000"""

QUERIES["q36"] = """
    select sum(ss_net_profit) / sum(ss_ext_sales_price)
             as gross_margin,
           i_category, i_class, grouping(i_category) + grouping(i_class)
             as lochierarchy
    from store_sales, date_dim d1, item, store
    where d1.d_year = 2001 and d1.d_date_sk = ss_sold_date_sk
      and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
      and s_state in ('TN', 'SD', 'AL', 'GA')
    group by rollup(i_category, i_class)
    order by lochierarchy desc, i_category, i_class
    limit 100"""

QUERIES["q37"] = """
    select i_item_id, i_item_desc, i_current_price
    from item, inventory, date_dim, catalog_sales
    where i_current_price between 68 and 68 + 30
      and inv_item_sk = i_item_sk
      and d_date_sk = inv_date_sk
      and d_year = 2000
      and i_manufact_id in (677, 940, 694, 808)
      and inv_quantity_on_hand between 100 and 500
      and cs_item_sk = i_item_sk
    group by i_item_id, i_item_desc, i_current_price
    order by i_item_id
    limit 100"""

QUERIES["q40"] = """
    select w_state, i_item_id,
           sum(case when d_year < 2000 then cs_sales_price -
               coalesce(cr_return_amount, 0) else 0 end)
             as sales_before,
           sum(case when d_year >= 2000 then cs_sales_price -
               coalesce(cr_return_amount, 0) else 0 end)
             as sales_after
    from catalog_sales
      left outer join catalog_returns
        on (cs_order_number = cr_order_number
            and cs_item_sk = cr_item_sk),
      warehouse, item, date_dim
    where i_current_price between 0.99 and 1.49
      and i_item_sk = cs_item_sk
      and cs_warehouse_sk = w_warehouse_sk
      and cs_sold_date_sk = d_date_sk
      and d_year in (1999, 2000, 2001)
    group by w_state, i_item_id
    order by w_state, i_item_id
    limit 100"""

QUERIES["q42"] = """
    select d_year, i_category_id, i_category,
           sum(ss_ext_sales_price) total_sales
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manager_id = 1 and d_moy = 11 and d_year = 2000
    group by d_year, i_category_id, i_category
    order by total_sales desc, d_year, i_category_id, i_category
    limit 100"""

QUERIES["q43"] = """
    select s_store_name, s_store_id,
           sum(case when d_day_name = 'Sunday' then ss_sales_price
                    else null end) sun_sales,
           sum(case when d_day_name = 'Monday' then ss_sales_price
                    else null end) mon_sales,
           sum(case when d_day_name = 'Tuesday' then ss_sales_price
                    else null end) tue_sales,
           sum(case when d_day_name = 'Wednesday' then ss_sales_price
                    else null end) wed_sales,
           sum(case when d_day_name = 'Thursday' then ss_sales_price
                    else null end) thu_sales,
           sum(case when d_day_name = 'Friday' then ss_sales_price
                    else null end) fri_sales,
           sum(case when d_day_name = 'Saturday' then ss_sales_price
                    else null end) sat_sales
    from date_dim, store_sales, store
    where d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk
      and s_gmt_offset = -5 and d_year = 2000
    group by s_store_name, s_store_id
    order by s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
             wed_sales, thu_sales, fri_sales, sat_sales
    limit 100"""

QUERIES["q45"] = """
    select ca_zip, ca_city, sum(ws_sales_price) sum_sales
    from web_sales, customer, customer_address, date_dim, item
    where ws_bill_customer_sk = c_customer_sk
      and c_current_addr_sk = ca_address_sk
      and ws_item_sk = i_item_sk
      and (substring(ca_zip, 1, 5) in
             ('85669', '86197', '88274', '83405', '86475', '85392',
              '85460', '80348', '81792')
           or i_item_id in (
               select i_item_id from item
               where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)))
      and ws_sold_date_sk = d_date_sk
      and d_qoy = 2 and d_year = 2001
    group by ca_zip, ca_city
    order by ca_zip, ca_city
    limit 100"""

QUERIES["q46"] = """
    select c_last_name, c_first_name, ca_city, bought_city,
           ss_ticket_number, amt, profit
    from (select ss_ticket_number, ss_customer_sk,
                 ca_city bought_city, sum(ss_coupon_amt) amt,
                 sum(ss_net_profit) profit
          from store_sales, date_dim, store, household_demographics,
               customer_address
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_hdemo_sk = hd_demo_sk
            and ss_addr_sk = ca_address_sk
            and (hd_dep_count = 4 or hd_vehicle_count = 3)
            and d_dow in (6, 0)
            and d_year in (1999, 2000, 2001)
            and s_city in ('Fairview', 'Midway')
          group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
                   ca_city) dn,
         customer, customer_address current_addr
    where ss_customer_sk = c_customer_sk
      and c_current_addr_sk = current_addr.ca_address_sk
      and current_addr.ca_city <> bought_city
    order by c_last_name, c_first_name, ca_city, bought_city,
             ss_ticket_number
    limit 100"""

QUERIES["q48"] = """
    select sum(ss_quantity) sum_q
    from store_sales, store, customer_demographics, customer_address,
         date_dim
    where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
      and d_year = 2000
      and ((cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'M'
            and cd_education_status = '4 yr Degree'
            and ss_sales_price between 100.00 and 150.00)
        or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'D'
            and cd_education_status = '2 yr Degree'
            and ss_sales_price between 50.00 and 100.00)
        or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'S'
            and cd_education_status = 'College'
            and ss_sales_price between 150.00 and 200.00))
      and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('CO', 'OH', 'TX')
            and ss_net_profit between 0 and 2000)
        or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('OR', 'MN', 'KY')
            and ss_net_profit between 150 and 3000)
        or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('VA', 'CA', 'MS')
            and ss_net_profit between 50 and 25000))"""

QUERIES["q50"] = """
    select s_store_name, s_company_id, s_state, s_zip,
           sum(case when (sr_returned_date_sk - ss_sold_date_sk <= 30)
               then 1 else 0 end) as d30,
           sum(case when (sr_returned_date_sk - ss_sold_date_sk > 30)
                     and (sr_returned_date_sk - ss_sold_date_sk <= 60)
               then 1 else 0 end) as d31_60,
           sum(case when (sr_returned_date_sk - ss_sold_date_sk > 60)
                     and (sr_returned_date_sk - ss_sold_date_sk <= 90)
               then 1 else 0 end) as d61_90,
           sum(case when (sr_returned_date_sk - ss_sold_date_sk > 90)
               then 1 else 0 end) as d90_plus
    from store_sales, store_returns, store, date_dim d1, date_dim d2
    where d2.d_year = 2001 and d2.d_moy = 8
      and ss_ticket_number = sr_ticket_number
      and ss_item_sk = sr_item_sk
      and ss_sold_date_sk = d1.d_date_sk
      and sr_returned_date_sk = d2.d_date_sk
      and ss_customer_sk = sr_customer_sk
      and ss_store_sk = s_store_sk
    group by s_store_name, s_company_id, s_state, s_zip
    order by s_store_name, s_company_id, s_state, s_zip
    limit 100"""

QUERIES["q52"] = """
    select d_year, i_brand_id brand_id, i_brand brand,
           sum(ss_ext_sales_price) ext_price
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manager_id = 1 and d_moy = 11 and d_year = 2000
    group by d_year, i_brand_id, i_brand
    order by d_year, ext_price desc, brand_id
    limit 100"""

QUERIES["q55"] = """
    select i_brand_id brand_id, i_brand brand,
           sum(ss_ext_sales_price) ext_price
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manager_id = 28 and d_moy = 11 and d_year = 1999
    group by i_brand_id, i_brand
    order by ext_price desc, brand_id
    limit 100"""

# --------------------------------------------------------------------------
# windows, set operations, multi-channel CTEs
# --------------------------------------------------------------------------

QUERIES["q9"] = """
    select case when (select count(*) from store_sales
                      where ss_quantity between 1 and 20) > 10000
                then (select avg(ss_ext_discount_amt) from store_sales
                      where ss_quantity between 1 and 20)
                else (select avg(ss_net_paid) from store_sales
                      where ss_quantity between 1 and 20) end bucket1,
           case when (select count(*) from store_sales
                      where ss_quantity between 21 and 40) > 10000
                then (select avg(ss_ext_discount_amt) from store_sales
                      where ss_quantity between 21 and 40)
                else (select avg(ss_net_paid) from store_sales
                      where ss_quantity between 21 and 40) end bucket2,
           case when (select count(*) from store_sales
                      where ss_quantity between 41 and 60) > 10000
                then (select avg(ss_ext_discount_amt) from store_sales
                      where ss_quantity between 41 and 60)
                else (select avg(ss_net_paid) from store_sales
                      where ss_quantity between 41 and 60) end bucket3
    from reason
    where r_reason_sk = 1"""

QUERIES["q18"] = """
    select i_item_id, ca_country, ca_state, ca_county,
           avg(cs_quantity) agg1, avg(cs_list_price) agg2,
           avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4,
           avg(cs_net_profit) agg5, avg(c_birth_year) agg6
    from catalog_sales, customer_demographics cd1, customer, item,
         customer_address, date_dim
    where cs_sold_date_sk = d_date_sk
      and cs_item_sk = i_item_sk
      and cs_bill_cdemo_sk = cd1.cd_demo_sk
      and cs_bill_customer_sk = c_customer_sk
      and cd1.cd_gender = 'F'
      and cd1.cd_education_status = 'Unknown'
      and c_current_addr_sk = ca_address_sk
      and c_birth_month in (1, 6, 8, 9, 12, 2)
      and d_year = 1998
      and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA', 'TN')
    group by rollup(i_item_id, ca_country, ca_state, ca_county)
    order by ca_country, ca_state, ca_county, i_item_id
    limit 100"""

QUERIES["q28"] = """
    select b1.lp b1_lp, b1.cnt b1_cnt, b2.lp b2_lp, b2.cnt b2_cnt,
           b3.lp b3_lp, b3.cnt b3_cnt
    from (select avg(ss_list_price) lp, count(ss_list_price) cnt
          from store_sales
          where ss_quantity between 0 and 5
            and (ss_list_price between 8 and 18
                 or ss_coupon_amt between 459 and 1459
                 or ss_wholesale_cost between 57 and 77)) b1,
         (select avg(ss_list_price) lp, count(ss_list_price) cnt
          from store_sales
          where ss_quantity between 6 and 10
            and (ss_list_price between 90 and 100
                 or ss_coupon_amt between 2323 and 3323
                 or ss_wholesale_cost between 31 and 51)) b2,
         (select avg(ss_list_price) lp, count(ss_list_price) cnt
          from store_sales
          where ss_quantity between 11 and 15
            and (ss_list_price between 142 and 152
                 or ss_coupon_amt between 12214 and 13214
                 or ss_wholesale_cost between 79 and 99)) b3"""

QUERIES["q38"] = """
    select count(*) cnt from (
      select distinct c_last_name, c_first_name, d_date
      from store_sales, date_dim, customer
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_customer_sk = customer.c_customer_sk
        and d_month_seq between 1200 and 1200 + 11
      intersect
      select distinct c_last_name, c_first_name, d_date
      from catalog_sales, date_dim, customer
      where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
        and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
        and d_month_seq between 1200 and 1200 + 11
      intersect
      select distinct c_last_name, c_first_name, d_date
      from web_sales, date_dim, customer
      where web_sales.ws_sold_date_sk = date_dim.d_date_sk
        and web_sales.ws_bill_customer_sk = customer.c_customer_sk
        and d_month_seq between 1200 and 1200 + 11
    ) hot_cust
    limit 100"""

QUERIES["q53"] = """
    select manufact_id, sum_sales, avg_quarterly_sales
    from (select i_manufact_id manufact_id,
                 sum(ss_sales_price) sum_sales,
                 avg(sum(ss_sales_price))
                   over (partition by i_manufact_id)
                   avg_quarterly_sales
          from item, store_sales, date_dim, store
          where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and d_month_seq in (1200, 1201, 1202, 1203, 1204, 1205,
                                1206, 1207, 1208, 1209, 1210, 1211)
            and ((i_category in ('Books', 'Home', 'Sports')
                  and i_class in ('classical', 'fishing', 'football'))
              or (i_category in ('Women', 'Music', 'Men')
                  and i_class in ('shirts', 'dresses', 'pants')))
          group by i_manufact_id, d_qoy) tmp1
    where case when avg_quarterly_sales > 0
               then abs(sum_sales - avg_quarterly_sales) /
                    avg_quarterly_sales else null end > 0.1
    order by avg_quarterly_sales, sum_sales, manufact_id
    limit 100"""

QUERIES["q56"] = """
    with ss as (
      select i_item_id, sum(ss_ext_sales_price) total_sales
      from store_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_color in ('red', 'blue', 'green'))
        and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and d_year = 2000 and d_moy = 2
        and ss_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id),
    cs as (
      select i_item_id, sum(cs_ext_sales_price) total_sales
      from catalog_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_color in ('red', 'blue', 'green'))
        and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
        and d_year = 2000 and d_moy = 2
        and cs_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id),
    ws as (
      select i_item_id, sum(ws_ext_sales_price) total_sales
      from web_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_color in ('red', 'blue', 'green'))
        and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
        and d_year = 2000 and d_moy = 2
        and ws_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id)
    select i_item_id, sum(total_sales) total_sales
    from (select * from ss union all
          select * from cs union all
          select * from ws) tmp1
    group by i_item_id
    order by total_sales, i_item_id
    limit 100"""

QUERIES["q60"] = """
    with ss as (
      select i_item_id, sum(ss_ext_sales_price) total_sales
      from store_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_category = 'Music')
        and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 9
        and ss_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id),
    cs as (
      select i_item_id, sum(cs_ext_sales_price) total_sales
      from catalog_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_category = 'Music')
        and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 9
        and cs_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id),
    ws as (
      select i_item_id, sum(ws_ext_sales_price) total_sales
      from web_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_category = 'Music')
        and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 9
        and ws_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id)
    select i_item_id, sum(total_sales) total_sales
    from (select * from ss union all
          select * from cs union all
          select * from ws) tmp1
    group by i_item_id
    order by i_item_id, total_sales
    limit 100"""

QUERIES["q61"] = """
    select promotions, total,
           cast(promotions as double) / cast(total as double) * 100
             as promo_pct
    from (select sum(ss_ext_sales_price) promotions
          from store_sales, store, promotion, date_dim, customer,
               customer_address, item
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_promo_sk = p_promo_sk
            and ss_customer_sk = c_customer_sk
            and ca_address_sk = c_current_addr_sk
            and ss_item_sk = i_item_sk
            and ca_gmt_offset = -5 and i_category = 'Books'
            and (p_channel_dmail = 'Y' or p_channel_email = 'Y'
                 or p_channel_tv = 'Y')
            and s_gmt_offset = -5 and d_year = 1998
            and d_moy = 11) promotional_sales,
         (select sum(ss_ext_sales_price) total
          from store_sales, store, date_dim, customer,
               customer_address, item
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_customer_sk = c_customer_sk
            and ca_address_sk = c_current_addr_sk
            and ss_item_sk = i_item_sk
            and ca_gmt_offset = -5 and i_category = 'Books'
            and s_gmt_offset = -5 and d_year = 1998
            and d_moy = 11) all_sales
    order by promotions, total
    limit 100"""

QUERIES["q62"] = """
    select w_warehouse_name, sm_type, web_name,
           sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30)
               then 1 else 0 end) as d30,
           sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30)
                     and (ws_ship_date_sk - ws_sold_date_sk <= 60)
               then 1 else 0 end) as d31_60,
           sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60)
                     and (ws_ship_date_sk - ws_sold_date_sk <= 90)
               then 1 else 0 end) as d61_90,
           sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90)
               then 1 else 0 end) as d90_plus
    from web_sales, warehouse, ship_mode, web_site, date_dim
    where d_month_seq between 1200 and 1200 + 11
      and ws_ship_date_sk = d_date_sk
      and ws_warehouse_sk = w_warehouse_sk
      and ws_ship_mode_sk = sm_ship_mode_sk
      and ws_web_site_sk = web_site_sk
    group by w_warehouse_name, sm_type, web_name
    order by w_warehouse_name, sm_type, web_name
    limit 100"""

QUERIES["q63"] = """
    select manager_id, sum_sales, avg_monthly_sales
    from (select i_manager_id manager_id,
                 sum(ss_sales_price) sum_sales,
                 avg(sum(ss_sales_price))
                   over (partition by i_manager_id) avg_monthly_sales
          from item, store_sales, date_dim, store
          where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and d_month_seq in (1200, 1201, 1202, 1203, 1204, 1205,
                                1206, 1207, 1208, 1209, 1210, 1211)
            and ((i_category in ('Books', 'Home', 'Sports')
                  and i_class in ('classical', 'fishing', 'football'))
              or (i_category in ('Women', 'Music', 'Men')
                  and i_class in ('shirts', 'dresses', 'pants')))
          group by i_manager_id, d_moy) tmp1
    where case when avg_monthly_sales > 0
               then abs(sum_sales - avg_monthly_sales) /
                    avg_monthly_sales else null end > 0.1
    order by manager_id, avg_monthly_sales, sum_sales
    limit 100"""

QUERIES["q65"] = """
    select s_store_name, i_item_desc, sc.revenue, i_current_price,
           i_wholesale_cost, i_brand
    from store, item,
         (select ss_store_sk, avg(revenue) as ave
          from (select ss_store_sk, ss_item_sk,
                       sum(ss_sales_price) as revenue
                from store_sales, date_dim
                where ss_sold_date_sk = d_date_sk
                  and d_month_seq between 1176 and 1176 + 11
                group by ss_store_sk, ss_item_sk) sa
          group by ss_store_sk) sb,
         (select ss_store_sk, ss_item_sk,
                 sum(ss_sales_price) as revenue
          from store_sales, date_dim
          where ss_sold_date_sk = d_date_sk
            and d_month_seq between 1176 and 1176 + 11
          group by ss_store_sk, ss_item_sk) sc
    where sb.ss_store_sk = sc.ss_store_sk
      and sc.revenue <= 0.1 * sb.ave
      and s_store_sk = sc.ss_store_sk
      and i_item_sk = sc.ss_item_sk
    order by s_store_name, i_item_desc, sc.revenue
    limit 100"""

QUERIES["q68"] = """
    select c_last_name, c_first_name, ca_city, bought_city,
           ss_ticket_number, extended_price, extended_tax,
           list_price
    from (select ss_ticket_number, ss_customer_sk,
                 ca_city bought_city,
                 sum(ss_ext_sales_price) extended_price,
                 sum(ss_ext_list_price) list_price,
                 sum(ss_ext_tax) extended_tax
          from store_sales, date_dim, store, household_demographics,
               customer_address
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_hdemo_sk = hd_demo_sk
            and ss_addr_sk = ca_address_sk
            and d_dom between 1 and 2
            and (hd_dep_count = 4 or hd_vehicle_count = 3)
            and d_year in (1999, 2000, 2001)
            and s_city in ('Fairview', 'Midway')
          group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
                   ca_city) dn,
         customer, customer_address current_addr
    where ss_customer_sk = c_customer_sk
      and c_current_addr_sk = current_addr.ca_address_sk
      and current_addr.ca_city <> bought_city
    order by c_last_name, ss_ticket_number
    limit 100"""

QUERIES["q69"] = """
    select cd_gender, cd_marital_status, cd_education_status,
           count(*) cnt1
    from customer c, customer_address ca, customer_demographics
    where c.c_current_addr_sk = ca.ca_address_sk
      and ca_state in ('KY', 'GA', 'NM')
      and cd_demo_sk = c.c_current_cdemo_sk
      and exists (select * from store_sales, date_dim
                  where c.c_customer_sk = ss_customer_sk
                    and ss_sold_date_sk = d_date_sk
                    and d_year = 2001 and d_moy between 4 and 6)
      and not exists (select * from web_sales, date_dim
                      where c.c_customer_sk = ws_bill_customer_sk
                        and ws_sold_date_sk = d_date_sk
                        and d_year = 2001 and d_moy between 4 and 6)
    group by cd_gender, cd_marital_status, cd_education_status
    order by cd_gender, cd_marital_status, cd_education_status
    limit 100"""

QUERIES["q71"] = """
    select i_brand_id brand_id, i_brand brand, t_hour, t_minute,
           sum(ext_price) ext_price
    from item,
         (select ws_ext_sales_price as ext_price,
                 ws_sold_date_sk as sold_date_sk,
                 ws_item_sk as sold_item_sk,
                 ws_sold_time_sk as time_sk
          from web_sales, date_dim
          where d_date_sk = ws_sold_date_sk
            and d_moy = 11 and d_year = 1999
          union all
          select ss_ext_sales_price as ext_price,
                 ss_sold_date_sk as sold_date_sk,
                 ss_item_sk as sold_item_sk,
                 ss_sold_time_sk as time_sk
          from store_sales, date_dim
          where d_date_sk = ss_sold_date_sk
            and d_moy = 11 and d_year = 1999) tmp, time_dim
    where sold_item_sk = i_item_sk and i_manager_id = 1
      and time_sk = t_time_sk
      and (t_hour = 8 or t_hour = 9)
    group by i_brand_id, i_brand, t_hour, t_minute
    order by ext_price desc, brand_id
    limit 100"""

QUERIES["q73"] = """
    select c_last_name, c_first_name, c_salutation,
           c_preferred_cust_flag, ss_ticket_number, cnt
    from (select ss_ticket_number, ss_customer_sk, count(*) cnt
          from store_sales, date_dim, store, household_demographics
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_hdemo_sk = hd_demo_sk
            and d_dom between 1 and 2
            and (hd_buy_potential = '>10000'
                 or hd_buy_potential = 'Unknown')
            and hd_vehicle_count > 0
            and d_year in (1999, 2000, 2001)
            and s_county in ('Williamson County', 'Ziebach County')
          group by ss_ticket_number, ss_customer_sk) dj, customer
    where ss_customer_sk = c_customer_sk and cnt between 1 and 5
    order by cnt desc, c_last_name asc, c_first_name, ss_ticket_number
    limit 100"""

QUERIES["q76"] = """
    select channel, col_name, d_year, d_qoy, i_category,
           count(*) sales_cnt, sum(ext_sales_price) sales_amt
    from (
      select 'store' as channel, 'ss_store_sk' col_name, d_year, d_qoy,
             i_category, ss_ext_sales_price ext_sales_price
      from store_sales, item, date_dim
      where ss_store_sk is null and ss_sold_date_sk = d_date_sk
        and ss_item_sk = i_item_sk
      union all
      select 'web' as channel, 'ws_ship_customer_sk' col_name, d_year,
             d_qoy, i_category, ws_ext_sales_price ext_sales_price
      from web_sales, item, date_dim
      where ws_ship_customer_sk is null
        and ws_sold_date_sk = d_date_sk and ws_item_sk = i_item_sk
      union all
      select 'catalog' as channel, 'cs_ship_mode_sk' col_name, d_year,
             d_qoy, i_category, cs_ext_sales_price ext_sales_price
      from catalog_sales, item, date_dim
      where cs_ship_mode_sk is null
        and cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk) foo
    group by channel, col_name, d_year, d_qoy, i_category
    order by channel, col_name, d_year, d_qoy, i_category
    limit 100"""

QUERIES["q79"] = """
    select c_last_name, c_first_name,
           substring(s_city, 1, 30) city, ss_ticket_number, amt, profit
    from (select ss_ticket_number, ss_customer_sk, s_city,
                 sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
          from store_sales, date_dim, store, household_demographics
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_hdemo_sk = hd_demo_sk
            and (hd_dep_count = 6 or hd_vehicle_count > 2)
            and d_dow = 1
            and d_year in (1999, 2000, 2001)
            and s_number_employees between 200 and 295
          group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
                   s_city) ms, customer
    where ss_customer_sk = c_customer_sk
    order by c_last_name, c_first_name, city, profit, ss_ticket_number
    limit 100"""

QUERIES["q82"] = """
    select i_item_id, i_item_desc, i_current_price
    from item, inventory, date_dim, store_sales
    where i_current_price between 62 and 62 + 30
      and inv_item_sk = i_item_sk
      and d_date_sk = inv_date_sk
      and d_year = 2000
      and i_manufact_id in (129, 270, 821, 423)
      and inv_quantity_on_hand between 100 and 500
      and ss_item_sk = i_item_sk
    group by i_item_id, i_item_desc, i_current_price
    order by i_item_id
    limit 100"""

QUERIES["q84"] = """
    select c_customer_id as customer_id,
           c_last_name || ', ' || c_first_name as customername
    from customer, customer_address, customer_demographics,
         household_demographics, income_band, store_returns
    where ca_city = 'Fairview'
      and c_current_addr_sk = ca_address_sk
      and ib_lower_bound >= 30000
      and ib_upper_bound <= 30000 + 50000
      and ib_income_band_sk = hd_income_band_sk
      and cd_demo_sk = c_current_cdemo_sk
      and hd_demo_sk = c_current_hdemo_sk
      and sr_cdemo_sk = cd_demo_sk
    order by c_customer_id
    limit 100"""

QUERIES["q86"] = """
    select sum(ws_net_paid) as total_sum, i_category, i_class,
           grouping(i_category) + grouping(i_class) as lochierarchy
    from web_sales, date_dim d1, item
    where d1.d_month_seq between 1200 and 1200 + 11
      and d1.d_date_sk = ws_sold_date_sk
      and i_item_sk = ws_item_sk
    group by rollup(i_category, i_class)
    order by lochierarchy desc, i_category, i_class
    limit 100"""

QUERIES["q87"] = """
    select count(*) cnt from (
      (select distinct c_last_name, c_first_name, d_date
       from store_sales, date_dim, customer
       where store_sales.ss_sold_date_sk = date_dim.d_date_sk
         and store_sales.ss_customer_sk = customer.c_customer_sk
         and d_month_seq between 1200 and 1200 + 11)
      except
      (select distinct c_last_name, c_first_name, d_date
       from catalog_sales, date_dim, customer
       where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
         and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
         and d_month_seq between 1200 and 1200 + 11)
      except
      (select distinct c_last_name, c_first_name, d_date
       from web_sales, date_dim, customer
       where web_sales.ws_sold_date_sk = date_dim.d_date_sk
         and web_sales.ws_bill_customer_sk = customer.c_customer_sk
         and d_month_seq between 1200 and 1200 + 11)
    ) cool_cust"""

QUERIES["q88"] = """
    select *
    from (select count(*) h8_30_to_9
          from store_sales, household_demographics, time_dim, store
          where ss_sold_time_sk = t_time_sk
            and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
            and t_hour = 8 and t_minute >= 30
            and ((hd_dep_count = 4 and hd_vehicle_count <= 4 + 2)
              or (hd_dep_count = 2 and hd_vehicle_count <= 2 + 2)
              or (hd_dep_count = 0 and hd_vehicle_count <= 0 + 2))
            and s_store_name = 'ese') s1,
         (select count(*) h9_to_9_30
          from store_sales, household_demographics, time_dim, store
          where ss_sold_time_sk = t_time_sk
            and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
            and t_hour = 9 and t_minute < 30
            and ((hd_dep_count = 4 and hd_vehicle_count <= 4 + 2)
              or (hd_dep_count = 2 and hd_vehicle_count <= 2 + 2)
              or (hd_dep_count = 0 and hd_vehicle_count <= 0 + 2))
            and s_store_name = 'ese') s2,
         (select count(*) h9_30_to_10
          from store_sales, household_demographics, time_dim, store
          where ss_sold_time_sk = t_time_sk
            and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
            and t_hour = 9 and t_minute >= 30
            and ((hd_dep_count = 4 and hd_vehicle_count <= 4 + 2)
              or (hd_dep_count = 2 and hd_vehicle_count <= 2 + 2)
              or (hd_dep_count = 0 and hd_vehicle_count <= 0 + 2))
            and s_store_name = 'ese') s3,
         (select count(*) h10_to_10_30
          from store_sales, household_demographics, time_dim, store
          where ss_sold_time_sk = t_time_sk
            and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
            and t_hour = 10 and t_minute < 30
            and ((hd_dep_count = 4 and hd_vehicle_count <= 4 + 2)
              or (hd_dep_count = 2 and hd_vehicle_count <= 2 + 2)
              or (hd_dep_count = 0 and hd_vehicle_count <= 0 + 2))
            and s_store_name = 'ese') s4"""

QUERIES["q89"] = """
    select i_category, i_class, i_brand, s_store_name, s_company_id,
           d_moy, sum_sales, avg_monthly_sales
    from (select i_category, i_class, i_brand, s_store_name,
                 s_company_id, d_moy, sum(ss_sales_price) sum_sales,
                 avg(sum(ss_sales_price)) over (partition by
                   i_category, i_brand, s_store_name, s_company_id)
                   avg_monthly_sales
          from item, store_sales, date_dim, store
          where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk and d_year = 1999
            and ((i_category in ('Books', 'Music', 'Sports')
                  and i_class in ('classical', 'fishing', 'football'))
              or (i_category in ('Men', 'Women', 'Home')
                  and i_class in ('pants', 'shirts', 'dresses')))
          group by i_category, i_class, i_brand, s_store_name,
                   s_company_id, d_moy) tmp1
    where case when avg_monthly_sales <> 0
               then abs(sum_sales - avg_monthly_sales) /
                    avg_monthly_sales else null end > 0.1
    order by sum_sales - avg_monthly_sales, s_store_name,
             i_category, i_class, i_brand, d_moy
    limit 100"""

QUERIES["q90"] = """
    select cast(amc as double) / cast(pmc as double) am_pm_ratio
    from (select count(*) amc from web_sales, household_demographics,
                 time_dim, web_page
          where ws_sold_time_sk = t_time_sk
            and ws_web_page_sk = wp_web_page_sk
            and ws_ship_customer_sk is not null
            and t_hour between 8 and 9
            and household_demographics.hd_demo_sk =
                web_sales.ws_web_page_sk % 7200
            and hd_dep_count = 6
            and wp_char_count between 5000 and 5200) at1,
         (select count(*) pmc from web_sales, household_demographics,
                 time_dim, web_page
          where ws_sold_time_sk = t_time_sk
            and ws_web_page_sk = wp_web_page_sk
            and ws_ship_customer_sk is not null
            and t_hour between 19 and 20
            and household_demographics.hd_demo_sk =
                web_sales.ws_web_page_sk % 7200
            and hd_dep_count = 6
            and wp_char_count between 5000 and 5200) pt
    order by am_pm_ratio
    limit 100"""

QUERIES["q91"] = """
    select cc_call_center_sk, cc_name, cc_manager,
           sum(cr_net_loss) returns_loss
    from call_center, catalog_returns, date_dim, customer,
         customer_address, customer_demographics,
         household_demographics
    where cr_call_center_sk = cc_call_center_sk
      and cr_returned_date_sk = d_date_sk
      and cr_returning_customer_sk = c_customer_sk
      and cd_demo_sk = c_current_cdemo_sk
      and hd_demo_sk = c_current_hdemo_sk
      and ca_address_sk = c_current_addr_sk
      and d_year = 1998 and d_moy = 11
      and ((cd_marital_status = 'M'
            and cd_education_status = 'Unknown')
        or (cd_marital_status = 'W'
            and cd_education_status = 'Advanced Degree'))
      and hd_buy_potential like '>10000%'
      and ca_gmt_offset = -7
    group by cc_call_center_sk, cc_name, cc_manager
    order by returns_loss desc
    limit 100"""

QUERIES["q93"] = """
    select ss_customer_sk, sum(act_sales) sumsales
    from (select ss_item_sk, ss_ticket_number, ss_customer_sk,
                 case when sr_return_quantity is not null
                      then (ss_quantity - sr_return_quantity) *
                           ss_sales_price
                      else ss_quantity * ss_sales_price end act_sales
          from store_sales
            left outer join store_returns
              on (sr_item_sk = ss_item_sk
                  and sr_ticket_number = ss_ticket_number),
            reason
          where sr_reason_sk = r_reason_sk
            and r_reason_desc = 'reason 28') t
    group by ss_customer_sk
    order by sumsales, ss_customer_sk
    limit 100"""

QUERIES["q96"] = """
    select count(*) cnt
    from store_sales, household_demographics, time_dim, store
    where ss_sold_time_sk = t_time_sk
      and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
      and t_hour = 20 and t_minute >= 30 and hd_dep_count = 7
      and s_store_name = 'ese'
    order by cnt
    limit 100"""

QUERIES["q97"] = """
    with ssci as (
      select ss_customer_sk customer_sk, ss_item_sk item_sk
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk
        and d_month_seq between 1200 and 1200 + 11
      group by ss_customer_sk, ss_item_sk),
    csci as (
      select cs_bill_customer_sk customer_sk, cs_item_sk item_sk
      from catalog_sales, date_dim
      where cs_sold_date_sk = d_date_sk
        and d_month_seq between 1200 and 1200 + 11
      group by cs_bill_customer_sk, cs_item_sk)
    select sum(case when ssci.customer_sk is not null
                     and csci.customer_sk is null
               then 1 else 0 end) store_only,
           sum(case when ssci.customer_sk is null
                     and csci.customer_sk is not null
               then 1 else 0 end) catalog_only,
           sum(case when ssci.customer_sk is not null
                     and csci.customer_sk is not null
               then 1 else 0 end) store_and_catalog
    from ssci full outer join csci
      on (ssci.customer_sk = csci.customer_sk
          and ssci.item_sk = csci.item_sk)
    limit 100"""

QUERIES["q98"] = """
    select i_item_id, i_item_desc, i_category, i_class,
           i_current_price,
           sum(ss_ext_sales_price) as itemrevenue,
           sum(ss_ext_sales_price) * 100.0 /
             sum(sum(ss_ext_sales_price))
               over (partition by i_class) as revenueratio
    from store_sales, item, date_dim
    where ss_item_sk = i_item_sk
      and i_category in ('Sports', 'Books', 'Home')
      and ss_sold_date_sk = d_date_sk
      and d_year = 1999 and d_moy between 2 and 3
    group by i_item_id, i_item_desc, i_category, i_class,
             i_current_price
    order by i_category, i_class, i_item_id, i_item_desc,
             revenueratio
    limit 100"""

QUERIES["q99"] = """
    select w_warehouse_name, sm_type, cc_name,
           sum(case when (cs_ship_date_sk - cs_sold_date_sk <= 30)
               then 1 else 0 end) as d30,
           sum(case when (cs_ship_date_sk - cs_sold_date_sk > 30)
                     and (cs_ship_date_sk - cs_sold_date_sk <= 60)
               then 1 else 0 end) as d31_60,
           sum(case when (cs_ship_date_sk - cs_sold_date_sk > 60)
                     and (cs_ship_date_sk - cs_sold_date_sk <= 90)
               then 1 else 0 end) as d61_90,
           sum(case when (cs_ship_date_sk - cs_sold_date_sk > 90)
               then 1 else 0 end) as d90_plus
    from catalog_sales, warehouse, ship_mode, call_center, date_dim
    where d_month_seq between 1200 and 1200 + 11
      and cs_ship_date_sk = d_date_sk
      and cs_warehouse_sk = w_warehouse_sk
      and cs_ship_mode_sk = sm_ship_mode_sk
      and cs_call_center_sk = cc_call_center_sk
    group by w_warehouse_name, sm_type, cc_name
    order by w_warehouse_name, sm_type, cc_name
    limit 100"""

# --------------------------------------------------------------------------
# correlated scalar aggregate subqueries (decorrelated to group-by+join)
# --------------------------------------------------------------------------

QUERIES["q1"] = """
    with customer_total_return as (
      select sr_customer_sk as ctr_customer_sk,
             sr_store_sk as ctr_store_sk,
             sum(sr_return_amt) as ctr_total_return
      from store_returns, date_dim
      where sr_returned_date_sk = d_date_sk and d_year = 2000
      group by sr_customer_sk, sr_store_sk)
    select c_customer_id
    from customer_total_return ctr1, store, customer
    where ctr1.ctr_total_return >
        (select avg(ctr_total_return) * 1.2
         from customer_total_return ctr2
         where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
      and s_store_sk = ctr1.ctr_store_sk
      and s_state = 'TN'
      and ctr1.ctr_customer_sk = c_customer_sk
    order by c_customer_id
    limit 100"""

QUERIES["q6"] = """
    select a.ca_state state, count(*) cnt
    from customer_address a, customer c, store_sales s, date_dim d,
         item i
    where a.ca_address_sk = c.c_current_addr_sk
      and c.c_customer_sk = s.ss_customer_sk
      and s.ss_sold_date_sk = d.d_date_sk
      and s.ss_item_sk = i.i_item_sk
      and d.d_month_seq =
        (select distinct d_month_seq from date_dim
         where d_year = 2001 and d_moy = 1)
      and i.i_current_price >
        (select avg(j.i_current_price) * 1.2 from item j
         where j.i_category = i.i_category)
    group by a.ca_state
    having count(*) >= 10
    order by cnt, a.ca_state
    limit 100"""

QUERIES["q32"] = """
    select sum(cs_ext_discount_amt) as excess_discount_amount
    from catalog_sales, item, date_dim
    where i_manufact_id = 977
      and i_item_sk = cs_item_sk
      and d_date_sk = cs_sold_date_sk
      and d_year = 2000 and d_moy between 1 and 4
      and cs_ext_discount_amt >
        (select 1.3 * avg(cs_ext_discount_amt)
         from catalog_sales, date_dim
         where cs_item_sk = i_item_sk
           and d_year = 2000 and d_moy between 1 and 4
           and d_date_sk = cs_sold_date_sk)
    limit 100"""

QUERIES["q81"] = """
    with customer_total_return as (
      select cr_returning_customer_sk as ctr_customer_sk,
             ca_state as ctr_state,
             sum(cr_return_amt_inc_tax) as ctr_total_return
      from catalog_returns, date_dim, customer_address
      where cr_returned_date_sk = d_date_sk and d_year = 2000
        and cr_returning_addr_sk = ca_address_sk
      group by cr_returning_customer_sk, ca_state)
    select c_customer_id, c_salutation, c_first_name, c_last_name,
           ctr_total_return
    from customer_total_return ctr1, customer_address, customer
    where ctr1.ctr_total_return >
        (select avg(ctr_total_return) * 1.2
         from customer_total_return ctr2
         where ctr1.ctr_state = ctr2.ctr_state)
      and ca_address_sk = c_current_addr_sk
      and ca_state = 'GA'
      and ctr1.ctr_customer_sk = c_customer_sk
    order by c_customer_id, c_salutation, c_first_name, c_last_name,
             ctr_total_return
    limit 100"""

QUERIES["q92"] = """
    select sum(ws_ext_discount_amt) as excess_discount_amount
    from web_sales, item, date_dim
    where i_manufact_id = 350
      and i_item_sk = ws_item_sk
      and d_date_sk = ws_sold_date_sk
      and d_year = 2000 and d_moy between 1 and 4
      and ws_ext_discount_amt >
        (select 1.3 * avg(ws_ext_discount_amt)
         from web_sales, date_dim
         where ws_item_sk = i_item_sk
           and d_year = 2000 and d_moy between 1 and 4
           and d_date_sk = ws_sold_date_sk)
    limit 100"""

# --------------------------------------------------------------------------
# round-3 extension batch 2
# --------------------------------------------------------------------------

QUERIES["q30"] = """
    with customer_total_return as (
      select wr_returning_customer_sk as ctr_customer_sk,
             ca_state as ctr_state,
             sum(wr_return_amt) as ctr_total_return
      from web_returns, date_dim, customer_address
      where wr_returned_date_sk = d_date_sk and d_year = 2002
        and wr_returning_addr_sk = ca_address_sk
      group by wr_returning_customer_sk, ca_state)
    select c_customer_id, c_salutation, c_first_name, c_last_name,
           c_preferred_cust_flag, c_birth_year, ctr_total_return
    from customer_total_return ctr1, customer_address, customer
    where ctr1.ctr_total_return >
        (select avg(ctr_total_return) * 1.2
         from customer_total_return ctr2
         where ctr1.ctr_state = ctr2.ctr_state)
      and ca_address_sk = c_current_addr_sk
      and ca_state = 'GA'
      and ctr1.ctr_customer_sk = c_customer_sk
    order by c_customer_id, c_salutation, c_first_name, c_last_name,
             c_preferred_cust_flag, c_birth_year, ctr_total_return
    limit 100"""

QUERIES["q31"] = """
    with ss as (
      select ca_county, d_qoy, d_year,
             sum(ss_ext_sales_price) as store_sales
      from store_sales, date_dim, customer_address
      where ss_sold_date_sk = d_date_sk
        and ss_addr_sk = ca_address_sk
      group by ca_county, d_qoy, d_year),
    ws as (
      select ca_county, d_qoy, d_year,
             sum(ws_ext_sales_price) as web_sales
      from web_sales, date_dim, customer_address
      where ws_sold_date_sk = d_date_sk
        and ws_bill_addr_sk = ca_address_sk
      group by ca_county, d_qoy, d_year)
    select ss1.ca_county, ss1.d_year,
           ws2.web_sales / ws1.web_sales web_q1_q2_increase,
           ss2.store_sales / ss1.store_sales store_q1_q2_increase
    from ss ss1, ss ss2, ws ws1, ws ws2
    where ss1.d_qoy = 1 and ss1.d_year = 2000
      and ss1.ca_county = ss2.ca_county
      and ss2.d_qoy = 2 and ss2.d_year = 2000
      and ss2.ca_county = ws1.ca_county
      and ws1.d_qoy = 1 and ws1.d_year = 2000
      and ws1.ca_county = ws2.ca_county
      and ws2.d_qoy = 2 and ws2.d_year = 2000
      and case when ws1.web_sales > 0
               then ws2.web_sales / ws1.web_sales else null end >
          case when ss1.store_sales > 0
               then ss2.store_sales / ss1.store_sales else null end
    order by ss1.ca_county
    limit 100"""

QUERIES["q35"] = """
    select ca_state, cd_gender, cd_marital_status,
           count(*) cnt1, avg(cd_dep_count) a1,
           max(cd_dep_count) m1, sum(cd_dep_count) s1
    from customer c, customer_address ca, customer_demographics
    where c.c_current_addr_sk = ca.ca_address_sk
      and cd_demo_sk = c.c_current_cdemo_sk
      and exists (select * from store_sales, date_dim
                  where c.c_customer_sk = ss_customer_sk
                    and ss_sold_date_sk = d_date_sk
                    and d_year = 2002 and d_qoy < 4)
      and exists (select * from web_sales, date_dim
                  where c.c_customer_sk = ws_bill_customer_sk
                    and ws_sold_date_sk = d_date_sk
                    and d_year = 2002 and d_qoy < 4)
    group by ca_state, cd_gender, cd_marital_status
    order by ca_state, cd_gender, cd_marital_status
    limit 100"""

QUERIES["q47"] = """
    with v1 as (
      select i_category, i_brand, s_store_name, s_company_id,
             d_year, d_moy, sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price)) over (partition by
               i_category, i_brand, s_store_name, s_company_id, d_year)
               avg_monthly_sales,
             rank() over (partition by
               i_category, i_brand, s_store_name, s_company_id
               order by d_year, d_moy) rn
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_year = 1999
      group by i_category, i_brand, s_store_name, s_company_id,
               d_year, d_moy)
    select v1.i_category, v1.i_brand, v1.s_store_name, v1.d_year,
           v1.d_moy, v1.avg_monthly_sales, v1.sum_sales
    from v1
    where v1.d_year = 1999
      and v1.avg_monthly_sales > 0
      and abs(v1.sum_sales - v1.avg_monthly_sales) /
          v1.avg_monthly_sales > 0.1
    order by v1.sum_sales - v1.avg_monthly_sales, v1.i_category,
             v1.i_brand, v1.s_store_name, v1.d_moy
    limit 100"""

QUERIES["q57"] = """
    with v1 as (
      select i_category, i_brand, cc_name, d_year, d_moy,
             sum(cs_sales_price) sum_sales,
             avg(sum(cs_sales_price)) over (partition by
               i_category, i_brand, cc_name, d_year)
               avg_monthly_sales,
             rank() over (partition by i_category, i_brand, cc_name
               order by d_year, d_moy) rn
      from item, catalog_sales, date_dim, call_center
      where cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
        and cc_call_center_sk = cs_call_center_sk
        and d_year = 1999
      group by i_category, i_brand, cc_name, d_year, d_moy)
    select v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
           v1.avg_monthly_sales, v1.sum_sales
    from v1
    where v1.d_year = 1999
      and v1.avg_monthly_sales > 0
      and abs(v1.sum_sales - v1.avg_monthly_sales) /
          v1.avg_monthly_sales > 0.1
    order by v1.sum_sales - v1.avg_monthly_sales, v1.i_category,
             v1.i_brand, v1.cc_name, v1.d_moy
    limit 100"""

QUERIES["q58"] = """
    with ss_items as (
      select i_item_id item_id, sum(ss_ext_sales_price) ss_item_rev
      from store_sales, item, date_dim
      where ss_item_sk = i_item_sk
        and d_week_seq = (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 1
                            and d_dom = 3)
        and ss_sold_date_sk = d_date_sk
      group by i_item_id),
    cs_items as (
      select i_item_id item_id, sum(cs_ext_sales_price) cs_item_rev
      from catalog_sales, item, date_dim
      where cs_item_sk = i_item_sk
        and d_week_seq = (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 1
                            and d_dom = 3)
        and cs_sold_date_sk = d_date_sk
      group by i_item_id),
    ws_items as (
      select i_item_id item_id, sum(ws_ext_sales_price) ws_item_rev
      from web_sales, item, date_dim
      where ws_item_sk = i_item_sk
        and d_week_seq = (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 1
                            and d_dom = 3)
        and ws_sold_date_sk = d_date_sk
      group by i_item_id)
    select ss_items.item_id,
           ss_item_rev,
           ss_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
             * 100 ss_dev,
           cs_item_rev,
           cs_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
             * 100 cs_dev,
           ws_item_rev,
           ws_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
             * 100 ws_dev,
           (ss_item_rev + cs_item_rev + ws_item_rev) / 3 average
    from ss_items, cs_items, ws_items
    where ss_items.item_id = cs_items.item_id
      and ss_items.item_id = ws_items.item_id
      and ss_item_rev between 0.9 * cs_item_rev and 1.1 * cs_item_rev
      and ss_item_rev between 0.9 * ws_item_rev and 1.1 * ws_item_rev
      and cs_item_rev between 0.9 * ss_item_rev and 1.1 * ss_item_rev
      and cs_item_rev between 0.9 * ws_item_rev and 1.1 * ws_item_rev
      and ws_item_rev between 0.9 * ss_item_rev and 1.1 * ss_item_rev
      and ws_item_rev between 0.9 * cs_item_rev and 1.1 * cs_item_rev
    order by item_id, ss_item_rev
    limit 100"""

QUERIES["q59"] = """
    with wss as (
      select d_week_seq, ss_store_sk,
             sum(case when d_day_name = 'Sunday' then ss_sales_price
                      else null end) sun_sales,
             sum(case when d_day_name = 'Monday' then ss_sales_price
                      else null end) mon_sales,
             sum(case when d_day_name = 'Tuesday' then ss_sales_price
                      else null end) tue_sales,
             sum(case when d_day_name = 'Wednesday' then ss_sales_price
                      else null end) wed_sales,
             sum(case when d_day_name = 'Thursday' then ss_sales_price
                      else null end) thu_sales,
             sum(case when d_day_name = 'Friday' then ss_sales_price
                      else null end) fri_sales,
             sum(case when d_day_name = 'Saturday' then ss_sales_price
                      else null end) sat_sales
      from store_sales, date_dim
      where d_date_sk = ss_sold_date_sk
      group by d_week_seq, ss_store_sk)
    select s_store_name1, s_store_id1, d_week_seq1,
           sun_sales1 / sun_sales2 r1, mon_sales1 / mon_sales2 r2,
           tue_sales1 / tue_sales2 r3, wed_sales1 / wed_sales2 r4,
           thu_sales1 / thu_sales2 r5, fri_sales1 / fri_sales2 r6,
           sat_sales1 / sat_sales2 r7
    from (select s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
                 s_store_id s_store_id1, sun_sales sun_sales1,
                 mon_sales mon_sales1, tue_sales tue_sales1,
                 wed_sales wed_sales1, thu_sales thu_sales1,
                 fri_sales fri_sales1, sat_sales sat_sales1
          from wss, store, date_dim d
          where d.d_week_seq = wss.d_week_seq
            and ss_store_sk = s_store_sk
            and d_month_seq between 1200 and 1200 + 11) y,
         (select s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
                 s_store_id s_store_id2, sun_sales sun_sales2,
                 mon_sales mon_sales2, tue_sales tue_sales2,
                 wed_sales wed_sales2, thu_sales thu_sales2,
                 fri_sales fri_sales2, sat_sales sat_sales2
          from wss, store, date_dim d
          where d.d_week_seq = wss.d_week_seq
            and ss_store_sk = s_store_sk
            and d_month_seq between 1212 and 1212 + 11) x
    where s_store_id1 = s_store_id2
      and d_week_seq1 = d_week_seq2 - 52
    order by s_store_name1, s_store_id1, d_week_seq1
    limit 100"""

QUERIES["q72"] = """
    select i_item_desc, w_warehouse_name, d1.d_week_seq,
           sum(case when p_promo_sk is null then 1 else 0 end) no_promo,
           sum(case when p_promo_sk is not null then 1 else 0 end) promo,
           count(*) total_cnt
    from catalog_sales
      join inventory on (cs_item_sk = inv_item_sk)
      join warehouse on (w_warehouse_sk = inv_warehouse_sk)
      join item on (i_item_sk = cs_item_sk)
      join customer_demographics on (cs_bill_cdemo_sk = cd_demo_sk)
      join household_demographics on (cs_bill_hdemo_sk = hd_demo_sk)
      join date_dim d1 on (cs_sold_date_sk = d1.d_date_sk)
      join date_dim d2 on (inv_date_sk = d2.d_date_sk)
      join date_dim d3 on (cs_ship_date_sk = d3.d_date_sk)
      left outer join promotion on (cs_promo_sk = p_promo_sk)
    where d1.d_week_seq = d2.d_week_seq
      and inv_quantity_on_hand < cs_quantity
      and d3.d_date_sk > d1.d_date_sk + 3
      and hd_buy_potential = '>10000'
      and d1.d_year = 1999
      and cd_marital_status = 'D'
    group by i_item_desc, w_warehouse_name, d1.d_week_seq
    order by total_cnt desc, i_item_desc, w_warehouse_name,
             d1.d_week_seq
    limit 100"""

QUERIES["q74"] = """
    with year_total as (
      select c_customer_id customer_id, c_first_name customer_first_name,
             c_last_name customer_last_name, d_year as year_,
             sum(ss_net_paid) year_total, 's' sale_type
      from customer, store_sales, date_dim
      where c_customer_sk = ss_customer_sk
        and ss_sold_date_sk = d_date_sk
        and d_year in (1999, 2000)
      group by c_customer_id, c_first_name, c_last_name, d_year
      union all
      select c_customer_id customer_id, c_first_name customer_first_name,
             c_last_name customer_last_name, d_year as year_,
             sum(ws_net_paid) year_total, 'w' sale_type
      from customer, web_sales, date_dim
      where c_customer_sk = ws_bill_customer_sk
        and ws_sold_date_sk = d_date_sk
        and d_year in (1999, 2000)
      group by c_customer_id, c_first_name, c_last_name, d_year)
    select t_s_secyear.customer_id, t_s_secyear.customer_first_name,
           t_s_secyear.customer_last_name
    from year_total t_s_firstyear, year_total t_s_secyear,
         year_total t_w_firstyear, year_total t_w_secyear
    where t_s_secyear.customer_id = t_s_firstyear.customer_id
      and t_s_firstyear.customer_id = t_w_secyear.customer_id
      and t_s_firstyear.customer_id = t_w_firstyear.customer_id
      and t_s_firstyear.sale_type = 's'
      and t_w_firstyear.sale_type = 'w'
      and t_s_secyear.sale_type = 's'
      and t_w_secyear.sale_type = 'w'
      and t_s_firstyear.year_ = 1999
      and t_s_secyear.year_ = 2000
      and t_w_firstyear.year_ = 1999
      and t_w_secyear.year_ = 2000
      and t_s_firstyear.year_total > 0
      and t_w_firstyear.year_total > 0
      and case when t_w_firstyear.year_total > 0
               then t_w_secyear.year_total / t_w_firstyear.year_total
               else null end >
          case when t_s_firstyear.year_total > 0
               then t_s_secyear.year_total / t_s_firstyear.year_total
               else null end
    order by 1, 2, 3
    limit 100"""

QUERIES["q75"] = """
    with all_sales as (
      select d_year, i_brand_id, i_class_id, i_category_id,
             i_manufact_id, sum(sales_cnt) sales_cnt,
             sum(sales_amt) sales_amt
      from (
        select d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               cs_quantity - coalesce(cr_return_quantity, 0) sales_cnt,
               cs_ext_sales_price -
                 coalesce(cr_return_amount, 0.0) sales_amt
        from catalog_sales
          join item on i_item_sk = cs_item_sk
          join date_dim on d_date_sk = cs_sold_date_sk
          left join catalog_returns
            on (cs_order_number = cr_order_number
                and cs_item_sk = cr_item_sk)
        where i_category = 'Books'
        union all
        select d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ss_quantity - coalesce(sr_return_quantity, 0) sales_cnt,
               ss_ext_sales_price -
                 coalesce(sr_return_amt, 0.0) sales_amt
        from store_sales
          join item on i_item_sk = ss_item_sk
          join date_dim on d_date_sk = ss_sold_date_sk
          left join store_returns
            on (ss_ticket_number = sr_ticket_number
                and ss_item_sk = sr_item_sk)
        where i_category = 'Books'
        union all
        select d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ws_quantity - coalesce(wr_return_quantity, 0) sales_cnt,
               ws_ext_sales_price -
                 coalesce(wr_return_amt, 0.0) sales_amt
        from web_sales
          join item on i_item_sk = ws_item_sk
          join date_dim on d_date_sk = ws_sold_date_sk
          left join web_returns
            on (ws_order_number = wr_order_number
                and ws_item_sk = wr_item_sk)
        where i_category = 'Books') sales_detail
      group by d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id)
    select prev_yr.d_year prev_year, curr_yr.d_year year_,
           curr_yr.i_brand_id, curr_yr.i_class_id,
           curr_yr.i_category_id, curr_yr.i_manufact_id,
           prev_yr.sales_cnt prev_yr_cnt, curr_yr.sales_cnt curr_yr_cnt,
           curr_yr.sales_cnt - prev_yr.sales_cnt sales_cnt_diff,
           curr_yr.sales_amt - prev_yr.sales_amt sales_amt_diff
    from all_sales curr_yr, all_sales prev_yr
    where curr_yr.i_brand_id = prev_yr.i_brand_id
      and curr_yr.i_class_id = prev_yr.i_class_id
      and curr_yr.i_category_id = prev_yr.i_category_id
      and curr_yr.i_manufact_id = prev_yr.i_manufact_id
      and curr_yr.d_year = 2002 and prev_yr.d_year = 2001
      and cast(curr_yr.sales_cnt as double) /
          cast(prev_yr.sales_cnt as double) < 0.9
    order by sales_cnt_diff, sales_amt_diff
    limit 100"""

QUERIES["q78"] = """
    with ws as (
      select d_year as ws_sold_year, ws_item_sk,
             ws_bill_customer_sk ws_customer_sk,
             sum(ws_quantity) ws_qty, sum(ws_wholesale_cost) ws_wc,
             sum(ws_sales_price) ws_sp
      from web_sales
        left join web_returns on (wr_order_number = ws_order_number
                                  and ws_item_sk = wr_item_sk)
        join date_dim on ws_sold_date_sk = d_date_sk
      where wr_order_number is null
      group by d_year, ws_item_sk, ws_bill_customer_sk),
    cs as (
      select d_year as cs_sold_year, cs_item_sk,
             cs_bill_customer_sk cs_customer_sk,
             sum(cs_quantity) cs_qty, sum(cs_wholesale_cost) cs_wc,
             sum(cs_sales_price) cs_sp
      from catalog_sales
        left join catalog_returns on (cr_order_number = cs_order_number
                                      and cs_item_sk = cr_item_sk)
        join date_dim on cs_sold_date_sk = d_date_sk
      where cr_order_number is null
      group by d_year, cs_item_sk, cs_bill_customer_sk),
    ss as (
      select d_year as ss_sold_year, ss_item_sk,
             ss_customer_sk,
             sum(ss_quantity) ss_qty, sum(ss_wholesale_cost) ss_wc,
             sum(ss_sales_price) ss_sp
      from store_sales
        left join store_returns on (sr_ticket_number = ss_ticket_number
                                    and ss_item_sk = sr_item_sk)
        join date_dim on ss_sold_date_sk = d_date_sk
      where sr_ticket_number is null
      group by d_year, ss_item_sk, ss_customer_sk)
    select ss_item_sk, round(ss_qty / (coalesce(ws_qty, 0) +
           coalesce(cs_qty, 0)), 2) ratio,
           ss_qty store_qty, ss_wc store_wholesale_cost,
           ss_sp store_sales_price
    from ss
      left join ws on (ws_sold_year = ss_sold_year
                       and ws_item_sk = ss_item_sk
                       and ws_customer_sk = ss_customer_sk)
      left join cs on (cs_sold_year = ss_sold_year
                       and cs_item_sk = ss_item_sk
                       and cs_customer_sk = ss_customer_sk)
    where (coalesce(ws_qty, 0) > 0 or coalesce(cs_qty, 0) > 0)
      and ss_sold_year = 2000
    order by ss_item_sk, ss_qty desc, ss_wc desc, ss_sp desc
    limit 100"""

QUERIES["q83"] = """
    with sr_items as (
      select i_item_id item_id, sum(sr_return_quantity) sr_item_qty
      from store_returns, item, date_dim
      where sr_item_sk = i_item_sk
        and d_date in (select d_date from date_dim
                       where d_week_seq in
                         (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 6
                            and d_dom = 30))
        and sr_returned_date_sk = d_date_sk
      group by i_item_id),
    cr_items as (
      select i_item_id item_id, sum(cr_return_quantity) cr_item_qty
      from catalog_returns, item, date_dim
      where cr_item_sk = i_item_sk
        and d_date in (select d_date from date_dim
                       where d_week_seq in
                         (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 6
                            and d_dom = 30))
        and cr_returned_date_sk = d_date_sk
      group by i_item_id),
    wr_items as (
      select i_item_id item_id, sum(wr_return_quantity) wr_item_qty
      from web_returns, item, date_dim
      where wr_item_sk = i_item_sk
        and d_date in (select d_date from date_dim
                       where d_week_seq in
                         (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 6
                            and d_dom = 30))
        and wr_returned_date_sk = d_date_sk
      group by i_item_id)
    select sr_items.item_id, sr_item_qty,
           sr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
             * 100 sr_dev,
           cr_item_qty,
           cr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
             * 100 cr_dev,
           wr_item_qty,
           wr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
             * 100 wr_dev,
           (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 average
    from sr_items, cr_items, wr_items
    where sr_items.item_id = cr_items.item_id
      and sr_items.item_id = wr_items.item_id
    order by sr_items.item_id, sr_item_qty
    limit 100"""

QUERIES["q85"] = """
    select substring(r_reason_desc, 1, 20) reason,
           avg(ws_quantity) aq, avg(wr_refunded_cash) arc,
           avg(wr_fee) af
    from web_sales, web_returns, web_page, customer_demographics cd1,
         customer_demographics cd2, customer_address, date_dim, reason
    where ws_web_page_sk = wp_web_page_sk
      and ws_item_sk = wr_item_sk
      and ws_order_number = wr_order_number
      and ws_sold_date_sk = d_date_sk and d_year = 2000
      and cd1.cd_demo_sk = wr_refunded_cdemo_sk
      and cd2.cd_demo_sk = wr_returning_cdemo_sk
      and ca_address_sk = wr_refunded_addr_sk
      and r_reason_sk = wr_reason_sk
      and ((cd1.cd_marital_status = 'M'
            and cd1.cd_marital_status = cd2.cd_marital_status
            and cd1.cd_education_status = 'Advanced Degree'
            and cd1.cd_education_status = cd2.cd_education_status
            and ws_sales_price between 100.00 and 150.00)
        or (cd1.cd_marital_status = 'S'
            and cd1.cd_marital_status = cd2.cd_marital_status
            and cd1.cd_education_status = 'College'
            and cd1.cd_education_status = cd2.cd_education_status
            and ws_sales_price between 50.00 and 100.00)
        or (cd1.cd_marital_status = 'W'
            and cd1.cd_marital_status = cd2.cd_marital_status
            and cd1.cd_education_status = '2 yr Degree'
            and cd1.cd_education_status = cd2.cd_education_status
            and ws_sales_price between 150.00 and 200.00))
      and ((ca_country = 'United States'
            and ca_state in ('IN', 'OH', 'NJ')
            and ws_net_profit between 100 and 200)
        or (ca_country = 'United States'
            and ca_state in ('WI', 'CT', 'KY')
            and ws_net_profit between 150 and 300)
        or (ca_country = 'United States'
            and ca_state in ('LA', 'IA', 'AR')
            and ws_net_profit between 50 and 250))
    group by r_reason_desc
    order by reason, aq, arc, af
    limit 100"""

QUERIES["q95"] = """
    with ws_wh as (
      select ws1.ws_order_number won, ws1.ws_warehouse_sk wh1,
             ws2.ws_warehouse_sk wh2
      from web_sales ws1, web_sales ws2
      where ws1.ws_order_number = ws2.ws_order_number
        and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
    select count(distinct ws1.ws_order_number) as order_count,
           sum(ws1.ws_ext_ship_cost) as total_shipping_cost,
           sum(ws1.ws_net_profit) as total_net_profit
    from web_sales ws1, date_dim, customer_address, web_site
    where d_year = 1999 and d_moy between 2 and 3
      and ws1.ws_ship_date_sk = d_date_sk
      and ws1.ws_ship_addr_sk = ca_address_sk
      and ca_state = 'CA'
      and ws1.ws_web_site_sk = web_site_sk
      and web_name = 'site_0'
      and ws1.ws_order_number in (select won from ws_wh)
      and ws1.ws_order_number in (select wr_order_number
                                  from web_returns, ws_wh
                                  where wr_order_number = ws_wh.won)
    limit 100"""
