"""TPC-DS query corpus for the scaled star schema in tpcds.py.

Faithful renditions of the official query shapes (qualification
parameter choices) over the columns the generator produces; queries
including the correlated-SCALAR-subquery family (q1/q6/q32/q81/q92),
which the front end decorrelates to group-by + join.  Reference
surface:
integration_tests qa_nightly + the official tpcds queries directory.

Every query is verified TPU-vs-CPU by ``tpcds.py --verify`` (rows
compared with float tolerance); the pass/fail matrix is written to
``benchmarks/tpcds_matrix.json``.
"""

QUERIES = {}

# --------------------------------------------------------------------------
# star-join aggregates
# --------------------------------------------------------------------------

QUERIES["q3"] = """
    select d_year, i_brand_id brand_id, i_brand brand,
           sum(ss_ext_sales_price) sum_agg
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manufact_id = 128 and d_moy = 11
    group by d_year, i_brand_id, i_brand
    order by d_year, sum_agg desc, brand_id
    limit 100"""

QUERIES["q7"] = """
    select i_item_id,
           avg(ss_quantity) agg1, avg(ss_list_price) agg2,
           avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
    from store_sales, customer_demographics, date_dim, item, promotion
    where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
      and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
      and cd_gender = 'M' and cd_marital_status = 'S'
      and cd_education_status = 'College'
      and (p_channel_email = 'N' or p_channel_event = 'N')
      and d_year = 2000
    group by i_item_id
    order by i_item_id
    limit 100"""

QUERIES["q12"] = """
    select i_item_id, i_item_desc, i_category, i_class, i_current_price,
           sum(ws_ext_sales_price) as itemrevenue,
           sum(ws_ext_sales_price) * 100.0 /
             sum(sum(ws_ext_sales_price)) over (partition by i_class)
             as revenueratio
    from web_sales, item, date_dim
    where ws_item_sk = i_item_sk
      and i_category in ('Sports', 'Books', 'Home')
      and ws_sold_date_sk = d_date_sk
      and d_year = 1999 and d_moy between 2 and 3
    group by i_item_id, i_item_desc, i_category, i_class,
             i_current_price
    order by i_category, i_class, i_item_id, i_item_desc, revenueratio
    limit 100"""

QUERIES["q13"] = """
    select avg(ss_quantity) avg_q, avg(ss_ext_sales_price) avg_esp,
           avg(ss_ext_wholesale_cost) avg_ewc,
           sum(ss_ext_wholesale_cost) sum_ewc
    from store_sales, store, customer_demographics,
         household_demographics, customer_address, date_dim
    where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
      and d_year = 2001
      and ((ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
            and cd_marital_status = 'M'
            and cd_education_status = 'Advanced Degree'
            and ss_sales_price between 100.00 and 150.00
            and hd_dep_count = 3)
        or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
            and cd_marital_status = 'S'
            and cd_education_status = 'College'
            and ss_sales_price between 50.00 and 100.00
            and hd_dep_count = 1)
        or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
            and cd_marital_status = 'W'
            and cd_education_status = '2 yr Degree'
            and ss_sales_price between 150.00 and 200.00
            and hd_dep_count = 1))
      and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('TX', 'OH', 'TX')
            and ss_net_profit between 100 and 200)
        or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('OR', 'NM', 'KY')
            and ss_net_profit between 150 and 300)
        or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('VA', 'TX', 'MS')
            and ss_net_profit between 50 and 250))"""

QUERIES["q15"] = """
    select ca_zip, sum(cs_sales_price) sum_sales
    from catalog_sales, customer, customer_address, date_dim
    where cs_bill_customer_sk = c_customer_sk
      and c_current_addr_sk = ca_address_sk
      and (substring(ca_zip, 1, 5) in
             ('85669', '86197', '88274', '83405', '86475', '85392',
              '85460', '80348', '81792')
           or ca_state in ('CA', 'WA', 'GA')
           or cs_sales_price > 500)
      and cs_sold_date_sk = d_date_sk
      and d_qoy = 2 and d_year = 2001
    group by ca_zip
    order by ca_zip
    limit 100"""

QUERIES["q19"] = """
    select i_brand_id brand_id, i_brand brand, i_manufact_id,
           i_manufact, sum(ss_ext_sales_price) ext_price
    from date_dim, store_sales, item, customer, customer_address, store
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manager_id = 8 and d_moy = 11 and d_year = 1998
      and ss_customer_sk = c_customer_sk
      and c_current_addr_sk = ca_address_sk
      and substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
      and ss_store_sk = s_store_sk
    group by i_brand_id, i_brand, i_manufact_id, i_manufact
    order by ext_price desc, brand_id
    limit 100"""

QUERIES["q20"] = """
    select i_item_id, i_item_desc, i_category, i_class, i_current_price,
           sum(cs_ext_sales_price) as itemrevenue,
           sum(cs_ext_sales_price) * 100.0 /
             sum(sum(cs_ext_sales_price)) over (partition by i_class)
             as revenueratio
    from catalog_sales, item, date_dim
    where cs_item_sk = i_item_sk
      and i_category in ('Sports', 'Books', 'Home')
      and cs_sold_date_sk = d_date_sk
      and d_year = 1999 and d_moy between 2 and 3
    group by i_item_id, i_item_desc, i_category, i_class,
             i_current_price
    order by i_category, i_class, i_item_id, i_item_desc, revenueratio
    limit 100"""

QUERIES["q21"] = """
    select w_warehouse_name, i_item_id,
           sum(case when d_moy < 3 then inv_quantity_on_hand else 0 end)
             as inv_before,
           sum(case when d_moy >= 3 then inv_quantity_on_hand else 0 end)
             as inv_after
    from inventory, warehouse, item, date_dim
    where i_current_price between 0.99 and 1.49
      and i_item_sk = inv_item_sk
      and inv_warehouse_sk = w_warehouse_sk
      and inv_date_sk = d_date_sk
      and d_year = 2000
    group by w_warehouse_name, i_item_id
    having sum(case when d_moy < 3 then inv_quantity_on_hand else 0
               end) > 0
    order by w_warehouse_name, i_item_id
    limit 100"""

QUERIES["q22"] = """
    select i_product_name, i_brand, i_class, i_category,
           avg(inv_quantity_on_hand) qoh
    from inventory, date_dim, item
    where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
      and d_month_seq between 1200 and 1200 + 11
    group by rollup(i_product_name, i_brand, i_class, i_category)
    order by qoh, i_product_name, i_brand, i_class, i_category
    limit 100"""

QUERIES["q25"] = """
    select i_item_id, i_item_desc, s_store_id, s_store_name,
           sum(ss_net_profit) as store_sales_profit,
           sum(sr_net_loss) as store_returns_loss,
           sum(cs_net_profit) as catalog_sales_profit
    from store_sales, store_returns, catalog_sales, date_dim d1,
         date_dim d2, date_dim d3, store, item
    where d1.d_moy = 4 and d1.d_year = 2001
      and d1.d_date_sk = ss_sold_date_sk
      and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
      and ss_customer_sk = sr_customer_sk
      and ss_item_sk = sr_item_sk
      and ss_ticket_number = sr_ticket_number
      and sr_returned_date_sk = d2.d_date_sk
      and d2.d_moy between 4 and 10 and d2.d_year = 2001
      and sr_customer_sk = cs_bill_customer_sk
      and sr_item_sk = cs_item_sk
      and cs_sold_date_sk = d3.d_date_sk
      and d3.d_moy between 4 and 10 and d3.d_year = 2001
    group by i_item_id, i_item_desc, s_store_id, s_store_name
    order by i_item_id, i_item_desc, s_store_id, s_store_name
    limit 100"""

QUERIES["q26"] = """
    select i_item_id,
           avg(cs_quantity) agg1, avg(cs_list_price) agg2,
           avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
    from catalog_sales, customer_demographics, date_dim, item, promotion
    where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
      and cs_bill_cdemo_sk = cd_demo_sk and cs_promo_sk = p_promo_sk
      and cd_gender = 'M' and cd_marital_status = 'S'
      and cd_education_status = 'College'
      and (p_channel_email = 'N' or p_channel_event = 'N')
      and d_year = 2000
    group by i_item_id
    order by i_item_id
    limit 100"""

QUERIES["q27"] = """
    select i_item_id, s_state, grouping(s_state) g_state,
           avg(ss_quantity) agg1, avg(ss_list_price) agg2,
           avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
    from store_sales, customer_demographics, date_dim, store, item
    where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
      and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
      and cd_gender = 'M' and cd_marital_status = 'S'
      and cd_education_status = 'College' and d_year = 2002
    group by rollup (i_item_id, s_state)
    order by i_item_id, s_state
    limit 100"""

QUERIES["q29"] = """
    select i_item_id, i_item_desc, s_store_id, s_store_name,
           sum(ss_quantity) as store_sales_quantity,
           sum(sr_return_quantity) as store_returns_quantity,
           sum(cs_quantity) as catalog_sales_quantity
    from store_sales, store_returns, catalog_sales, date_dim d1,
         date_dim d2, date_dim d3, store, item
    where d1.d_moy = 9 and d1.d_year = 1999
      and d1.d_date_sk = ss_sold_date_sk
      and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
      and ss_customer_sk = sr_customer_sk
      and ss_item_sk = sr_item_sk
      and ss_ticket_number = sr_ticket_number
      and sr_returned_date_sk = d2.d_date_sk
      and d2.d_moy between 9 and 12 and d2.d_year = 1999
      and sr_customer_sk = cs_bill_customer_sk
      and sr_item_sk = cs_item_sk
      and cs_sold_date_sk = d3.d_date_sk
      and d3.d_year in (1999, 2000, 2001)
    group by i_item_id, i_item_desc, s_store_id, s_store_name
    order by i_item_id, i_item_desc, s_store_id, s_store_name
    limit 100"""

QUERIES["q33"] = """
    with ss as (
      select i_manufact_id, sum(ss_ext_sales_price) total_sales
      from store_sales, date_dim, customer_address, item
      where i_manufact_id in (
              select i_manufact_id from item where i_category = 'Books')
        and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 1
        and ss_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_manufact_id),
    cs as (
      select i_manufact_id, sum(cs_ext_sales_price) total_sales
      from catalog_sales, date_dim, customer_address, item
      where i_manufact_id in (
              select i_manufact_id from item where i_category = 'Books')
        and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 1
        and cs_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_manufact_id),
    ws as (
      select i_manufact_id, sum(ws_ext_sales_price) total_sales
      from web_sales, date_dim, customer_address, item
      where i_manufact_id in (
              select i_manufact_id from item where i_category = 'Books')
        and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 1
        and ws_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_manufact_id)
    select i_manufact_id, sum(total_sales) total_sales
    from (select * from ss union all
          select * from cs union all
          select * from ws) tmp1
    group by i_manufact_id
    order by total_sales, i_manufact_id
    limit 100"""

QUERIES["q34"] = """
    select c_last_name, c_first_name, c_salutation,
           c_preferred_cust_flag, ss_ticket_number, cnt
    from (select ss_ticket_number, ss_customer_sk, count(*) cnt
          from store_sales, date_dim, store, household_demographics
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_hdemo_sk = hd_demo_sk
            and (d_dom between 1 and 3 or d_dom between 25 and 28)
            and (hd_buy_potential = '>10000'
                 or hd_buy_potential = 'Unknown')
            and hd_vehicle_count > 0
            and d_year in (1999, 2000, 2001)
            and s_county in ('Williamson County', 'Ziebach County',
                             'Walker County', 'Rush County')
          group by ss_ticket_number, ss_customer_sk) dn, customer
    where ss_customer_sk = c_customer_sk and cnt between 15 and 20
    order by c_last_name, c_first_name, c_salutation,
             c_preferred_cust_flag desc, ss_ticket_number
    limit 1000"""

QUERIES["q36"] = """
    select sum(ss_net_profit) / sum(ss_ext_sales_price)
             as gross_margin,
           i_category, i_class, grouping(i_category) + grouping(i_class)
             as lochierarchy
    from store_sales, date_dim d1, item, store
    where d1.d_year = 2001 and d1.d_date_sk = ss_sold_date_sk
      and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
      and s_state in ('TN', 'SD', 'AL', 'GA')
    group by rollup(i_category, i_class)
    order by lochierarchy desc, i_category, i_class
    limit 100"""

QUERIES["q37"] = """
    select i_item_id, i_item_desc, i_current_price
    from item, inventory, date_dim, catalog_sales
    where i_current_price between 68 and 68 + 30
      and inv_item_sk = i_item_sk
      and d_date_sk = inv_date_sk
      and d_year = 2000
      and i_manufact_id in (677, 940, 694, 808)
      and inv_quantity_on_hand between 100 and 500
      and cs_item_sk = i_item_sk
    group by i_item_id, i_item_desc, i_current_price
    order by i_item_id
    limit 100"""

QUERIES["q40"] = """
    select w_state, i_item_id,
           sum(case when d_year < 2000 then cs_sales_price -
               coalesce(cr_return_amount, 0) else 0 end)
             as sales_before,
           sum(case when d_year >= 2000 then cs_sales_price -
               coalesce(cr_return_amount, 0) else 0 end)
             as sales_after
    from catalog_sales
      left outer join catalog_returns
        on (cs_order_number = cr_order_number
            and cs_item_sk = cr_item_sk),
      warehouse, item, date_dim
    where i_current_price between 0.99 and 1.49
      and i_item_sk = cs_item_sk
      and cs_warehouse_sk = w_warehouse_sk
      and cs_sold_date_sk = d_date_sk
      and d_year in (1999, 2000, 2001)
    group by w_state, i_item_id
    order by w_state, i_item_id
    limit 100"""

QUERIES["q42"] = """
    select d_year, i_category_id, i_category,
           sum(ss_ext_sales_price) total_sales
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manager_id = 1 and d_moy = 11 and d_year = 2000
    group by d_year, i_category_id, i_category
    order by total_sales desc, d_year, i_category_id, i_category
    limit 100"""

QUERIES["q43"] = """
    select s_store_name, s_store_id,
           sum(case when d_day_name = 'Sunday' then ss_sales_price
                    else null end) sun_sales,
           sum(case when d_day_name = 'Monday' then ss_sales_price
                    else null end) mon_sales,
           sum(case when d_day_name = 'Tuesday' then ss_sales_price
                    else null end) tue_sales,
           sum(case when d_day_name = 'Wednesday' then ss_sales_price
                    else null end) wed_sales,
           sum(case when d_day_name = 'Thursday' then ss_sales_price
                    else null end) thu_sales,
           sum(case when d_day_name = 'Friday' then ss_sales_price
                    else null end) fri_sales,
           sum(case when d_day_name = 'Saturday' then ss_sales_price
                    else null end) sat_sales
    from date_dim, store_sales, store
    where d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk
      and s_gmt_offset = -5 and d_year = 2000
    group by s_store_name, s_store_id
    order by s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
             wed_sales, thu_sales, fri_sales, sat_sales
    limit 100"""

QUERIES["q45"] = """
    select ca_zip, ca_city, sum(ws_sales_price) sum_sales
    from web_sales, customer, customer_address, date_dim, item
    where ws_bill_customer_sk = c_customer_sk
      and c_current_addr_sk = ca_address_sk
      and ws_item_sk = i_item_sk
      and (substring(ca_zip, 1, 5) in
             ('85669', '86197', '88274', '83405', '86475', '85392',
              '85460', '80348', '81792')
           or i_item_id in (
               select i_item_id from item
               where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)))
      and ws_sold_date_sk = d_date_sk
      and d_qoy = 2 and d_year = 2001
    group by ca_zip, ca_city
    order by ca_zip, ca_city
    limit 100"""

QUERIES["q46"] = """
    select c_last_name, c_first_name, ca_city, bought_city,
           ss_ticket_number, amt, profit
    from (select ss_ticket_number, ss_customer_sk,
                 ca_city bought_city, sum(ss_coupon_amt) amt,
                 sum(ss_net_profit) profit
          from store_sales, date_dim, store, household_demographics,
               customer_address
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_hdemo_sk = hd_demo_sk
            and ss_addr_sk = ca_address_sk
            and (hd_dep_count = 4 or hd_vehicle_count = 3)
            and d_dow in (6, 0)
            and d_year in (1999, 2000, 2001)
            and s_city in ('Fairview', 'Midway')
          group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
                   ca_city) dn,
         customer, customer_address current_addr
    where ss_customer_sk = c_customer_sk
      and c_current_addr_sk = current_addr.ca_address_sk
      and current_addr.ca_city <> bought_city
    order by c_last_name, c_first_name, ca_city, bought_city,
             ss_ticket_number
    limit 100"""

QUERIES["q48"] = """
    select sum(ss_quantity) sum_q
    from store_sales, store, customer_demographics, customer_address,
         date_dim
    where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
      and d_year = 2000
      and ((cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'M'
            and cd_education_status = '4 yr Degree'
            and ss_sales_price between 100.00 and 150.00)
        or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'D'
            and cd_education_status = '2 yr Degree'
            and ss_sales_price between 50.00 and 100.00)
        or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'S'
            and cd_education_status = 'College'
            and ss_sales_price between 150.00 and 200.00))
      and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('CO', 'OH', 'TX')
            and ss_net_profit between 0 and 2000)
        or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('OR', 'MN', 'KY')
            and ss_net_profit between 150 and 3000)
        or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
            and ca_state in ('VA', 'CA', 'MS')
            and ss_net_profit between 50 and 25000))"""

QUERIES["q50"] = """
    select s_store_name, s_company_id, s_state, s_zip,
           sum(case when (sr_returned_date_sk - ss_sold_date_sk <= 30)
               then 1 else 0 end) as d30,
           sum(case when (sr_returned_date_sk - ss_sold_date_sk > 30)
                     and (sr_returned_date_sk - ss_sold_date_sk <= 60)
               then 1 else 0 end) as d31_60,
           sum(case when (sr_returned_date_sk - ss_sold_date_sk > 60)
                     and (sr_returned_date_sk - ss_sold_date_sk <= 90)
               then 1 else 0 end) as d61_90,
           sum(case when (sr_returned_date_sk - ss_sold_date_sk > 90)
               then 1 else 0 end) as d90_plus
    from store_sales, store_returns, store, date_dim d1, date_dim d2
    where d2.d_year = 2001 and d2.d_moy = 8
      and ss_ticket_number = sr_ticket_number
      and ss_item_sk = sr_item_sk
      and ss_sold_date_sk = d1.d_date_sk
      and sr_returned_date_sk = d2.d_date_sk
      and ss_customer_sk = sr_customer_sk
      and ss_store_sk = s_store_sk
    group by s_store_name, s_company_id, s_state, s_zip
    order by s_store_name, s_company_id, s_state, s_zip
    limit 100"""

QUERIES["q52"] = """
    select d_year, i_brand_id brand_id, i_brand brand,
           sum(ss_ext_sales_price) ext_price
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manager_id = 1 and d_moy = 11 and d_year = 2000
    group by d_year, i_brand_id, i_brand
    order by d_year, ext_price desc, brand_id
    limit 100"""

QUERIES["q55"] = """
    select i_brand_id brand_id, i_brand brand,
           sum(ss_ext_sales_price) ext_price
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manager_id = 28 and d_moy = 11 and d_year = 1999
    group by i_brand_id, i_brand
    order by ext_price desc, brand_id
    limit 100"""

# --------------------------------------------------------------------------
# windows, set operations, multi-channel CTEs
# --------------------------------------------------------------------------

QUERIES["q9"] = """
    select case when (select count(*) from store_sales
                      where ss_quantity between 1 and 20) > 10000
                then (select avg(ss_ext_discount_amt) from store_sales
                      where ss_quantity between 1 and 20)
                else (select avg(ss_net_paid) from store_sales
                      where ss_quantity between 1 and 20) end bucket1,
           case when (select count(*) from store_sales
                      where ss_quantity between 21 and 40) > 10000
                then (select avg(ss_ext_discount_amt) from store_sales
                      where ss_quantity between 21 and 40)
                else (select avg(ss_net_paid) from store_sales
                      where ss_quantity between 21 and 40) end bucket2,
           case when (select count(*) from store_sales
                      where ss_quantity between 41 and 60) > 10000
                then (select avg(ss_ext_discount_amt) from store_sales
                      where ss_quantity between 41 and 60)
                else (select avg(ss_net_paid) from store_sales
                      where ss_quantity between 41 and 60) end bucket3
    from reason
    where r_reason_sk = 1"""

QUERIES["q18"] = """
    select i_item_id, ca_country, ca_state, ca_county,
           avg(cs_quantity) agg1, avg(cs_list_price) agg2,
           avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4,
           avg(cs_net_profit) agg5, avg(c_birth_year) agg6
    from catalog_sales, customer_demographics cd1, customer, item,
         customer_address, date_dim
    where cs_sold_date_sk = d_date_sk
      and cs_item_sk = i_item_sk
      and cs_bill_cdemo_sk = cd1.cd_demo_sk
      and cs_bill_customer_sk = c_customer_sk
      and cd1.cd_gender = 'F'
      and cd1.cd_education_status = 'Unknown'
      and c_current_addr_sk = ca_address_sk
      and c_birth_month in (1, 6, 8, 9, 12, 2)
      and d_year = 1998
      and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA', 'TN')
    group by rollup(i_item_id, ca_country, ca_state, ca_county)
    order by ca_country, ca_state, ca_county, i_item_id
    limit 100"""

QUERIES["q28"] = """
    select b1.lp b1_lp, b1.cnt b1_cnt, b2.lp b2_lp, b2.cnt b2_cnt,
           b3.lp b3_lp, b3.cnt b3_cnt
    from (select avg(ss_list_price) lp, count(ss_list_price) cnt
          from store_sales
          where ss_quantity between 0 and 5
            and (ss_list_price between 8 and 18
                 or ss_coupon_amt between 459 and 1459
                 or ss_wholesale_cost between 57 and 77)) b1,
         (select avg(ss_list_price) lp, count(ss_list_price) cnt
          from store_sales
          where ss_quantity between 6 and 10
            and (ss_list_price between 90 and 100
                 or ss_coupon_amt between 2323 and 3323
                 or ss_wholesale_cost between 31 and 51)) b2,
         (select avg(ss_list_price) lp, count(ss_list_price) cnt
          from store_sales
          where ss_quantity between 11 and 15
            and (ss_list_price between 142 and 152
                 or ss_coupon_amt between 12214 and 13214
                 or ss_wholesale_cost between 79 and 99)) b3"""

QUERIES["q38"] = """
    select count(*) cnt from (
      select distinct c_last_name, c_first_name, d_date
      from store_sales, date_dim, customer
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_customer_sk = customer.c_customer_sk
        and d_month_seq between 1200 and 1200 + 11
      intersect
      select distinct c_last_name, c_first_name, d_date
      from catalog_sales, date_dim, customer
      where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
        and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
        and d_month_seq between 1200 and 1200 + 11
      intersect
      select distinct c_last_name, c_first_name, d_date
      from web_sales, date_dim, customer
      where web_sales.ws_sold_date_sk = date_dim.d_date_sk
        and web_sales.ws_bill_customer_sk = customer.c_customer_sk
        and d_month_seq between 1200 and 1200 + 11
    ) hot_cust
    limit 100"""

QUERIES["q53"] = """
    select manufact_id, sum_sales, avg_quarterly_sales
    from (select i_manufact_id manufact_id,
                 sum(ss_sales_price) sum_sales,
                 avg(sum(ss_sales_price))
                   over (partition by i_manufact_id)
                   avg_quarterly_sales
          from item, store_sales, date_dim, store
          where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and d_month_seq in (1200, 1201, 1202, 1203, 1204, 1205,
                                1206, 1207, 1208, 1209, 1210, 1211)
            and ((i_category in ('Books', 'Home', 'Sports')
                  and i_class in ('classical', 'fishing', 'football'))
              or (i_category in ('Women', 'Music', 'Men')
                  and i_class in ('shirts', 'dresses', 'pants')))
          group by i_manufact_id, d_qoy) tmp1
    where case when avg_quarterly_sales > 0
               then abs(sum_sales - avg_quarterly_sales) /
                    avg_quarterly_sales else null end > 0.1
    order by avg_quarterly_sales, sum_sales, manufact_id
    limit 100"""

QUERIES["q56"] = """
    with ss as (
      select i_item_id, sum(ss_ext_sales_price) total_sales
      from store_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_color in ('red', 'blue', 'green'))
        and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and d_year = 2000 and d_moy = 2
        and ss_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id),
    cs as (
      select i_item_id, sum(cs_ext_sales_price) total_sales
      from catalog_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_color in ('red', 'blue', 'green'))
        and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
        and d_year = 2000 and d_moy = 2
        and cs_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id),
    ws as (
      select i_item_id, sum(ws_ext_sales_price) total_sales
      from web_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_color in ('red', 'blue', 'green'))
        and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
        and d_year = 2000 and d_moy = 2
        and ws_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id)
    select i_item_id, sum(total_sales) total_sales
    from (select * from ss union all
          select * from cs union all
          select * from ws) tmp1
    group by i_item_id
    order by total_sales, i_item_id
    limit 100"""

QUERIES["q60"] = """
    with ss as (
      select i_item_id, sum(ss_ext_sales_price) total_sales
      from store_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_category = 'Music')
        and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 9
        and ss_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id),
    cs as (
      select i_item_id, sum(cs_ext_sales_price) total_sales
      from catalog_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_category = 'Music')
        and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 9
        and cs_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id),
    ws as (
      select i_item_id, sum(ws_ext_sales_price) total_sales
      from web_sales, date_dim, customer_address, item
      where i_item_id in (select i_item_id from item
                          where i_category = 'Music')
        and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 9
        and ws_bill_addr_sk = ca_address_sk and ca_gmt_offset = -5
      group by i_item_id)
    select i_item_id, sum(total_sales) total_sales
    from (select * from ss union all
          select * from cs union all
          select * from ws) tmp1
    group by i_item_id
    order by i_item_id, total_sales
    limit 100"""

QUERIES["q61"] = """
    select promotions, total,
           cast(promotions as double) / cast(total as double) * 100
             as promo_pct
    from (select sum(ss_ext_sales_price) promotions
          from store_sales, store, promotion, date_dim, customer,
               customer_address, item
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_promo_sk = p_promo_sk
            and ss_customer_sk = c_customer_sk
            and ca_address_sk = c_current_addr_sk
            and ss_item_sk = i_item_sk
            and ca_gmt_offset = -5 and i_category = 'Books'
            and (p_channel_dmail = 'Y' or p_channel_email = 'Y'
                 or p_channel_tv = 'Y')
            and s_gmt_offset = -5 and d_year = 1998
            and d_moy = 11) promotional_sales,
         (select sum(ss_ext_sales_price) total
          from store_sales, store, date_dim, customer,
               customer_address, item
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_customer_sk = c_customer_sk
            and ca_address_sk = c_current_addr_sk
            and ss_item_sk = i_item_sk
            and ca_gmt_offset = -5 and i_category = 'Books'
            and s_gmt_offset = -5 and d_year = 1998
            and d_moy = 11) all_sales
    order by promotions, total
    limit 100"""

QUERIES["q62"] = """
    select w_warehouse_name, sm_type, web_name,
           sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30)
               then 1 else 0 end) as d30,
           sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30)
                     and (ws_ship_date_sk - ws_sold_date_sk <= 60)
               then 1 else 0 end) as d31_60,
           sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60)
                     and (ws_ship_date_sk - ws_sold_date_sk <= 90)
               then 1 else 0 end) as d61_90,
           sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90)
               then 1 else 0 end) as d90_plus
    from web_sales, warehouse, ship_mode, web_site, date_dim
    where d_month_seq between 1200 and 1200 + 11
      and ws_ship_date_sk = d_date_sk
      and ws_warehouse_sk = w_warehouse_sk
      and ws_ship_mode_sk = sm_ship_mode_sk
      and ws_web_site_sk = web_site_sk
    group by w_warehouse_name, sm_type, web_name
    order by w_warehouse_name, sm_type, web_name
    limit 100"""

QUERIES["q63"] = """
    select manager_id, sum_sales, avg_monthly_sales
    from (select i_manager_id manager_id,
                 sum(ss_sales_price) sum_sales,
                 avg(sum(ss_sales_price))
                   over (partition by i_manager_id) avg_monthly_sales
          from item, store_sales, date_dim, store
          where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and d_month_seq in (1200, 1201, 1202, 1203, 1204, 1205,
                                1206, 1207, 1208, 1209, 1210, 1211)
            and ((i_category in ('Books', 'Home', 'Sports')
                  and i_class in ('classical', 'fishing', 'football'))
              or (i_category in ('Women', 'Music', 'Men')
                  and i_class in ('shirts', 'dresses', 'pants')))
          group by i_manager_id, d_moy) tmp1
    where case when avg_monthly_sales > 0
               then abs(sum_sales - avg_monthly_sales) /
                    avg_monthly_sales else null end > 0.1
    order by manager_id, avg_monthly_sales, sum_sales
    limit 100"""

QUERIES["q65"] = """
    select s_store_name, i_item_desc, sc.revenue, i_current_price,
           i_wholesale_cost, i_brand
    from store, item,
         (select ss_store_sk, avg(revenue) as ave
          from (select ss_store_sk, ss_item_sk,
                       sum(ss_sales_price) as revenue
                from store_sales, date_dim
                where ss_sold_date_sk = d_date_sk
                  and d_month_seq between 1176 and 1176 + 11
                group by ss_store_sk, ss_item_sk) sa
          group by ss_store_sk) sb,
         (select ss_store_sk, ss_item_sk,
                 sum(ss_sales_price) as revenue
          from store_sales, date_dim
          where ss_sold_date_sk = d_date_sk
            and d_month_seq between 1176 and 1176 + 11
          group by ss_store_sk, ss_item_sk) sc
    where sb.ss_store_sk = sc.ss_store_sk
      and sc.revenue <= 0.1 * sb.ave
      and s_store_sk = sc.ss_store_sk
      and i_item_sk = sc.ss_item_sk
    order by s_store_name, i_item_desc, sc.revenue
    limit 100"""

QUERIES["q68"] = """
    select c_last_name, c_first_name, ca_city, bought_city,
           ss_ticket_number, extended_price, extended_tax,
           list_price
    from (select ss_ticket_number, ss_customer_sk,
                 ca_city bought_city,
                 sum(ss_ext_sales_price) extended_price,
                 sum(ss_ext_list_price) list_price,
                 sum(ss_ext_tax) extended_tax
          from store_sales, date_dim, store, household_demographics,
               customer_address
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_hdemo_sk = hd_demo_sk
            and ss_addr_sk = ca_address_sk
            and d_dom between 1 and 2
            and (hd_dep_count = 4 or hd_vehicle_count = 3)
            and d_year in (1999, 2000, 2001)
            and s_city in ('Fairview', 'Midway')
          group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
                   ca_city) dn,
         customer, customer_address current_addr
    where ss_customer_sk = c_customer_sk
      and c_current_addr_sk = current_addr.ca_address_sk
      and current_addr.ca_city <> bought_city
    order by c_last_name, ss_ticket_number
    limit 100"""

QUERIES["q69"] = """
    select cd_gender, cd_marital_status, cd_education_status,
           count(*) cnt1
    from customer c, customer_address ca, customer_demographics
    where c.c_current_addr_sk = ca.ca_address_sk
      and ca_state in ('KY', 'GA', 'NM')
      and cd_demo_sk = c.c_current_cdemo_sk
      and exists (select * from store_sales, date_dim
                  where c.c_customer_sk = ss_customer_sk
                    and ss_sold_date_sk = d_date_sk
                    and d_year = 2001 and d_moy between 4 and 6)
      and not exists (select * from web_sales, date_dim
                      where c.c_customer_sk = ws_bill_customer_sk
                        and ws_sold_date_sk = d_date_sk
                        and d_year = 2001 and d_moy between 4 and 6)
    group by cd_gender, cd_marital_status, cd_education_status
    order by cd_gender, cd_marital_status, cd_education_status
    limit 100"""

QUERIES["q71"] = """
    select i_brand_id brand_id, i_brand brand, t_hour, t_minute,
           sum(ext_price) ext_price
    from item,
         (select ws_ext_sales_price as ext_price,
                 ws_sold_date_sk as sold_date_sk,
                 ws_item_sk as sold_item_sk,
                 ws_sold_time_sk as time_sk
          from web_sales, date_dim
          where d_date_sk = ws_sold_date_sk
            and d_moy = 11 and d_year = 1999
          union all
          select ss_ext_sales_price as ext_price,
                 ss_sold_date_sk as sold_date_sk,
                 ss_item_sk as sold_item_sk,
                 ss_sold_time_sk as time_sk
          from store_sales, date_dim
          where d_date_sk = ss_sold_date_sk
            and d_moy = 11 and d_year = 1999) tmp, time_dim
    where sold_item_sk = i_item_sk and i_manager_id = 1
      and time_sk = t_time_sk
      and (t_hour = 8 or t_hour = 9)
    group by i_brand_id, i_brand, t_hour, t_minute
    order by ext_price desc, brand_id
    limit 100"""

QUERIES["q73"] = """
    select c_last_name, c_first_name, c_salutation,
           c_preferred_cust_flag, ss_ticket_number, cnt
    from (select ss_ticket_number, ss_customer_sk, count(*) cnt
          from store_sales, date_dim, store, household_demographics
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_hdemo_sk = hd_demo_sk
            and d_dom between 1 and 2
            and (hd_buy_potential = '>10000'
                 or hd_buy_potential = 'Unknown')
            and hd_vehicle_count > 0
            and d_year in (1999, 2000, 2001)
            and s_county in ('Williamson County', 'Ziebach County')
          group by ss_ticket_number, ss_customer_sk) dj, customer
    where ss_customer_sk = c_customer_sk and cnt between 1 and 5
    order by cnt desc, c_last_name asc, c_first_name, ss_ticket_number
    limit 100"""

QUERIES["q76"] = """
    select channel, col_name, d_year, d_qoy, i_category,
           count(*) sales_cnt, sum(ext_sales_price) sales_amt
    from (
      select 'store' as channel, 'ss_store_sk' col_name, d_year, d_qoy,
             i_category, ss_ext_sales_price ext_sales_price
      from store_sales, item, date_dim
      where ss_store_sk is null and ss_sold_date_sk = d_date_sk
        and ss_item_sk = i_item_sk
      union all
      select 'web' as channel, 'ws_ship_customer_sk' col_name, d_year,
             d_qoy, i_category, ws_ext_sales_price ext_sales_price
      from web_sales, item, date_dim
      where ws_ship_customer_sk is null
        and ws_sold_date_sk = d_date_sk and ws_item_sk = i_item_sk
      union all
      select 'catalog' as channel, 'cs_ship_mode_sk' col_name, d_year,
             d_qoy, i_category, cs_ext_sales_price ext_sales_price
      from catalog_sales, item, date_dim
      where cs_ship_mode_sk is null
        and cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk) foo
    group by channel, col_name, d_year, d_qoy, i_category
    order by channel, col_name, d_year, d_qoy, i_category
    limit 100"""

QUERIES["q79"] = """
    select c_last_name, c_first_name,
           substring(s_city, 1, 30) city, ss_ticket_number, amt, profit
    from (select ss_ticket_number, ss_customer_sk, s_city,
                 sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
          from store_sales, date_dim, store, household_demographics
          where ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk
            and ss_hdemo_sk = hd_demo_sk
            and (hd_dep_count = 6 or hd_vehicle_count > 2)
            and d_dow = 1
            and d_year in (1999, 2000, 2001)
            and s_number_employees between 200 and 295
          group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
                   s_city) ms, customer
    where ss_customer_sk = c_customer_sk
    order by c_last_name, c_first_name, city, profit, ss_ticket_number
    limit 100"""

QUERIES["q82"] = """
    select i_item_id, i_item_desc, i_current_price
    from item, inventory, date_dim, store_sales
    where i_current_price between 62 and 62 + 30
      and inv_item_sk = i_item_sk
      and d_date_sk = inv_date_sk
      and d_year = 2000
      and i_manufact_id in (129, 270, 821, 423)
      and inv_quantity_on_hand between 100 and 500
      and ss_item_sk = i_item_sk
    group by i_item_id, i_item_desc, i_current_price
    order by i_item_id
    limit 100"""

QUERIES["q84"] = """
    select c_customer_id as customer_id,
           c_last_name || ', ' || c_first_name as customername
    from customer, customer_address, customer_demographics,
         household_demographics, income_band, store_returns
    where ca_city = 'Fairview'
      and c_current_addr_sk = ca_address_sk
      and ib_lower_bound >= 30000
      and ib_upper_bound <= 30000 + 50000
      and ib_income_band_sk = hd_income_band_sk
      and cd_demo_sk = c_current_cdemo_sk
      and hd_demo_sk = c_current_hdemo_sk
      and sr_cdemo_sk = cd_demo_sk
    order by c_customer_id
    limit 100"""

QUERIES["q86"] = """
    select sum(ws_net_paid) as total_sum, i_category, i_class,
           grouping(i_category) + grouping(i_class) as lochierarchy
    from web_sales, date_dim d1, item
    where d1.d_month_seq between 1200 and 1200 + 11
      and d1.d_date_sk = ws_sold_date_sk
      and i_item_sk = ws_item_sk
    group by rollup(i_category, i_class)
    order by lochierarchy desc, i_category, i_class
    limit 100"""

QUERIES["q87"] = """
    select count(*) cnt from (
      (select distinct c_last_name, c_first_name, d_date
       from store_sales, date_dim, customer
       where store_sales.ss_sold_date_sk = date_dim.d_date_sk
         and store_sales.ss_customer_sk = customer.c_customer_sk
         and d_month_seq between 1200 and 1200 + 11)
      except
      (select distinct c_last_name, c_first_name, d_date
       from catalog_sales, date_dim, customer
       where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
         and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
         and d_month_seq between 1200 and 1200 + 11)
      except
      (select distinct c_last_name, c_first_name, d_date
       from web_sales, date_dim, customer
       where web_sales.ws_sold_date_sk = date_dim.d_date_sk
         and web_sales.ws_bill_customer_sk = customer.c_customer_sk
         and d_month_seq between 1200 and 1200 + 11)
    ) cool_cust"""

QUERIES["q88"] = """
    select *
    from (select count(*) h8_30_to_9
          from store_sales, household_demographics, time_dim, store
          where ss_sold_time_sk = t_time_sk
            and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
            and t_hour = 8 and t_minute >= 30
            and ((hd_dep_count = 4 and hd_vehicle_count <= 4 + 2)
              or (hd_dep_count = 2 and hd_vehicle_count <= 2 + 2)
              or (hd_dep_count = 0 and hd_vehicle_count <= 0 + 2))
            and s_store_name = 'ese') s1,
         (select count(*) h9_to_9_30
          from store_sales, household_demographics, time_dim, store
          where ss_sold_time_sk = t_time_sk
            and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
            and t_hour = 9 and t_minute < 30
            and ((hd_dep_count = 4 and hd_vehicle_count <= 4 + 2)
              or (hd_dep_count = 2 and hd_vehicle_count <= 2 + 2)
              or (hd_dep_count = 0 and hd_vehicle_count <= 0 + 2))
            and s_store_name = 'ese') s2,
         (select count(*) h9_30_to_10
          from store_sales, household_demographics, time_dim, store
          where ss_sold_time_sk = t_time_sk
            and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
            and t_hour = 9 and t_minute >= 30
            and ((hd_dep_count = 4 and hd_vehicle_count <= 4 + 2)
              or (hd_dep_count = 2 and hd_vehicle_count <= 2 + 2)
              or (hd_dep_count = 0 and hd_vehicle_count <= 0 + 2))
            and s_store_name = 'ese') s3,
         (select count(*) h10_to_10_30
          from store_sales, household_demographics, time_dim, store
          where ss_sold_time_sk = t_time_sk
            and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
            and t_hour = 10 and t_minute < 30
            and ((hd_dep_count = 4 and hd_vehicle_count <= 4 + 2)
              or (hd_dep_count = 2 and hd_vehicle_count <= 2 + 2)
              or (hd_dep_count = 0 and hd_vehicle_count <= 0 + 2))
            and s_store_name = 'ese') s4"""

QUERIES["q89"] = """
    select i_category, i_class, i_brand, s_store_name, s_company_id,
           d_moy, sum_sales, avg_monthly_sales
    from (select i_category, i_class, i_brand, s_store_name,
                 s_company_id, d_moy, sum(ss_sales_price) sum_sales,
                 avg(sum(ss_sales_price)) over (partition by
                   i_category, i_brand, s_store_name, s_company_id)
                   avg_monthly_sales
          from item, store_sales, date_dim, store
          where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
            and ss_store_sk = s_store_sk and d_year = 1999
            and ((i_category in ('Books', 'Music', 'Sports')
                  and i_class in ('classical', 'fishing', 'football'))
              or (i_category in ('Men', 'Women', 'Home')
                  and i_class in ('pants', 'shirts', 'dresses')))
          group by i_category, i_class, i_brand, s_store_name,
                   s_company_id, d_moy) tmp1
    where case when avg_monthly_sales <> 0
               then abs(sum_sales - avg_monthly_sales) /
                    avg_monthly_sales else null end > 0.1
    order by sum_sales - avg_monthly_sales, s_store_name,
             i_category, i_class, i_brand, d_moy
    limit 100"""

QUERIES["q90"] = """
    select cast(amc as double) / cast(pmc as double) am_pm_ratio
    from (select count(*) amc from web_sales, household_demographics,
                 time_dim, web_page
          where ws_sold_time_sk = t_time_sk
            and ws_web_page_sk = wp_web_page_sk
            and ws_ship_customer_sk is not null
            and t_hour between 8 and 9
            and household_demographics.hd_demo_sk =
                web_sales.ws_web_page_sk % 7200
            and hd_dep_count = 6
            and wp_char_count between 5000 and 5200) at1,
         (select count(*) pmc from web_sales, household_demographics,
                 time_dim, web_page
          where ws_sold_time_sk = t_time_sk
            and ws_web_page_sk = wp_web_page_sk
            and ws_ship_customer_sk is not null
            and t_hour between 19 and 20
            and household_demographics.hd_demo_sk =
                web_sales.ws_web_page_sk % 7200
            and hd_dep_count = 6
            and wp_char_count between 5000 and 5200) pt
    order by am_pm_ratio
    limit 100"""

QUERIES["q91"] = """
    select cc_call_center_sk, cc_name, cc_manager,
           sum(cr_net_loss) returns_loss
    from call_center, catalog_returns, date_dim, customer,
         customer_address, customer_demographics,
         household_demographics
    where cr_call_center_sk = cc_call_center_sk
      and cr_returned_date_sk = d_date_sk
      and cr_returning_customer_sk = c_customer_sk
      and cd_demo_sk = c_current_cdemo_sk
      and hd_demo_sk = c_current_hdemo_sk
      and ca_address_sk = c_current_addr_sk
      and d_year = 1998 and d_moy = 11
      and ((cd_marital_status = 'M'
            and cd_education_status = 'Unknown')
        or (cd_marital_status = 'W'
            and cd_education_status = 'Advanced Degree'))
      and hd_buy_potential like '>10000%'
      and ca_gmt_offset = -7
    group by cc_call_center_sk, cc_name, cc_manager
    order by returns_loss desc
    limit 100"""

QUERIES["q93"] = """
    select ss_customer_sk, sum(act_sales) sumsales
    from (select ss_item_sk, ss_ticket_number, ss_customer_sk,
                 case when sr_return_quantity is not null
                      then (ss_quantity - sr_return_quantity) *
                           ss_sales_price
                      else ss_quantity * ss_sales_price end act_sales
          from store_sales
            left outer join store_returns
              on (sr_item_sk = ss_item_sk
                  and sr_ticket_number = ss_ticket_number),
            reason
          where sr_reason_sk = r_reason_sk
            and r_reason_desc = 'reason 28') t
    group by ss_customer_sk
    order by sumsales, ss_customer_sk
    limit 100"""

QUERIES["q96"] = """
    select count(*) cnt
    from store_sales, household_demographics, time_dim, store
    where ss_sold_time_sk = t_time_sk
      and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
      and t_hour = 20 and t_minute >= 30 and hd_dep_count = 7
      and s_store_name = 'ese'
    order by cnt
    limit 100"""

QUERIES["q97"] = """
    with ssci as (
      select ss_customer_sk customer_sk, ss_item_sk item_sk
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk
        and d_month_seq between 1200 and 1200 + 11
      group by ss_customer_sk, ss_item_sk),
    csci as (
      select cs_bill_customer_sk customer_sk, cs_item_sk item_sk
      from catalog_sales, date_dim
      where cs_sold_date_sk = d_date_sk
        and d_month_seq between 1200 and 1200 + 11
      group by cs_bill_customer_sk, cs_item_sk)
    select sum(case when ssci.customer_sk is not null
                     and csci.customer_sk is null
               then 1 else 0 end) store_only,
           sum(case when ssci.customer_sk is null
                     and csci.customer_sk is not null
               then 1 else 0 end) catalog_only,
           sum(case when ssci.customer_sk is not null
                     and csci.customer_sk is not null
               then 1 else 0 end) store_and_catalog
    from ssci full outer join csci
      on (ssci.customer_sk = csci.customer_sk
          and ssci.item_sk = csci.item_sk)
    limit 100"""

QUERIES["q98"] = """
    select i_item_id, i_item_desc, i_category, i_class,
           i_current_price,
           sum(ss_ext_sales_price) as itemrevenue,
           sum(ss_ext_sales_price) * 100.0 /
             sum(sum(ss_ext_sales_price))
               over (partition by i_class) as revenueratio
    from store_sales, item, date_dim
    where ss_item_sk = i_item_sk
      and i_category in ('Sports', 'Books', 'Home')
      and ss_sold_date_sk = d_date_sk
      and d_year = 1999 and d_moy between 2 and 3
    group by i_item_id, i_item_desc, i_category, i_class,
             i_current_price
    order by i_category, i_class, i_item_id, i_item_desc,
             revenueratio
    limit 100"""

QUERIES["q99"] = """
    select w_warehouse_name, sm_type, cc_name,
           sum(case when (cs_ship_date_sk - cs_sold_date_sk <= 30)
               then 1 else 0 end) as d30,
           sum(case when (cs_ship_date_sk - cs_sold_date_sk > 30)
                     and (cs_ship_date_sk - cs_sold_date_sk <= 60)
               then 1 else 0 end) as d31_60,
           sum(case when (cs_ship_date_sk - cs_sold_date_sk > 60)
                     and (cs_ship_date_sk - cs_sold_date_sk <= 90)
               then 1 else 0 end) as d61_90,
           sum(case when (cs_ship_date_sk - cs_sold_date_sk > 90)
               then 1 else 0 end) as d90_plus
    from catalog_sales, warehouse, ship_mode, call_center, date_dim
    where d_month_seq between 1200 and 1200 + 11
      and cs_ship_date_sk = d_date_sk
      and cs_warehouse_sk = w_warehouse_sk
      and cs_ship_mode_sk = sm_ship_mode_sk
      and cs_call_center_sk = cc_call_center_sk
    group by w_warehouse_name, sm_type, cc_name
    order by w_warehouse_name, sm_type, cc_name
    limit 100"""

# --------------------------------------------------------------------------
# correlated scalar aggregate subqueries (decorrelated to group-by+join)
# --------------------------------------------------------------------------

QUERIES["q1"] = """
    with customer_total_return as (
      select sr_customer_sk as ctr_customer_sk,
             sr_store_sk as ctr_store_sk,
             sum(sr_return_amt) as ctr_total_return
      from store_returns, date_dim
      where sr_returned_date_sk = d_date_sk and d_year = 2000
      group by sr_customer_sk, sr_store_sk)
    select c_customer_id
    from customer_total_return ctr1, store, customer
    where ctr1.ctr_total_return >
        (select avg(ctr_total_return) * 1.2
         from customer_total_return ctr2
         where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
      and s_store_sk = ctr1.ctr_store_sk
      and s_state = 'TN'
      and ctr1.ctr_customer_sk = c_customer_sk
    order by c_customer_id
    limit 100"""

QUERIES["q6"] = """
    select a.ca_state state, count(*) cnt
    from customer_address a, customer c, store_sales s, date_dim d,
         item i
    where a.ca_address_sk = c.c_current_addr_sk
      and c.c_customer_sk = s.ss_customer_sk
      and s.ss_sold_date_sk = d.d_date_sk
      and s.ss_item_sk = i.i_item_sk
      and d.d_month_seq =
        (select distinct d_month_seq from date_dim
         where d_year = 2001 and d_moy = 1)
      and i.i_current_price >
        (select avg(j.i_current_price) * 1.2 from item j
         where j.i_category = i.i_category)
    group by a.ca_state
    having count(*) >= 10
    order by cnt, a.ca_state
    limit 100"""

QUERIES["q32"] = """
    select sum(cs_ext_discount_amt) as excess_discount_amount
    from catalog_sales, item, date_dim
    where i_manufact_id = 977
      and i_item_sk = cs_item_sk
      and d_date_sk = cs_sold_date_sk
      and d_year = 2000 and d_moy between 1 and 4
      and cs_ext_discount_amt >
        (select 1.3 * avg(cs_ext_discount_amt)
         from catalog_sales, date_dim
         where cs_item_sk = i_item_sk
           and d_year = 2000 and d_moy between 1 and 4
           and d_date_sk = cs_sold_date_sk)
    limit 100"""

QUERIES["q81"] = """
    with customer_total_return as (
      select cr_returning_customer_sk as ctr_customer_sk,
             ca_state as ctr_state,
             sum(cr_return_amt_inc_tax) as ctr_total_return
      from catalog_returns, date_dim, customer_address
      where cr_returned_date_sk = d_date_sk and d_year = 2000
        and cr_returning_addr_sk = ca_address_sk
      group by cr_returning_customer_sk, ca_state)
    select c_customer_id, c_salutation, c_first_name, c_last_name,
           ctr_total_return
    from customer_total_return ctr1, customer_address, customer
    where ctr1.ctr_total_return >
        (select avg(ctr_total_return) * 1.2
         from customer_total_return ctr2
         where ctr1.ctr_state = ctr2.ctr_state)
      and ca_address_sk = c_current_addr_sk
      and ca_state = 'GA'
      and ctr1.ctr_customer_sk = c_customer_sk
    order by c_customer_id, c_salutation, c_first_name, c_last_name,
             ctr_total_return
    limit 100"""

QUERIES["q92"] = """
    select sum(ws_ext_discount_amt) as excess_discount_amount
    from web_sales, item, date_dim
    where i_manufact_id = 350
      and i_item_sk = ws_item_sk
      and d_date_sk = ws_sold_date_sk
      and d_year = 2000 and d_moy between 1 and 4
      and ws_ext_discount_amt >
        (select 1.3 * avg(ws_ext_discount_amt)
         from web_sales, date_dim
         where ws_item_sk = i_item_sk
           and d_year = 2000 and d_moy between 1 and 4
           and d_date_sk = ws_sold_date_sk)
    limit 100"""

# --------------------------------------------------------------------------
# round-3 extension batch 2
# --------------------------------------------------------------------------

QUERIES["q30"] = """
    with customer_total_return as (
      select wr_returning_customer_sk as ctr_customer_sk,
             ca_state as ctr_state,
             sum(wr_return_amt) as ctr_total_return
      from web_returns, date_dim, customer_address
      where wr_returned_date_sk = d_date_sk and d_year = 2002
        and wr_returning_addr_sk = ca_address_sk
      group by wr_returning_customer_sk, ca_state)
    select c_customer_id, c_salutation, c_first_name, c_last_name,
           c_preferred_cust_flag, c_birth_year, ctr_total_return
    from customer_total_return ctr1, customer_address, customer
    where ctr1.ctr_total_return >
        (select avg(ctr_total_return) * 1.2
         from customer_total_return ctr2
         where ctr1.ctr_state = ctr2.ctr_state)
      and ca_address_sk = c_current_addr_sk
      and ca_state = 'GA'
      and ctr1.ctr_customer_sk = c_customer_sk
    order by c_customer_id, c_salutation, c_first_name, c_last_name,
             c_preferred_cust_flag, c_birth_year, ctr_total_return
    limit 100"""

QUERIES["q31"] = """
    with ss as (
      select ca_county, d_qoy, d_year,
             sum(ss_ext_sales_price) as store_sales
      from store_sales, date_dim, customer_address
      where ss_sold_date_sk = d_date_sk
        and ss_addr_sk = ca_address_sk
      group by ca_county, d_qoy, d_year),
    ws as (
      select ca_county, d_qoy, d_year,
             sum(ws_ext_sales_price) as web_sales
      from web_sales, date_dim, customer_address
      where ws_sold_date_sk = d_date_sk
        and ws_bill_addr_sk = ca_address_sk
      group by ca_county, d_qoy, d_year)
    select ss1.ca_county, ss1.d_year,
           ws2.web_sales / ws1.web_sales web_q1_q2_increase,
           ss2.store_sales / ss1.store_sales store_q1_q2_increase
    from ss ss1, ss ss2, ws ws1, ws ws2
    where ss1.d_qoy = 1 and ss1.d_year = 2000
      and ss1.ca_county = ss2.ca_county
      and ss2.d_qoy = 2 and ss2.d_year = 2000
      and ss2.ca_county = ws1.ca_county
      and ws1.d_qoy = 1 and ws1.d_year = 2000
      and ws1.ca_county = ws2.ca_county
      and ws2.d_qoy = 2 and ws2.d_year = 2000
      and case when ws1.web_sales > 0
               then ws2.web_sales / ws1.web_sales else null end >
          case when ss1.store_sales > 0
               then ss2.store_sales / ss1.store_sales else null end
    order by ss1.ca_county
    limit 100"""

QUERIES["q35"] = """
    select ca_state, cd_gender, cd_marital_status,
           count(*) cnt1, avg(cd_dep_count) a1,
           max(cd_dep_count) m1, sum(cd_dep_count) s1
    from customer c, customer_address ca, customer_demographics
    where c.c_current_addr_sk = ca.ca_address_sk
      and cd_demo_sk = c.c_current_cdemo_sk
      and exists (select * from store_sales, date_dim
                  where c.c_customer_sk = ss_customer_sk
                    and ss_sold_date_sk = d_date_sk
                    and d_year = 2002 and d_qoy < 4)
      and exists (select * from web_sales, date_dim
                  where c.c_customer_sk = ws_bill_customer_sk
                    and ws_sold_date_sk = d_date_sk
                    and d_year = 2002 and d_qoy < 4)
    group by ca_state, cd_gender, cd_marital_status
    order by ca_state, cd_gender, cd_marital_status
    limit 100"""

QUERIES["q47"] = """
    with v1 as (
      select i_category, i_brand, s_store_name, s_company_id,
             d_year, d_moy, sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price)) over (partition by
               i_category, i_brand, s_store_name, s_company_id, d_year)
               avg_monthly_sales,
             rank() over (partition by
               i_category, i_brand, s_store_name, s_company_id
               order by d_year, d_moy) rn
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_year = 1999
      group by i_category, i_brand, s_store_name, s_company_id,
               d_year, d_moy)
    select v1.i_category, v1.i_brand, v1.s_store_name, v1.d_year,
           v1.d_moy, v1.avg_monthly_sales, v1.sum_sales
    from v1
    where v1.d_year = 1999
      and v1.avg_monthly_sales > 0
      and abs(v1.sum_sales - v1.avg_monthly_sales) /
          v1.avg_monthly_sales > 0.1
    order by v1.sum_sales - v1.avg_monthly_sales, v1.i_category,
             v1.i_brand, v1.s_store_name, v1.d_moy
    limit 100"""

QUERIES["q57"] = """
    with v1 as (
      select i_category, i_brand, cc_name, d_year, d_moy,
             sum(cs_sales_price) sum_sales,
             avg(sum(cs_sales_price)) over (partition by
               i_category, i_brand, cc_name, d_year)
               avg_monthly_sales,
             rank() over (partition by i_category, i_brand, cc_name
               order by d_year, d_moy) rn
      from item, catalog_sales, date_dim, call_center
      where cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
        and cc_call_center_sk = cs_call_center_sk
        and d_year = 1999
      group by i_category, i_brand, cc_name, d_year, d_moy)
    select v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
           v1.avg_monthly_sales, v1.sum_sales
    from v1
    where v1.d_year = 1999
      and v1.avg_monthly_sales > 0
      and abs(v1.sum_sales - v1.avg_monthly_sales) /
          v1.avg_monthly_sales > 0.1
    order by v1.sum_sales - v1.avg_monthly_sales, v1.i_category,
             v1.i_brand, v1.cc_name, v1.d_moy
    limit 100"""

QUERIES["q58"] = """
    with ss_items as (
      select i_item_id item_id, sum(ss_ext_sales_price) ss_item_rev
      from store_sales, item, date_dim
      where ss_item_sk = i_item_sk
        and d_week_seq = (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 1
                            and d_dom = 3)
        and ss_sold_date_sk = d_date_sk
      group by i_item_id),
    cs_items as (
      select i_item_id item_id, sum(cs_ext_sales_price) cs_item_rev
      from catalog_sales, item, date_dim
      where cs_item_sk = i_item_sk
        and d_week_seq = (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 1
                            and d_dom = 3)
        and cs_sold_date_sk = d_date_sk
      group by i_item_id),
    ws_items as (
      select i_item_id item_id, sum(ws_ext_sales_price) ws_item_rev
      from web_sales, item, date_dim
      where ws_item_sk = i_item_sk
        and d_week_seq = (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 1
                            and d_dom = 3)
        and ws_sold_date_sk = d_date_sk
      group by i_item_id)
    select ss_items.item_id,
           ss_item_rev,
           ss_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
             * 100 ss_dev,
           cs_item_rev,
           cs_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
             * 100 cs_dev,
           ws_item_rev,
           ws_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
             * 100 ws_dev,
           (ss_item_rev + cs_item_rev + ws_item_rev) / 3 average
    from ss_items, cs_items, ws_items
    where ss_items.item_id = cs_items.item_id
      and ss_items.item_id = ws_items.item_id
      and ss_item_rev between 0.9 * cs_item_rev and 1.1 * cs_item_rev
      and ss_item_rev between 0.9 * ws_item_rev and 1.1 * ws_item_rev
      and cs_item_rev between 0.9 * ss_item_rev and 1.1 * ss_item_rev
      and cs_item_rev between 0.9 * ws_item_rev and 1.1 * ws_item_rev
      and ws_item_rev between 0.9 * ss_item_rev and 1.1 * ss_item_rev
      and ws_item_rev between 0.9 * cs_item_rev and 1.1 * cs_item_rev
    order by item_id, ss_item_rev
    limit 100"""

QUERIES["q59"] = """
    with wss as (
      select d_week_seq, ss_store_sk,
             sum(case when d_day_name = 'Sunday' then ss_sales_price
                      else null end) sun_sales,
             sum(case when d_day_name = 'Monday' then ss_sales_price
                      else null end) mon_sales,
             sum(case when d_day_name = 'Tuesday' then ss_sales_price
                      else null end) tue_sales,
             sum(case when d_day_name = 'Wednesday' then ss_sales_price
                      else null end) wed_sales,
             sum(case when d_day_name = 'Thursday' then ss_sales_price
                      else null end) thu_sales,
             sum(case when d_day_name = 'Friday' then ss_sales_price
                      else null end) fri_sales,
             sum(case when d_day_name = 'Saturday' then ss_sales_price
                      else null end) sat_sales
      from store_sales, date_dim
      where d_date_sk = ss_sold_date_sk
      group by d_week_seq, ss_store_sk)
    select s_store_name1, s_store_id1, d_week_seq1,
           sun_sales1 / sun_sales2 r1, mon_sales1 / mon_sales2 r2,
           tue_sales1 / tue_sales2 r3, wed_sales1 / wed_sales2 r4,
           thu_sales1 / thu_sales2 r5, fri_sales1 / fri_sales2 r6,
           sat_sales1 / sat_sales2 r7
    from (select s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
                 s_store_id s_store_id1, sun_sales sun_sales1,
                 mon_sales mon_sales1, tue_sales tue_sales1,
                 wed_sales wed_sales1, thu_sales thu_sales1,
                 fri_sales fri_sales1, sat_sales sat_sales1
          from wss, store, date_dim d
          where d.d_week_seq = wss.d_week_seq
            and ss_store_sk = s_store_sk
            and d_month_seq between 1200 and 1200 + 11) y,
         (select s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
                 s_store_id s_store_id2, sun_sales sun_sales2,
                 mon_sales mon_sales2, tue_sales tue_sales2,
                 wed_sales wed_sales2, thu_sales thu_sales2,
                 fri_sales fri_sales2, sat_sales sat_sales2
          from wss, store, date_dim d
          where d.d_week_seq = wss.d_week_seq
            and ss_store_sk = s_store_sk
            and d_month_seq between 1212 and 1212 + 11) x
    where s_store_id1 = s_store_id2
      and d_week_seq1 = d_week_seq2 - 52
    order by s_store_name1, s_store_id1, d_week_seq1
    limit 100"""

QUERIES["q72"] = """
    select i_item_desc, w_warehouse_name, d1.d_week_seq,
           sum(case when p_promo_sk is null then 1 else 0 end) no_promo,
           sum(case when p_promo_sk is not null then 1 else 0 end) promo,
           count(*) total_cnt
    from catalog_sales
      join inventory on (cs_item_sk = inv_item_sk)
      join warehouse on (w_warehouse_sk = inv_warehouse_sk)
      join item on (i_item_sk = cs_item_sk)
      join customer_demographics on (cs_bill_cdemo_sk = cd_demo_sk)
      join household_demographics on (cs_bill_hdemo_sk = hd_demo_sk)
      join date_dim d1 on (cs_sold_date_sk = d1.d_date_sk)
      join date_dim d2 on (inv_date_sk = d2.d_date_sk)
      join date_dim d3 on (cs_ship_date_sk = d3.d_date_sk)
      left outer join promotion on (cs_promo_sk = p_promo_sk)
    where d1.d_week_seq = d2.d_week_seq
      and inv_quantity_on_hand < cs_quantity
      and d3.d_date_sk > d1.d_date_sk + 3
      and hd_buy_potential = '>10000'
      and d1.d_year = 1999
      and cd_marital_status = 'D'
    group by i_item_desc, w_warehouse_name, d1.d_week_seq
    order by total_cnt desc, i_item_desc, w_warehouse_name,
             d1.d_week_seq
    limit 100"""

QUERIES["q74"] = """
    with year_total as (
      select c_customer_id customer_id, c_first_name customer_first_name,
             c_last_name customer_last_name, d_year as year_,
             sum(ss_net_paid) year_total, 's' sale_type
      from customer, store_sales, date_dim
      where c_customer_sk = ss_customer_sk
        and ss_sold_date_sk = d_date_sk
        and d_year in (1999, 2000)
      group by c_customer_id, c_first_name, c_last_name, d_year
      union all
      select c_customer_id customer_id, c_first_name customer_first_name,
             c_last_name customer_last_name, d_year as year_,
             sum(ws_net_paid) year_total, 'w' sale_type
      from customer, web_sales, date_dim
      where c_customer_sk = ws_bill_customer_sk
        and ws_sold_date_sk = d_date_sk
        and d_year in (1999, 2000)
      group by c_customer_id, c_first_name, c_last_name, d_year)
    select t_s_secyear.customer_id, t_s_secyear.customer_first_name,
           t_s_secyear.customer_last_name
    from year_total t_s_firstyear, year_total t_s_secyear,
         year_total t_w_firstyear, year_total t_w_secyear
    where t_s_secyear.customer_id = t_s_firstyear.customer_id
      and t_s_firstyear.customer_id = t_w_secyear.customer_id
      and t_s_firstyear.customer_id = t_w_firstyear.customer_id
      and t_s_firstyear.sale_type = 's'
      and t_w_firstyear.sale_type = 'w'
      and t_s_secyear.sale_type = 's'
      and t_w_secyear.sale_type = 'w'
      and t_s_firstyear.year_ = 1999
      and t_s_secyear.year_ = 2000
      and t_w_firstyear.year_ = 1999
      and t_w_secyear.year_ = 2000
      and t_s_firstyear.year_total > 0
      and t_w_firstyear.year_total > 0
      and case when t_w_firstyear.year_total > 0
               then t_w_secyear.year_total / t_w_firstyear.year_total
               else null end >
          case when t_s_firstyear.year_total > 0
               then t_s_secyear.year_total / t_s_firstyear.year_total
               else null end
    order by 1, 2, 3
    limit 100"""

QUERIES["q75"] = """
    with all_sales as (
      select d_year, i_brand_id, i_class_id, i_category_id,
             i_manufact_id, sum(sales_cnt) sales_cnt,
             sum(sales_amt) sales_amt
      from (
        select d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               cs_quantity - coalesce(cr_return_quantity, 0) sales_cnt,
               cs_ext_sales_price -
                 coalesce(cr_return_amount, 0.0) sales_amt
        from catalog_sales
          join item on i_item_sk = cs_item_sk
          join date_dim on d_date_sk = cs_sold_date_sk
          left join catalog_returns
            on (cs_order_number = cr_order_number
                and cs_item_sk = cr_item_sk)
        where i_category = 'Books'
        union all
        select d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ss_quantity - coalesce(sr_return_quantity, 0) sales_cnt,
               ss_ext_sales_price -
                 coalesce(sr_return_amt, 0.0) sales_amt
        from store_sales
          join item on i_item_sk = ss_item_sk
          join date_dim on d_date_sk = ss_sold_date_sk
          left join store_returns
            on (ss_ticket_number = sr_ticket_number
                and ss_item_sk = sr_item_sk)
        where i_category = 'Books'
        union all
        select d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ws_quantity - coalesce(wr_return_quantity, 0) sales_cnt,
               ws_ext_sales_price -
                 coalesce(wr_return_amt, 0.0) sales_amt
        from web_sales
          join item on i_item_sk = ws_item_sk
          join date_dim on d_date_sk = ws_sold_date_sk
          left join web_returns
            on (ws_order_number = wr_order_number
                and ws_item_sk = wr_item_sk)
        where i_category = 'Books') sales_detail
      group by d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id)
    select prev_yr.d_year prev_year, curr_yr.d_year year_,
           curr_yr.i_brand_id, curr_yr.i_class_id,
           curr_yr.i_category_id, curr_yr.i_manufact_id,
           prev_yr.sales_cnt prev_yr_cnt, curr_yr.sales_cnt curr_yr_cnt,
           curr_yr.sales_cnt - prev_yr.sales_cnt sales_cnt_diff,
           curr_yr.sales_amt - prev_yr.sales_amt sales_amt_diff
    from all_sales curr_yr, all_sales prev_yr
    where curr_yr.i_brand_id = prev_yr.i_brand_id
      and curr_yr.i_class_id = prev_yr.i_class_id
      and curr_yr.i_category_id = prev_yr.i_category_id
      and curr_yr.i_manufact_id = prev_yr.i_manufact_id
      and curr_yr.d_year = 2002 and prev_yr.d_year = 2001
      and cast(curr_yr.sales_cnt as double) /
          cast(prev_yr.sales_cnt as double) < 0.9
    order by sales_cnt_diff, sales_amt_diff
    limit 100"""

QUERIES["q78"] = """
    with ws as (
      select d_year as ws_sold_year, ws_item_sk,
             ws_bill_customer_sk ws_customer_sk,
             sum(ws_quantity) ws_qty, sum(ws_wholesale_cost) ws_wc,
             sum(ws_sales_price) ws_sp
      from web_sales
        left join web_returns on (wr_order_number = ws_order_number
                                  and ws_item_sk = wr_item_sk)
        join date_dim on ws_sold_date_sk = d_date_sk
      where wr_order_number is null
      group by d_year, ws_item_sk, ws_bill_customer_sk),
    cs as (
      select d_year as cs_sold_year, cs_item_sk,
             cs_bill_customer_sk cs_customer_sk,
             sum(cs_quantity) cs_qty, sum(cs_wholesale_cost) cs_wc,
             sum(cs_sales_price) cs_sp
      from catalog_sales
        left join catalog_returns on (cr_order_number = cs_order_number
                                      and cs_item_sk = cr_item_sk)
        join date_dim on cs_sold_date_sk = d_date_sk
      where cr_order_number is null
      group by d_year, cs_item_sk, cs_bill_customer_sk),
    ss as (
      select d_year as ss_sold_year, ss_item_sk,
             ss_customer_sk,
             sum(ss_quantity) ss_qty, sum(ss_wholesale_cost) ss_wc,
             sum(ss_sales_price) ss_sp
      from store_sales
        left join store_returns on (sr_ticket_number = ss_ticket_number
                                    and ss_item_sk = sr_item_sk)
        join date_dim on ss_sold_date_sk = d_date_sk
      where sr_ticket_number is null
      group by d_year, ss_item_sk, ss_customer_sk)
    select ss_item_sk, round(ss_qty / (coalesce(ws_qty, 0) +
           coalesce(cs_qty, 0)), 2) ratio,
           ss_qty store_qty, ss_wc store_wholesale_cost,
           ss_sp store_sales_price
    from ss
      left join ws on (ws_sold_year = ss_sold_year
                       and ws_item_sk = ss_item_sk
                       and ws_customer_sk = ss_customer_sk)
      left join cs on (cs_sold_year = ss_sold_year
                       and cs_item_sk = ss_item_sk
                       and cs_customer_sk = ss_customer_sk)
    where (coalesce(ws_qty, 0) > 0 or coalesce(cs_qty, 0) > 0)
      and ss_sold_year = 2000
    order by ss_item_sk, ss_qty desc, ss_wc desc, ss_sp desc
    limit 100"""

QUERIES["q83"] = """
    with sr_items as (
      select i_item_id item_id, sum(sr_return_quantity) sr_item_qty
      from store_returns, item, date_dim
      where sr_item_sk = i_item_sk
        and d_date in (select d_date from date_dim
                       where d_week_seq in
                         (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 6
                            and d_dom = 30))
        and sr_returned_date_sk = d_date_sk
      group by i_item_id),
    cr_items as (
      select i_item_id item_id, sum(cr_return_quantity) cr_item_qty
      from catalog_returns, item, date_dim
      where cr_item_sk = i_item_sk
        and d_date in (select d_date from date_dim
                       where d_week_seq in
                         (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 6
                            and d_dom = 30))
        and cr_returned_date_sk = d_date_sk
      group by i_item_id),
    wr_items as (
      select i_item_id item_id, sum(wr_return_quantity) wr_item_qty
      from web_returns, item, date_dim
      where wr_item_sk = i_item_sk
        and d_date in (select d_date from date_dim
                       where d_week_seq in
                         (select d_week_seq from date_dim
                          where d_year = 2000 and d_moy = 6
                            and d_dom = 30))
        and wr_returned_date_sk = d_date_sk
      group by i_item_id)
    select sr_items.item_id, sr_item_qty,
           sr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
             * 100 sr_dev,
           cr_item_qty,
           cr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
             * 100 cr_dev,
           wr_item_qty,
           wr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
             * 100 wr_dev,
           (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 average
    from sr_items, cr_items, wr_items
    where sr_items.item_id = cr_items.item_id
      and sr_items.item_id = wr_items.item_id
    order by sr_items.item_id, sr_item_qty
    limit 100"""

QUERIES["q85"] = """
    select substring(r_reason_desc, 1, 20) reason,
           avg(ws_quantity) aq, avg(wr_refunded_cash) arc,
           avg(wr_fee) af
    from web_sales, web_returns, web_page, customer_demographics cd1,
         customer_demographics cd2, customer_address, date_dim, reason
    where ws_web_page_sk = wp_web_page_sk
      and ws_item_sk = wr_item_sk
      and ws_order_number = wr_order_number
      and ws_sold_date_sk = d_date_sk and d_year = 2000
      and cd1.cd_demo_sk = wr_refunded_cdemo_sk
      and cd2.cd_demo_sk = wr_returning_cdemo_sk
      and ca_address_sk = wr_refunded_addr_sk
      and r_reason_sk = wr_reason_sk
      and ((cd1.cd_marital_status = 'M'
            and cd1.cd_marital_status = cd2.cd_marital_status
            and cd1.cd_education_status = 'Advanced Degree'
            and cd1.cd_education_status = cd2.cd_education_status
            and ws_sales_price between 100.00 and 150.00)
        or (cd1.cd_marital_status = 'S'
            and cd1.cd_marital_status = cd2.cd_marital_status
            and cd1.cd_education_status = 'College'
            and cd1.cd_education_status = cd2.cd_education_status
            and ws_sales_price between 50.00 and 100.00)
        or (cd1.cd_marital_status = 'W'
            and cd1.cd_marital_status = cd2.cd_marital_status
            and cd1.cd_education_status = '2 yr Degree'
            and cd1.cd_education_status = cd2.cd_education_status
            and ws_sales_price between 150.00 and 200.00))
      and ((ca_country = 'United States'
            and ca_state in ('IN', 'OH', 'NJ')
            and ws_net_profit between 100 and 200)
        or (ca_country = 'United States'
            and ca_state in ('WI', 'CT', 'KY')
            and ws_net_profit between 150 and 300)
        or (ca_country = 'United States'
            and ca_state in ('LA', 'IA', 'AR')
            and ws_net_profit between 50 and 250))
    group by r_reason_desc
    order by reason, aq, arc, af
    limit 100"""

QUERIES["q95"] = """
    with ws_wh as (
      select ws1.ws_order_number won, ws1.ws_warehouse_sk wh1,
             ws2.ws_warehouse_sk wh2
      from web_sales ws1, web_sales ws2
      where ws1.ws_order_number = ws2.ws_order_number
        and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
    select count(distinct ws1.ws_order_number) as order_count,
           sum(ws1.ws_ext_ship_cost) as total_shipping_cost,
           sum(ws1.ws_net_profit) as total_net_profit
    from web_sales ws1, date_dim, customer_address, web_site
    where d_year = 1999 and d_moy between 2 and 3
      and ws1.ws_ship_date_sk = d_date_sk
      and ws1.ws_ship_addr_sk = ca_address_sk
      and ca_state = 'CA'
      and ws1.ws_web_site_sk = web_site_sk
      and web_name = 'site_0'
      and ws1.ws_order_number in (select won from ws_wh)
      and ws1.ws_order_number in (select wr_order_number
                                  from web_returns, ws_wh
                                  where wr_order_number = ws_wh.won)
    limit 100"""

# --------------------------------------------------------------------------
# round-4 additions: the 24 hardest plan shapes (multi-level CTE chains,
# INTERSECT-in-CTE, rollup+window, full-outer over windows, NOT-EXISTS
# pairs, the giant q64 multi-join).  Reference surface:
# integration_tests qa_nightly_select_test + official tpcds query dir.
# --------------------------------------------------------------------------

QUERIES["q2"] = """
    with wscs as (
      select ws_sold_date_sk sold_date_sk,
             ws_ext_sales_price sales_price
      from web_sales
      union all
      select cs_sold_date_sk sold_date_sk,
             cs_ext_sales_price sales_price
      from catalog_sales),
    wswscs as (
      select d_week_seq,
             sum(case when (d_day_name = 'Sunday')
                 then sales_price else null end) sun_sales,
             sum(case when (d_day_name = 'Monday')
                 then sales_price else null end) mon_sales,
             sum(case when (d_day_name = 'Tuesday')
                 then sales_price else null end) tue_sales,
             sum(case when (d_day_name = 'Wednesday')
                 then sales_price else null end) wed_sales,
             sum(case when (d_day_name = 'Thursday')
                 then sales_price else null end) thu_sales,
             sum(case when (d_day_name = 'Friday')
                 then sales_price else null end) fri_sales,
             sum(case when (d_day_name = 'Saturday')
                 then sales_price else null end) sat_sales
      from wscs, date_dim
      where d_date_sk = sold_date_sk
      group by d_week_seq)
    select d_week_seq1,
           round(sun_sales1 / sun_sales2, 2),
           round(mon_sales1 / mon_sales2, 2),
           round(tue_sales1 / tue_sales2, 2),
           round(wed_sales1 / wed_sales2, 2),
           round(thu_sales1 / thu_sales2, 2),
           round(fri_sales1 / fri_sales2, 2),
           round(sat_sales1 / sat_sales2, 2)
    from (select wswscs.d_week_seq d_week_seq1, sun_sales sun_sales1,
                 mon_sales mon_sales1, tue_sales tue_sales1,
                 wed_sales wed_sales1, thu_sales thu_sales1,
                 fri_sales fri_sales1, sat_sales sat_sales1
          from wswscs, date_dim
          where date_dim.d_week_seq = wswscs.d_week_seq
            and d_year = 2000) y,
         (select wswscs.d_week_seq d_week_seq2, sun_sales sun_sales2,
                 mon_sales mon_sales2, tue_sales tue_sales2,
                 wed_sales wed_sales2, thu_sales thu_sales2,
                 fri_sales fri_sales2, sat_sales sat_sales2
          from wswscs, date_dim
          where date_dim.d_week_seq = wswscs.d_week_seq
            and d_year = 2000 + 1) z
    where d_week_seq1 = d_week_seq2 - 53
    order by d_week_seq1"""

QUERIES["q4"] = """
    with year_total as (
      select c_customer_id customer_id, c_first_name customer_first_name,
             c_last_name customer_last_name,
             c_preferred_cust_flag customer_preferred_cust_flag,
             c_birth_country customer_birth_country,
             c_login customer_login,
             c_email_address customer_email_address,
             d_year dyear,
             sum(((ss_ext_list_price - ss_ext_wholesale_cost
                   - ss_ext_discount_amt) + ss_ext_sales_price) / 2)
               year_total,
             's' sale_type
      from customer, store_sales, date_dim
      where c_customer_sk = ss_customer_sk
        and ss_sold_date_sk = d_date_sk
      group by c_customer_id, c_first_name, c_last_name,
               c_preferred_cust_flag, c_birth_country, c_login,
               c_email_address, d_year
      union all
      select c_customer_id customer_id, c_first_name customer_first_name,
             c_last_name customer_last_name,
             c_preferred_cust_flag customer_preferred_cust_flag,
             c_birth_country customer_birth_country,
             c_login customer_login,
             c_email_address customer_email_address,
             d_year dyear,
             sum((((cs_ext_list_price - cs_ext_wholesale_cost
                    - cs_ext_discount_amt) + cs_ext_sales_price) / 2))
               year_total,
             'c' sale_type
      from customer, catalog_sales, date_dim
      where c_customer_sk = cs_bill_customer_sk
        and cs_sold_date_sk = d_date_sk
      group by c_customer_id, c_first_name, c_last_name,
               c_preferred_cust_flag, c_birth_country, c_login,
               c_email_address, d_year
      union all
      select c_customer_id customer_id, c_first_name customer_first_name,
             c_last_name customer_last_name,
             c_preferred_cust_flag customer_preferred_cust_flag,
             c_birth_country customer_birth_country,
             c_login customer_login,
             c_email_address customer_email_address,
             d_year dyear,
             sum((((ws_ext_list_price - ws_ext_wholesale_cost
                    - ws_ext_discount_amt) + ws_ext_sales_price) / 2))
               year_total,
             'w' sale_type
      from customer, web_sales, date_dim
      where c_customer_sk = ws_bill_customer_sk
        and ws_sold_date_sk = d_date_sk
      group by c_customer_id, c_first_name, c_last_name,
               c_preferred_cust_flag, c_birth_country, c_login,
               c_email_address, d_year)
    select t_s_secyear.customer_id, t_s_secyear.customer_first_name,
           t_s_secyear.customer_last_name,
           t_s_secyear.customer_preferred_cust_flag
    from year_total t_s_firstyear, year_total t_s_secyear,
         year_total t_c_firstyear, year_total t_c_secyear,
         year_total t_w_firstyear, year_total t_w_secyear
    where t_s_secyear.customer_id = t_s_firstyear.customer_id
      and t_s_firstyear.customer_id = t_c_secyear.customer_id
      and t_s_firstyear.customer_id = t_c_firstyear.customer_id
      and t_s_firstyear.customer_id = t_w_firstyear.customer_id
      and t_s_firstyear.customer_id = t_w_secyear.customer_id
      and t_s_firstyear.sale_type = 's'
      and t_c_firstyear.sale_type = 'c'
      and t_w_firstyear.sale_type = 'w'
      and t_s_secyear.sale_type = 's'
      and t_c_secyear.sale_type = 'c'
      and t_w_secyear.sale_type = 'w'
      and t_s_firstyear.dyear = 2001
      and t_s_secyear.dyear = 2001 + 1
      and t_c_firstyear.dyear = 2001
      and t_c_secyear.dyear = 2001 + 1
      and t_w_firstyear.dyear = 2001
      and t_w_secyear.dyear = 2001 + 1
      and t_s_firstyear.year_total > 0
      and t_c_firstyear.year_total > 0
      and t_w_firstyear.year_total > 0
      and case when t_c_firstyear.year_total > 0
          then t_c_secyear.year_total / t_c_firstyear.year_total
          else null end
        > case when t_s_firstyear.year_total > 0
          then t_s_secyear.year_total / t_s_firstyear.year_total
          else null end
      and case when t_c_firstyear.year_total > 0
          then t_c_secyear.year_total / t_c_firstyear.year_total
          else null end
        > case when t_w_firstyear.year_total > 0
          then t_w_secyear.year_total / t_w_firstyear.year_total
          else null end
    order by t_s_secyear.customer_id, t_s_secyear.customer_first_name,
             t_s_secyear.customer_last_name,
             t_s_secyear.customer_preferred_cust_flag
    limit 100"""

QUERIES["q5"] = """
    with ssr as (
      select s_store_id,
             sum(sales_price) as sales,
             sum(profit) as profit,
             sum(return_amt) as returns_amt,
             sum(net_loss) as profit_loss
      from (select ss_store_sk as store_sk,
                   ss_sold_date_sk as date_sk,
                   ss_ext_sales_price as sales_price,
                   ss_net_profit as profit,
                   cast(0 as double) as return_amt,
                   cast(0 as double) as net_loss
            from store_sales
            union all
            select sr_store_sk as store_sk,
                   sr_returned_date_sk as date_sk,
                   cast(0 as double) as sales_price,
                   cast(0 as double) as profit,
                   sr_return_amt as return_amt,
                   sr_net_loss as net_loss
            from store_returns) salesreturns, date_dim, store
      where date_sk = d_date_sk
        and d_date between date '2000-08-23'
                       and date '2000-08-23' + interval 14 days
        and store_sk = s_store_sk
      group by s_store_id),
    csr as (
      select cp_catalog_page_id,
             sum(sales_price) as sales,
             sum(profit) as profit,
             sum(return_amt) as returns_amt,
             sum(net_loss) as profit_loss
      from (select cs_catalog_page_sk as page_sk,
                   cs_sold_date_sk as date_sk,
                   cs_ext_sales_price as sales_price,
                   cs_net_profit as profit,
                   cast(0 as double) as return_amt,
                   cast(0 as double) as net_loss
            from catalog_sales
            union all
            select cr_catalog_page_sk as page_sk,
                   cr_returned_date_sk as date_sk,
                   cast(0 as double) as sales_price,
                   cast(0 as double) as profit,
                   cr_return_amount as return_amt,
                   cr_net_loss as net_loss
            from catalog_returns) salesreturns, date_dim, catalog_page
      where date_sk = d_date_sk
        and d_date between date '2000-08-23'
                       and date '2000-08-23' + interval 14 days
        and page_sk = cp_catalog_page_sk
      group by cp_catalog_page_id),
    wsr as (
      select web_site_id,
             sum(sales_price) as sales,
             sum(profit) as profit,
             sum(return_amt) as returns_amt,
             sum(net_loss) as profit_loss
      from (select ws_web_site_sk as wsr_web_site_sk,
                   ws_sold_date_sk as date_sk,
                   ws_ext_sales_price as sales_price,
                   ws_net_profit as profit,
                   cast(0 as double) as return_amt,
                   cast(0 as double) as net_loss
            from web_sales
            union all
            select ws_web_site_sk as wsr_web_site_sk,
                   wr_returned_date_sk as date_sk,
                   cast(0 as double) as sales_price,
                   cast(0 as double) as profit,
                   wr_return_amt as return_amt,
                   wr_net_loss as net_loss
            from web_returns
            left outer join web_sales
              on (wr_item_sk = ws_item_sk
                  and wr_order_number = ws_order_number))
           salesreturns, date_dim, web_site
      where date_sk = d_date_sk
        and d_date between date '2000-08-23'
                       and date '2000-08-23' + interval 14 days
        and wsr_web_site_sk = web_site_sk
      group by web_site_id)
    select channel, id, sum(sales) as sales,
           sum(returns_amt) as returns_amt, sum(profit) as profit
    from (select 'store channel' as channel,
                 'store' || s_store_id as id,
                 sales, returns_amt, profit - profit_loss as profit
          from ssr
          union all
          select 'catalog channel' as channel,
                 'catalog_page' || cp_catalog_page_id as id,
                 sales, returns_amt, profit - profit_loss as profit
          from csr
          union all
          select 'web channel' as channel,
                 'web_site' || web_site_id as id,
                 sales, returns_amt, profit - profit_loss as profit
          from wsr) x
    group by rollup(channel, id)
    order by channel, id
    limit 100"""

QUERIES["q8"] = """
    select s_store_name, sum(ss_net_profit)
    from store_sales, date_dim, store,
         (select ca_zip from (
            select substring(ca_zip, 1, 5) ca_zip
            from customer_address
            where substring(ca_zip, 1, 2) in
              ('24', '35', '46', '57', '68', '79', '80', '91', '12',
               '23', '34', '45', '56', '67', '78', '89', '90', '10')
            intersect
            select ca_zip from (
              select substring(ca_zip, 1, 5) ca_zip, count(*) cnt
              from customer_address, customer
              where ca_address_sk = c_current_addr_sk
                and c_preferred_cust_flag = 'Y'
              group by ca_zip
              having count(*) > 1) a1) a2) v1
    where ss_store_sk = s_store_sk
      and ss_sold_date_sk = d_date_sk
      and d_qoy = 2 and d_year = 1998
      and substring(s_zip, 1, 2) = substring(v1.ca_zip, 1, 2)
    group by s_store_name
    order by s_store_name
    limit 100"""

QUERIES["q10"] = """
    select cd_gender, cd_marital_status, cd_education_status,
           count(*) cnt1, cd_purchase_estimate, count(*) cnt2,
           cd_credit_rating, count(*) cnt3, cd_dep_count, count(*) cnt4,
           cd_dep_employed_count, count(*) cnt5,
           cd_dep_college_count, count(*) cnt6
    from customer c, customer_address ca, customer_demographics
    where c.c_current_addr_sk = ca.ca_address_sk
      and ca_county in ('Williamson County', 'Ziebach County',
                        'Walker County', 'Rush County')
      and cd_demo_sk = c.c_current_cdemo_sk
      and exists (select * from store_sales, date_dim
                  where c.c_customer_sk = ss_customer_sk
                    and ss_sold_date_sk = d_date_sk
                    and d_year = 2002 and d_moy between 1 and 1 + 3)
      and (exists (select * from web_sales, date_dim
                   where c.c_customer_sk = ws_bill_customer_sk
                     and ws_sold_date_sk = d_date_sk
                     and d_year = 2002 and d_moy between 1 and 1 + 3)
           or exists (select * from catalog_sales, date_dim
                      where c.c_customer_sk = cs_bill_customer_sk
                        and cs_sold_date_sk = d_date_sk
                        and d_year = 2002 and d_moy between 1 and 1 + 3))
    group by cd_gender, cd_marital_status, cd_education_status,
             cd_purchase_estimate, cd_credit_rating, cd_dep_count,
             cd_dep_employed_count, cd_dep_college_count
    order by cd_gender, cd_marital_status, cd_education_status,
             cd_purchase_estimate, cd_credit_rating, cd_dep_count,
             cd_dep_employed_count, cd_dep_college_count
    limit 100"""

QUERIES["q11"] = """
    with year_total as (
      select c_customer_id customer_id, c_first_name customer_first_name,
             c_last_name customer_last_name,
             c_preferred_cust_flag customer_preferred_cust_flag,
             c_birth_country customer_birth_country,
             c_login customer_login,
             c_email_address customer_email_address,
             d_year dyear,
             sum(ss_ext_list_price - ss_ext_discount_amt) year_total,
             's' sale_type
      from customer, store_sales, date_dim
      where c_customer_sk = ss_customer_sk
        and ss_sold_date_sk = d_date_sk
      group by c_customer_id, c_first_name, c_last_name,
               c_preferred_cust_flag, c_birth_country, c_login,
               c_email_address, d_year
      union all
      select c_customer_id customer_id, c_first_name customer_first_name,
             c_last_name customer_last_name,
             c_preferred_cust_flag customer_preferred_cust_flag,
             c_birth_country customer_birth_country,
             c_login customer_login,
             c_email_address customer_email_address,
             d_year dyear,
             sum(ws_ext_list_price - ws_ext_discount_amt) year_total,
             'w' sale_type
      from customer, web_sales, date_dim
      where c_customer_sk = ws_bill_customer_sk
        and ws_sold_date_sk = d_date_sk
      group by c_customer_id, c_first_name, c_last_name,
               c_preferred_cust_flag, c_birth_country, c_login,
               c_email_address, d_year)
    select t_s_secyear.customer_id, t_s_secyear.customer_first_name,
           t_s_secyear.customer_last_name,
           t_s_secyear.customer_preferred_cust_flag
    from year_total t_s_firstyear, year_total t_s_secyear,
         year_total t_w_firstyear, year_total t_w_secyear
    where t_s_secyear.customer_id = t_s_firstyear.customer_id
      and t_s_firstyear.customer_id = t_w_secyear.customer_id
      and t_s_firstyear.customer_id = t_w_firstyear.customer_id
      and t_s_firstyear.sale_type = 's'
      and t_w_firstyear.sale_type = 'w'
      and t_s_secyear.sale_type = 's'
      and t_w_secyear.sale_type = 'w'
      and t_s_firstyear.dyear = 2001
      and t_s_secyear.dyear = 2001 + 1
      and t_w_firstyear.dyear = 2001
      and t_w_secyear.dyear = 2001 + 1
      and t_s_firstyear.year_total > 0
      and t_w_firstyear.year_total > 0
      and case when t_w_firstyear.year_total > 0
          then t_w_secyear.year_total / t_w_firstyear.year_total
          else 0.0 end
        > case when t_s_firstyear.year_total > 0
          then t_s_secyear.year_total / t_s_firstyear.year_total
          else 0.0 end
    order by t_s_secyear.customer_id, t_s_secyear.customer_first_name,
             t_s_secyear.customer_last_name,
             t_s_secyear.customer_preferred_cust_flag
    limit 100"""

QUERIES["q14"] = """
    with cross_items as (
      select i_item_sk ss_item_sk
      from item,
        (select iss.i_brand_id brand_id, iss.i_class_id class_id,
                iss.i_category_id category_id
         from store_sales, item iss, date_dim d1
         where ss_item_sk = iss.i_item_sk
           and ss_sold_date_sk = d1.d_date_sk
           and d1.d_year between 1999 and 1999 + 2
         intersect
         select ics.i_brand_id, ics.i_class_id, ics.i_category_id
         from catalog_sales, item ics, date_dim d2
         where cs_item_sk = ics.i_item_sk
           and cs_sold_date_sk = d2.d_date_sk
           and d2.d_year between 1999 and 1999 + 2
         intersect
         select iws.i_brand_id, iws.i_class_id, iws.i_category_id
         from web_sales, item iws, date_dim d3
         where ws_item_sk = iws.i_item_sk
           and ws_sold_date_sk = d3.d_date_sk
           and d3.d_year between 1999 and 1999 + 2) x
      where i_brand_id = brand_id
        and i_class_id = class_id
        and i_category_id = category_id),
    avg_sales as (
      select avg(quantity * list_price) average_sales
      from (select ss_quantity quantity, ss_list_price list_price
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk
              and d_year between 1999 and 1999 + 2
            union all
            select cs_quantity quantity, cs_list_price list_price
            from catalog_sales, date_dim
            where cs_sold_date_sk = d_date_sk
              and d_year between 1999 and 1999 + 2
            union all
            select ws_quantity quantity, ws_list_price list_price
            from web_sales, date_dim
            where ws_sold_date_sk = d_date_sk
              and d_year between 1999 and 1999 + 2) x)
    select channel, i_brand_id, i_class_id, i_category_id,
           sum(sales), sum(number_sales)
    from (select 'store' channel, i_brand_id, i_class_id, i_category_id,
                 sum(ss_quantity * ss_list_price) sales,
                 count(*) number_sales
          from store_sales, item, date_dim
          where ss_item_sk in (select ss_item_sk from cross_items)
            and ss_item_sk = i_item_sk
            and ss_sold_date_sk = d_date_sk
            and d_year = 1999 + 2 and d_moy = 11
          group by i_brand_id, i_class_id, i_category_id
          having sum(ss_quantity * ss_list_price) >
                 (select average_sales from avg_sales)
          union all
          select 'catalog' channel, i_brand_id, i_class_id,
                 i_category_id,
                 sum(cs_quantity * cs_list_price) sales,
                 count(*) number_sales
          from catalog_sales, item, date_dim
          where cs_item_sk in (select ss_item_sk from cross_items)
            and cs_item_sk = i_item_sk
            and cs_sold_date_sk = d_date_sk
            and d_year = 1999 + 2 and d_moy = 11
          group by i_brand_id, i_class_id, i_category_id
          having sum(cs_quantity * cs_list_price) >
                 (select average_sales from avg_sales)
          union all
          select 'web' channel, i_brand_id, i_class_id, i_category_id,
                 sum(ws_quantity * ws_list_price) sales,
                 count(*) number_sales
          from web_sales, item, date_dim
          where ws_item_sk in (select ss_item_sk from cross_items)
            and ws_item_sk = i_item_sk
            and ws_sold_date_sk = d_date_sk
            and d_year = 1999 + 2 and d_moy = 11
          group by i_brand_id, i_class_id, i_category_id
          having sum(ws_quantity * ws_list_price) >
                 (select average_sales from avg_sales)) y
    group by rollup(channel, i_brand_id, i_class_id, i_category_id)
    order by channel, i_brand_id, i_class_id, i_category_id
    limit 100"""

QUERIES["q16"] = """
    select count(distinct cs_order_number) as order_count,
           sum(cs_ext_ship_cost) as total_shipping_cost,
           sum(cs_net_profit) as total_net_profit
    from catalog_sales cs1, date_dim, customer_address, call_center
    where d_date between date '2002-02-01'
                     and date '2002-02-01' + interval 60 days
      and cs1.cs_ship_date_sk = d_date_sk
      and cs1.cs_ship_addr_sk = ca_address_sk
      and ca_state = 'GA'
      and cs1.cs_call_center_sk = cc_call_center_sk
      and cc_county in ('Williamson County')
      and exists (select * from catalog_sales cs2
                  where cs1.cs_order_number = cs2.cs_order_number
                    and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
      and not exists (select * from catalog_returns cr1
                      where cs1.cs_order_number = cr1.cr_order_number)
    order by count(distinct cs_order_number)
    limit 100"""

QUERIES["q17"] = """
    select i_item_id, i_item_desc, s_state,
           count(ss_quantity) as store_sales_quantitycount,
           avg(ss_quantity) as store_sales_quantityave,
           stddev_samp(ss_quantity) as store_sales_quantitystdev,
           stddev_samp(ss_quantity) / avg(ss_quantity)
             as store_sales_quantitycov,
           count(sr_return_quantity) as store_returns_quantitycount,
           avg(sr_return_quantity) as store_returns_quantityave,
           stddev_samp(sr_return_quantity) as store_returns_quantitystdev,
           stddev_samp(sr_return_quantity) / avg(sr_return_quantity)
             as store_returns_quantitycov,
           count(cs_quantity) as catalog_sales_quantitycount,
           avg(cs_quantity) as catalog_sales_quantityave,
           stddev_samp(cs_quantity) as catalog_sales_quantitystdev,
           stddev_samp(cs_quantity) / avg(cs_quantity)
             as catalog_sales_quantitycov
    from store_sales, store_returns, catalog_sales,
         date_dim d1, date_dim d2, date_dim d3, store, item
    where d1.d_quarter_name = '2001Q1'
      and d1.d_date_sk = ss_sold_date_sk
      and i_item_sk = ss_item_sk
      and s_store_sk = ss_store_sk
      and ss_customer_sk = sr_customer_sk
      and ss_item_sk = sr_item_sk
      and ss_ticket_number = sr_ticket_number
      and sr_returned_date_sk = d2.d_date_sk
      and d2.d_quarter_name in ('2001Q1', '2001Q2', '2001Q3')
      and sr_customer_sk = cs_bill_customer_sk
      and sr_item_sk = cs_item_sk
      and cs_sold_date_sk = d3.d_date_sk
      and d3.d_quarter_name in ('2001Q1', '2001Q2', '2001Q3')
    group by i_item_id, i_item_desc, s_state
    order by i_item_id, i_item_desc, s_state
    limit 100"""

QUERIES["q23"] = """
    with frequent_ss_items as (
      select substring(i_item_desc, 1, 30) itemdesc, i_item_sk item_sk,
             d_date solddate, count(*) cnt
      from store_sales, date_dim, item
      where ss_sold_date_sk = d_date_sk
        and ss_item_sk = i_item_sk
        and d_year in (2000, 2000 + 1, 2000 + 2, 2000 + 3)
      group by substring(i_item_desc, 1, 30), i_item_sk, d_date
      having count(*) > 4),
    max_store_sales as (
      select max(csales) tpcds_cmax
      from (select c_customer_sk,
                   sum(ss_quantity * ss_sales_price) csales
            from store_sales, customer, date_dim
            where ss_customer_sk = c_customer_sk
              and ss_sold_date_sk = d_date_sk
              and d_year in (2000, 2000 + 1, 2000 + 2, 2000 + 3)
            group by c_customer_sk) t),
    best_ss_customer as (
      select c_customer_sk, sum(ss_quantity * ss_sales_price) ssales
      from store_sales, customer
      where ss_customer_sk = c_customer_sk
      group by c_customer_sk
      having sum(ss_quantity * ss_sales_price) >
             (50 / 100.0) * (select tpcds_cmax from max_store_sales))
    select sum(sales)
    from (select cs_quantity * cs_list_price sales
          from catalog_sales, date_dim
          where d_year = 2000 and d_moy = 2
            and cs_sold_date_sk = d_date_sk
            and cs_item_sk in (select item_sk from frequent_ss_items)
            and cs_bill_customer_sk in
                (select c_customer_sk from best_ss_customer)
          union all
          select ws_quantity * ws_list_price sales
          from web_sales, date_dim
          where d_year = 2000 and d_moy = 2
            and ws_sold_date_sk = d_date_sk
            and ws_item_sk in (select item_sk from frequent_ss_items)
            and ws_bill_customer_sk in
                (select c_customer_sk from best_ss_customer)) x
    limit 100"""

QUERIES["q24"] = """
    with ssales as (
      select c_last_name, c_first_name, s_store_name, ca_state, s_state,
             i_color, i_current_price, i_manager_id, i_units, i_size,
             sum(ss_net_paid) netpaid
      from store_sales, store_returns, store, item, customer,
           customer_address
      where ss_ticket_number = sr_ticket_number
        and ss_item_sk = sr_item_sk
        and ss_customer_sk = c_customer_sk
        and ss_item_sk = i_item_sk
        and ss_store_sk = s_store_sk
        and c_birth_country = upper(ca_country)
        and s_zip = ca_zip
        and s_market_id = 8
      group by c_last_name, c_first_name, s_store_name, ca_state,
               s_state, i_color, i_current_price, i_manager_id,
               i_units, i_size)
    select c_last_name, c_first_name, s_store_name, sum(netpaid) paid
    from ssales
    where i_color = 'red'
    group by c_last_name, c_first_name, s_store_name
    having sum(netpaid) > (select 0.05 * avg(netpaid) from ssales)
    order by c_last_name, c_first_name, s_store_name
    limit 100"""

QUERIES["q39"] = """
    with inv as (
      select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
             stdev, mean,
             case when mean = 0 then null else stdev / mean end cov
      from (select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
                   stddev_samp(inv_quantity_on_hand) stdev,
                   avg(inv_quantity_on_hand) mean
            from inventory, item, warehouse, date_dim
            where inv_item_sk = i_item_sk
              and inv_warehouse_sk = w_warehouse_sk
              and inv_date_sk = d_date_sk
              and d_year = 2001
            group by w_warehouse_name, w_warehouse_sk, i_item_sk,
                     d_moy) foo
      where case when mean = 0 then 0 else stdev / mean end > 0.5)
    select inv1.w_warehouse_sk wsk1, inv1.i_item_sk isk1,
           inv1.d_moy moy1, inv1.mean mean1, inv1.cov cov1,
           inv2.w_warehouse_sk wsk2, inv2.i_item_sk isk2,
           inv2.d_moy moy2, inv2.mean mean2, inv2.cov cov2
    from inv inv1, inv inv2
    where inv1.i_item_sk = inv2.i_item_sk
      and inv1.w_warehouse_sk = inv2.w_warehouse_sk
      and inv1.d_moy = 1
      and inv2.d_moy = 1 + 1
    order by wsk1, isk1, moy1, mean1, cov1, wsk2, isk2, moy2, mean2,
             cov2
    limit 100"""

QUERIES["q41"] = """
    select distinct i_product_name
    from item i1
    where i_manufact_id between 700 and 700 + 40
      and (select count(*) as item_cnt
           from item
           where (i_manufact = i1.i_manufact
                  and ((i_category = 'Women'
                        and (i_color = 'red' or i_color = 'blue')
                        and (i_units = 'Each' or i_units = 'Dozen')
                        and (i_size = 'small' or i_size = 'medium'))
                       or (i_category = 'Women'
                           and (i_color = 'green' or i_color = 'yellow')
                           and (i_units = 'Case' or i_units = 'Pallet')
                           and (i_size = 'large'
                                or i_size = 'extra large'))
                       or (i_category = 'Men'
                           and (i_color = 'purple' or i_color = 'orange')
                           and (i_units = 'Each' or i_units = 'Case')
                           and (i_size = 'petite' or i_size = 'economy'))
                       or (i_category = 'Men'
                           and (i_color = 'white' or i_color = 'black')
                           and (i_units = 'Dozen' or i_units = 'Pallet')
                           and (i_size = 'small' or i_size = 'medium'))))
              or (i_manufact = i1.i_manufact
                  and ((i_category = 'Sports'
                        and (i_color = 'red' or i_color = 'green')
                        and (i_units = 'Each' or i_units = 'Dozen')
                        and (i_size = 'small' or i_size = 'large'))
                       or (i_category = 'Music'
                           and (i_color = 'blue' or i_color = 'white')
                           and (i_units = 'Case' or i_units = 'Each')
                           and (i_size = 'medium' or i_size = 'petite'))
                       or (i_category = 'Books'
                           and (i_color = 'yellow' or i_color = 'black')
                           and (i_units = 'Dozen' or i_units = 'Pallet')
                           and (i_size = 'economy' or i_size = 'small'))
                       or (i_category = 'Home'
                           and (i_color = 'orange' or i_color = 'purple')
                           and (i_units = 'Case' or i_units = 'Pallet')
                           and (i_size = 'large'
                                or i_size = 'extra large'))))) > 0
    order by i_product_name
    limit 100"""

QUERIES["q44"] = """
    select asceding.rnk, i1.i_product_name best_performing,
           i2.i_product_name worst_performing
    from (select * from (
            select item_sk, rank() over (order by rank_col asc) rnk
            from (select ss_item_sk item_sk,
                         avg(ss_net_profit) rank_col
                  from store_sales ss1
                  where ss_store_sk = 4
                  group by ss_item_sk
                  having avg(ss_net_profit) > 0.9 *
                    (select avg(ss_net_profit) rank_col
                     from store_sales
                     where ss_store_sk = 4
                       and ss_hdemo_sk is null
                     group by ss_store_sk)) v1) v11
          where rnk < 11) asceding,
         (select * from (
            select item_sk, rank() over (order by rank_col desc) rnk
            from (select ss_item_sk item_sk,
                         avg(ss_net_profit) rank_col
                  from store_sales ss1
                  where ss_store_sk = 4
                  group by ss_item_sk
                  having avg(ss_net_profit) > 0.9 *
                    (select avg(ss_net_profit) rank_col
                     from store_sales
                     where ss_store_sk = 4
                       and ss_hdemo_sk is null
                     group by ss_store_sk)) v2) v21
          where rnk < 11) descending,
         item i1, item i2
    where asceding.rnk = descending.rnk
      and i1.i_item_sk = asceding.item_sk
      and i2.i_item_sk = descending.item_sk
    order by asceding.rnk
    limit 100"""

QUERIES["q49"] = """
    select channel, item, return_ratio, return_rank, currency_rank
    from (select 'web' as channel, web.item, web.return_ratio,
                 web.return_rank, web.currency_rank
          from (select item, return_ratio, currency_ratio,
                       rank() over (order by return_ratio) as return_rank,
                       rank() over (order by currency_ratio)
                         as currency_rank
                from (select ws.ws_item_sk as item,
                             cast(sum(coalesce(wr.wr_return_quantity, 0))
                                  as double) /
                             cast(sum(coalesce(ws.ws_quantity, 0))
                                  as double) as return_ratio,
                             cast(sum(coalesce(wr.wr_return_amt, 0))
                                  as double) /
                             cast(sum(coalesce(ws.ws_net_paid, 0))
                                  as double) as currency_ratio
                      from web_sales ws
                      left outer join web_returns wr
                        on (ws.ws_order_number = wr.wr_order_number
                            and ws.ws_item_sk = wr.wr_item_sk),
                      date_dim
                      where wr.wr_return_amt > 100
                        and ws.ws_net_profit > 1
                        and ws.ws_net_paid > 0
                        and ws.ws_quantity > 0
                        and ws_sold_date_sk = d_date_sk
                        and d_year = 2001 and d_moy = 12
                      group by ws.ws_item_sk) in_web) web
          where web.return_rank <= 10 or web.currency_rank <= 10
          union all
          select 'catalog' as channel, catalog.item,
                 catalog.return_ratio, catalog.return_rank,
                 catalog.currency_rank
          from (select item, return_ratio, currency_ratio,
                       rank() over (order by return_ratio) as return_rank,
                       rank() over (order by currency_ratio)
                         as currency_rank
                from (select cs.cs_item_sk as item,
                             cast(sum(coalesce(cr.cr_return_quantity, 0))
                                  as double) /
                             cast(sum(coalesce(cs.cs_quantity, 0))
                                  as double) as return_ratio,
                             cast(sum(coalesce(cr.cr_return_amount, 0))
                                  as double) /
                             cast(sum(coalesce(cs.cs_net_paid, 0))
                                  as double) as currency_ratio
                      from catalog_sales cs
                      left outer join catalog_returns cr
                        on (cs.cs_order_number = cr.cr_order_number
                            and cs.cs_item_sk = cr.cr_item_sk),
                      date_dim
                      where cr.cr_return_amount > 100
                        and cs.cs_net_profit > 1
                        and cs.cs_net_paid > 0
                        and cs.cs_quantity > 0
                        and cs_sold_date_sk = d_date_sk
                        and d_year = 2001 and d_moy = 12
                      group by cs.cs_item_sk) in_cat) catalog
          where catalog.return_rank <= 10
             or catalog.currency_rank <= 10
          union all
          select 'store' as channel, store.item, store.return_ratio,
                 store.return_rank, store.currency_rank
          from (select item, return_ratio, currency_ratio,
                       rank() over (order by return_ratio) as return_rank,
                       rank() over (order by currency_ratio)
                         as currency_rank
                from (select sts.ss_item_sk as item,
                             cast(sum(coalesce(sr.sr_return_quantity, 0))
                                  as double) /
                             cast(sum(coalesce(sts.ss_quantity, 0))
                                  as double) as return_ratio,
                             cast(sum(coalesce(sr.sr_return_amt, 0))
                                  as double) /
                             cast(sum(coalesce(sts.ss_net_paid, 0))
                                  as double) as currency_ratio
                      from store_sales sts
                      left outer join store_returns sr
                        on (sts.ss_ticket_number = sr.sr_ticket_number
                            and sts.ss_item_sk = sr.sr_item_sk),
                      date_dim
                      where sr.sr_return_amt > 100
                        and sts.ss_net_profit > 1
                        and sts.ss_net_paid > 0
                        and sts.ss_quantity > 0
                        and ss_sold_date_sk = d_date_sk
                        and d_year = 2001 and d_moy = 12
                      group by sts.ss_item_sk) in_store) store
          where store.return_rank <= 10
             or store.currency_rank <= 10) sq1
    order by 1, 4, 5, 2
    limit 100"""

QUERIES["q51"] = """
    with web_v1 as (
      select ws_item_sk item_sk, d_date,
             sum(sum(ws_sales_price))
               over (partition by ws_item_sk order by d_date
                     rows between unbounded preceding and current row)
               cume_sales
      from web_sales, date_dim
      where ws_sold_date_sk = d_date_sk
        and d_month_seq between 1200 and 1200 + 11
        and ws_item_sk is not null
      group by ws_item_sk, d_date),
    store_v1 as (
      select ss_item_sk item_sk, d_date,
             sum(sum(ss_sales_price))
               over (partition by ss_item_sk order by d_date
                     rows between unbounded preceding and current row)
               cume_sales
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk
        and d_month_seq between 1200 and 1200 + 11
        and ss_item_sk is not null
      group by ss_item_sk, d_date)
    select * from (
      select item_sk, d_date, web_sales, store_sales,
             max(web_sales)
               over (partition by item_sk order by d_date
                     rows between unbounded preceding and current row)
               web_cumulative,
             max(store_sales)
               over (partition by item_sk order by d_date
                     rows between unbounded preceding and current row)
               store_cumulative
      from (select case when web.item_sk is not null
                        then web.item_sk else store.item_sk end item_sk,
                   case when web.d_date is not null
                        then web.d_date else store.d_date end d_date,
                   web.cume_sales web_sales,
                   store.cume_sales store_sales
            from web_v1 web full outer join store_v1 store
              on (web.item_sk = store.item_sk
                  and web.d_date = store.d_date)) x) y
    where web_cumulative > store_cumulative
    order by item_sk, d_date
    limit 100"""

QUERIES["q54"] = """
    with my_customers as (
      select distinct c_customer_sk, c_current_addr_sk
      from (select cs_sold_date_sk sold_date_sk,
                   cs_bill_customer_sk customer_sk,
                   cs_item_sk item_sk
            from catalog_sales
            union all
            select ws_sold_date_sk sold_date_sk,
                   ws_bill_customer_sk customer_sk,
                   ws_item_sk item_sk
            from web_sales) cs_or_ws_sales, item, date_dim, customer
      where sold_date_sk = d_date_sk
        and item_sk = i_item_sk
        and i_category = 'Women'
        and i_class = 'dresses'
        and c_customer_sk = cs_or_ws_sales.customer_sk
        and d_moy = 12 and d_year = 1998),
    my_revenue as (
      select c_customer_sk, sum(ss_ext_sales_price) as revenue
      from my_customers, store_sales, customer_address, store, date_dim
      where c_current_addr_sk = ca_address_sk
        and ca_county = s_county and ca_state = s_state
        and ss_customer_sk = c_customer_sk
        and ss_sold_date_sk = d_date_sk
        and d_month_seq between
            (select distinct d_month_seq + 1 from date_dim
             where d_year = 1998 and d_moy = 12)
            and
            (select distinct d_month_seq + 3 from date_dim
             where d_year = 1998 and d_moy = 12)
      group by c_customer_sk),
    segments as (
      select cast((revenue / 50) as int) as segment from my_revenue)
    select segment, count(*) as num_customers,
           segment * 50 as segment_base
    from segments
    group by segment
    order by segment, num_customers
    limit 100"""

QUERIES["q64"] = """
    with cs_ui as (
      select cs_item_sk,
             sum(cs_ext_list_price) as sale,
             sum(cr_refunded_cash + cr_reversed_charge
                 + cr_store_credit) as refund
      from catalog_sales, catalog_returns
      where cs_item_sk = cr_item_sk
        and cs_order_number = cr_order_number
      group by cs_item_sk
      having sum(cs_ext_list_price) >
             2 * sum(cr_refunded_cash + cr_reversed_charge
                     + cr_store_credit)),
    cross_sales as (
      select i_product_name product_name, i_item_sk item_sk,
             s_store_name store_name, s_zip store_zip,
             ad1.ca_street_number b_street_number,
             ad1.ca_street_name b_street_name,
             ad1.ca_city b_city, ad1.ca_zip b_zip,
             ad2.ca_street_number c_street_number,
             ad2.ca_street_name c_street_name,
             ad2.ca_city c_city, ad2.ca_zip c_zip,
             d1.d_year as syear, d2.d_year as fsyear, d3.d_year s2year,
             count(*) cnt,
             sum(ss_wholesale_cost) s1, sum(ss_list_price) s2,
             sum(ss_coupon_amt) s3
      from store_sales, store_returns, cs_ui,
           date_dim d1, date_dim d2, date_dim d3,
           store, customer, customer_demographics cd1,
           customer_demographics cd2, promotion,
           household_demographics hd1, household_demographics hd2,
           customer_address ad1, customer_address ad2,
           income_band ib1, income_band ib2, item
      where ss_store_sk = s_store_sk
        and ss_sold_date_sk = d1.d_date_sk
        and ss_customer_sk = c_customer_sk
        and ss_cdemo_sk = cd1.cd_demo_sk
        and ss_hdemo_sk = hd1.hd_demo_sk
        and ss_addr_sk = ad1.ca_address_sk
        and ss_item_sk = i_item_sk
        and ss_item_sk = sr_item_sk
        and ss_ticket_number = sr_ticket_number
        and ss_item_sk = cs_ui.cs_item_sk
        and c_current_cdemo_sk = cd2.cd_demo_sk
        and c_current_hdemo_sk = hd2.hd_demo_sk
        and c_current_addr_sk = ad2.ca_address_sk
        and c_first_sales_date_sk = d2.d_date_sk
        and c_first_shipto_date_sk = d3.d_date_sk
        and ss_promo_sk = p_promo_sk
        and hd1.hd_income_band_sk = ib1.ib_income_band_sk
        and hd2.hd_income_band_sk = ib2.ib_income_band_sk
        and cd1.cd_marital_status <> cd2.cd_marital_status
        and i_color in ('red', 'blue', 'green', 'purple', 'white',
                        'orange')
        and i_current_price between 20 and 20 + 50
      group by i_product_name, i_item_sk, s_store_name, s_zip,
               ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city,
               ad1.ca_zip, ad2.ca_street_number, ad2.ca_street_name,
               ad2.ca_city, ad2.ca_zip, d1.d_year, d2.d_year, d3.d_year)
    select cs1.product_name, cs1.store_name, cs1.store_zip,
           cs1.b_street_number, cs1.b_street_name, cs1.b_city,
           cs1.b_zip, cs1.c_street_number, cs1.c_street_name,
           cs1.c_city, cs1.c_zip, cs1.syear, cs1.cnt,
           cs1.s1 as s11, cs1.s2 as s21, cs1.s3 as s31,
           cs2.s1 as s12, cs2.s2 as s22, cs2.s3 as s32,
           cs2.syear as syear2, cs2.cnt as cnt2
    from cross_sales cs1, cross_sales cs2
    where cs1.item_sk = cs2.item_sk
      and cs1.syear = 1999
      and cs2.syear = 1999 + 1
      and cs2.cnt <= cs1.cnt
      and cs1.store_name = cs2.store_name
      and cs1.store_zip = cs2.store_zip
    order by cs1.product_name, cs1.store_name, cnt2, cs1.s1, s12
    limit 100"""

QUERIES["q66"] = """
    select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
           w_state, w_country, ship_carriers, year_,
           sum(jan_sales) as jan_sales, sum(feb_sales) as feb_sales,
           sum(mar_sales) as mar_sales, sum(apr_sales) as apr_sales,
           sum(may_sales) as may_sales, sum(jun_sales) as jun_sales,
           sum(jul_sales) as jul_sales, sum(aug_sales) as aug_sales,
           sum(sep_sales) as sep_sales, sum(oct_sales) as oct_sales,
           sum(nov_sales) as nov_sales, sum(dec_sales) as dec_sales,
           sum(jan_net) as jan_net, sum(feb_net) as feb_net,
           sum(mar_net) as mar_net, sum(apr_net) as apr_net,
           sum(may_net) as may_net, sum(jun_net) as jun_net,
           sum(jul_net) as jul_net, sum(aug_net) as aug_net,
           sum(sep_net) as sep_net, sum(oct_net) as oct_net,
           sum(nov_net) as nov_net, sum(dec_net) as dec_net
    from (
      select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
             w_state, w_country,
             'DHL' || ',' || 'UPS' as ship_carriers,
             d_year as year_,
             sum(case when d_moy = 1 then ws_ext_sales_price
                      * ws_quantity else 0 end) as jan_sales,
             sum(case when d_moy = 2 then ws_ext_sales_price
                      * ws_quantity else 0 end) as feb_sales,
             sum(case when d_moy = 3 then ws_ext_sales_price
                      * ws_quantity else 0 end) as mar_sales,
             sum(case when d_moy = 4 then ws_ext_sales_price
                      * ws_quantity else 0 end) as apr_sales,
             sum(case when d_moy = 5 then ws_ext_sales_price
                      * ws_quantity else 0 end) as may_sales,
             sum(case when d_moy = 6 then ws_ext_sales_price
                      * ws_quantity else 0 end) as jun_sales,
             sum(case when d_moy = 7 then ws_ext_sales_price
                      * ws_quantity else 0 end) as jul_sales,
             sum(case when d_moy = 8 then ws_ext_sales_price
                      * ws_quantity else 0 end) as aug_sales,
             sum(case when d_moy = 9 then ws_ext_sales_price
                      * ws_quantity else 0 end) as sep_sales,
             sum(case when d_moy = 10 then ws_ext_sales_price
                      * ws_quantity else 0 end) as oct_sales,
             sum(case when d_moy = 11 then ws_ext_sales_price
                      * ws_quantity else 0 end) as nov_sales,
             sum(case when d_moy = 12 then ws_ext_sales_price
                      * ws_quantity else 0 end) as dec_sales,
             sum(case when d_moy = 1 then ws_net_paid * ws_quantity
                      else 0 end) as jan_net,
             sum(case when d_moy = 2 then ws_net_paid * ws_quantity
                      else 0 end) as feb_net,
             sum(case when d_moy = 3 then ws_net_paid * ws_quantity
                      else 0 end) as mar_net,
             sum(case when d_moy = 4 then ws_net_paid * ws_quantity
                      else 0 end) as apr_net,
             sum(case when d_moy = 5 then ws_net_paid * ws_quantity
                      else 0 end) as may_net,
             sum(case when d_moy = 6 then ws_net_paid * ws_quantity
                      else 0 end) as jun_net,
             sum(case when d_moy = 7 then ws_net_paid * ws_quantity
                      else 0 end) as jul_net,
             sum(case when d_moy = 8 then ws_net_paid * ws_quantity
                      else 0 end) as aug_net,
             sum(case when d_moy = 9 then ws_net_paid * ws_quantity
                      else 0 end) as sep_net,
             sum(case when d_moy = 10 then ws_net_paid * ws_quantity
                      else 0 end) as oct_net,
             sum(case when d_moy = 11 then ws_net_paid * ws_quantity
                      else 0 end) as nov_net,
             sum(case when d_moy = 12 then ws_net_paid * ws_quantity
                      else 0 end) as dec_net
      from web_sales, warehouse, date_dim, time_dim, ship_mode
      where ws_warehouse_sk = w_warehouse_sk
        and ws_sold_date_sk = d_date_sk
        and ws_sold_time_sk = t_time_sk
        and ws_ship_mode_sk = sm_ship_mode_sk
        and d_year = 2001
        and t_time between 30838 and 30838 + 28800
        and sm_carrier in ('DHL', 'UPS')
      group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
               w_state, w_country, d_year
      union all
      select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
             w_state, w_country,
             'DHL' || ',' || 'UPS' as ship_carriers,
             d_year as year_,
             sum(case when d_moy = 1 then cs_sales_price * cs_quantity
                      else 0 end) as jan_sales,
             sum(case when d_moy = 2 then cs_sales_price * cs_quantity
                      else 0 end) as feb_sales,
             sum(case when d_moy = 3 then cs_sales_price * cs_quantity
                      else 0 end) as mar_sales,
             sum(case when d_moy = 4 then cs_sales_price * cs_quantity
                      else 0 end) as apr_sales,
             sum(case when d_moy = 5 then cs_sales_price * cs_quantity
                      else 0 end) as may_sales,
             sum(case when d_moy = 6 then cs_sales_price * cs_quantity
                      else 0 end) as jun_sales,
             sum(case when d_moy = 7 then cs_sales_price * cs_quantity
                      else 0 end) as jul_sales,
             sum(case when d_moy = 8 then cs_sales_price * cs_quantity
                      else 0 end) as aug_sales,
             sum(case when d_moy = 9 then cs_sales_price * cs_quantity
                      else 0 end) as sep_sales,
             sum(case when d_moy = 10 then cs_sales_price * cs_quantity
                      else 0 end) as oct_sales,
             sum(case when d_moy = 11 then cs_sales_price * cs_quantity
                      else 0 end) as nov_sales,
             sum(case when d_moy = 12 then cs_sales_price * cs_quantity
                      else 0 end) as dec_sales,
             sum(case when d_moy = 1 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as jan_net,
             sum(case when d_moy = 2 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as feb_net,
             sum(case when d_moy = 3 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as mar_net,
             sum(case when d_moy = 4 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as apr_net,
             sum(case when d_moy = 5 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as may_net,
             sum(case when d_moy = 6 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as jun_net,
             sum(case when d_moy = 7 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as jul_net,
             sum(case when d_moy = 8 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as aug_net,
             sum(case when d_moy = 9 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as sep_net,
             sum(case when d_moy = 10 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as oct_net,
             sum(case when d_moy = 11 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as nov_net,
             sum(case when d_moy = 12 then cs_net_paid_inc_ship
                      * cs_quantity else 0 end) as dec_net
      from catalog_sales, warehouse, date_dim, time_dim, ship_mode
      where cs_warehouse_sk = w_warehouse_sk
        and cs_sold_date_sk = d_date_sk
        and cs_sold_time_sk = t_time_sk
        and cs_ship_mode_sk = sm_ship_mode_sk
        and d_year = 2001
        and t_time between 30838 and 30838 + 28800
        and sm_carrier in ('DHL', 'UPS')
      group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
               w_state, w_country, d_year) x
    group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
             w_state, w_country, ship_carriers, year_
    order by w_warehouse_name
    limit 100"""

QUERIES["q67"] = """
    select * from (
      select i_category, i_class, i_brand, i_product_name, d_year,
             d_qoy, d_moy, s_store_id, sumsales,
             rank() over (partition by i_category
                          order by sumsales desc) rk
      from (select i_category, i_class, i_brand, i_product_name,
                   d_year, d_qoy, d_moy, s_store_id,
                   sum(coalesce(ss_sales_price * ss_quantity, 0))
                     sumsales
            from store_sales, date_dim, store, item
            where ss_sold_date_sk = d_date_sk
              and ss_item_sk = i_item_sk
              and ss_store_sk = s_store_sk
              and d_month_seq between 1200 and 1200 + 11
            group by rollup(i_category, i_class, i_brand,
                            i_product_name, d_year, d_qoy, d_moy,
                            s_store_id)) dw1) dw2
    where rk <= 100
    order by i_category, i_class, i_brand, i_product_name, d_year,
             d_qoy, d_moy, s_store_id, sumsales, rk
    limit 100"""

QUERIES["q70"] = """
    select sum(ss_net_profit) as total_sum, s_state, s_county,
           grouping(s_state) + grouping(s_county) as lochierarchy,
           rank() over (
             partition by grouping(s_state) + grouping(s_county),
               case when grouping(s_county) = 0 then s_state end
             order by sum(ss_net_profit) desc) as rank_within_parent
    from store_sales, date_dim d1, store
    where d1.d_month_seq between 1200 and 1200 + 11
      and d1.d_date_sk = ss_sold_date_sk
      and s_store_sk = ss_store_sk
      and s_state in (select s_state
                      from (select s_state as s_state,
                                   rank() over (partition by s_state
                                     order by sum(ss_net_profit) desc)
                                     as ranking
                            from store_sales, store, date_dim
                            where d_month_seq between 1200 and 1200 + 11
                              and d_date_sk = ss_sold_date_sk
                              and s_store_sk = ss_store_sk
                            group by s_state) tmp1
                      where ranking <= 5)
    group by rollup(s_state, s_county)
    order by lochierarchy desc,
             case when lochierarchy = 0 then s_state end,
             rank_within_parent
    limit 100"""

QUERIES["q77"] = """
    with ss as (
      select s_store_sk, sum(ss_ext_sales_price) as sales,
             sum(ss_net_profit) as profit
      from store_sales, date_dim, store
      where ss_sold_date_sk = d_date_sk
        and d_date between date '2000-08-03'
                       and date '2000-08-03' + interval 30 days
        and ss_store_sk = s_store_sk
      group by s_store_sk),
    sr as (
      select s_store_sk, sum(sr_return_amt) as returns_amt,
             sum(sr_net_loss) as profit_loss
      from store_returns, date_dim, store
      where sr_returned_date_sk = d_date_sk
        and d_date between date '2000-08-03'
                       and date '2000-08-03' + interval 30 days
        and sr_store_sk = s_store_sk
      group by s_store_sk),
    cs as (
      select cs_call_center_sk, sum(cs_ext_sales_price) as sales,
             sum(cs_net_profit) as profit
      from catalog_sales, date_dim
      where cs_sold_date_sk = d_date_sk
        and d_date between date '2000-08-03'
                       and date '2000-08-03' + interval 30 days
      group by cs_call_center_sk),
    cr as (
      select cr_call_center_sk, sum(cr_return_amount) as returns_amt,
             sum(cr_net_loss) as profit_loss
      from catalog_returns, date_dim
      where cr_returned_date_sk = d_date_sk
        and d_date between date '2000-08-03'
                       and date '2000-08-03' + interval 30 days
      group by cr_call_center_sk),
    ws as (
      select wp_web_page_sk, sum(ws_ext_sales_price) as sales,
             sum(ws_net_profit) as profit
      from web_sales, date_dim, web_page
      where ws_sold_date_sk = d_date_sk
        and d_date between date '2000-08-03'
                       and date '2000-08-03' + interval 30 days
        and ws_web_page_sk = wp_web_page_sk
      group by wp_web_page_sk),
    wr as (
      select wp_web_page_sk, sum(wr_return_amt) as returns_amt,
             sum(wr_net_loss) as profit_loss
      from web_returns, date_dim, web_page
      where wr_returned_date_sk = d_date_sk
        and d_date between date '2000-08-03'
                       and date '2000-08-03' + interval 30 days
        and wr_web_page_sk = wp_web_page_sk
      group by wp_web_page_sk)
    select channel, id, sum(sales) as sales,
           sum(returns_amt) as returns_amt, sum(profit) as profit
    from (select 'store channel' as channel, ss.s_store_sk as id,
                 sales, coalesce(returns_amt, 0) as returns_amt,
                 profit - coalesce(profit_loss, 0) as profit
          from ss left join sr on ss.s_store_sk = sr.s_store_sk
          union all
          select 'catalog channel' as channel,
                 cs_call_center_sk as id, sales, returns_amt,
                 profit - profit_loss as profit
          from cs, cr
          union all
          select 'web channel' as channel, ws.wp_web_page_sk as id,
                 sales, coalesce(returns_amt, 0) as returns_amt,
                 profit - coalesce(profit_loss, 0) as profit
          from ws left join wr
            on ws.wp_web_page_sk = wr.wp_web_page_sk) x
    group by rollup(channel, id)
    order by channel, id
    limit 100"""

QUERIES["q80"] = """
    with ssr as (
      select s_store_id as store_id,
             sum(ss_ext_sales_price) as sales,
             sum(coalesce(sr_return_amt, 0)) as returns_amt,
             sum(ss_net_profit - coalesce(sr_net_loss, 0)) as profit
      from store_sales
      left outer join store_returns
        on (ss_item_sk = sr_item_sk
            and ss_ticket_number = sr_ticket_number),
      date_dim, store, item, promotion
      where ss_sold_date_sk = d_date_sk
        and d_date between date '2000-08-23'
                       and date '2000-08-23' + interval 30 days
        and ss_store_sk = s_store_sk
        and ss_item_sk = i_item_sk
        and i_current_price > 50
        and ss_promo_sk = p_promo_sk
        and p_channel_tv = 'N'
      group by s_store_id),
    csr as (
      select cp_catalog_page_id as catalog_page_id,
             sum(cs_ext_sales_price) as sales,
             sum(coalesce(cr_return_amount, 0)) as returns_amt,
             sum(cs_net_profit - coalesce(cr_net_loss, 0)) as profit
      from catalog_sales
      left outer join catalog_returns
        on (cs_item_sk = cr_item_sk
            and cs_order_number = cr_order_number),
      date_dim, catalog_page, item, promotion
      where cs_sold_date_sk = d_date_sk
        and d_date between date '2000-08-23'
                       and date '2000-08-23' + interval 30 days
        and cs_catalog_page_sk = cp_catalog_page_sk
        and cs_item_sk = i_item_sk
        and i_current_price > 50
        and cs_promo_sk = p_promo_sk
        and p_channel_tv = 'N'
      group by cp_catalog_page_id),
    wsr as (
      select web_site_id,
             sum(ws_ext_sales_price) as sales,
             sum(coalesce(wr_return_amt, 0)) as returns_amt,
             sum(ws_net_profit - coalesce(wr_net_loss, 0)) as profit
      from web_sales
      left outer join web_returns
        on (ws_item_sk = wr_item_sk
            and ws_order_number = wr_order_number),
      date_dim, web_site, item, promotion
      where ws_sold_date_sk = d_date_sk
        and d_date between date '2000-08-23'
                       and date '2000-08-23' + interval 30 days
        and ws_web_site_sk = web_site_sk
        and ws_item_sk = i_item_sk
        and i_current_price > 50
        and ws_promo_sk = p_promo_sk
        and p_channel_tv = 'N'
      group by web_site_id)
    select channel, id, sum(sales) as sales,
           sum(returns_amt) as returns_amt, sum(profit) as profit
    from (select 'store channel' as channel,
                 'store' || store_id as id, sales, returns_amt, profit
          from ssr
          union all
          select 'catalog channel' as channel,
                 'catalog_page' || catalog_page_id as id,
                 sales, returns_amt, profit
          from csr
          union all
          select 'web channel' as channel,
                 'web_site' || web_site_id as id,
                 sales, returns_amt, profit
          from wsr) x
    group by rollup(channel, id)
    order by channel, id
    limit 100"""

QUERIES["q94"] = """
    select count(distinct ws_order_number) as order_count,
           sum(ws_ext_ship_cost) as total_shipping_cost,
           sum(ws_net_profit) as total_net_profit
    from web_sales ws1, date_dim, customer_address, web_site
    where d_date between date '1999-02-01'
                     and date '1999-02-01' + interval 60 days
      and ws1.ws_ship_date_sk = d_date_sk
      and ws1.ws_ship_addr_sk = ca_address_sk
      and ca_state = 'GA'
      and ws1.ws_web_site_sk = web_site_sk
      and web_company_name = 'pri'
      and exists (select * from web_sales ws2
                  where ws1.ws_order_number = ws2.ws_order_number
                    and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
      and not exists (select * from web_returns wr1
                      where ws1.ws_order_number = wr1.wr_order_number)
    order by count(distinct ws_order_number)
    limit 100"""
