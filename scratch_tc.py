"""Time the compiled table core: exact vs variable conf."""
import time, sys
import jax, jax.numpy as jnp
from bench import build_df
from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.exec import tpu_aggregate as TA

variable = len(sys.argv) > 1 and sys.argv[1] == "var"
captured = {}
orig = TA.TpuHashAggregate._fused_table_core
def spy(self, batch):
    r = orig(self, batch)
    if r is not None and "args" not in captured:
        captured["args"] = (tuple(c.data for c in batch.columns),
                            tuple(c.validity for c in batch.columns),
                            batch.rows_dev)
    return r
TA.TpuHashAggregate._fused_table_core = spy

s = TpuSession(TpuConf({
    "spark.rapids.tpu.sql.enabled": True,
    "spark.rapids.tpu.sql.batchSizeRows": 1 << 22,
    "spark.rapids.tpu.sql.reader.batchSizeRows": 1 << 22,
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": variable,
}))
df = build_df(s, 4_000_000, 1)
df.to_arrow()
print("captured:", "args" in captured, flush=True)
core = None
for k, v in TA.TpuHashAggregate._CORE_CACHE.items():
    if v not in (None, False) and isinstance(k, tuple) and k and \
            isinstance(k[0], tuple) and k[0] and k[0][0] == "table":
        core = v
datas, valids, nrows = captured["args"]
def force(out):
    fit, ng, kp, bg = out
    return float(jnp.sum(kp[0][0].astype(jnp.float32)).item())
force(core(datas, valids, nrows))
for i in range(3):
    t0 = time.perf_counter()
    force(core(datas, valids, nrows))
    print(f"table core ({'var' if variable else 'exact'}) "
          f"{time.perf_counter()-t0:.2f}s", flush=True)
