import cProfile, pstats, sys, time
from bench import build_df
from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.config import TpuConf
n = int(sys.argv[1]) if len(sys.argv) > 1 else 4_000_000
s = TpuSession(TpuConf({
    "spark.rapids.tpu.sql.enabled": True,
    "spark.rapids.tpu.sql.batchSizeRows": 1 << 22,
    "spark.rapids.tpu.sql.reader.batchSizeRows": 1 << 22,
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": False,
}))
df = build_df(s, n, 4)
t0 = time.perf_counter(); df.to_arrow()
print(f"first {time.perf_counter()-t0:.1f}s", flush=True)
for i in range(3):
    t0 = time.perf_counter(); out = df.to_arrow()
    print(f"warm{i} {time.perf_counter()-t0:.1f}s rows={out.num_rows}", flush=True)
