"""Shim layer + compression codec + API-surface validation tests.

Reference patterns: ShimLoader version detection, TableCompressionCodec
round-trip, and api_validation/ (reflection audit of API parity).
"""
import numpy as np
import pytest

from spark_rapids_tpu.shims import detect_shim, get_shard_map, JaxShim09
from spark_rapids_tpu.shuffle.compression import get_codec
from spark_rapids_tpu.memory.catalog import BufferCatalog, StorageTier
from spark_rapids_tpu.memory.spillable import SpillableBatch
from spark_rapids_tpu.columnar import ColumnarBatch


class TestShims:
    def test_detects_current_jax(self):
        shim = detect_shim()
        assert shim is not None
        sm = get_shard_map()
        assert callable(sm)

    def test_key_array(self):
        k = detect_shim().key_array(7)
        assert k is not None


class TestCompression:
    @pytest.mark.parametrize("name", ["none", "zlib"])
    def test_roundtrip(self, name):
        codec = get_codec(name)
        data = bytes(np.random.default_rng(0).integers(
            0, 255, 10000, dtype=np.uint8)) * 3
        comp = codec.compress(data)
        assert codec.decompress(comp, len(data)) == data
        if name == "zlib":
            assert len(comp) < len(data)

    def test_unknown_codec_raises(self):
        with pytest.raises(ValueError):
            get_codec("snappy9000")

    def test_compressed_disk_spill_roundtrip(self):
        cat = BufferCatalog.reset(spill_dir="/tmp/srt_test_spill",
                                  host_limit=1, compression="zlib")
        b = ColumnarBatch.from_pydict(
            {"a": list(range(200)), "s": [f"v{i % 7}" for i in range(200)]})
        expect = b.to_pydict()
        sb = SpillableBatch(b, catalog=cat)
        cat.spill_device_to_fit(cat.device_limit)
        assert cat._entries[sb.buffer_id].tier == StorageTier.DISK
        got = sb.materialize()
        assert got.to_pydict() == expect
        sb.close()


# The reference's api_validation module audits CPU-vs-GPU exec constructor
# parity via reflection; here we audit DataFrame API parity against the
# PySpark surface users migrate from.
PYSPARK_DATAFRAME_METHODS = [
    "select", "filter", "where", "withColumn", "withColumnRenamed", "drop",
    "groupBy", "agg", "join", "union", "unionAll", "distinct",
    "dropDuplicates", "sort", "orderBy", "limit", "repartition", "coalesce",
    "collect", "count", "show", "first", "head", "take", "cache", "persist",
    "toPandas", "explain", "schema", "columns", "write",
]

PYSPARK_FUNCTIONS = [
    "col", "lit", "sum", "count", "min", "max", "avg", "mean", "first",
    "last", "when", "coalesce", "isnull", "isnan", "sqrt", "exp", "log",
    "floor", "ceil", "abs", "round", "pow", "greatest", "least", "upper",
    "lower", "length", "trim", "ltrim", "rtrim", "substring", "concat",
    "md5", "year", "month", "dayofmonth", "quarter", "dayofweek", "hour",
    "minute", "second", "date_add", "date_sub", "datediff", "hash",
    "monotonically_increasing_id", "spark_partition_id", "rand",
    "row_number", "rank", "dense_rank", "lead", "lag",
]


class TestApiValidation:
    def test_dataframe_surface(self):
        from spark_rapids_tpu.api.dataframe import DataFrame
        missing = [m for m in PYSPARK_DATAFRAME_METHODS
                   if not hasattr(DataFrame, m)]
        assert not missing, f"DataFrame API gaps vs PySpark: {missing}"

    def test_functions_surface(self):
        from spark_rapids_tpu.api import functions as F
        missing = [m for m in PYSPARK_FUNCTIONS if not hasattr(F, m)]
        assert not missing, f"functions API gaps vs PySpark: {missing}"

    def test_column_surface(self):
        from spark_rapids_tpu.api.column import Col
        for m in ["alias", "cast", "isNull", "isNotNull", "isin",
                  "eqNullSafe", "like", "rlike", "startswith", "endswith",
                  "contains", "substr", "asc", "desc"]:
            assert hasattr(Col, m), f"Col missing {m}"
