"""Fleet observability tests: the stable plan fingerprint
(obs/fingerprint), the persistent query-history store (obs/history),
the online anomaly sentinel (obs/anomaly), the shared band/direction
core (analysis/bands), the hardened scrape-server lifecycle (obs/prom)
and the dashboard + offline history CLI."""
import json
import os
import queue as _pyqueue
import urllib.request

import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.analysis import bands
from spark_rapids_tpu.analysis.regression import Delta, compare
from spark_rapids_tpu.obs import anomaly, fingerprint, history
from spark_rapids_tpu.service.metrics import QueryMetrics


@pytest.fixture(autouse=True)
def _fleet_reset():
    """Isolate the process-wide fleet planes (and restore the default
    config afterwards — last-configured service wins)."""
    history.stop()
    history.reset()
    anomaly.reset()
    yield
    history.stop()
    default = TpuConf({})
    history.configure(default)
    anomaly.configure(default)
    history.reset()
    anomaly.reset()


def _metrics(i=0, tenant="default", exec_ms=100.0, outcome="completed",
             ts=None):
    m = QueryMetrics(query_id=f"q{i}", tenant=tenant, priority=0)
    m.execute_ms = exec_ms
    m.queue_wait_ms = 1.0
    m.outcome = outcome
    if ts is not None:
        m.submitted_ts = ts
    return m


def _row(fp="fpA", exec_ms=100.0, i=0, flushes=2, cause=None):
    return {"fingerprint": fp, "exec_ms": exec_ms, "queue_ms": 1.0,
            "host_drop_tax_ms": 0.0, "spill_ms": 0.0,
            "device_util_pct": 60.0, "flushes": flushes,
            "doctor_cause": cause, "ts": 1000.0 + i}


# ---------------------------------------------------------------------------
# plan fingerprint
# ---------------------------------------------------------------------------

def _fp_for(conf_extra=None, lit=5, extra_agg=False, tenant_tag=None):
    s = TpuSession(TpuConf(dict(conf_extra or {})))
    df = s.range(0, 64, num_partitions=2) \
        .select((F.col("id") % 7).alias("k"), F.col("id").alias("v")) \
        .filter(F.col("v") > lit).group_by("k")
    if extra_agg:
        df = df.agg(F.sum("v").alias("sv"), F.count("v").alias("cv"))
    else:
        df = df.agg(F.sum("v").alias("sv"))
    df.collect()
    assert s.last_query_fingerprint
    return s.last_query_fingerprint


class TestFingerprint:
    def test_stable_across_pipeline_and_superstage_matrix(self):
        digests = {
            _fp_for({"spark.rapids.tpu.exec.pipelineParallelism": pp,
                     "spark.rapids.tpu.sql.superstage.enabled": ss})
            for pp in (1, 4) for ss in (True, False)}
        assert len(digests) == 1, digests

    def test_same_plan_two_sessions_same_digest(self):
        assert _fp_for() == _fp_for()

    def test_literal_change_same_digest(self):
        assert _fp_for(lit=5) == _fp_for(lit=50)

    def test_shape_change_moves_digest(self):
        assert _fp_for() != _fp_for(extra_agg=True)

    def test_obs_and_logging_confs_do_not_move_conf_fingerprint(self):
        base = fingerprint.conf_fingerprint(TpuConf({}))
        same = fingerprint.conf_fingerprint(TpuConf({
            "spark.rapids.tpu.obs.history.enabled": False,
            "spark.rapids.tpu.obs.anomaly.sigma": 9.0,
            "spark.rapids.tpu.eventLog.path": "/tmp/x.jsonl",
            "spark.rapids.tpu.exec.pipelineParallelism": 4,
            "spark.rapids.tpu.sql.superstage.enabled": False,
        }))
        assert base == same

    def test_plan_affecting_conf_moves_conf_fingerprint(self):
        base = fingerprint.conf_fingerprint(TpuConf({}))
        moved = fingerprint.conf_fingerprint(TpuConf({
            "spark.rapids.tpu.sql.shuffle.partitions": 3}))
        assert base != moved

    def test_plan_shape_has_no_literals_or_ids(self):
        s = TpuSession(TpuConf({}))
        df = s.range(0, 64, num_partitions=2) \
            .filter(F.col("id") > 42424242)
        df.collect()
        # re-derive the shape from a fresh identical plan: one line per
        # operator, literals absent
        df2 = s.range(0, 64, num_partitions=2) \
            .filter(F.col("id") > 42424242)
        df2.collect()
        assert s.last_query_fingerprint


# ---------------------------------------------------------------------------
# shared band/direction core
# ---------------------------------------------------------------------------

class TestBands:
    def test_higher_direction(self):
        assert bands.band_status(79.0, 100.0, "higher", 20.0) \
            == bands.REGRESSION
        assert bands.band_status(121.0, 100.0, "higher", 20.0) \
            == bands.IMPROVEMENT
        assert bands.band_status(100.0, 100.0, "higher", 20.0) \
            == bands.OK

    def test_lower_direction_with_floor(self):
        # floor guards near-zero baselines
        assert bands.band_status(3.0, 2.0, "lower", 25.0,
                                 abs_floor=50.0) == bands.OK
        assert bands.band_status(300.0, 100.0, "lower", 25.0,
                                 abs_floor=50.0) == bands.REGRESSION

    def test_exact_direction_never_improves(self):
        assert bands.band_status(2.0, 2.0, "exact") == bands.OK
        assert bands.band_status(1.0, 2.0, "exact") == bands.REGRESSION
        assert bands.band_status(3.0, 2.0, "exact") == bands.REGRESSION

    def test_parity_with_regression_compare(self):
        # the offline gate and the shared core agree on the same inputs
        baseline = {"keys": {"rows_per_sec": {
            "value": 100.0, "band_pct": 10.0, "direction": "higher"}}}
        deltas = compare({"rows_per_sec": 85.0}, baseline)
        d = [x for x in deltas if x.key == "rows_per_sec"][0]
        assert isinstance(d, Delta) and d.status == "regression"
        assert bands.band_status(85.0, 100.0, "higher", 10.0) \
            == bands.REGRESSION


# ---------------------------------------------------------------------------
# history store
# ---------------------------------------------------------------------------

class TestHistory:
    def test_note_query_record_join(self, tmp_path):
        history.configure(TpuConf({
            "spark.rapids.tpu.obs.history.dir": str(tmp_path)}))
        history.note_query("q0", {"fingerprint": "fpJ", "flushes": 3,
                                  "device_util_pct": 44.0,
                                  "doctor_cause": "host_staging"})
        row = history.record(_metrics(0, tenant="t9", exec_ms=12.5))
        assert row["fingerprint"] == "fpJ"
        assert row["flushes"] == 3
        assert row["tenant"] == "t9"
        assert row["exec_ms"] == 12.5
        assert row["doctor_cause"] == "host_staging"
        # the artifact is consumed: a second record has no join
        row2 = history.record(_metrics(0))
        assert row2["fingerprint"] == "unknown"
        history.stop()
        rows = [json.loads(ln) for p in history.segment_paths()
                for ln in open(p)]
        assert len(rows) == 2 and rows[0]["fingerprint"] == "fpJ"

    def test_size_rotation_and_retention(self, tmp_path):
        history.configure(TpuConf({
            "spark.rapids.tpu.obs.history.dir": str(tmp_path),
            "spark.rapids.tpu.obs.history.rotation.maxBytes": 600,
            "spark.rapids.tpu.obs.history.retention.maxSegments": 3}))
        for i in range(30):
            history.note_query(f"q{i}", {"fingerprint": "fpR"})
            history.record(_metrics(i))
        history.stop()
        segs = history.segment_paths()
        assert 1 < len(segs) <= 3, segs
        # retention deleted the oldest: the surviving sequence numbers
        # are the highest ones and every surviving file is bounded
        for p in segs:
            assert os.path.getsize(p) <= 600 + 400  # one-row overshoot
        names = [os.path.basename(p) for p in segs]
        assert names == sorted(names)
        assert names[-1] != "history-000001.jsonl"

    def test_age_rotation_uses_row_timestamps(self, tmp_path):
        history.configure(TpuConf({
            "spark.rapids.tpu.obs.history.dir": str(tmp_path),
            "spark.rapids.tpu.obs.history.rotation.maxAgeSeconds": 500}))
        history.record(_metrics(0, ts=1000.0))
        history.record(_metrics(1, ts=1100.0))   # same segment
        history.record(_metrics(2, ts=1700.0))   # > 500s later: rolls
        history.stop()
        segs = history.segment_paths()
        assert len(segs) == 2, segs
        first = [json.loads(ln) for ln in open(segs[0])]
        second = [json.loads(ln) for ln in open(segs[1])]
        assert [r["ts"] for r in first] == [1000.0, 1100.0]
        assert [r["ts"] for r in second] == [1700.0]

    def test_full_queue_drops_and_counts_never_blocks(self, tmp_path):
        history.configure(TpuConf({
            "spark.rapids.tpu.obs.history.dir": str(tmp_path)}))
        history.stop()                      # kill the writer...
        history._Q = _pyqueue.Queue(maxsize=1)   # ...and leave a full q
        history._Q.put_nowait(_row())
        before = history.stats_section()["dropped"]
        row = history.record(_metrics(0))        # must not block
        assert row is not None
        assert history.stats_section()["dropped"] == before + 1
        history._Q = None

    def test_in_memory_only_without_dir(self):
        history.configure(TpuConf({}))
        row = history.record(_metrics(0))
        assert row is not None
        assert history.segment_paths() == []
        assert history.stats_section()["rows"] == 1
        assert history.fleet_aggregates()["unknown"]["count"] == 1

    def test_adopts_newest_segment_across_restart(self, tmp_path):
        conf = TpuConf({
            "spark.rapids.tpu.obs.history.dir": str(tmp_path)})
        history.configure(conf)
        history.record(_metrics(0))
        history.stop()
        history.configure(conf)             # simulated restart
        history.record(_metrics(1))
        history.stop()
        segs = history.segment_paths()
        assert len(segs) == 1
        assert len(open(segs[0]).readlines()) == 2

    def test_disabled_records_nothing(self):
        history.configure(TpuConf({
            "spark.rapids.tpu.obs.history.enabled": False}))
        assert history.record(_metrics(0)) is None
        assert history.stats_section()["rows"] == 0


# ---------------------------------------------------------------------------
# anomaly sentinel
# ---------------------------------------------------------------------------

def _sentinel_conf(minn=5, k=3, sigma=2.0):
    return TpuConf({
        "spark.rapids.tpu.obs.anomaly.warmupMinRuns": minn,
        "spark.rapids.tpu.obs.anomaly.breachRuns": k,
        "spark.rapids.tpu.obs.anomaly.sigma": sigma,
    })


class TestAnomaly:
    def test_warmup_never_alarms(self):
        anomaly.configure(_sentinel_conf(minn=10))
        events = []
        for i in range(10):
            events += anomaly.fold(_row(exec_ms=100.0 * (i + 1), i=i))
        assert events == []

    def test_k_consecutive_outliers_breach_once(self):
        anomaly.configure(_sentinel_conf())
        for i in range(6):
            assert anomaly.fold(_row(exec_ms=100.0 + i % 3, i=i)) == []
        got = []
        for i in range(6, 12):
            got += anomaly.fold(_row(exec_ms=300.0, i=i))
        breaches = [e for e in got if e["kind"] == "breach"]
        assert len(breaches) == 1
        assert breaches[0]["fingerprint"] == "fpA"
        assert breaches[0]["key"] == "exec_ms"
        assert breaches[0]["drift_pct"] > 100
        assert anomaly.active_count() == 1

    def test_single_spike_below_k_never_breaches(self):
        anomaly.configure(_sentinel_conf(k=3))
        for i in range(6):
            anomaly.fold(_row(exec_ms=100.0, i=i))
        evs = list(anomaly.fold(_row(exec_ms=900.0, i=6)))
        evs += anomaly.fold(_row(exec_ms=100.0, i=7))
        assert [e for e in evs if e["kind"] == "breach"] == []
        assert anomaly.active_count() == 0

    def test_level_shift_not_absorbed_then_recovery(self):
        # outliers never train the model, so a sustained shift stays
        # active until the metric actually returns to the baseline
        anomaly.configure(_sentinel_conf())
        for i in range(6):
            anomaly.fold(_row(exec_ms=100.0, i=i))
        for i in range(6, 16):
            anomaly.fold(_row(exec_ms=300.0, i=i))
        assert anomaly.active_count() == 1
        rec = []
        for i in range(16, 22):
            rec += anomaly.fold(_row(exec_ms=100.0, i=i))
        assert [e for e in rec if e["kind"] == "recovery"]
        assert anomaly.active_count() == 0

    def test_exact_key_flush_count_change_breaches(self):
        anomaly.configure(_sentinel_conf())
        for i in range(6):
            anomaly.fold(_row(flushes=2, i=i))
        got = []
        for i in range(6, 10):
            got += anomaly.fold(_row(flushes=3, i=i))
        keys = {e["key"] for e in got if e["kind"] == "breach"}
        assert "flushes" in keys

    def test_breach_isolated_to_drifting_fingerprint(self):
        anomaly.configure(_sentinel_conf())
        for i in range(6):
            anomaly.fold(_row(fp="good", exec_ms=100.0, i=i))
            anomaly.fold(_row(fp="bad", exec_ms=100.0, i=i))
        got = []
        for i in range(6, 12):
            got += anomaly.fold(_row(fp="good", exec_ms=100.0, i=i))
            got += anomaly.fold(_row(fp="bad", exec_ms=400.0, i=i))
        assert {e["fingerprint"] for e in got
                if e["kind"] == "breach"} == {"bad"}

    def test_trend_and_cause_shift(self):
        anomaly.configure(_sentinel_conf())
        for i in range(5):
            anomaly.fold(_row(exec_ms=100.0, i=i, cause="host_staging"))
        for i in range(5, 60):
            anomaly.fold(_row(exec_ms=100.0, i=i,
                              cause="device_compute"))
        t = anomaly.trend_section()["fpA"]
        assert t["runs"] == 60
        assert t["drift"]["exec_ms"]["baseline"] > 0
        assert t["cause_shift"] == {"from": "host_staging",
                                    "to": "device_compute"}

    def test_doctor_stats_carry_trend(self):
        from spark_rapids_tpu.obs import doctor
        anomaly.configure(_sentinel_conf())
        for i in range(8):
            anomaly.fold(_row(exec_ms=100.0, i=i))
        assert "fpA" in doctor.stats_section().get("trend", {})

    def test_bundle_rate_limit(self):
        anomaly.configure(TpuConf({
            "spark.rapids.tpu.obs.anomaly.bundleIntervalSeconds": 3600}))
        assert anomaly.should_bundle() is True
        assert anomaly.should_bundle() is False

    def test_disabled_folds_nothing(self):
        anomaly.configure(TpuConf({
            "spark.rapids.tpu.obs.anomaly.enabled": False}))
        for i in range(20):
            assert anomaly.fold(_row(exec_ms=100.0 * (i + 1), i=i)) == []
        assert anomaly.stats_section()["fingerprints"] == 0


# ---------------------------------------------------------------------------
# scrape-server lifecycle + dashboard
# ---------------------------------------------------------------------------

class TestScrapeServer:
    def test_back_to_back_servers_on_one_port(self):
        from spark_rapids_tpu.obs.prom import serve_scrapes
        s1, port = serve_scrapes(0)
        s1.stop()
        s2, p2 = serve_scrapes(port)     # rebind right after stop()
        try:
            assert p2 == port
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5).read()
            assert b"tpu_history_rows_total" in body
        finally:
            s2.stop()
        s2.stop()                        # idempotent

    def test_live_port_raises_clear_error(self):
        from spark_rapids_tpu.obs.prom import (ScrapeServerBusyError,
                                               serve_scrapes)
        s1, port = serve_scrapes(0)
        try:
            with pytest.raises(ScrapeServerBusyError) as ei:
                serve_scrapes(port)
            assert str(port) in str(ei.value)
        finally:
            s1.stop()

    def test_dashboard_route(self):
        from spark_rapids_tpu.obs.prom import serve_scrapes
        history.configure(TpuConf({}))
        history.note_query("q0", {"fingerprint": "fpDash"})
        history.record(_metrics(0))
        s1, port = serve_scrapes(0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/dashboard", timeout=5) \
                .read().decode()
            assert "TPU fleet dashboard" in body
            assert "fpDash" in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=5)
        finally:
            s1.stop()


class TestDashboard:
    def test_render_escapes_and_degrades(self):
        from spark_rapids_tpu.obs import dashboard
        history.configure(TpuConf({}))
        history.note_query("q0", {
            "fingerprint": "<script>alert(1)</script>",
            "doctor_cause": "device_compute"})
        history.record(_metrics(0))
        html = dashboard.render_html()
        assert "<script>alert(1)</script>" not in html
        assert "&lt;script&gt;" in html

    def test_render_empty_state(self):
        from spark_rapids_tpu.obs import dashboard
        html = dashboard.render_html()
        assert "no history rows yet" in html


# ---------------------------------------------------------------------------
# offline CLI
# ---------------------------------------------------------------------------

class TestHistoryCli:
    def _seed(self, tmp_path, n=20):
        history.configure(TpuConf({
            "spark.rapids.tpu.obs.history.dir": str(tmp_path)}))
        for i in range(n):
            history.note_query(f"q{i}", {"fingerprint": "fpCli"})
            history.record(_metrics(
                i, exec_ms=100.0 if i < n // 2 else 200.0,
                ts=1000.0 + i))
        history.stop()

    def test_load_and_summary(self, tmp_path):
        self._seed(tmp_path)
        from spark_rapids_tpu.tools import history as cli
        rows = cli.load_rows(str(tmp_path))
        assert len(rows) == 20
        summ = cli.summarize(rows)
        assert summ["fpCli"]["count"] == 20
        assert summ["fpCli"]["outcomes"] == {"completed": 20}

    def test_trend_and_compare(self, tmp_path, capsys):
        self._seed(tmp_path)
        from spark_rapids_tpu.tools import history as cli
        rows = cli.load_rows(str(tmp_path), fingerprint="fpCli")
        series = cli.trend(rows, "exec_ms", buckets=4)
        assert len(series) == 4
        assert series[-1]["p50"] > series[0]["p50"]
        res = cli.compare_windows(rows, keys=("exec_ms",))
        assert res["keys"]["exec_ms"]["delta_pct"] == pytest.approx(
            100.0, abs=1.0)
        assert cli.main(["summary", str(tmp_path)]) == 0
        assert cli.main(["trend", str(tmp_path), "--fingerprint",
                         "fpCli"]) == 0
        assert cli.main(["compare", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "fpCli" in out

    def test_empty_dir_exits_nonzero(self, tmp_path):
        from spark_rapids_tpu.tools import history as cli
        assert cli.main(["summary", str(tmp_path)]) == 1


# ---------------------------------------------------------------------------
# lint scope extension + seeded fixture
# ---------------------------------------------------------------------------

class TestFleetLint:
    MODULES = ("spark_rapids_tpu/obs/fingerprint.py",
               "spark_rapids_tpu/obs/history.py",
               "spark_rapids_tpu/obs/anomaly.py",
               "spark_rapids_tpu/obs/dashboard.py",
               "spark_rapids_tpu/analysis/bands.py",
               "spark_rapids_tpu/tools/history.py")

    def test_fleet_modules_in_sync_obs_hyg_scopes(self):
        from spark_rapids_tpu.analysis import lint as AL
        for rel in self.MODULES:
            scopes = AL._scopes_for(rel)
            assert AL.SYNC001 in scopes, rel
            assert AL.OBS002 in scopes, rel
            assert AL.HYG002 in scopes, rel

    def test_seeded_fleet_fixture_trips_all_three_rules(self):
        from spark_rapids_tpu.analysis import lint as AL
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "lint_fixtures", "fleet_sync.py")
        with open(path) as f:
            fs = AL.lint_source(f.read(), path)
        rules = {f.rule for f in fs}
        assert {AL.SYNC001, AL.OBS002, AL.HYG002} <= rules

    def test_shipped_fleet_modules_lint_clean(self):
        from spark_rapids_tpu.analysis import lint as AL
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in self.MODULES:
            path = os.path.join(repo, rel)
            with open(path) as f:
                fs = AL.lint_source(f.read(), rel,
                                    scopes=AL._scopes_for(rel))
            assert fs == [], (rel, AL.format_findings(fs))


# ---------------------------------------------------------------------------
# service integration: one row per terminal query, zero extra flushes
# ---------------------------------------------------------------------------

class TestServiceIntegration:
    def test_history_rows_match_terminal_queries(self, tmp_path):
        from spark_rapids_tpu.service.server import QueryService
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.obs.history.dir": str(tmp_path)}))
        df = s.range(0, 64, num_partitions=2) \
            .select((F.col("id") % 7).alias("k"),
                    F.col("id").alias("v")) \
            .group_by("k").agg(F.sum("v").alias("sv"))
        with QueryService(s, num_workers=1) as svc:
            for _ in range(3):
                svc.submit(df).result(60)
            snap = svc.stats().snapshot()
        assert snap["history"]["rows"] == 3
        assert snap["history"]["dropped"] == 0
        assert snap["history"]["fingerprints"] == 1
        assert snap["anomaly"]["checks"] > 0
        fp = next(iter(history.fleet_aggregates()))
        assert fp != "unknown" and len(fp) == 16

    def test_history_off_adds_zero_device_flushes(self):
        from spark_rapids_tpu.columnar import pending as _pending

        def _run(conf):
            s = TpuSession(conf)
            df = s.range(0, 64, num_partitions=2) \
                .select((F.col("id") % 7).alias("k")) \
                .group_by("k").agg(F.count("k").alias("c"))
            df.collect()                  # warm
            f0 = _pending.FLUSH_COUNT
            df.collect()
            return _pending.FLUSH_COUNT - f0

        on = _run(TpuConf({}))
        off = _run(TpuConf({
            "spark.rapids.tpu.obs.history.enabled": False,
            "spark.rapids.tpu.obs.anomaly.enabled": False}))
        assert on == off
