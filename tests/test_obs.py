"""Observability tests: span tracer, metrics registry, Prometheus
exposition, and the query report generator."""
import json
import threading
import urllib.request

import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.obs import trace, registry
from spark_rapids_tpu.obs.prom import render_text, serve_scrapes
from spark_rapids_tpu.obs.registry import MetricsRegistry, get_registry

from data_gen import IntGen, KeyGen, gen_df


@pytest.fixture(autouse=True)
def _trace_off_after():
    yield
    trace.disable()
    trace.reset()


class TestSpanTracer:
    def test_disabled_span_is_shared_noop(self):
        assert trace.span("a") is trace.span("b", "kernel", x=1)
        # disabled traced functions call straight through
        @trace.traced("f")
        def f(x):
            return x + 1
        assert f(1) == 2
        assert trace.get_tracer().num_spans() == 0

    def test_spans_record_and_nest(self):
        trace.enable()
        with trace.span("outer", "engine"):
            with trace.span("inner", "kernel", k="v"):
                pass
        tr = trace.get_tracer()
        assert tr.num_spans() == 2
        doc = tr.to_chrome_trace()
        by_name = {e["name"]: e for e in doc["traceEvents"]
                   if e.get("ph") == "X"}
        assert by_name["inner"]["args"]["depth"] == \
            by_name["outer"]["args"]["depth"] + 1
        assert by_name["inner"]["args"]["k"] == "v"
        # inner fully contained in outer on the timeline
        o, i = by_name["outer"], by_name["inner"]
        assert o["ts"] <= i["ts"]
        assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1e-3

    def test_span_records_error_type(self):
        trace.enable()
        with pytest.raises(ValueError):
            with trace.span("boom"):
                raise ValueError("x")
        doc = trace.get_tracer().to_chrome_trace()
        ev = [e for e in doc["traceEvents"] if e.get("name") == "boom"][0]
        assert ev["args"]["error"] == "ValueError"

    def test_query_id_attribution(self):
        from spark_rapids_tpu.service.cancellation import (CancelToken,
                                                           query_context)
        trace.enable()
        with query_context(CancelToken("q42")):
            with trace.span("work"):
                pass
        doc = trace.get_tracer().to_chrome_trace()
        ev = [e for e in doc["traceEvents"] if e.get("name") == "work"][0]
        assert ev["args"]["query_id"] == "q42"

    def test_emit_retroactive(self):
        import time
        trace.enable()
        t0 = time.perf_counter_ns()
        trace.emit("waited", "memory", t0, 5_000_000, note="x")
        doc = trace.get_tracer().to_chrome_trace()
        ev = [e for e in doc["traceEvents"] if e.get("name") == "waited"][0]
        assert ev["dur"] == pytest.approx(5000.0)  # µs

    def test_bounded_buffer_counts_drops(self):
        trace.enable(max_spans=3)
        for i in range(5):
            with trace.span(f"s{i}"):
                pass
        tr = trace.get_tracer()
        assert tr.num_spans() == 3
        assert tr.dropped == 2
        assert tr.to_chrome_trace()["otherData"]["dropped_spans"] == 2

    def test_write_and_reload_chrome_json(self, tmp_path):
        trace.enable()
        with trace.span("x"):
            pass
        path = str(tmp_path / "t.json")
        out = trace.flush(path)
        assert out == path
        doc = json.load(open(path))
        assert doc["displayTimeUnit"] == "ms"
        phs = {e["ph"] for e in doc["traceEvents"]}
        assert phs <= {"X", "M"}
        for e in doc["traceEvents"]:
            if e["ph"] == "X":
                assert {"name", "cat", "ts", "dur", "pid",
                        "tid"} <= set(e)

    def test_flush_without_path_is_noop(self):
        trace.enable()
        assert trace.flush() is None

    def test_session_conf_end_to_end(self, tmp_path):
        path = str(tmp_path / "trace.json")
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.obs.trace.enabled": True,
            "spark.rapids.tpu.obs.trace.path": path,
        }))
        df = gen_df(s, {"k": KeyGen(), "v": IntGen()}, 200)
        df.group_by("k").agg(F.sum("v").alias("s")).collect()
        doc = json.load(open(path))
        names = {e["name"] for e in doc["traceEvents"]}
        assert "query" in names
        cats = {e.get("cat") for e in doc["traceEvents"]
                if e.get("ph") == "X"}
        # engine (query) + exec (operators) at minimum; kernels when the
        # plan dispatches them
        assert {"engine", "exec"} <= cats


class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("c_total", "help")
        c.inc()
        c.inc(2)
        g = reg.gauge("g", "help")
        g.set(5)
        g.dec(1.5)
        h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(10.0)
        snap = reg.snapshot()
        assert snap["c_total"] == 3
        assert snap["g"] == 3.5
        hs = snap["h_seconds"]
        assert hs["count"] == 3
        assert hs["buckets"][0.1] == 1
        assert hs["buckets"][1.0] == 2          # cumulative
        assert hs["buckets"]["+Inf"] == 3

    def test_labels_and_deterministic_snapshot(self):
        reg = MetricsRegistry()
        c = reg.counter("ops_total", labels=("kind",))
        c.labels(kind="b").inc(2)
        c.labels(kind="a").inc(1)
        snap = reg.snapshot()
        assert list(snap["ops_total"]) == ["kind=a", "kind=b"]
        # get-or-create returns the same family
        assert reg.counter("ops_total", labels=("kind",)) is c

    def test_gauge_callback(self):
        reg = MetricsRegistry()
        state = {"v": 7}
        reg.gauge("cb", fn=lambda: state["v"])
        assert reg.snapshot()["cb"] == 7
        state["v"] = 9
        assert reg.snapshot()["cb"] == 9

    def test_default_instruments_registered(self):
        snap = get_registry().snapshot()
        for name in ("tpu_arena_device_bytes", "tpu_arena_device_peak_bytes",
                     "tpu_semaphore_wait_seconds",
                     "tpu_service_queue_wait_seconds",
                     "tpu_compile_cache_requests_total",
                     "tpu_shuffle_bytes_total"):
            assert name in snap, name

    def test_arena_peak_gauge_tracks_catalog(self):
        from spark_rapids_tpu.memory.catalog import BufferCatalog
        cat = BufferCatalog.get()
        base = cat.device_peak_bytes
        bid = cat.register(object(), 1234)
        try:
            assert registry.ARENA_DEVICE_PEAK_BYTES.value >= base + 1234
            assert cat.stats()["device_peak_bytes"] == cat.device_peak_bytes
        finally:
            cat.unregister(bid)


class TestPromExposition:
    def test_render_text_format(self):
        reg = MetricsRegistry()
        reg.counter("a_total", "a help").inc(2)
        reg.gauge("b", 'hel"p\nnl').set(1.5)
        h = reg.histogram("lat_seconds", buckets=(0.1, 1.0),
                          labels=("op",))
        h.labels(op="x").observe(0.5)
        txt = render_text(reg)
        lines = txt.splitlines()
        assert "# TYPE a_total counter" in lines
        assert "a_total 2" in lines
        assert "# TYPE lat_seconds histogram" in lines
        assert 'lat_seconds_bucket{op="x",le="0.1"} 0' in lines
        assert 'lat_seconds_bucket{op="x",le="1"} 1' in lines
        assert 'lat_seconds_bucket{op="x",le="+Inf"} 1' in lines
        assert 'lat_seconds_count{op="x"} 1' in lines
        # +Inf bucket must equal _count (prometheus invariant)
        assert txt.endswith("\n")

    def test_scrape_endpoint(self):
        server, port = serve_scrapes(port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5).read()
            assert b"tpu_arena_device_bytes" in body
        finally:
            server.shutdown()

    def test_service_metrics_text_covers_series(self, tmp_path):
        from spark_rapids_tpu.service.server import QueryService
        s = TpuSession(TpuConf({}))
        df = gen_df(s, {"k": KeyGen(), "v": IntGen()}, 200)
        s.register_table("obs_t", df)
        with QueryService(s, num_workers=1) as svc:
            svc.submit("SELECT k, SUM(v) FROM obs_t GROUP BY k").result(60)
            txt = svc.metrics_text()
            stats = svc.stats().snapshot()
        for series in ("tpu_arena_device_bytes",
                       "tpu_semaphore_wait_seconds",
                       "tpu_service_queue_wait_seconds",
                       "tpu_compile_cache_requests_total",
                       "tpu_service_queries_total"):
            assert series in txt, series
        assert 'tpu_service_queries_total{event="completed"}' in txt
        assert stats["completed"] >= 1
        # queue-wait histogram observed the query
        hist = get_registry().snapshot()["tpu_service_queue_wait_seconds"]
        assert hist["count"] >= 1


class TestMetricSetDeterminism:
    def test_snapshot_sorted_and_level_filtered(self):
        from spark_rapids_tpu.exec.base import (MetricSet, ESSENTIAL,
                                                DEBUG, MODERATE)
        ms = MetricSet()
        ms.get("zeta", ESSENTIAL).add(1)
        ms.get("alpha", ESSENTIAL).add(2)
        ms.get("mid", MODERATE).add(3)
        assert list(ms.snapshot(DEBUG)) == ["alpha", "mid", "zeta"]
        assert list(ms.snapshot(ESSENTIAL)) == ["alpha", "zeta"]

    def test_essential_snapshot_skips_deferred_device_reads(self):
        from spark_rapids_tpu.exec.base import (MetricSet, ESSENTIAL,
                                                MODERATE)

        class Exploding:
            def __int__(self):
                raise AssertionError("deferred value was forced")

        ms = MetricSet()
        ms.get("wall", ESSENTIAL).add(5)
        ms.get("deviceRows", MODERATE).add(Exploding())
        # ESSENTIAL snapshot must not resolve the MODERATE metric's
        # pending device value (no device sync)
        snap = ms.snapshot(ESSENTIAL)
        assert snap == {"wall": 5}


class TestTimedSpans:
    def test_timed_emits_exec_span_with_node_name(self):
        from spark_rapids_tpu.exec.base import Metric, timed

        class FakeNode:
            name = "TpuFakeOp"

        trace.enable()
        with timed(Metric("opTime"), FakeNode()):
            pass
        doc = trace.get_tracer().to_chrome_trace()
        evs = [e for e in doc["traceEvents"]
               if e.get("name") == "TpuFakeOp"]
        assert evs and evs[0]["cat"] == "exec"
        assert evs[0]["args"]["metric"] == "opTime"

    def test_timed_without_tracing_allocates_no_span(self):
        from spark_rapids_tpu.exec.base import Metric, timed
        m = Metric("opTime")
        with timed(m) as t:
            assert t._span is None
        assert m.value > 0


class TestReportTool:
    def _make_log(self, tmp_path):
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({"spark.rapids.tpu.eventLog.path": log}))
        df = gen_df(s, {"k": KeyGen(), "v": IntGen()}, 300)
        df.group_by("k").agg(F.sum("v").alias("s")).collect()
        return log

    def test_report_text(self, tmp_path, capsys):
        from spark_rapids_tpu.tools.report import main
        log = self._make_log(tmp_path)
        assert main([log]) == 0
        out = capsys.readouterr().out
        assert "plan + time shares" in out
        assert "TpuHashAggregate" in out
        assert "%" in out

    def test_report_html(self, tmp_path):
        from spark_rapids_tpu.tools.report import main
        log = self._make_log(tmp_path)
        html_path = str(tmp_path / "report.html")
        assert main([log, "--html", html_path]) == 0
        html = open(html_path).read()
        assert html.startswith("<!DOCTYPE html>")
        assert "TpuHashAggregate" in html

    def test_report_joins_trace(self, tmp_path, capsys):
        from spark_rapids_tpu.tools.report import main
        tp = str(tmp_path / "trace.json")
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.eventLog.path": log,
            "spark.rapids.tpu.obs.trace.enabled": True,
            "spark.rapids.tpu.obs.trace.path": tp,
        }))
        df = gen_df(s, {"k": KeyGen(), "v": IntGen()}, 300)
        df.group_by("k").agg(F.sum("v").alias("s")).collect()
        assert main([log, "--trace", tp]) == 0
        out = capsys.readouterr().out
        assert "critical-path spans" in out
        assert "query" in out

    def test_plan_time_shares_sum_to_one(self, tmp_path):
        from spark_rapids_tpu.tools.report import plan_time_shares
        from spark_rapids_tpu.tools.events import read_event_log
        log = self._make_log(tmp_path)
        rec = read_event_log(log)[0]
        rows = plan_time_shares(rec)
        assert rows
        assert sum(r["share"] for r in rows) == pytest.approx(1.0)
