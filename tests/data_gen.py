"""Composable random typed data generators.

Reference: integration_tests data_gen.py (928 LoC): nullable ratios,
special values (NaN, +-0.0, int extremes, epoch edges), deterministic
seeds.
"""
import string

import numpy as np

from spark_rapids_tpu.columnar import dtypes as T


class DataGen:
    def __init__(self, dtype, nullable=True, null_ratio=0.1):
        self.dtype = dtype
        self.nullable = nullable
        self.null_ratio = null_ratio if nullable else 0.0

    def generate(self, rng, n):
        vals = self._values(rng, n)
        if self.null_ratio > 0:
            mask = rng.random(n) < self.null_ratio
            vals = [None if m else v for v, m in zip(vals, mask)]
        return list(vals)

    def _values(self, rng, n):
        raise NotImplementedError


class IntGen(DataGen):
    SPECIALS = [0, 1, -1, 2**31 - 1, -2**31, 2**63 - 1, -2**63]

    def __init__(self, dtype=T.INT64, lo=None, hi=None, **kw):
        super().__init__(dtype, **kw)
        info = np.iinfo(dtype.np_dtype)
        self.lo = info.min if lo is None else lo
        self.hi = info.max if hi is None else hi

    def _values(self, rng, n):
        vals = rng.integers(self.lo, self.hi, n, dtype=np.int64,
                            endpoint=True)
        out = [int(v) for v in vals]
        specials = [s for s in self.SPECIALS if self.lo <= s <= self.hi]
        for i in range(min(len(specials), n // 10)):
            out[int(rng.integers(0, n))] = specials[i]
        return out


class FloatGen(DataGen):
    SPECIALS = [0.0, -0.0, float("nan"), float("inf"), float("-inf"),
                1.0, -1.0]

    def __init__(self, dtype=T.FLOAT64, no_nans=False, **kw):
        super().__init__(dtype, **kw)
        self.no_nans = no_nans

    def _values(self, rng, n):
        out = list((rng.random(n) - 0.5) * 2e6)
        specials = [s for s in self.SPECIALS
                    if not (self.no_nans and (s != s))]
        for i in range(min(len(specials), n // 10)):
            out[int(rng.integers(0, n))] = specials[i]
        return [float(v) for v in out]


class BoolGen(DataGen):
    def __init__(self, **kw):
        super().__init__(T.BOOL, **kw)

    def _values(self, rng, n):
        return [bool(v) for v in rng.integers(0, 2, n)]


class StringGen(DataGen):
    def __init__(self, max_len=12, charset=string.ascii_letters + "0123456789",
                 **kw):
        super().__init__(T.STRING, **kw)
        self.max_len = max_len
        self.charset = charset

    def _values(self, rng, n):
        out = []
        for _ in range(n):
            k = int(rng.integers(0, self.max_len + 1))
            out.append("".join(self.charset[int(i)] for i in
                               rng.integers(0, len(self.charset), k)))
        if n > 3:
            out[0] = ""
            out[1] = " lead"
            out[2] = "trail "
        return out


class DateGen(DataGen):
    def __init__(self, **kw):
        super().__init__(T.DATE, **kw)

    def _values(self, rng, n):
        vals = [int(v) for v in rng.integers(-30000, 30000, n)]
        if n > 2:
            vals[0] = 0
            vals[1] = -1
        return vals


class TimestampGen(DataGen):
    def __init__(self, **kw):
        super().__init__(T.TIMESTAMP, **kw)

    def _values(self, rng, n):
        return [int(v) for v in
                rng.integers(-2**50, 2**50, n)]


class KeyGen(DataGen):
    """Low-cardinality int keys for join/group tests."""

    def __init__(self, cardinality=20, **kw):
        super().__init__(T.INT64, **kw)
        self.cardinality = cardinality

    def _values(self, rng, n):
        return [int(v) for v in rng.integers(0, self.cardinality, n)]


def gen_table(gens: dict, n: int, seed: int = 42):
    """dict of name -> DataGen => dict of name -> list (pydict)."""
    rng = np.random.default_rng(seed)
    return {name: g.generate(rng, n) for name, g in gens.items()}


def gen_df(session, gens: dict, n: int, seed: int = 42, num_partitions=1):
    from spark_rapids_tpu.columnar import Schema, Field
    data = gen_table(gens, n, seed)
    schema = Schema([Field(name, g.dtype, g.nullable)
                     for name, g in gens.items()])
    return session.create_dataframe(data, schema=schema,
                                    num_partitions=num_partitions)


class DecimalGen(DataGen):
    """DECIMAL(p, s) values (exact int64 unscaled under the hood)."""

    SPECIAL_UNSCALED = [0, 1, -1]

    def __init__(self, precision=10, scale=2, **kw):
        import decimal
        super().__init__(T.DecimalType(precision, scale), **kw)
        self.precision = precision
        self.scale = scale
        self._dec = decimal.Decimal

    def _values(self, rng, n):
        import decimal
        hi = 10 ** self.precision - 1
        vals = [int(v) for v in rng.integers(-hi, hi, n)]
        specials = self.SPECIAL_UNSCALED + [hi, -hi]
        for i in range(min(len(specials), n // 10)):
            vals[int(rng.integers(0, n))] = specials[i]
        q = decimal.Decimal(1).scaleb(-self.scale)
        return [(self._dec(v) * q) for v in vals]


class EpochEdgeDateGen(DateGen):
    """Dates clustered at epoch edges (the reference's epoch-edge
    specials: 1969-12-31, 1970-01-01, far past/future)."""

    def _values(self, rng, n):
        vals = super()._values(rng, n)
        edges = [0, -1, 1, -719162, 2932896]  # 0001-01-01, 9999-12-31
        for i, e in enumerate(edges):
            if i < n:
                vals[int(rng.integers(0, n))] = e
        return vals


class UnicodeStringGen(StringGen):
    """Multi-byte UTF-8 content (2/3/4-byte code points) exercising the
    byte-vs-codepoint distinction in string kernels."""

    def __init__(self, **kw):
        kw.setdefault("charset",
                      "aZ9éß中文\U0001f600-_ ")
        super().__init__(**kw)


ALL_GENS = {
    "int64": lambda: IntGen(),
    "int32": lambda: IntGen(T.INT32, lo=-2**31, hi=2**31 - 1),
    "small_int": lambda: IntGen(lo=-1000, hi=1000),
    "float64": lambda: FloatGen(),
    "float_no_nan": lambda: FloatGen(no_nans=True),
    "bool": lambda: BoolGen(),
    "string": lambda: StringGen(),
    "unicode": lambda: UnicodeStringGen(),
    "date": lambda: DateGen(),
    "edge_date": lambda: EpochEdgeDateGen(),
    "timestamp": lambda: TimestampGen(),
    "decimal": lambda: DecimalGen(),
    "key": lambda: KeyGen(),
}


def random_schema_gens(rng, n_cols=None, pool=None):
    """FuzzerUtils role: a random schema of named generators."""
    names = sorted(pool or ALL_GENS)
    k = int(n_cols or rng.integers(2, 6))
    picks = [names[int(i)] for i in rng.integers(0, len(names), k)]
    return {f"c{i}_{p}": ALL_GENS[p]() for i, p in enumerate(picks)}
