"""End-to-end query across two OS processes: map stage in a child
executor, reduce in the parent over the TCP shuffle wire — plus the
dead-executor retry path (ShuffleFetchFailedError -> local map re-run).
Reference: RapidsShuffleInternalManagerBase write/read split + Spark
stage retry."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as papq
import pytest

from spark_rapids_tpu.distributed import run_two_process_query


@pytest.fixture(scope="module")
def table_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("dist_tables")
    rng = np.random.default_rng(42)
    tdir = os.path.join(str(d), "t")
    os.makedirs(tdir)
    # several files -> several map partitions -> a real exchange
    for i in range(4):
        n = 5_000
        papq.write_table(pa.table({
            "k": rng.integers(0, 1000, n).astype(np.int64),
            "v": rng.standard_normal(n),
            "w": rng.integers(-50, 50, n).astype(np.int64),
        }), os.path.join(tdir, f"part-{i}.parquet"))
    return {"t": tdir}


SQL = """
  select k % 16 as grp, sum(w) as sw, count(*) as c, avg(v) as av
  from t group by k % 16 order by grp"""


def _local_rows(tables):
    from spark_rapids_tpu.distributed import _make_session
    return _make_session(tables).sql(SQL).collect()


def test_query_across_two_processes(table_dir):
    out, recovered = run_two_process_query(SQL, table_dir)
    assert not recovered
    got = list(zip(*[out.column(i).to_pylist()
                     for i in range(out.num_columns)]))
    want = _local_rows(table_dir)
    assert len(got) == len(want) == 16
    for a, b in zip(got, want):
        assert a[0] == b[0] and a[1] == b[1] and a[2] == b[2]
        assert abs(a[3] - b[3]) < 1e-9


def test_dead_executor_recovers_by_rerunning_map(table_dir):
    out, recovered = run_two_process_query(
        SQL, table_dir, kill_child_before_reduce=True)
    assert recovered, "expected ShuffleFetchFailedError + local retry"
    got = list(zip(*[out.column(i).to_pylist()
                     for i in range(out.num_columns)]))
    want = _local_rows(table_dir)
    assert len(got) == len(want) == 16
    for a, b in zip(got, want):
        assert a[0] == b[0] and a[1] == b[1] and a[2] == b[2]
        assert abs(a[3] - b[3]) < 1e-9
