"""Seeded OBS003 fixture — ``ci/lint.py`` must exit NONZERO.

Self-meter record-path functions shaped like ``obs/overhead.py`` but
allocating per call: a dict literal where the plane counters should be
preallocated lists, an f-string label, and an eager ``str()``.  The
meter brackets every default-on plane's hot entry points, so any
allocation here is paid on every metered call — a tax on the tax.
Never imported by the engine.
"""
import time

_NS = [0] * 4


def note_bad_dict(plane, t0):
    # per-call dict allocation instead of a preallocated counter list
    cell = {"plane": plane, "ns": time.perf_counter_ns() - t0}
    return cell


def record_bad_label(plane, t0):
    name = f"plane:{plane}"
    _NS[plane] += time.perf_counter_ns() - t0
    return name


def note_good(plane, t0):
    # the allocation-free shape: interned id, preallocated list write
    _NS[plane] += time.perf_counter_ns() - t0
