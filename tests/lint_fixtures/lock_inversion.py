"""Seeded LOCK001/LOCK002 fixture — ``ci/lint.py`` must exit NONZERO.

Two module locks acquired in opposite orders from two call paths (the
classic AB/BA deadlock), plus a sleep and socket write performed while
holding a lock.  Never imported by the engine; exists only so the lint
self-tests can prove the analyzer fires.
"""
import threading
import time

lock_a = threading.Lock()
lock_b = threading.Lock()


def forward():
    with lock_a:
        with lock_b:
            return 1


def backward():
    with lock_b:
        with lock_a:
            return 2


def blocking_under_lock(sock):
    with lock_a:
        time.sleep(0.1)
        sock.sendall(b"x")
