"""Seeded SYNC001/OBS002/HYG002 fixture shaped like a cost-plane
helper — ``ci/lint.py`` must exit NONZERO.

The device-compute cost plane (obs/costplane.py) captures static XLA
costs at compile time and joins them with dispatch counters the exec
layer already maintains, so its lint scope bans exactly what this
helper does: pulling a device buffer to "measure" achieved rates,
materializing args to size a bucket, a flight-recorder event that
allocates per capture, and a wall-clock read where the busy window
must come from the monotonic flush observer.  Never imported by the
engine.
"""
import time

import jax
import numpy as np

from spark_rapids_tpu.obs import flight as _flight


def bad_capture(cache, dev, bucket):
    host = jax.device_get(dev)                # SYNC001: host pull
    rows = np.asarray(dev).shape[0]           # SYNC001: materialization
    jax.block_until_ready(dev)                # SYNC001: forced sync
    _flight.record(_flight.EV_COST, f"{cache}:{bucket}")  # OBS002
    stamp = time.time()                       # HYG002: wall clock
    return host, rows, stamp


def good_capture(cache, lowered, bucket):
    # the cost plane's real shape: static cost_analysis() of an
    # already-lowered program, interned name constants, int args only
    costs = lowered.cost_analysis() or {}
    _flight.record(_flight.EV_COST, name=cache, a=int(bucket),
                   b=int(costs.get("flops", 0.0)))
    return costs
