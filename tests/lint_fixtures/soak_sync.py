"""Seeded SYNC001/OBS002/HYG002 fixture shaped like a soak-plane
helper — ``ci/lint.py`` must exit NONZERO.

The soak plane (obs/burn.py, service/soak.py, service/faults.py)
drives the REAL service and folds rows the planes already collected —
its lint scope bans exactly what this helper does: a device pull
while "sampling" the drift window, a fault marker that allocates its
name per fire, and a wall-clock read where the row's own timestamp
(or a monotonic clock) is required.  Never imported by the engine.
"""
import time

import jax
import numpy as np

from spark_rapids_tpu.obs import flight as _flight


def bad_sample(dev, window):
    floor = np.asarray(dev).min()             # SYNC001: materialization
    evidence = jax.device_get(dev)            # SYNC001: host pull
    _flight.record(_flight.EV_FAULT, f"fault:{window}")  # OBS002
    stamp = time.time()                       # HYG002: wall clock
    return floor, evidence, stamp


def good_sample(row, samples):
    # the burn plane's real shape: host arithmetic over bytes already
    # sampled, interned name constants, the row's own timestamp
    _flight.record(_flight.EV_FAULT, "fault", a=int(row.get("ts", 0)))
    samples.append(int(row.get("device_bytes", 0)))
    return min(samples)
