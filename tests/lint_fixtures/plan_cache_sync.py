"""Seeded SYNC001/OBS002/HYG002 fixture shaped like a plan-cache /
scheduler helper — ``ci/lint.py`` must exit NONZERO.

The plan cache (cache/plan_cache.py) and admission scheduler
(service/scheduler.py) are pure host bookkeeping over certificates and
frozen baselines, so their lint scope bans exactly what this helper
does: a device pull while "validating" a cached plan, a
flight-recorder event that allocates per lookup, and a wall-clock read
where a monotonic planner-path timer is required.  Never imported by
the engine.
"""
import time

import jax
import numpy as np

from spark_rapids_tpu.obs import flight as _flight


def bad_lookup(dev, digest):
    probe = np.asarray(dev).sum()             # SYNC001: materialization
    sample = jax.device_get(dev)              # SYNC001: host pull
    _flight.record(_flight.EV_STATE, f"plan_cache:{digest}")  # OBS002
    t0 = time.time()                          # HYG002: wall clock
    return probe, sample, t0


def good_lookup(entry, baselines):
    # the cache's real shape: host dict reads over the certificate
    # already in hand, interned event names, counts as int kwargs
    _flight.record(_flight.EV_STATE, "plan_cache",
                   a=int(entry.get("hits", 0)))
    return baselines.get(entry.get("plan_fingerprint"))
