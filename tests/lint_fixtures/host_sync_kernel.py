"""Seeded SYNC001 fixture — ``ci/lint.py`` must exit NONZERO.

Every banned host-synchronization shape in one device-hot-path-shaped
buffer: an explicit barrier, a device pull, and a numpy materialization.
Never imported by the engine.
"""
import jax
import numpy as np


def bad_kernel(x):
    jax.block_until_ready(x)
    host = jax.device_get(x)
    arr = np.asarray(x)
    return host, arr
