"""Seeded RES001 fixture — ``ci/residency.py --fixture RES001`` must
exit NONZERO.

An undeclared device->host transfer on the execution spine: a value the
taint walk PROVES device-resident (produced by ``jnp.*``, carried
through a local helper) is materialized with ``np.asarray`` outside any
``residency.declared_transfer`` region.  Never imported by the engine.
"""
import jax.numpy as jnp
import numpy as np


def _device_counts(col):
    # helper return taint: DEVICE (interprocedural — the call graph
    # must carry it back to the caller's np.asarray argument)
    return jnp.cumsum(col.astype(jnp.int32))


def bad_finalize(col):
    counts = _device_counts(col)
    return np.asarray(counts)          # RES001: undeclared transfer
