"""Suppression fixture — ``ci/lint.py`` must exit ZERO here.

The same violation shapes as the seeded-bad fixtures, each carrying a
justified ``# lint: allow(<RULE>)`` suppression: a comment-only allow
(covers the next source line, justification may span comment lines) and
a trailing allow (covers its own line).
"""
import threading
import time

_lock = threading.Lock()


def heartbeat():
    with _lock:
        # lint: allow(LOCK001): fixture — demonstrates a justified
        # comment-only suppression spanning multiple justification
        # lines; the sleep under this uncontended lock is intentional
        time.sleep(0.01)


def swallow():
    try:
        return 1
    except:  # lint: allow(HYG001): fixture — trailing-allow form
        return None
