"""Seeded OBS002 fixture — ``ci/lint.py`` must exit NONZERO.

Flight-recorder ``record()`` call sites shaped like a device hot path
(``kernels/`` / ``exec/tpu_*``) but with per-call allocation: an
f-string name, a dict-literal payload, and eager ``str.format``.  The
recorder is always-on, so these allocate on every event even when
nobody ever reads the ring.  Never imported by the engine.
"""
from spark_rapids_tpu.obs import flight as _flight


def bad_kernel(table, rows):
    _flight.record(_flight.EV_KERNEL, f"gather:{rows}")
    _flight.record(_flight.EV_KERNEL, "gather", a={"rows": rows})
    _flight.record(_flight.EV_KERNEL, "gather:{}".format(rows))
    return table


def good_kernel(table, rows):
    # the allocation-free shape: interned constants + plain ints
    _flight.record(_flight.EV_KERNEL, "gather", a=rows)
    return table
