"""Seeded RES002 fixture — ``ci/residency.py --fixture RES002`` must
exit NONZERO.

A device->host sync while HOLDING the device semaphore: every
concurrent dispatcher queues behind a host round trip, the exact stall
the admission semaphore exists to prevent.  Never imported by the
engine.
"""
import threading

import jax.numpy as jnp

_DISPATCH_SEM = threading.Semaphore(4)


def bad_dispatch(col):
    dev = jnp.sum(col)
    with _DISPATCH_SEM:
        return float(dev)              # RES002: sync under the semaphore
