"""Seeded SYNC001/OBS002/HYG002 fixture shaped like an AOT warmup
sweep — ``ci/lint.py`` must exit NONZERO.

The AOT compile service (compile/aot.py) and its admission-aware
warmup daemon (service/warmup.py) run jitted programs from a
background thread and price compiles into the shared telemetry, so
their lint scope bans exactly what this helper does: a blocking
device sync after a warm call (jit compiles synchronously on first
invocation — waiting on the dummy result only stalls the sweep behind
real device work), a flight-recorder event that allocates per warm,
and a wall-clock read where the compile ledger requires a monotonic
one.  Never imported by the engine.
"""
import time

import jax
import numpy as np

from spark_rapids_tpu.obs import flight as _flight


def bad_warm_one(warm, bucket):
    out = warm(bucket)
    out.block_until_ready()                   # SYNC001: blocking sync
    rows = np.asarray(out).shape[0]           # SYNC001: materialization
    host = jax.device_get(out)                # SYNC001: host pull
    _flight.record(_flight.EV_STATE, f"warmed:{bucket}")  # OBS002
    stamp = time.time()                       # HYG002: wall clock
    return rows, host, stamp


def good_warm_one(warm, bucket):
    # the daemon's real shape: call the jitted program (first-call
    # compile is synchronous), drop the result, interned event name,
    # bucket rides the integer payload slot
    warm(bucket)
    _flight.record(_flight.EV_STATE, "warmed", a=int(bucket))
    return True
