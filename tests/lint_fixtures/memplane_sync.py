"""Seeded SYNC001/OBS002 fixture shaped like a memory-plane helper —
``ci/lint.py`` must exit NONZERO.

The memory observability plane (obs/memplane.py) prices spills from
catalog transitions the memory layer already makes, so its lint scope
bans exactly what this buffer does: a device pull while sizing a
victim, and a flight-recorder event that allocates per spill.  Never
imported by the engine.
"""
import jax
import numpy as np

from spark_rapids_tpu.obs import flight as _flight


def bad_note_spill(entry, dev):
    nbytes = np.asarray(dev).nbytes           # SYNC001: materialization
    host = jax.device_get(dev)                # SYNC001: host pull
    _flight.record(_flight.EV_MEM, f"spill:{nbytes}")   # OBS002: f-string
    return host


def good_note_spill(entry, nbytes, dur_ns):
    # the allocation-free shape: sizes from the catalog entry, interned
    # name constants, plain ints
    _flight.record(_flight.EV_MEM, "spill", a=nbytes, b=dur_ns)
    return nbytes
