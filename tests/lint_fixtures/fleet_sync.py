"""Seeded SYNC001/OBS002/HYG002 fixture shaped like a fleet-plane
helper — ``ci/lint.py`` must exit NONZERO.

The fleet plane (obs/fingerprint.py, obs/history.py, obs/anomaly.py,
obs/dashboard.py) folds rows the planes already collected into host
dicts, so its lint scope bans exactly what this helper does: a device
pull while "enriching" a history row, a flight-recorder event that
allocates per fold, and a wall-clock read where the row's own
timestamp is required.  Never imported by the engine.
"""
import time

import jax
import numpy as np

from spark_rapids_tpu.obs import flight as _flight


def bad_fold(dev, fingerprint):
    drift = np.asarray(dev).mean()            # SYNC001: materialization
    evidence = jax.device_get(dev)            # SYNC001: host pull
    _flight.record(_flight.EV_MEM, f"anomaly:{fingerprint}")  # OBS002
    stamp = time.time()                       # HYG002: wall clock
    return drift, evidence, stamp


def good_fold(row, state):
    # the sentinel's real shape: host arithmetic over the row already
    # in hand, interned name constants, the row's own timestamp
    _flight.record(_flight.EV_MEM, "anomaly", a=int(row.get("ts", 0)))
    return state.get(row.get("fingerprint"), 0.0)
