"""Seeded SYNC001/LOCK001 fixture shaped like a superstage compiler
helper — ``ci/lint.py`` must exit NONZERO.

The compile/ layer exists to eliminate host round trips, so its lint
scope bans exactly what this buffer does: a device pull inside the
carving path and a blocking sleep under the stage lock.  Never imported
by the engine.
"""
import threading
import time

import jax
import numpy as np

_STAGE_LOCK = threading.Lock()


def bad_carve(node, dev):
    rows = int(jax.device_get(dev))          # SYNC001: host pull
    buf = np.asarray(dev)                    # SYNC001: materialization
    with _STAGE_LOCK:
        time.sleep(0.01)                     # LOCK001: blocking hold
    return rows, buf
