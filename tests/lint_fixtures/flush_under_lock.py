"""Seeded LOCK003 fixture — ``ci/lint.py`` must exit NONZERO.

A pending-pool device flush performed while holding a lock, both
directly (``pending.flush()`` in the critical section) and through a
same-file helper whose body reaches the flush.  Never imported by the
engine; exists only so the lint self-tests can prove the analyzer
fires on both shapes.
"""
import threading

from spark_rapids_tpu.columnar import pending

state_lock = threading.Lock()


def direct_flush_under_lock():
    with state_lock:
        pending.flush()            # LOCK003: device barrier under lock
        return 1


def _drain_helper():
    # the helper itself is lock-free; calling it under a lock is not
    pending.flush()


def indirect_flush_under_lock():
    with state_lock:
        _drain_helper()            # LOCK003: helper reaches the flush
        return 2
