"""Seeded SYNC001/OBS002/HYG002 fixture shaped like a query-doctor
helper — ``ci/lint.py`` must exit NONZERO.

The cross-plane doctor (obs/doctor.py) and the regression sentinel
(analysis/regression.py) diagnose from summaries the planes already
collected, so their lint scope bans exactly what this helper does: a
device pull while "corroborating" a share, a flight-recorder event
that allocates per verdict, and a wall-clock read where a monotonic
one is required.  Never imported by the engine.
"""
import time

import jax
import numpy as np

from spark_rapids_tpu.obs import flight as _flight


def bad_corroborate(dev, cause):
    share = np.asarray(dev).mean()            # SYNC001: materialization
    evidence = jax.device_get(dev)            # SYNC001: host pull
    _flight.record(_flight.EV_MEM, f"verdict:{cause}")  # OBS002: f-string
    stamp = time.time()                       # HYG002: wall clock
    return share, evidence, stamp


def good_corroborate(summary, cause, share_pct):
    # the doctor's real shape: host arithmetic over dicts already in
    # hand, interned name constants, monotonic clocks only
    _flight.record(_flight.EV_MEM, "verdict", a=int(share_pct))
    return summary.get(cause, 0.0)
