"""Seeded RES003 fixture — ``ci/residency.py --fixture RES003`` must
exit NONZERO.

A device->host transfer INSIDE a pipeline drain loop: one sync per
batch serializes the whole pipeline per iteration instead of amortizing
a single pull at the stage barrier.  Never imported by the engine.
"""
import jax.numpy as jnp
import numpy as np


def bad_drain(batches):
    out = []
    for batch in batches:
        dev = jnp.nonzero(batch, size=16)[0]
        out.append(np.asarray(dev))    # RES003: transfer in drain loop
    return out
