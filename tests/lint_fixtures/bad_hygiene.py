"""Seeded HYG001/HYG002/HYG003 fixture — ``ci/lint.py`` must exit
NONZERO.

A bare except, a wall-clock timestamp where monotonic is required, and
an exec-node class that defines ``execute`` without an ``output_schema``
override.  Never imported by the engine (``TpuExec``/``risky`` are
deliberately unresolved — lint is AST-only).
"""
import time


class BadExec(TpuExec):  # noqa: F821
    def execute(self):
        return []


def swallow():
    try:
        risky()  # noqa: F821
    except:
        return None


def stamp():
    return time.time()
