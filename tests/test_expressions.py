"""Expression library tests vs Python/numpy oracles.

Pattern parity: reference CastOpSuite / arithmetic integration tests
(integration_tests/src/main/python/arithmetic_ops_test.py).
"""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import ColumnarBatch, dtypes as T
import spark_rapids_tpu.expr as E


def _batch():
    return ColumnarBatch.from_pydict({
        "i": [1, 2, None, -4, 5],
        "j": [10, 0, 3, None, 2],
        "f": [1.5, -2.0, float("nan"), None, 0.0],
        "s": ["foo", "Bar", None, "baz", "foobar"],
        "b": [True, False, None, True, False],
    }, schema=None)


def _eval(expr, batch=None):
    batch = batch or _batch()
    bound = expr.bind(batch.schema)
    col = E.eval_as_column(bound, batch)
    return col.to_pylist(batch.num_rows)


def col(name):
    return E.AttributeReference(name)


class TestArithmetic:
    def test_add(self):
        assert _eval(E.Add(col("i"), col("j"))) == [11, 2, None, None, 7]

    def test_mul_lit(self):
        assert _eval(E.Multiply(col("i"), E.lit(3))) == [3, 6, None, -12, 15]

    def test_divide_by_zero_is_null(self):
        got = _eval(E.Divide(col("i"), col("j")))
        assert got[1] is None  # 2/0 -> null
        assert got[0] == pytest.approx(0.1)

    def test_remainder_sign(self):
        b = ColumnarBatch.from_pydict({"x": [7, -7, 7, -7],
                                       "y": [3, 3, -3, -3]})
        got = _eval(E.Remainder(col("x"), col("y")), b)
        assert got == [1, -1, 1, -1]  # Java remainder semantics

    def test_abs_neg(self):
        assert _eval(E.Abs(col("i"))) == [1, 2, None, 4, 5]
        assert _eval(E.UnaryMinus(col("i"))) == [-1, -2, None, 4, -5]

    def test_sqrt(self):
        b = ColumnarBatch.from_pydict({"x": [4.0, 9.0, None]})
        assert _eval(E.Sqrt(col("x")), b) == [2.0, 3.0, None]

    def test_round(self):
        b = ColumnarBatch.from_pydict({"x": [2.5, -2.5, 1.44, None]})
        assert _eval(E.Round(col("x")), b) == [3.0, -3.0, 1.0, None]

    def test_shift(self):
        b = ColumnarBatch.from_pydict({"x": [1, 2, -8]})
        assert _eval(E.ShiftLeft(col("x"), E.lit(2)), b) == [4, 8, -32]


class TestPredicates:
    def test_comparisons(self):
        assert _eval(E.LessThan(col("i"), col("j"))) == [
            True, False, None, None, False]
        assert _eval(E.EqualTo(col("i"), E.lit(2))) == [
            False, True, None, False, False]

    def test_string_compare(self):
        assert _eval(E.GreaterThan(col("s"), E.lit("baz"))) == [
            True, False, None, False, True]

    def test_and_or_three_valued(self):
        t, f, n = E.lit(True), E.lit(False), E.Literal(None, T.BOOL)
        b = _batch()
        assert _eval(E.And(f, n), b) == [False] * 5
        assert _eval(E.And(t, n), b) == [None] * 5
        assert _eval(E.Or(t, n), b) == [True] * 5
        assert _eval(E.Or(f, n), b) == [None] * 5

    def test_is_null(self):
        assert _eval(E.IsNull(col("i"))) == [
            False, False, True, False, False]
        assert _eval(E.IsNotNull(col("i"))) == [
            True, True, False, True, True]

    def test_isnan(self):
        assert _eval(E.IsNaN(col("f"))) == [
            False, False, True, False, False]

    def test_equal_null_safe(self):
        got = _eval(E.EqualNullSafe(col("i"), E.Literal(None, T.INT64)))
        assert got == [False, False, True, False, False]

    def test_in(self):
        assert _eval(E.In(col("i"), [1, 5])) == [
            True, False, None, False, True]


class TestConditional:
    def test_if(self):
        got = _eval(E.If(E.GreaterThan(col("i"), E.lit(1)),
                         col("i"), col("j")))
        # null predicate falls through to the else branch (Spark CASE rules)
        assert got == [10, 2, 3, None, 5]

    def test_coalesce(self):
        assert _eval(E.Coalesce(col("i"), col("j"))) == [1, 2, 3, -4, 5]

    def test_case_when(self):
        e = E.CaseWhen(
            [(E.LessThan(col("i"), E.lit(0)), E.lit(-1)),
             (E.GreaterThan(col("i"), E.lit(2)), E.lit(1))],
            E.lit(0))
        assert _eval(e) == [0, 0, 0, -1, 1]

    def test_if_strings(self):
        got = _eval(E.If(E.GreaterThan(col("i"), E.lit(1)),
                         col("s"), E.lit("small")))
        # null predicate -> else branch
        assert got == ["small", "Bar", "small", "small", "foobar"]


class TestCast:
    def test_int_to_double(self):
        assert _eval(E.Cast(col("i"), T.FLOAT64)) == [
            1.0, 2.0, None, -4.0, 5.0]

    def test_double_to_int_truncates(self):
        b = ColumnarBatch.from_pydict({"x": [1.9, -1.9, float("nan")]})
        assert _eval(E.Cast(col("x"), T.INT32), b) == [1, -1, 0]

    def test_int_to_string(self):
        assert _eval(E.Cast(col("i"), T.STRING)) == [
            "1", "2", None, "-4", "5"]

    def test_double_to_string(self):
        b = ColumnarBatch.from_pydict({"x": [1.0, 2.5, None]})
        assert _eval(E.Cast(col("x"), T.STRING), b) == ["1.0", "2.5", None]

    def test_string_to_int(self):
        b = ColumnarBatch.from_pydict({"x": ["12", " 7 ", "bad", None]})
        assert _eval(E.Cast(col("x"), T.INT64), b) == [12, 7, None, None]

    def test_string_to_date(self):
        b = ColumnarBatch.from_pydict({"x": ["1970-01-02", "2020-02-29"]})
        got = _eval(E.Cast(col("x"), T.DATE), b)
        assert got == [1, 18321]

    def test_bool_to_string(self):
        assert _eval(E.Cast(col("b"), T.STRING)) == [
            "true", "false", None, "true", "false"]


class TestStrings:
    def test_upper_lower(self):
        assert _eval(E.Upper(col("s"))) == ["FOO", "BAR", None, "BAZ",
                                            "FOOBAR"]
        assert _eval(E.Lower(col("s"))) == ["foo", "bar", None, "baz",
                                            "foobar"]

    def test_length(self):
        assert _eval(E.Length(col("s"))) == [3, 3, None, 3, 6]

    def test_substring(self):
        got = _eval(E.Substring(col("s"), E.lit(2), E.lit(2)))
        assert got == ["oo", "ar", None, "az", "oo"]

    def test_concat(self):
        got = _eval(E.ConcatStrings(col("s"), E.lit("_x")))
        assert got == ["foo_x", "Bar_x", None, "baz_x", "foobar_x"]

    def test_like(self):
        assert _eval(E.Like(col("s"), E.lit("foo%"))) == [
            True, False, None, False, True]
        assert _eval(E.Like(col("s"), E.lit("%a%"))) == [
            False, True, None, True, True]
        assert _eval(E.Like(col("s"), E.lit("_az"))) == [
            False, False, None, True, False]

    def test_trim(self):
        b = ColumnarBatch.from_pydict({"x": ["  hi  ", "a", "   ", ""]})
        assert _eval(E.StringTrim(col("x")), b) == ["hi", "a", "", ""]
        assert _eval(E.StringTrimLeft(col("x")), b) == ["hi  ", "a", "", ""]
        assert _eval(E.StringTrimRight(col("x")), b) == ["  hi", "a", "", ""]

    def test_starts_ends_contains(self):
        assert _eval(E.StartsWith(col("s"), E.lit("fo"))) == [
            True, False, None, False, True]
        assert _eval(E.EndsWith(col("s"), E.lit("ar"))) == [
            False, True, None, False, True]
        assert _eval(E.Contains(col("s"), E.lit("oba"))) == [
            False, False, None, False, True]


class TestDatetime:
    def test_year_month_day(self):
        b = ColumnarBatch.from_pydict(
            {"d": [0, 59, 18321, -1]},
            schema=None)
        d = E.Cast(col("d"), T.DATE)
        assert _eval(E.Year(d), b) == [1970, 1970, 2020, 1969]
        assert _eval(E.Month(d), b) == [1, 3, 2, 12]
        assert _eval(E.DayOfMonth(d), b) == [1, 1, 29, 31]

    def test_day_of_week(self):
        b = ColumnarBatch.from_pydict({"d": [0, 3]})
        d = E.Cast(col("d"), T.DATE)
        # 1970-01-01 Thursday=5 in Spark dayofweek (Sun=1)
        assert _eval(E.DayOfWeek(d), b) == [5, 1]

    def test_date_add_diff(self):
        b = ColumnarBatch.from_pydict({"d": [10, 20]})
        d = E.Cast(col("d"), T.DATE)
        assert _eval(E.DateAdd(d, E.lit(5)), b) == [15, 25]
        assert _eval(E.DateDiff(d, E.Cast(E.lit(0), T.DATE)), b) == [10, 20]

    def test_timestamp_fields(self):
        us = 3 * 3_600_000_000 + 25 * 60_000_000 + 45_000_000
        b = ColumnarBatch.from_pydict({"t": [us, -1]})
        t = E.Cast(col("t"), T.TIMESTAMP)
        assert _eval(E.Hour(t), b) == [3, 23]
        assert _eval(E.Minute(t), b) == [25, 59]
        assert _eval(E.Second(t), b) == [45, 59]


class TestMisc:
    def test_hash_deterministic_not_null(self):
        got1 = _eval(E.Murmur3Hash(col("i"), col("s")))
        got2 = _eval(E.Murmur3Hash(col("i"), col("s")))
        assert got1 == got2
        assert all(v is not None for v in got1)

    def test_md5(self):
        b = ColumnarBatch.from_pydict({"x": ["abc", None]})
        got = _eval(E.Md5(col("x")), b)
        assert got == ["900150983cd24fb0d6963f7d28e17f72", None]


from spark_rapids_tpu.api import functions as F  # noqa: E402


class TestCentralMoments:
    """stddev/variance use Welford (count, mean, M2) buffers: the naive
    sumsq - sum^2/n recovery is catastrophically cancellative on
    large-mean data (reference merges M2 buffers for the same reason,
    AggregateFunctions.scala GpuStddevSamp family)."""

    def test_stddev_variance_match_oracle(self):
        import numpy as np
        from harness import assert_tpu_and_cpu_are_equal_collect

        def q(s):
            rng = np.random.default_rng(4)
            df = s.create_dataframe({
                "k": rng.integers(0, 10, 3000).astype(np.int64),
                "v": rng.standard_normal(3000)}, num_partitions=3)
            return df.group_by("k").agg(
                F.stddev("v").alias("sd"),
                F.stddev_pop("v").alias("sp"),
                F.variance("v").alias("vr"),
                F.var_pop("v").alias("vp"))
        rows = assert_tpu_and_cpu_are_equal_collect(q)
        assert len(rows) == 10

    def test_large_mean_no_cancellation(self):
        import numpy as np
        from harness import assert_tpu_and_cpu_are_equal_collect

        def q(s):
            rng = np.random.default_rng(4)
            # mean 1e8, sd ~1: the sumsq formula returns 0.0 here
            df = s.create_dataframe({
                "k": rng.integers(0, 10, 3000).astype(np.int64),
                "v": rng.standard_normal(3000) + 1e8})
            return df.group_by("k").agg(F.stddev("v").alias("sd"))
        rows = assert_tpu_and_cpu_are_equal_collect(q)
        assert all(r[1] > 0.5 for r in rows)

    def test_single_row_group_is_null_for_sample(self):
        import numpy as np
        from harness import assert_tpu_and_cpu_are_equal_collect

        def q(s):
            df = s.create_dataframe({
                "k": np.array([1, 2, 2], np.int64),
                "v": np.array([5.0, 1.0, 3.0])})
            return df.group_by("k").agg(F.stddev("v").alias("sd"),
                                        F.stddev_pop("v").alias("sp"))
        assert_tpu_and_cpu_are_equal_collect(q)
