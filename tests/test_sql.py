"""SQL front-end tests: parse+lower, then CPU-vs-TPU engine equality.

Reference pattern: the reference's qa_nightly_select_test.py runs a large
SQL sweep through Spark's parser and compares GPU vs CPU results; here
the framework owns the parser (api/sql.py) and the oracle is the CPU
engine (SURVEY.md §4).
"""
import datetime

import numpy as np
import pytest

from harness import assert_tpu_and_cpu_are_equal_collect, with_cpu_session

from spark_rapids_tpu.api.sql import parse_sql, SqlError


def _tables(s):
    rng = np.random.default_rng(42)
    n = 500
    t1 = s.create_dataframe({
        "k": rng.integers(0, 20, n).astype(np.int64),
        "v": rng.integers(-100, 100, n).astype(np.int64),
        "x": np.round(rng.random(n) * 100, 3),
        "name": np.array([f"item_{i % 37}" for i in range(n)]),
        "d": np.array([datetime.date(1995, 1, 1) +
                       datetime.timedelta(days=int(i)) for i in
                       rng.integers(0, 1500, n)]),
    }, num_partitions=3)
    t2 = s.create_dataframe({
        "k": np.arange(20, dtype=np.int64),
        "label": np.array([f"grp_{i}" for i in range(20)]),
        "w": rng.random(20),
    })
    t1.create_or_replace_temp_view("t1")
    t2.create_or_replace_temp_view("t2")
    return t1, t2


def _sql(query):
    def fn(s):
        _tables(s)
        return s.sql(query)
    return fn


# -- parser-level ----------------------------------------------------------

def test_parse_errors():
    with pytest.raises(SqlError):
        parse_sql("select from t")
    with pytest.raises(SqlError):
        parse_sql("select * t1")   # trailing junk
    with pytest.raises(SqlError):
        parse_sql("select a from t where")


def test_parse_shapes():
    ast = parse_sql("""
        with c as (select k from t1)
        select k, sum(v) as sv from c join t2 on c.k = t2.k
        where k > 2 group by k having sum(v) > 0
        order by sv desc limit 5""")
    assert ast.ctes[0][0] == "c"
    assert ast.limit == 5
    assert len(ast.group_by) == 1


# -- end-to-end equality ---------------------------------------------------

def test_select_where():
    assert_tpu_and_cpu_are_equal_collect(_sql(
        "SELECT k, v + 1 AS v1, x * 2 FROM t1 WHERE v > 0 AND x < 50"))


def test_select_star():
    assert_tpu_and_cpu_are_equal_collect(_sql(
        "SELECT * FROM t2 WHERE w > 0.5"))


def test_case_between_in_like():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k,
               CASE WHEN v < 0 THEN 'neg' WHEN v = 0 THEN 'zero'
                    ELSE 'pos' END AS sgn,
               v BETWEEN -10 AND 10 AS near,
               k IN (1, 3, 5, 7) AS odd_pick,
               name LIKE 'item_1%' AS starts1
        FROM t1"""))


def test_is_null_and_not():
    assert_tpu_and_cpu_are_equal_collect(_sql(
        "SELECT k, v IS NOT NULL, NOT (v > 0) FROM t1 WHERE x IS NOT NULL"))


def test_cast():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT CAST(x AS int) AS xi, CAST(k AS string) AS ks,
               CAST(v AS double) / 4 AS vq FROM t1"""))


def test_group_by_having():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k, sum(v) AS sv, count(*) AS n, avg(x) AS ax,
               min(v) AS mn, max(v) AS mx
        FROM t1 GROUP BY k HAVING count(*) > 5"""))


def test_group_by_expr_and_ordinal():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k % 3 AS kg, sum(x) AS sx FROM t1 GROUP BY 1"""))


def test_agg_arith_combo():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k, sum(v) * 1.0 / count(*) AS ratio,
               sum(x + 1) - max(v) AS combo
        FROM t1 GROUP BY k"""))


def test_global_agg():
    assert_tpu_and_cpu_are_equal_collect(_sql(
        "SELECT count(*) AS n, sum(v) AS sv, avg(x) AS ax FROM t1"))


def test_join_on():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT t1.k, t1.v, t2.label FROM t1
        JOIN t2 ON t1.k = t2.k WHERE t2.w > 0.3"""))


def test_join_comma_where():
    """Comma join + WHERE equality must become an equi join."""
    def fn(s):
        _tables(s)
        df = s.sql("""
            SELECT t1.k, t2.label, t1.v FROM t1, t2
            WHERE t1.k = t2.k AND t1.v > 0""")
        return df
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_join_left_right_full():
    for how in ("LEFT", "RIGHT", "FULL"):
        assert_tpu_and_cpu_are_equal_collect(_sql(f"""
            SELECT t1.k, t1.v, t2.label FROM t1
            {how} JOIN t2 ON t1.k = t2.k AND t2.w > 0.5"""))


def test_self_join_aliases():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT a.k, a.label, b.label AS label2
        FROM t2 a JOIN t2 b ON a.k = b.k"""))


def test_order_limit_offset():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k, v FROM t1 ORDER BY v DESC, k ASC LIMIT 17"""),
        ignore_order=False)


def test_order_by_alias_and_ordinal():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k, sum(v) AS sv FROM t1 GROUP BY k ORDER BY 2 DESC, k"""),
        ignore_order=False)


def test_order_by_hidden_column():
    # sort key not in the select list
    assert_tpu_and_cpu_are_equal_collect(_sql(
        "SELECT k FROM t1 ORDER BY v, k, x"), ignore_order=False)


def test_distinct():
    assert_tpu_and_cpu_are_equal_collect(_sql(
        "SELECT DISTINCT k FROM t1 ORDER BY k"), ignore_order=False)


def test_union_all_and_union():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k, v FROM t1 WHERE v > 50
        UNION ALL SELECT k, v FROM t1 WHERE v < -50"""))
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k FROM t1 WHERE v > 0 UNION SELECT k FROM t1 WHERE v < 0"""))


def test_intersect_except():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k FROM t1 WHERE v > 0 INTERSECT SELECT k FROM t1
        WHERE v < 0"""))
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k FROM t1 EXCEPT SELECT k FROM t1 WHERE v >= 0"""))


def test_cte():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        WITH big AS (SELECT k, v FROM t1 WHERE v > 20),
             agg AS (SELECT k, count(*) AS n FROM big GROUP BY k)
        SELECT agg.k, agg.n, t2.label FROM agg JOIN t2 ON agg.k = t2.k"""))


def test_from_subquery():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT sub.k, sub.sv * 2 AS sv2
        FROM (SELECT k, sum(v) AS sv FROM t1 GROUP BY k) AS sub
        WHERE sub.sv > 0"""))


def test_scalar_subquery():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k, x FROM t1 WHERE x > (SELECT avg(x) FROM t1)"""))


def test_in_subquery_semi_anti():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k, v FROM t1 WHERE k IN (SELECT k FROM t2 WHERE w > 0.5)"""))
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k, v FROM t1
        WHERE k NOT IN (SELECT k FROM t2 WHERE w > 0.5) AND v > 0"""))


def test_string_funcs():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT upper(name), substring(name, 1, 4), length(name),
               name || '_sfx' AS cc, replace(name, 'item', 'it') AS rep
        FROM t1 WHERE name LIKE '%3%'"""))


def test_date_funcs_and_literals():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT year(d) AS y, month(d) AS m, dayofmonth(d) AS dd,
               date_add(d, 10) AS d10
        FROM t1 WHERE d >= DATE '1996-06-01'
          AND d < DATE '1998-12-01' - INTERVAL '90' DAY"""))


def test_math_funcs():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT abs(v) AS av, round(x, 1) AS rx, sqrt(abs(v)) AS sv,
               floor(x) AS fx, ceil(x) AS cx, pmod(v, 7) AS pv,
               greatest(v, 0) AS gv, least(x, 50.0) AS lx
        FROM t1"""))


def test_window_over():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k, v,
               row_number() OVER (PARTITION BY k ORDER BY v, x) AS rn,
               rank() OVER (PARTITION BY k ORDER BY v, x) AS rk,
               sum(v) OVER (PARTITION BY k ORDER BY v, x
                            ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
                   AS running
        FROM t1"""))


def test_window_over_aggregate():
    # window over an aggregated relation (TPC-DS shape)
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k, sv, rank() OVER (ORDER BY sv DESC, k) AS rnk
        FROM (SELECT k, sum(v) AS sv FROM t1 GROUP BY k) s
        ORDER BY rnk"""), ignore_order=False)


def test_no_from():
    assert_tpu_and_cpu_are_equal_collect(_sql(
        "SELECT 1 + 2 AS three, 'x' AS s, CAST(2.5 AS int) AS i"))


def test_qa_style_sweep():
    """A miniature qa_nightly_select_test-style battery."""
    queries = [
        "SELECT k+v, k-v, k*2, v/3, v%5 FROM t1",
        "SELECT -v, +v, NOT (v>0) FROM t1",
        "SELECT k FROM t1 WHERE v > 10 OR (x < 20 AND k <> 3)",
        "SELECT coalesce(NULL, v, 0), nullif(k, 3), if(v>0, 'p', 'n') "
        "FROM t1",
        "SELECT count(v), first(k), last(k) FROM t1 GROUP BY k % 4",
        "SELECT t2.label, max(t1.x) FROM t1 JOIN t2 ON t1.k = t2.k "
        "GROUP BY t2.label",
    ]
    for q in queries:
        assert_tpu_and_cpu_are_equal_collect(_sql(q))


def test_sql_plan_uses_tpu():
    """The SQL path must hit TPU execs, not fall back wholesale."""
    def fn(s):
        _tables(s)
        df = s.sql("SELECT k, sum(v) AS sv FROM t1 WHERE x > 1 GROUP BY k")
        return df
    from harness import with_tpu_session

    def run(s):
        df = fn(s)
        df.collect()
        tree = df._last_physical_plan.tree_string()
        assert "TpuHashAggregate" in tree, tree
        # the filter either survives as its own exec, collapses into a
        # staged chain, or is absorbed into the aggregate's fused core
        # (marked "staged=N ops" in the node string)
        assert ("TpuFilter" in tree or "TpuStagedCompute" in tree or
                "staged=" in tree), tree
        return []
    with_tpu_session(run)


# -- review-fix regressions -------------------------------------------------

def test_non_equi_join_conditions():
    """Pure non-equi ON clauses: pair-level semantics on both engines."""
    def fn(how):
        def run(s):
            a = s.create_dataframe({"k": [1, 2, 3, 4],
                                    "v": [10, 20, 30, 40]})
            b = s.create_dataframe({"x": [2, 3], "w": [100, 200]})
            a.create_or_replace_temp_view("a")
            b.create_or_replace_temp_view("b")
            return s.sql(f"SELECT * FROM a {how} JOIN b ON a.k < b.x")
        return run
    for how in ("INNER", "LEFT", "RIGHT", "FULL"):
        assert_tpu_and_cpu_are_equal_collect(fn(how))


def test_union_trailing_order_limit():
    """ORDER BY/LIMIT after a set op applies to the whole union."""
    def fn(s):
        _tables(s)
        return s.sql("""
            SELECT k FROM t1 WHERE k <= 2
            UNION ALL SELECT k FROM t1 ORDER BY k DESC LIMIT 3""")
    rows = with_cpu_session(lambda s: fn(s).collect())
    assert len(rows) == 3
    assert_tpu_and_cpu_are_equal_collect(fn, ignore_order=False)


def test_not_in_subquery_with_nulls():
    """NOT IN with a NULL in the subquery returns nothing (3VL)."""
    def fn(s):
        t = s.create_dataframe({"k": [1, 2, 3]})
        u = s.create_dataframe({"x": [1, None]})
        t.create_or_replace_temp_view("t")
        u.create_or_replace_temp_view("u")
        return s.sql("SELECT k FROM t WHERE k NOT IN (SELECT x FROM u)")
    assert with_cpu_session(lambda s: fn(s).collect()) == []
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_not_exists():
    def fn(s):
        _tables(s)
        return s.sql("""
            SELECT count(*) FROM t1
            WHERE NOT EXISTS (SELECT k FROM t2 WHERE w > 99)""")
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_intersect_except_null_safe():
    """Set operations treat NULLs as equal (IS NOT DISTINCT FROM)."""
    def mk(s):
        a = s.create_dataframe({"x": [1, None, 5]})
        b = s.create_dataframe({"x": [1, None, 7]})
        a.create_or_replace_temp_view("a")
        b.create_or_replace_temp_view("b")

    def inter(s):
        mk(s)
        return s.sql("SELECT x FROM a INTERSECT SELECT x FROM b")

    def exc(s):
        mk(s)
        return s.sql("SELECT x FROM a EXCEPT SELECT x FROM b")
    got = sorted(with_cpu_session(lambda s: inter(s).collect()),
                 key=lambda r: (r[0] is None, r))
    assert got == [(1,), (None,)]
    assert with_cpu_session(lambda s: exc(s).collect()) == [(5,)]
    assert_tpu_and_cpu_are_equal_collect(inter)
    assert_tpu_and_cpu_are_equal_collect(exc)


def test_cte_visible_across_setop_branches():
    def fn(s):
        _tables(s)
        return s.sql("""
            WITH c AS (SELECT k FROM t1 WHERE v > 0)
            SELECT k FROM c UNION ALL SELECT k FROM c""")
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_setop_parenthesized_branch_keeps_its_clauses():
    """ORDER BY/LIMIT inside a parenthesized branch stays local."""
    def fn(s):
        t = s.create_dataframe({"k": [1, 2, 3]})
        t.create_or_replace_temp_view("t")
        return s.sql("""
            SELECT k FROM t UNION ALL
            (SELECT k FROM t ORDER BY k DESC LIMIT 1)""")
    rows = sorted(with_cpu_session(lambda s: fn(s).collect()))
    assert rows == [(1,), (2,), (3,), (3,)]
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_setop_trailing_offset():
    def fn(s):
        t = s.create_dataframe({"k": [1, 2, 3]})
        t.create_or_replace_temp_view("t")
        return s.sql("""
            SELECT k FROM t UNION ALL SELECT k FROM t
            ORDER BY k LIMIT 3 OFFSET 2""")
    rows = with_cpu_session(lambda s: fn(s).collect())
    assert rows == [(2,), (2,), (3,)]
    assert_tpu_and_cpu_are_equal_collect(fn, ignore_order=False)


def test_not_in_empty_subquery_keeps_nulls():
    """x NOT IN (empty set) is TRUE for every x, including NULL."""
    def fn(s):
        t = s.create_dataframe({"k": [1, None]})
        u = s.create_dataframe({"x": [5, None]})
        t.create_or_replace_temp_view("t")
        u.create_or_replace_temp_view("u")
        return s.sql(
            "SELECT k FROM t WHERE k NOT IN (SELECT x FROM u WHERE x > 100)")
    rows = with_cpu_session(lambda s: fn(s).collect())
    assert sorted(rows, key=lambda r: (r[0] is None, r)) == [(1,), (None,)]
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_staged_chain_single_node():
    """A 4-op filter/project chain collapses into ONE staged node."""
    from harness import with_tpu_session
    from spark_rapids_tpu.api import functions as F

    def run(s):
        df = s.create_dataframe({"a": list(range(100)),
                                 "b": [i * 0.5 for i in range(100)]})
        out = (df.filter(F.col("a") > 1)
                 .select((F.col("a") + 1).alias("a2"), "b")
                 .filter(F.col("a2") < 80)
                 .select((F.col("a2") * 2).alias("a4"), "b")
                 .select("a4"))
        rows = out.collect()
        assert len(rows) == 77
        tree = out._last_physical_plan.tree_string()
        assert tree.count("TpuStagedCompute") == 1, tree
        assert "TpuFilter" not in tree and "TpuProject" not in tree, tree
        return []
    with_tpu_session(run)


# -- distinct aggregates / grouping sets / correlated exists ----------------

def test_count_distinct():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k, count(DISTINCT v) AS dv, count(*) AS n,
               sum(v) AS sv, max(v) AS mx
        FROM t1 GROUP BY k"""))


def test_count_distinct_global_and_avg():
    assert_tpu_and_cpu_are_equal_collect(_sql(
        "SELECT count(DISTINCT k) FROM t1"))
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k % 3 AS g, count(DISTINCT k) AS dk, avg(x) AS ax
        FROM t1 GROUP BY k % 3"""))


def test_sum_distinct_dataframe():
    from spark_rapids_tpu.api import functions as F

    def fn(s):
        df = s.create_dataframe({"g": [1, 1, 1, 2, 2],
                                 "v": [5, 5, 7, 3, 3]})
        return df.group_by("g").agg(
            F.count_distinct("v").alias("dv"),
            F.sum_distinct("v").alias("sv"))
    rows = sorted(with_cpu_session(lambda s: fn(s).collect()))
    assert rows == [(1, 2, 12), (2, 1, 3)]
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_rollup_sql():
    def fn(s):
        _tables(s)
        return s.sql("""
            SELECT k % 2 AS a, k % 3 AS b, sum(v) AS sv, count(*) AS n
            FROM t1 GROUP BY ROLLUP(k % 2, k % 3)""")
    rows = with_cpu_session(lambda s: fn(s).collect())
    # rollup produces (a,b), (a,), and grand-total rows
    assert any(r[0] is None and r[1] is None for r in rows)
    assert any(r[0] is not None and r[1] is None for r in rows)
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_cube_and_grouping_sets_sql():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k % 2 AS a, k % 3 AS b, sum(v) AS sv
        FROM t1 GROUP BY CUBE(k % 2, k % 3)"""))
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k % 2 AS a, k % 3 AS b, count(*) AS n
        FROM t1 GROUP BY GROUPING SETS ((k % 2, k % 3), (k % 2), ())"""))


def test_rollup_dataframe():
    from spark_rapids_tpu.api import functions as F

    def fn(s):
        df = s.create_dataframe({"a": [1, 1, 2], "b": [1, 2, 1],
                                 "v": [10, 20, 30]})
        return df.rollup("a", "b").agg(F.sum("v").alias("sv"))
    rows = sorted(with_cpu_session(lambda s: fn(s).collect()),
                  key=lambda r: (r[0] is None, r[0] or 0,
                                 r[1] is None, r[1] or 0))
    assert (1, None, 30) in rows and (None, None, 60) in rows
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_correlated_exists():
    def fn(negated):
        def run(s):
            _tables(s)
            op = "NOT EXISTS" if negated else "EXISTS"
            return s.sql(f"""
                SELECT k, v FROM t1
                WHERE {op} (SELECT 1 FROM t2
                            WHERE t2.k = t1.k AND t2.w > 0.5)""")
        return run
    assert_tpu_and_cpu_are_equal_collect(fn(False))
    assert_tpu_and_cpu_are_equal_collect(fn(True))


def test_rollup_aggregate_over_key_column():
    """Aggregate inputs must not read the null-filled key copies."""
    def fn(s):
        t = s.create_dataframe({"k": [1, 1, 2]})
        t.create_or_replace_temp_view("t")
        return s.sql(
            "SELECT k, count(k) AS c FROM t GROUP BY ROLLUP(k)")
    rows = with_cpu_session(lambda s: fn(s).collect())
    assert (None, 3) in rows, rows
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_rollup_alias_and_bare_grouping_set_member():
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k % 2 AS a, sum(v) AS sv FROM t1 GROUP BY ROLLUP(a)"""))
    assert_tpu_and_cpu_are_equal_collect(_sql("""
        SELECT k % 2 AS a, count(*) AS n
        FROM t1 GROUP BY GROUPING SETS (a, ())"""))


def test_rollup_expression_keys_dataframe():
    from spark_rapids_tpu.api import functions as F

    def fn(s):
        df = s.create_dataframe({"a": [1, 2, 3, 4], "v": [10, 20, 30, 40]})
        return df.rollup((F.col("a") % 2).alias("x")).agg(
            F.sum("v").alias("sv"))
    rows = sorted(with_cpu_session(lambda s: fn(s).collect()),
                  key=lambda r: (r[0] is None, r[0] or 0))
    assert rows == [(0, 60), (1, 40), (None, 100)]
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_count_distinct_in_rollup():
    from spark_rapids_tpu.api import functions as F

    def fn(s):
        df = s.create_dataframe({"g": [1, 1, 2], "v": [5, 5, 7]})
        return df.rollup("g").agg(dv=F.count_distinct("v"))
    rows = sorted(with_cpu_session(lambda s: fn(s).collect()),
                  key=lambda r: (r[0] is None, r[0] or 0))
    assert rows == [(1, 1), (2, 1), (None, 2)]
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_grouping_indicator_function():
    """grouping(col): 1 on subtotal rows where col is rolled up."""
    def fn(s):
        t = s.create_dataframe({"a": [1, 1, 2], "b": [1, 2, 1],
                                "v": [10, 20, 30]})
        t.create_or_replace_temp_view("t")
        return s.sql("""
            SELECT a, b, grouping(a) AS ga, grouping(b) AS gb,
                   sum(v) AS sv
            FROM t GROUP BY ROLLUP(a, b)
            ORDER BY ga, gb, a, b""")
    rows = with_cpu_session(lambda s: fn(s).collect())
    assert (None, None, 1, 1, 60) in rows
    assert all(r[2] == 0 for r in rows if r[0] is not None)
    assert_tpu_and_cpu_are_equal_collect(fn, ignore_order=False)
