"""Mortgage ETL pipeline parity test (tiny scale).

Pattern parity: reference mortgage_test.py (integration_tests) over the
MortgageSpark.scala ETL — here the whole pipeline must agree with the
CPU oracle, covering multi-key joins, conditional aggregation, the
explode(array) expansion, and floor/pmod arithmetic in one plan.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from benchmarks.mortgage import generate, etl  # noqa: E402
from harness import assert_tpu_and_cpu_are_equal_collect  # noqa: E402


def test_mortgage_etl_parity(tmp_path):
    d = str(tmp_path)
    generate(d, scale=0.0004, seed=7)

    def fn(s):
        return etl(s, d)
    rows = assert_tpu_and_cpu_are_equal_collect(
        fn, conf={"spark.rapids.tpu.sql.shuffle.partitions": "2"})
    assert len(rows) > 0


def test_mortgage_counts(tmp_path):
    from harness import with_tpu_session, with_cpu_session
    d = str(tmp_path)
    generate(d, scale=0.0004, seed=11)
    n_tpu = with_tpu_session(
        lambda s: etl(s, d).count(),
        conf={"spark.rapids.tpu.sql.shuffle.partitions": "2"})
    n_cpu = with_cpu_session(lambda s: etl(s, d).count())
    assert n_tpu == n_cpu > 0
