"""HBM memory observability plane tests (obs/memplane.py): allocation
provenance (owner decomposition exact to device_bytes, peak
attribution), the priced spill ledger (totals equal the catalog's spill
counters), trigger-reason threading, the pinned-skip signal, leak
detection at query terminal states, headroom, the
Prometheus/stats/report/event-log surfaces, and the zero-extra-flush +
parallelism-stability acceptance contracts."""
import json
import time

import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.memory.spillable import SpillableBatch
from spark_rapids_tpu.obs import flight, memplane
from spark_rapids_tpu.obs.prom import render_text
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.service.cancellation import CancelToken, query_context

MS = 1_000_000          # ns per ms


@pytest.fixture(autouse=True)
def _memplane_reset():
    """Isolate the process-wide plane AND the catalog singleton the
    tests shrink (restore default budgets afterwards; catalog reset
    also resets the plane's decomposition epoch)."""
    BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
    yield
    memplane.configure(TpuConf({}))
    BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")


def _batch(rows=256):
    return ColumnarBatch.from_pydict(
        {"a": list(range(rows)), "b": [float(i) for i in range(rows)]})


# ---------------------------------------------------------------------------
# allocation provenance
# ---------------------------------------------------------------------------

class TestProvenance:
    def test_owner_decomposition_sums_exactly_to_device_bytes(self):
        cat = BufferCatalog.get()
        with query_context(CancelToken("q-own-1", None)):
            a = SpillableBatch(_batch(), op="TpuSortExec", site="operator")
        with query_context(CancelToken("q-own-2", None)):
            b = SpillableBatch(_batch(128), op="TpuShuffleExchange",
                               site="exchange")
        view = memplane.owners()
        # the acceptance contract: EXACT equality, not approximate —
        # both sides mutate under the same catalog lock
        assert view["device_bytes"] == cat.device_bytes > 0
        assert sum(r["bytes"] for r in view["owners"]) == cat.device_bytes
        by_q = {r["query_id"]: r for r in view["owners"]}
        assert by_q["q-own-1"]["site"] == "operator"
        assert by_q["q-own-1"]["op"] == "TpuSortExec"
        assert by_q["q-own-2"]["site"] == "exchange"
        # the incremental per-site counters agree with the exact scan
        assert memplane.live_site_bytes("operator") == \
            by_q["q-own-1"]["bytes"]
        assert memplane.live_site_bytes("exchange") == \
            by_q["q-own-2"]["bytes"]
        a.close()
        view = memplane.owners()
        assert view["device_bytes"] == cat.device_bytes
        assert sum(r["bytes"] for r in view["owners"]) == cat.device_bytes
        assert memplane.live_site_bytes("operator") == 0
        b.close()
        assert memplane.owners()["device_bytes"] == 0

    def test_registration_tag_names_the_calling_code(self):
        cat = BufferCatalog.get()
        sb = SpillableBatch(_batch(), op="TagOp")
        e = cat._entries[sb.buffer_id]
        # the tag walks past memory/ and obs/ frames to the real caller
        assert e.owner_tag.startswith("test_memplane.py:")
        sb.close()

    def test_peak_attribution_snapshots_owner_set_at_peak(self):
        marker = memplane.begin_query()
        big = SpillableBatch(_batch(512), op="BigOp", site="operator")
        small = SpillableBatch(_batch(32), op="SmallOp", site="other")
        peak_expected = BufferCatalog.get().device_bytes
        small.close()          # live bytes drop below the peak
        s = memplane.query_summary(marker)
        assert s["peak_advanced"]
        assert s["peak_device_bytes"] == peak_expected
        assert sum(s["peak_by_site"].values()) == s["peak_device_bytes"]
        assert {"operator", "other"} <= set(s["peak_by_site"])
        ops = {r["op"] for r in s["peak_owners"]}
        assert {"BigOp", "SmallOp"} <= ops
        big.close()

    def test_query_marker_isolates_window(self):
        keep = SpillableBatch(_batch(64), op="Before")
        marker = memplane.begin_query()
        mine = SpillableBatch(_batch(64), op="Mine", site="operator")
        s = memplane.query_summary(marker)
        assert s["registered"]["count"] == 1
        assert [r["op"] for r in s["registered"]["by_site"]] == ["Mine"]
        keep.close()
        mine.close()


# ---------------------------------------------------------------------------
# spill ledger
# ---------------------------------------------------------------------------

def _tiny_catalog(device_limit=16 * 1024, host_limit=8 << 30):
    return BufferCatalog.reset(spill_dir="/tmp/srt_test_spill",
                               device_limit=device_limit,
                               host_limit=host_limit)


class TestSpillLedger:
    def test_ledger_totals_equal_catalog_spill_counters(self):
        cat = _tiny_catalog(host_limit=16 * 1024)
        handles = [SpillableBatch(_batch(), op="TpuSortExec",
                                  site="operator") for _ in range(4)]
        cat.spill_device_to_fit(cat.device_limit, reason="budget")
        rows = memplane.ledger()
        d2h = [r for r in rows if r["direction"] == "device_to_host"]
        h2d = [r for r in rows if r["direction"] == "host_to_disk"]
        assert d2h, "forced budget produced no device spills"
        # the acceptance contract: ledger byte totals equal the
        # catalog's own spill counters
        assert sum(r["nbytes"] for r in d2h) == cat.spilled_device_to_host
        assert sum(r["nbytes"] for r in h2d) == cat.spilled_host_to_disk
        assert all(r["reason"] == "budget" for r in d2h)
        assert [r["rank"] for r in d2h] == list(range(len(d2h)))
        assert all(r["ms"] >= 0.0 for r in rows)
        assert all(r["site"] == "operator" and r["op"] == "TpuSortExec"
                   for r in rows)
        # unspill prices the whole read-back (disk hop included) as ONE
        # ledger record per materialize
        n0 = len(memplane.ledger())
        handles[0].materialize()
        rows = memplane.ledger()
        back = [r for r in rows[n0:] if r["direction"] == "unspill"]
        assert len(back) == 1
        assert back[0]["nbytes"] == handles[0].nbytes
        for h in handles:
            h.close()

    def test_reason_threads_from_arena_and_pressure_paths(self):
        from spark_rapids_tpu.memory.arena import DeviceManager
        dm = DeviceManager.get()   # may itself reset the catalog: first
        cat = _tiny_catalog()
        saved = dm.catalog
        dm.catalog = cat           # point admission at the tiny budget
        try:
            a = SpillableBatch(_batch(), op="A")
            dm.reserve(cat.device_limit)                  # budget path
            b = SpillableBatch(_batch(), op="B")
            from spark_rapids_tpu.memory.pressure import oom_retry
            calls = [0]

            def flaky():
                calls[0] += 1
                if calls[0] == 1:
                    raise RuntimeError(
                        "RESOURCE_EXHAUSTED: out of memory")
                return 1

            assert oom_retry(flaky) == 1              # pressure path
            reasons = [r["reason"] for r in memplane.ledger()]
            assert "budget" in reasons and "pressure" in reasons
            a.close()
            b.close()
        finally:
            dm.catalog = saved

    def test_pinned_working_set_signals_skip_not_silence(self):
        cat = _tiny_catalog()
        pinned = SpillableBatch(_batch(), op="PinnedOp", site="operator")
        cat._entries[pinned.buffer_id].refcount = 1       # in active use
        skipped0 = memplane.stats_section()["spill_skipped"]
        spilled = cat.spill_device_to_fit(cat.device_limit)
        assert spilled == 0                    # nothing evictable
        sec = memplane.stats_section()
        assert sec["spill_skipped"] == skipped0 + 1
        evs = [e for e in flight.snapshot()
               if e["kind"] == flight.EV_MEM and e["name"] == "pinned"]
        assert evs and evs[-1]["a"] == pinned.nbytes
        assert evs[-1]["b"] == 1               # pinned entry count
        cat._entries[pinned.buffer_id].refcount = 0
        pinned.close()

    def test_ledger_bound_drops_and_counts(self):
        memplane.configure(TpuConf({
            "spark.rapids.tpu.obs.mem.maxLedger": 2}))
        cat = _tiny_catalog()
        handles = [SpillableBatch(_batch(64), op="X") for _ in range(6)]
        cat.spill_device_to_fit(cat.device_limit)
        assert len(memplane.ledger()) <= 2
        assert memplane.ledger_dropped() > 0
        assert memplane.stats_section()["ledger_dropped"] > 0
        for h in handles:
            h.close()

    def test_disabled_plane_records_nothing(self):
        memplane.configure(TpuConf({
            "spark.rapids.tpu.obs.mem.enabled": False}))
        assert not memplane.is_enabled()
        cat = _tiny_catalog()
        # note: catalog reset re-reads nothing; the off switch persists
        memplane.configure(TpuConf({
            "spark.rapids.tpu.obs.mem.enabled": False}))
        handles = [SpillableBatch(_batch(), op="X") for _ in range(3)]
        cat.spill_device_to_fit(cat.device_limit)
        assert memplane.ledger() == []
        assert memplane.owners()["owners"] == []
        s = memplane.query_summary(None)
        assert s["spill_ms"] == 0.0 and s["registered"]["count"] == 0
        for h in handles:
            h.close()

    def test_active_windows_blame_mem_spill_timeline_gap(self):
        # a 20ms idle window where the only evidence is ledger spill
        # work -> the timeline classifies it mem_spill
        from spark_rapids_tpu.obs import timeline
        timeline.reset()
        try:
            memplane.note_spill(
                memplane.DIR_DEVICE_TO_HOST, "b0", "q", "operator",
                "Op", 1024, "budget", 0, 15 * MS, 0)
            now = time.perf_counter_ns()
            t0 = now - 20 * MS
            s = timeline._summarize(0, t0, now, is_query=True)
            assert s["gaps"]["mem_spill"] == pytest.approx(75.0, abs=5.0)
            assert sum(s["gaps"].values()) + s["util_pct"] == \
                pytest.approx(100.0, abs=0.5)
            assert memplane.active_segments(t0, now)
        finally:
            timeline.reset()


# ---------------------------------------------------------------------------
# leak detection + headroom
# ---------------------------------------------------------------------------

class TestLeakAndHeadroom:
    def test_leak_check_flags_unreleased_non_survivors(self):
        with query_context(CancelToken("q-leak", None)):
            leaked = SpillableBatch(_batch(), op="LeakyOp",
                                    site="operator")
            kept = SpillableBatch(_batch(64), op="ShuffleOut",
                                  site="exchange")
        leaks = memplane.leak_check("q-leak",
                                    survivors=(kept.buffer_id,))
        assert [lk["buffer_id"] for lk in leaks] == [leaked.buffer_id]
        lk = leaks[0]
        assert lk["op"] == "LeakyOp" and lk["site"] == "operator"
        assert lk["tag"].startswith("test_memplane.py:")
        assert lk["nbytes"] == leaked.nbytes
        evs = [e for e in flight.snapshot()
               if e["kind"] == flight.EV_MEM and e["name"] == "leak"]
        assert evs and evs[-1]["b"] == 1
        leaked.close()
        kept.close()
        assert memplane.leak_check("q-leak") == []

    def test_headroom_decomposes_limit(self):
        cat = _tiny_catalog(device_limit=1 << 20)
        free_h = SpillableBatch(_batch(), op="Spillable")
        pin = SpillableBatch(_batch(64), op="Pinned")
        cat._entries[pin.buffer_id].refcount = 2
        h = memplane.headroom()
        assert h["device_limit"] == 1 << 20
        assert h["device_bytes"] == cat.device_bytes
        assert h["pinned_bytes"] == pin.nbytes
        assert h["spillable_bytes"] == free_h.nbytes
        assert h["free_bytes"] == h["device_limit"] - h["device_bytes"]
        # what an admission could count on: free + evictable
        assert h["headroom_bytes"] == \
            h["free_bytes"] + h["spillable_bytes"]
        cat._entries[pin.buffer_id].refcount = 0
        free_h.close()
        pin.close()


# ---------------------------------------------------------------------------
# end-to-end: session roll-up, event log, zero extra flushes, stability
# ---------------------------------------------------------------------------

def _shuffle_df(s):
    return (s.create_dataframe(
                {"k": [i % 7 for i in range(2000)],
                 "v": [float(i) for i in range(2000)]}, num_partitions=2)
            .group_by("k").agg(F.sum("v").alias("sv")))


class TestEndToEnd:
    def test_session_rollup_and_zero_extra_flushes(self):
        from spark_rapids_tpu.columnar import pending
        s = TpuSession(TpuConf({}))
        df = _shuffle_df(s)
        df.to_arrow()          # first run is the one that sets the peak
        mem_first = s.last_query_memplane
        assert mem_first["peak_advanced"]
        assert mem_first["peak_device_bytes"] > 0
        assert sum(mem_first["peak_by_site"].values()) == \
            mem_first["peak_device_bytes"]
        df.to_arrow()                                  # warm
        mem_on = s.last_query_memplane
        assert mem_on["registered"]["count"] > 0
        assert mem_on["leaked_entries"] == 0           # no false leaks
        by_site = {r["site"] for r in mem_on["registered"]["by_site"]}
        assert "exchange" in by_site
        flushes_on = s.last_query_flushes
        f0 = pending.FLUSH_COUNT
        df.to_arrow()
        assert pending.FLUSH_COUNT - f0 == flushes_on
        # the acceptance contract: disabling the plane changes NOTHING
        # about device flushes — an exact FLUSH_COUNT delta
        memplane.configure(TpuConf({
            "spark.rapids.tpu.obs.mem.enabled": False}))
        df.to_arrow()
        assert s.last_query_flushes == flushes_on
        assert s.last_query_memplane["registered"]["count"] == 0

    def test_event_log_record_carries_memplane(self, tmp_path):
        from spark_rapids_tpu.tools.events import read_event_log
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({"spark.rapids.tpu.eventLog.path": log}))
        _shuffle_df(s).to_arrow()
        rec = list(read_event_log(log))[-1]
        assert rec["peak_device_bytes"] > 0
        assert rec["spill_ms"] == rec["memplane"]["spill_ms"]
        assert rec["unspill_count"] == rec["memplane"]["unspill_count"]
        assert rec["leaked_entries"] == 0
        assert rec["memplane"]["registered"]["count"] > 0

    def test_seeded_leak_lands_in_event_log_and_bundle(self, tmp_path):
        from spark_rapids_tpu.obs import diagnostics
        from spark_rapids_tpu.tools.events import read_event_log
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({"spark.rapids.tpu.eventLog.path": log}))
        with query_context(CancelToken("q-leak-e2e", None)):
            leaked = SpillableBatch(_batch(), op="LeakyOp",
                                    site="operator")
            _shuffle_df(s).to_arrow()
        rec = list(read_event_log(log))[-1]
        assert rec["leaked_entries"] >= 1
        tags = [lk["tag"] for lk in rec["memplane"]["leaks"]]
        assert any(t.startswith("test_memplane.py:") for t in tags)
        bundle = diagnostics.collect_bundle("test")
        assert bundle["memory"]["leaked_total"] >= 1
        assert "ledger_tail" in bundle["memory"]
        mine = [e for e in bundle["arena"]["entries"]
                if e["buffer_id"] == leaked.buffer_id]
        assert mine and mine[0]["op"] == "LeakyOp"
        assert mine[0]["owner_query"] == "q-leak-e2e"
        assert mine[0]["tag"].startswith("test_memplane.py:")
        leaked.close()

    def test_registration_digest_stable_across_parallelism(self):
        # the provenance surface must not depend on pipeline
        # interleaving: the same batches register whatever the worker
        # count (spill totals are timing-dependent, so the digest runs
        # spill-free and covers registered.by_site)
        digests = []
        for par in (1, 4):
            BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
            s = TpuSession(TpuConf({
                "spark.rapids.tpu.exec.pipelineParallelism": par}))
            df = _shuffle_df(s)
            df.to_arrow()                              # warm
            df.to_arrow()
            mem = s.last_query_memplane
            digests.append(json.dumps(mem["registered"]["by_site"],
                                      sort_keys=True))
        assert digests[0] == digests[1]


# ---------------------------------------------------------------------------
# surfaces: Prometheus, stats section, tools/report.py
# ---------------------------------------------------------------------------

class TestSurfaces:
    def test_prometheus_exposition_covers_mem_families(self):
        cat = _tiny_catalog()
        handles = [SpillableBatch(_batch(), op="X", site="operator")
                   for _ in range(3)]
        cat.spill_device_to_fit(cat.device_limit, reason="pressure")
        text = render_text(get_registry())
        for series in (
                'tpu_mem_live_bytes{site="operator"}',
                'tpu_mem_live_bytes{site="exchange"}',
                'tpu_mem_spill_seconds_bucket',
                "tpu_mem_headroom_bytes",
                "tpu_mem_pinned_bytes",
                "tpu_mem_spillable_bytes",
                "tpu_mem_leaked_entries_total",
                "tpu_mem_ledger_dropped_total"):
            assert series in text, series
        for h in handles:
            h.close()

    def test_stats_section_shape(self):
        sb = SpillableBatch(_batch(), op="StatOp", site="operator")
        sec = memplane.stats_section()
        assert sec["enabled"]
        assert sec["live_by_site"].get("operator") == sb.nbytes
        assert sec["device_bytes"] == sb.nbytes
        assert set(sec["spill"]) == set(memplane.DIRECTIONS)
        assert sec["headroom"]["device_bytes"] == sb.nbytes
        assert sec["owners"][0]["op"] == "StatOp"
        sb.close()

    def test_service_stats_carries_memory_section(self):
        from spark_rapids_tpu.service import QueryService
        s = TpuSession(TpuConf({}))
        svc = QueryService(session=s, num_workers=1)
        try:
            snap = svc.stats().snapshot()
            assert "memory" in snap
            assert set(snap["memory"]["spill"]) == \
                set(memplane.DIRECTIONS)
        finally:
            svc.shutdown(wait=True, timeout=10.0)

    def test_report_renders_memory_section(self):
        from spark_rapids_tpu.tools.report import memory_lines
        rec = {"memplane": {
            "peak_device_bytes": 4096, "spill_ms": 2.5,
            "unspill_ms": 1.0, "unspill_count": 1, "spill_skipped": 0,
            "leaked_entries": 1,
            "peak_by_site": {"operator": 3072, "exchange": 1024},
            "peak_owners": [{"query_id": "q1", "site": "operator",
                             "op": "TpuSortExec", "bytes": 3072}],
            "spill": {"device_to_host": {"count": 2, "bytes": 2048,
                                         "ms": 2.5},
                      "host_to_disk": {"count": 0, "bytes": 0,
                                       "ms": 0.0},
                      "unspill": {"count": 1, "bytes": 1024, "ms": 1.0}},
            "ledger": [{"direction": "device_to_host", "site": "operator",
                        "op": "TpuSortExec", "nbytes": 1024,
                        "reason": "budget", "rank": 0, "ms": 1.2}],
            "ledger_records": 3,
            "leaks": [{"buffer_id": "b1", "tier": 0, "nbytes": 512,
                       "site": "operator", "op": "LeakyOp",
                       "tag": "exec.py:42", "refcount": 0}]}}
        text = "\n".join(memory_lines(rec))
        assert "peak_device_bytes=4096" in text
        assert "operator" in text and "75.0%" in text   # 3072 of 4096
        assert "device_to_host" in text and "budget" in text
        assert "leaked registrations" in text
        assert "registered_at=exec.py:42" in text

    def test_report_tolerates_pre_memplane_records(self):
        from spark_rapids_tpu.tools.report import memory_lines
        (line,) = memory_lines({"query_id": "old"})
        assert "no memplane recorded" in line
