"""Chunked join gather (JoinGatherer.scala role): a skewed key whose
expansion exceeds the chunk budget must emit multiple bounded batches
with exactly the oracle's rows."""
import numpy as np

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.config import TpuConf


def _sessions(chunk_rows):
    mk = lambda on: TpuSession(TpuConf({
        "spark.rapids.tpu.sql.enabled": on,
        "spark.rapids.tpu.sql.join.gather.chunkRows": chunk_rows,
    }))
    return mk(True), mk(False)


def _dup_key_data():
    rng = np.random.default_rng(21)
    # left: one hot key (explodes), plus normal keys
    lk = np.concatenate([np.full(50, 7), rng.integers(0, 20, 200)])
    rk = np.concatenate([np.full(40, 7), rng.integers(0, 20, 100)])
    return ({"k": lk.astype(np.int64),
             "a": np.arange(len(lk), dtype=np.int64)},
            {"k2": rk.astype(np.int64),
             "b": np.arange(len(rk), dtype=np.int64)})


def _run(s, ldata, rdata, how):
    lf = s.create_dataframe(ldata, num_partitions=1)
    rf = s.create_dataframe(rdata, num_partitions=1)
    out = lf.join(rf, on=F.col("k") == F.col("k2"), how=how).to_arrow()
    rows = sorted(map(tuple, zip(*[out.column(c).to_pylist()
                                   for c in out.column_names])))
    return rows


def test_chunked_inner_join_matches_unchunked():
    ldata, rdata = _dup_key_data()
    # hot key 7 alone produces 50*40 = 2000 matches >> 256-row chunks
    tpu, cpu = _sessions(chunk_rows=256)
    got = _run(tpu, ldata, rdata, "inner")
    exp = _run(cpu, ldata, rdata, "inner")
    assert got == exp
    assert len(got) >= 2000


def test_chunked_left_outer_matches_unchunked():
    ldata, rdata = _dup_key_data()
    tpu, cpu = _sessions(chunk_rows=256)
    got = _run(tpu, ldata, rdata, "left")
    exp = _run(cpu, ldata, rdata, "left")
    assert got == exp
