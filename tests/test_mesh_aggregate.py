"""Mesh-mode distributed aggregation: the whole group-by as one SPMD
program over the virtual 8-device CPU mesh.

Reference: BASELINE.json config 4 (RapidsShuffleManager over multi-host
ICI) — here the partial-agg -> shuffle -> final-agg pipeline is a single
shard_map program with lax.all_to_all (exec/tpu_mesh_aggregate.py).
"""
import numpy as np
import pytest

from harness import with_cpu_session, with_tpu_session

MESH_CONF = {"spark.rapids.tpu.shuffle.mode": "mesh"}


def _df(s, n=4000):
    rng = np.random.default_rng(12)
    return s.create_dataframe({
        "k": rng.integers(0, 50, n).astype(np.int64),
        "v": rng.integers(-100, 100, n).astype(np.int64),
        "x": rng.random(n),
    }, num_partitions=4)


def _agg(s):
    from spark_rapids_tpu.api import functions as F
    return _df(s).group_by("k").agg(
        F.sum("v").alias("sv"), F.count().alias("n"),
        F.min("v").alias("mn"), F.max("x").alias("mx"),
        F.avg("x").alias("ax"))


def test_mesh_aggregate_matches_cpu():
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")
    cpu = sorted(with_cpu_session(lambda s: _agg(s).collect()))
    tpu = sorted(with_tpu_session(lambda s: _agg(s).collect(),
                                  conf=MESH_CONF))
    assert len(cpu) == len(tpu)
    for a, b in zip(cpu, tpu):
        for x, y in zip(a, b):
            if isinstance(x, float):
                assert abs(x - y) <= 1e-9 * max(1.0, abs(x)), (a, b)
            else:
                assert x == y, (a, b)


def test_mesh_aggregate_planned():
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")

    def run(s):
        df = _agg(s)
        df.collect()
        tree = df._last_physical_plan.tree_string()
        assert "TpuMeshAggregate" in tree, tree
        return []
    with_tpu_session(run, conf=MESH_CONF)


def test_mesh_aggregate_nulls_and_sql():
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")

    def fn(s):
        df = s.create_dataframe(
            {"k": [1, 1, None, 2, None], "v": [10, 20, 30, 40, None]},
            num_partitions=2)
        df.create_or_replace_temp_view("t")
        return s.sql("SELECT k, sum(v) AS sv, count(*) AS n "
                     "FROM t GROUP BY k").collect()
    cpu = sorted(with_cpu_session(fn),
                 key=lambda r: (r[0] is None, r[0] or 0))
    tpu = sorted(with_tpu_session(fn, conf=MESH_CONF),
                 key=lambda r: (r[0] is None, r[0] or 0))
    assert cpu == tpu
