"""Flight recorder, stall watchdog, and diagnostic-bundle tests.

Ring half: overwrite-oldest semantics at capacity, concurrent writers
(one ring per thread, no cross-thread loss), global snapshot
time-ordering and query filtering.  Watchdog half: deterministic
``poll_once(now_ns=...)`` firing on a stalled RUNNING handle, once per
query, with pruning after the query leaves the inflight set.  Bundle
half: the acceptance path — an OOM-failed and a deadline-killed query
(tracing disabled, the default) each produce one ``diag-*.json`` with
the query's flight tail, every thread's stack, and the arena map; the
event-log outcome record links the bundle; rotation bounds the
directory; tools/diagnose.py renders it.
"""
import json
import os
import threading
import time
import types

import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.obs import diagnostics, flight
from spark_rapids_tpu.obs.watchdog import Watchdog
from spark_rapids_tpu.service import QueryCancelledError, QueryService
from spark_rapids_tpu.tools import diagnose
from spark_rapids_tpu.tools.events import read_event_log
from spark_rapids_tpu.udf import pandas_udf


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Isolate every test's rings; restore capacity + enabled state."""
    old_cap = flight._CAPACITY
    flight.reset()
    flight.enable()
    yield
    flight._CAPACITY = old_cap
    flight.reset()
    flight.enable()


def _tpu_session(extra=None):
    settings = {"spark.rapids.tpu.sql.enabled": True,
                "spark.rapids.tpu.sql.shuffle.partitions": 4}
    settings.update(extra or {})
    return TpuSession(TpuConf(settings))


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

class TestRing:
    def test_overwrite_oldest_at_capacity(self):
        flight._CAPACITY = 8
        for i in range(20):
            flight.record(flight.EV_KERNEL, "k", a=i)
        events = flight.snapshot()
        # only the most recent 8 survive, oldest first
        assert [e["a"] for e in events] == list(range(12, 20))
        occ = flight.occupancy()
        assert occ["events_recorded"] == 20
        assert occ["events_buffered"] == 8
        assert occ["capacity_per_thread"] == 8

    def test_disable_suppresses_recording(self):
        flight.record(flight.EV_KERNEL, "k")
        before = flight.occupancy()["events_recorded"]
        flight.disable()
        flight.record(flight.EV_KERNEL, "k")
        assert flight.occupancy()["events_recorded"] == before
        assert not flight.is_enabled()
        flight.enable()
        flight.record(flight.EV_KERNEL, "k")
        assert flight.occupancy()["events_recorded"] == before + 1

    def test_concurrent_writers_one_ring_each(self):
        n_threads, n_events = 4, 200
        flight._CAPACITY = 256
        barrier = threading.Barrier(n_threads)

        def _writer(tid):
            barrier.wait()
            for i in range(n_events):
                flight.record(flight.EV_KERNEL, "k", a=i,
                              query_id="q%d" % tid)
        threads = [threading.Thread(target=_writer, args=(t,),
                                    name="writer-%d" % t)
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        occ = flight.occupancy()
        assert occ["threads"] == n_threads
        assert occ["events_recorded"] == n_threads * n_events
        # no cross-thread loss: every thread's full sequence is present
        for tid in range(n_threads):
            mine = flight.snapshot(query_id="q%d" % tid)
            assert [e["a"] for e in mine] == list(range(n_events))

    def test_snapshot_is_globally_time_ordered(self):
        def _writer(qid):
            for i in range(50):
                flight.record(flight.EV_STATE, "s", a=i, query_id=qid)
        threads = [threading.Thread(target=_writer, args=("q%d" % t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ts = [e["ts_ns"] for e in flight.snapshot()]
        assert ts == sorted(ts)
        tail = flight.snapshot(last=10)
        assert len(tail) == 10
        assert [e["ts_ns"] for e in tail] == ts[-10:]

    def test_query_filter_drops_unattributed(self):
        flight.record(flight.EV_KERNEL, "mine", query_id="qA")
        flight.record(flight.EV_KERNEL, "other", query_id="qB")
        flight.record(flight.EV_KERNEL, "orphan")      # no query context
        names = [e["name"] for e in flight.snapshot(query_id="qA")]
        assert names == ["mine"]

    def test_configure_applies_conf_group(self):
        conf = TpuConf({
            "spark.rapids.tpu.obs.flightRecorder.enabled": False,
            "spark.rapids.tpu.obs.flightRecorder.capacityPerThread": 32})
        try:
            flight.configure(conf)
            assert not flight.is_enabled()
            assert flight._CAPACITY == 32
        finally:
            flight.enable()


# ---------------------------------------------------------------------------
# watchdog (deterministic: injected clock, fake service)
# ---------------------------------------------------------------------------

class _FakeService:
    """Duck-typed QueryService surface the watchdog consumes."""

    def __init__(self):
        self.items = []
        self.bundles = []
        self.events = []
        self._events = self

    def _inflight_items(self):
        return list(self.items)

    def _write_diag_bundle(self, trigger, handle, error=None):
        self.bundles.append((trigger, getattr(handle, "query_id", None),
                             error))
        return "/dev/null/diag-%d.json" % len(self.bundles)

    def log_service_event(self, kind, query_id, **fields):
        self.events.append((kind, query_id, fields))


def _handle(query_id, ident, status="RUNNING"):
    return types.SimpleNamespace(query_id=query_id, status=status,
                                 _worker_ident=ident)


class TestWatchdog:
    def test_fires_once_on_stalled_query(self):
        svc = _FakeService()
        wd = Watchdog(svc, interval_s=0.05, stall_s=1.0)
        ident = threading.get_ident()
        h = _handle("qS", ident)
        svc.items = [("qS", h)]
        flight.record(flight.EV_STATE, "running", query_id="qS")

        t0 = 1_000_000
        assert wd.poll_once(now_ns=t0) == []          # baseline observed
        # half the window: quiet but not yet stalled
        assert wd.poll_once(now_ns=t0 + int(0.5e9)) == []
        # past the window with an unchanged ring count: fire
        assert wd.poll_once(now_ns=t0 + int(1.5e9)) == ["qS"]
        assert svc.bundles and svc.bundles[0][:2] == ("watchdog", "qS")
        kind, qid, fields = svc.events[0]
        assert (kind, qid) == ("watchdog", "qS")
        assert fields["stalled_s"] >= 1.0
        assert fields["diag_bundle"].endswith("diag-1.json")
        # still stalled: at most one trigger per query
        assert wd.poll_once(now_ns=t0 + int(9e9)) == []
        st = wd.state()
        assert st["triggers"] == 1
        assert st["last_trigger"]["query_id"] == "qS"

    def test_progress_resets_the_window(self):
        svc = _FakeService()
        wd = Watchdog(svc, interval_s=0.05, stall_s=1.0)
        h = _handle("qP", threading.get_ident())
        svc.items = [("qP", h)]
        flight.record(flight.EV_STATE, "running", query_id="qP")
        t0 = 1_000_000
        wd.poll_once(now_ns=t0)
        flight.record(flight.EV_KERNEL, "k", query_id="qP")   # progress
        assert wd.poll_once(now_ns=t0 + int(2e9)) == []
        # window restarts from the progress observation
        assert wd.poll_once(now_ns=t0 + int(2.5e9)) == []
        assert wd.poll_once(now_ns=t0 + int(3.5e9)) == ["qP"]

    def test_finished_queries_are_pruned(self):
        svc = _FakeService()
        wd = Watchdog(svc, interval_s=0.05, stall_s=1.0)
        h = _handle("qF", threading.get_ident())
        svc.items = [("qF", h)]
        flight.record(flight.EV_STATE, "running", query_id="qF")
        wd.poll_once(now_ns=1_000_000)
        assert wd.state()["watched"] == 1
        svc.items = []                       # query left the inflight set
        wd.poll_once(now_ns=2_000_000)
        assert wd.state()["watched"] == 0

    def test_non_running_handles_ignored(self):
        svc = _FakeService()
        wd = Watchdog(svc, interval_s=0.05, stall_s=1.0)
        h = _handle("qQ", threading.get_ident(), status="QUEUED")
        svc.items = [("qQ", h)]
        wd.poll_once(now_ns=1_000_000)
        assert wd.poll_once(now_ns=int(1e12)) == []
        assert wd.state()["watched"] == 0

    def test_daemon_lifecycle(self):
        svc = _FakeService()
        wd = Watchdog(svc, interval_s=0.05, stall_s=60.0)
        assert not wd.running
        wd.start()
        try:
            assert wd.running
            assert wd.state()["enabled"]
        finally:
            wd.stop()
        assert not wd.running


# ---------------------------------------------------------------------------
# bundles: collection, rotation, rendering
# ---------------------------------------------------------------------------

class TestBundles:
    def test_collect_bundle_core_sections(self):
        flight.record(flight.EV_OOM, "device_alloc", a=1, b=2,
                      query_id="q9")
        bundle = diagnostics.collect_bundle(
            "oom", query_id="q9",
            error=RuntimeError("RESOURCE_EXHAUSTED: boom"))
        assert bundle["trigger"] == "oom"
        assert bundle["error"]["type"] == "RuntimeError"
        assert any(e["kind"] == flight.EV_OOM
                   for e in bundle["flight"]["query_events"])
        # every live thread's stack, this one included
        names = {t.get("name") for t in bundle["threads"]}
        assert threading.current_thread().name in names
        assert "stats" in bundle["arena"]

    def test_write_bundle_rotation(self, tmp_path):
        d = str(tmp_path / "diag")
        paths = []
        for i in range(5):
            paths.append(diagnostics.write_bundle(
                {"trigger": "failed", "query_id": "q%d" % i}, d,
                max_bundles=3))
        names = sorted(os.listdir(d))
        assert len(names) == 3
        # newest survive, oldest rotated away
        assert os.path.basename(paths[-1]) in names
        assert os.path.basename(paths[0]) not in names
        assert diagnose.list_bundles(d) == \
            [os.path.join(d, n) for n in names]

    def test_redaction(self):
        conf = TpuConf({"spark.rapids.tpu.secret.apiKey": "hunter2",
                        "spark.rapids.tpu.sql.enabled": True})
        red = diagnostics.redacted_conf(conf)
        assert red["spark.rapids.tpu.secret.apiKey"] == "***"
        assert red["spark.rapids.tpu.sql.enabled"] is True

    def test_diagnose_renders_and_cli(self, tmp_path, capsys):
        flight.record(flight.EV_KERNEL, "gather", a=7, query_id="q1")
        bundle = diagnostics.collect_bundle(
            "failed", query_id="q1", error=ValueError("boom"))
        path = diagnostics.write_bundle(bundle, str(tmp_path))
        text = diagnose.render_bundle(bundle)
        assert "incident bundle" in text and "boom" in text
        assert "flight recorder" in text and "thread stacks" in text
        assert diagnose.main([path]) == 0
        assert "trigger=failed" in capsys.readouterr().out
        assert diagnose.main(["--list", str(tmp_path)]) == 0
        assert diagnose.main(["--list", str(tmp_path / "empty")]) == 1


# ---------------------------------------------------------------------------
# service integration: the acceptance path
# ---------------------------------------------------------------------------

def _bundle_files(d):
    return [os.path.join(d, n) for n in sorted(os.listdir(d))
            if n.startswith("diag-") and n.endswith(".json")]


class TestServiceBundles:
    def _failing_df(self, s, noisy=32):
        """A query whose UDF records plenty of flight events (inside the
        worker's query context, so they attribute) and then OOMs —
        every attempt fails, so the outcome is a device_oom failure."""
        def _oom(series):
            for _ in range(noisy):
                flight.record(flight.EV_KERNEL, "doomed_kernel",
                              a=len(series))
            raise RuntimeError("RESOURCE_EXHAUSTED: injected test OOM")
        oom = pandas_udf(_oom, return_type=T.INT64)
        return s.range(0, 64, num_partitions=2) \
            .select(oom(F.col("id")).alias("id"))

    def test_oom_failure_writes_bundle_with_flight_tail(self, tmp_path):
        d = str(tmp_path / "diag")
        log = str(tmp_path / "events.jsonl")
        s = _tpu_session({
            "spark.rapids.tpu.obs.diagnostics.dir": d,
            "spark.rapids.tpu.eventLog.path": log,
            "spark.rapids.tpu.service.retry.maxAttempts": 2,
            "spark.rapids.tpu.service.retry.initialBackoffMs": 5})
        # tracing stays disabled (the default): the flight recorder is
        # the only always-on signal — exactly the acceptance scenario
        with QueryService(s, num_workers=1) as svc:
            h = svc.submit(self._failing_df(s), tenant="doomed")
            with pytest.raises(RuntimeError):
                h.result(timeout=120)
        files = _bundle_files(d)
        assert len(files) == 1 and "-oom.json" in files[0]
        with open(files[0]) as f:
            bundle = json.load(f)
        assert bundle["trigger"] == "oom"
        assert str(bundle["query_id"]) == str(h.query_id)
        assert "RESOURCE_EXHAUSTED" in bundle["error"]["message"]
        # >= the last 64 flight events for this query made the bundle
        q_events = bundle["flight"]["query_events"]
        assert len(q_events) >= 64
        assert any(e["name"] == "doomed_kernel" for e in q_events)
        assert any(e["kind"] == "retry" for e in q_events)
        # every thread's stack + the arena map are in the artifact
        assert bundle["threads"]
        assert "stats" in bundle["arena"]
        # the event-log failure record links the bundle (satellite a)
        recs = read_event_log(log, events="failed")
        mine = [r for r in recs if r["query_id"] == h.query_id]
        assert mine and mine[0]["diag_bundle"] == files[0]
        assert mine[0]["reason"] == "device_oom"
        # tools/diagnose.py renders it
        assert "doomed_kernel" in diagnose.render_bundle(bundle)

    def test_deadline_kill_writes_bundle(self, tmp_path):
        d = str(tmp_path / "diag")
        log = str(tmp_path / "events.jsonl")
        s = _tpu_session({
            "spark.rapids.tpu.obs.diagnostics.dir": d,
            "spark.rapids.tpu.eventLog.path": log})

        def _slow(series):
            time.sleep(0.05)
            return series
        slow = pandas_udf(_slow, return_type=T.INT64)
        df = s.range(0, 64, num_partitions=2) \
            .select(slow(F.col("id")).alias("id"))
        with QueryService(s, num_workers=1) as svc:
            h = svc.submit(df, tenant="dl", deadline_ms=40)
            with pytest.raises(QueryCancelledError):
                h.result(timeout=60)
        files = _bundle_files(d)
        assert len(files) == 1 and "-deadline.json" in files[0]
        with open(files[0]) as f:
            bundle = json.load(f)
        assert bundle["trigger"] == "deadline"
        assert bundle["cancel"]["reason"] == "deadline"
        assert bundle["threads"]
        recs = read_event_log(log, events="cancelled")
        mine = [r for r in recs if r["query_id"] == h.query_id]
        assert mine and mine[0]["diag_bundle"] == files[0]

    def test_no_diag_dir_means_no_bundle(self, tmp_path):
        log = str(tmp_path / "events.jsonl")
        s = _tpu_session({
            "spark.rapids.tpu.eventLog.path": log,
            "spark.rapids.tpu.service.retry.maxAttempts": 1})
        with QueryService(s, num_workers=1) as svc:
            h = svc.submit(self._failing_df(s, noisy=1), tenant="t")
            with pytest.raises(RuntimeError):
                h.result(timeout=120)
        recs = read_event_log(log, events="failed")
        mine = [r for r in recs if r["query_id"] == h.query_id]
        assert mine and mine[0]["diag_bundle"] is None

    def test_stats_expose_watchdog_and_flight(self, tmp_path):
        s = _tpu_session()
        with QueryService(s, num_workers=1) as svc:
            svc.submit(s.range(0, 16)).result(timeout=60)
            snap = svc.stats().snapshot()
            assert snap["flight_recorder"]["enabled"] is True
            assert snap["flight_recorder"]["events_recorded"] > 0
            wd = snap["watchdog"]
            assert wd["enabled"] is True and wd["triggers"] == 0
            assert svc.watchdog.running
        assert not svc.watchdog.running     # stopped with the service

    @pytest.mark.slow
    def test_recorder_overhead_is_small(self):
        """Loose, non-gating sanity bound on the always-on cost: the
        same query batch with the recorder on vs off stays within a
        generous ratio (scheduling noise dominates at this scale)."""
        s = _tpu_session()
        df = s.range(0, 20_000, num_partitions=4) \
            .filter(F.col("id") % 3 == 0) \
            .group_by((F.col("id") % 8).alias("k")) \
            .agg(F.sum("id").alias("sv"))

        def _run(n=6):
            with QueryService(s, num_workers=2) as svc:
                handles = [svc.submit(df) for _ in range(n)]
                for h in handles:
                    h.result(timeout=120)
            t0 = time.perf_counter()
            with QueryService(s, num_workers=2) as svc:
                handles = [svc.submit(df) for _ in range(n)]
                for h in handles:
                    h.result(timeout=120)
            return time.perf_counter() - t0

        flight.disable()
        try:
            t_off = _run()
        finally:
            flight.enable()
        t_on = _run()
        assert t_on <= t_off * 2.0 + 0.25, (t_on, t_off)
