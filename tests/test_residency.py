"""Device-residency analyzer + transfer-guard tests (analysis/residency.py).

Static half: the interprocedural taint walk over fixture buffers and
the real execution spine (which must be RES-clean with full registry
coverage).  Runtime half: the scoped transfer guard the tier-1
conftest forces on — undeclared device->host pulls raise, declared
sites lift the guard and land exact per-query counts on the session.
"""
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu.analysis import residency

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")

sys.path.insert(0, os.path.join(REPO_ROOT, "benchmarks"))


def _analyze(src, path="<fixture>"):
    findings, _declared = residency.analyze_source(src, path)
    return findings


# ---------------------------------------------------------------------------
# static pass: rules, call graph, registry coverage
# ---------------------------------------------------------------------------

class TestStaticRules:
    @pytest.mark.parametrize("rule", residency.ALL_RULES)
    def test_each_seeded_fixture_trips_its_rule(self, rule):
        path = os.path.join(FIXTURES, f"residency_{rule.lower()}.py")
        with open(path, encoding="utf-8") as f:
            findings = _analyze(f.read(), path)
        assert any(f.rule == rule for f in findings), \
            [f"{f.rule}:{f.line}" for f in findings]

    def test_interprocedural_device_return(self):
        # device taint carried through TWO helper hops to the sink
        src = ("import jax.numpy as jnp\n"
               "import numpy as np\n"
               "def inner(c):\n"
               "    return jnp.cumsum(c)\n"
               "def outer(c):\n"
               "    return inner(c)\n"
               "def sink(c):\n"
               "    return np.asarray(outer(c))\n")
        findings = _analyze(src)
        assert [f.rule for f in findings] == [residency.RES001]
        assert findings[0].line == 8

    def test_call_graph_recursion_terminates(self):
        # mutually recursive helpers: the fixpoint must terminate and
        # still prove the device return through the cycle
        src = ("import jax.numpy as jnp\n"
               "import numpy as np\n"
               "def a(c, d):\n"
               "    if d:\n"
               "        return b(c, d - 1)\n"
               "    return jnp.sum(c)\n"
               "def b(c, d):\n"
               "    return a(c, d)\n"
               "def sink(c):\n"
               "    return np.asarray(a(c, 3))\n")
        findings = _analyze(src)
        assert [f.rule for f in findings] == [residency.RES001]

    def test_declared_region_attributes_not_flags(self):
        src = ("import jax.numpy as jnp\n"
               "import numpy as np\n"
               "from spark_rapids_tpu.analysis import residency\n"
               "def fin(c):\n"
               "    dev = jnp.cumsum(c)\n"
               "    with residency.declared_transfer(site='size_probe'):\n"
               "        return np.asarray(dev)\n")
        findings, declared = residency.analyze_source(src)
        assert findings == []
        assert [d.site for d in declared] == ["size_probe"]

    def test_allow_comment_suppresses_with_reason(self):
        src = ("import jax.numpy as jnp\n"
               "import numpy as np\n"
               "def fin(c):\n"
               "    dev = jnp.sum(c)\n"
               "    return np.asarray(dev)"
               "  # residency: allow(RES001, reason=test plumbing)\n")
        assert _analyze(src) == []

    def test_allow_comment_without_reason_ignored(self):
        src = ("import jax.numpy as jnp\n"
               "import numpy as np\n"
               "def fin(c):\n"
               "    dev = jnp.sum(c)\n"
               "    return np.asarray(dev)  # residency: allow(RES001, reason=)\n")
        assert [f.rule for f in _analyze(src)] == [residency.RES001]

    def test_unknown_taint_not_flagged(self):
        # bare-parameter pull: UNKNOWN, not DEVICE-proven — the static
        # pass stays silent (the runtime guard owns that gap)
        src = ("import numpy as np\n"
               "def f(x):\n"
               "    return np.asarray(x)\n")
        assert _analyze(src) == []

    def test_host_value_not_flagged(self):
        src = ("import numpy as np\n"
               "def f():\n"
               "    h = np.arange(8)\n"
               "    return np.asarray(h)\n")
        assert _analyze(src) == []


class TestProjectSurface:
    def test_spine_is_res_clean(self):
        report = residency.analyze_project(REPO_ROOT)
        assert report.errors == []
        assert report.findings == [], \
            [f"{f.path}:{f.line} {f.rule}" for f in report.findings]

    def test_registry_coverage_complete(self):
        assert residency.coverage_gaps(REPO_ROOT) == []

    def test_sync_allowlist_not_stale(self):
        assert residency.stale_sync_allowlist(REPO_ROOT) == []

    def test_lint_allowlist_derived_from_registry(self):
        from spark_rapids_tpu.analysis import lint
        assert lint._SYNC_NP_FILE_ALLOWLIST == \
            residency.SYNC_NP_FILE_ALLOWLIST
        covered = {f for s in residency.SITES.values()
                   for f in s.covers_files}
        assert residency.SYNC_NP_FILE_ALLOWLIST == frozenset(covered)

    def test_cli_clean_and_fixture_inversion(self, capsys):
        sys.path.insert(0, os.path.join(REPO_ROOT, "ci"))
        try:
            import importlib
            cli = importlib.import_module("residency")
            if not hasattr(cli, "main"):   # name-collision guard
                cli = importlib.reload(cli)
            assert cli.main([]) == 0
            assert cli.main(["--fixture", "RES001"]) == 1
            assert cli.main(["--fixture", "NOPE"]) == 2
        finally:
            sys.path.remove(os.path.join(REPO_ROOT, "ci"))


# ---------------------------------------------------------------------------
# runtime half: interposer, declared counters
# ---------------------------------------------------------------------------

PLANTED = ("import jax.numpy as jnp\n"
           "import numpy as np\n"
           "def finalize(col):\n"
           "    counts = jnp.cumsum(col)\n"
           "    return np.asarray(counts)\n")


class TestTransferGuard:
    def test_planted_pull_trips_static_and_runtime(self):
        # the SAME planted undeclared np.asarray is caught by both
        # halves: the taint walk flags RES001, and executing it under
        # the armed guard raises UndeclaredTransferError
        findings = _analyze(PLANTED, "planted.py")
        assert [f.rule for f in findings] == [residency.RES001]
        ns = {}
        exec(compile(PLANTED, "planted.py", "exec"), ns)
        with residency.guard_scope({}):
            with pytest.raises(residency.UndeclaredTransferError):
                ns["finalize"](jnp.arange(8))

    def test_declared_region_lifts_guard_and_counts(self):
        marker = residency.snapshot()
        with residency.guard_scope({}):
            dev = jnp.arange(8)
            with residency.declared_transfer(site="size_probe"):
                out = np.asarray(dev)
        assert out.tolist() == list(range(8))
        total, sites = residency.delta(marker)
        assert total == 1 and sites == {"size_probe": 1}

    def test_uncounted_site_excluded_from_delta(self):
        marker = residency.snapshot()
        with residency.guard_scope({}):
            dev = jnp.arange(4)
            with residency.declared_transfer(site="pending_probe"):
                np.asarray(dev)
        total, sites = residency.delta(marker)
        assert total == 0 and sites == {}

    def test_float_int_sinks_trip(self):
        with residency.guard_scope({}):
            dev = jnp.float32(1.5)
            with pytest.raises(residency.UndeclaredTransferError):
                float(dev)

    def test_guard_disarmed_passthrough(self):
        # no guard scope: pulls behave normally even after the
        # interposer is installed by other tests
        assert float(jnp.float32(2.5)) == 2.5
        assert np.asarray(jnp.arange(3)).tolist() == [0, 1, 2]

    def test_unregistered_site_raises(self):
        # getattr keeps this lexical call out of the coverage scan —
        # a literal declared_transfer('not_a_site') would itself be a
        # registry coverage gap (which is the point of the scan)
        enter = getattr(residency, "declared_" + "transfer")
        with pytest.raises(KeyError):
            with enter(site="not_a_site"):
                pass

    def test_host_values_never_blocked(self):
        with residency.guard_scope({}):
            assert np.asarray([1, 2, 3]).tolist() == [1, 2, 3]
            assert np.array(7).item() == 7

    def test_guard_env_off_switch(self, monkeypatch):
        monkeypatch.setenv("SPARK_RAPIDS_TPU_FORCE_TRANSFER_GUARD", "0")
        assert not residency.guard_enabled()
        with residency.guard_scope({}):
            # scope is a no-op: undeclared pull passes
            assert float(jnp.float32(3.5)) == 3.5


# ---------------------------------------------------------------------------
# end-to-end: TPC-DS q3/q42 declared-count exactness under the guard
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpcds_dir(tmp_path_factory):
    import tpcds
    d = str(tmp_path_factory.mktemp("residency_tpcds") / "sf")
    tpcds.generate(d, scale=0.002, seed=11)
    return d


def _declared_counts(query, data_dir, parallelism, superstage):
    import tpcds
    from harness import with_tpu_session

    def fn(s):
        tpcds.register(s, data_dir)
        s.sql(tpcds.QUERIES[query]).collect()
        return dict(s.last_query_declared_transfers)

    return with_tpu_session(fn, conf={
        "spark.rapids.tpu.exec.pipelineParallelism": parallelism,
        "spark.rapids.tpu.sql.superstage": superstage,
    })


@pytest.mark.parametrize("query", ["q3", "q42"])
@pytest.mark.parametrize("superstage", [True, False])
def test_declared_counts_exact_across_parallelism(query, superstage,
                                                  tpcds_dir):
    """The per-query declared-transfer profile is a property of the
    PLAN, not the execution schedule: morsel parallelism {1,4} must
    reproduce identical per-site counts, and a repeat run must too
    (superstage on/off legitimately differ — fusing stages is HOW the
    superstage removes flushes — so each mode pins its own profile)."""
    seq = _declared_counts(query, tpcds_dir, 1, superstage)
    par = _declared_counts(query, tpcds_dir, 4, superstage)
    again = _declared_counts(query, tpcds_dir, 1, superstage)
    assert seq == par, f"{query} ss={superstage}: {seq} vs par4 {par}"
    assert seq == again, f"{query} ss={superstage}: not reproducible"
    assert sum(seq.values()) > 0, "query ran with no declared transfers"


def test_declared_counts_on_event_log(tpcds_dir):
    import tpcds
    from harness import with_tpu_session

    def fn(s):
        tpcds.register(s, tpcds_dir)
        s.sql(tpcds.QUERIES["q3"]).collect()
        return dict(s.last_query_event)

    rec = with_tpu_session(fn)
    assert "declared_transfers" in rec
    assert "declared_transfer_sites" in rec
    sites = rec["declared_transfer_sites"]
    assert rec["declared_transfers"] == sum(sites.values())
    # rides next to the staging counters the doctor joins against
    assert "flushes" in rec
