"""Plan-cache + predictive-scheduler tests (cache/plan_cache.py +
service/scheduler.py).

Six surfaces:

1. Correctness — cache-on results are sha-identical to cache-off
   across pipelineParallelism {1,4} x superstage on/off, including a
   hit whose literals differ from the entry's cold run (the
   literal-normalized key contract).
2. Certificate replay — a hit replays the stored FlushPrediction
   EXACTLY (runtime FLUSH_COUNT delta == predicted), skipping the
   verifier and the flush-budget walk.
3. Lifecycle — conf-fingerprint invalidation, bounded LRU eviction,
   and the validation-miss safety net (a poisoned certificate is never
   trusted).
4. Scheduler — frozen-baseline predictions (obs/anomaly.baseline),
   rank tiers inside FairQueryQueue, predicted-breach shed vs deadline
   breach as DISTINCT SLO causes, and the zero-false-shed gates.
5. Pre-warm hints — shape → (program, bucket) mapping into the AOT
   warmup daemon, hint-origin compiles counted separately.
6. Hygiene — lint scopes extended to both new modules + the seeded
   fixture, and report/dashboard rendering (placeholder-tolerant on
   pre-r16 event logs).
"""
import hashlib
import json
import os
import time
import types

import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.cache import plan_cache
from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar import pending
from spark_rapids_tpu.compile import aot
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.obs import anomaly, slo
from spark_rapids_tpu.service.errors import ServiceOverloaded
from spark_rapids_tpu.service.queue import FairQueryQueue
from spark_rapids_tpu.service.scheduler import (AdmissionScheduler,
                                                PredictedBreach)
from spark_rapids_tpu.service.server import QueryService
from spark_rapids_tpu.service.warmup import WarmupDaemon
from spark_rapids_tpu.udf import pandas_udf

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _plan_cache_reset():
    """Isolate the process-wide cache/scheduler planes (and restore the
    default config afterwards — last-configured service wins)."""
    plan_cache.reset()
    anomaly.reset()
    slo.reset()
    aot.reset()
    yield
    default = TpuConf({})
    plan_cache.configure(default)
    anomaly.configure(default)
    slo.configure(default)
    plan_cache.reset()
    anomaly.reset()
    slo.reset()
    aot.reset()


def _session(extra=None):
    settings = {"spark.rapids.tpu.sql.enabled": True,
                "spark.rapids.tpu.sql.shuffle.partitions": 4}
    settings.update(extra or {})
    return TpuSession(TpuConf(settings))


def _df(s, lit=5):
    return s.range(0, 256, num_partitions=2) \
        .select((F.col("id") % 7).alias("k"), F.col("id").alias("v")) \
        .filter(F.col("v") > lit) \
        .group_by("k").agg(F.sum("v").alias("sv"))


def _sha(rows):
    return hashlib.sha256(
        json.dumps(sorted(str(r) for r in rows)).encode()).hexdigest()


def _seed_baseline(fp, exec_ms, n=10):
    """Freeze an EWMA exec_ms baseline for ``fp`` (constant series:
    baseline == exec_ms, variance == 0 — the conservative floor equals
    the mean, so shed decisions in tests are deterministic)."""
    for _ in range(n):
        anomaly.fold({"fingerprint": fp, "exec_ms": float(exec_ms),
                      "flushes": 1})


# ---------------------------------------------------------------------------
# 1. correctness: cache-on == cache-off, literals free to differ
# ---------------------------------------------------------------------------

class TestCacheCorrectness:
    @pytest.mark.parametrize("pp", [1, 4])
    @pytest.mark.parametrize("ss", [True, False])
    def test_hit_sha_identical_to_cache_off(self, pp, ss):
        base = {"spark.rapids.tpu.exec.pipelineParallelism": pp,
                "spark.rapids.tpu.sql.superstage": ss}
        off = _session(dict(base,
                            **{"spark.rapids.tpu.cache.plan.enabled":
                               False}))
        sha_off = _sha(_df(off, lit=50).collect())
        assert off.last_query_plan_cache is None

        on = _session(base)
        _df(on, lit=5).collect()                      # cold: stores
        assert on.last_query_plan_cache[0] == "miss"
        rows = _df(on, lit=50).collect()              # DIFFERENT literal
        assert on.last_query_plan_cache[0] == "hit"
        assert _sha(rows) == sha_off
        st = plan_cache.stats_section()
        assert st["hits"] == 1 and st["misses"] == 1
        assert st["hit_pct"] == 50.0

    def test_shape_change_is_a_miss(self):
        s = _session()
        _df(s).collect()
        s.range(0, 256, num_partitions=2) \
            .select((F.col("id") % 7).alias("k"), F.col("id").alias("v")) \
            .group_by("k").agg(F.sum("v").alias("sv"),
                               F.count("v").alias("cv")).collect()
        assert s.last_query_plan_cache[0] == "miss"
        assert plan_cache.stats_section()["hits"] == 0
        assert plan_cache.entry_count() == 2


# ---------------------------------------------------------------------------
# 2. certificate replay: PV-FLUSH stays exact on the cached path
# ---------------------------------------------------------------------------

class TestFlushReplay:
    def _proj(self, s, lit):
        # a shape the PV-FLUSH model covers exactly (single pipeline,
        # no exchange) — predicted == runtime delta holds bit-exact
        return s.range(0, 256, num_partitions=2) \
            .select((F.col("id") % 7).alias("k"),
                    F.col("id").alias("v")) \
            .filter(F.col("v") > lit)

    def test_hit_replays_exact_flush_count(self):
        s = _session()
        self._proj(s, 5).collect()                    # cold
        self._proj(s, 25).collect()                   # hit: warms caches
        assert s.last_query_plan_cache[0] == "hit"
        f0 = pending.FLUSH_COUNT
        self._proj(s, 50).collect()                   # measured hit
        delta = pending.FLUSH_COUNT - f0
        assert s.last_query_plan_cache[0] == "hit"
        assert s.last_query_predicted_flushes is not None
        assert delta == s.last_query_predicted_flushes
        assert s.last_query_flushes == s.last_query_predicted_flushes

    def test_replayed_prediction_matches_cold_path(self):
        # replay fidelity on an exchange-bearing shape: a hit reports
        # EXACTLY the prediction and runtime cost the cold path did
        s = _session()
        _df(s, lit=5).collect()                       # cold
        pred_cold = s.last_query_predicted_flushes
        flushes_cold = s.last_query_flushes
        _df(s, lit=50).collect()                      # hit
        assert s.last_query_plan_cache[0] == "hit"
        assert s.last_query_predicted_flushes == pred_cold
        assert s.last_query_flushes == flushes_cold

    def test_warm_planner_path_recorded(self):
        s = _session()
        _df(s, lit=5).collect()
        cold_ms = s.last_query_plan_cache[1]
        _df(s, lit=50).collect()
        warm_ms = s.last_query_plan_cache[1]
        assert cold_ms > 0 and warm_ms > 0
        top = plan_cache.top_entries(1)[0]
        assert top["hits"] == 1
        assert top["cold_ms"] == pytest.approx(cold_ms, abs=0.01)
        assert top["warm_ms"] == pytest.approx(warm_ms, abs=0.01)


# ---------------------------------------------------------------------------
# 3. lifecycle: invalidation, bounded eviction, validation miss
# ---------------------------------------------------------------------------

class TestCacheLifecycle:
    def test_conf_fingerprint_change_invalidates(self):
        _df(_session()).collect()
        assert plan_cache.entry_count() == 1
        # a plan-affecting conf moved: stored certificates out of scope
        _df(_session({"spark.rapids.tpu.sql.batchSizeRows":
                      1 << 19})).collect()
        st = plan_cache.stats_section()
        assert st["invalidated"] == 1
        assert st["misses"] == 2 and st["hits"] == 0
        assert plan_cache.entry_count() == 1

    def test_obs_conf_overlay_does_not_invalidate(self):
        _df(_session()).collect()
        s2 = _session({"spark.rapids.tpu.obs.slo.targetMs": 250.0})
        _df(s2, lit=50).collect()
        assert s2.last_query_plan_cache[0] == "hit"
        assert plan_cache.stats_section()["invalidated"] == 0

    def test_bounded_lru_eviction(self):
        s = _session({"spark.rapids.tpu.cache.plan.maxEntries": 2})
        base = s.range(0, 256, num_partitions=2) \
            .select((F.col("id") % 7).alias("k"), F.col("id").alias("v"))
        base.filter(F.col("v") > 5).group_by("k") \
            .agg(F.sum("v").alias("sv")).collect()
        base.group_by("k").agg(F.sum("v").alias("sv"),
                               F.count("v").alias("cv")).collect()
        base.filter(F.col("v") > 5).group_by("k") \
            .agg(F.count("v").alias("cv")).collect()
        assert plan_cache.entry_count() <= 2
        assert plan_cache.stats_section()["evicted"] >= 1

    def test_poisoned_certificate_never_trusted(self):
        s = _session()
        expected = _sha(_df(s, lit=50).collect())
        key = plan_cache.shape_key(_df(s)._plan)
        with plan_cache._LOCK:
            plan_cache._ENTRIES[key]["plan_fingerprint"] = "poisoned!"
        rows = _df(s, lit=50).collect()
        assert _sha(rows) == expected                 # cold path result
        assert s.last_query_plan_cache[0] == "miss"
        st = plan_cache.stats_section()
        assert st["validation_misses"] == 1
        # the shape re-stored with its REAL fingerprint: next repeat hits
        _df(s, lit=25).collect()
        assert s.last_query_plan_cache[0] == "hit"


# ---------------------------------------------------------------------------
# 4. scheduler: baseline accessor, assess, queue ranking, SLO causes
# ---------------------------------------------------------------------------

class TestBaselineAccessor:
    def test_none_until_frozen_then_mean_var(self):
        assert anomaly.baseline("nofp", "exec_ms") is None
        for _ in range(7):
            anomaly.fold({"fingerprint": "fpX", "exec_ms": 100.0})
        assert anomaly.baseline("fpX", "exec_ms") is None   # warming
        anomaly.fold({"fingerprint": "fpX", "exec_ms": 100.0})
        mean, var = anomaly.baseline("fpX", "exec_ms")
        assert abs(mean - 100.0) < 1e-6
        assert var >= 0.0
        assert anomaly.baseline("fpX", "queue_ms") is None  # other key


class TestSchedulerAssess:
    def _seeded(self, exec_ms=5000.0):
        s = _session()
        df = _df(s)
        df.collect()
        _seed_baseline(s.last_query_fingerprint, exec_ms)
        return s, df

    def test_predicted_breach_shed_over_tight_budget(self):
        s, df = self._seeded(5000.0)
        sched = AdmissionScheduler(s.conf.with_overrides(
            {"spark.rapids.tpu.obs.slo.targetMs": 100.0}))
        d = sched.assess(df._plan, s.conf, None)
        assert abs(d.predicted_ms - 5000.0) < 1.0
        assert d.rank == 2
        assert d.budget_ms == 100.0
        assert "predicted_breach" in d.shed_reason
        st = sched.stats_section()
        assert st["predicted_breach_shed"] == 1
        assert st["ranks"][2] == 1

    def test_in_budget_ranks_zero_no_shed(self):
        s, df = self._seeded(5000.0)
        sched = AdmissionScheduler(s.conf.with_overrides(
            {"spark.rapids.tpu.obs.slo.targetMs": 60000.0}))
        d = sched.assess(df._plan, s.conf, None)
        assert d.rank == 0 and d.shed_reason is None

    def test_deadline_is_the_tighter_budget(self):
        s, df = self._seeded(5000.0)
        sched = AdmissionScheduler(s.conf.with_overrides(
            {"spark.rapids.tpu.obs.slo.targetMs": 60000.0}))
        d = sched.assess(df._plan, s.conf, 50.0)
        assert d.budget_ms == 50.0
        assert d.rank == 2 and "predicted_breach" in d.shed_reason

    def test_no_baseline_never_sheds(self):
        # zero-false-shed gate: an unpredictable query is admitted
        # unranked no matter how tight the budget is
        s = _session()
        df = _df(s)                                   # never planned
        sched = AdmissionScheduler(s.conf.with_overrides(
            {"spark.rapids.tpu.obs.slo.targetMs": 0.001}))
        d = sched.assess(df._plan, s.conf, 0.001)
        assert d.predicted_ms is None
        assert d.rank is None and d.shed_reason is None

    def test_no_budget_never_sheds(self):
        s, df = self._seeded(5000.0)
        sched = AdmissionScheduler(s.conf)            # targetMs = 0
        d = sched.assess(df._plan, s.conf, None)
        assert d.predicted_ms is not None
        assert d.shed_reason is None and d.rank is None

    def test_disabled_scheduler_is_inert(self):
        s, df = self._seeded(5000.0)
        sched = AdmissionScheduler(s.conf.with_overrides(
            {"spark.rapids.tpu.service.sched.enabled": False,
             "spark.rapids.tpu.obs.slo.targetMs": 1.0}))
        d = sched.assess(df._plan, s.conf, 1.0)
        assert d.predicted_ms is None and d.shed_reason is None

    def test_observe_folds_honesty_error(self):
        s, df = self._seeded(5000.0)
        sched = AdmissionScheduler(s.conf)
        m = types.SimpleNamespace(predicted_exec_ms=120.0,
                                  outcome="completed", execute_ms=100.0)
        assert abs(sched.observe(m) - 20.0) < 1e-6
        assert sched.observe(types.SimpleNamespace(
            predicted_exec_ms=None, outcome="completed",
            execute_ms=1.0)) is None
        err = sched.stats_section()["pred_err_pct"]
        assert err["n"] == 1 and abs(err["mean"] - 20.0) < 0.11


def _q(i, tenant="t1", rank=None, priority=0):
    return types.SimpleNamespace(tenant=tenant, priority=priority,
                                 est_bytes=0, query_id=f"q{i}",
                                 _sched_rank=rank)


class TestQueueRanking:
    def test_ranked_insert_orders_tiers_fifo_within(self):
        q = FairQueryQueue(max_depth=16)
        for i, rank in enumerate([2, None, 0, 2, 0, None]):
            q.offer(_q(i, rank=rank))
        order = [q.take(timeout=1).query_id for _ in range(6)]
        assert order == ["q2", "q4", "q1", "q5", "q0", "q3"]

    def test_tenant_fairness_beats_rank(self):
        # ranking reorders ONE tenant's deque; cross-tenant round-robin
        # is untouched — t1's predicted breach still dequeues first
        q = FairQueryQueue(max_depth=16)
        q.offer(_q(0, tenant="t1", rank=2))
        q.offer(_q(1, tenant="t2", rank=0))
        assert q.take(timeout=1).query_id == "q0"
        assert q.take(timeout=1).query_id == "q1"

    def test_priority_classes_beat_rank(self):
        q = FairQueryQueue(max_depth=16)
        q.offer(_q(0, rank=0, priority=0))
        q.offer(_q(1, rank=2, priority=5))
        assert q.take(timeout=1).query_id == "q1"

    def test_unstamped_degrades_to_fifo(self):
        q = FairQueryQueue(max_depth=16)
        for i in range(4):
            q.offer(types.SimpleNamespace(tenant="t", priority=0,
                                          est_bytes=0, query_id=f"q{i}"))
        order = [q.take(timeout=1).query_id for _ in range(4)]
        assert order == ["q0", "q1", "q2", "q3"]


# ---------------------------------------------------------------------------
# service integration: predicted-breach vs deadline causes, zero false
# sheds in-band
# ---------------------------------------------------------------------------

class TestServiceIntegration:
    def test_predicted_breach_shed_and_cause(self, tmp_path):
        ev = str(tmp_path / "events.jsonl")
        s = _session({"spark.rapids.tpu.obs.slo.targetMs": 50.0,
                      "spark.rapids.tpu.eventLog.path": ev})
        df = _df(s)
        df.collect()                                  # seed cache entry
        _seed_baseline(s.last_query_fingerprint, 10000.0)
        with QueryService(s, num_workers=1) as svc:
            with pytest.raises(PredictedBreach) as ei:
                svc.submit(df)
            assert isinstance(ei.value, ServiceOverloaded)
            assert ei.value.predicted_ms > ei.value.budget_ms
            snap = svc.stats().snapshot()
        assert snap["shed"] == 1
        assert snap["scheduler"]["predicted_breach_shed"] == 1
        assert snap["plan_cache"]["entries"] == 1
        causes = slo.stats_section()["tenants"]["default"]["breach_causes"]
        assert causes == {"predicted_breach": 1}
        with open(ev) as f:
            shed = [r for r in (json.loads(l) for l in f)
                    if r.get("event") == "shed"]
        assert shed and "predicted_breach" in shed[-1]["reason"]
        assert shed[-1]["predicted_exec_ms"] == pytest.approx(
            10000.0, rel=0.01)
        assert "diag_bundle" in shed[-1]

    def test_deadline_breach_is_a_distinct_cause(self):
        s = _session({"spark.rapids.tpu.obs.slo.targetMs": 50.0})

        def _slow(series):
            time.sleep(0.2)
            return series
        slow = pandas_udf(_slow, return_type=T.INT64)
        df = s.range(0, 64, num_partitions=2) \
            .select(slow(F.col("id")).alias("id"))
        with QueryService(s, num_workers=1) as svc:
            h = svc.submit(df, deadline_ms=60)
            with pytest.raises(Exception):
                h.result(timeout=60)
            snap = svc.stats().snapshot()
        assert snap["scheduler"]["predicted_breach_shed"] == 0
        causes = slo.stats_section()["tenants"]["default"]["breach_causes"]
        assert causes.get("deadline") == 1
        assert "predicted_breach" not in causes

    def test_in_band_traffic_zero_false_sheds(self):
        s = _session({"spark.rapids.tpu.obs.slo.targetMs": 60000.0})
        df = _df(s)
        df.collect()
        _seed_baseline(s.last_query_fingerprint, 80.0)
        with QueryService(s, num_workers=1) as svc:
            h = svc.submit(df)
            h.result(timeout=60)
            snap = svc.stats().snapshot()
        assert snap["shed"] == 0 and snap["completed"] == 1
        assert h.metrics.predicted_exec_ms == pytest.approx(80.0,
                                                            rel=0.01)
        assert h.metrics.to_record()["predicted_exec_ms"] is not None
        # the honesty loop closed: |predicted - actual| folded in
        assert snap["scheduler"]["pred_err_pct"]["n"] == 1

    def test_mixed_burst_repeat_shapes_hit(self):
        s = _session()
        df = _df(s)
        with QueryService(s, num_workers=2) as svc:
            handles = [svc.submit(_df(s, lit=5 + i), tenant=f"t{i % 2}")
                       for i in range(6)]
            for h in handles:
                h.result(timeout=120)
            snap = svc.stats().snapshot()
        assert snap["completed"] == 6
        pc = snap["plan_cache"]
        assert pc["hits"] >= 5 and pc["misses"] == 1


# ---------------------------------------------------------------------------
# 5. pre-warm hints
# ---------------------------------------------------------------------------

class TestPrewarmHints:
    def test_note_hint_contract(self):
        s = _session()                                # wires the lattice
        with pytest.raises(ValueError):
            aot.note_hint("not_a_program", 1024)
        assert aot.note_hint("fused_project", 2048) is True
        assert aot.note_hint("fused_project", 2048) is True  # re-note ok
        st = aot.stats_section()
        assert st["hints_noted"] == 2
        assert st["hints_pending"] == 1

    def test_hinted_bucket_joins_candidates_and_counts(self):
        _session()
        compiled = []
        aot.register_warmer("fused_project", compiled.append)
        aot.note_hint("fused_project", 4096)
        cands = aot.warm_candidates()
        assert ("fused_project", "default", 4096) in cands
        assert aot.warm_one("fused_project", "default", 4096)
        assert compiled == [4096]
        st = aot.stats_section()
        assert st["hint_compiles"] == 1               # hint-origin
        assert st["warmup_compiles"] == 1
        assert st["hints_pending"] == 0

    def test_daemon_note_hint_counts(self):
        _session()
        d = WarmupDaemon()
        assert d.note_hint("fused_project", 2048) is True
        assert d.note_hint("bogus", 2048) is False    # swallowed
        st = d.state()
        assert st["hints_observed"] == 2
        assert st["hints_fresh"] == 1

    def test_shape_maps_to_programs(self):
        s = _session()
        hints = AdmissionScheduler._prewarm_hints(_df(s)._plan, s.conf)
        progs = {p for p, _ in hints}
        assert "staged_compute" in progs
        assert "hash_aggregate_grouped" in progs
        assert "fused_project" in progs
        buckets = {b for _, b in hints}
        assert len(buckets) == 1 and all(b >= 1 for b in buckets)


# ---------------------------------------------------------------------------
# 6. lint scopes + seeded fixture + rendering
# ---------------------------------------------------------------------------

class TestPlanCacheLint:
    MODULES = ("spark_rapids_tpu/cache/plan_cache.py",
               "spark_rapids_tpu/service/scheduler.py")

    def test_new_modules_in_sync_obs_hyg_scopes(self):
        from spark_rapids_tpu.analysis import lint as AL
        for rel in self.MODULES:
            scopes = AL._scopes_for(rel)
            assert AL.SYNC001 in scopes, rel
            assert AL.OBS002 in scopes, rel
            assert AL.HYG002 in scopes, rel

    def test_seeded_fixture_trips_all_three_rules(self):
        from spark_rapids_tpu.analysis import lint as AL
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "lint_fixtures", "plan_cache_sync.py")
        with open(path) as f:
            fs = AL.lint_source(f.read(), path)
        rules = {f.rule for f in fs}
        assert {AL.SYNC001, AL.OBS002, AL.HYG002} <= rules

    def test_shipped_modules_lint_clean(self):
        from spark_rapids_tpu.analysis import lint as AL
        for rel in self.MODULES:
            path = os.path.join(REPO_ROOT, rel)
            with open(path) as f:
                fs = AL.lint_source(f.read(), rel,
                                    scopes=AL._scopes_for(rel))
            assert fs == [], (rel, AL.format_findings(fs))


class TestRendering:
    def test_report_header_shows_plan_cache_disposition(self):
        from spark_rapids_tpu.tools.report import render_query_report
        rec = {"wall_ms": 5.0, "plan_cache": "hit",
               "planner_path_ms": 0.8, "physical_plan": "",
               "node_metrics": {}}
        out = render_query_report("q1", {"engine": [rec], "service": []})
        assert "plan_cache=hit" in out
        assert "planner_path_ms=0.8" in out

    def test_pre_r16_engine_record_still_renders(self):
        from spark_rapids_tpu.tools.report import render_query_report
        rec = {"wall_ms": 5.0, "physical_plan": "", "node_metrics": {}}
        out = render_query_report("q1", {"engine": [rec], "service": []})
        assert "plan_cache" not in out

    def test_service_story_predicted_vs_actual(self):
        from spark_rapids_tpu.tools.report import render_query_report
        rec = {"event": "completed", "ts": 1.0, "attempts": 1,
               "queue_wait_ms": 1.0, "execute_ms": 80.0,
               "sem_wait_ms": 0.0, "spill_bytes": 0,
               "predicted_exec_ms": 100.0}
        out = render_query_report("q1", {"engine": [], "service": [rec]})
        assert "predicted   exec_ms=100.0" in out
        assert "err=25.0%" in out

    def test_service_story_pre_r16_has_no_predicted_line(self):
        from spark_rapids_tpu.tools.report import render_query_report
        rec = {"event": "completed", "ts": 1.0, "attempts": 1,
               "queue_wait_ms": 1.0, "execute_ms": 80.0,
               "sem_wait_ms": 0.0, "spill_bytes": 0}
        out = render_query_report("q1", {"engine": [], "service": [rec]})
        assert "predicted " not in out

    def test_dashboard_plan_cache_panel(self):
        from spark_rapids_tpu.obs import dashboard
        s = _session()
        _df(s, lit=5).collect()
        _df(s, lit=50).collect()
        page = dashboard.render_html()
        assert "Plan cache" in page
        assert "hit rate: 50.0%" in page
        assert plan_cache.top_entries(1)[0]["digest"] in page
