"""DataFrame-level CPU-vs-TPU equality tests.

Reference pattern: integration_tests/src/main/python/{hash_aggregate_test,
join_test,sort_test,arithmetic_ops_test}.py — same oracle, same shape.
"""
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar import dtypes as T

from harness import (assert_tpu_and_cpu_are_equal_collect,
                     with_tpu_session)
from data_gen import (IntGen, FloatGen, StringGen, BoolGen, KeyGen, DateGen,
                      gen_df)

N = 300


def _base_gens():
    return {
        "k": KeyGen(cardinality=12),
        "i": IntGen(lo=-10_000, hi=10_000),
        "f": FloatGen(),
        "s": StringGen(max_len=8),
        "b": BoolGen(),
    }


class TestProjectFilter:
    def test_project_arithmetic(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .select(F.col("i") + 1, F.col("i") * F.col("k"),
                    (F.col("f") / 2).alias("h"),
                    (F.col("i") % 7).alias("m")))

    def test_filter_predicates(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .filter((F.col("i") > 0) & (F.col("k") < 8)))

    def test_filter_or_null(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .filter(F.col("i").is_null() | (F.col("f") > 0)))

    def test_conditional(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .select(F.when(F.col("i") > 0, "pos")
                    .when(F.col("i") < 0, "neg")
                    .otherwise("zero").alias("sign"),
                    F.coalesce("i", "k").alias("c")))

    def test_string_funcs(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .select(F.upper("s"), F.lower("s"), F.length("s"),
                    F.substring("s", 2, 3),
                    F.col("s").like("a%").alias("lk"),
                    F.trim(F.col("s")).alias("t")))

    def test_casts(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .select(F.col("i").cast("double"), F.col("f").cast("int"),
                    F.col("i").cast("string"), F.col("b").cast("int")))

    def test_date_funcs(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"d": DateGen()}, N)
            .select(F.year(F.col("d").cast(T.DATE)).alias("y"),
                    F.month(F.col("d").cast(T.DATE)).alias("m"),
                    F.dayofmonth(F.col("d").cast(T.DATE)).alias("dom"),
                    F.dayofweek(F.col("d").cast(T.DATE)).alias("dow")))


class TestHashAggregate:
    def test_groupby_sum_count(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .group_by("k")
            .agg(F.sum("i").alias("si"), F.count().alias("c"),
                 F.count("f").alias("cf")))

    def test_groupby_min_max_avg(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .group_by("k")
            .agg(F.min("i").alias("mn"), F.max("i").alias("mx"),
                 F.avg("f").alias("av")))

    def test_groupby_string_key(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .group_by("s").agg(F.count().alias("c"), F.sum("i").alias("si")))

    def test_groupby_multi_key(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .group_by("k", "b").agg(F.sum("i").alias("si")))

    def test_global_agg(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .agg(F.sum("i").alias("si"), F.count().alias("c"),
                 F.min("f").alias("mn"), F.max("f").alias("mx")))

    def test_groupby_min_max_string(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .group_by("k").agg(F.min("s").alias("mn"),
                               F.max("s").alias("mx")))

    def test_distinct(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=8),
                                 "b": BoolGen()}, N).distinct())

    def test_groupby_partitioned_input(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N, num_partitions=4)
            .group_by("k").agg(F.sum("i").alias("si"),
                               F.count().alias("c")))


class TestJoin:
    @pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                     "semi", "anti"])
    def test_join_types(self, how):
        def fn(s):
            left = gen_df(s, {"k": KeyGen(cardinality=15),
                              "a": IntGen()}, N, seed=1)
            right = gen_df(s, {"k": KeyGen(cardinality=20),
                               "b": IntGen()}, N // 2, seed=2)
            return left.join(right, on="k", how=how)
        assert_tpu_and_cpu_are_equal_collect(fn)

    def test_join_string_keys(self):
        def fn(s):
            left = gen_df(s, {"k": StringGen(max_len=3, null_ratio=0.05),
                              "a": IntGen()}, N, seed=3)
            right = gen_df(s, {"k": StringGen(max_len=3, null_ratio=0.05),
                               "b": IntGen()}, N // 2, seed=4)
            return left.join(right, on="k")
        assert_tpu_and_cpu_are_equal_collect(fn)

    def test_join_partitioned(self):
        def fn(s):
            left = gen_df(s, {"k": KeyGen(), "a": IntGen()}, N, seed=5,
                          num_partitions=3)
            right = gen_df(s, {"k": KeyGen(), "b": IntGen()}, N, seed=6,
                           num_partitions=2)
            return left.join(right, on="k")
        assert_tpu_and_cpu_are_equal_collect(fn)

    def test_cross_join(self):
        def fn(s):
            left = gen_df(s, {"a": IntGen()}, 20, seed=7)
            right = gen_df(s, {"b": IntGen()}, 15, seed=8)
            return left.join(right, how="cross")
        assert_tpu_and_cpu_are_equal_collect(fn)


class TestSortLimit:
    def test_global_sort(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N).sort("i", "k"),
            ignore_order=False)

    def test_sort_desc_nulls(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N)
            .sort(F.col("i").desc(), F.col("s").asc()),
            ignore_order=False)

    def test_sort_strings(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen(max_len=20)}, N).sort("s"),
            ignore_order=False)

    def test_sort_partitioned(self):
        # full tie-break: row order among key-ties is engine-dependent
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N, num_partitions=4)
            .sort("f", "i", "k", "s", "b"),
            ignore_order=False)

    def test_limit(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, _base_gens(), N).sort("i", "k", "f", "s")
            .limit(17), ignore_order=False)

    def test_union(self):
        def fn(s):
            a = gen_df(s, {"x": IntGen()}, 50, seed=9)
            b = gen_df(s, {"x": IntGen()}, 60, seed=10)
            return a.union(b)
        assert_tpu_and_cpu_are_equal_collect(fn)

    def test_count_action(self):
        from harness import with_cpu_session, with_tpu_session
        fn = lambda s: gen_df(s, _base_gens(), N).filter(
            F.col("i") > 0).count()
        assert with_cpu_session(fn) == with_tpu_session(fn)


class TestMixedTypeComparison:
    """Comparisons/joins across int/float/date widths must promote to a
    common type before key-word encoding (analyzer-coercion role); the
    encodings are only ordered within one type family."""

    def test_float_col_vs_int_literal(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"f": FloatGen()}, N)
            .filter(F.col("f") > 0))

    def test_fraction_vs_int_literal(self):
        # 0.5 > 1 must be False (was silently wrong pre-promotion)
        import pyarrow as pa
        rows = with_tpu_session(
            lambda s: s.create_dataframe(
                pa.table({"f": [0.5, 1.5, -0.5, 2.0]}))
            .filter(F.col("f") > 1).collect())
        assert rows == [(1.5,), (2.0,)]

    def test_int_col_vs_float_literal(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"i": IntGen(lo=-10, hi=10)}, N)
            .filter(F.col("i") >= 2.5))

    def test_mixed_type_join_keys(self):
        import pyarrow as pa

        def fn(s):
            left = s.create_dataframe(pa.table({"a": [1, 2, 3, 4]}))
            right = s.create_dataframe(
                pa.table({"b": [1.0, 3.0, 9.5, 2.5]}))
            return left.join(right, F.col("a") == F.col("b"), "inner")
        assert_tpu_and_cpu_are_equal_collect(fn)

    def test_decimal_vs_float_comparison(self):
        import pyarrow as pa
        from decimal import Decimal

        def fn(s):
            t = pa.table({"d": pa.array(
                [Decimal("1.00"), Decimal("0.25"), Decimal("3.50"), None],
                type=pa.decimal128(10, 2))})
            return s.create_dataframe(t).filter(F.col("d") > 0.5)
        assert_tpu_and_cpu_are_equal_collect(fn)

    def test_isin_fractional_values(self):
        import pyarrow as pa
        rows = with_tpu_session(
            lambda s: s.create_dataframe(pa.table({"i": [0, 1, 2]}))
            .filter(F.col("i").isin(0.5, 2.0)).collect())
        assert rows == [(2,)]

    def test_double_to_long_boundary(self):
        import pyarrow as pa
        rows = with_tpu_session(
            lambda s: s.create_dataframe(
                pa.table({"f": [1e18, -1e18, 2.5, 9.3e18, -9.3e18]}))
            .select(F.col("f").cast("bigint").alias("l")).collect())
        assert rows == [(1000000000000000000,), (-1000000000000000000,),
                        (2,), (9223372036854775807,),
                        (-9223372036854775808,)]
