"""Negative-path tests for the speculative table-aggregation contract.

The sort-free bucket-table fast path dispatches speculatively and
verifies a device-side fit flag at the next flush barrier (the exchange,
the FINAL-aggregate merge, or — with deferred verification — the
consumer's own barrier: join phase A / session collect).  These tests
FORCE misfits at each barrier and assert the redo path reproduces the
CPU oracle exactly.

Construction: input partitions each hold a narrow key band (every
partial-aggregate batch FITS the table), but the bands are far apart, so
any post-shuffle reduce partition mixes bands and the FINAL merge core
MISFITS (key range >> tableSize) — exercising redo after a FINAL-mode
concat, through the deferred join barrier, and at root collect.
"""
import numpy as np
import pytest

from harness import assert_tpu_and_cpu_are_equal_collect

from spark_rapids_tpu.api import functions as F


BANDS = 4
KEYS_PER_BAND = 200        # < tableSize: each band alone FITS
BAND_STRIDE = 10_000_000   # band spacing: mixed bands MISFIT
TABLE_SIZE = 256
ROWS_PER_BAND = 8000       # batch capacity must reach tableSize for the
                           # table path to engage at all


def _banded_data(rows_per_band=ROWS_PER_BAND, seed=3):
    """Rows ordered band-by-band so partition i sees only band i."""
    rng = np.random.default_rng(seed)
    ks, vs = [], []
    for band in range(BANDS):
        base = band * BAND_STRIDE
        ks.append(base + rng.integers(0, KEYS_PER_BAND, rows_per_band))
        vs.append(rng.integers(-1000, 1000, rows_per_band))
    return {"k": np.concatenate(ks).astype(np.int64),
            "v": np.concatenate(vs).astype(np.float64)}


CONF = {
    # keep the table path on and small enough that mixed bands misfit
    "spark.rapids.tpu.sql.agg.tablePath.enabled": True,
    "spark.rapids.tpu.sql.agg.tableSize": TABLE_SIZE,
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": True,
}


def _agg_df(s):
    df = s.create_dataframe(_banded_data(), num_partitions=BANDS)
    return (df.group_by("k")
              .agg(F.sum("v").alias("sv"), F.count().alias("c"),
                   F.max("v").alias("mv")))


class TestSpeculativeMisfit:
    def test_banded_final_concat_is_exact(self):
        """Partial batches each fit their band; the FINAL merge over
        mixed post-shuffle bands runs the exact sort-merge core — the
        pipeline must reproduce the oracle with no misfit anywhere."""
        rows = assert_tpu_and_cpu_are_equal_collect(_agg_df, conf=CONF)
        assert len(rows) == BANDS * KEYS_PER_BAND

    def test_misfit_deferred_to_root_collect(self):
        """COMPLETE-mode aggregate at plan root with misfitting keys:
        the deferred fit flag resolves at session collect, whose
        resolve_speculative must swap in the exact redo."""
        def q(s):
            df = s.create_dataframe(_banded_data(), num_partitions=1)
            return (df.group_by("k")
                      .agg(F.sum("v").alias("sv"), F.count().alias("c")))
        rows = assert_tpu_and_cpu_are_equal_collect(q, conf=CONF)
        assert len(rows) == BANDS * KEYS_PER_BAND

    def test_misfit_through_deferred_join_barrier(self):
        """A COMPLETE-mode aggregate (single input partition, no
        exchange) speculates via the table path, MISFITS (key range >>
        tableSize), and defers its fit flag to the join's phase-A
        flush; the redo chain must recompute the aggregate + finalize
        exactly there, before any probe output is exposed."""
        def q(s):
            data = _banded_data()    # all bands in ONE partition: misfit
            df = s.create_dataframe(data, num_partitions=1)
            agg = (df.group_by("k")
                     .agg(F.sum("v").alias("sv"), F.count().alias("c"),
                          F.max("v").alias("mv")))
            dim_keys = np.concatenate(
                [b * BAND_STRIDE + np.arange(KEYS_PER_BAND)
                 for b in range(BANDS)]).astype(np.int64)
            dim = s.create_dataframe({
                "dk": dim_keys,
                "w": np.arange(len(dim_keys)).astype(np.float64)})
            j = agg.join(dim, agg["k"] == dim["dk"], "inner")
            return j.select(F.col("k"), F.col("sv"), F.col("c"),
                            (F.col("mv") + F.col("w")).alias("mw"))
        rows = assert_tpu_and_cpu_are_equal_collect(q, conf=CONF)
        assert len(rows) == BANDS * KEYS_PER_BAND

    def test_fitting_complete_agg_through_join(self):
        """Same shape but FITTING keys: the deferred flag verifies OK at
        the join barrier and no redo runs (the fast path stays fast and
        correct)."""
        def q(s):
            rng = np.random.default_rng(5)
            df = s.create_dataframe({
                "k": rng.integers(0, 100, 9000).astype(np.int64),
                "v": rng.standard_normal(9000)}, num_partitions=1)
            agg = df.group_by("k").agg(F.sum("v").alias("sv"))
            dim = s.create_dataframe({
                "dk": np.arange(100, dtype=np.int64),
                "w": np.arange(100).astype(np.float64)})
            j = agg.join(dim, agg["k"] == dim["dk"], "inner")
            return j.select(F.col("k"), (F.col("sv") * F.col("w"))
                            .alias("sw"))
        rows = assert_tpu_and_cpu_are_equal_collect(q, conf=CONF)
        assert len(rows) == 100

    def test_misfit_through_exchange_and_aqe(self):
        """Misfit partials crossing a shuffle with AQE enabled: the
        exchange's verify-at-flush + any AQE re-plan must still produce
        oracle rows."""
        def q(s):
            df = s.create_dataframe(_banded_data(), num_partitions=BANDS)
            agg = (df.group_by("k").agg(F.sum("v").alias("sv")))
            return agg.filter(F.col("sv") > -10_000_000)
        conf = dict(CONF)
        conf["spark.rapids.tpu.sql.adaptive.enabled"] = True
        rows = assert_tpu_and_cpu_are_equal_collect(q, conf=conf)
        assert len(rows) >= 1

    def test_all_batches_misfit_tiny_table(self):
        """tableSize so small even one band misfits: every batch redoes
        on the sort path end-to-end."""
        conf = dict(CONF)
        conf["spark.rapids.tpu.sql.agg.tableSize"] = 16
        rows = assert_tpu_and_cpu_are_equal_collect(_agg_df, conf=conf)
        assert len(rows) == BANDS * KEYS_PER_BAND


class TestCompactionMisfitUnderProject:
    """Round-5 regression (TPC-DS q97 at SF1): a COMPLETE/FINAL
    aggregate whose group count exceeds the speculative compaction cap
    must NOT hand the truncated batch to a consumer that drops the fit
    flag (a Project re-evaluates columns into fresh batches).  The
    aggregate verifies its own merge output unless the planner marked
    the consumer as a deferred-verify barrier."""

    def test_high_cardinality_agg_under_project(self):
        import numpy as np
        from tests.harness import assert_tpu_and_cpu_are_equal_collect
        from spark_rapids_tpu.api import functions as F
        rng = np.random.default_rng(9)
        n = 4000
        data = {"k": rng.integers(0, 1500, n).astype(np.int64),
                "v": rng.integers(0, 100, n).astype(np.int64)}

        def q(s):
            df = s.create_dataframe(data, num_partitions=1)
            agg = df.group_by("k").agg(F.sum("v").alias("sv"))
            # projection consumer: drops any speculative flag
            proj = agg.select((F.col("sv") * 2).alias("d"))
            return proj.agg(F.sum("d").alias("t"), F.count().alias("c"))
        assert_tpu_and_cpu_are_equal_collect(
            q, conf={"spark.rapids.tpu.sql.agg.speculativeCompactRows":
                     64})
