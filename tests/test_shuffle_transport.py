"""Shuffle transport stack tests.

Reference test pattern (SURVEY.md §4.2): the distributed protocol is
tested WITHOUT real hardware by injecting transactions and mock
connections into the client/server state machines
(RapidsShuffleClientSuite / RapidsShuffleServerSuite /
WindowedBlockIteratorSuite / RapidsShuffleHeartbeatManagerTest), plus an
end-to-end two-executor exchange over the in-process transport.
"""
import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.shuffle import (
    BlockIdSpec, BounceBufferManager, EndpointRegistry, InProcessTransport,
    MapOutputTracker, MetadataRequest, MetadataResponse, PeerInfo,
    RapidsShuffleClient, RapidsShuffleFetchHandler,
    RapidsShuffleHeartbeatEndpoint, RapidsShuffleHeartbeatManager,
    ShuffleExecutorContext, ShuffleFetchFailedError, Transaction,
    TransferRequest, TransferResponse, WindowedBlockIterator,
    batch_from_meta, build_table_meta, decode_meta, encode_meta)
from spark_rapids_tpu.shuffle.client import ClientConnection


def make_batch(n=10, seed=0, with_strings=True):
    rng = np.random.default_rng(seed)
    data = {
        "a": rng.integers(-100, 100, n).astype(np.int64),
        "b": rng.standard_normal(n),
    }
    b = ColumnarBatch.from_pydict(data)
    if with_strings:
        words = [None if i % 7 == 3 else f"w{i}-{seed}" for i in range(n)]
        b2 = ColumnarBatch.from_pydict({**data, "s": words})
        return b2
    return b


# ---------------------------------------------------------------------------
# TableMeta protocol (MetaUtilsSuite role)
# ---------------------------------------------------------------------------

class TestTableMeta:
    def test_roundtrip_plain_and_string(self):
        b = make_batch(13, seed=1)
        meta, blob = build_table_meta(b)
        assert meta.num_rows == 13
        assert meta.total_bytes == len(blob)
        out = batch_from_meta(meta, blob)
        assert out.to_pydict() == b.to_pydict()

    def test_roundtrip_nested_columns(self):
        """Lists, structs, and maps must survive the TableMeta wire
        (dtype-driven recursive buffer reconstruction)."""
        import pyarrow as pa
        from spark_rapids_tpu.columnar.arrow import from_arrow
        t = pa.table({
            "i": [1, 2, 3],
            "l": [[1, 2], None, []],
            "sl": [["x", None], ["yy"], None],
            "st": pa.array([{"x": 1, "y": "u"}, None, {"x": 3, "y": None}]),
            "mp": pa.array([{"k": 1}, None, {"a": 2, "b": 3}],
                           type=pa.map_(pa.string(), pa.int64())),
            "nn": pa.array([[[1], [2, 3]], None, [[]]],
                           type=pa.list_(pa.list_(pa.int64()))),
        })
        b = from_arrow(t)
        meta, blob = build_table_meta(b)
        again = decode_meta(encode_meta(meta))
        out = batch_from_meta(again, blob)
        assert out.to_pydict() == b.to_pydict()

    def test_wire_encoding_roundtrip(self):
        b = make_batch(5, seed=2)
        meta, _ = build_table_meta(b)
        again = decode_meta(encode_meta(meta))
        assert again == meta

    def test_degenerate_rows_only(self):
        from spark_rapids_tpu.columnar.schema import Schema
        b = ColumnarBatch(Schema(()), [], 42)
        meta, blob = build_table_meta(b)
        assert meta.degenerate and meta.total_bytes == 0
        out = batch_from_meta(decode_meta(encode_meta(meta)), blob)
        assert out.num_rows == 42 and out.num_cols == 0

    def test_decimal_field_roundtrip(self):
        from spark_rapids_tpu.columnar.column import Column
        from spark_rapids_tpu.columnar.schema import Field, Schema
        import jax.numpy as jnp
        dt = T.DecimalType(12, 2)
        col = Column(dt, jnp.asarray(np.array([100, -250], np.int64)),
                     jnp.asarray(np.array([True, True])))
        b = ColumnarBatch(Schema([Field("d", dt)]), [col], 2)
        meta, blob = build_table_meta(b)
        out = batch_from_meta(decode_meta(encode_meta(meta)), blob)
        assert out.schema["d"].dtype == dt


# ---------------------------------------------------------------------------
# WindowedBlockIterator (WindowedBlockIteratorSuite role)
# ---------------------------------------------------------------------------

class TestWindowedBlockIterator:
    def test_single_block_smaller_than_window(self):
        it = WindowedBlockIterator([10], 100)
        windows = list(it)
        assert len(windows) == 1
        (r,) = windows[0]
        assert (r.block_index, r.block_offset, r.length,
                r.window_offset) == (0, 0, 10, 0)

    def test_block_split_across_windows(self):
        it = WindowedBlockIterator([250], 100)
        windows = list(it)
        assert [w[0].length for w in windows] == [100, 100, 50]
        assert [w[0].block_offset for w in windows] == [0, 100, 200]

    def test_many_blocks_packed_into_one_window(self):
        it = WindowedBlockIterator([10, 20, 30], 100)
        (window,) = list(it)
        assert [r.block_index for r in window] == [0, 1, 2]
        assert [r.window_offset for r in window] == [0, 10, 30]

    def test_mixed_sizes_cover_all_bytes(self):
        sizes = [5, 1000, 0, 17, 256, 3]
        it = WindowedBlockIterator(sizes, 64)
        got = {i: 0 for i in range(len(sizes))}
        for window in it:
            used = 0
            for r in window:
                got[r.block_index] += r.length
                assert r.window_offset == used
                used += r.length
            assert used <= 64
        assert [got[i] for i in range(len(sizes))] == sizes

    def test_rejects_bad_input(self):
        with pytest.raises(ValueError):
            WindowedBlockIterator([1], 0)
        with pytest.raises(ValueError):
            WindowedBlockIterator([-1], 10)


# ---------------------------------------------------------------------------
# BounceBufferManager
# ---------------------------------------------------------------------------

class TestBounceBuffers:
    def test_acquire_release(self):
        mgr = BounceBufferManager("t", 1024, 2)
        a = mgr.acquire()
        b = mgr.acquire()
        assert mgr.num_free == 0
        assert mgr.acquire(blocking=False) is None
        a.close()
        assert mgr.num_free == 1
        c = mgr.acquire()
        assert c is a
        b.close()
        c.close()
        assert mgr.num_free == 2

    def test_blocking_acquire_wakes_on_release(self):
        mgr = BounceBufferManager("t", 16, 1)
        held = mgr.acquire()
        got = []

        def waiter():
            got.append(mgr.acquire(timeout=5.0))

        th = threading.Thread(target=waiter)
        th.start()
        time.sleep(0.05)
        held.close()
        th.join(timeout=5.0)
        assert got and got[0] is held

    def test_double_release_raises(self):
        mgr = BounceBufferManager("t", 16, 1)
        b = mgr.acquire()
        b.close()
        with pytest.raises(ValueError):
            b.close()


# ---------------------------------------------------------------------------
# Transaction semantics
# ---------------------------------------------------------------------------

class TestTransaction:
    def test_callback_after_completion_fires_immediately(self):
        tx = Transaction()
        tx.complete_success(7)
        seen = []
        tx.on_complete(lambda t: seen.append(t.nbytes))
        assert seen == [7]

    def test_only_first_completion_wins(self):
        tx = Transaction()
        tx.complete_error("boom")
        tx.complete_success(1)
        assert tx.status.value == "error"
        assert tx.error_message == "boom"


# ---------------------------------------------------------------------------
# Client state machine with a mock connection (RapidsShuffleClientSuite)
# ---------------------------------------------------------------------------

class MockConnection(ClientConnection):
    """Scripted connection: the test decides how each request resolves."""

    def __init__(self):
        super().__init__("mock-peer")
        self.data_handler = None
        self.metadata_requests = []
        self.transfer_requests = []

    def register_data_handler(self, handler):
        self.data_handler = handler

    def request_metadata(self, req, handler):
        tx = Transaction()
        self.metadata_requests.append((req, handler, tx))
        return tx

    def request_transfer(self, req, handler):
        tx = Transaction()
        self.transfer_requests.append((req, handler, tx))
        return tx


class CollectingHandler(RapidsShuffleFetchHandler):
    def __init__(self):
        self.batches = []
        self.errors = []
        self.expected = None

    def start(self, expected_batches):
        self.expected = expected_batches

    def batch_received(self, handle):
        self.batches.append(handle)

    def transfer_error(self, message):
        self.errors.append(message)


class TestClientStateMachine:
    def test_full_fetch_via_injected_messages(self):
        conn = MockConnection()
        client = RapidsShuffleClient(conn)
        handler = CollectingHandler()
        blocks = [BlockIdSpec(0, 0, 1)]
        client.do_fetch(blocks, handler)

        # respond to the metadata request with one table
        src = make_batch(9, seed=3)
        meta, blob = build_table_meta(src)
        (req, meta_cb, tx) = conn.metadata_requests[0]
        meta_cb(MetadataResponse(req.request_id, [[meta]]))
        tx.complete_success()

        assert handler.expected == 1
        # client should now have issued a transfer request with one tag
        (treq, transfer_cb, ttx) = conn.transfer_requests[0]
        assert len(treq.tags) == 1
        transfer_cb(TransferResponse(treq.request_id, True))
        ttx.complete_success()

        # deliver the blob in two windows, out of arrival order within
        # a table is not required — windows are offset-addressed
        tag = treq.tags[0]
        half = len(blob) // 2
        conn.data_handler(tag, half, blob[half:])
        conn.data_handler(tag, 0, blob[:half])

        assert len(handler.batches) == 1
        out = handler.batches[0].materialize()
        assert out.to_pydict() == src.to_pydict()

    def test_metadata_error_surfaces(self):
        conn = MockConnection()
        client = RapidsShuffleClient(conn)
        handler = CollectingHandler()
        client.do_fetch([BlockIdSpec(0, 0, 0)], handler)
        (req, meta_cb, tx) = conn.metadata_requests[0]
        meta_cb(MetadataResponse(req.request_id, [], error="no such block"))
        assert handler.errors == ["no such block"]

    def test_transfer_rejection_surfaces(self):
        conn = MockConnection()
        client = RapidsShuffleClient(conn)
        handler = CollectingHandler()
        client.do_fetch([BlockIdSpec(0, 0, 0)], handler)
        src = make_batch(3, seed=4)
        meta, _ = build_table_meta(src)
        (req, meta_cb, tx) = conn.metadata_requests[0]
        meta_cb(MetadataResponse(req.request_id, [[meta]]))
        (treq, transfer_cb, ttx) = conn.transfer_requests[0]
        transfer_cb(TransferResponse(treq.request_id, False, error="busy"))
        assert handler.errors == ["busy"]

    def test_degenerate_table_needs_no_transfer(self):
        from spark_rapids_tpu.columnar.schema import Schema
        conn = MockConnection()
        client = RapidsShuffleClient(conn)
        handler = CollectingHandler()
        client.do_fetch([BlockIdSpec(0, 0, 0)], handler)
        meta, _ = build_table_meta(ColumnarBatch(Schema(()), [], 17))
        (req, meta_cb, tx) = conn.metadata_requests[0]
        meta_cb(MetadataResponse(req.request_id, [[meta]]))
        assert not conn.transfer_requests
        assert len(handler.batches) == 1
        assert handler.batches[0].materialize().num_rows == 17


# ---------------------------------------------------------------------------
# End-to-end over the in-process transport (two executors)
# ---------------------------------------------------------------------------

@pytest.fixture()
def fresh_registry():
    reg = EndpointRegistry.reset()
    yield reg
    EndpointRegistry.reset()


class TestEndToEndExchange:
    def test_remote_fetch_two_executors(self, fresh_registry):
        tracker = MapOutputTracker()
        ex_a = ShuffleExecutorContext(
            "exec-a", InProcessTransport("exec-a", fresh_registry), tracker,
            bounce_buffer_size=64, num_bounce_buffers=2)
        ex_b = ShuffleExecutorContext(
            "exec-b", InProcessTransport("exec-b", fresh_registry), tracker,
            bounce_buffer_size=64, num_bounce_buffers=2)

        # exec-a runs map task 0; partitions 0/1 both get data
        b0 = make_batch(11, seed=5)
        b1 = make_batch(7, seed=6)
        ex_a.write_map_output(0, 0, {0: [b0], 1: [b1]})
        # exec-b runs map task 1
        b2 = make_batch(5, seed=7)
        ex_b.write_map_output(0, 1, {0: [b2]})

        # reduce partition 0 on exec-b: local hit (b2) + remote (b0)
        out = list(ex_b.read_partition(0, 0, timeout_s=10.0))
        assert len(out) == 2
        dicts = [o.to_pydict() for o in out]
        assert b2.to_pydict() in dicts
        assert b0.to_pydict() in dicts

        # reduce partition 1 on exec-b: purely remote, multi-window
        # (batch bytes >> 64-byte bounce buffers)
        out1 = list(ex_b.read_partition(0, 1, timeout_s=10.0))
        assert len(out1) == 1
        assert out1[0].to_pydict() == b1.to_pydict()

    def test_concurrent_reduce_tasks_same_peer(self, fresh_registry):
        """Two reduce tasks on one executor fetching from the same peer:
        each client's data handler must keep receiving (registration is
        additive, not a single clobbered slot)."""
        tracker = MapOutputTracker()
        ex_a = ShuffleExecutorContext(
            "exec-a", InProcessTransport("exec-a", fresh_registry), tracker,
            bounce_buffer_size=64, num_bounce_buffers=2)
        ex_b = ShuffleExecutorContext(
            "exec-b", InProcessTransport("exec-b", fresh_registry), tracker,
            bounce_buffer_size=64, num_bounce_buffers=2)
        b0 = make_batch(11, seed=5)
        b1 = make_batch(7, seed=6)
        ex_a.write_map_output(0, 0, {0: [b0], 1: [b1]})

        results = {}
        errors = []

        def fetch(pid):
            try:
                results[pid] = list(ex_b.read_partition(0, pid,
                                                        timeout_s=10.0))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        ts = [threading.Thread(target=fetch, args=(p,)) for p in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=20)
        assert not errors
        assert results[0][0].to_pydict() == b0.to_pydict()
        assert results[1][0].to_pydict() == b1.to_pydict()

    def test_fetch_failure_raises_for_scheduler(self, fresh_registry):
        tracker = MapOutputTracker()
        ex_a = ShuffleExecutorContext(
            "exec-a", InProcessTransport("exec-a", fresh_registry), tracker)
        ex_b = ShuffleExecutorContext(
            "exec-b", InProcessTransport("exec-b", fresh_registry), tracker)
        ex_a.write_map_output(0, 0, {0: [make_batch(4, seed=8)]})
        # exec-a vanishes (executor loss)
        fresh_registry.drop_peers["exec-a"] = "connection reset"
        with pytest.raises(ShuffleFetchFailedError):
            list(ex_b.read_partition(0, 0, timeout_s=2.0))

    def test_server_bytes_accounting(self, fresh_registry):
        tracker = MapOutputTracker()
        ex_a = ShuffleExecutorContext(
            "exec-a", InProcessTransport("exec-a", fresh_registry), tracker,
            bounce_buffer_size=128, num_bounce_buffers=1)
        ex_b = ShuffleExecutorContext(
            "exec-b", InProcessTransport("exec-b", fresh_registry), tracker)
        src = make_batch(50, seed=9)
        meta, blob = build_table_meta(src)
        ex_a.write_map_output(0, 0, {0: [src]})
        out = list(ex_b.read_partition(0, 0, timeout_s=10.0))
        assert out[0].to_pydict() == src.to_pydict()
        deadline = time.time() + 5
        while ex_a.server.bytes_served < len(blob) and time.time() < deadline:
            time.sleep(0.01)
        assert ex_a.server.bytes_served == len(blob)


# ---------------------------------------------------------------------------
# Heartbeat manager (RapidsShuffleHeartbeatManagerTest role)
# ---------------------------------------------------------------------------

class RecordingTransport:
    def __init__(self):
        self.connected = []

    def connect(self, peer):
        self.connected.append(peer)


class TestHeartbeat:
    def test_registration_returns_existing_peers(self):
        mgr = RapidsShuffleHeartbeatManager()
        t1, t2 = RecordingTransport(), RecordingTransport()
        RapidsShuffleHeartbeatEndpoint(mgr, t1, PeerInfo("e1"))
        assert t1.connected == []
        RapidsShuffleHeartbeatEndpoint(mgr, t2, PeerInfo("e2"))
        assert t2.connected == ["e1"]

    def test_heartbeat_returns_only_new_peers(self):
        mgr = RapidsShuffleHeartbeatManager()
        t1 = RecordingTransport()
        ep1 = RapidsShuffleHeartbeatEndpoint(mgr, t1, PeerInfo("e1"))
        RapidsShuffleHeartbeatEndpoint(mgr, RecordingTransport(),
                                       PeerInfo("e2"))
        assert [p.executor_id for p in ep1.beat()] == ["e2"]
        assert ep1.beat() == []          # no news on the next beat
        RapidsShuffleHeartbeatEndpoint(mgr, RecordingTransport(),
                                       PeerInfo("e3"))
        assert [p.executor_id for p in ep1.beat()] == ["e3"]
        assert t1.connected == ["e2", "e3"]

    def test_liveness_timeout(self):
        mgr = RapidsShuffleHeartbeatManager(timeout_s=0.05)
        mgr.register_executor(PeerInfo("e1"))
        assert [p.executor_id for p in mgr.live_executors()] == ["e1"]
        time.sleep(0.1)
        assert mgr.live_executors() == []
