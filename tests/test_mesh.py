"""Distributed mesh primitive tests on the virtual 8-device CPU mesh.

Pattern parity: reference shuffle suites test the transport without a
cluster (SURVEY §4.2); here the SPMD primitives (all_to_all exchange,
psum reductions, range routing) run on virtual devices and compare
against host oracles.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from spark_rapids_tpu.parallel import (make_mesh, shard_rows,
                                       distributed_sum_by_key,
                                       distributed_global_sum,
                                       distributed_join_sum,
                                       distributed_sort)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEV:
        pytest.skip("needs the virtual 8-device mesh")
    return make_mesh(N_DEV)


def test_distributed_sum_by_key(mesh):
    rng = np.random.default_rng(0)
    n = N_DEV * 128
    keys = rng.integers(0, 23, n).astype(np.int64)
    vals = rng.random(n)
    sk, sv, sm = shard_rows(
        [jnp.asarray(keys), jnp.asarray(vals),
         jnp.asarray(np.ones(n, bool))], mesh)
    k, s, v, overflow = distributed_sum_by_key(mesh)(sk, sv, sm)
    assert not bool(np.asarray(overflow).any())
    got = {int(a): float(b)
           for a, b, c in zip(np.asarray(k), np.asarray(s),
                              np.asarray(v)) if c}
    expect = {int(a): float(vals[keys == a].sum())
              for a in np.unique(keys)}
    assert set(got) == set(expect)
    for a in expect:
        assert abs(got[a] - expect[a]) < 1e-6


def test_distributed_global_sum(mesh):
    rng = np.random.default_rng(1)
    n = N_DEV * 64
    vals = rng.random(n)
    sv, sm = shard_rows(
        [jnp.asarray(vals), jnp.asarray(np.ones(n, bool))], mesh)
    total = np.asarray(distributed_global_sum(mesh)(sv, sm))
    assert abs(float(total[0]) - vals.sum()) < 1e-6


def test_distributed_join_sum(mesh):
    rng = np.random.default_rng(2)
    n = N_DEV * 128
    lk = rng.integers(0, 19, n).astype(np.int64)
    lv = rng.random(n)
    rk = rng.integers(5, 29, n).astype(np.int64)
    rv = rng.random(n)
    args = shard_rows(
        [jnp.asarray(lk), jnp.asarray(lv),
         jnp.asarray(np.ones(n, bool)),
         jnp.asarray(rk), jnp.asarray(rv),
         jnp.asarray(np.ones(n, bool))], mesh)
    k, s, hit, overflow = distributed_join_sum(mesh)(*args)
    assert not bool(np.asarray(overflow).any())
    got = {int(a): float(b)
           for a, b, c in zip(np.asarray(k), np.asarray(s),
                              np.asarray(hit)) if c}
    expect = {}
    for key in set(lk) & set(rk):
        expect[int(key)] = float(lv[lk == key].sum() *
                                 rv[rk == key].sum())
    assert set(got) == set(expect)
    for a in expect:
        assert abs(got[a] - expect[a]) < 1e-6 * max(1.0, abs(expect[a]))


def test_distributed_sort(mesh):
    rng = np.random.default_rng(3)
    n = N_DEV * 128
    keys = rng.integers(-10_000, 10_000, n).astype(np.int64)
    sk, sm = shard_rows(
        [jnp.asarray(keys), jnp.asarray(np.ones(n, bool))], mesh)
    out, valid, overflow = distributed_sort(mesh)(sk, sm)
    assert not bool(np.asarray(overflow).any())
    o = np.asarray(out)[np.asarray(valid)]
    assert len(o) == n
    # device regions concatenate to the full globally sorted order
    np.testing.assert_array_equal(o, np.sort(keys))


def test_distributed_sort_skew_overflow_flag(mesh):
    # all keys identical: one device owns everything; with slack 4 and
    # 8 devices the region overflows and the flag must say so
    n = N_DEV * 64
    keys = np.zeros(n, dtype=np.int64)
    sk, sm = shard_rows(
        [jnp.asarray(keys), jnp.asarray(np.ones(n, bool))], mesh)
    out, valid, overflow = distributed_sort(mesh)(sk, sm)
    assert bool(np.asarray(overflow).any())
